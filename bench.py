"""Benchmark: Monte-Carlo distributed-MPC throughput on one chip.

Headline config from BASELINE.json ("env_forest obstacle field: 256 Monte-Carlo
scenarios x 8 agents, batched"): each scenario runs a full receding-horizon
control period — per-agent vision-cone env queries, consensus-ADMM over vmapped
conic-QP solves, low-level SO(3) attitude control at 1 kHz, 10 physics substeps
— and 256 scenarios are batched in one jitted computation (vmap over the
scenario axis), the exact workload the reference executes one-scenario-at-a-time
with sequential cvxpy/Clarabel solves (test_rqpcontrollers.py:112-124 runs its
100 Monte-Carlo re-solves in a Python loop). The low-level SO(3) law runs inside
every 1 kHz substep, as the reference's hot loop does (rqp_example.py:120-131).

Baseline: the reference's cvxpy/Clarabel stack is not installed in this image.
Two CPU baselines are measured instead (both recorded in BASELINE.md):

1. **Reference-architecture baseline** (the ``vs_baseline`` denominator):
   the reference's actual execution model — n per-agent conic QPs solved
   SEQUENTIALLY by a native (C++, f64) solver per consensus iteration, one
   scenario at a time, warm-started, same stopping rule
   (rqp_cadmm.py:644-648 runs exactly this loop through cvxpy+Clarabel).
   Generous to the baseline: QP assembly, env queries, and physics are
   EXCLUDED from its timing (the reference pays cvxpy re-canonicalization
   per solve on top).
2. **Same-program XLA-CPU baseline**: this framework's own fused program on
   the host CPU — a much stronger baseline than the reference stack (fully
   vectorized, no per-solve overhead); reported as ``vs_xla_cpu`` for
   transparency.

Default mode prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``--sweep`` measures the full BASELINE.json matrix — MPC steps/sec/chip at
N in {4, 16, 64} agents for centralized / C-ADMM / DD, p50 control-step time
per consensus iteration, and the 1024-agent swarm config — and writes
``BENCH_SWEEP.json`` (a markdown table is printed for BASELINE.md).

``--profile <dir>`` wraps the headline timed window in a ``jax.profiler.trace``
for op-level attribution (SURVEY.md §5.1).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

# Importing jax does NOT initialize any backend (that happens lazily on first
# device use) — safe before the watchdog probe in ensure_backend_or_die().
import jax
import jax.numpy as jnp
import numpy as np

N_AGENTS = 8
N_SCENARIOS = 256
TIMED_STEPS = 10
CPU_TIMED_STEPS = 4

PROBE_TIMEOUT_S = 60
PROBE_ATTEMPTS = 2

HEADLINE_METRIC = (
    f"scenario_mpc_steps_per_sec_{N_SCENARIOS}x{N_AGENTS}_cadmm_forest"
)


def _fail_headline(error: str, metric: str = HEADLINE_METRIC,
                   status: str = "error") -> None:
    """Emit a machine-readable failure JSON and exit nonzero — a diagnosable
    record instead of a silent hang. ``metric`` names the mode that failed so
    a probe failure during ``--sweep``/``--components`` is not filed as a
    failed *headline* measurement (the unit only applies to the headline).
    ``status``: ``"backend_unavailable"`` for probe/infra failures so
    downstream tooling (tools/bench_retry.py, trajectory plots) can
    distinguish a wedged chip from a genuine regression."""
    print(json.dumps({
        "metric": metric,
        "value": None,
        "unit": ("scenario-MPC-steps/s" if metric == HEADLINE_METRIC
                 else None),
        "vs_baseline": None,
        "status": status,
        "error": error,
    }), flush=True)
    raise SystemExit(1)


def _force_cpu() -> None:
    """Route the rest of the process to XLA-CPU (before any backend init)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")


def ensure_backend(metric: str = HEADLINE_METRIC,
                   cpu_fallback: bool = False) -> tuple[str, str | None]:
    """:func:`ensure_backend_or_die` with an optional XLA-CPU fallback:
    returns ``(platform, fallback_reason)``. When the accelerator probe
    fails (wedged tunnel / absent plugin / silent CPU fallback) and
    ``cpu_fallback`` is set, the process is routed to XLA-CPU and the
    reason is returned so the caller can TAG its record ``"backend":
    "cpu"`` — a valid measurement on the fallback backend instead of a
    null-valued error row (BENCH_r04/r05 recorded exactly those nulls and
    the bench trajectory had holes)."""
    ok, detail = _probe_backend()
    if ok and not (detail == "cpu" and not _cpu_explicitly_requested()):
        return detail, None
    if ok:  # silent CPU fallback: plugin absent but probe "succeeded".
        reason = ("JAX silently fell back to host CPU (accelerator plugin "
                  "absent) — record tagged backend=cpu, not published as "
                  "the TPU headline")
    else:
        reason = "backend unavailable: " + detail
    if not cpu_fallback:
        _fail_headline(reason, metric=metric, status="backend_unavailable")
    _force_cpu()
    return "cpu", reason


# Topology the last successful probe reported (platform / n_devices /
# n_processes) — the sweep stamps it on every cell so a chip-round record
# can never be ambiguous about the mesh that measured it, without this
# process paying an in-process backend init to ask.
_PROBE_INFO: dict = {}


def _probe_backend() -> tuple[bool, str]:
    """Subprocess-watchdogged backend probe (no printing, no exiting):
    ``(True, platform)`` when a backend answered, ``(False, error)``
    otherwise. See :func:`ensure_backend_or_die` for why the probe exists
    and why it runs in a subprocess.

    Delegates to ``resilience.backend.probe_subprocess``, which warms a
    REAL device computation (matmul + an explicit ``convert_element_type``
    round-trip) rather than just ``jax.devices()`` — round 2's probe
    passed on backend enumeration while the first dispatched op raised the
    lazy backend-init ``UNAVAILABLE`` (BENCH_r02.json); a probe "pass" now
    implies the first real dispatch succeeds. The probe also reports the
    visible device/process counts (stashed in :data:`_PROBE_INFO`) and,
    when ``TAT_EXPECTED_DEVICES`` / ``TAT_EXPECTED_PROCESSES`` are set,
    FAILS on a shortfall with a classified ``topology_mismatch`` — the
    MULTICHIP_r01 failure mode (1 of 8 devices visible, probe green)
    becomes a tagged CPU round instead of an 8x-undersharded headline."""
    from tpu_aerial_transport.resilience import backend as backend_mod

    errors = []
    for attempt in range(PROBE_ATTEMPTS):
        info: dict = {}
        ok, detail = backend_mod.probe_subprocess(
            timeout_s=PROBE_TIMEOUT_S, info=info,
        )
        if ok:
            # SUCCESSFUL probes only: after a failed probe (e.g. a
            # topology_mismatch routing the round to XLA-CPU) the
            # accelerator's reported topology must NOT be stamped onto
            # the cpu-tagged cells — _annotate_topology then falls back
            # to the live in-process counts, which ARE the fallback
            # backend's topology.
            if info:
                _PROBE_INFO.update(info)
            return True, detail
        errors.append(f"attempt {attempt + 1}: {detail}")
    return False, " ;; ".join(errors)


def ensure_backend_or_die(metric: str = HEADLINE_METRIC) -> str:
    """Probe JAX backend availability in a subprocess under a watchdog; return
    the platform name the probe saw (e.g. ``"axon"``/``"tpu"``/``"cpu"``).

    Backend init happens lazily on first device use; when the TPU tunnel is
    unreachable a bare ``jax.devices()`` can block far past any useful budget
    (the round-2 driver lost its whole bench window to exactly this, see
    BENCH_r02.json rc:1 after hanging). The probe pays one extra backend init
    (~5-20 s when healthy) to guarantee the failure mode is a fast, diagnosable
    JSON line rather than a timeout.

    A silent JAX fallback to host CPU (accelerator plugin absent) would pass a
    naive probe and publish CPU throughput under the TPU headline metric — so
    a ``cpu`` platform is treated as a failure unless the caller explicitly
    *leads* with cpu in ``JAX_PLATFORMS`` (a fallback list like ``"axon,cpu"``
    is a TPU request, not a CPU one). Modes that can measure meaningfully on
    the host go through :func:`ensure_backend` instead, which converts both
    failure modes into a TAGGED XLA-CPU measurement.

    The axon site hook rewrites ``jax_platforms`` to ``"axon,cpu"`` at
    interpreter startup, overriding the env var (see conftest.py) — both the
    probe subprocess and :func:`_honor_jax_platforms_env` in the parent
    counter it with a config-level override so ``JAX_PLATFORMS=cpu`` really
    does select CPU.
    """
    # Single implementation: the no-fallback path of ensure_backend (kept
    # under this name for external scripts/watchers that invoke it).
    return ensure_backend(metric=metric, cpu_fallback=False)[0]


def _cpu_explicitly_requested() -> bool:
    """True iff JAX_PLATFORMS' FIRST entry is cpu — ``"axon,cpu"`` is a
    priority list preferring TPU, not an explicit CPU request."""
    first = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip().lower()
    return first == "cpu"


def _honor_jax_platforms_env() -> None:
    """Counter the axon site hook's startup rewrite so an explicit
    ``JAX_PLATFORMS=cpu python bench.py`` actually measures on CPU
    (shared implementation: tpu_aerial_transport/utils/platform.py)."""
    from tpu_aerial_transport.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()


def _finite_or_none(x: float, digits: int = 2):
    """NaN/inf -> None so the headline stays strictly valid JSON
    (``json.dumps(float('nan'))`` emits the bare token ``NaN``)."""
    return round(x, digits) if np.isfinite(x) else None


def _setup(n):
    from tpu_aerial_transport.control import centralized, lowlevel
    from tpu_aerial_transport.envs import forest as forest_mod
    from tpu_aerial_transport.harness import setup

    params, col, state0 = setup.rqp_setup(n)
    forest = forest_mod.make_forest(seed=0)
    f_eq = centralized.equilibrium_forces(params)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    acc_des = (jnp.array([0.3, 0.0, 0.0], jnp.float32), jnp.zeros(3, jnp.float32))
    return params, col, state0, forest, f_eq, ll, acc_des


def _substeps(params, ll, state, f_des, n_sub=10, dt=1e-3, unroll=1):
    """1 kHz low-level control + physics, the reference's inner loop.

    ``unroll``: scan-unroll factor. Results are bit-identical at any value
    (measured, CPU); unrolling lets XLA fuse elementwise chains ACROSS
    substeps, attacking the kernel-count bottleneck the roofline identifies
    (artifacts/roofline.json: headline at ~2% HBM peak because the two-rate
    cascade serializes many small kernels). CPU A/B is noise (1.03x); the
    on-chip A/B is the sweep cell headline_substep_unroll10."""
    from tpu_aerial_transport.models import rqp

    def body(s, _):
        f, M = ll.control(s, f_des)
        return rqp.integrate(params, s, (f, M), dt), None

    state, _ = jax.lax.scan(body, state, None, length=n_sub, unroll=unroll)
    return state


def make_mpc_step(controller: str, n: int, max_iter: int = 20,
                  inner_iters: int | None = None, socp_fused: str = "auto",
                  force_fixed_iters: bool = False, inner_tol: float = 0.0,
                  substep_unroll: int = 1,
                  pad_operators: bool | None = None,
                  socp_precision: str = "auto", effort: str = "auto"):
    # Default inner ADMM budgets are the measured knees. C-ADMM: 20 — below
    # it the warm-started agent solves miss the 5e-3 primal tolerance and
    # fall back to equilibrium forces (visible as an exactly-zero consensus
    # residual); at 20 forces match an inner=80 solve to < 1e-4 N. DD: 40 —
    # its quasi-Newton dual ascent needs tighter primal optima (at 20 it
    # rails against the outer iteration cap), and its 18-var QPs make inner
    # iterations ~20x cheaper than C-ADMM's (9+3n)-var ones.
    """Build ``(mpc_step(cs, state) -> (cs, state, stats), cs0, state0)`` for one
    scenario with the given high-level controller."""
    from tpu_aerial_transport.control import cadmm, centralized, dd
    from tpu_aerial_transport.envs import forest as forest_mod

    params, col, state0, forest, f_eq, ll, acc_des = _setup(n)

    if controller == "cadmm":
        cfg = cadmm.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=max_iter,
            inner_iters=inner_iters if inner_iters is not None else 20,
            socp_fused=socp_fused, socp_precision=socp_precision,
            inner_tol=inner_tol,
            pad_operators=pad_operators, effort=effort,
            # res_tol = 0 can never be met (inf-norm >= 0), so the consensus
            # loop runs to exactly max_iter + 1 iterations — the fixed-count
            # mode _measured_iter_ms differences.
            **({"res_tol": 0.0} if force_fixed_iters else {}),
        )
        cs0 = cadmm.init_cadmm_state(params, cfg)
        # Precompute the state-independent Schur plan once, outside the
        # rollout scan (None at n = 3, where the full-QP path runs).
        plan = cadmm.make_plan(params, cfg)

        def mpc_step(cs, state):
            f_app, cs, stats = cadmm.control(
                params, cfg, f_eq, cs, state, acc_des, forest, plan=plan
            )
            return cs, _substeps(params, ll, state, f_app,
                                 unroll=substep_unroll), stats

    elif controller == "dd":
        cfg = dd.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=max_iter,
            inner_iters=inner_iters if inner_iters is not None else 40,
            socp_fused=socp_fused, socp_precision=socp_precision,
            inner_tol=inner_tol,
            pad_operators=pad_operators, effort=effort,
            **({"prim_inf_tol": 0.0} if force_fixed_iters else {}),
        )
        cs0 = dd.init_dd_state(params, cfg)
        plan = dd.make_dd_plan(params, cfg)  # state-independent QN cores.

        def mpc_step(cs, state):
            f_des, cs, stats = dd.control(
                params, cfg, f_eq, cs, state, acc_des, forest, plan=plan
            )
            return cs, _substeps(params, ll, state, f_des,
                                 unroll=substep_unroll), stats

    elif controller == "centralized":
        cfg = centralized.make_config(
            params, col.collision_radius, col.max_deceleration,
            solver_iters=120,
        )
        cs0 = centralized.init_ctrl_state(params, cfg)

        def mpc_step(cs, state):
            env_cbf = forest_mod.collision_cbf_rows(
                forest, state.xl, state.vl, col.collision_radius,
                col.max_deceleration, cfg.vision_radius, cfg.dist_eps,
                cfg.alpha_env_cbf, cfg.n_env_cbfs,
            )
            f_des, cs, stats = centralized.control(
                params, cfg, f_eq, cs, state, acc_des, env_cbf
            )
            return cs, _substeps(params, ll, state, f_des,
                                 unroll=substep_unroll), stats

    else:
        raise ValueError(controller)

    return mpc_step, cs0, state0


def _scenario_batch(state0, n_scenarios):
    xs = jnp.asarray(
        np.random.default_rng(0).normal(size=(n_scenarios, 3)) * 2.0
        + np.array([5.0, 0.0, 2.0]),
        jnp.float32,
    )
    return jax.vmap(
        lambda x: state0.replace(xl=x, vl=jnp.array([0.5, 0.0, 0.0], jnp.float32))
    )(xs)


def build(controller="cadmm", n=N_AGENTS, n_scenarios=N_SCENARIOS,
          socp_fused="auto", buckets=0, inner_tol=0.0, substep_unroll=1,
          pad_operators=None):
    mpc_step, cs0, state0 = make_mpc_step(controller, n, socp_fused=socp_fused,
                                          inner_tol=inner_tol,
                                          substep_unroll=substep_unroll,
                                          pad_operators=pad_operators)
    states = _scenario_batch(state0, n_scenarios)
    css = jax.vmap(lambda _: cs0)(jnp.arange(n_scenarios))

    if buckets >= 2:
        # Congestion-bucketed batch: decouple the vmapped while_loop's
        # worst-lane iteration count across env-CBF-activity groups
        # (harness/bucketing.py; per-scenario results identical).
        from tpu_aerial_transport.envs import forest as forest_mod
        from tpu_aerial_transport.harness import bucketing
        from tpu_aerial_transport.harness import setup as setup_mod

        _, col, _ = setup_mod.rqp_setup(n)
        forest = forest_mod.make_forest(seed=0)
        metric = bucketing.env_congestion_metric(
            forest, col.collision_radius + 5.0
        )
        batched_step = bucketing.bucketed_step(mpc_step, metric, buckets)
    else:
        batched_step = jax.vmap(mpc_step)

    def rollout(css, states, n_steps):
        def body(carry, _):
            cs, s = carry
            cs, s, stats = batched_step(cs, s)
            # Per-step per-lane consensus iterations ride out of the scan
            # so any cell built on this rollout can record the
            # iters_mean/p99 effort fields (solver-effort observability).
            return (cs, s), stats.iters

        (css, states), iters_seq = jax.lax.scan(
            body, (css, states), None, length=n_steps
        )
        return css, states, iters_seq

    return jax.jit(rollout, static_argnames="n_steps"), css, states


def measure(step, css, states, device, n_steps, n_scenarios, reps=3,
            return_last=False):
    css = jax.device_put(css, device)
    states = jax.device_put(states, device)
    # Compile + warmup at the timed length so the timed calls hit the
    # cache; its wall time is what THIS process paid before its first
    # measured step (previously folded into nothing), returned as
    # compile_wall_s. Under a warm persistent XLA cache that is a
    # cache-load, not a compile — compare rows only under the same
    # _meta.xla_cache_dir state (the sweep stamps it).
    t0 = time.perf_counter()
    out = step(css, states, n_steps)
    jax.block_until_ready(out[1].xl)
    compile_wall_s = time.perf_counter() - t0
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = step(css, states, n_steps)
        jax.block_until_ready(out[1].xl)
        times.append(time.perf_counter() - t0)
    # Median over reps: one-off dispatch/timing glitches produced wildly
    # wrong single-sample readings through the device tunnel.
    rate = n_scenarios * n_steps / float(np.median(times))
    if return_last:
        # The last timed rep's output, for callers that read a result off
        # the measured run (e.g. the fused A/B cells' final consensus
        # residual) without paying an extra rollout.
        return rate, compile_wall_s, out
    return rate, compile_wall_s


def ref_arch_cpu_rate(n=N_AGENTS, max_iter=20, inner_iters=20, n_steps=5):
    """Reference-architecture CPU baseline: sequential per-agent native conic
    solves (C++ f64 ADMM standing in for Clarabel) inside the C-ADMM consensus
    loop, one scenario at a time — the reference's execution model
    (rqp_cadmm.py:631-675). Only the solve loop + consensus bookkeeping are
    timed (QP assembly / env query / physics excluded — generous).
    Returns MPC steps/s, or None if the native solver is unavailable."""
    from tpu_aerial_transport import native
    from tpu_aerial_transport.control import cadmm

    if not native.available():
        return None
    params, col, state0, forest, f_eq, ll, acc_des = _setup(n)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=max_iter, inner_iters=inner_iters,
    )
    state = state0.replace(vl=jnp.array([0.5, 0.0, 0.0], jnp.float32))
    env_cbfs = cadmm.agent_env_cbfs(params, cfg, forest, state)
    onehots = jnp.eye(n, dtype=jnp.float32)
    leaders = (jnp.arange(n) == 0).astype(jnp.float32)
    rho = float(cfg.rho0)
    P, q0, A, lb, ub, shift = jax.vmap(
        lambda oh, ld, cbf: cadmm._build_agent_qp(
            params, cfg, f_eq, state, acc_des, cbf, oh, ld, rho
        )
    )(onehots, leaders, env_cbfs)
    P, q0, A, lb, ub, shift = (np.asarray(x, np.float64)
                               for x in (P, q0, A, lb, ub, shift))
    n_box = 13 + cfg.n_env_cbfs

    f_eq_np = np.asarray(f_eq, np.float64)
    f = np.tile(f_eq_np, (n, 1, 1))  # (n, n, 3) local copies.
    lam = np.zeros_like(f)
    f_mean = f_eq_np.copy()
    warms = [None] * n

    # State evolves between control steps (untimed physics, same two-rate
    # pattern as the TPU bench) so warm starts face a moving target — without
    # this the repeated identical state converges in one consensus iteration
    # and flatters the baseline.
    from tpu_aerial_transport.models import rqp as rqp_mod

    def advance(state, f_app):
        fz = jnp.sum(jnp.asarray(f_app, jnp.float32) * state.R[..., :, 2],
                     axis=-1)
        for _ in range(10):
            state = rqp_mod.integrate(
                params, state, (fz, jnp.zeros((n, 3), jnp.float32)), 1e-3
            )
        return state

    def rebuild(state):
        cbfs = cadmm.agent_env_cbfs(params, cfg, forest, state)
        out = jax.vmap(
            lambda oh, ld, cbf: cadmm._build_agent_qp(
                params, cfg, f_eq, state, acc_des, cbf, oh, ld, rho
            )
        )(onehots, leaders, cbfs)
        return tuple(np.asarray(x, np.float64) for x in out)

    t_total = 0.0
    for _ in range(n_steps):
        t0 = time.perf_counter()
        for _it in range(max_iter):
            for i in range(n):  # THE reference's sequential agent loop.
                q = q0[i].copy()
                q[9:] += (lam[i] - rho * f_mean).reshape(-1)
                x, y, z, prim, dual = native.solve_socp_native(
                    P[i], q, A[i], lb[i], ub[i], n_box=n_box,
                    soc_dims=(4, 4), iters=inner_iters, shift=shift[i],
                    warm=warms[i],
                )
                warms[i] = (x, y, z)
                f[i] = x[9:].reshape(n, 3)
            f_mean = f.mean(axis=0)
            res = np.abs(f - f_mean[None]).max()
            if res < cfg.res_tol:
                break
            lam += rho * (f - f_mean[None])
        t_total += time.perf_counter() - t0
        # Untimed: physics + QP re-assembly for the next step.
        f_app = np.stack([f[i, i] for i in range(n)])
        state = advance(state, f_app)
        P, q0, A, lb, ub, shift = rebuild(state)
    return n_steps / t_total


def headline(profile_dir: str | None = None, platform: str = "unknown",
             socp_fused: str = "auto", buckets: int = 0,
             inner_tol: float = 0.0, backend_note: str | None = None):
    on_cpu = platform == "cpu"
    timed_steps = CPU_TIMED_STEPS if on_cpu else TIMED_STEPS
    step, css, states = build(socp_fused=socp_fused, buckets=buckets,
                              inner_tol=inner_tol)
    compile_wall_s = None
    if profile_dir:
        # Warm up outside the trace so the profile shows steady-state execution.
        _, compile_wall_s = measure(
            step, css, states, jax.devices()[0], timed_steps, N_SCENARIOS
        )
        # Compiled-HLO dump next to the trace: op_name metadata maps each
        # instruction to its tat.* named scope, which op_profile.py
        # --by-phase rolls op self-time up to (CPU traces carry no per-
        # event tf_op stat, so the dump is the attribution source there).
        try:
            os.makedirs(profile_dir, exist_ok=True)
            hlo_text = step.lower(css, states, timed_steps).compile().as_text()
            with open(os.path.join(profile_dir, "headline.hlo.txt"),
                      "w") as fh:
                fh.write(hlo_text)
        except Exception as e:  # profiling aid only — never sink the bench.
            print(f"# headline HLO dump failed: {e}", flush=True)
        with jax.profiler.trace(profile_dir):
            tpu_rate, _ = measure(
                step, css, states, jax.devices()[0], timed_steps, N_SCENARIOS
            )
    else:
        tpu_rate, compile_wall_s = measure(
            step, css, states, jax.devices()[0], timed_steps, N_SCENARIOS
        )
    if on_cpu:
        vs_xla_cpu = 1.0  # the measurement IS the XLA-CPU rate.
    else:
        try:
            cpu_rate, _ = measure(
                step, css, states, jax.devices("cpu")[0], CPU_TIMED_STEPS,
                N_SCENARIOS,
            )
            vs_xla_cpu = tpu_rate / cpu_rate
        except Exception:
            vs_xla_cpu = float("nan")
    try:
        ref_rate = ref_arch_cpu_rate()
        vs_ref = tpu_rate / ref_rate if ref_rate else float("nan")
    except Exception:
        vs_ref = float("nan")

    out = {
        "metric": HEADLINE_METRIC,
        "value": _finite_or_none(tpu_rate, 1),
        "unit": "scenario-MPC-steps/s",
        "platform": platform,
        # Alias of platform: the backend the number was MEASURED on, under
        # the key name the fallback contract promises ("backend": "cpu"
        # marks an XLA-CPU fallback record — a valid point on the CPU
        # trajectory, not comparable to TPU rounds; no more null-valued
        # holes). "platform" is retained for cross-round record
        # compatibility; after a fallback both are the measured backend
        # and "backend_note" carries why.
        "backend": platform,
        # vs the reference's execution model (sequential native per-agent
        # solves on CPU, BASELINE.json's 'cvxpy/Clarabel CPU baseline').
        # Denominator history: r1 used TPU/XLA-CPU; r2+ use TPU/ref-arch-CPU —
        # the explicit aliases below disambiguate cross-round comparisons.
        "vs_baseline": _finite_or_none(vs_ref),
        "vs_ref_arch_cpu": _finite_or_none(vs_ref),
        "vs_xla_cpu": _finite_or_none(vs_xla_cpu),
        # First-call wall time (compile + warmup) — what a fresh process
        # pays before its first measured step (previously folded into
        # nothing; under --profile it comes from the pre-trace warmup).
        "compile_wall_s": (None if compile_wall_s is None
                           else round(compile_wall_s, 2)),
    }
    if backend_note:
        out["backend_note"] = backend_note
    print(json.dumps(out))


def _single_stream(controller, n, n_steps=50, pad_operators=None):
    """Single-scenario MPC rate + p50 control-call time per consensus iteration
    (the BASELINE.json 'p50 solve-time/ADMM-iter' metric; the centralized
    controller has no consensus loop — reference SolverStatistics reports
    iter = -1 — so the per-iteration metric is omitted for it).

    The ``n_steps`` rollout runs as ONE on-device ``lax.scan`` and the wall
    time is divided by ``n_steps``: per-call host dispatch through the device
    tunnel is ~100 ms, which would otherwise swamp the few-ms step compute."""
    mpc_step, cs0, state0 = make_mpc_step(controller, n,
                                          pad_operators=pad_operators)
    state0 = state0.replace(vl=jnp.array([0.5, 0.0, 0.0], jnp.float32))

    def roll(cs, state):
        def body(carry, _):
            cs, s = carry
            cs, s, stats = mpc_step(cs, s)
            return (cs, s), stats.iters

        (cs, s), iters = jax.lax.scan(body, (cs, state), None, length=n_steps)
        return cs, s, iters

    jitted = jax.jit(roll)
    t0 = time.perf_counter()
    cs, s, iters = jitted(cs0, state0)  # compile + warmup.
    jax.block_until_ready(s.xl)
    compile_wall_s = time.perf_counter() - t0
    # Median-of-3 like measure(): a single timed call was the dominant
    # noise source on shared/cpu-share-throttled hosts (observed 2x
    # run-to-run swings on identical programs).
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        cs, s, iters = jitted(cs0, state0)
        jax.block_until_ready(s.xl)
        times.append(time.perf_counter() - t0)
    per_step = float(np.median(times)) / n_steps
    iters = np.asarray(iters)
    # These are scan-amortized MEANS over n_steps (per-step host timing is
    # impossible without paying ~100 ms dispatch per step); with warm-started
    # steady-state steps the mean tracks the median closely.
    out = {
        "mpc_steps_per_sec": 1.0 / per_step,
        "step_ms_mean": per_step * 1e3,
        "compile_wall_s": compile_wall_s,
    }
    # Time per consensus/ADMM iteration — the BASELINE.json metric. Only
    # meaningful for the distributed solvers (centralized reports iters = -1,
    # reference SolverStatistics semantics).
    if (iters > 0).any():
        p50_iters = float(np.median(iters[iters > 0]))
        out["p50_iters"] = p50_iters
        out["ms_per_consensus_iter"] = per_step * 1e3 / p50_iters
    return out


def _single_stream_donated(controller, n, n_steps=50, reps=3):
    """Donation-clean single-stream step time: the rollout jit DONATES its
    (ctrl_state, physics-state) carries and the reps CHAIN outputs back as
    inputs — the serving pattern (state updated in place across calls; no
    fresh HBM buffers per call). Chained reps measure warm steady state, so
    this column is reported next to — not instead of — the replay-from-init
    ``step_ms_mean`` the scaling table tracks against the recorded
    baseline."""
    mpc_step, cs0, state0 = make_mpc_step(controller, n)
    state0 = state0.replace(vl=jnp.array([0.5, 0.0, 0.0], jnp.float32))

    def roll(cs, state):
        def body(carry, _):
            cs, s = carry
            cs, s, _ = mpc_step(cs, s)
            return (cs, s), None

        return jax.lax.scan(body, (cs, state), None, length=n_steps)[0]

    jitted = jax.jit(roll, donate_argnums=(0, 1))
    # Decouple constant-deduped leaves before donating (see
    # harness.rollout.jit_rollout's shared-buffer caveat).
    t0 = time.perf_counter()
    cs, s = jitted(*jax.tree.map(jnp.copy, (cs0, state0)))
    jax.block_until_ready(s.xl)
    compile_wall_s = time.perf_counter() - t0
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        cs, s = jitted(cs, s)
        jax.block_until_ready(s.xl)
        times.append(time.perf_counter() - t0)
    return {"step_ms_donated": float(np.median(times)) / n_steps * 1e3,
            "compile_wall_s": compile_wall_s}


SCALING_PATH = "BENCH_SCALING.json"


def scaling(out_path: str = SCALING_PATH):
    """Per-n scaling table + padded-vs-unpadded A/B (the n = 64 consensus
    cliff as a first-class metric). For each consensus controller and
    n in {4, 16, 64}: single-stream ``step_ms_mean`` with the tile-padded
    operator layout (the default) and with ``pad_operators=False`` (the
    historical sub-tile layout), plus a donation-clean chained column at
    the cliff sizes. Runs on whatever backend is up — the reference
    baseline for the cliff is BASELINE.md's recorded ``cadmm_n64_single``
    10.65 ms (round 2, re-measured 11.7 ms on this image's XLA-CPU pre-
    padding). Writes ``BENCH_SCALING.json`` and prints one markdown table
    + one final JSON line."""
    platform = jax.devices()[0].platform
    results = {"_meta": {"platform": platform, "git_head": _git_head()}}
    for ctrl in ("cadmm", "dd"):
        for n in (4, 16, 64):
            for padded in (True, False):
                key = f"{ctrl}_n{n}_single" + ("" if padded else "_unpadded")
                results[key] = _single_stream(ctrl, n, pad_operators=padded)
                print(f"# {key}: "
                      f"{results[key]['step_ms_mean']:.2f} ms", flush=True)
    for ctrl, n in (("cadmm", 64), ("dd", 64)):
        key = f"{ctrl}_n{n}_single_donated"
        results[key] = _single_stream_donated(ctrl, n)
        print(f"# {key}: {results[key]['step_ms_donated']:.2f} ms",
              flush=True)
    _write_json_atomic(out_path, results)

    print(f"\n| Config ({platform}) | padded ms | unpadded ms | speedup | "
          "donated-chained ms |")
    print("|---|---|---|---|---|")
    for ctrl in ("cadmm", "dd"):
        for n in (4, 16, 64):
            p = results[f"{ctrl}_n{n}_single"]["step_ms_mean"]
            u = results[f"{ctrl}_n{n}_single_unpadded"]["step_ms_mean"]
            d = results.get(f"{ctrl}_n{n}_single_donated", {})
            d_s = (f"{d['step_ms_donated']:.2f}"
                   if "step_ms_donated" in d else "—")
            print(f"| {ctrl} n={n} single-stream | {p:.2f} | {u:.2f} | "
                  f"{u / p:.2f}x | {d_s} |")
    from tpu_aerial_transport.ops import socp as socp_mod

    n64 = results["cadmm_n64_single"]["step_ms_mean"]
    print(json.dumps({
        "metric": "cadmm_n64_single_step_ms",
        "value": round(n64, 2),
        "unit": "ms",
        "backend": platform,
        # What the controllers' "auto" default resolves to HERE — padding
        # is tile prep, ON for tiled backends, OFF on CPU.
        "default_layout": ("padded" if socp_mod.resolve_pad_operators(None)
                           else "unpadded"),
        "unpadded_ms": round(
            results["cadmm_n64_single_unpadded"]["step_ms_mean"], 2
        ),
        "recorded_baseline_ms": 10.65,  # BASELINE.md round 2.
        "vs_recorded_baseline": round(10.65 / n64, 2),
    }), flush=True)


def _iters_stats(iters_seq) -> dict:
    """Solver-effort fields from a rollout's per-step (x per-lane)
    consensus-iteration sequence: mean, exact p99, and the log2-bucket
    histogram (obs.telemetry.iter_histogram — the ONE bucketing
    implementation, right-closed like the in-jit accumulators, so bench
    cells and the telemetry effort section read on the same axis)."""
    from tpu_aerial_transport.obs import telemetry as telemetry_mod

    it = np.asarray(iters_seq).reshape(-1)
    it = it[it >= 0]
    if not it.size:
        return {}
    return {
        "iters_mean": float(it.mean()),
        "iters_p99": float(np.percentile(it, 99)),
        "iters_hist": [int(v) for v in telemetry_mod.iter_histogram(it)],
        "iters_buckets": list(telemetry_mod.ITER_BUCKETS),
    }


def _batched(controller, n, n_scenarios, n_steps=10, socp_fused="auto",
             buckets=0, inner_tol=0.0, substep_unroll=1,
             pad_operators=None):
    step, css, states = build(controller, n, n_scenarios,
                              socp_fused=socp_fused, buckets=buckets,
                              inner_tol=inner_tol,
                              substep_unroll=substep_unroll,
                              pad_operators=pad_operators)
    rate, compile_wall_s, out = measure(
        step, css, states, jax.devices()[0], n_steps, n_scenarios,
        return_last=True,
    )
    return rate, compile_wall_s, _iters_stats(out[2])


def _fused_measure(controller, n, n_scenarios, fused, precision,
                   n_steps=10):
    """Measure one fused-A/B arm: the `_batched` rollout with the inner
    solves pinned to ``fused`` x ``precision``, ALSO returning the final
    step's worst-lane consensus residual (the bf16 parity-bar input) and
    the config's residual tolerance (the bar itself — the paper's 1e-2 N).
    Returns ``(rate, compile_wall_s, final_res, res_bar)``."""
    mpc_step, cs0, state0 = make_mpc_step(
        controller, n, socp_fused=fused, socp_precision=precision
    )
    states = _scenario_batch(state0, n_scenarios)
    css = jax.vmap(lambda _: cs0)(jnp.arange(n_scenarios))
    batched_step = jax.vmap(mpc_step)

    def rollout(css, states, n_steps):
        def body(carry, _):
            cs, s = carry
            cs, s, stats = batched_step(cs, s)
            return (cs, s), jnp.max(stats.solve_res)

        (css, states), res_seq = jax.lax.scan(
            body, (css, states), None, length=n_steps
        )
        return css, states, res_seq[-1]

    step = jax.jit(rollout, static_argnames="n_steps")
    rate, compile_wall_s, out = measure(
        step, css, states, jax.devices()[0], n_steps, n_scenarios,
        return_last=True,
    )
    final_res = float(out[2])
    # The parity bar: the consensus loop's own stop tolerance (reference
    # res_tol = 1e-2 N; DD's prim_inf_tol mirrors it).
    res_bar = 1e-2
    return rate, compile_wall_s, final_res, res_bar


def _fused_ab_cell(controller, n, n_scenarios, fused, precision="f32"):
    """Whole-solve mega-kernel A/B cell (ops/socp.py fused="kernel" vs
    "scan"), with the bf16-storage arm gated on the consensus-residual
    parity bar: a bf16 arm whose final worst-lane consensus residual
    fails the bar (>= the paper's 1e-2 N tolerance) REFUSES — the cell
    re-measures at f32 and records the refusal — so a chip round can
    never read a non-converging bf16 rate as a win. The gate decision
    lands on the cell as ``precision`` (requested) + ``precision_resolved``
    (what was measured), the ``impl``/``impl_resolved`` pattern of the
    ring A/B cells; ``fused``/``fused_resolved`` record the trace-time
    off-TPU downgrade (kernel -> scan on a CPU rung) the same way."""
    from tpu_aerial_transport.control import cadmm as cadmm_mod
    from tpu_aerial_transport.control import dd as dd_mod
    from tpu_aerial_transport.ops import socp as socp_mod

    # Resolve the mode THE SAME WAY solve_socp's dispatch will — through
    # the one shared resolver, at this cell's actual per-agent operator
    # dims (the padded tier when pad_operators resolves on, raw
    # otherwise) — so a VMEM-fits fallback or off-TPU downgrade can never
    # leave a scan measurement labeled as a kernel verdict.
    params, col, *_ = _setup(n)
    if controller == "cadmm":
        dims_cfg = cadmm_mod.make_config(
            params, col.collision_radius, col.max_deceleration,
            socp_fused=fused, socp_precision=precision,
        )
        _, _, nv_p, n_box_p, m_p = cadmm_mod._qp_dims(dims_cfg, n)
    else:
        dims_cfg = dd_mod.make_config(
            params, col.collision_radius, col.max_deceleration,
            socp_fused=fused, socp_precision=precision,
        )
        _, _, nv_p, n_box_p, m_p = dd_mod._qp_dims(dims_cfg)
    # Chunking folded into the shared resolver (the fused cells run
    # unchunked — inner_tol 0 — but the label and dispatch must share
    # the one decision either way).
    fused_resolved = socp_mod.runtime_fused_mode(
        fused, nv_p, m_p, n_box_p, check_every=0, tol=0.0
    )
    # Off the kernel path the precision knob is inert (bit-identical scan
    # program — asserted in tests/test_fused_solve.py): resolve it to f32
    # up front so a CPU-rung bf16 cell is labeled as the f32 scan
    # measurement it actually is.
    precision_eff = precision if fused_resolved in (
        "kernel", "kernel_interpret") else "f32"
    rate, compile_wall_s, final_res, res_bar = _fused_measure(
        controller, n, n_scenarios, fused, precision_eff
    )
    value = {
        "scenario_mpc_steps_per_sec": rate,
        "agent_mpc_steps_per_sec": rate * n,
        "compile_wall_s": compile_wall_s,
        "fused": fused,
        "fused_resolved": fused_resolved,
        "precision": precision,
        "precision_resolved": precision_eff,
        "final_consensus_res": final_res,
        "res_bar": res_bar,
    }
    if precision_eff == "bf16" and not (final_res < res_bar):
        # The bf16 arm missed the bar — measure the f32 twin to tell a
        # REAL refusal (bf16 broke a convergence f32 achieves) from an
        # inconclusive operating point (benchmark configs often run to
        # the iteration cap above the bar in EITHER precision — a
        # cap-railed f32 residual means the bar cannot indict bf16 here).
        rate32, compile32, res32, _ = _fused_measure(
            controller, n, n_scenarios, fused, "f32"
        )
        if res32 < res_bar:
            # Parity-bar refusal: record the f32 measurement as the
            # cell's rate — one a deployment could actually run at.
            value.update({
                "scenario_mpc_steps_per_sec": rate32,
                "agent_mpc_steps_per_sec": rate32 * n,
                "compile_wall_s": compile_wall_s + compile32,
                "precision_resolved": "f32",
                "final_consensus_res": res32,
                "bf16_refused": True,
                "bf16_final_consensus_res": final_res,
                "bf16_rate_unusable": rate,
            })
        else:
            value.update({
                "res_bar_inconclusive": True,
                "f32_final_consensus_res": res32,
            })
    return value


def _effort_measure(controller, n, n_scenarios, effort, n_steps=10):
    """Measure one effort-A/B arm: the batched rollout with the consensus
    controllers' effort knob pinned to ``effort``, returning the rate,
    compile wall, per-step x per-lane consensus-iteration sequence, the
    per-step inner-iteration totals (adaptive arm only — fixed stages no
    accounting; its inner effort is the static budget), and the final
    worst-lane consensus residual (the equal-quality bar input)."""
    adaptive = effort == "adaptive"
    mpc_step, cs0, state0 = make_mpc_step(controller, n, effort=effort)
    states = _scenario_batch(state0, n_scenarios)
    css = jax.vmap(lambda _: cs0)(jnp.arange(n_scenarios))
    batched_step = jax.vmap(mpc_step)

    def rollout(css, states, n_steps):
        def body(carry, _):
            cs, s = carry
            cs, s, stats = batched_step(cs, s)
            extras = (stats.iters, jnp.max(stats.solve_res))
            if adaptive:
                extras = extras + (stats.inner_iters,)
            return (cs, s), extras

        (css, states), extras = jax.lax.scan(
            body, (css, states), None, length=n_steps
        )
        return (css, states) + extras

    step = jax.jit(rollout, static_argnames="n_steps")
    rate, compile_wall_s, out = measure(
        step, css, states, jax.devices()[0], n_steps, n_scenarios,
        return_last=True,
    )
    iters_seq = np.asarray(out[2])
    final_res = float(np.asarray(out[3])[-1])
    inner_seq = np.asarray(out[4]) if adaptive else None
    return rate, compile_wall_s, iters_seq, inner_seq, final_res


def _effort_ab_cell(controller, n, n_scenarios, effort):
    """Adaptive-solver-effort A/B cell (the controllers' ``effort`` knob,
    socp.resolve_effort): fixed vs adaptive twins at the same operating
    point, recording the rate, the consensus-iteration histogram fields
    (``iters_mean``/``iters_p99``/``iters_hist`` — the straggler-spread
    evidence the flip criterion reads), the adaptive arm's inner-effort
    accounting, and the final consensus residual against the paper's
    1e-2 N bar (an adaptive "win" above the bar its fixed twin meets is
    a quality regression, not a flip candidate — the criterion is
    written at socp.resolve_effort). ``effort``/``effort_resolved``
    follow the impl/impl_resolved convention; effort has no backend
    downgrade, so they differ only for "auto"."""
    from tpu_aerial_transport.control import cadmm as cadmm_mod
    from tpu_aerial_transport.control import dd as dd_mod
    from tpu_aerial_transport.ops import socp as socp_mod

    effort_resolved = socp_mod.resolve_effort(effort)
    # Label the solve impl the cell ACTUALLY dispatches through the ONE
    # shared resolver, WITH the chunking mode the adaptive arm forces
    # (check_every/tol — the tolerance-chunked early-exit path; fixed
    # arms run unchunked unless inner_tol says otherwise): the
    # fused_resolved label and solve_socp's dispatch share the decision.
    params, col, *_ = _setup(n)
    if controller == "cadmm":
        dims_cfg = cadmm_mod.make_config(
            params, col.collision_radius, col.max_deceleration,
            effort=effort_resolved,
        )
        base_cfg = dims_cfg
        _, _, nv_p, n_box_p, m_p = cadmm_mod._qp_dims(dims_cfg, n)
        default_tol = base_cfg.solver_tol
    else:
        dims_cfg = dd_mod.make_config(
            params, col.collision_radius, col.max_deceleration,
            effort=effort_resolved,
        )
        base_cfg = dims_cfg.base
        _, _, nv_p, n_box_p, m_p = dd_mod._qp_dims(dims_cfg)
        default_tol = dd_mod.ADAPTIVE_GATE_TOL  # gate-only default.
    # The chunking the controller ACTUALLY dispatches with (read from
    # the config, not re-hardcoded here — the label and the dispatch
    # must come from the same values).
    tol_eff = (base_cfg.inner_tol if base_cfg.inner_tol > 0
               else default_tol)
    adaptive = effort_resolved == "adaptive"
    fused_resolved = socp_mod.runtime_fused_mode(
        "auto", nv_p, m_p, n_box_p,
        check_every=(base_cfg.inner_check_every if adaptive else 0),
        tol=(tol_eff if adaptive else 0.0),
    )
    rate, compile_wall_s, iters_seq, inner_seq, final_res = _effort_measure(
        controller, n, n_scenarios, effort_resolved
    )
    value = {
        "scenario_mpc_steps_per_sec": rate,
        "agent_mpc_steps_per_sec": rate * n,
        "compile_wall_s": compile_wall_s,
        "effort": effort,
        "effort_resolved": effort_resolved,
        "fused": "auto",
        "fused_resolved": fused_resolved,
        "final_consensus_res": final_res,
        # The equal-quality bar for the flip criterion: the consensus
        # loop's own stop tolerance (the paper's res_tol = 1e-2 N).
        "res_bar": 1e-2,
        **_iters_stats(iters_seq),
    }
    if inner_seq is not None:
        from tpu_aerial_transport.obs import telemetry as telemetry_mod

        # PER-SOLVE effort (inner total / consensus iters / n agents —
        # the telemetry accumulators' scale-free axis).
        per_solve = inner_seq.reshape(-1) / np.maximum(
            np.asarray(iters_seq).reshape(-1), 1
        ) / n
        value.update({
            "inner_iters_mean_per_step": float(inner_seq.mean()),
            "inner_per_solve_mean": float(per_solve.mean()),
            "inner_per_solve_p99": float(np.percentile(per_solve, 99)),
            "inner_hist": [
                int(v) for v in telemetry_mod.iter_histogram(per_solve)
            ],
        })
    return value


# Largest world the DENSE env-query arm is measured at on a cell budget:
# the dense sweep materializes (B, T, G=33) grid-evaluation intermediates
# — ~2.2 GB of f32 at B=64, T=65536 — and its compile+measure wall blows
# the cell deadline well before that. Dense arms above this are recorded
# as SKIPPED-with-reason cells (never silently absent): the whole point
# of the A/B is that dense CANNOT run the city-scale worlds the bucketed
# tier opens.
DENSE_ENV_CELL_MAX_TREES = 16384

# Jittered-grid tree density for the env-query cells' city worlds
# [trees/m^2]: just under the reference MIN_DIST_BETWEEN_TREES packing
# limit (1/3.2^2 ~ 0.0977), so the generated worlds are legal reference
# forests, only bigger.
ENV_CELL_DENSITY = 0.085


def _env_world(n_trees, seed=0):
    """A forest with exactly ``n_trees`` trees: the reference 200-tree
    mountain world at the paper's size, a jittered-grid city world
    (square tree counts) above it."""
    import math

    from tpu_aerial_transport.envs import forest as forest_mod

    if n_trees <= forest_mod.MAX_TREES:
        return forest_mod.make_forest(seed=seed, max_trees=n_trees), 28.0
    n_side = math.isqrt(n_trees)
    if n_side * n_side != n_trees:
        raise ValueError(f"n_trees={n_trees}: env cells use square "
                         "jittered-grid worlds")
    pitch = 1.0 / math.sqrt(ENV_CELL_DENSITY)
    world_size = (n_side + 0.5) * pitch
    forest = forest_mod.make_forest(
        seed=seed, max_trees=n_trees, world_size=world_size,
        density=ENV_CELL_DENSITY,
    )
    return forest, world_size / 2.0 * 0.9


def _env_query_cell(impl, n_trees, n_scenarios=64, n_steps=10):
    """Environment-query A/B cell (envs/spatial.py): the batched capsule
    query running end-to-end through ``collision_cbf_rows`` (sweep +
    top-k + CBF row construction) at world size ``n_trees``, dense vs
    bucketed arms. Fields follow the ring/fused cell conventions:
    ``env_query``/``env_query_resolved`` label the impl through the ONE
    shared resolver (spatial.runtime_env_query — the same decision that
    dispatches), and the bucketed arm records the grid-occupancy
    telemetry (``grid``: K, cell count, max/mean occupancy — the
    overflow/occupancy record the build-time refusal pairs with). The
    flip criterion for the "auto" threshold is written at
    ``spatial.resolve_env_query``."""
    from tpu_aerial_transport.envs import forest as forest_mod
    from tpu_aerial_transport.envs import spatial as spatial_mod
    from tpu_aerial_transport.harness import setup as setup_mod

    _, col, _ = setup_mod.rqp_setup(4)
    vision_radius = col.collision_radius + 5.0
    forest, half_extent = _env_world(n_trees)
    value = {"n_trees": n_trees, "env_query": impl}
    if impl == "bucketed":
        forest = spatial_mod.with_grid(
            forest, vision_radius + forest.bark_radius
        )
        value["grid"] = spatial_mod.grid_stats(forest.grid)
    value["env_query_resolved"] = spatial_mod.runtime_env_query(
        impl, forest
    )

    def one(x, v):
        return forest_mod.collision_cbf_rows(
            forest, x, v, col.collision_radius, col.max_deceleration,
            vision_radius, 0.1, 1.5, 10, env_query=impl,
        )

    batched = jax.vmap(one)
    rng = np.random.default_rng(0)
    xs = jnp.asarray(
        np.concatenate(
            [rng.uniform(-half_extent, half_extent, (n_scenarios, 2))
             + np.asarray(forest_mod.MOUNTAIN_CENTER),
             np.full((n_scenarios, 1), 2.0)], axis=1),
        jnp.float32,
    )
    vs = jnp.asarray(rng.normal(size=(n_scenarios, 3)) * 0.5, jnp.float32)

    def roll(xs, vs, n_steps):
        def body(x, _):
            cbf = batched(x, vs)
            # Drift the batch so every scan step is a fresh query (no
            # loop-invariant hoisting of the sweep).
            return x + 0.05, (cbf.min_dist, cbf.collision)
        _, outs = jax.lax.scan(body, xs, None, length=n_steps)
        return outs

    step = jax.jit(roll, static_argnames="n_steps")
    t0 = time.perf_counter()
    jax.block_until_ready(step(xs, vs, n_steps))
    compile_wall_s = time.perf_counter() - t0
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(step(xs, vs, n_steps))
        times.append(time.perf_counter() - t0)
    rate = n_scenarios * n_steps / float(np.median(times))
    value.update({
        "scenario_env_queries_per_sec": rate,
        "compile_wall_s": compile_wall_s,
        "n_scenarios": n_scenarios,
    })
    return value


def _measured_iter_ms(controller, n, k_lo=4, k_hi=24, n_steps=30):
    """MEASURED per-consensus-iteration latency (not p50-divided): run the
    single-stream rollout with the consensus loop forced to a fixed
    iteration count (stop tolerance 0 never triggers, so every step runs
    exactly ``max_iter + 1`` iterations) at two counts and difference the
    scan-amortized wall times — fixed per-step work (env query, QP build,
    low-level, physics) cancels exactly.

    Max-over-agents semantics (reference rqp_cadmm.py:649 times each
    consensus iteration as the max over per-agent solve times): the vmapped
    agent batch executes all n solves in lockstep inside one program, so a
    batched iteration's wall time IS the slowest agent's — the same
    statistic by construction."""
    per_step = {}
    compile_wall_s = 0.0
    for k in (k_lo, k_hi):
        mpc_step, cs0, state0 = make_mpc_step(
            controller, n, max_iter=k, force_fixed_iters=True
        )
        state0 = state0.replace(vl=jnp.array([0.5, 0.0, 0.0], jnp.float32))

        def roll(cs, state):
            def body(carry, _):
                cs, s = carry
                cs, s, _ = mpc_step(cs, s)
                return (cs, s), None

            return jax.lax.scan(body, (cs, state), None, length=n_steps)[0]

        jitted = jax.jit(roll)
        t0 = time.perf_counter()
        cs, s = jitted(cs0, state0)
        jax.block_until_ready(s.xl)
        compile_wall_s += time.perf_counter() - t0
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            cs, s = jitted(cs0, state0)
            jax.block_until_ready(s.xl)
            times.append(time.perf_counter() - t0)
        per_step[k] = float(np.median(times)) / n_steps
    return {
        "ms_per_consensus_iter_measured":
            (per_step[k_hi] - per_step[k_lo]) / (k_hi - k_lo) * 1e3,
        "fixed_iter_step_ms": {str(k): v * 1e3 for k, v in per_step.items()},
        "compile_wall_s": compile_wall_s,  # both fixed-iter arms summed.
    }


def _sharded_ab_cell(controller, n, impl, n_steps=10, max_iter=8):
    """Consensus-exchange A/B (parallel/ring.py): the agent-sharded MPC
    step — full hot path: env CBFs, consensus solve, low-level + physics —
    with the cross-shard exchange pinned to ``impl`` ("allreduce" psum
    barriers / "ring" ppermute hops / "pallas_ring" async-DMA kernel),
    scanned ``n_steps`` on a mesh over every available device that divides
    ``n``. On one device the cell degenerates (axis_size 1 → no exchange)
    but still measures the sharded program; the multi-device twins are the
    A/B. ``pallas_ring`` downgrades to the XLA ring off-TPU at trace time
    (``ring._resolve_impl``) — so a backend-guard CPU re-run of the pallas
    cell measures the ring; the ``rung`` + ``impl_resolved`` fields keep
    that legible."""
    from tpu_aerial_transport.control import cadmm as cadmm_mod
    from tpu_aerial_transport.control import dd as dd_mod
    from tpu_aerial_transport.parallel import mesh as mesh_mod
    from tpu_aerial_transport.parallel import ring as ring_mod

    params, col, state0, forest, f_eq, ll, acc_des = _setup(n)
    # Devices of the platform the cell EFFECTIVELY runs on: under the
    # backend guard's CPU fallback (run_on_cpu's jax.default_device(cpu)
    # context) jax.devices() would still enumerate the wedged chip and
    # commit the shard_map right back to it.
    devs = jax.devices(ring_mod.effective_platform())
    ndev = len(devs)
    n_shards = max(d for d in range(1, min(ndev, n) + 1) if n % d == 0)
    m = mesh_mod.make_mesh({"agent": n_shards}, devices=devs)
    if controller == "cadmm":
        cfg = cadmm_mod.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=max_iter, inner_iters=20, consensus_impl=impl,
        )
        cs0 = cadmm_mod.init_cadmm_state(params, cfg)
        step = mesh_mod.cadmm_control_sharded(params, cfg, f_eq, m, forest)
    else:
        cfg = dd_mod.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=max_iter, inner_iters=40, consensus_impl=impl,
        )
        cs0 = dd_mod.init_dd_state(params, cfg)
        step = mesh_mod.dd_control_sharded(params, cfg, f_eq, m, forest)
    state0 = state0.replace(vl=jnp.array([0.5, 0.0, 0.0], jnp.float32))

    def roll(cs, state, n_steps):
        def body(carry, _):
            cs, s = carry
            f, cs, _ = step(cs, s, acc_des)
            return (cs, _substeps(params, ll, s, f)), None

        return jax.lax.scan(body, (cs, state), None, length=n_steps)[0]

    jitted = jax.jit(roll, static_argnames="n_steps")
    t0 = time.perf_counter()
    out = jitted(cs0, state0, n_steps=n_steps)
    jax.block_until_ready(jax.tree.leaves(out)[0])
    compile_wall_s = time.perf_counter() - t0
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = jitted(cs0, state0, n_steps=n_steps)
        jax.block_until_ready(jax.tree.leaves(out)[0])
        times.append(time.perf_counter() - t0)
    return {
        "mpc_steps_per_sec": n_steps / float(np.median(times)),
        "impl": impl,
        "impl_resolved": ring_mod._resolve_impl(impl),
        "devices": n_shards,
        "n": n,
        "compile_wall_s": compile_wall_s,
    }


def _donated_resume_cell(n=4, n_hl_steps=8, n_chunks=4):
    """Donated-vs-undonated chunked-resume A/B — the bench side of the
    PR-4 TC105 wart (ROADMAP "KNOWN WART"): the recovery tier defaults
    ``donate=False`` because donated chunk carries on XLA-CPU under the
    persistent compilation cache can flip low-order result bits with
    allocation history, breaking bit-exact resume. This cell measures, on
    whatever backend the sweep runs at, (a) the wall-time cost of that
    default (donated vs undonated chunked rollout) and (b) whether the
    donated arm IS bit-identical here — the next chip round reads this
    cell to decide whether ``recovery`` can flip its default on TPU
    (expected placement-stable)."""
    from tpu_aerial_transport.control import cadmm as cadmm_mod
    from tpu_aerial_transport.harness import rollout as ro

    params, col, state0, forest, f_eq, ll, _ = _setup(n)
    cfg = cadmm_mod.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=8, inner_iters=10,
    )
    plan = cadmm_mod.make_plan(params, cfg)
    cs0 = cadmm_mod.init_cadmm_state(params, cfg)
    state0 = state0.replace(vl=jnp.array([0.5, 0.0, 0.0], jnp.float32))
    x0 = state0.xl

    def acc_des_fn(state, t):
        del t
        dvl = -1.0 * state.vl - 1.0 * (state.xl - x0)
        return (dvl, jnp.zeros(3, state.xl.dtype)), x0, jnp.zeros(3)

    def hl(cs, s, a):
        return cadmm_mod.control(
            params, cfg, f_eq, cs, s, a, forest, plan=plan
        )

    def run_arm(donate):
        runner = ro.make_chunked_rollout(
            hl, ll.control, params, n_hl_steps=n_hl_steps,
            n_chunks=n_chunks, acc_des_fn=acc_des_fn, donate=donate,
        )

        def once():
            # Fresh decoupled copies per call: donated buffers are
            # consumed (and constant-deduped leaves must not be donated
            # twice — the jit_rollout shared-buffer caveat).
            s0, c0 = jax.tree.map(jnp.copy, (state0, cs0))
            fs, fc, _ = runner(s0, c0)
            jax.block_until_ready(fs.xl)
            return fs, fc

        t0 = time.perf_counter()
        once()  # compile + warm.
        compile_wall_s = time.perf_counter() - t0
        times, finals = [], []
        for _ in range(3):
            t0 = time.perf_counter()
            finals.append(once())
            times.append(time.perf_counter() - t0)
        # finals[-2:] are same-program replays with different allocation
        # history — exactly the axis the XLA-CPU wart varies along.
        return (float(np.median(times)) / n_hl_steps * 1e3, finals,
                compile_wall_s)

    undonated_ms, finals_u, compile_u = run_arm(False)
    donated_ms, finals_d, compile_d = run_arm(True)

    def bitexact(a, b):
        return bool(all(
            np.array_equal(np.asarray(x), np.asarray(y))
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
        ))

    return {
        "donated_ms_per_step": donated_ms,
        "undonated_ms_per_step": undonated_ms,
        "speedup": undonated_ms / donated_ms,
        # THE wart question: can resume rely on donated chunk carries?
        "donated_bitexact_vs_undonated": bitexact(finals_d[-1], finals_u[-1]),
        "donated_replay_bitexact": bitexact(finals_d[-1], finals_d[-2]),
        "n": n, "chunks": n_chunks,
        "compile_wall_s": compile_u + compile_d,  # both arms summed.
    }


# Cold-start ladder A/B (tpu_aerial_transport/aot/): what a FRESH process
# pays to serve its first registered control step, one cell per
# fallback-ladder rung. The entry is the registered C-ADMM control step —
# the program every serving replica dispatches first.
COLDSTART_ENTRY = "control.cadmm:control"
COLDSTART_SERVE_TIMEOUT_S = 420.0
COLDSTART_BUILD_TIMEOUT_S = 600.0


def _coldstart_cell(mode: str, platform: str) -> dict:
    """Time-to-first-step of a fresh subprocess serving
    :data:`COLDSTART_ENTRY` through ``tools/aot_bundle.py serve``:

    - ``bundled``: from the AOT bundle's precompiled executable — the
      zero-compile acceptance row (``--expect-zero-compile``: the child
      exits 3 if it traced/lowered/compiled ANYTHING);
    - ``cached``: ordinary jit under a WARM persistent XLA cache (the
      cell clears a cell-private cache dir, pays one unmeasured populate
      run, then measures — a fleet's steady state, not first-populate);
    - ``cold``: ordinary jit, no cache — the pre-bundle world.

    Self-contained: the bundled arm (re)builds ``artifacts/aot/<platform>``
    first — exec artifacts bind to the exact jaxlib/XLA fingerprint, so
    serving a stale cached bundle would silently measure the export rung
    instead. Build/populate run OUTSIDE the measured window (separate
    subprocesses); every subprocess runs group-killable under its own
    timeout (resilience.backend.run_group). The child's ladder rung is
    returned as ``serve_rung`` — the ``rung`` key belongs to the backend
    guard."""
    from tpu_aerial_transport.resilience import backend as backend_mod
    from tpu_aerial_transport.utils.platform import XLA_CACHE_ENV

    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "aot_bundle.py")
    env = dict(os.environ, JAX_PLATFORMS=platform)
    # The rung under test is the ONLY warm state the child sees: the
    # parent's cache knob must not leak into the bundled/cold arms.
    env[XLA_CACHE_ENV] = ""
    bundle_dir = os.path.join("artifacts", "aot", platform)
    cache_dir = os.path.join("artifacts", "aot", f"xla-cache-{platform}")

    def run(cmd, timeout_s):
        proc = backend_mod.run_group(cmd, timeout_s, env=env)
        if proc.returncode != 0:
            tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
            raise RuntimeError(
                f"coldstart_{mode} child rc={proc.returncode}: "
                + " | ".join(tail)
            )
        return proc

    serve_cmd = [sys.executable, tool, "serve",
                 "--entry", COLDSTART_ENTRY, "--mode", mode]
    if mode == "bundled":
        run([sys.executable, tool, "build", "--out", bundle_dir,
             "--entry", COLDSTART_ENTRY], COLDSTART_BUILD_TIMEOUT_S)
        serve_cmd += ["--bundle", bundle_dir, "--expect-zero-compile"]
    elif mode == "cached":
        import shutil

        shutil.rmtree(cache_dir, ignore_errors=True)
        run(serve_cmd + ["--cache-dir", cache_dir],
            COLDSTART_SERVE_TIMEOUT_S)  # populate, unmeasured.
        serve_cmd += ["--cache-dir", cache_dir]

    t0 = time.monotonic()
    proc = run(serve_cmd, COLDSTART_SERVE_TIMEOUT_S)
    wall = round(time.monotonic() - t0, 2)
    row = None
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            row = json.loads(line)
            break
        except ValueError:
            continue
    if not isinstance(row, dict) or "ttfs_s" not in row:
        raise RuntimeError(
            f"coldstart_{mode}: no JSON row in serve output"
        )
    row["serve_rung"] = row.pop("rung")
    row["process_wall_s"] = wall
    return row


def _serving_cell(families=("cadmm4",), n_requests: int = 64,
                  buckets=(8, 16), seed: int = 0,
                  rate_scale: float = 2.0, surgery=None, dispatch=None,
                  trace: bool = False) -> dict:
    """Continuous-batching serving-tier cell (tpu_aerial_transport/
    serving/): a Poisson request stream through the ScenarioServer on the
    jit rung, reporting completed scenario-MPC-steps/s and mean batch
    occupancy. The Poisson rate is calibrated from a warmup chunk so the
    arrival load saturates the largest bucket (``rate_scale`` × one
    bucket of arrivals per chunk wall) on any host — the acceptance bar
    is mean occupancy >= 0.75 on the CPU tier. Compilation of every
    (family, bucket) program happens in the warmup, OUTSIDE the timed
    window, and is reported as compile_wall_s like every other cell.

    ``surgery``/``dispatch`` forward the ISSUE-18 serving knobs (the
    ``serving_surgery_*`` / ``serving_dispatch_*`` A/B cells); ``trace``
    runs the host tracer and reports the critical-path boundary-stall
    decomposition (surgery+publish+harvest+batch_wait per completed
    request — the dispatch knob's flip criterion) plus a content digest
    of every completed result so the A/B arms assert equal outputs, not
    just comparable walls."""
    import hashlib

    from tpu_aerial_transport.obs import trace as trace_lib
    from tpu_aerial_transport.serving import batcher, lanes
    from tpu_aerial_transport.serving import server as server_mod
    from tpu_aerial_transport.serving.queue import ScenarioRequest

    fams = [batcher.make_family(f) for f in families]
    buckets = tuple(sorted(buckets))
    surgery_mode = lanes.resolve_surgery(surgery)
    if lanes.resolve_dispatch(dispatch) == "pipelined":
        surgery_mode = "device"

    # Warm every (family, bucket) compiled program; time the warmup as
    # the cell's compile cost and one warm chunk for rate calibration.
    t0 = time.perf_counter()
    for fam in fams:
        for b in buckets:
            carry = jax.tree.map(
                lambda x: np.stack([np.asarray(x)] * b),
                fam.template_carry_host(),
            )
            jax.block_until_ready(fam.batched_jit(carry, np.int32(0)))
            if surgery_mode == "device" and fam.surgery_entry:
                probe = ScenarioRequest(
                    family=fam.name, horizon=fam.chunk_len,
                    x0=(0.1, 0.0, 0.0),
                )
                sargs = lanes.make_surgery_args(
                    fam.batched_template_host(b), [(0, probe)], [1], b
                )
                carry = jax.tree.map(
                    lambda x: np.stack([np.asarray(x)] * b),
                    fam.template_carry_host(),
                )
                jax.block_until_ready(fam.surgery_jit(carry, *sargs))
    compile_wall_s = time.perf_counter() - t0
    fam0 = fams[0]
    carry = jax.tree.map(
        lambda x: np.stack([np.asarray(x)] * buckets[-1]),
        fam0.template_carry_host(),
    )
    t0 = time.perf_counter()
    jax.block_until_ready(fam0.batched_jit(carry, np.int32(0)))
    chunk_wall_s = max(time.perf_counter() - t0, 1e-4)
    rate_hz = rate_scale * buckets[-1] * len(fams) / chunk_wall_s

    tracer = trace_lib.Tracer(track="bench") if trace else None
    srv = server_mod.ScenarioServer(
        families=fams, buckets=buckets, capacity=4 * n_requests,
        surgery=surgery, dispatch=dispatch, tracer=tracer,
    )
    rng = np.random.default_rng(seed)
    stream = []
    for i in range(n_requests):
        fam = fams[int(rng.integers(len(fams)))]
        stream.append(ScenarioRequest(
            family=fam.name,
            horizon=int(rng.integers(1, 4)) * fam.chunk_len,
            x0=tuple(float(v) for v in rng.normal(0, 1.0, 3)),
            # Deterministic ids: the default process-global counter would
            # make result_digest differ across arms of the same sweep.
            request_id=f"bench{i:05d}",
        ))
    tickets = []
    t0 = time.perf_counter()
    next_due = t0
    while stream or srv.has_work():
        now = time.perf_counter()
        while stream and now >= next_due:
            tickets.append(srv.submit(stream.pop(0)))
            next_due += rng.exponential(1.0 / rate_hz)
        srv.pump()
    wall_s = time.perf_counter() - t0
    stats = srv.stats()
    # Content digest of the completed results IN SUBMIT ORDER: the A/B
    # arms run the same seeded stream, so equal digests mean the knob
    # changed nothing but the wall clock (the bitwise contract).
    h = hashlib.sha256()
    for t in tickets:
        if t.result is not None:
            h.update(t.request.request_id.encode())
            for leaf in jax.tree.leaves(t.result):
                h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    out = {
        "scenario_mpc_steps_per_sec": stats["scenario_steps"] / wall_s,
        "mean_occupancy": stats["mean_occupancy"],
        "completed": stats.get("completed", 0),
        "requests": stats["requests"],
        "poisson_rate_hz": round(rate_hz, 1),
        "surgery": stats["surgery"],
        "dispatch": stats["dispatch"],
        "result_digest": h.hexdigest()[:16],
        "compile_wall_s": compile_wall_s,
    }
    if tracer is not None:
        cp = trace_lib.critical_path(tracer.rows)
        per = cp.get("per_segment", {})
        stall = ("batch_wait", "surgery", "publish", "harvest")
        out["boundary_stall_s_per_request"] = (
            sum(per[s]["mean"] for s in stall if s in per)
        )
        out["segments_mean_s"] = {
            s: round(st["mean"], 6) for s, st in per.items()
        }
    return out


def _serving_donate_cell(canonical: str = "cadmm4", bucket: int = 8,
                         n_boundaries: int = 6) -> dict:
    """Donated-vs-undonated serving boundary carry A/B — the serving
    twin of ``chunked_resume_donate_ab``. The loop each arm times is the
    device-surgery server's steady state: batched chunk -> lane surgery
    (one late join, one filler reset mid-run), carry device-resident
    throughout. The donated arm is the registered
    ``serving.lanes:lane_surgery`` jit (TC105, donate_argnums=(0,)); the
    undonated arm is the same program without aliasing. Bit-identity
    fields answer the same wart question as the resume cell: can a
    serving replica rely on donated boundary carries on THIS backend
    (selects copy bits, so only allocation-history effects could
    differ)."""
    from tpu_aerial_transport.serving import batcher, lanes
    from tpu_aerial_transport.serving.queue import ScenarioRequest

    fam = batcher.make_family(canonical)
    template_b = fam.batched_template_host(bucket)
    probe = ScenarioRequest(
        family=canonical, horizon=fam.chunk_len, x0=(0.2, -0.1, 0.05),
        v0=(0.0, 0.02, 0.0),
    )

    def run_arm(donate):
        surgery = jax.jit(
            lanes.lane_surgery,
            donate_argnums=(0,) if donate else (),
        )
        chunk = fam.batched_jit  # shared, non-donating (both arms).

        def once():
            carry = jax.tree.map(
                lambda x: np.array(np.asarray(x), copy=True), template_b
            )
            for k in range(n_boundaries):
                carry, _logs = chunk(
                    carry, np.int32(k * fam.chunk_len)
                )
                joins = [(0, probe)] if k == 1 else []
                resets = [1] if k == 2 else []
                sargs = lanes.make_surgery_args(
                    template_b, joins, resets, bucket
                )
                carry, harvested = surgery(carry, *sargs)
            jax.block_until_ready(carry)
            return jax.tree.map(np.asarray, carry)

        t0 = time.perf_counter()
        once()  # compile + warm.
        compile_wall_s = time.perf_counter() - t0
        times, finals = [], []
        for _ in range(3):
            t0 = time.perf_counter()
            finals.append(once())
            times.append(time.perf_counter() - t0)
        return (float(np.median(times)) / n_boundaries * 1e3, finals,
                compile_wall_s)

    undonated_ms, finals_u, compile_u = run_arm(False)
    donated_ms, finals_d, compile_d = run_arm(True)

    def bitexact(a, b):
        return bool(all(
            np.array_equal(np.asarray(x), np.asarray(y))
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
        ))

    return {
        "donated_ms_per_boundary": donated_ms,
        "undonated_ms_per_boundary": undonated_ms,
        "speedup": undonated_ms / donated_ms,
        "donated_bitexact_vs_undonated": bitexact(
            finals_d[-1], finals_u[-1]
        ),
        "donated_replay_bitexact": bitexact(finals_d[-1], finals_d[-2]),
        "bucket": bucket, "boundaries": n_boundaries,
        "compile_wall_s": compile_u + compile_d,
    }


# Pods-tier weak-scaling cells (tools/pods_local.py localhost harness):
# fixed per-process work (PODS_SCENARIOS_PER_PROC scenarios x 8 agents),
# 1 process vs 2 — the 2-process arm IS the 1024-agent BASELINE config
# (128 payloads x 8 quads) run end-to-end through the pods tier.
PODS_TIMEOUT_S = 1500.0
PODS_SCENARIOS_PER_PROC = 64
PODS_STEPS = 4
PODS_MAX_ITER = 6
PODS_LOCAL_DEVICES = 4


def _pods_cell(processes: int, scenarios: int, n: int = 8,
               steps: int = PODS_STEPS, max_iter: int = PODS_MAX_ITER,
               local_devices: int = PODS_LOCAL_DEVICES) -> dict:
    """One pods weak-scaling cell: run the multi-process localhost
    harness (coordinator + N group-killable workers, CPU backend,
    TAT_VIRTUAL_DEVICES virtual devices each) under a deadline and parse
    its one-line JSON. The harness's own topology gate
    (``pods.check_topology``) raises a classified ``topology_mismatch``
    inside the workers; a 1-core host returns a written ``skipped``
    reason instead of flaking. Workers watch their parent pid, so a
    deadline group-kill here cannot orphan the gloo rendezvous."""
    from tpu_aerial_transport.resilience import backend as backend_mod

    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "pods_local.py")
    cmd = [sys.executable, tool, "--mode", "bench",
           "--processes", str(processes),
           "--local-devices", str(local_devices),
           "--n", str(n), "--scenarios", str(scenarios),
           "--steps", str(steps), "--max-iter", str(max_iter),
           "--timeout", str(PODS_TIMEOUT_S - 120)]
    proc = backend_mod.run_group(cmd, PODS_TIMEOUT_S)
    row = None
    for line in reversed((proc.stdout or "").strip().splitlines()):
        try:
            row = json.loads(line)
            break
        except ValueError:
            continue
    if not isinstance(row, dict):
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
        raise RuntimeError(
            f"pods harness rc={proc.returncode}: " + " | ".join(tail)
        )
    if "error" in row:
        # Surfaces the workers' classified failure (topology_mismatch,
        # wedge...) to the guard's classifier.
        raise RuntimeError(f"pods harness failed: {row['error']}"[:400])
    return row


SWEEP_PARTIAL_PATH = "BENCH_SWEEP_PARTIAL.json"
SWEEP_JOURNAL_PATH = "BENCH_SWEEP_JOURNAL.jsonl"
SWEEP_METRICS_PATH = "artifacts/bench_sweep.metrics.jsonl"


def _annotate_topology(value):
    """Additive topology fields on every sweep cell (plain v2 bench_cell
    fields, no schema bump): ``n_devices`` / ``n_processes`` from the
    subprocess probe's report (falling back to the live counts — by
    record time the cell already initialized the backend), plus a
    ``mesh`` shape where the cell implies one (the sharded A/B cells'
    agent mesh; pods cells carry their own). A chip-round record can
    never again be ambiguous about what topology measured it
    (MULTICHIP_r01's 1-of-8-devices round was exactly that ambiguity).

    Cells the guard DEGRADED to the CPU rung get the CPU fallback's own
    topology, not the probed accelerator's — stamping the chip's mesh on
    a cpu-tagged cell would be the ambiguity this field exists to kill.
    Error cells measured nothing and are left unstamped."""
    if not isinstance(value, dict) or "error" in value:
        return value
    from tpu_aerial_transport.resilience import backend as backend_mod

    fell_back = (value.get("rung") == backend_mod.RUNG_CPU
                 and _PROBE_INFO.get("platform") not in (None, "cpu"))
    if fell_back:
        value.setdefault("n_devices", len(jax.devices("cpu")))
        value.setdefault("n_processes", jax.process_count())
    else:
        value.setdefault(
            "n_devices",
            _PROBE_INFO.get("n_devices", len(jax.devices())),
        )
        value.setdefault(
            "n_processes",
            _PROBE_INFO.get("n_processes", jax.process_count()),
        )
    if "mesh" not in value:
        value["mesh"] = ({"agent": value["devices"]}
                         if "devices" in value else None)
    return value


def _git_head() -> str:
    """HEAD SHA with a ``-dirty`` suffix when the tree has uncommitted
    changes (mid-debug edits must invalidate sweep checkpoints too);
    ``unknown`` when git is unavailable (treated as never matching)."""
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            cwd=here, timeout=10,
        )
        head = out.stdout.strip()
        if not head:
            return "unknown"
        # Tracked files only: the sweep's own untracked checkpoint file must
        # not mark the tree dirty (that would always refuse resume).
        dirty = subprocess.run(
            ["git", "status", "--porcelain", "--untracked-files=no"],
            capture_output=True, text=True, cwd=here, timeout=10,
        ).stdout.strip()
        return head + ("-dirty" if dirty else "")
    except Exception:
        return "unknown"


def _write_json_atomic(path: str, payload) -> None:
    """Temp-file + os.replace so an abrupt death mid-write (the exact crash
    the checkpoint exists to survive) cannot truncate the checkpoint."""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=1)
    os.replace(tmp, path)


def sweep(resume: bool = False, platform: str | None = None,
          trace: str | None = None):
    """Full BASELINE.json matrix. Each measured config ("chunk" of the
    sweep) is journaled to ``BENCH_SWEEP_JOURNAL.jsonl`` (the same
    append-only fsync'd jsonl ``resilience.recovery`` uses for rollout
    chunks — truncation-tolerant, so a crash mid-append costs one cell,
    not the file) and checkpointed to ``BENCH_SWEEP_PARTIAL.json``;
    ``--resume`` restores completed cells from the journal instead of
    restarting — the axon tunnel has died mid-sweep (~1.5-2 h of compiles)
    more than once, and without checkpointing every completed config was
    lost with it. Both records are stamped with the git HEAD they were
    measured at; resuming across code changes is refused so stale numbers
    cannot silently mix into BENCH_SWEEP.json. A resumed sweep reports
    ``resumed_from_chunk`` (restored-cell count) in its ``_meta`` and in
    the final JSON line (tools/bench_retry.py passes ``--resume`` on
    retry attempts and forwards the field)."""
    from tpu_aerial_transport.obs import export as export_mod
    from tpu_aerial_transport.resilience import backend as backend_mod
    from tpu_aerial_transport.resilience.recovery import RunJournal

    head = _git_head()
    journal = RunJournal(".", filename=SWEEP_JOURNAL_PATH)
    results = {"_meta": {
        "git_head": head,
        # compile_wall_s provenance: under a warm persistent cache the
        # first call is a cache-load, not a compile — rows are only
        # comparable across rounds under the same cache state.
        "xla_cache_dir": jax.config.jax_compilation_cache_dir or None,
        # What the subprocess probe saw (platform/devices/processes) —
        # the round-level topology record (per-cell fields ride on each
        # value via _annotate_topology).
        **({"topology": dict(_PROBE_INFO)} if _PROBE_INFO else {}),
    }}
    if os.path.exists(SWEEP_PARTIAL_PATH) and not resume:
        raise SystemExit(
            f"{SWEEP_PARTIAL_PATH} exists (a crashed sweep's checkpoint, "
            "possibly hours of measurements). Pass --resume to continue it, "
            "or delete the file to start fresh — refusing to overwrite."
        )
    resumed_from_chunk = 0
    legacy_cells: dict = {}
    if resume and (journal.exists() or os.path.exists(SWEEP_PARTIAL_PATH)):
        cached_head, cached_cells = "missing", {}
        if journal.exists():
            # The journal is the source of truth (latest event per cell
            # wins, so a retried error cell shows its newest outcome).
            for e in journal.read():
                if e.get("event") == "run_start":
                    cached_head = e.get("git_head", "missing")
                elif e.get("event") == "cell":
                    cached_cells[e["cell"]] = e["value"]
        else:  # pre-journal partial checkpoint (older crashed sweep).
            with open(SWEEP_PARTIAL_PATH) as fh:
                cached = json.load(fh)
            cached_head = cached.get("_meta", {}).get("git_head", "missing")
            cached_cells = {k: v for k, v in cached.items() if k != "_meta"}
            # Re-journal below: without cell events for these, a SECOND
            # crash+resume would read the (journal-first) empty journal
            # and silently re-measure every legacy cell.
            legacy_cells = cached_cells
        # 'unknown'/'-dirty' states never match safely: dirty trees can
        # differ between the two runs even at the same SHA.
        if cached_head != head or "unknown" in (cached_head, head) \
                or head.endswith("-dirty"):
            raise SystemExit(
                f"refusing --resume: the sweep journal was measured at "
                f"git {cached_head[:19]} but HEAD is {head[:19]} — the cached "
                "numbers could silently mix with post-change ones. Delete "
                f"{SWEEP_JOURNAL_PATH} and {SWEEP_PARTIAL_PATH} to start "
                "fresh."
            )
        results.update(cached_cells)
        resumed_from_chunk = len(cached_cells)
        results["_meta"]["resumed_from_chunk"] = resumed_from_chunk
        print(f"# resuming sweep from journal: {resumed_from_chunk} cells "
              f"cached ({sorted(k for k in results if k != '_meta')})",
              flush=True)
    elif journal.exists():
        # Fresh start over a stale journal (its partial twin is gone, so
        # the old sweep either completed or was deliberately reset).
        os.remove(journal.path)
    if not any(e.get("event") == "run_start" for e in journal.read()):
        journal.append({"event": "run_start", "mode": "sweep",
                        "git_head": head})
    for key, value in legacy_cells.items():
        journal.append({"event": "cell", "cell": key, "value": value})

    # Test/debug hook: TAT_SWEEP_CELLS=<regex> restricts which cells run
    # (the fault-injection end-to-end test sweeps a cheap subset; a human
    # debugging one cell re-measures just it). Parsed BEFORE the metrics
    # writer: a cell-filtered run must APPEND to the tracked flight
    # recorder, not reset it (see below).
    cells_spec = os.environ.get("TAT_SWEEP_CELLS", "")
    cells_pat = re.compile(cells_spec) if cells_spec else None

    # Flight-recorder export (obs.export): one bench_cell event per
    # measured config, appended across --resume attempts; a fresh FULL
    # sweep resets the file with the journal. A CELL-FILTERED run
    # appends instead — resetting would replace the whole tracked trail
    # with the filtered subset (the same footgun the BENCH_SWEEP.json
    # carried_cells provenance exists for). tools/run_health.py renders
    # it, tools/ci_check.sh schema-validates it.
    if not resume and cells_pat is None \
            and os.path.exists(SWEEP_METRICS_PATH):
        os.remove(SWEEP_METRICS_PATH)
    metrics = export_mod.MetricsWriter(
        SWEEP_METRICS_PATH,
        meta=(None if os.path.exists(SWEEP_METRICS_PATH)
              else {"mode": "sweep", "git_head": head,
                    "resumed_from_chunk": resumed_from_chunk}),
    )

    def record(key, value):
        value = _annotate_topology(value)
        results[key] = value
        journal.append({"event": "cell", "cell": key, "value": value})
        metrics.emit("bench_cell", cell=key, value=value)
        _write_json_atomic(SWEEP_PARTIAL_PATH, results)
        print(f"# {key}: {value}", flush=True)

    # Backend guard (resilience.backend): every cell's compile+measure
    # runs under a deadline watchdog; classified infra failures (wedge,
    # init, crash, oom) trip the per-backend circuit breaker and the cell
    # re-runs on the tagged XLA-CPU rung — the sweep CONTINUES and each
    # cell records the rung it actually ran at, instead of a wedged chip
    # eating the round (the r03-r05 failure mode). backend_event rows land
    # in both the sweep journal (resume keeps them) and the metrics file.
    # The primary rung comes from the (subprocess-watchdogged) probe that
    # ensure_backend already ran — resolving it via jax.default_backend()
    # here would be the first IN-PROCESS backend init, unwatchdogged on
    # this thread (the guard only pays that inside run()'s deadline).
    # --trace: wire a span tracer through the guard so every guarded
    # cell records a guard_dispatch span (label + rung + classified
    # failure kind) — "where did the sweep's wall time go" as one
    # Perfetto timeline. The sink is the sweep metrics writer (the
    # durable-jsonl rule every other traced surface follows), so a
    # sweep that dies mid-run keeps its recorded spans; the Chrome file
    # at the end is a rendering of them, not the only copy.
    tracer = None
    if trace:
        from tpu_aerial_transport.obs import trace as trace_lib

        tracer = trace_lib.Tracer(metrics, track="sweep")
    guard = backend_mod.BackendGuard(
        metrics=metrics, journal=journal, tracer=tracer,
        primary_rung=(None if platform is None else
                      backend_mod.RUNG_CPU if platform == "cpu"
                      else backend_mod.RUNG_ONCHIP),
    )

    def want(key: str) -> bool:
        return cells_pat is None or bool(cells_pat.search(key))

    # A cell-filtered run re-measures ONLY the matching cells: carry the
    # existing BENCH_SWEEP.json's other cells forward instead of silently
    # replacing hours of prior measurements with a near-empty record. The
    # mixed provenance is stamped, never silent: _meta lists the carried
    # cells and the head they were measured at.
    if cells_pat is not None and os.path.exists("BENCH_SWEEP.json"):
        try:
            with open("BENCH_SWEEP.json") as fh:
                prior = json.load(fh)
        except ValueError:
            prior = {}
        carried = {k: v for k, v in prior.items()
                   if k != "_meta" and not cells_pat.search(k)
                   and k not in results}
        if carried:
            results.update(carried)
            results["_meta"]["carried_cells"] = sorted(carried)
            results["_meta"]["carried_from_head"] = (
                prior.get("_meta", {}).get("git_head", "unknown"))

    def guarded_cell(key, fn, *args, unpadded=False, **kw):
        """Measure one cell through the guard; the returned value dict
        carries ``rung`` (on-chip / on-chip-unpadded / cpu-tagged)."""
        rung = None
        if unpadded and guard.primary_rung == backend_mod.RUNG_ONCHIP:
            rung = backend_mod.RUNG_ONCHIP_UNPADDED
        value, ran_at = guard.run(
            key, lambda: fn(*args, **kw),
            fallback_fn=backend_mod.run_on_cpu(lambda: fn(*args, **kw)),
            rung=rung,
        )
        return {**value, "rung": ran_at}

    def _batched_cell(kw) -> dict:
        rate, compile_wall_s, iters_stats = _batched(
            kw["controller"], kw["n"], kw["n_scenarios"],
            socp_fused=kw.get("socp_fused", "auto"),
            buckets=kw.get("buckets", 0),
            inner_tol=kw.get("inner_tol", 0.0),
            substep_unroll=kw.get("substep_unroll", 1),
            pad_operators=kw.get("pad_operators"))
        return {"scenario_mpc_steps_per_sec": rate,
                "agent_mpc_steps_per_sec": rate * kw["n"],
                "compile_wall_s": compile_wall_s,
                **iters_stats}

    # Consensus-exchange A/B cells (parallel/ring.py) — run FIRST with the
    # other decision cells: the next chip round reads the
    # {cadmm,dd}_n*_sharded_{ring,pallas_ring} twins against their
    # _allreduce baselines to decide the non-CPU default (flip criterion
    # written at ring.resolve_consensus), and the donated-resume A/B to
    # decide the recovery tier's TC105 donate default. Meaningful on ANY
    # backend (the CPU mesh measures the XLA ring's bookkeeping cost;
    # pallas cells are chip-only). TAT_SWEEP_SHARDED_N is a test/debug
    # hook shrinking the agent count (the fault-injection e2e sweeps a
    # cheap n=4 twin; keys carry the actual n).
    # Platform for cell-selection decisions: the (subprocess-watchdogged)
    # probe's verdict when the caller passed one — touching
    # jax.devices() here would be the first IN-PROCESS backend init,
    # unwatchdogged on this thread (the guard only pays that inside
    # run()'s deadline; see the guard comment above).
    sweep_platform = platform or jax.devices()[0].platform
    ab_n = int(os.environ.get("TAT_SWEEP_SHARDED_N", "64"))
    ring_impls = ["allreduce", "ring"]
    if sweep_platform != "cpu":
        ring_impls.append("pallas_ring")
    for ctrl in ("cadmm", "dd"):
        for impl in ring_impls:
            key = f"{ctrl}_n{ab_n}_sharded_{impl}"
            if not want(key) or (key in results
                                 and "error" not in results[key]):
                continue
            try:
                record(key, guarded_cell(
                    key, _sharded_ab_cell, ctrl, ab_n, impl,
                ))
            except Exception as e:
                record(key, {"error": f"{type(e).__name__}: {e}"[:300]})
    key = "chunked_resume_donate_ab"
    if want(key) and not (key in results and "error" not in results[key]):
        try:
            record(key, guarded_cell(key, _donated_resume_cell))
        except Exception as e:
            record(key, {"error": f"{type(e).__name__}: {e}"[:300]})

    # Whole-solve mega-kernel A/B cells (ops/socp.py fused="kernel" — the
    # "attack the 84%" decision cells): scan vs kernel twins at n in
    # {16, 64} for both consensus controllers, plus the bf16-storage arm
    # gated on the consensus-residual parity bar (_fused_ab_cell). Run on
    # ANY backend: off-TPU the kernel downgrades to scan at trace time
    # (fused_resolved records it), so a CPU round produces rung-tagged
    # baseline rows and the chip round overwrites them with the real
    # verdict — the flip criterion is written at socp.resolve_fused.
    for ctrl in ("cadmm", "dd"):
        for n_f, ns_f in ((16, 64), (64, 16)):
            fused_cells = [
                (f"{ctrl}_n{n_f}_fused_scan", dict(fused="scan")),
                (f"{ctrl}_n{n_f}_fused_kernel", dict(fused="kernel")),
                (f"{ctrl}_n{n_f}_fused_kernel_bf16",
                 dict(fused="kernel", precision="bf16")),
            ]
            for key, kw in fused_cells:
                if not want(key) or (key in results
                                     and "error" not in results[key]):
                    continue
                try:
                    record(key, guarded_cell(
                        key, _fused_ab_cell, ctrl, n_f, ns_f, **kw,
                    ))
                except Exception as e:
                    record(key, {"error": f"{type(e).__name__}: {e}"[:300]})

    # Adaptive-solver-effort A/B cells (the controllers' effort knob,
    # socp.resolve_effort — the "converged lanes shouldn't pay for
    # stragglers" decision cells): fixed vs adaptive twins at n in
    # {16, 64} for both consensus controllers, recording rate + the
    # consensus-iteration histogram fields + the equal-quality residual
    # bar. Meaningful on ANY backend — adaptivity is pure XLA on the scan
    # path (the kernel path additionally keeps its in-kernel early exit
    # on-chip), so a CPU round is a real A/B, not just a baseline row;
    # the flip criterion is written at socp.resolve_effort.
    for ctrl in ("cadmm", "dd"):
        for n_f, ns_f in ((16, 64), (64, 16)):
            for eff in ("fixed", "adaptive"):
                key = f"{ctrl}_n{n_f}_effort_{eff}"
                if not want(key) or (key in results
                                     and "error" not in results[key]):
                    continue
                try:
                    record(key, guarded_cell(
                        key, _effort_ab_cell, ctrl, n_f, ns_f, eff,
                    ))
                except Exception as e:
                    record(key, {"error": f"{type(e).__name__}: {e}"[:300]})

    # Environment-query A/B cells (envs/spatial.py — the city-scale
    # world decision cells): dense vs bucketed arms of the batched
    # capsule query through collision_cbf_rows at T in {200, 4096,
    # 65536} trees. Meaningful on ANY backend (the gather + sweep math
    # is pure XLA); dense arms above DENSE_ENV_CELL_MAX_TREES are
    # recorded as SKIPPED-with-reason cells — the (B, T, G) dense
    # intermediates blow the cell's memory/deadline budget, which IS the
    # finding (dense cannot run the worlds the bucketed tier opens).
    # The "auto"-threshold flip criterion is written at
    # spatial.resolve_env_query.
    for env_impl in ("dense", "bucketed"):
        for n_trees in (200, 4096, 65536):
            key = f"env_{env_impl}_T{n_trees}"
            if not want(key) or (key in results
                                 and "error" not in results[key]):
                continue
            if env_impl == "dense" and n_trees > DENSE_ENV_CELL_MAX_TREES:
                record(key, {
                    "skipped": True,
                    "reason": (
                        f"dense arm at T={n_trees}: the O(T) sweep "
                        f"materializes (B, T, 33) grid intermediates "
                        f"(~{64 * n_trees * 33 * 4 / 1e9:.1f} GB f32 per "
                        "buffer at B=64) and blows the cell "
                        "memory/deadline budget — the bucketed twin "
                        "measures this world; recorded, not hidden"),
                    "env_query": env_impl, "n_trees": n_trees,
                })
                continue
            try:
                record(key, guarded_cell(
                    key, _env_query_cell, env_impl, n_trees,
                ))
            except Exception as e:
                record(key, {"error": f"{type(e).__name__}: {e}"[:300]})

    # Cold-start ladder A/B (tpu_aerial_transport/aot/): time-to-first-
    # step of a FRESH process per fallback-ladder rung — the zero-compile
    # acceptance row reads coldstart_bundled.ttfs_s against
    # coldstart_cold.ttfs_s (≥5x on the CPU tier). Fresh subprocesses, so
    # the parent's compile/cache state cannot leak into any arm; each
    # cell's serve rung lands in the metrics file as an aot_serve event
    # (schema v3) for tools/run_health.py. Meaningful on any backend; the
    # guard's CPU fallback re-measures the ladder on the host.
    cs_platform = sweep_platform
    for cs_mode in ("bundled", "cached", "cold"):
        key = f"coldstart_{cs_mode}"
        if not want(key) or (key in results
                             and "error" not in results[key]):
            continue
        try:
            # The cell's own child timeouts legitimately allow build +
            # serve (bundled) or populate + serve (cached) — the guard's
            # default 600 s deadline would misclassify a healthy slow
            # build as wedge_timeout (a breaker strike) AND leave the
            # abandoned build child racing the CPU fallback's rebuild
            # into the same bundle dir.
            value, ran_at = guard.run(
                key,
                lambda m=cs_mode: _coldstart_cell(m, cs_platform),
                fallback_fn=lambda m=cs_mode: _coldstart_cell(m, "cpu"),
                deadline_s=(COLDSTART_BUILD_TIMEOUT_S
                            + 2 * COLDSTART_SERVE_TIMEOUT_S + 60.0),
            )
            record(key, {**value, "rung": ran_at})
            metrics.emit(
                "aot_serve", entry=COLDSTART_ENTRY, label=key,
                rung=value["serve_rung"], wall_s=value["ttfs_s"],
            )
        except Exception as e:
            record(key, {"error": f"{type(e).__name__}: {e}"[:300]})
    have = {m: results.get(f"coldstart_{m}") for m in ("bundled", "cold")}
    if (want("coldstart_speedup")
            and "coldstart_speedup" not in results
            and all(v and "ttfs_s" in v for v in have.values())):
        record("coldstart_speedup", {
            # ttfs excludes interpreter + jax import (paid at deploy,
            # before any request — see tools/aot_bundle.py cmd_serve);
            # process_wall is the whole subprocess, import included.
            "bundled_vs_cold_ttfs":
                have["cold"]["ttfs_s"] / have["bundled"]["ttfs_s"],
            "bundled_vs_cold_process_wall":
                have["cold"]["process_wall_s"]
                / have["bundled"]["process_wall_s"],
            "bundled_compiles":
                have["bundled"]["backend_compiles"],
            "cold_compiles": have["cold"]["backend_compiles"],
        })

    # Pods-tier weak-scaling cells (tpu_aerial_transport/parallel/pods.py
    # via the tools/pods_local.py localhost harness): fixed per-process
    # work, 1 vs 2 processes — the 2-process arm runs the 1024-agent
    # BASELINE config (128 payloads x 8 quads) END-TO-END through the
    # multi-process 2-D mesh tier on this host (CPU backend + gloo), so
    # the chip round only has to swap the backend. Fresh subprocess
    # fleets, group-killable, own deadlines (the guard's would
    # misclassify a healthy multi-process compile as a wedge); a 1-core
    # host records the harness's written skip reason as the cell value.
    for key, procs, nsc in (
        ("pods_weakscale_1proc", 1, PODS_SCENARIOS_PER_PROC),
        ("pods_swarm_128x8_2proc", 2, 2 * PODS_SCENARIOS_PER_PROC),
    ):
        if not want(key) or (key in results
                             and "error" not in results[key]):
            continue
        try:
            value, ran_at = guard.run(
                key, lambda p=procs, s=nsc: _pods_cell(p, s),
                deadline_s=PODS_TIMEOUT_S + 60.0,
            )
            record(key, {**value, "rung": ran_at})
        except Exception as e:
            record(key, {"error": f"{type(e).__name__}: {e}"[:300]})
    ws = {k: results.get(k) for k in
          ("pods_weakscale_1proc", "pods_swarm_128x8_2proc")}
    if (want("pods_weakscale") and "pods_weakscale" not in results
            and all(v and "scenario_mpc_steps_per_sec" in v
                    for v in ws.values())):
        r1 = ws["pods_weakscale_1proc"]["scenario_mpc_steps_per_sec"]
        w2 = ws["pods_swarm_128x8_2proc"]
        r2 = w2["scenario_mpc_steps_per_sec"]
        record("pods_weakscale", {
            # Topology of the SCALED-TO arm (the derived cell pairs two
            # topologies; _annotate_topology would otherwise stamp this
            # process's own 1-device view, which is neither).
            "n_processes": w2.get("n_processes"),
            "n_devices": w2.get("n_devices"),
            "mesh": w2.get("mesh"),
            # Weak scaling at fixed per-process work: ideal 2-process
            # rate is 2x the 1-process rate; the shortfall is the pods
            # overhead (cross-process exchange + rendezvous + host
            # contention on this box — the chip round re-reads this cell
            # on real DCN).
            "scenarios_per_process": PODS_SCENARIOS_PER_PROC,
            "rate_1proc": r1,
            "rate_2proc": r2,
            "scaling_efficiency": r2 / (2.0 * r1),
            "overhead_fraction": 1.0 - r2 / (2.0 * r1),
        })

    # Scenario-serving tier cells (tpu_aerial_transport/serving/): the
    # continuous-batching throughput + soak workload the ROADMAP's
    # serving item names — guard-wrapped like every cell, meaningful on
    # any backend (the rung is recorded; CPU is the acceptance tier for
    # mean occupancy >= 0.75 under the Poisson load).
    for key, skw in (
        ("serving_throughput_cadmm4",
         dict(families=("cadmm4",), n_requests=64)),
        ("serving_soak_mixed",
         dict(families=("cadmm4", "centralized4"), n_requests=128)),
        # ISSUE-18 serving-knob A/B cells (serving/lanes.py resolvers).
        # Surgery pair: host splice vs the device-resident donated select
        # program — flip criterion lives in lanes.resolve_surgery.
        # Dispatch pair: sync vs double-buffered chunk dispatch (both on
        # device surgery so ONLY the dispatch mode differs) — the flip
        # reads boundary_stall_s_per_request at equal result_digest
        # (lanes.resolve_dispatch). Traced so the stall decomposition is
        # measured, not inferred.
        ("serving_surgery_host",
         dict(families=("cadmm4",), n_requests=48, surgery="host",
              trace=True)),
        ("serving_surgery_device",
         dict(families=("cadmm4",), n_requests=48, surgery="device",
              trace=True)),
        ("serving_dispatch_sync",
         dict(families=("cadmm4",), n_requests=48, surgery="device",
              dispatch="sync", trace=True)),
        ("serving_dispatch_pipelined",
         dict(families=("cadmm4",), n_requests=48,
              dispatch="pipelined", trace=True)),
    ):
        if not want(key) or (key in results
                             and "error" not in results[key]):
            continue
        try:
            record(key, guarded_cell(key, _serving_cell, **skw))
        except Exception as e:
            record(key, {"error": f"{type(e).__name__}: {e}"[:300]})

    # Donated-vs-undonated serving boundary carry (the serving twin of
    # chunked_resume_donate_ab — TC105's serving-side wart question).
    key = "serving_donate_ab"
    if want(key) and not (key in results
                          and "error" not in results[key]):
        try:
            record(key, guarded_cell(key, _serving_donate_cell))
        except Exception as e:
            record(key, {"error": f"{type(e).__name__}: {e}"[:300]})

    # The round-5 A/B cells run right after the ring/donate decision
    # cells above: if the tunnel dies mid-sweep, the checkpoint must
    # already hold the cells that decide default flips
    # (consensus impl/donate/fused/buckets/inner_tol/unroll), not
    # just the long-standing matrix.
    # A/B cells for the round-4 switches (VERDICT r4 item 6): headline
    # config x {scan, pallas} x {0, 2 buckets}, plus the n=64 fused A/B.
    # TPU-only — the Pallas kernel has no CPU lowering worth timing and the
    # bucketing question (worst-lane while_loop drag) is a device question.
    if sweep_platform != "cpu":
        ab_cells = [
            (f"headline_fused_{fused}_buckets{nb}",
             dict(controller="cadmm", n=N_AGENTS, n_scenarios=N_SCENARIOS,
                  socp_fused=fused, buckets=nb))
            for fused in ("scan", "pallas") for nb in (0, 2)
        ] + [
            (f"cadmm_n64_batch64_fused_{fused}",
             dict(controller="cadmm", n=64, n_scenarios=64, socp_fused=fused))
            for fused in ("scan", "pallas")
        ] + [
            # Tolerance-chunked inner solves (inner_tol): CPU A/B measured
            # 1.67x on DD n=64 but a SLOWDOWN for C-ADMM (0.43-0.89x, knee-
            # sized inner budget — BASELINE.md round 5), so on-chip cells
            # are DD plus one headline confirmation only.
            ("dd_n64_batch64_innertol",
             dict(controller="dd", n=64, n_scenarios=64, inner_tol=2e-3)),
            ("headline_innertol",
             dict(controller="cadmm", n=N_AGENTS, n_scenarios=N_SCENARIOS,
                  inner_tol=2e-3)),
            ("dd_n64_batch64_fused_pallas",
             dict(controller="dd", n=64, n_scenarios=64, socp_fused="pallas")),
            ("dd_n64_batch64_innertol_pallas",
             dict(controller="dd", n=64, n_scenarios=64, socp_fused="pallas",
                  inner_tol=2e-3)),
            # DD worst-lane outer iterations ride the cap harder than
            # C-ADMM's — congestion bucketing may pay off most here.
            ("dd_n64_batch64_buckets2",
             dict(controller="dd", n=64, n_scenarios=64, buckets=2)),
            # Substep-scan unrolling (kernel-count lever; see the _substeps
            # docstring for the rationale and CPU parity measurement).
            ("headline_substep_unroll10",
             dict(controller="cadmm", n=N_AGENTS, n_scenarios=N_SCENARIOS,
                  substep_unroll=10)),
            # Padded-operator A/B (ops/socp.py tile tier, default ON since
            # the tile-alignment round): the unpadded twins quantify the
            # padding win on-chip; the CPU A/B lives in `--scaling`.
            ("headline_unpadded",
             dict(controller="cadmm", n=N_AGENTS, n_scenarios=N_SCENARIOS,
                  pad_operators=False)),
            ("cadmm_n64_batch64_unpadded",
             dict(controller="cadmm", n=64, n_scenarios=64,
                  pad_operators=False)),
            ("dd_n64_batch64_unpadded",
             dict(controller="dd", n=64, n_scenarios=64,
                  pad_operators=False)),
        ]
        for key, kw in ab_cells:
            # An "error" cell is retried on --resume (unlike a measured one):
            # a transient tunnel death must not be checkpointed as a result.
            if not want(key) or (key in results
                                 and "error" not in results[key]):
                continue
            try:
                record(key, guarded_cell(
                    key, _batched_cell, kw,
                    unpadded=kw.get("pad_operators") is False,
                ))
            except Exception as e:
                # Keep going: a Pallas lowering failure that ALSO fails on
                # the CPU rung IS a result for its cell and must not kill
                # the scan/bucket cells after it.
                record(key, {"error": f"{type(e).__name__}: {e}"[:300]})

    # MPC steps/sec/chip at N in {4, 16, 64} for all three controllers.
    for ctrl in ("centralized", "cadmm", "dd"):
        for n in (4, 16, 64):
            key = f"{ctrl}_n{n}_single"
            if key in results or not want(key):
                continue
            record(key, guarded_cell(key, _single_stream, ctrl, n))
    # Measured per-consensus-iteration latency (differenced fixed-iteration
    # runs; see _measured_iter_ms — VERDICT r3 item 7).
    for ctrl in ("cadmm", "dd"):
        for n in (4, 16, 64):
            key = f"{ctrl}_n{n}_iter_latency"
            if key in results or not want(key):
                continue
            record(key, guarded_cell(key, _measured_iter_ms, ctrl, n))
    # Batched throughput (the TPU's actual operating point) at the same Ns.
    for ctrl in ("cadmm", "dd"):
        for n, ns in ((4, 256), (16, 128), (64, 64)):
            key = f"{ctrl}_n{n}_batch{ns}"
            if key in results or not want(key):
                continue
            record(key, guarded_cell(
                key, _batched_cell,
                dict(controller=ctrl, n=n, n_scenarios=ns),
            ))
    # Swarm (BASELINE.json config 5): 128 payloads x 8 quads = 1024 agents.
    if "swarm_128x8" not in results and want("swarm_128x8"):
        record("swarm_128x8", guarded_cell(
            "swarm_128x8", _batched_cell,
            dict(controller="cadmm", n=8, n_scenarios=128),
        ))
    # North-star ratio (BASELINE.json): TPU throughput vs the reference-
    # architecture CPU baseline at 64 agents.
    for n, ns in ((8, 256), (64, 64)):
        ns_key = f"north_star_n{n}"
        if ns_key in results or not want(ns_key):
            continue
        try:
            ref = ref_arch_cpu_rate(n=n, n_steps=3)
        except Exception as e:  # native solver unavailable/failed: keep the
            print(f"# ref_arch_cpu_rate(n={n}) failed: {e}", flush=True)
            ref = None  # TPU measurements already collected above.
        if ref:
            key = f"cadmm_n{n}_batch{ns}"
            if key in results:
                src = results[key]
            else:
                src = guarded_cell(
                    ns_key, _batched_cell,
                    dict(controller="cadmm", n=n, n_scenarios=ns),
                )
            tpu = src["scenario_mpc_steps_per_sec"]
            record(ns_key, {
                "tpu_scenario_mpc_steps_per_sec": tpu,
                "ref_arch_cpu_mpc_steps_per_sec": ref,
                "ratio": tpu / ref,
                # The rung the numerator ACTUALLY ran at: a cpu-tagged
                # rate must never be read as a TPU speedup.
                **({"rung": src["rung"]} if "rung" in src else {}),
            })

    _write_json_atomic("BENCH_SWEEP.json", results)
    metrics.emit("done", chunks=len(results) - 1)
    if tracer is not None and tracer.rows:
        from tpu_aerial_transport.obs import trace as trace_lib

        trace_lib.write_chrome_trace(trace, tracer.rows)
        print(f"# sweep trace: {trace} ({len(tracer.rows)} spans)",
              flush=True)
    if os.path.exists(SWEEP_PARTIAL_PATH):
        os.remove(SWEEP_PARTIAL_PATH)
    if journal.exists():
        os.remove(journal.path)

    # Markdown table for BASELINE.md.
    print("\n| Config | MPC steps/s | mean step ms | ms/consensus-iter "
          "(measured) |")
    print("|---|---|---|---|")
    for ctrl in ("centralized", "cadmm", "dd"):
        for n in (4, 16, 64):
            r = results.get(f"{ctrl}_n{n}_single")
            if r is None:  # filtered out via TAT_SWEEP_CELLS.
                continue
            lat = results.get(f"{ctrl}_n{n}_iter_latency", {})
            per_iter = lat.get("ms_per_consensus_iter_measured")
            per_iter_s = f"{per_iter:.2f}" if per_iter is not None else "—"
            print(f"| {ctrl} n={n} single-stream | "
                  f"{r['mpc_steps_per_sec']:.1f} | {r['step_ms_mean']:.2f} | "
                  f"{per_iter_s} |")
    for key in [k for k in results
                if "batch" in k or "swarm" in k or "fused" in k
                or "innertol" in k or "sharded" in k or "donate" in k
                or "coldstart" in k or "serving" in k or "pods" in k
                or "effort" in k]:
        r = results[key]
        if "error" in r:
            print(f"| {key} | ERROR: {r['error']} | — | — |")
            continue
        if "skipped" in r:
            print(f"| {key} | SKIPPED: {r['skipped']} | — | — |")
            continue
        if "scaling_efficiency" in r:  # derived pods weak-scaling cell.
            print(f"| {key} | {r['scaling_efficiency']:.2f} efficiency at "
                  f"{r['scenarios_per_process']} scenarios/process "
                  f"(overhead {r['overhead_fraction']:.0%}) | — | — |")
            continue
        if "ttfs_s" in r:  # cold-start ladder cell (aot/).
            print(f"| {key} | TTFS {r['ttfs_s']:.2f} s "
                  f"[{r['serve_rung']}, {r['backend_compiles']} compiles, "
                  f"rung={r.get('rung', '?')}] | — | — |")
            continue
        if "bundled_vs_cold_ttfs" in r:  # derived cold-start ratio.
            print(f"| {key} | bundled {r['bundled_vs_cold_ttfs']:.1f}x "
                  f"faster than cold to first step | — | — |")
            continue
        if "mean_occupancy" in r:  # serving-tier cell (serving/).
            occ = r["mean_occupancy"]
            print(f"| {key} | {r['scenario_mpc_steps_per_sec']:.1f} "
                  f"scenario-steps/s [occupancy "
                  f"{occ if occ is None else round(occ, 3)}, "
                  f"{r['completed']}/{r['requests']} completed, "
                  f"rung={r.get('rung', '?')}] | — | — |")
            continue
        if "donated_ms_per_step" in r:  # the donated-resume A/B cell.
            print(f"| {key} | donated {r['donated_ms_per_step']:.2f} ms vs "
                  f"{r['undonated_ms_per_step']:.2f} ms "
                  f"({r['speedup']:.2f}x; bitexact="
                  f"{r['donated_bitexact_vs_undonated']}) | — | — |")
            continue
        if "donated_ms_per_boundary" in r:  # serving-surgery donate A/B.
            print(f"| {key} | donated "
                  f"{r['donated_ms_per_boundary']:.2f} ms/boundary vs "
                  f"{r['undonated_ms_per_boundary']:.2f} ms "
                  f"({r['speedup']:.2f}x; bitexact="
                  f"{r['donated_bitexact_vs_undonated']}) | — | — |")
            continue
        if "scenario_mpc_steps_per_sec" not in r:
            if "mpc_steps_per_sec" in r:  # sharded consensus A/B cell.
                impl_s = (f" [{r['impl']}@{r['devices']}dev"
                          f" rung={r.get('rung', '?')}]"
                          if "impl" in r else "")
                print(f"| {key} | {r['mpc_steps_per_sec']:.1f} "
                      f"MPC-steps/s{impl_s} | — | — |")
            else:
                print(f"| {key} | ERROR: {r.get('error', '?')} | — | — |")
            continue
        agent_s = (f" ({r['agent_mpc_steps_per_sec']:.0f} agent-steps/s)"
                   if "agent_mpc_steps_per_sec" in r else "")
        print(f"| {key} | {r['scenario_mpc_steps_per_sec']:.1f} "
              f"scenario-steps/s{agent_s} | — | — |")
    # Final machine-readable row (tools/bench_retry.py forwards it as the
    # attempt's ``result``): how many cells this sweep holds and how many
    # were restored from the journal rather than re-measured.
    print(json.dumps({
        "metric": "bench_sweep",
        "value": len(results) - 1,
        "unit": "cells",
        "resumed_from_chunk": resumed_from_chunk,
    }), flush=True)


def multichip(n_steps: int = 10, n_swarm: int = 128, reps: int = 3,
              max_iter: int = 20, inner_cadmm: int = 20, inner_dd: int = 40):
    """BASELINE.json multi-device configs, runnable unchanged the day a
    multi-chip slice appears (VERDICT r3 item 6): gated on
    ``len(jax.devices()) > 1``; exercised for shape/compile correctness on
    the virtual 8-device CPU mesh by tests/test_multichip_bench.py.

    Configs (BASELINE.json "benchmark configs" 3-5):
    - ``dd_n16_sharded``: 16-agent DD with agents sharded over the mesh
      (2 agents/device on 8 devices) — psum price sums + all_gathered QN
      dual step over ICI, full MPC step (env CBF + low-level + physics).
    - ``cadmm_n8_sharded``: 8-agent C-ADMM, one agent per device.
    - ``swarm_scenario_sharded``: 128 payloads x 8 quads (1024 agents),
      scenario axis sharded over the mesh (pure data parallelism).
    Emits one JSON line per config.
    """
    from tpu_aerial_transport.control import cadmm as cadmm_mod
    from tpu_aerial_transport.control import dd as dd_mod
    from tpu_aerial_transport.parallel import mesh as mesh_mod

    ndev = len(jax.devices())
    if ndev < 2:
        raise SystemExit(
            f"--multichip needs >1 device, have {ndev}; on a single chip "
            "run the standard modes (for the CPU shape check: "
            "JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_"
            "device_count=8 python bench.py --multichip)"
        )

    def timed_rollout(roll, *args):
        jitted = jax.jit(roll, static_argnames="n_steps")
        out = jitted(*args, n_steps=n_steps)
        jax.block_until_ready(jax.tree.leaves(out)[0])
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = jitted(*args, n_steps=n_steps)
            jax.block_until_ready(jax.tree.leaves(out)[0])
            times.append(time.perf_counter() - t0)
        return n_steps / float(np.median(times))

    results = {}

    # Agent-sharded distributed controllers: full MPC step in a scan.
    for key, ctrl, n in (("dd_n16_sharded", "dd", 16),
                         ("cadmm_n8_sharded", "cadmm", 8)):
        params, col, state0, forest, f_eq, ll, acc_des = _setup(n)
        m = mesh_mod.make_mesh({"agent": min(ndev, n)})
        if ctrl == "dd":
            cfg = dd_mod.make_config(
                params, col.collision_radius, col.max_deceleration,
                max_iter=max_iter, inner_iters=inner_dd,
            )
            cs0 = dd_mod.init_dd_state(params, cfg)
            step = mesh_mod.dd_control_sharded(params, cfg, f_eq, m, forest)
        else:
            cfg = cadmm_mod.make_config(
                params, col.collision_radius, col.max_deceleration,
                max_iter=max_iter, inner_iters=inner_cadmm,
            )
            cs0 = cadmm_mod.init_cadmm_state(params, cfg)
            step = mesh_mod.cadmm_control_sharded(params, cfg, f_eq, m, forest)
        state0 = state0.replace(vl=jnp.array([0.5, 0.0, 0.0], jnp.float32))

        def roll(cs, state, n_steps):
            def body(carry, _):
                cs, s = carry
                f, cs, _ = step(cs, s, acc_des)
                return (cs, _substeps(params, ll, s, f)), None

            return jax.lax.scan(body, (cs, state), None, length=n_steps)[0]

        rate = timed_rollout(roll, cs0, state0)
        results[key] = rate
        print(json.dumps({
            "metric": f"multichip_{key}", "value": _finite_or_none(rate, 1),
            "unit": "MPC-steps/s", "devices": ndev,
            "mesh": {"agent": int(m.shape["agent"])},
        }), flush=True)

    # Scenario-sharded swarm: 128 payloads x 8 quads = 1024 agents.
    step, css, states = build("cadmm", 8, n_swarm)
    m = mesh_mod.make_mesh({"scenario": ndev})
    css = mesh_mod.shard_scenarios(m, css)
    states = mesh_mod.shard_scenarios(m, states)
    out = step(css, states, n_steps)
    jax.block_until_ready(out[1].xl)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = step(css, states, n_steps)
        jax.block_until_ready(out[1].xl)
        times.append(time.perf_counter() - t0)
    rate = n_swarm * n_steps / float(np.median(times))
    results["swarm_scenario_sharded"] = rate
    print(json.dumps({
        "metric": "multichip_swarm_scenario_sharded",
        "value": _finite_or_none(rate, 1),
        "unit": "scenario-MPC-steps/s", "devices": ndev,
        "agents_total": 8 * n_swarm,
        "agent_mpc_steps_per_sec": _finite_or_none(rate * 8, 1),
    }), flush=True)
    return results


def components():
    """Component split of the headline batched step (SURVEY.md §5.1):
    env query / consensus solve / low-level+physics / QP build, each timed as
    its own jitted computation at the headline config."""
    from tpu_aerial_transport.control import cadmm

    params, col, state0, forest, f_eq, ll, acc_des = _setup(N_AGENTS)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=20, inner_iters=20,
    )
    states = _scenario_batch(state0, N_SCENARIOS)
    css = jax.vmap(lambda _: cadmm.init_cadmm_state(params, cfg))(
        jnp.arange(N_SCENARIOS)
    )
    dev = jax.devices()[0]

    def timed(name, fn, *args, reps=3, inner=10):
        # ``inner`` repetitions run inside one jitted lax.scan: per-dispatch
        # latency through the device tunnel is ~10-100 ms (and varies), which
        # would swamp any per-call timing of a ~ms-scale component. ``fn``
        # takes an ``eps`` scalar first and must fold it into its inputs; the
        # carry threads a data-dependent (runtime-zero) eps through the scan
        # so XLA cannot hoist the loop-invariant body and run it once.
        def scanned(*xs):
            def body(eps, _):
                out = fn(eps, *xs)
                tot = sum(jnp.sum(jnp.abs(l)) for l in jax.tree.leaves(out))
                return tot * 1e-38, None  # flushes to ~0, not provably 0.

            eps, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=inner)
            return eps

        f = jax.jit(scanned)
        out = f(*args)
        jax.block_until_ready(out)
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = f(*args)
            jax.block_until_ready(out)
            ts.append(time.perf_counter() - t0)
        ms = float(np.median(ts)) * 1e3 / inner
        print(f"{name:40s} {ms:8.2f} ms")
        return ms

    def jitter(ss, eps):
        return jax.vmap(lambda s: s.replace(xl=s.xl + eps))(ss)

    timed("env query (per-agent vision CBFs)",
          lambda eps, ss: jax.vmap(
              lambda s: cadmm.agent_env_cbfs(params, cfg, forest, s)
          )(jitter(ss, eps)), states)
    timed("cadmm control (full, incl. env)",
          lambda eps, a, ss: jax.vmap(
              lambda ai, si: cadmm.control(
                  params, cfg, f_eq, ai, si, acc_des, forest
              )
          )(a, jitter(ss, eps))[0], css, states)
    timed("cadmm control (no env)",
          lambda eps, a, ss: jax.vmap(
              lambda ai, si: cadmm.control(
                  params, cfg, f_eq, ai, si, acc_des, None
              )
          )(a, jitter(ss, eps))[0], css, states)
    timed("low-level + 10x physics",
          lambda eps, ss: jax.vmap(
              lambda s: _substeps(params, ll, s, f_eq).xl
          )(jitter(ss, eps)), states)


# One v5e chip (the bench device): peak dense f32 MXU throughput and HBM
# bandwidth used for %-of-peak numbers. The package pins matmul precision to
# f32 ("highest" — see tpu_aerial_transport/__init__.py), so the f32 peak is
# the honest ceiling; the bf16 peak is 4x higher but unusable for the stiff
# small-inertia dynamics here.
PEAK_F32_FLOPS = 49e12
PEAK_HBM_BPS = 819e9


def smoke():
    """~30-second chip validation (VERDICT r4 item 2's precondition for the
    fused A/B): solve one small batch of structured SOCPs twice — scan path
    and Pallas path — on the default device, and report whether Mosaic
    compiles the kernel and the two solutions agree. One JSON line; exit
    nonzero only on infrastructure failure (a kernel compile failure is a
    RESULT, reported in the line)."""
    from tpu_aerial_transport.ops import admm_kernel, socp

    rng = np.random.default_rng(0)
    nv, n_box, soc = 12, 23, (4, 4)
    m = n_box + sum(soc)
    # Below this bound the "pallas" request really builds the kernel; above
    # it solve_socp silently falls back to scan and the smoke would compare
    # scan against scan — a false PASS in the kernel-validation tool.
    assert nv + m <= admm_kernel.MAX_FUSED_DIM, (nv + m)

    def make():
        L = rng.normal(size=(nv, nv))
        return (L @ L.T + 0.1 * np.eye(nv), rng.normal(size=nv),
                rng.normal(size=(m, nv)) * 0.5,
                rng.uniform(-2, -0.5, n_box), rng.uniform(0.5, 2, n_box))

    Ps, qs, As, lbs, ubs = (
        jnp.asarray(np.stack(a), jnp.float32)
        for a in zip(*[make() for _ in range(256)])
    )

    def compile_mode(mode):
        """Lower+compile (Mosaic runs here) — separated from execution so a
        post-compile runtime fault (e.g. a Mosaic VMEM error surfacing at
        block_until_ready) is not misreported as a compile failure."""
        def one(P, q, A, lb, ub):
            return socp.solve_socp(
                P, q, A, lb, ub, n_box=n_box, soc_dims=soc, iters=60,
                fused=mode,
            )
        t0 = time.perf_counter()
        compiled = jax.jit(jax.vmap(one)).lower(Ps, qs, As, lbs, ubs).compile()
        return compiled, time.perf_counter() - t0

    def execute(compiled):
        sol = compiled(Ps, qs, As, lbs, ubs)
        jax.block_until_ready(sol.x)
        return sol

    out = {"metric": "pallas_smoke", "platform": jax.devices()[0].platform}
    compiled_scan, t_scan = compile_mode("scan")
    sol_scan = execute(compiled_scan)
    out["scan_ok"] = bool(np.isfinite(np.asarray(sol_scan.x)).all())
    out["scan_compile_s"] = round(t_scan, 1)
    out["pallas_compiles"] = False
    out["pallas_runs"] = False
    out["value"] = 0
    try:
        compiled_pl, t_pl = compile_mode("pallas")
        out["pallas_compiles"] = True
        out["pallas_compile_s"] = round(t_pl, 1)
        sol_pl = execute(compiled_pl)
        out["pallas_runs"] = True
        diff = float(jnp.abs(sol_pl.x - sol_scan.x).max())
        out["x_maxdiff_vs_scan"] = diff
        out["value"] = 1 if diff < 5e-4 else 0
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"[:400]
    print(json.dumps(out), flush=True)


def roofline(out_path: str = "artifacts/roofline.json"):
    """FLOPs / HBM-bytes attribution and %-of-peak for the headline step and
    its components, from XLA's own compiled-program cost model
    (``compiled.cost_analysis()``) plus measured wall time. Writes JSON and
    prints a markdown table for BASELINE.md (SURVEY.md §5.1 tracing tier)."""
    from tpu_aerial_transport.control import cadmm
    from tpu_aerial_transport.models import rqp

    dev = jax.devices()[0]
    results = {}

    def analyze(name, fn, args, n_units, unit_desc, inner: int = 1):
        """n_units = logical steps per call (for per-step normalization).
        ``inner`` > 1 re-runs fn inside a jitted lax.scan to amortize the
        ~100 ms per-dispatch latency through the device tunnel (a
        runtime-zero eps threads the carry so XLA cannot hoist the body);
        FLOPs/bytes come from the UN-scanned program's cost analysis.
        Caveat: XLA's cost model counts a while_loop body ONCE (trip count
        unknown at compile time), so FLOPs/bytes for the consensus loop are
        per-iteration lower bounds."""
        jitted = jax.jit(fn) if not hasattr(fn, "lower") else fn
        ca = jitted.lower(*args).compile().cost_analysis()
        ca = ca[0] if isinstance(ca, list) else (ca or {})
        flops = float(ca.get("flops", float("nan")))
        hbm = float(ca.get("bytes accessed", float("nan")))
        if inner > 1:
            def scanned(*xs):
                def body(eps, _):
                    # eps (runtime zero) perturbs every float input so the
                    # body is loop-variant — XLA cannot hoist it and run once.
                    xs_eps = jax.tree.map(
                        lambda a: a + eps
                        if (hasattr(a, "dtype")
                            and jnp.issubdtype(a.dtype, jnp.floating)) else a,
                        xs,
                    )
                    out = fn(*xs_eps)
                    leaves = [l for l in jax.tree.leaves(out)
                              if hasattr(l, "dtype")
                              and jnp.issubdtype(l.dtype, jnp.floating)]
                    tot = sum(jnp.sum(jnp.abs(l)) for l in leaves) + eps
                    return tot * 1e-38, None

                eps, _ = jax.lax.scan(
                    body, jnp.float32(0.0), None, length=inner
                )
                return eps

            timed_fn = jax.jit(scanned)
        else:
            timed_fn = jitted
        out = timed_fn(*args)
        jax.block_until_ready(out)
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = timed_fn(*args)
            jax.block_until_ready(out)
            ts.append(time.perf_counter() - t0)
        sec = float(np.median(ts)) / inner
        ai = flops / hbm
        rec = {
            "unit": unit_desc,
            "flops_per_unit": flops / n_units,
            "hbm_bytes_per_unit": hbm / n_units,
            "arithmetic_intensity_flops_per_byte": ai,
            "wall_s_per_call": sec,
            "achieved_gflops": flops / sec / 1e9,
            "achieved_hbm_gbps": hbm / sec / 1e9,
            "pct_of_f32_peak_flops": 100.0 * flops / sec / PEAK_F32_FLOPS,
            "pct_of_hbm_peak": 100.0 * hbm / sec / PEAK_HBM_BPS,
            # Machine balance (f32): ~60 flops/byte on v5e. Below it the
            # kernel is bandwidth-bound, above it compute-bound.
            "roofline_side": ("compute-bound" if ai > PEAK_F32_FLOPS / PEAK_HBM_BPS
                              else "bandwidth-bound"),
        }
        results[name] = rec
        print(f"# {name}: {json.dumps(rec)}", flush=True)

    # Headline: 256 x 8 C-ADMM forest rollout, TIMED_STEPS MPC steps.
    step, css, states = build()
    css = jax.device_put(css, dev)
    states = jax.device_put(states, dev)
    analyze(
        "headline_256x8_cadmm_rollout",
        step, (css, states, TIMED_STEPS),
        TIMED_STEPS * N_SCENARIOS, "scenario-MPC-step",
    )

    # Components at the headline config (same split as --components).
    params, col, state0, forest, f_eq, ll, acc_des = _setup(N_AGENTS)
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=20, inner_iters=20,
    )
    plan = cadmm.make_plan(params, cfg)
    states_b = _scenario_batch(state0, N_SCENARIOS)
    css_b = jax.vmap(lambda _: cadmm.init_cadmm_state(params, cfg))(
        jnp.arange(N_SCENARIOS)
    )
    analyze(
        "cadmm_control_batch256",
        lambda a, s: jax.vmap(
            lambda ai_, si: cadmm.control(
                params, cfg, f_eq, ai_, si, acc_des, forest, plan=plan
            )[0]
        )(a, s),
        (css_b, states_b), N_SCENARIOS, "scenario-control-step", inner=10,
    )
    analyze(
        "env_query_batch256",
        lambda s: jax.vmap(
            lambda si: cadmm.agent_env_cbfs(params, cfg, forest, si).lhs
        )(s),
        (states_b,), N_SCENARIOS, "scenario-env-query", inner=10,
    )
    analyze(
        "lowlevel_physics_x10_batch256",
        lambda s: jax.vmap(lambda si: _substeps(params, ll, si, f_eq).xl)(s),
        (states_b,), N_SCENARIOS, "scenario-physics-period", inner=10,
    )

    import os
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump({
            "peak_f32_flops": PEAK_F32_FLOPS,
            "peak_hbm_bytes_per_s": PEAK_HBM_BPS,
            "machine_balance_flops_per_byte": PEAK_F32_FLOPS / PEAK_HBM_BPS,
            "note": ("flops / 'bytes accessed' from XLA cost_analysis of the "
                     "compiled program; wall time measured on the chip; "
                     "dispatch overhead amortized over the scan/batch"),
            "results": results,
        }, fh, indent=1)
    print(f"roofline written to {out_path}")

    print("\n| Component | FLOPs/unit | HBM B/unit | AI (F/B) | GFLOP/s "
          "| %f32 peak | GB/s | %HBM peak | side |")
    print("|---|---|---|---|---|---|---|---|---|")
    for name, r in results.items():
        print(f"| {name} | {r['flops_per_unit']:.3g} | "
              f"{r['hbm_bytes_per_unit']:.3g} | "
              f"{r['arithmetic_intensity_flops_per_byte']:.1f} | "
              f"{r['achieved_gflops']:.0f} | "
              f"{r['pct_of_f32_peak_flops']:.1f} | "
              f"{r['achieved_hbm_gbps']:.0f} | {r['pct_of_hbm_peak']:.1f} | "
              f"{r['roofline_side']} |")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sweep", action="store_true")
    ap.add_argument("--resume", action="store_true",
                    help="with --sweep: skip configs already checkpointed "
                         "in BENCH_SWEEP_PARTIAL.json")
    ap.add_argument("--components", action="store_true")
    ap.add_argument("--multichip", action="store_true",
                    help="BASELINE.json multi-device configs (needs >1 "
                         "device; CPU shape-check via JAX_PLATFORMS=cpu + "
                         "xla_force_host_platform_device_count)")
    ap.add_argument("--roofline", action="store_true")
    ap.add_argument("--smoke", action="store_true",
                    help="~30 s Pallas-kernel compile+numerics check on the "
                         "current device (run FIRST when the chip returns)")
    ap.add_argument("--scaling", action="store_true",
                    help="per-n scaling table + padded-vs-unpadded A/B "
                         "(the n=64 consensus-cliff metric; runs on CPU "
                         "too — writes BENCH_SCALING.json)")
    ap.add_argument("--profile", default=None, metavar="DIR")
    ap.add_argument("--trace", default="",
                    help="--sweep: write a Chrome/Perfetto trace of the "
                         "sweep's guarded cells (guard_dispatch spans "
                         "with label/rung/failure kind) to this path")
    ap.add_argument("--fused", default="auto",
                    choices=["auto", "scan", "pallas", "interpret"],
                    help="inner ADMM chunk mode for the headline "
                         "(ops/admm_kernel.py A/B switch)")
    ap.add_argument("--buckets", type=int, default=0,
                    help="headline congestion-bucket count (0/1 = off; "
                         "harness/bucketing.py A/B switch)")
    ap.add_argument("--inner-tol", type=float, default=0.0,
                    help="tolerance-chunked inner solves (0 = fixed-budget; "
                         "A/B switch, see BASELINE.md round 5)")
    args = ap.parse_args()
    _honor_jax_platforms_env()
    # Persistent XLA compilation cache — the SAME knob as the test
    # conftest and the AOT serve driver (TAT_XLA_CACHE_DIR; "" disables).
    # Bench programs are identical run-to-run, so a bench_retry re-attempt
    # or a --resume'd sweep skips the backend compiles the crashed attempt
    # already paid instead of recompiling the matrix from scratch.
    from tpu_aerial_transport.utils.platform import enable_persistent_cache
    enable_persistent_cache()
    # Same precedence order as the dispatch chain below, so a backend-probe
    # failure is always labeled with the mode that would have run.
    mode_metric = ("bench_smoke" if args.smoke
                   else "bench_sweep" if args.sweep
                   else "bench_multichip" if args.multichip
                   else "bench_components" if args.components
                   else "bench_roofline" if args.roofline
                   else "bench_scaling" if args.scaling
                   else HEADLINE_METRIC)
    # The headline, the scaling table AND the sweep are meaningful on
    # XLA-CPU: a wedged/absent chip produces TAGGED cpu records instead of
    # null-valued error rows (the BENCH_r04/r05 failure mode). The sweep
    # additionally degrades PER CELL through the backend guard — a chip
    # that wedges mid-sweep costs one watchdog deadline per tripped cell,
    # then the open circuit routes the rest to the CPU rung. The remaining
    # modes are chip-specific and keep the structured hard failure
    # (status=backend_unavailable).
    cpu_fallback = args.scaling or args.sweep or not (
        args.smoke or args.multichip or args.components or args.roofline
    )
    platform, backend_note = ensure_backend(
        metric=mode_metric, cpu_fallback=cpu_fallback
    )
    if args.smoke:
        smoke()
    elif args.sweep:
        sweep(resume=args.resume, platform=platform,
              trace=args.trace or None)
    elif args.multichip:
        multichip()
    elif args.components:
        components()
    elif args.roofline:
        roofline()
    elif args.scaling:
        scaling()
    else:
        headline(args.profile, platform=platform, socp_fused=args.fused,
                 buckets=args.buckets, inner_tol=args.inner_tol,
                 backend_note=backend_note)


if __name__ == "__main__":
    main()
