"""Benchmark: Monte-Carlo distributed-MPC throughput on one chip.

Headline config from BASELINE.json ("env_forest obstacle field: 256 Monte-Carlo
scenarios x 8 agents, batched"): each scenario runs a full receding-horizon
control period — per-agent vision-cone env queries, consensus-ADMM over vmapped
conic-QP solves, low-level SO(3) attitude control at 1 kHz, 10 physics substeps
— and 256 scenarios are batched in one jitted computation (vmap over the
scenario axis), the exact workload the reference executes one-scenario-at-a-time
with sequential cvxpy/Clarabel solves (test_rqpcontrollers.py:112-124 runs its
100 Monte-Carlo re-solves in a Python loop). The low-level SO(3) law runs inside
every 1 kHz substep, as the reference's hot loop does (rqp_example.py:120-131).

Baseline: the reference's cvxpy/Clarabel stack is not installed in this image, so
the recorded baseline is THIS framework executed on the host CPU via XLA — a
generous stand-in (same fused program; the reference additionally pays cvxpy
re-canonicalization per solve and runs agents sequentially). ``vs_baseline`` is
the TPU/CPU throughput ratio at identical batch size.

Default mode prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``--sweep`` measures the full BASELINE.json matrix — MPC steps/sec/chip at
N in {4, 16, 64} agents for centralized / C-ADMM / DD, p50 control-step time
per consensus iteration, and the 1024-agent swarm config — and writes
``BENCH_SWEEP.json`` (a markdown table is printed for BASELINE.md).

``--profile <dir>`` wraps the headline timed window in a ``jax.profiler.trace``
for op-level attribution (SURVEY.md §5.1).
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

N_AGENTS = 8
N_SCENARIOS = 256
TIMED_STEPS = 10
CPU_TIMED_STEPS = 4


def _setup(n):
    from tpu_aerial_transport.control import centralized, lowlevel
    from tpu_aerial_transport.envs import forest as forest_mod
    from tpu_aerial_transport.harness import setup

    params, col, state0 = setup.rqp_setup(n)
    forest = forest_mod.make_forest(seed=0)
    f_eq = centralized.equilibrium_forces(params)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    acc_des = (jnp.array([0.3, 0.0, 0.0], jnp.float32), jnp.zeros(3, jnp.float32))
    return params, col, state0, forest, f_eq, ll, acc_des


def _substeps(params, ll, state, f_des, n_sub=10, dt=1e-3):
    """1 kHz low-level control + physics, the reference's inner loop."""
    from tpu_aerial_transport.models import rqp

    def body(s, _):
        f, M = ll.control(s, f_des)
        return rqp.integrate(params, s, (f, M), dt), None

    state, _ = jax.lax.scan(body, state, None, length=n_sub)
    return state


def make_mpc_step(controller: str, n: int, max_iter: int = 20,
                  inner_iters: int = 25):
    """Build ``(mpc_step(cs, state) -> (cs, state, stats), cs0, state0)`` for one
    scenario with the given high-level controller."""
    from tpu_aerial_transport.control import cadmm, centralized, dd
    from tpu_aerial_transport.envs import forest as forest_mod

    params, col, state0, forest, f_eq, ll, acc_des = _setup(n)

    if controller == "cadmm":
        cfg = cadmm.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=max_iter, inner_iters=inner_iters,
        )
        cs0 = cadmm.init_cadmm_state(params, cfg)

        def mpc_step(cs, state):
            f_app, cs, stats = cadmm.control(
                params, cfg, f_eq, cs, state, acc_des, forest
            )
            return cs, _substeps(params, ll, state, f_app), stats

    elif controller == "dd":
        cfg = dd.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=max_iter, inner_iters=inner_iters,
        )
        cs0 = dd.init_dd_state(params, cfg)

        def mpc_step(cs, state):
            f_des, cs, stats = dd.control(
                params, cfg, f_eq, cs, state, acc_des, forest
            )
            return cs, _substeps(params, ll, state, f_des), stats

    elif controller == "centralized":
        cfg = centralized.make_config(
            params, col.collision_radius, col.max_deceleration,
            solver_iters=120,
        )
        cs0 = centralized.init_ctrl_state(params, cfg)

        def mpc_step(cs, state):
            env_cbf = forest_mod.collision_cbf_rows(
                forest, state.xl, state.vl, col.collision_radius,
                col.max_deceleration, cfg.vision_radius, cfg.dist_eps,
                cfg.alpha_env_cbf, cfg.n_env_cbfs,
            )
            f_des, cs, stats = centralized.control(
                params, cfg, f_eq, cs, state, acc_des, env_cbf
            )
            return cs, _substeps(params, ll, state, f_des), stats

    else:
        raise ValueError(controller)

    return mpc_step, cs0, state0


def _scenario_batch(state0, n_scenarios):
    xs = jnp.asarray(
        np.random.default_rng(0).normal(size=(n_scenarios, 3)) * 2.0
        + np.array([5.0, 0.0, 2.0]),
        jnp.float32,
    )
    return jax.vmap(
        lambda x: state0.replace(xl=x, vl=jnp.array([0.5, 0.0, 0.0], jnp.float32))
    )(xs)


def build(controller="cadmm", n=N_AGENTS, n_scenarios=N_SCENARIOS):
    mpc_step, cs0, state0 = make_mpc_step(controller, n)
    states = _scenario_batch(state0, n_scenarios)
    css = jax.vmap(lambda _: cs0)(jnp.arange(n_scenarios))

    def rollout(css, states, n_steps):
        def body(carry, _):
            cs, s = carry
            cs, s, _ = jax.vmap(mpc_step)(cs, s)
            return (cs, s), None

        (css, states), _ = jax.lax.scan(
            body, (css, states), None, length=n_steps
        )
        return css, states

    return jax.jit(rollout, static_argnames="n_steps"), css, states


def measure(step, css, states, device, n_steps, n_scenarios):
    css = jax.device_put(css, device)
    states = jax.device_put(states, device)
    # Compile + warmup at the timed length so the timed call hits the cache.
    out = step(css, states, n_steps)
    jax.block_until_ready(out[1].xl)
    t0 = time.perf_counter()
    out = step(css, states, n_steps)
    jax.block_until_ready(out[1].xl)
    return n_scenarios * n_steps / (time.perf_counter() - t0)


def headline(profile_dir: str | None = None):
    step, css, states = build()
    if profile_dir:
        # Warm up outside the trace so the profile shows steady-state execution.
        measure(step, css, states, jax.devices()[0], TIMED_STEPS, N_SCENARIOS)
        with jax.profiler.trace(profile_dir):
            tpu_rate = measure(
                step, css, states, jax.devices()[0], TIMED_STEPS, N_SCENARIOS
            )
    else:
        tpu_rate = measure(
            step, css, states, jax.devices()[0], TIMED_STEPS, N_SCENARIOS
        )
    try:
        cpu_rate = measure(
            step, css, states, jax.devices("cpu")[0], CPU_TIMED_STEPS, N_SCENARIOS
        )
        vs = tpu_rate / cpu_rate
    except Exception:
        vs = float("nan")

    print(json.dumps({
        "metric": f"scenario_mpc_steps_per_sec_{N_SCENARIOS}x{N_AGENTS}_cadmm_forest",
        "value": round(tpu_rate, 1),
        "unit": "scenario-MPC-steps/s",
        "vs_baseline": round(vs, 2),
    }))


def _single_stream(controller, n, n_steps=30):
    """Single-scenario MPC rate + p50 control-call time per consensus iteration
    (the BASELINE.json 'p50 solve-time/ADMM-iter' metric; the centralized
    controller has no consensus loop — reference SolverStatistics reports
    iter = -1 — so the per-iteration metric is omitted for it)."""
    mpc_step, cs0, state0 = make_mpc_step(controller, n)
    step = jax.jit(mpc_step)
    state = state0.replace(vl=jnp.array([0.5, 0.0, 0.0], jnp.float32))
    cs, state_out, stats = step(cs0, state)  # compile
    jax.block_until_ready(state_out.xl)
    cs = cs0
    times, iters = [], []
    for _ in range(n_steps):
        t0 = time.perf_counter()
        cs, state, stats = step(cs, state)
        jax.block_until_ready(state.xl)
        times.append(time.perf_counter() - t0)
        iters.append(int(stats.iters))
    out = {
        "mpc_steps_per_sec": 1.0 / float(np.median(times)),
        "p50_step_ms": float(np.median(times)) * 1e3,
    }
    # p50 time per consensus/ADMM iteration — the BASELINE.json metric. Only
    # meaningful for the distributed solvers (centralized reports iters = -1,
    # reference SolverStatistics semantics).
    if any(k > 0 for k in iters):
        per_iter = [t / k for t, k in zip(times, iters) if k > 0]
        out["p50_iters"] = float(np.median([k for k in iters if k > 0]))
        out["p50_ms_per_consensus_iter"] = float(np.median(per_iter)) * 1e3
    return out


def _batched(controller, n, n_scenarios, n_steps=10):
    step, css, states = build(controller, n, n_scenarios)
    return measure(step, css, states, jax.devices()[0], n_steps, n_scenarios)


def sweep():
    results = {}
    # MPC steps/sec/chip at N in {4, 16, 64} for all three controllers.
    for ctrl in ("centralized", "cadmm", "dd"):
        for n in (4, 16, 64):
            key = f"{ctrl}_n{n}_single"
            results[key] = _single_stream(ctrl, n)
            print(f"# {key}: {results[key]}", flush=True)
    # Batched throughput (the TPU's actual operating point) at the same Ns.
    for ctrl in ("cadmm", "dd"):
        for n, ns in ((4, 256), (16, 128), (64, 32)):
            key = f"{ctrl}_n{n}_batch{ns}"
            rate = _batched(ctrl, n, ns)
            results[key] = {"scenario_mpc_steps_per_sec": rate,
                            "agent_mpc_steps_per_sec": rate * n}
            print(f"# {key}: {results[key]}", flush=True)
    # Swarm (BASELINE.json config 5): 128 payloads x 8 quads = 1024 agents.
    rate = _batched("cadmm", 8, 128)
    results["swarm_128x8"] = {"scenario_mpc_steps_per_sec": rate,
                              "agent_mpc_steps_per_sec": rate * 8}
    print(f"# swarm_128x8: {results['swarm_128x8']}", flush=True)

    with open("BENCH_SWEEP.json", "w") as fh:
        json.dump(results, fh, indent=1)

    # Markdown table for BASELINE.md.
    print("\n| Config | MPC steps/s | p50 step ms | p50 ms/consensus-iter |")
    print("|---|---|---|---|")
    for ctrl in ("centralized", "cadmm", "dd"):
        for n in (4, 16, 64):
            r = results[f"{ctrl}_n{n}_single"]
            per_iter = r.get("p50_ms_per_consensus_iter")
            per_iter_s = f"{per_iter:.2f}" if per_iter is not None else "—"
            print(f"| {ctrl} n={n} single-stream | "
                  f"{r['mpc_steps_per_sec']:.1f} | {r['p50_step_ms']:.2f} | "
                  f"{per_iter_s} |")
    for key in [k for k in results if "batch" in k or "swarm" in k]:
        r = results[key]
        print(f"| {key} | {r['scenario_mpc_steps_per_sec']:.1f} scenario-steps/s "
              f"({r['agent_mpc_steps_per_sec']:.0f} agent-steps/s) | — | — |")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sweep", action="store_true")
    ap.add_argument("--profile", default=None, metavar="DIR")
    args = ap.parse_args()
    if args.sweep:
        sweep()
    else:
        headline(args.profile)


if __name__ == "__main__":
    main()
