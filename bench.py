"""Benchmark: Monte-Carlo distributed-MPC throughput on one chip.

Headline config from BASELINE.json ("env_forest obstacle field: 256 Monte-Carlo
scenarios x 8 agents, batched"): each scenario runs a full receding-horizon
control period — per-agent vision-cone env queries, consensus-ADMM over vmapped
conic-QP solves, low-level thrust projection, 10 physics substeps at 1 kHz — and
256 scenarios are batched in one jitted computation (vmap over the scenario
axis), the exact workload the reference executes one-scenario-at-a-time with
sequential cvxpy/Clarabel solves (test_rqpcontrollers.py:112-124 runs its 100
Monte-Carlo re-solves in a Python loop).

Baseline: the reference's cvxpy/Clarabel stack is not installed in this image, so
the recorded baseline is THIS framework executed on the host CPU via XLA — a
generous stand-in (same fused program; the reference additionally pays cvxpy
re-canonicalization per solve and runs agents sequentially). ``vs_baseline`` is
the TPU/CPU throughput ratio at identical batch size.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

N_AGENTS = 8
N_SCENARIOS = 256
TIMED_STEPS = 10
CPU_TIMED_STEPS = 2


def build():
    from tpu_aerial_transport.control import cadmm, centralized
    from tpu_aerial_transport.envs import forest as forest_mod
    from tpu_aerial_transport.harness import setup
    from tpu_aerial_transport.models import rqp

    n = N_AGENTS
    params, col, state0 = setup.rqp_setup(n)
    forest = forest_mod.make_forest(seed=0)
    # Warm starts carry solver state across control steps and consensus
    # iterations, so 25 inner ADMM iterations hold the consensus residual well
    # under the 1e-2 N tolerance (see tests/test_cadmm.py).
    cfg = cadmm.make_config(
        params, col.collision_radius, col.max_deceleration,
        max_iter=20, inner_iters=25,
    )
    f_eq = centralized.equilibrium_forces(params)
    acc_des = (jnp.array([0.3, 0.0, 0.0], jnp.float32), jnp.zeros(3, jnp.float32))

    # Scenario batch: payloads scattered around the forest edge, flying in.
    xs = jnp.asarray(
        np.random.default_rng(0).normal(size=(N_SCENARIOS, 3)) * 2.0
        + np.array([5.0, 0.0, 2.0]),
        jnp.float32,
    )
    states = jax.vmap(
        lambda x: state0.replace(xl=x, vl=jnp.array([0.5, 0.0, 0.0], jnp.float32))
    )(xs)
    astates = jax.vmap(lambda _: cadmm.init_cadmm_state(params, cfg))(
        jnp.arange(N_SCENARIOS)
    )

    def mpc_step(astate, state):
        f_app, astate, _ = cadmm.control(
            params, cfg, f_eq, astate, state, acc_des, forest
        )
        fz = jnp.sum(f_app * state.R[..., :, 2], axis=-1)
        M = jnp.zeros((n, 3), jnp.float32)
        for _ in range(10):
            state = rqp.integrate(params, state, (fz, M), 1e-3)
        return astate, state

    def rollout(astates, states, n_steps):
        def body(carry, _):
            a, s = carry
            return jax.vmap(mpc_step)(a, s), None

        (astates, states), _ = jax.lax.scan(
            body, (astates, states), None, length=n_steps
        )
        return astates, states

    return jax.jit(rollout, static_argnames="n_steps"), astates, states


def measure(step, astates, states, device, n_steps):
    astates = jax.device_put(astates, device)
    states = jax.device_put(states, device)
    # Compile + warmup at the timed length so the timed call hits the cache.
    out = step(astates, states, n_steps)
    jax.block_until_ready(out[1].xl)
    t0 = time.perf_counter()
    out = step(astates, states, n_steps)
    jax.block_until_ready(out[1].xl)
    return N_SCENARIOS * n_steps / (time.perf_counter() - t0)


def main():
    step, astates, states = build()
    tpu_rate = measure(step, astates, states, jax.devices()[0], TIMED_STEPS)
    try:
        cpu_rate = measure(
            step, astates, states, jax.devices("cpu")[0], CPU_TIMED_STEPS
        )
        vs = tpu_rate / cpu_rate
    except Exception:
        vs = float("nan")

    print(json.dumps({
        "metric": f"scenario_mpc_steps_per_sec_{N_SCENARIOS}x{N_AGENTS}_cadmm_forest",
        "value": round(tpu_rate, 1),
        "unit": "scenario-MPC-steps/s",
        "vs_baseline": round(vs, 2),
    }))


if __name__ == "__main__":
    main()
