"""tpu-aerial-transport: a TPU-native (JAX/XLA) framework for distributed
multi-quadrotor aerial payload transportation.

Brand-new implementation of the capabilities of
``AkshayThiru/distributed-aerial-transportation`` (see SURVEY.md), re-designed for
TPU: pytree system models, a batched conic-QP solver with closed-form SOC
projections, vmapped per-agent distributed MPC (consensus-ADMM and dual
decomposition) with mesh all-reduces, a closed-form JAX collision environment, and
end-to-end jit-compiled receding-horizon rollouts.
"""

import os as _os

import jax as _jax

# The compute in this framework is dominated by small (3x3 .. ~64x64) matmuls inside
# rigid-body dynamics and the conic-QP solver, where bf16 mantissa loss directly
# corrupts physics and KKT residuals while buying no MXU throughput (the tiles are far
# below the 128x128 systolic array). Default to full-f32 matmuls; override with
# TAT_MATMUL_PRECISION=default to restore JAX's platform default.
if _os.environ.get("TAT_MATMUL_PRECISION", "highest") != "default":
    _jax.config.update(
        "jax_default_matmul_precision",
        _os.environ.get("TAT_MATMUL_PRECISION", "highest"),
    )

__version__ = "0.1.0"
