"""Controllers (reference ``control/``): centralized RQP SOCP+CBF filter,
C-ADMM and dual-decomposition distributed solvers, RP centralized QP,
low-level SO(3) thrust/moment controllers."""

from tpu_aerial_transport.control import (  # noqa: F401
    cadmm,
    centralized,
    dd,
    lowlevel,
    rp_centralized,
    so3_tracking,
    types,
)
