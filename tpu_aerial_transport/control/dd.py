"""Dual-decomposition distributed controller for the RQP model.

TPU-native re-design of reference ``control/rqp_dd.py``. Each agent's primal holds
only its own force ``f_i`` plus aggregate-of-others force ``F_i`` and moment
``M_i`` (consensus ``F_i + f_i = sum_j f_j``, ``M_i + r_i x Rl^T f_i = sum_j r_j x
Rl^T f_j``, docstring :48-51), with a linear price cost ``c_fi^T f_i + c_Fi^T F_i
+ c_Mi^T M_i`` assembled from every agent's duals (the logical all-gather,
:716-722). The dual update is a quasi-Newton ascent (:634-693): per-agent
strong-convexity matrices ``Q_i (9x9)`` from the cost curvature, global consensus
matrix ``A (6n x 9n)``, QN matrix ``A Q^{-1} A^T + beta I`` Cholesky-factored once
per control step, dual step ``cho_solve(QN, A @ primal)``.

TPU mapping: each agent's QP has a **constant 18 variables regardless of n** (vs
9+3n for C-ADMM's full local copies), so DD is the better-scaling distributed
mode; all n QPs solve as one vmapped batch, the price assembly is two ``sum``
reductions (``psum`` over a mesh axis in the ``parallel`` layer), and the 6n-dim
QN solve is replicated on every device — tiny and deterministic, as SURVEY.md §5.8
prescribes. Like the C-ADMM port, the per-agent KKT systems are factored once per
control step (only the price vector moves between dual iterations).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from tpu_aerial_transport.control.cadmm import (
    RQPCADMMConfig,
    agent_env_cbfs_for,
)
from tpu_aerial_transport.control.centralized import (
    equilibrium_forces,
    smooth_block as cadmm_smooth_block,
)
from tpu_aerial_transport.control.types import EnvCBF, SolverStats
from tpu_aerial_transport.envs import forest as forest_mod
from tpu_aerial_transport.models.rqp import GRAVITY, RQPParams, RQPState
from tpu_aerial_transport.obs import phases
from tpu_aerial_transport.ops import lie, socp
from tpu_aerial_transport.parallel import ring


# Stop tolerance of DD's GATE-ONLY adaptive-effort default (see
# control()'s make_solve note): effectively never reached by a
# warm-started solve, so DD's default adaptivity is the bias-free
# consensus-level gate alone. Named so callers that must LABEL the
# dispatch (bench._effort_ab_cell's shared-resolver call) read the same
# constant control() dispatches with.
ADAPTIVE_GATE_TOL = 1e-6


@struct.dataclass
class RQPDDConfig:
    """DD constants (reference ``_set_controller_constants``, rqp_dd.py:197-241 and
    :604-616). Shares every primal constant with C-ADMM; adds the dual-ascent
    regularization ``beta`` (0 by default) and the primal-infeasibility stop."""

    base: RQPCADMMConfig
    beta: float = 0.0
    prim_inf_tol: float = 1e-2
    sc_eps: float = 1e-6  # strong-convexity floor (reference :514).


def make_config(
    params: RQPParams,
    collision_radius: float,
    max_deceleration: float,
    n_env_cbfs: int = 10,
    max_iter: int = 100,
    inner_iters: int = 60,
    prim_inf_tol: float = 1e-2,
    k_smooth: float = 0.0,
    dt: float = 1e-3,
    socp_fused: str = "auto",
    socp_precision: str = "auto",
    inner_tol: float = 0.0,
    inner_check_every: int = 10,
    solve_retry_iters: int = 4,
    pad_operators: bool | None = None,
    track_agent_stats: bool = False,
    consensus_impl: str = "auto",
    effort: str = "auto",
    env_query: str = "auto",
) -> RQPDDConfig:
    """Defaults are reference-conservative. For warm-started receding-horizon
    use the measured inner-iteration knee is ~40: the quasi-Newton dual ascent
    needs tighter primal optima than C-ADMM's consensus (at 20 it rails
    against the outer cap) — see bench.py / BASELINE.md.

    **k_smooth x row-equilibration interaction**: same caveat as
    :func:`control.cadmm.make_config` (measured there,
    tests/test_ksmooth.py:75) — exact row equilibration removed the
    accidental preconditioning that hid the smoothing cost's ~100:1 P
    anisotropy, so a ``k_smooth > 0`` agent QP needs ~300 inner ADMM
    iterations instead of ~80. DD is hit harder than C-ADMM by
    under-budgeted inner solves (tolerance-missed primal optima bias the
    quasi-Newton dual ascent), so when enabling smoothing raise
    ``inner_iters`` to >= 300 or set ``inner_tol > 0`` for early exit."""
    from tpu_aerial_transport.control import cadmm as cadmm_mod

    base = cadmm_mod.make_config(
        params, collision_radius, max_deceleration,
        n_env_cbfs=n_env_cbfs, max_iter=max_iter, inner_iters=inner_iters,
        k_smooth=k_smooth, dt=dt, socp_fused=socp_fused,
        socp_precision=socp_precision,
        inner_tol=inner_tol, inner_check_every=inner_check_every,
        solve_retry_iters=solve_retry_iters, pad_operators=pad_operators,
        track_agent_stats=track_agent_stats,
        consensus_impl=consensus_impl,
        effort=effort,
        env_query=env_query,
    )
    return RQPDDConfig(base=base, prim_inf_tol=prim_inf_tol)


@struct.dataclass
class DDState:
    """Solver state across control steps (reference ``_set_variables`` +
    ``_set_warm_start``, :618-632): primal optima, duals, per-agent warm starts."""

    f: jnp.ndarray  # (n, 3) own forces.
    F: jnp.ndarray  # (n, 3) aggregate-of-others forces.
    M: jnp.ndarray  # (n, 3) aggregate-of-others moments.
    lam_F: jnp.ndarray  # (n, 3) duals of the force consensus rows.
    lam_M: jnp.ndarray  # (n, 3) duals of the moment consensus rows.
    warm: socp.SOCPSolution  # leading agent axis.
    # Last DELIVERED network-visible values (resilience layer only; None in
    # nominal use — see the matching ``CADMMState.held`` note): under
    # message dropout the peers' price/violation aggregations keep
    # consuming these snapshots, frozen at the agent's last delivered step.
    held_f: jnp.ndarray | None = None
    held_lam_F: jnp.ndarray | None = None
    held_lam_M: jnp.ndarray | None = None


def _qp_dims(cfg: RQPDDConfig):
    """Static DD per-agent QP dims ``(nv, n_box, nv_p, n_box_p, m_p)`` —
    the ``_p`` values are the tile bucket (ops/socp.py padded tier), equal
    to the raw dims when ``pad_operators`` is off."""
    nv, n_box = 18, 13 + cfg.base.n_env_cbfs
    if cfg.base.pad_operators:
        nv_p, n_box_p = socp.padded_dims(nv, n_box, (4, 4))
    else:
        nv_p, n_box_p = nv, n_box
    return nv, n_box, nv_p, n_box_p, n_box_p + 8


def init_dd_state(params: RQPParams, cfg: RQPDDConfig) -> DDState:
    n = params.n
    f_eq = equilibrium_forces(params)
    dtype = f_eq.dtype
    F0 = jnp.sum(f_eq, axis=0)[None, :] - f_eq
    # prev_Mi = -JT_inv hat(r_com_i) f_eq_i (reference :466).
    M0 = -jnp.einsum(
        "ij,njk,nk->ni", params.JT_inv,
        jax.vmap(lie.hat)(params.r_com), f_eq,
    )
    nv, _, nv_p, _, m_p = _qp_dims(cfg)
    x0 = jnp.concatenate(
        [jnp.zeros((n, 9), dtype), f_eq, F0, M0], axis=1
    )
    # Warm starts live in the (possibly padded) solve layout; pad entries
    # start — and stay — at exactly 0 (socp.pad_qp docstring).
    warm = socp.SOCPSolution(
        x=jnp.pad(x0, ((0, 0), (0, nv_p - nv))),
        y=jnp.zeros((n, m_p), dtype),
        z=jnp.zeros((n, m_p), dtype),
        prim_res=jnp.zeros((n,), dtype),
        dual_res=jnp.zeros((n,), dtype),
    )
    return DDState(
        f=f_eq, F=F0, M=M0,
        lam_F=jnp.zeros((n, 3), dtype),
        lam_M=jnp.zeros((n, 3), dtype),
        warm=warm,
    )


def _build_agent_qp(
    params: RQPParams,
    cfg: RQPCADMMConfig,
    fi_eq: jnp.ndarray,
    r_com_i: jnp.ndarray,
    R_i: jnp.ndarray,
    w_i: jnp.ndarray,
    state: RQPState,
    acc_des,
    env_cbf: EnvCBF,
    is_leader: jnp.ndarray,
):
    """Per-agent DD primal QP (docstring rqp_dd.py:30-46), vmapped over agents.

    Variable layout: [dv_com 0:3 | dvl 3:6 | dwl 6:9 | f_i 9:12 | F_i 12:15 |
    M_i 15:18] — 18 vars independent of n. Box rows: [dyn-trans 3 | dyn-rot 3 |
    kin 3 | fz 1 | tilt 1 | wl 1 | vl 1 | env k]; SOC: thrust cone + norm cap.
    The iteration-varying price vector c enters via q (caller adds it).
    """
    dtype = state.xl.dtype
    nv = 18
    dvl_des, dwl_des = acc_des
    e3 = jnp.array([0.0, 0.0, 1.0], dtype=dtype)
    Rl = state.Rl
    Gi = lie.hat(r_com_i) @ Rl.T  # hat(r_com_i) Rl^T.

    P = jnp.zeros((nv, nv), dtype)
    q = jnp.zeros((nv,), dtype)
    k_dvl = cfg.k_dvl * is_leader
    k_dwl = cfg.k_dwl * is_leader
    P = P.at[3:6, 3:6].add(2.0 * k_dvl * jnp.eye(3, dtype=dtype))
    q = q.at[3:6].add(-2.0 * k_dvl * dvl_des)
    P = P.at[6:9, 6:9].add(2.0 * k_dwl * jnp.eye(3, dtype=dtype))
    q = q.at[6:9].add(-2.0 * k_dwl * dwl_des)

    # (k_f/n) ||f_i + F_i - mT g e3||^2 on blocks [f, F].
    Sf = jnp.zeros((3, nv), dtype)
    Sf = Sf.at[:, 9:12].set(jnp.eye(3, dtype=dtype))
    Sf = Sf.at[:, 12:15].set(jnp.eye(3, dtype=dtype))
    P = P + 2.0 * cfg.k_f * (Sf.T @ Sf)
    q = q + (-2.0 * cfg.k_f) * (Sf.T @ (params.mT * GRAVITY * e3))
    # (k_m/n) ||M_i + hat(r_com_i) Rl^T f_i||^2.
    Sm = jnp.zeros((3, nv), dtype)
    Sm = Sm.at[:, 9:12].set(Gi)
    Sm = Sm.at[:, 15:18].set(jnp.eye(3, dtype=dtype))
    P = P + 2.0 * cfg.k_m * (Sm.T @ Sm)
    # k_feq ||f_i - fi_eq||^2.
    P = P.at[9:12, 9:12].add(2.0 * cfg.k_feq * jnp.eye(3, dtype=dtype))
    q = q.at[9:12].add(-2.0 * cfg.k_feq * fi_eq)
    # Own-force smoothing cost (reference rqp_dd.py:451-457, default 0).
    P = P.at[9:12, 9:12].add(cadmm_smooth_block(cfg, R_i, w_i))

    n_box = 13 + cfg.n_env_cbfs
    A = jnp.zeros((n_box, nv), dtype)
    lb = jnp.zeros((n_box,), dtype)
    ub = jnp.zeros((n_box,), dtype)

    # Dynamics translation: mT dv_com - f_i - F_i = -mT g e3.
    A = A.at[0:3, 0:3].set(params.mT * jnp.eye(3, dtype=dtype))
    A = A.at[0:3, 9:12].set(-jnp.eye(3, dtype=dtype))
    A = A.at[0:3, 12:15].set(-jnp.eye(3, dtype=dtype))
    rhs = -params.mT * GRAVITY * e3
    lb = lb.at[0:3].set(rhs)
    ub = ub.at[0:3].set(rhs)

    # Dynamics rotation: dwl - JT_inv (hat(r_i) Rl^T f_i + M_i) = -JT_inv (wl x JT wl).
    A = A.at[3:6, 6:9].set(jnp.eye(3, dtype=dtype))
    A = A.at[3:6, 9:12].set(-params.JT_inv @ Gi)
    A = A.at[3:6, 15:18].set(-params.JT_inv)
    rot_rhs = -params.JT_inv @ jnp.cross(state.wl, params.JT @ state.wl)
    lb = lb.at[3:6].set(rot_rhs)
    ub = ub.at[3:6].set(rot_rhs)

    # Kinematics.
    R_w_hat = Rl @ lie.hat(state.wl)
    R_w_hat_sq = Rl @ lie.hat_square(state.wl, state.wl)
    A = A.at[6:9, 0:3].set(-jnp.eye(3, dtype=dtype))
    A = A.at[6:9, 3:6].set(jnp.eye(3, dtype=dtype))
    A = A.at[6:9, 6:9].set(-Rl @ lie.hat(params.x_com))
    kin_rhs = -R_w_hat_sq @ params.x_com
    lb = lb.at[6:9].set(kin_rhs)
    ub = ub.at[6:9].set(kin_rhs)

    # f_z >= min_fz.
    A = A.at[9, 11].set(1.0)
    lb = lb.at[9].set(cfg.min_fz)
    ub = ub.at[9].set(socp.INF)

    # Tilt / |wl| / |vl| CBFs.
    A = A.at[10, 6:9].set(-(Rl[2] @ lie.hat(e3)))
    tilt_rhs = (
        -R_w_hat_sq[2, 2]
        - (cfg.alpha1_p_cbf + cfg.alpha2_p_cbf) * R_w_hat[2, 2]
        - cfg.alpha1_p_cbf * cfg.alpha2_p_cbf * (Rl[2, 2] - cfg.cos_max_p_ang)
    )
    lb = lb.at[10].set(tilt_rhs)
    ub = ub.at[10].set(socp.INF)
    A = A.at[11, 6:9].set(-2.0 * state.wl)
    lb = lb.at[11].set(
        -cfg.alpha_wl_cbf * (cfg.max_wl_sq - jnp.dot(state.wl, state.wl))
    )
    ub = ub.at[11].set(socp.INF)
    A = A.at[12, 3:6].set(-2.0 * state.vl)
    lb = lb.at[12].set(
        -cfg.alpha_vl_cbf * (cfg.max_vl_sq - jnp.dot(state.vl, state.vl))
    )
    ub = ub.at[12].set(socp.INF)

    A = A.at[13 : 13 + cfg.n_env_cbfs, 3:6].set(env_cbf.lhs)
    lb = lb.at[13 : 13 + cfg.n_env_cbfs].set(env_cbf.rhs)
    ub = ub.at[13 : 13 + cfg.n_env_cbfs].set(socp.INF)

    # SOC rows on f_i.
    soc = jnp.zeros((8, nv), dtype)
    shift_soc = jnp.zeros((8,), dtype)
    soc = soc.at[0, 11].set(cfg.sec_max_f_ang)
    soc = soc.at[1:4, 9:12].set(jnp.eye(3, dtype=dtype))
    shift_soc = shift_soc.at[4].set(cfg.max_f)
    soc = soc.at[5:8, 9:12].set(jnp.eye(3, dtype=dtype))

    A_full = jnp.concatenate([A, soc], axis=0)
    shift = jnp.concatenate([jnp.zeros((n_box,), dtype), shift_soc])
    # Exact row/block equilibration (see cadmm._build_agent_qp).
    A_full, lb, ub, shift, _ = socp.equilibrate_rows(
        A_full, lb, ub, shift, n_box, (4, 4)
    )
    return P, q, A_full, lb, ub, shift


def strong_convexity_matrix(
    params: RQPParams,
    cfg: RQPCADMMConfig,
    state: RQPState,
    r_com_i: jnp.ndarray,
    R_i: jnp.ndarray,
    w_i: jnp.ndarray,
    is_leader: jnp.ndarray,
    eps: float,
):
    """Per-agent curvature lower-bound over (f_i, F_i, M_i) (reference
    ``strong_convexity_matrix``, rqp_dd.py:513-555): sum of 2 k (C^T C) for each
    quadratic cost term, with the dynamics equalities substituted so dvl/dwl
    become affine in (f_i, F_i, M_i)."""
    dtype = state.xl.dtype
    eye = jnp.eye(3, dtype=dtype)
    mat = eps * jnp.eye(9, dtype=dtype)

    def add(mat, Cf, CF, CM, k):
        C = jnp.concatenate([Cf, CF, CM], axis=1)  # (3, 9)
        return mat + 2.0 * k * (C.T @ C)

    zero = jnp.zeros((3, 3), dtype)
    # k_feq on f_i.
    mat = add(mat, eye, zero, zero, cfg.k_feq)
    # k_smooth on f_i (reference :518-524, default 0).
    mat = mat.at[0:3, 0:3].add(cadmm_smooth_block(cfg, R_i, w_i))
    # k_f on f_i + F_i.
    mat = add(mat, eye, eye, zero, cfg.k_f)
    # k_m on M_i + hat(r_i) Rl^T f_i.
    Gi = lie.hat(r_com_i) @ state.Rl.T
    mat = add(mat, Gi, zero, eye, cfg.k_m)
    # k_dwl (leader only): dwl = JT_inv Gi f + JT_inv M + const.
    coeff_dwl_f = params.JT_inv @ Gi
    mat = add(mat, coeff_dwl_f, zero, params.JT_inv, cfg.k_dwl * is_leader)
    # k_dvl (leader only): dvl = f/mT + F/mT + Rl hat(x_com) dwl + const.
    Rx = state.Rl @ lie.hat(params.x_com)
    mat = add(
        mat,
        eye / params.mT + Rx @ coeff_dwl_f,
        eye / params.mT,
        Rx @ params.JT_inv,
        cfg.k_dvl * is_leader,
    )
    return mat


def _consensus_matrix(params: RQPParams, Rl: jnp.ndarray):
    """Global consensus constraint matrix ``A (6n, 9n)`` (reference :643-653):
    row block i reads ``[F_i - sum_{j!=i} f_j ; M_i - sum_{j!=i} r_j x Rl^T f_j]``
    off the stacked per-agent primal ``(f_j, F_j, M_j)``. With ``Rl = I`` this
    is the payload-frame matrix (state-free — see :class:`DDPlan`).

    Built as a block tensor ``(i, row_half, 3, j, var_block, 3)`` with masked
    einsums — an O(n^2) Python scatter loop here emitted tens of thousands of
    HLO ops at n = 64 and crashed the TPU compiler."""
    n = params.n
    dtype = Rl.dtype
    G = jax.vmap(lambda r: lie.hat(r) @ Rl.T)(params.r_com)  # (n, 3, 3)
    I3 = jnp.eye(3, dtype=dtype)
    eyen = jnp.eye(n, dtype=dtype)
    offd = 1.0 - eyen
    blocks = jnp.zeros((n, 2, 3, n, 3, 3), dtype)
    # F rows (half 0): +I on F_i (var block 1), -I on every other f_j (block 0).
    blocks = blocks.at[:, 0, :, :, 1, :].set(jnp.einsum("ij,ab->iajb", eyen, I3))
    blocks = blocks.at[:, 0, :, :, 0, :].set(jnp.einsum("ij,ab->iajb", -offd, I3))
    # M rows (half 1): +I on M_i (block 2), -G_j on every other f_j (block 0).
    blocks = blocks.at[:, 1, :, :, 2, :].set(jnp.einsum("ij,ab->iajb", eyen, I3))
    blocks = blocks.at[:, 1, :, :, 0, :].set(jnp.einsum("ij,jab->iajb", -offd, G))
    return blocks.reshape(6 * n, 9 * n)


class DDPlan(NamedTuple):
    """State-independent quasi-Newton preparation for the DD dual ascent.

    In the payload frame — primal blocks ``(ft_i, Ft_i) = (Rl^T f_i,
    Rl^T F_i)`` (the moment aggregates ``M_i`` are already payload-frame) and
    the F-consensus rows pre-rotated by ``Rl^T`` — both the per-agent
    strong-convexity matrices and the consensus matrix become independent of
    the state: every ``Rl`` in their blocks either cancels (orthogonal
    conjugation inside a squared norm) or multiplies a whole row block whose
    Gram product drops it. The expensive per-control-step work the reference
    re-does each step (reference :634-657: n 9x9 inverses + the 6n x 6n
    Cholesky) therefore precomputes ONCE here; rotating the per-iteration
    violations into the payload frame and the dual step back out reproduces
    the world-frame quasi-Newton step EXACTLY (orthogonal change of basis).

    The dynamic leader (``leader_idx`` is a runtime pytree leaf) adds
    tracking-cost curvature to one agent's 9x9 block; that enters as a
    rank-9 Woodbury correction of the precomputed base inverse per step.

    The optional ``k_smooth`` curvature (reference :518-524) is omitted from
    the preconditioner (it is state-dependent); since the strong-convexity
    matrix is a curvature LOWER bound used as a dual-ascent scaling, omitting
    a PSD term only makes the dual steps more conservative. k_smooth defaults
    to 0, where the preconditioner is exact.
    """

    qn_inv_base: jnp.ndarray  # (6n, 6n) inverse of Ac Qinv_base Ac^T + beta I.
    D: jnp.ndarray  # (n, 9, 9) Qinv_leader - Qinv_base per would-be leader.
    Ac: jnp.ndarray  # (6n, 9n) payload-frame consensus matrix.


def make_dd_plan(params: RQPParams, cfg: RQPDDConfig) -> DDPlan:
    """Precompute the payload-frame QN cores (see :class:`DDPlan`)."""
    n = params.n
    base = cfg.base
    dtype = params.r.dtype
    eye3 = jnp.eye(3, dtype=dtype)
    # Payload-frame strong-convexity matrices == world ones at Rl = I; the
    # k_smooth term is state-dependent and excluded (class docstring).
    frame_state = _identity_rl_state(n, dtype)
    cfg_nosmooth = base.replace(k_smooth=0.0)

    def q_pair(r_i, R_i, w_i):
        q_base = strong_convexity_matrix(
            params, cfg_nosmooth, frame_state, r_i, R_i, w_i,
            jnp.zeros((), dtype), cfg.sc_eps,
        )
        q_lead = strong_convexity_matrix(
            params, cfg_nosmooth, frame_state, r_i, R_i, w_i,
            jnp.ones((), dtype), cfg.sc_eps,
        )
        return q_base, q_lead

    Q_base, Q_lead = jax.vmap(q_pair)(
        params.r_com, frame_state.R, frame_state.w
    )
    Qinv_base = jnp.linalg.inv(Q_base)
    Qinv_base = 0.5 * (Qinv_base + jnp.swapaxes(Qinv_base, -1, -2))
    Qinv_lead = jnp.linalg.inv(Q_lead)
    Qinv_lead = 0.5 * (Qinv_lead + jnp.swapaxes(Qinv_lead, -1, -2))

    Ac = _consensus_matrix(params, eye3)  # payload frame.
    Ac_blocks = Ac.reshape(6 * n, n, 9)
    AQinv = jnp.einsum("mnj,njk->mnk", Ac_blocks, Qinv_base).reshape(
        6 * n, 9 * n
    )
    qn = AQinv @ Ac.T + cfg.beta * jnp.eye(6 * n, dtype=dtype)
    qn_inv = jnp.linalg.inv(qn)
    qn_inv = 0.5 * (qn_inv + qn_inv.T)
    return DDPlan(qn_inv_base=qn_inv, D=Qinv_lead - Qinv_base, Ac=Ac)


def _identity_rl_state(n: int, dtype) -> RQPState:
    """A placeholder state with Rl = I and identity quad attitudes, used to
    evaluate state-free payload-frame blocks through the world-frame builders."""
    from tpu_aerial_transport.models import rqp as rqp_mod

    eye = jnp.eye(3, dtype=dtype)
    return rqp_mod.rqp_state(
        R=jnp.tile(eye, (n, 1, 1)), w=jnp.zeros((n, 3), dtype),
        xl=jnp.zeros(3, dtype), vl=jnp.zeros(3, dtype),
        Rl=eye, wl=jnp.zeros(3, dtype),
    )


def control(
    params: RQPParams,
    cfg: RQPDDConfig,
    f_eq: jnp.ndarray,
    dd_state: DDState,
    state: RQPState,
    acc_des,
    forest: forest_mod.Forest | None = None,
    axis_name: str | None = None,
    plan: DDPlan | None = None,
    health=None,
):
    """One DD control step: ``-> (f (n_local, 3), DDState, SolverStats)``
    (reference ``RQPDDController.control``, :695-752).

    ``health``: optional :class:`resilience.faults.FaultStep` (``.alive``/
    ``.msg_ok``, global (n,) bool) for graceful degradation, mirroring
    :func:`control.cadmm.control`: dead agents are masked out of the price
    and consensus-violation aggregations (their force contribution is
    zero, so survivors' aggregate-of-others targets redistribute the
    load), their primal/dual state and warm starts freeze, and their
    applied force is zero; dropped messages (``alive & ~msg_ok``) hold the
    agent's step-start prices/forces in the aggregations while it keeps
    iterating locally. The QN preconditioner keeps its all-healthy cores —
    a curvature bound used as a dual-ascent scaling, so masking only makes
    the masked agents' (zeroed) steps trivially consistent. ``health=None``
    compiles the exact nominal program.

    ``plan``: optional precomputed :func:`make_dd_plan` (state-independent
    QN cores). When None it is computed inline; passing it explicitly keeps
    the big 6n x 6n inverse out of the compiled step.

    With ``axis_name=None`` all n agents run in one program (vmap; single
    chip). Inside ``shard_map`` over a mesh axis named ``axis_name``, each
    shard holds a block of agents (the leading axis of every ``DDState``
    leaf); the price sums and consensus-violation sums become ``lax.psum``
    collectives, and the 6n-dim quasi-Newton dual step is **replicated** on
    every shard after a ``lax.all_gather`` of the per-agent violation blocks
    (the dual gradient ``Ac @ prim`` *is* the stacked per-agent consensus
    violations ``[err_F_i; err_M_i]``, so it never needs the full 9n primal) —
    exactly the collective realization SURVEY.md §5.8 prescribes for the
    reference's price all-gather (rqp_dd.py:716-722) + centralized QN solve
    (:678-693). ``state``/``acc_des``/``f_eq`` are replicated; ``f_eq`` is
    always the full (n, 3) table."""
    n = params.n
    base = cfg.base
    dtype = state.xl.dtype

    n_local = dd_state.f.shape[0]
    if axis_name is None:
        agent_ids = jnp.arange(n_local)
    else:
        agent_ids = lax.axis_index(axis_name) * n_local + jnp.arange(n_local)

    # Consensus-exchange seam (parallel/ring.py): the price sums,
    # violation sums, and dual-gradient gather all ride one impl-selected
    # exchange, attributed under tat.consensus_exchange (see the matching
    # construction in cadmm.control). n % n_shards == 0 is a shard_map
    # precondition (parallel.mesh._sharded_control).
    n_shards = 1 if axis_name is None else n // n_local
    impl = cfg.base.consensus_impl

    def _exch(x, op):
        return ring.consensus_exchange(
            x, axis_name, axis_size=n_shards, op=op, impl=impl
        )

    def _sum_over_agents(x):
        s = jnp.sum(x, axis=0)
        return s if axis_name is None else _exch(s, "sum")

    def _max_over_agents(x):
        s = jnp.max(x)
        return s if axis_name is None else _exch(s, "max")

    def _min_over_agents(x):
        s = jnp.min(x)
        return s if axis_name is None else _exch(s, "min")

    def _gather_blocks(x):
        """(n_local, d) local blocks -> (n, d) full table, shard-ordered."""
        if axis_name is None:
            return x
        return ring.consensus_gather(
            x, axis_name, axis_size=n_shards, impl=impl
        ).reshape(n, x.shape[-1])

    if health is not None:
        # Graceful-degradation masks (see the docstring; cadmm.control has
        # the matching construction).
        alive_l = jnp.take(health.alive, agent_ids, axis=0)
        msg_ok_l = jnp.take(health.msg_ok, agent_ids, axis=0)
        w_alive = alive_l.astype(dtype)  # (n_local,)
        # Dead agents anchor to zero force; their implied aggregates follow.
        f_eq = f_eq * health.alive.astype(dtype)[:, None]
        # Peers' view of a dropped agent: its last DELIVERED values (held
        # snapshots frozen across the whole dropout window; see
        # CADMMState.held). None (direct call, first step) falls back to
        # the carried values.
        lamF_stale = (dd_state.held_lam_F if dd_state.held_lam_F is not None
                      else dd_state.lam_F)
        lamM_stale = (dd_state.held_lam_M if dd_state.held_lam_M is not None
                      else dd_state.lam_M)
        f_stale = (dd_state.held_f if dd_state.held_f is not None
                   else dd_state.f)

    r_local = jnp.take(params.r, agent_ids, axis=0)
    r_com_local = jnp.take(params.r_com, agent_ids, axis=0)
    f_eq_local = jnp.take(f_eq, agent_ids, axis=0)

    with phases.scope(phases.CBF_ROWS):
        env_cbfs = agent_env_cbfs_for(params, base, forest, state, r_local)
    # Equality test (not .at[idx]) so leader_idx = -1 (unset_leader) yields no
    # leader rather than wrapping to the last agent.
    leaders = (agent_ids == base.leader_idx).astype(dtype)

    R_local = jnp.take(state.R, agent_ids, axis=0)
    w_local = jnp.take(state.w, agent_ids, axis=0)
    with phases.scope(phases.QP_BUILD):
        P, q0, A, lb, ub, shift = jax.vmap(
            lambda fi_eq, r_i, R_i, w_i, ld, cbf: _build_agent_qp(
                params, base, fi_eq, r_i, R_i, w_i, state, acc_des, cbf, ld
            )
        )(f_eq_local, r_com_local, R_local, w_local, leaders, env_cbfs)

        _, n_box_raw, _, n_box, m = _qp_dims(cfg)
        if base.pad_operators:
            # Tile-aligned operator layout (ops/socp.py padded tier; exact
            # — pad rows are free, pad variables rest at 0).
            P, q0, A, lb, ub, shift = jax.vmap(
                lambda P_, q_, A_, lb_, ub_, s_: socp.pad_qp(
                    P_, q_, A_, lb_, ub_, s_, n_box=n_box_raw, soc_dims=(4, 4)
                )
            )(P, q0, A, lb, ub, shift)
        rho_vec = jax.vmap(
            lambda lb_, ub_: socp.make_rho_vec(m, n_box, lb_, ub_, 0.4, dtype)
        )(lb, ub)
        op = socp.kkt_operator(P, A, rho_vec)

    # Quasi-Newton preparation (reference :634-657, where n 9x9 inverses and
    # a 6n x 6n factorization re-ran every control step): the state-free
    # payload-frame cores come from the plan (see :class:`DDPlan`); per step
    # only the dynamic leader's rank-9 Woodbury correction runs. Replicated
    # on every shard — it needs only replicated inputs and the result is tiny.
    if plan is None:
        plan = make_dd_plan(params, cfg)
    l_idx = jnp.asarray(base.leader_idx, jnp.int32)
    has_leader = ((l_idx >= 0) & (l_idx < n)).astype(dtype)
    li = jnp.clip(l_idx, 0, n - 1)
    A_l = lax.dynamic_slice(plan.Ac, (jnp.int32(0), 9 * li), (6 * n, 9))
    Dl = jnp.take(plan.D, li, axis=0) * has_leader
    Pb = plan.qn_inv_base
    PA = Pb @ A_l  # (6n, 9)
    # (B + A_l D A_l^T)^{-1} = P - P A_l (I + D A_l^T P A_l)^{-1} D A_l^T P
    # (Woodbury without D^{-1}; D = 0 when no leader makes this a no-op).
    K9 = jnp.eye(9, dtype=dtype) + Dl @ (A_l.T @ PA)
    qn_inv = Pb - PA @ jnp.linalg.solve(K9, Dl @ PA.T)
    qn_inv = 0.5 * (qn_inv + qn_inv.T)

    G_local = jax.vmap(lambda r: lie.hat(r) @ state.Rl.T)(r_com_local)

    # Consensus-level adaptive effort (base.effort, socp.resolve_effort):
    # Python-level branches only, so effort="fixed" stages the exact
    # pre-knob program (the cadmm.control contract; asserted in
    # tests/test_effort.py).
    adaptive = base.effort == "adaptive"
    if not adaptive:
        _solve_v = jax.vmap(
            lambda P_, q_, A_, lb_, ub_, shift_, op_, warm_: socp.solve_socp(
                P_, q_, A_, lb_, ub_,
                n_box=n_box, soc_dims=(4, 4), iters=base.inner_iters,
                warm=warm_, shift=shift_, op=op_, fused=base.socp_fused,
                precision=base.socp_precision,
                tol=base.inner_tol,
                check_every=(base.inner_check_every if base.inner_tol > 0
                             else 0),
            )
        )

        def solve_one(P_, q_, A_, lb_, ub_, shift_, op_, warm_, active):
            del active  # fixed effort: no gating ops staged.
            return _solve_v(P_, q_, A_, lb_, ub_, shift_, op_, warm_), None
    else:
        # Tolerance-chunked solves with the per-scenario converged gate
        # broadcast over the agent axis (see the matching cadmm.control
        # make_solve). DD's default is GATE-ONLY — a 1e-6 tolerance the
        # warm-started solves essentially never hit — NOT C-ADMM's
        # solver_tol: the quasi-Newton dual ascent is biased by
        # tolerance-missed primal optima (the make_config k_smooth note),
        # and the bias is SCALE-dependent — measured: at 5e-3 the n=4
        # cold-start A/B rails the outer cap (mean 24.5 vs 2.0 outer
        # iterations, residual 0.105 vs the 1e-2 bar); 5e-4 repairs n=4
        # (res 8.9e-3, ~3x less inner effort) but still breaks the n=64
        # bench cell (outer 20.9 vs 3.1, residual 0.178 vs 0.009). The
        # consensus-level gate is bias-FREE at any scale (a gated lane's
        # outputs are discarded by the outer freeze regardless), so it is
        # the only adaptivity DD enables by default; callers who want DD
        # inner early exit at a scale they have validated opt in via
        # inner_tol.
        _dd_tol = (base.inner_tol if base.inner_tol > 0
                   else ADAPTIVE_GATE_TOL)
        solve_one = jax.vmap(
            lambda P_, q_, A_, lb_, ub_, shift_, op_, warm_, act_:
            socp.solve_socp(
                P_, q_, A_, lb_, ub_,
                n_box=n_box, soc_dims=(4, 4), iters=base.inner_iters,
                warm=warm_, shift=shift_, op=op_, fused=base.socp_fused,
                precision=base.socp_precision,
                tol=_dd_tol, check_every=base.inner_check_every,
                active=act_, report_iters=True,
            ),
            in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None),
        )

    # Solver-failure fallbacks (reference :486-489): equilibrium forces and the
    # aggregates they imply.
    fallback_F = jnp.sum(f_eq, axis=0)[None, :] - f_eq_local
    fallback_M = -jnp.einsum("ij,njk,nk->ni", params.JT_inv, G_local, f_eq_local)

    def _continue_pred(it, err, ok_last, fail_count):
        """The dual-ascent loop's continue predicate — shared by ``cond``
        and the adaptive-effort lane gate so the two cannot drift."""
        return (((err >= cfg.prim_inf_tol)
                 | ((ok_last < 1.0) & (fail_count <= retry_cap)))
                & (it <= base.max_iter))

    def dd_iter(carry):
        (f, F, M, lam_F, lam_M, warm, it, err, err_buf, okf, _ok_last,
         fail_count) = carry[:12]
        if adaptive:
            # The lane's own would-continue bit (see cadmm.control).
            lane_active = _continue_pred(it, err, _ok_last, fail_count)
        else:
            lane_active = None
        # Price assembly (the all-gather, reference :716-722) — two psum
        # reductions over the agent axis. With health, each agent's
        # NETWORK-VISIBLE price contribution is its held (stale) value
        # while dropped and zero while dead; the aggregation and the
        # subtract-own step use the same visible values so "sum of the
        # others' prices" stays exact w.r.t. delivered messages.
        with phases.scope(phases.CONSENSUS):
            if health is None:
                lamF_eff, lamM_eff = lam_F, lam_M
            else:
                lamF_eff = jnp.where(
                    msg_ok_l[:, None], lam_F, lamF_stale
                ) * w_alive[:, None]
                lamM_eff = jnp.where(
                    msg_ok_l[:, None], lam_M, lamM_stale
                ) * w_alive[:, None]
            sum_lF = _sum_over_agents(lamF_eff)
            sum_lM = _sum_over_agents(lamM_eff)
            c_F = lam_F
            c_M = lam_M
            c_f = -(sum_lF[None, :] - lamF_eff) + jnp.einsum(
                "nij,nj->ni",
                jax.vmap(lambda r: state.Rl @ lie.hat(r))(r_com_local),
                sum_lM[None, :] - lamM_eff,
            )
            q = (q0.at[:, 9:12].add(c_f).at[:, 12:15].add(c_F)
                 .at[:, 15:18].add(c_M))
        with phases.scope(phases.LOCAL_SOLVE):
            sols, eff = solve_one(P, q, A, lb, ub, shift, op, warm,
                                  lane_active)
        x = sols.x
        ok = (sols.prim_res < base.solver_tol) & jnp.all(
            jnp.isfinite(x), axis=-1
        )
        okc = ok[:, None]
        f_new = jnp.where(okc, x[:, 9:12], f_eq_local)
        F_new = jnp.where(okc, x[:, 12:15], fallback_F)
        M_new = jnp.where(okc, x[:, 15:18], fallback_M)
        if health is not None:
            # Dead agents freeze at their last pre-death primal and never
            # trigger retries; their warm starts freeze too.
            f_new = jnp.where(alive_l[:, None], f_new, f)
            F_new = jnp.where(alive_l[:, None], F_new, F)
            M_new = jnp.where(alive_l[:, None], M_new, M)
            ok = ok | ~alive_l
        # Keep any FINITE iterate as the warm start (tolerance-missed solves
        # accumulate inner progress across dual-ascent retries instead of
        # restarting identically); only non-finite iterates revert (see the
        # matching note in cadmm._consensus_iter_impl).
        finite = socp.solution_is_finite(sols)
        if health is not None:
            finite = finite & alive_l
        warm_new = jax.tree.map(
            lambda new, old: jnp.where(
                finite.reshape((n_local,) + (1,) * (new.ndim - 1)), new, old
            ),
            sols, warm,
        )
        # Primal infeasibility (the all-reduce, reference :659-676). With
        # health, the force sums see each agent's network-visible value
        # (held while dropped, zero while dead) and dead agents' violation
        # blocks are zeroed so they drive neither the residual nor the
        # dual ascent.
        with phases.scope(phases.CONSENSUS):
            if health is None:
                f_c = f_new
            else:
                f_c = jnp.where(
                    msg_ok_l[:, None], f_new, f_stale
                ) * w_alive[:, None]
            moments = jnp.einsum("nij,nj->ni", G_local, f_c)
            sum_f = _sum_over_agents(f_c)
            sum_m = _sum_over_agents(moments)
            err_F = F_new - (sum_f[None, :] - f_c)
            err_M = M_new - (sum_m[None, :] - moments)
            if health is not None:
                err_F = err_F * w_alive[:, None]
                err_M = err_M * w_alive[:, None]
            err_new = _max_over_agents(
                jnp.maximum(jnp.max(jnp.abs(err_F)), jnp.max(jnp.abs(err_M)))
            )
        err_buf = err_buf.at[it].set(err_new)
        it = it + 1
        # Quasi-Newton dual ascent (reference :678-693). The dual gradient
        # ``Ac @ prim`` equals the stacked per-agent consensus violations
        # [err_F_i; err_M_i], so each shard contributes its local blocks
        # (all_gather) and the tiny 6n-dim solve replicates on every shard.
        # The F-violations rotate into the payload frame to match the
        # precomputed QN basis and the F-step rotates back — an exact
        # orthogonal change of basis, identical to the world-frame step.
        # Gated like the reference's loop (:742-748): it breaks BEFORE the
        # ascent when converged or past the iteration cap.
        with phases.scope(phases.DUAL_UPDATE):
            dual_grad = _gather_blocks(
                jnp.concatenate([err_F @ state.Rl, err_M], axis=1)
            ).reshape(-1)
            step = (qn_inv @ dual_grad).reshape(n, 6)
            step = jnp.take(step, agent_ids, axis=0)
            do_dual = (err_new >= cfg.prim_inf_tol) & (it <= base.max_iter)
            lam_F_new = jnp.where(
                do_dual, lam_F + step[:, :3] @ state.Rl.T, lam_F
            )
            lam_M_new = jnp.where(do_dual, lam_M + step[:, 3:], lam_M)
            if health is not None:
                # Frozen duals for dead agents.
                lam_F_new = jnp.where(alive_l[:, None], lam_F_new, lam_F)
                lam_M_new = jnp.where(alive_l[:, None], lam_M_new, lam_M)
        ok_last = _sum_over_agents(ok.astype(dtype)) / n
        okf = jnp.minimum(okf, ok_last)  # worst-iteration success fraction.
        fail_count = jnp.where(ok_last < 1.0, fail_count + 1, 0)  # consecutive.
        out = (f_new, F_new, M_new, lam_F_new, lam_M_new, warm_new, it,
               err_new, err_buf, okf, ok_last, fail_count)
        if adaptive:
            # Effective inner iterations spent this dual-ascent iteration
            # (this shard's agents) — see the matching cadmm.control note.
            out = out + (carry[12] + jnp.sum(eff),)
        return out

    # Per-lane batch semantics: lax.while_loop's batching rule already
    # selects old-vs-new carry per lane from the full per-lane cond, so
    # converged scenarios stay frozen inside a vmapped batch (see the
    # matching note in cadmm.control) — no manual freeze wrapper.

    retry_cap = base.solve_retry_iters or base.max_iter

    def cond(carry):
        # Positional indexing (the adaptive-effort carry appends an
        # inner-iteration accumulator at the end): it=6, err=7,
        # ok_last=10, fail_count=11.
        # Solve failures keep the loop alive even at primal feasibility:
        # fallback values can satisfy the consensus equations trivially
        # while the failed agents' true solves still need retries (see the
        # matching note in cadmm.control's cond; bounded by
        # solve_retry_iters (default 4) FAILING iterations, counted from
        # failure onset).
        return _continue_pred(carry[6], carry[7], carry[10], carry[11])

    err_buf0 = jnp.full((base.max_iter + 1,), jnp.nan, dtype)
    init = (
        dd_state.f, dd_state.F, dd_state.M, dd_state.lam_F, dd_state.lam_M,
        dd_state.warm, jnp.zeros((), jnp.int32), jnp.asarray(jnp.inf, dtype),
        err_buf0, jnp.ones((), dtype), jnp.ones((), dtype),
        jnp.zeros((), jnp.int32),
    )
    if adaptive:
        init = init + (jnp.zeros((), jnp.int32),)  # inner-iteration total.
    carry = lax.while_loop(cond, dd_iter, init)
    (f, F, M, lam_F, lam_M, warm, iters, err, err_buf, ok_frac,
     _ok_last, _fail_count) = carry[:12]

    if health is not None:
        # Delivered-snapshot updates (see the matching cadmm.control note).
        ok_m = msg_ok_l[:, None]
        held_f = jnp.where(ok_m, f, f_stale)
        held_lF = jnp.where(ok_m, lam_F, lamF_stale)
        held_lM = jnp.where(ok_m, lam_M, lamM_stale)
    else:
        held_f, held_lF, held_lM = (
            dd_state.held_f, dd_state.held_lam_F, dd_state.held_lam_M
        )
    new_state = DDState(f=f, F=F, M=M, lam_F=lam_F, lam_M=lam_M, warm=warm,
                        held_f=held_f, held_lam_F=held_lF, held_lam_M=held_lM)
    if health is not None:
        f = f * w_alive[:, None]  # dead agents actuate nothing.
    collision = _max_over_agents(env_cbfs.collision.astype(jnp.int32)) > 0
    stats = SolverStats(
        iters=iters,
        solve_res=err,
        collision=collision,
        min_env_dist=_min_over_agents(env_cbfs.min_dist),
        err_seq=err_buf,
        ok_frac=ok_frac,
    )
    if adaptive:
        # Whole-fleet effective inner iterations this step (see the
        # matching cadmm.control note on the f32 exchange).
        inner_tot = carry[12]
        if axis_name is not None:
            inner_tot = _exch(inner_tot.astype(dtype), "sum").astype(
                jnp.int32
            )
        stats = stats.replace(inner_iters=inner_tot)
    if base.track_agent_stats:
        # Exit-time per-agent QP residuals for solve-health telemetry
        # (see the matching cadmm.control block).
        stats = stats.replace(
            agent_solve_res=_gather_blocks(warm.prim_res[:, None])[:, 0]
        )
    return f, new_state, stats


def jit_control_step(params, cfg, f_eq, forest=None, plan=None,
                     donate: bool = True):
    """Jitted single DD control step with the solver-state carry DONATED
    (primal optima, duals, warm starts updated in place) — the DD twin of
    :func:`control.cadmm.jit_control_step`; same contract: thread the
    returned state forward, never reuse the donated argument."""
    if plan is None:
        plan = make_dd_plan(params, cfg)

    def step(dd_state, state, acc_des):
        return control(
            params, cfg, f_eq, dd_state, state, acc_des, forest, plan=plan
        )

    return jax.jit(step, donate_argnums=(0,) if donate else ())
