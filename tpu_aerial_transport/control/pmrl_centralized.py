"""Centralized QP + CBF safety-filter controller for the PMRL model.

The reference ships PMRL as dynamics + visualization only — no controller
exists for it ("future-work model", SURVEY.md §2.3; reference
system/point_mass_rigid_link.py). This module closes that gap with the same
controller family the reference builds for RP/RQP (control/rp_centralized.py
:11-22 problem shape, constants scaled to the PMRL assembly), designed
TPU-first:

- PMRL accelerations are **exactly affine** in the applied robot thrusts:
  the link tensions solve a linear SPD system whose right-hand side is
  affine in ``f`` (models/pmrl.py:100-143), so ``(dvl, dwl) = B f + c``
  exactly. ``B`` is extracted with one ``jax.jacfwd`` over the true forward
  dynamics — no hand linearization to drift out of sync with the model.
- Decision variables ``[dvl | dwl | f_1..f_n]`` with the affine dynamics as
  equality rows; tracking/regularization costs; payload tilt / |wl| / |vl|
  CBF rows (identical math to rp_centralized.py:153-175); per-robot
  min-vertical-thrust, thrust-cone, and norm-cap constraints. Point-mass
  robots have no attitude, so the solved ``f`` applies directly — there is
  no low-level attitude stage.
- Equilibrium thrusts are state-dependent here (they depend on the current
  link directions): tensions solve the static wrench balance
  ``sum T_i q_i = ml g e3``, ``sum r_i x Rl^T (T_i q_i) = 0`` in least
  squares, then ``f_eq,i = m_i g e3 + T_i q_i`` (the PMRL analogue of
  reference rp_centralized.py:122-130).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from tpu_aerial_transport.control.types import SolverStats
from tpu_aerial_transport.models import pmrl
from tpu_aerial_transport.models.pmrl import GRAVITY, PMRLParams, PMRLState
from tpu_aerial_transport.ops import lie, socp


@struct.dataclass
class PMRLCentralizedConfig:
    min_fz: float
    sec_max_f_ang: float
    max_f: float
    cos_max_p_ang: float
    alpha1_p_cbf: float
    alpha2_p_cbf: float
    max_wl_sq: float
    alpha_wl_cbf: float
    max_vl_sq: float
    alpha_vl_cbf: float
    k_f: float
    k_feq: float
    k_dvl: float
    k_dwl: float
    # Robot-acceleration tracking weight. Essential for PMRL: link tensions
    # act along the links, so at (near-)vertical links the payload has ~zero
    # instantaneous lateral authority and a payload-acceleration cost alone
    # cannot command the link swing that creates it. Tracking desired ROBOT
    # accelerations (also exactly affine in f) swings the links, which then
    # drives the payload — the standard cable/link-suspended flying pattern.
    k_rob: float = 1.0
    # Swing damping in the default robot-acceleration target:
    # a_des,i = dvl_des - swing_damp * L_i dq_i. Undamped link swing drives
    # payload-speed excursions whose |vl| CBF row can become infeasible
    # against the thrust-cone limits (every such step falls back to the
    # previous forces, which feeds the oscillation). Calibrated by a closed
    # -loop gain sweep (round 4): at 2.0 the setpoint approach limit-cycles
    # at ~0.2 m error with solver fallbacks (ok_frac dips to 0); at 3.5 it
    # settles to ~0.03 m with ok_frac == 1 throughout, across
    # k_rob in [0.5, 2] and k_feq in [0.02, 0.1].
    swing_damp: float = 3.5
    solver_iters: int = struct.field(pytree_node=False, default=150)
    solver_tol: float = struct.field(pytree_node=False, default=5e-3)
    solver_check_every: int = struct.field(pytree_node=False, default=25)


def make_config(params: PMRLParams,
                solver_iters: int = 150) -> PMRLCentralizedConfig:
    """RP-centralized constants (reference rp_centralized.py:147-175) scaled
    to the PMRL assembly's total mass ``ml + sum m_i``."""
    n = params.n
    mTg = float(params.ml + jnp.sum(params.m)) * GRAVITY
    return PMRLCentralizedConfig(
        min_fz=mTg / (n * 10.0),
        sec_max_f_ang=float(1.0 / jnp.cos(jnp.pi / 6.0)),
        max_f=2.0 * mTg / n,
        cos_max_p_ang=float(jnp.cos(jnp.pi / 6.0)),  # 30 deg, as for RP.
        alpha1_p_cbf=1.0,
        alpha2_p_cbf=1.0,
        max_wl_sq=float((jnp.pi / 6.0) ** 2),
        alpha_wl_cbf=1.0,
        max_vl_sq=1.0,
        alpha_vl_cbf=1.0,
        k_f=0.1,
        k_feq=0.1,
        k_dvl=1.0,
        k_dwl=1.0,
        k_rob=1.0,
        swing_damp=3.5,
        solver_iters=solver_iters,
    )


def equilibrium_forces(params: PMRLParams, state: PMRLState) -> jnp.ndarray:
    """State-dependent static thrusts ``(n, 3)``: least-squares tensions
    balancing the payload wrench along the CURRENT link directions, plus each
    robot's own weight (see module docstring)."""
    q, Rl = state.q, state.Rl
    e3 = jnp.array([0.0, 0.0, 1.0], dtype=q.dtype)
    rcq = jnp.cross(params.r, q @ Rl)  # (n, 3) rows r_i x (Rl^T q_i).
    A = jnp.concatenate([q.T, rcq.T], axis=0)  # (6, n)
    b = jnp.concatenate([params.ml * GRAVITY * e3, jnp.zeros(3, q.dtype)])
    T = jnp.linalg.lstsq(A, b)[0]  # (n,)
    return params.m[:, None] * GRAVITY * e3[None, :] + T[:, None] * q


@struct.dataclass
class CtrlState:
    prev_f: jnp.ndarray  # (n, 3)
    warm: socp.SOCPSolution


def qp_dims(n: int):
    """(n_box, m, soc_dims): box rows [dyn-dvl 3 | dyn-dwl 3 | fz n | tilt 1 |
    wl 1 | vl 1]; per robot SOC(4) cone + SOC(4) norm cap."""
    n_box = 9 + n
    soc_dims = (4,) * (2 * n)
    return n_box, n_box + sum(soc_dims), soc_dims


def init_ctrl_state(params: PMRLParams, cfg: PMRLCentralizedConfig,
                    state: PMRLState) -> CtrlState:
    n = params.n
    _, m, _ = qp_dims(n)
    f_eq = equilibrium_forces(params, state)
    x0 = jnp.concatenate([jnp.zeros(6, f_eq.dtype), f_eq.reshape(-1)])
    warm = socp.SOCPSolution(
        x=x0,
        y=jnp.zeros((m,), f_eq.dtype),
        z=jnp.zeros((m,), f_eq.dtype),
        prim_res=jnp.zeros((), f_eq.dtype),
        dual_res=jnp.zeros((), f_eq.dtype),
    )
    return CtrlState(prev_f=f_eq, warm=warm)


def _affine_dynamics(params: PMRLParams, state: PMRLState):
    """Exact affine maps through the implicit tension solve (the dynamics
    are affine in ``f``; models/pmrl.py:100-143): payload accelerations
    ``[dvl; dwl] = B f + c`` (6, 3n) and robot accelerations
    ``ddx = B_rob f + c_rob`` (3n, 3n), where
    ``ddx_i = dvl + L_i ddq_i + Rl (hat^2(wl) + hat(dwl)) r_i`` is the
    world-frame acceleration of robot i's point mass. ``c``s from a
    zero-thrust evaluation, ``B``s via jacfwd (exact — the map is affine)."""
    n = params.n
    Rl, wl = state.Rl, state.wl
    hat_sq = lie.hat_square(wl, wl)

    def accs(f_flat):
        (ddq, dvl, dwl), _ = pmrl.forward_dynamics(
            params, state, f_flat.reshape(n, 3)
        )
        kin = (hat_sq + lie.hat(dwl)) @ params.r.T  # (3, n)
        ddx = dvl[None, :] + ddq * params.L[:, None] + (Rl @ kin).T  # (n, 3)
        return jnp.concatenate([dvl, dwl]), ddx.reshape(-1)

    zero = jnp.zeros(3 * n, dtype=state.xl.dtype)
    c, c_rob = accs(zero)
    B, B_rob = jax.jacfwd(accs)(zero)  # (6, 3n), (3n, 3n).
    return B, c, B_rob, c_rob


def _build_qp(params: PMRLParams, cfg: PMRLCentralizedConfig, f_eq,
              state: PMRLState, acc_des, rob_acc_des):
    """Variables [dvl 0:3 | dwl 3:6 | f 6:6+3n]; rows per :func:`qp_dims`."""
    n = params.n
    dtype = state.xl.dtype
    nv = 6 + 3 * n
    dvl_des, dwl_des = acc_des
    e3 = jnp.array([0.0, 0.0, 1.0], dtype=dtype)
    Rl = state.Rl
    mT = params.ml + jnp.sum(params.m)

    P = jnp.zeros((nv, nv), dtype)
    q = jnp.zeros((nv,), dtype)
    P = P.at[0:3, 0:3].add(2.0 * cfg.k_dvl * jnp.eye(3, dtype=dtype))
    q = q.at[0:3].add(-2.0 * cfg.k_dvl * dvl_des)
    P = P.at[3:6, 3:6].add(2.0 * cfg.k_dwl * jnp.eye(3, dtype=dtype))
    q = q.at[3:6].add(-2.0 * cfg.k_dwl * dwl_des)
    S = jnp.tile(jnp.eye(3, dtype=dtype), (1, n))
    P = P.at[6:, 6:].add(
        2.0 * cfg.k_f * (S.T @ S) + 2.0 * cfg.k_feq * jnp.eye(3 * n, dtype=dtype)
    )
    q = q.at[6:].add(
        -2.0 * cfg.k_f * (S.T @ (mT * GRAVITY * e3))
        - 2.0 * cfg.k_feq * f_eq.reshape(-1)
    )

    n_box, _, _ = qp_dims(n)
    A = jnp.zeros((n_box, nv), dtype)
    lb = jnp.zeros((n_box,), dtype)
    ub = jnp.zeros((n_box,), dtype)

    B, c, B_rob, c_rob = _affine_dynamics(params, state)

    # Robot-acceleration tracking (see k_rob docstring): quadratic in f only.
    resid0 = c_rob - rob_acc_des.reshape(-1)
    P = P.at[6:, 6:].add(2.0 * cfg.k_rob * (B_rob.T @ B_rob))
    q = q.at[6:].add(2.0 * cfg.k_rob * (B_rob.T @ resid0))

    # Exact affine dynamics rows: [dvl; dwl] - B f = c, row-equilibrated —
    # the dwl rows carry Jl_inv ~ O(50) entries vs O(1) dvl rows, and the
    # solver's EQ_RHO_SCALE amplifies the mismatch into f32 ADMM stalls as
    # the links swing (same treatment as the C-ADMM Schur plan's coupling
    # rows).
    dyn = jnp.concatenate([jnp.eye(6, dtype=dtype), -B], axis=1)  # (6, nv)
    scale = 1.0 / jnp.linalg.norm(dyn, axis=1)
    A = A.at[0:6, :].set(dyn * scale[:, None])
    lb = lb.at[0:6].set(c * scale)
    ub = ub.at[0:6].set(c * scale)

    # Per-robot vertical-thrust floor.
    for i in range(n):
        A = A.at[6 + i, 6 + 3 * i + 2].set(1.0)
    lb = lb.at[6 : 6 + n].set(cfg.min_fz)
    ub = ub.at[6 : 6 + n].set(socp.INF)

    # Payload tilt / |wl| / |vl| CBF rows (identical math to
    # rp_centralized.py:153-175).
    R_w_hat = Rl @ lie.hat(state.wl)
    R_w_hat_sq = Rl @ lie.hat_square(state.wl, state.wl)
    r_tilt = 6 + n
    A = A.at[r_tilt, 3:6].set(-(Rl[2] @ lie.hat(e3)))
    tilt_rhs = (
        -R_w_hat_sq[2, 2]
        - (cfg.alpha1_p_cbf + cfg.alpha2_p_cbf) * R_w_hat[2, 2]
        - cfg.alpha1_p_cbf * cfg.alpha2_p_cbf * (Rl[2, 2] - cfg.cos_max_p_ang)
    )
    lb = lb.at[r_tilt].set(tilt_rhs)
    ub = ub.at[r_tilt].set(socp.INF)

    A = A.at[7 + n, 3:6].set(-2.0 * state.wl)
    lb = lb.at[7 + n].set(
        -cfg.alpha_wl_cbf * (cfg.max_wl_sq - jnp.dot(state.wl, state.wl))
    )
    ub = ub.at[7 + n].set(socp.INF)

    A = A.at[8 + n, 0:3].set(-2.0 * state.vl)
    lb = lb.at[8 + n].set(
        -cfg.alpha_vl_cbf * (cfg.max_vl_sq - jnp.dot(state.vl, state.vl))
    )
    ub = ub.at[8 + n].set(socp.INF)

    soc = jnp.zeros((8 * n, nv), dtype)
    shift_soc = jnp.zeros((8 * n,), dtype)
    for i in range(n):
        base = 8 * i
        fi = 6 + 3 * i
        soc = soc.at[base, fi + 2].set(cfg.sec_max_f_ang)
        soc = soc.at[base + 1 : base + 4, fi : fi + 3].set(jnp.eye(3, dtype=dtype))
        shift_soc = shift_soc.at[base + 4].set(cfg.max_f)
        soc = soc.at[base + 5 : base + 8, fi : fi + 3].set(jnp.eye(3, dtype=dtype))

    A_full = jnp.concatenate([A, soc], axis=0)
    shift = jnp.concatenate([jnp.zeros((n_box,), dtype), shift_soc])
    return P, q, A_full, lb, ub, shift


def control(
    params: PMRLParams,
    cfg: PMRLCentralizedConfig,
    ctrl_state: CtrlState,
    state: PMRLState,
    acc_des,
    rob_acc_des=None,
):
    """One control step: ``-> (f (n, 3), CtrlState, SolverStats)`` with the
    previous-solution fallback the reference controllers use
    (rp_centralized.py:291-302). ``f`` feeds ``pmrl.integrate`` directly.

    ``rob_acc_des (n, 3)``: desired robot accelerations (default:
    ``dvl_des - swing_damp * L_i dq_i`` — every robot accelerates like the
    payload target while damping its link's swing; see the k_rob /
    swing_damp config docstrings)."""
    n = params.n
    if rob_acc_des is None:
        rob_acc_des = (
            acc_des[0][None, :]
            - cfg.swing_damp * params.L[:, None] * state.dq
        )
    f_eq = equilibrium_forces(params, state)
    P, q, A, lb, ub, shift = _build_qp(
        params, cfg, f_eq, state, acc_des, rob_acc_des
    )
    n_box, _, soc_dims = qp_dims(n)
    sol = socp.solve_socp(
        P, q, A, lb, ub,
        n_box=n_box, soc_dims=soc_dims, iters=cfg.solver_iters,
        warm=ctrl_state.warm, shift=shift,
        check_every=cfg.solver_check_every, tol=cfg.solver_tol,
    )
    f = sol.x[6:].reshape(n, 3)
    ok = (sol.prim_res < cfg.solver_tol) & jnp.all(jnp.isfinite(sol.x))
    f_out = jnp.where(ok, f, ctrl_state.prev_f)
    keep = lambda new, old: jnp.where(ok, new, old)
    warm = socp.SOCPSolution(
        x=keep(sol.x, ctrl_state.warm.x),
        y=keep(sol.y, ctrl_state.warm.y),
        z=keep(sol.z, ctrl_state.warm.z),
        prim_res=sol.prim_res,
        dual_res=sol.dual_res,
    )
    stats = SolverStats(
        iters=jnp.asarray(-1, jnp.int32),
        solve_res=sol.prim_res,
        collision=jnp.zeros((), bool),
        min_env_dist=jnp.asarray(jnp.inf, state.xl.dtype),
        ok_frac=ok.astype(sol.x.dtype),
    )
    return f_out, CtrlState(prev_f=f_out, warm=warm), stats
