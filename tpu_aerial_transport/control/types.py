"""Shared controller data types (pytrees)."""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct


@struct.dataclass
class SolverStats:
    """Per-control-step statistics (reference ``SolverStatistics``,
    control/rqp_centralized.py:18-24). ``iters`` is -1 for the centralized solver;
    distributed solvers report consensus iterations. ``err_seq`` (fixed-length,
    NaN-padded) carries per-iteration consensus residuals for convergence plots."""

    iters: jnp.ndarray  # () int32.
    solve_res: jnp.ndarray  # () primal residual of the conic solve.
    collision: jnp.ndarray  # () bool.
    min_env_dist: jnp.ndarray  # () float.
    err_seq: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((0,))
    )  # (max_iters,) consensus residuals (distributed only).
    # Worst-iteration fraction of per-agent solves that met solver_tol (the
    # rest fell back to equilibrium forces, reference rqp_cadmm.py:491-494).
    # 1.0 = no fallbacks. Surfaces silent solver-accuracy regressions that
    # would otherwise only show as an exactly-zero consensus residual.
    ok_frac: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.ones(())
    )
    # Fallback-ladder rung the rollout landed on this step (stamped via
    # stats.replace by resilience.rollout.resilient_rollout after the
    # ladder select; controllers themselves leave it 0):
    # 0 = clean warm solve, 1 = internal retry/equilibrium substitution
    # (ok_frac < 1), 2 = non-finite forces -> held previous force,
    # 3 = non-finite forces and no finite previous -> equilibrium forces.
    fallback_rung: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((), jnp.int32)
    )
    # Per-agent final QP residuals ((n,); the distributed controllers'
    # exit-time warm-start prim_res) for per-agent solve-health telemetry
    # (obs.telemetry). Populated ONLY under the controllers' static
    # ``track_agent_stats`` config so the default program is unchanged;
    # the (0,) default means "not tracked".
    agent_solve_res: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((0,))
    )
    # Total effective inner ADMM iterations this control step (summed over
    # agents and consensus iterations) for the solver-effort telemetry
    # histograms (obs.telemetry). Populated ONLY by the consensus
    # controllers under ``effort="adaptive"`` (a Python-level branch, so
    # the fixed-effort program is byte-identical to the pre-knob one);
    # the (0,) default means "not tracked" — the agent_solve_res sentinel
    # convention.
    inner_iters: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((0,), jnp.int32)
    )


@struct.dataclass
class EnvCBF:
    """Environment collision-avoidance CBF rows ``lhs @ dvl >= rhs`` plus the
    side-channel observability outputs (reference
    ``_set_collision_avoidance_cbf_parameters``, control/rqp_centralized.py:280-337).
    Inactive rows are lhs = 0 with rhs < 0 (vacuously satisfied)."""

    lhs: jnp.ndarray  # (k, 3).
    rhs: jnp.ndarray  # (k,).
    collision: jnp.ndarray  # () bool.
    min_dist: jnp.ndarray  # () float.


def inactive_env_cbf(
    n_rows: int, vision_radius: float, dist_eps: float, alpha: float,
    dtype=jnp.float32,
) -> EnvCBF:
    """The no-environment default (reference :281-288)."""
    return EnvCBF(
        lhs=jnp.zeros((n_rows, 3), dtype),
        rhs=jnp.full((n_rows,), -alpha * (vision_radius - dist_eps), dtype),
        collision=jnp.zeros((), bool),
        min_dist=jnp.asarray(vision_radius, dtype),
    )
