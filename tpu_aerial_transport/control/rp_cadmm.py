"""Consensus-ADMM distributed controller for the rigid-payload (RP) model.

BEYOND-REFERENCE: the reference ships distributed solvers only for the RQP
model (control/rqp_cadmm.py); its RP controller is centralized-only
(control/rp_centralized.py). This module applies the same global-consensus
decomposition to RP — demonstrating the distributed machinery generalizes
across the model families — with the same TPU realization as
:mod:`control.cadmm`: all n agent SOCPs solved in one vmapped batch per
consensus iteration, consensus mean/residual as ``psum``-style reductions
(``axis_name`` for a sharded mesh, plain ``jnp`` single-program otherwise),
converged lanes frozen by ``lax.while_loop``'s batching semantics.

Decomposition (mirroring reference rqp_cadmm.py:465-471, :569-574 on the RP
problem): each agent holds a full local copy ``f^(i) (n, 3)`` of all
forces plus private ``dvl, dwl``; agent i's QP keeps ONLY its own
actuation rows (min-thrust box + thrust-cone/norm-cap SOCs — other agents'
rows are zeroed/relaxed, which with fixed shapes is the vmappable
equivalent of the reference's per-agent constraint subsetting,
rqp_cadmm.py:394-404), the shared payload dynamics equalities, and the
shared state CBF rows; the tracking cost rides on the leader alone and the
force-regularization weights are scaled 1/n so the agent costs SUM to the
centralized objective. Consensus-ADMM then drives the copies together:
``f_mean = mean_i f^(i)``, ``lam_i += rho (f^(i) - f_mean)``, stop when
``max_i |f^(i) - f_mean|_inf < res_tol`` (reference stopping rule,
rqp_cadmm.py:560-564).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from tpu_aerial_transport.control import rp_centralized
from tpu_aerial_transport.control.rp_centralized import RPCentralizedConfig
from tpu_aerial_transport.control.types import SolverStats
from tpu_aerial_transport.models.rp import RPParams, RPState
from tpu_aerial_transport.ops import socp


@struct.dataclass
class RPCADMMConfig:
    base: RPCentralizedConfig
    rho: float = 1.0
    res_tol: float = 1e-2
    leader_idx: int = 0
    max_iter: int = struct.field(pytree_node=False, default=20)
    inner_iters: int = struct.field(pytree_node=False, default=20)
    # Bound on CONSECUTIVE failing consensus iterations (retries); 0 = up
    # to max_iter. Same knob and default as
    # RQPCADMMConfig.solve_retry_iters.
    solve_retry_iters: int = struct.field(pytree_node=False, default=4)
    # Carry consensus duals across control steps. Default OFF: measured in
    # closed loop (circle track, tests/test_rp_cadmm.py), carried duals
    # drift — stale consensus prices at a moved reference bias the agent
    # solves, solver failures feed fallback forces into the dual update,
    # and tracking error grows without bound (0.27 -> 0.44 -> 0.84 over 800
    # steps, |lam| 3.8 -> 9.3), while per-step reset tracks at ~0.10 with
    # small duals. Warm PRIMAL starts are still carried either way.
    carry_duals: bool = struct.field(pytree_node=False, default=False)


def make_config(
    params: RPParams,
    max_iter: int = 20,
    inner_iters: int = 20,
    res_tol: float = 1e-2,
    rho: float = 1.0,
    leader_idx: int = 0,
    carry_duals: bool = False,
    solve_retry_iters: int = 4,
) -> RPCADMMConfig:
    """Distributed deltas vs the centralized config (mirroring the RQP
    reference's _set_controller_constants distributed scaling,
    rqp_cadmm.py:192-236): force-regularization weights divided by n so the
    per-agent costs sum to the centralized objective."""
    n = params.n
    base = rp_centralized.make_config(params, solver_iters=inner_iters)
    base = base.replace(k_f=base.k_f / n)
    return RPCADMMConfig(
        base=base, rho=rho, res_tol=res_tol, leader_idx=leader_idx,
        max_iter=max_iter, inner_iters=inner_iters, carry_duals=carry_duals,
        solve_retry_iters=solve_retry_iters,
    )


@struct.dataclass
class RPCADMMState:
    """Per-agent copies, duals, and warm starts across control steps."""

    f: jnp.ndarray  # (n, n, 3) agent i's copy of all forces.
    lam: jnp.ndarray  # (n, n, 3) consensus duals.
    warm: socp.SOCPSolution  # batched (n, ...) warm starts.


def init_state(params: RPParams, cfg: RPCADMMConfig,
               f_eq: jnp.ndarray) -> RPCADMMState:
    n = params.n
    dtype = f_eq.dtype
    nv = 6 + 3 * n
    m = (9 + n) + 8 * n
    warm = socp.SOCPSolution(
        x=jnp.zeros((n, nv), dtype),
        y=jnp.zeros((n, m), dtype),
        z=jnp.zeros((n, m), dtype),
        prim_res=jnp.zeros((n,), dtype),
        dual_res=jnp.zeros((n,), dtype),
    )
    return RPCADMMState(
        f=jnp.tile(f_eq[None], (n, 1, 1)),
        lam=jnp.zeros((n, n, 3), dtype),
        warm=warm,
    )


def _agent_qp(params: RPParams, cfg: RPCADMMConfig, f_eq, state: RPState,
              acc_des, onehot, leader):
    """Agent i's QP from the centralized builder + fixed-shape masking:
    zero the OTHER agents' SOC rows (a zero row with its translated-cone
    shift is trivially satisfiable), relax their min-thrust boxes to -inf,
    gate the tracking cost on leadership, and keep the equilibrium anchor
    on the own force only."""
    n = params.n
    dtype = state.xl.dtype
    base = cfg.base
    P, q, A, lb, ub, shift, scales = rp_centralized._build_qp(
        params, base, f_eq, state, acc_des
    )
    n_box = 9 + n

    # Tracking cost only on the leader (reference rqp_cadmm.py:231-233):
    # the builder added 2 k_dvl I / 2 k_dwl I and linear terms — rescale.
    track = leader.astype(dtype)
    P = P.at[0:6, 0:6].multiply(track)
    q = q.at[0:6].multiply(track)
    # Equilibrium anchor on the OWN force only (sum over agents equals the
    # centralized k_feq term).
    own3 = jnp.repeat(onehot, 3)
    damp = 2.0 * base.k_feq * (1.0 - own3)
    P = P.at[6:, 6:].add(-jnp.diag(damp))
    q = q.at[6:].add(2.0 * base.k_feq * f_eq.reshape(-1) * (1.0 - own3))

    # Other agents' min-thrust rows: relax to -inf (rows 6 : 6+n). The own
    # row's bound must carry the row-equilibration scale the builder
    # applied — writing the raw base.min_fz against a rescaled A row would
    # silently tighten/loosen the constraint by the row norm.
    lb = lb.at[6:6 + n].set(
        jnp.where(onehot > 0, base.min_fz * scales[6:6 + n], -socp.INF)
    )
    # Other agents' SOC blocks: zero the rows (2 blocks of 4 per agent,
    # after the n_box rows). Row-mask of shape (8n,): 1 for own block.
    soc_mask = jnp.repeat(onehot, 8)
    A = A.at[n_box:].multiply(soc_mask[:, None])
    return P, q, A, lb, ub, shift


def control(
    params: RPParams,
    cfg: RPCADMMConfig,
    f_eq: jnp.ndarray,
    cstate: RPCADMMState,
    state: RPState,
    acc_des,
    axis_name: str | None = None,
):
    """One distributed control step ``-> (f (n_local, 3), RPCADMMState,
    SolverStats)``. ``f`` is each agent's own column of its copy (the
    force it will actually apply), as in the RQP controller.

    With ``axis_name=None`` all n agents run in one program (vmap). Inside
    ``shard_map`` over a mesh axis named ``axis_name`` each shard holds a
    block of agents (the leading axis of every ``RPCADMMState`` leaf); the
    consensus mean runs as ``psum(local sum) / n`` (correct for any shard
    split) and the residual as a ``pmax`` collective."""
    n = params.n
    base = cfg.base
    dtype = state.xl.dtype
    n_box = 9 + n
    soc_dims = (4,) * (2 * n)

    n_local = cstate.f.shape[0]
    if axis_name is None:
        agent_ids = jnp.arange(n_local)
    else:
        agent_ids = lax.axis_index(axis_name) * n_local + jnp.arange(n_local)
    onehots = (agent_ids[:, None] == jnp.arange(n)[None, :]).astype(dtype)
    leaders = (agent_ids == cfg.leader_idx).astype(dtype)

    P, q0, A, lb, ub, shift = jax.vmap(
        lambda oh, ld: _agent_qp(params, cfg, f_eq, state, acc_des, oh, ld)
    )(onehots, leaders)

    # Augmented-Lagrangian quadratic: rho/2 ||f - f_mean||^2 adds rho I to
    # the force block — fold into the KKT operator once per control step.
    rho = jnp.asarray(cfg.rho, dtype)
    nv = 6 + 3 * n
    P_aug = P + jnp.diag(
        jnp.concatenate([jnp.zeros((6,), dtype), jnp.full((3 * n,), rho)])
    )[None]
    m = A.shape[1]
    # One constant feeds BOTH the precomputed operator and the solver so the
    # two rho_vecs cannot silently diverge (the KKTOp.sigma-mismatch hazard,
    # socp.py:73-77, applies to rho identically).
    solver_rho = 0.4
    rho_vec = jax.vmap(
        lambda lb_, ub_: socp.make_rho_vec(m, n_box, lb_, ub_, solver_rho,
                                           dtype)
    )(lb, ub)
    op = socp.kkt_operator(P_aug, A, rho_vec)

    solve_one = jax.vmap(
        lambda P_, q_, A_, lb_, ub_, shift_, op_, warm_: socp.solve_socp(
            P_, q_, A_, lb_, ub_,
            n_box=n_box, soc_dims=soc_dims, iters=cfg.inner_iters,
            rho=solver_rho, warm=warm_, shift=shift_, op=op_,
        )
    )

    def _mean_over_agents(x):
        # psum(local sum) / n — cadmm.control's reduction form: correct for
        # ANY shard split, not just equal shards.
        s = jnp.sum(x, axis=0)
        if axis_name is not None:
            s = lax.psum(s, axis_name)
        return s / n

    def _max_over_agents(x):
        s = jnp.max(x)
        return s if axis_name is None else lax.pmax(s, axis_name)

    fallback = jnp.tile(f_eq[None], (n_local, 1, 1))

    def admm_iter(carry):
        f, lam, f_mean, warm, it, res, okf, _ok_last, fail_count = carry
        # Linear term: <lam_i, f> - rho <f_mean, f> on the force block.
        q = q0.at[:, 6:].add((lam - rho * f_mean[None]).reshape(n_local, -1))
        sols = solve_one(P_aug, q, A, lb, ub, shift, op, warm)
        ok = (sols.prim_res < base.solver_tol) & jnp.all(
            jnp.isfinite(sols.x), axis=-1
        )
        f_new = jnp.where(
            ok[:, None, None], sols.x[:, 6:].reshape(n_local, n, 3), fallback
        )
        # Keep any FINITE iterate as the warm start (see the matching note
        # in cadmm._consensus_iter_impl): tolerance-missed solves accumulate
        # progress across retries; only non-finite iterates revert.
        finite = socp.solution_is_finite(sols)
        warm_new = jax.tree.map(
            lambda new, old: jnp.where(
                finite.reshape((n_local,) + (1,) * (new.ndim - 1)), new, old
            ),
            sols, warm,
        )
        f_mean_new = _mean_over_agents(f_new)
        res_new = _max_over_agents(jnp.abs(f_new - f_mean_new[None]))
        # Gated like the loop's own break (cadmm.py pattern; reference
        # rqp_cadmm.py:655-665): no dual step once converged/past the cap,
        # so the state carried to the next control step sits at the
        # converged fixed point.
        do_dual = (res_new >= cfg.res_tol) & (it + 1 <= cfg.max_iter)
        lam_new = jnp.where(
            do_dual, lam + rho * (f_new - f_mean_new[None]), lam
        )
        ok_last = _mean_over_agents(ok.astype(dtype))
        okf = jnp.minimum(okf, ok_last)
        fail_count = jnp.where(ok_last < 1.0, fail_count + 1, 0)  # consecutive.
        return (f_new, lam_new, f_mean_new, warm_new, it + 1, res_new, okf,
                ok_last, fail_count)

    retry_cap = cfg.solve_retry_iters or cfg.max_iter

    def cond(carry):
        *_, it, res, _okf, ok_last, fail_count = carry
        # Solve failures keep the loop alive even at consensus agreement
        # (see the matching note in cadmm.control's cond; bounded by
        # solve_retry_iters (default 4) FAILING iterations from onset —
        # warm starts persist across control steps too).
        return (((res >= cfg.res_tol)
                 | ((ok_last < 1.0) & (fail_count <= retry_cap)))
                & (it <= cfg.max_iter))

    f_mean0 = _mean_over_agents(cstate.f)
    lam0 = cstate.lam if cfg.carry_duals else jnp.zeros_like(cstate.lam)
    init = (cstate.f, lam0, f_mean0, cstate.warm,
            jnp.zeros((), jnp.int32), jnp.asarray(jnp.inf, dtype),
            jnp.ones((), dtype), jnp.ones((), dtype),
            jnp.zeros((), jnp.int32))
    (f, lam, f_mean, warm, iters, res, ok_frac, _ok_last,
     _fail_count) = lax.while_loop(cond, admm_iter, init)

    # Agent i's own column of its copy (local rows index the GLOBAL agent
    # axis by agent_ids under sharding).
    f_own = jnp.take_along_axis(
        f, agent_ids[:, None, None], axis=1
    )[:, 0, :]
    new_state = RPCADMMState(f=f, lam=lam, warm=warm)
    stats = SolverStats(
        iters=iters,
        solve_res=res,
        collision=jnp.zeros((), bool),
        min_env_dist=jnp.asarray(jnp.inf, dtype),
        ok_frac=ok_frac,
    )
    return f_own, new_state, stats
