"""Centralized QP + CBF safety-filter controller for the rigid-payload (RP) model.

TPU-native re-design of reference ``control/rp_centralized.py``
(``RPCentralizedController``, problem docstring :11-22): decision variables
``[dvl | dwl | f_1..f_n]`` (no CoM split — RP forces act at payload body points),
quadratic tracking + regularization costs, payload dynamics equalities, per-agent
thrust-cone/norm SOCs, tilt / |wl| / |vl| CBF rows. No environment CBFs (the
reference leaves them as a TODO at :74).

Reference constants (:147-175): min_fz = ml g / 10n, cone 30 deg,
max_f = 2 ml g / n, max payload tilt 30 deg (vs 15 for RQP), |wl| <= pi/6,
|vl| <= 1, k_f = k_feq = 0.1, k_dvl = k_dwl = 1.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

from tpu_aerial_transport.control.types import SolverStats
from tpu_aerial_transport.models.rp import GRAVITY, RPParams, RPState
from tpu_aerial_transport.ops import lie, socp


@struct.dataclass
class RPCentralizedConfig:
    min_fz: float
    sec_max_f_ang: float
    max_f: float
    cos_max_p_ang: float
    alpha1_p_cbf: float
    alpha2_p_cbf: float
    max_wl_sq: float
    alpha_wl_cbf: float
    max_vl_sq: float
    alpha_vl_cbf: float
    k_f: float
    k_feq: float
    k_dvl: float
    k_dwl: float
    solver_iters: int = struct.field(pytree_node=False, default=150)
    solver_tol: float = struct.field(pytree_node=False, default=5e-3)


def make_config(params: RPParams, solver_iters: int = 150) -> RPCentralizedConfig:
    n = params.n
    mlg = float(params.ml) * GRAVITY
    return RPCentralizedConfig(
        min_fz=mlg / (n * 10.0),
        sec_max_f_ang=float(1.0 / jnp.cos(jnp.pi / 6.0)),
        max_f=2.0 * mlg / n,
        cos_max_p_ang=float(jnp.cos(jnp.pi / 6.0)),  # 30 deg for RP.
        alpha1_p_cbf=1.0,
        alpha2_p_cbf=1.0,
        max_wl_sq=float((jnp.pi / 6.0) ** 2),
        alpha_wl_cbf=1.0,
        max_vl_sq=1.0,
        alpha_vl_cbf=1.0,
        k_f=0.1,
        k_feq=0.1,
        k_dvl=1.0,
        k_dwl=1.0,
        solver_iters=solver_iters,
    )


def equilibrium_forces(params: RPParams) -> jnp.ndarray:
    """Vertical static-wrench-balance forces (reference :122-130)."""
    n = params.n
    e3 = jnp.array([0.0, 0.0, 1.0], dtype=params.r.dtype)
    rxe = jnp.cross(params.r, e3)
    wrench = jnp.concatenate(
        [jnp.ones((n, 1), params.r.dtype), rxe[:, :2]], axis=1
    ).T
    rhs = jnp.array([params.ml * GRAVITY, 0.0, 0.0], dtype=params.r.dtype)
    fz = jnp.linalg.lstsq(wrench, rhs)[0]
    return jnp.concatenate([jnp.zeros((n, 2), params.r.dtype), fz[:, None]], axis=1)


@struct.dataclass
class CtrlState:
    prev_f: jnp.ndarray  # (n, 3)
    warm: socp.SOCPSolution


def init_ctrl_state(params: RPParams, cfg: RPCentralizedConfig) -> CtrlState:
    n = params.n
    n_box = 9 + n
    m = n_box + 8 * n
    f_eq = equilibrium_forces(params)
    x0 = jnp.concatenate([jnp.zeros(6, f_eq.dtype), f_eq.reshape(-1)])
    warm = socp.SOCPSolution(
        x=x0,
        y=jnp.zeros((m,), f_eq.dtype),
        z=jnp.zeros((m,), f_eq.dtype),
        prim_res=jnp.zeros((), f_eq.dtype),
        dual_res=jnp.zeros((), f_eq.dtype),
    )
    return CtrlState(prev_f=f_eq, warm=warm)


def _build_qp(params: RPParams, cfg: RPCentralizedConfig, f_eq, state: RPState,
              acc_des):
    """[dvl 0:3 | dwl 3:6 | f 6:6+3n]; box rows [dyn-trans 3 | dyn-rot 3 |
    fz n | tilt 1 | wl 1 | vl 1] then 2n SOC(4) blocks."""
    n = params.n
    dtype = state.xl.dtype
    nv = 6 + 3 * n
    dvl_des, dwl_des = acc_des
    e3 = jnp.array([0.0, 0.0, 1.0], dtype=dtype)
    Rl = state.Rl

    P = jnp.zeros((nv, nv), dtype)
    q = jnp.zeros((nv,), dtype)
    P = P.at[0:3, 0:3].add(2.0 * cfg.k_dvl * jnp.eye(3, dtype=dtype))
    q = q.at[0:3].add(-2.0 * cfg.k_dvl * dvl_des)
    P = P.at[3:6, 3:6].add(2.0 * cfg.k_dwl * jnp.eye(3, dtype=dtype))
    q = q.at[3:6].add(-2.0 * cfg.k_dwl * dwl_des)
    S = jnp.tile(jnp.eye(3, dtype=dtype), (1, n))
    P = P.at[6:, 6:].add(
        2.0 * cfg.k_f * (S.T @ S) + 2.0 * cfg.k_feq * jnp.eye(3 * n, dtype=dtype)
    )
    q = q.at[6:].add(
        -2.0 * cfg.k_f * (S.T @ (params.ml * GRAVITY * e3))
        - 2.0 * cfg.k_feq * f_eq.reshape(-1)
    )

    n_box = 9 + n
    A = jnp.zeros((n_box, nv), dtype)
    lb = jnp.zeros((n_box,), dtype)
    ub = jnp.zeros((n_box,), dtype)

    # ml dvl - sum f_i = -ml g e3.
    A = A.at[0:3, 0:3].set(params.ml * jnp.eye(3, dtype=dtype))
    A = A.at[0:3, 6:].set(-S)
    rhs = -params.ml * GRAVITY * e3
    lb = lb.at[0:3].set(rhs)
    ub = ub.at[0:3].set(rhs)

    # dwl - sum Jl_inv hat(r_i) Rl^T f_i = -Jl_inv (wl x Jl wl).
    G = jnp.concatenate([lie.hat(params.r[i]) @ Rl.T for i in range(n)], axis=1)
    A = A.at[3:6, 3:6].set(jnp.eye(3, dtype=dtype))
    A = A.at[3:6, 6:].set(-params.Jl_inv @ G)
    rot_rhs = -params.Jl_inv @ jnp.cross(state.wl, params.Jl @ state.wl)
    lb = lb.at[3:6].set(rot_rhs)
    ub = ub.at[3:6].set(rot_rhs)

    for i in range(n):
        A = A.at[6 + i, 6 + 3 * i + 2].set(1.0)
    lb = lb.at[6 : 6 + n].set(cfg.min_fz)
    ub = ub.at[6 : 6 + n].set(socp.INF)

    R_w_hat = Rl @ lie.hat(state.wl)
    R_w_hat_sq = Rl @ lie.hat_square(state.wl, state.wl)
    r_tilt = 6 + n
    A = A.at[r_tilt, 3:6].set(-(Rl[2] @ lie.hat(e3)))
    tilt_rhs = (
        -R_w_hat_sq[2, 2]
        - (cfg.alpha1_p_cbf + cfg.alpha2_p_cbf) * R_w_hat[2, 2]
        - cfg.alpha1_p_cbf * cfg.alpha2_p_cbf * (Rl[2, 2] - cfg.cos_max_p_ang)
    )
    lb = lb.at[r_tilt].set(tilt_rhs)
    ub = ub.at[r_tilt].set(socp.INF)

    A = A.at[7 + n, 3:6].set(-2.0 * state.wl)
    lb = lb.at[7 + n].set(
        -cfg.alpha_wl_cbf * (cfg.max_wl_sq - jnp.dot(state.wl, state.wl))
    )
    ub = ub.at[7 + n].set(socp.INF)

    A = A.at[8 + n, 0:3].set(-2.0 * state.vl)
    lb = lb.at[8 + n].set(
        -cfg.alpha_vl_cbf * (cfg.max_vl_sq - jnp.dot(state.vl, state.vl))
    )
    ub = ub.at[8 + n].set(socp.INF)

    soc = jnp.zeros((8 * n, nv), dtype)
    shift_soc = jnp.zeros((8 * n,), dtype)
    for i in range(n):
        base = 8 * i
        fi = 6 + 3 * i
        soc = soc.at[base, fi + 2].set(cfg.sec_max_f_ang)
        soc = soc.at[base + 1 : base + 4, fi : fi + 3].set(jnp.eye(3, dtype=dtype))
        shift_soc = shift_soc.at[base + 4].set(cfg.max_f)
        soc = soc.at[base + 5 : base + 8, fi : fi + 3].set(jnp.eye(3, dtype=dtype))

    A_full = jnp.concatenate([A, soc], axis=0)
    shift = jnp.concatenate([jnp.zeros((n_box,), dtype), shift_soc])
    # Row equilibration (exact, see socp.equilibrate_rows): the rotation
    # dynamics rows carry Jl_inv ~ O(50) against O(ml) translation rows;
    # without rescaling the leader-cost QPs of the distributed RP
    # controller measurably need ~600 ADMM iterations instead of ~40.
    A_full, lb, ub, shift, scales = socp.equilibrate_rows(
        A_full, lb, ub, shift, n_box, (4,) * (2 * n)
    )
    # scales returned so callers that rewrite individual bounds (the
    # distributed rp_cadmm._agent_qp min-thrust relaxation) can stay in the
    # equilibrated row scaling instead of silently mixing raw constants
    # into rescaled rows.
    return P, q, A_full, lb, ub, shift, scales


def control(
    params: RPParams,
    cfg: RPCentralizedConfig,
    f_eq: jnp.ndarray,
    ctrl_state: CtrlState,
    state: RPState,
    acc_des,
):
    """One control step: ``-> (f (n, 3), CtrlState, SolverStats)`` with
    previous-solution fallback (reference ``control``, :291-302)."""
    n = params.n
    P, q, A, lb, ub, shift, _ = _build_qp(params, cfg, f_eq, state, acc_des)
    sol = socp.solve_socp(
        P, q, A, lb, ub,
        n_box=9 + n, soc_dims=(4,) * (2 * n), iters=cfg.solver_iters,
        warm=ctrl_state.warm, shift=shift,
    )
    f = sol.x[6:].reshape(n, 3)
    ok = (sol.prim_res < cfg.solver_tol) & jnp.all(jnp.isfinite(sol.x))
    f_out = jnp.where(ok, f, ctrl_state.prev_f)
    keep = lambda new, old: jnp.where(ok, new, old)
    warm = socp.SOCPSolution(
        x=keep(sol.x, ctrl_state.warm.x),
        y=keep(sol.y, ctrl_state.warm.y),
        z=keep(sol.z, ctrl_state.warm.z),
        prim_res=sol.prim_res,
        dual_res=sol.dual_res,
    )
    stats = SolverStats(
        iters=jnp.asarray(-1, jnp.int32),
        solve_res=sol.prim_res,
        collision=jnp.zeros((), bool),
        min_env_dist=jnp.asarray(jnp.inf, state.xl.dtype),
    )
    return f_out, CtrlState(prev_f=f_out, warm=warm), stats
