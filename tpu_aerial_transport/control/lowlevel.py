"""Low-level per-quadrotor (thrust, moment) controller for the RQP model.

TPU-native replacement for reference ``control/rqp_centralized.py:457-535``
(``RQPLowLevelController``): maps desired world-frame force vectors ``f_des (n, 3)``
to per-quad scalar thrusts + body moments, fully vmapped over the agent axis.

- thrust_i = <f_des_i, R_i e3>                      (reference :527)
- attitude target: zero-yaw rotation with body z along f_des_i / ||f_des_i||
  (reference :503-516, 529-530)
- moment from the PD or sliding-mode SO(3) law with ``wd = dwd = 0`` (the reference
  notes at :531 that ``wd = state.w`` "causes instability").
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
from flax import struct

from tpu_aerial_transport.control import so3_tracking
from tpu_aerial_transport.models.rqp import RQPParams, RQPState
from tpu_aerial_transport.ops import lie


@struct.dataclass
class LowLevelController:
    """Pure-pytree controller config. ``so3_params`` selects the law by type."""

    J: jnp.ndarray  # (n, 3, 3) quad inertias.
    so3_params: so3_tracking.So3PDParams | so3_tracking.So3SMParams

    def control(self, state: RQPState, f_des: jnp.ndarray,
                thrust_scale: jnp.ndarray | None = None):
        """``f_des (n, 3)`` -> ``(f (n,), M (n, 3))``. Jit/vmap-safe.
        ``thrust_scale``: optional (n,) actuator-health scale (see
        :func:`lowlevel_control`)."""
        return lowlevel_control(self.J, self.so3_params, state, f_des,
                                thrust_scale)


def make_lowlevel_controller(
    so3_controller_type: str, params: RQPParams
) -> LowLevelController:
    """Factory mirroring ``RQPLowLevelController.__init__`` (gains at :487-497)."""
    if so3_controller_type == "pd":
        ll = so3_tracking.So3PDParams(k_R=0.25, k_Omega=0.075)
    elif so3_controller_type == "sm":
        ll = so3_tracking.So3SMParams(
            r=0.5, k_R=1.415, l_R=0.707, k_s=0.113, l_s=0.057
        )
    else:
        raise NotImplementedError(so3_controller_type)
    return LowLevelController(J=params.J, so3_params=ll)


def lowlevel_control(J, so3_params, state: RQPState, f_des,
                     thrust_scale=None):
    """Batched low-level control step (the body of ``RQPLowLevelController.control``,
    reference :518-535, without the per-agent Python loop).

    ``thrust_scale``: optional (n,) per-agent actuator-health scale from the
    resilience layer — rotor/actuator degradation caps both the scalar
    thrust and the moment authority multiplicatively (0 = dead agent:
    zero wrench). ``None`` is the nominal path.
    """
    # Scalar thrusts: projection of the desired force on each quad's body z-axis.
    body_z = state.R[..., :, 2]  # (n, 3) = R_i e3.
    f = jnp.sum(f_des * body_z, axis=-1)  # (n,)

    # Attitude targets: zero-yaw rotation with z-axis along f_des. A zero
    # desired force (a dead agent's masked command) keeps the current
    # attitude target direction well-defined instead of emitting NaNs.
    norm = jnp.linalg.norm(f_des, axis=-1, keepdims=True)
    qd = f_des / jnp.where(norm > 0, norm, 1.0)
    qd = jnp.where(norm > 0, qd, state.R[..., :, 2])
    Rd = lie.rotation_from_z(qd)  # (n, 3, 3)

    wd = jnp.zeros_like(state.w)
    dwd = jnp.zeros_like(state.w)
    if isinstance(so3_params, so3_tracking.So3PDParams):
        M = so3_tracking.so3_pd_tracking_control(
            state.R, Rd, state.w, wd, dwd, J, so3_params
        )
    else:
        M = so3_tracking.so3_sm_tracking_control(
            state.R, Rd, state.w, wd, dwd, J, so3_params
        )
    if thrust_scale is not None:
        f = f * thrust_scale
        M = M * thrust_scale[:, None]
    return f, M
