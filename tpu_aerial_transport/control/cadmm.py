"""Consensus-ADMM distributed controller for the RQP model.

TPU-native re-design of reference ``control/rqp_cadmm.py``: ``n`` agents each hold
a full local copy ``f^(i) in R^{n x 3}`` of all forces (global-consensus ADMM).
Per outer iteration (reference ``control``, :631-675):

  1. each agent solves its primal SOCP (cost docstring :27-46) with augmented
     objective ``<lambda_i, f> + (rho/2)||f - f_mean||^2``; only the agent's own
     force column carries actuation constraints (:394-404);
  2. consensus mean ``f_mean = (1/n) sum_i f^(i)`` and inf-norm residual
     ``max_i ||f^(i) - f_mean||_inf`` — the logical all-reduce (:582-625);
  3. stop when residual < ``res_tol`` (1e-2 N) or iteration cap; else dual update
     ``lambda_i += rho (f^(i) - f_mean)`` (:627-629).

TPU mapping (SURVEY.md §2.10): the reference's sequential per-agent loop becomes a
``vmap`` over the agent axis (one fused kernel for all n primal SOCPs); the
consensus mean/max are ``jnp`` reductions on-chip (and ``lax.psum``/``pmax`` over a
mesh axis in the ``parallel`` layer). Because the reference's default rho schedule
is constant (``rho0 = 1, tau_incr = 1``, :565-567), each agent's KKT matrix is
fixed within a control step: we factor all n of them once (vmapped Cholesky) and
reuse across every consensus iteration — only the linear term moves.

All controller state (local copies, duals, means, per-agent warm starts) persists
across control steps in :class:`CADMMState`, matching the reference's warm-start
behavior (:576-580 and cvxpy ``warm_start=True``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from tpu_aerial_transport.control.types import EnvCBF, SolverStats, inactive_env_cbf
from tpu_aerial_transport.envs import forest as forest_mod
from tpu_aerial_transport.models.rqp import GRAVITY, RQPParams, RQPState
from tpu_aerial_transport.ops import lie, socp
from tpu_aerial_transport.control.centralized import equilibrium_forces


@struct.dataclass
class RQPCADMMConfig:
    """Constants from reference ``_set_controller_constants`` (:192-236, :556-567).
    Note the distributed deltas vs centralized: ``k_f, k_m`` scaled by 1/n,
    ``alpha_env_cbf = 1.5``, per-agent vision cone of half-angle 100 deg."""

    min_fz: float
    sec_max_f_ang: float
    max_f: float
    cos_max_p_ang: float
    alpha1_p_cbf: float
    alpha2_p_cbf: float
    max_wl_sq: float
    alpha_wl_cbf: float
    max_vl_sq: float
    alpha_vl_cbf: float
    dist_eps: float
    vision_radius: float
    alpha_env_cbf: float
    max_deceleration: float
    vision_cone_ang: float
    k_f: float  # already divided by n.
    k_m: float
    k_feq: float
    k_dvl: float
    k_dwl: float
    rho0: float
    res_tol: float
    # Dynamic leader index (reference static index 0, rqp_cadmm.py:556-558,
    # with runtime set_leader/unset_leader hooks :503-507). A pytree LEAF, not
    # a static field, so a leader change mid-rollout (via :func:`set_leader`)
    # re-uses the compiled step; -1 means no leader (no agent carries the
    # tracking cost).
    leader_idx: int = 0
    # Static fields.
    n_env_cbfs: int = struct.field(pytree_node=False, default=10)
    max_iter: int = struct.field(pytree_node=False, default=100)
    inner_iters: int = struct.field(pytree_node=False, default=60)
    # Inner ADMM budget for consensus iterations >= 2, whose warm start is the
    # SAME control step's previous iterate (far closer than the cross-step
    # warm start the first iteration sees). 0 = use ``inner_iters``.
    inner_iters_warm: int = struct.field(pytree_node=False, default=0)
    solver_tol: float = struct.field(pytree_node=False, default=5e-3)
    max_f_ang: float = struct.field(pytree_node=False, default=jnp.pi / 6)


def make_config(
    params: RQPParams,
    collision_radius: float,
    max_deceleration: float,
    n_env_cbfs: int = 10,
    max_iter: int = 100,
    inner_iters: int = 60,
    res_tol: float = 1e-2,
    inner_iters_warm: int = 0,
) -> RQPCADMMConfig:
    """Defaults are reference-conservative (max_iter mirrors the reference's
    100-iteration cap). For warm-started receding-horizon use, the measured
    inner-iteration knee is ~20 (below it the agent solves miss ``solver_tol``
    and trip the equilibrium fallback; at 20 forces match an inner=80 solve to
    < 1e-4 N) — see bench.py / BASELINE.md."""
    n = params.n
    mTg = float(params.mT) * GRAVITY
    return RQPCADMMConfig(
        min_fz=mTg / (n * 10.0),
        sec_max_f_ang=float(1.0 / jnp.cos(jnp.pi / 6.0)),
        max_f=2.0 * mTg / n,
        cos_max_p_ang=float(jnp.cos(jnp.pi / 12.0)),
        alpha1_p_cbf=1.0,
        alpha2_p_cbf=1.0,
        max_wl_sq=float((jnp.pi / 6.0) ** 2),
        alpha_wl_cbf=1.0,
        max_vl_sq=1.0,
        alpha_vl_cbf=1.0,
        dist_eps=0.1,
        vision_radius=collision_radius + 5.0,
        alpha_env_cbf=1.5,
        max_deceleration=max_deceleration,
        vision_cone_ang=float(100.0 * jnp.pi / 180.0),
        k_f=0.1 / n,
        k_m=0.1 / n,
        k_feq=0.1,
        k_dvl=1.0,
        k_dwl=1.0,
        rho0=1.0,
        res_tol=res_tol,
        n_env_cbfs=n_env_cbfs,
        max_iter=max_iter,
        inner_iters=inner_iters,
        inner_iters_warm=inner_iters_warm,
    )


def set_leader(cfg, leader_idx):
    """Runtime leader change (reference ``set_leader``, rqp_cadmm.py:503-505 /
    rqp_dd.py:507-509): agent ``leader_idx`` alone carries the tracking cost.
    ``leader_idx`` is a dynamic pytree leaf, so the returned config re-uses any
    compiled control step — usable mid-rollout (even traced, via
    ``cfg.replace(leader_idx=...)`` inside a scan). Works on both
    :class:`RQPCADMMConfig` and the DD config (pass ``cfg.base``-level
    replace for that, or use the same helper on the wrapper)."""
    if hasattr(cfg, "base"):  # RQPDDConfig wraps the shared base config.
        return cfg.replace(base=cfg.base.replace(leader_idx=leader_idx))
    return cfg.replace(leader_idx=leader_idx)


def unset_leader(cfg):
    """No agent carries the tracking cost (reference ``unset_leader``,
    rqp_cadmm.py:506-507): the team holds formation/equilibrium only."""
    return set_leader(cfg, -1)


def set_tolerance(cfg, res_tol: float):
    """Runtime consensus-tolerance setter (reference ``set_tolerance``,
    rqp_cadmm.py:677-682 / rqp_dd.py:754-759). Dynamic leaf — no recompile."""
    if hasattr(cfg, "base"):
        return cfg.replace(
            base=cfg.base.replace(res_tol=res_tol), prim_inf_tol=res_tol
        )
    return cfg.replace(res_tol=res_tol)


def set_max_iter(cfg, max_iter: int):
    """Runtime iteration-cap setter (reference ``set_max_iterations``,
    rqp_cadmm.py:683-688 / rqp_dd.py:760-764). ``max_iter`` sizes the fixed
    ``err_seq`` buffer, so it is a STATIC field: changing it recompiles the
    step (the reference equivalent re-allocates its Python-side buffers)."""
    if hasattr(cfg, "base"):
        return cfg.replace(base=cfg.base.replace(max_iter=max_iter))
    return cfg.replace(max_iter=max_iter)


@struct.dataclass
class CADMMState:
    """Distributed-solver state carried across control steps (reference
    ``_set_variables`` + ``_set_warm_start``, :569-580)."""

    f: jnp.ndarray  # (n, n, 3): f[i, j] = agent i's copy of agent j's force.
    lam: jnp.ndarray  # (n, n, 3) duals.
    f_mean: jnp.ndarray  # (n, 3) consensus mean.
    warm: socp.SOCPSolution  # leading agent axis on every leaf.


def init_cadmm_state(params: RQPParams, cfg: RQPCADMMConfig) -> CADMMState:
    n = params.n
    f_eq = equilibrium_forces(params)
    dtype = f_eq.dtype
    nv = 9 + 3 * n
    n_box = 13 + cfg.n_env_cbfs
    m = n_box + 8
    x0 = jnp.concatenate([jnp.zeros(9, dtype), f_eq.reshape(-1)])
    warm = socp.SOCPSolution(
        x=jnp.tile(x0, (n, 1)),
        y=jnp.zeros((n, m), dtype),
        z=jnp.zeros((n, m), dtype),
        prim_res=jnp.zeros((n,), dtype),
        dual_res=jnp.zeros((n,), dtype),
    )
    return CADMMState(
        f=jnp.tile(f_eq, (n, 1, 1)),
        lam=jnp.zeros((n, n, 3), dtype),
        f_mean=f_eq,
        warm=warm,
    )


def _build_agent_qp(
    params: RQPParams,
    cfg: RQPCADMMConfig,
    f_eq: jnp.ndarray,
    state: RQPState,
    acc_des,
    env_cbf: EnvCBF,
    onehot: jnp.ndarray,
    is_leader: jnp.ndarray,
    rho,
):
    """Per-agent primal QP matrices (vmapped over ``onehot``/``is_leader``/CBF).

    Variable layout matches the centralized controller: [dv_com | dvl | dwl | f].
    Box rows: [dyn-trans 3 | dyn-rot 3 | kin 3 | own fz 1 | tilt 1 | wl 1 | vl 1 |
    env k]; SOC: own thrust cone + own norm cap. The consensus-ADMM quadratic
    ``(rho/2)||f||^2`` is baked into P (rho is constant within a control step);
    the iteration-varying linear term ``lambda - rho f_mean`` is added by the
    caller per consensus iteration.
    """
    n = params.n
    dtype = state.xl.dtype
    nv = 9 + 3 * n
    dvl_des, dwl_des = acc_des
    e3 = jnp.array([0.0, 0.0, 1.0], dtype=dtype)
    Rl = state.Rl

    P = jnp.zeros((nv, nv), dtype)
    q = jnp.zeros((nv,), dtype)
    k_dvl = cfg.k_dvl * is_leader
    k_dwl = cfg.k_dwl * is_leader
    P = P.at[3:6, 3:6].add(2.0 * k_dvl * jnp.eye(3, dtype=dtype))
    q = q.at[3:6].add(-2.0 * k_dvl * dvl_des)
    P = P.at[6:9, 6:9].add(2.0 * k_dwl * jnp.eye(3, dtype=dtype))
    q = q.at[6:9].add(-2.0 * k_dwl * dwl_des)

    S = jnp.tile(jnp.eye(3, dtype=dtype), (1, n))
    G = jnp.concatenate(
        [lie.hat(params.r_com[i]) @ Rl.T for i in range(n)], axis=1
    )
    own = jnp.repeat(onehot, 3)  # (3n,) mask of the agent's own force block.
    Pff = (
        2.0 * cfg.k_f * (S.T @ S)
        + 2.0 * cfg.k_m * (G.T @ G)
        + 2.0 * cfg.k_feq * jnp.diag(own)
        + rho * jnp.eye(3 * n, dtype=dtype)  # (rho/2)||f||^2.
    )
    P = P.at[9:, 9:].add(Pff)
    q = q.at[9:].add(
        -2.0 * cfg.k_f * (S.T @ (params.mT * GRAVITY * e3))
        - 2.0 * cfg.k_feq * own * f_eq.reshape(-1)
    )

    n_box = 13 + cfg.n_env_cbfs
    A = jnp.zeros((n_box, nv), dtype)
    lb = jnp.zeros((n_box,), dtype)
    ub = jnp.zeros((n_box,), dtype)

    A = A.at[0:3, 0:3].set(params.mT * jnp.eye(3, dtype=dtype))
    A = A.at[0:3, 9:].set(-S)
    rhs = -params.mT * GRAVITY * e3
    lb = lb.at[0:3].set(rhs)
    ub = ub.at[0:3].set(rhs)

    A = A.at[3:6, 6:9].set(jnp.eye(3, dtype=dtype))
    A = A.at[3:6, 9:].set(-params.JT_inv @ G)
    rot_rhs = -params.JT_inv @ jnp.cross(state.wl, params.JT @ state.wl)
    lb = lb.at[3:6].set(rot_rhs)
    ub = ub.at[3:6].set(rot_rhs)

    R_w_hat = Rl @ lie.hat(state.wl)
    R_w_hat_sq = Rl @ lie.hat_square(state.wl, state.wl)
    A = A.at[6:9, 0:3].set(-jnp.eye(3, dtype=dtype))
    A = A.at[6:9, 3:6].set(jnp.eye(3, dtype=dtype))
    A = A.at[6:9, 6:9].set(-Rl @ lie.hat(params.x_com))
    kin_rhs = -R_w_hat_sq @ params.x_com
    lb = lb.at[6:9].set(kin_rhs)
    ub = ub.at[6:9].set(kin_rhs)

    # Own-column f_z lower bound (row 9): one-hot selects the agent's column.
    fz_row = jnp.kron(onehot, e3)  # (3n,)
    A = A.at[9, 9:].set(fz_row)
    lb = lb.at[9].set(cfg.min_fz)
    ub = ub.at[9].set(socp.INF)

    A = A.at[10, 6:9].set(-(Rl[2] @ lie.hat(e3)))
    tilt_rhs = (
        -R_w_hat_sq[2, 2]
        - (cfg.alpha1_p_cbf + cfg.alpha2_p_cbf) * R_w_hat[2, 2]
        - cfg.alpha1_p_cbf * cfg.alpha2_p_cbf * (Rl[2, 2] - cfg.cos_max_p_ang)
    )
    lb = lb.at[10].set(tilt_rhs)
    ub = ub.at[10].set(socp.INF)

    A = A.at[11, 6:9].set(-2.0 * state.wl)
    lb = lb.at[11].set(
        -cfg.alpha_wl_cbf * (cfg.max_wl_sq - jnp.dot(state.wl, state.wl))
    )
    ub = ub.at[11].set(socp.INF)

    A = A.at[12, 3:6].set(-2.0 * state.vl)
    lb = lb.at[12].set(
        -cfg.alpha_vl_cbf * (cfg.max_vl_sq - jnp.dot(state.vl, state.vl))
    )
    ub = ub.at[12].set(socp.INF)

    A = A.at[13 : 13 + cfg.n_env_cbfs, 3:6].set(env_cbf.lhs)
    lb = lb.at[13 : 13 + cfg.n_env_cbfs].set(env_cbf.rhs)
    ub = ub.at[13 : 13 + cfg.n_env_cbfs].set(socp.INF)

    # SOC rows: own thrust cone [sec30 fz; f_own], own norm cap [max_f; f_own].
    soc = jnp.zeros((8, nv), dtype)
    shift_soc = jnp.zeros((8,), dtype)
    own_block = jnp.kron(onehot, jnp.eye(3, dtype=dtype))  # (3, 3n)
    soc = soc.at[0, 9:].set(cfg.sec_max_f_ang * fz_row)
    soc = soc.at[1:4, 9:].set(own_block)
    shift_soc = shift_soc.at[4].set(cfg.max_f)
    soc = soc.at[5:8, 9:].set(own_block)

    A_full = jnp.concatenate([A, soc], axis=0)
    shift = jnp.concatenate([jnp.zeros((n_box,), dtype), shift_soc])
    return P, q, A_full, lb, ub, shift


def agent_env_cbfs(
    params: RQPParams,
    cfg: RQPCADMMConfig,
    forest: forest_mod.Forest | None,
    state: RQPState,
) -> EnvCBF:
    """Per-agent vision-cone CBF rows for all n agents (single-program path)."""
    return agent_env_cbfs_for(params, cfg, forest, state, params.r)


def agent_env_cbfs_for(
    params: RQPParams,
    cfg: RQPCADMMConfig,
    forest: forest_mod.Forest | None,
    state: RQPState,
    r_block: jnp.ndarray,
) -> EnvCBF:
    """Per-agent vision-cone-masked collision CBF rows, batched over the agents
    whose attachment points are in ``r_block`` (a shard's block, or all of
    ``params.r``). Reference ``_set_collision_avoidance_cbf_parameters``,
    rqp_cadmm.py:307-373: camera at the agent's attachment point, cone toward
    its bearing from the payload center."""
    n = r_block.shape[0]
    if forest is None:
        base = inactive_env_cbf(
            cfg.n_env_cbfs, cfg.vision_radius, cfg.dist_eps, cfg.alpha_env_cbf,
            dtype=state.xl.dtype,
        )
        return jax.tree.map(lambda x: jnp.tile(x, (n,) + (1,) * x.ndim), base)

    # The braking capsule is identical for every agent (it depends only on the
    # payload state, reference :319-332) — run the expensive segment-cylinder
    # sweep ONCE and give each agent its own vision-cone mask + top-k rows.
    collision_radius = cfg.vision_radius - 5.0  # vision = collision + 5 (:216).
    cap_a, cap_b, cap_h, speed, cap_dir = forest_mod.braking_capsule(
        state.xl, state.vl, collision_radius, cfg.max_deceleration
    )
    data = forest_mod.capsule_forest_distance(
        forest, cap_a, cap_b, collision_radius, cfg.vision_radius
    )

    def one_agent(r_i):
        camera = (state.xl + state.Rl @ r_i)[:2]
        d = camera - state.xl[:2]
        norm = jnp.linalg.norm(d)
        direction = d / jnp.where(norm > 0, norm, 1.0)
        mask = forest_mod.vision_cone_mask(
            forest, camera, direction, cfg.vision_cone_ang
        )
        # Degenerate bearing (attachment above payload center): reference flags
        # collision and disables rows (:337-339).
        mask = mask & (norm > 0)
        cbf = forest_mod.cbf_rows_from_distance(
            data, state.xl, state.vl, cap_h, speed, cap_dir,
            cfg.max_deceleration, cfg.vision_radius, cfg.dist_eps,
            cfg.alpha_env_cbf, cfg.n_env_cbfs, extra_mask=mask,
        )
        return cbf.replace(collision=cbf.collision | (norm == 0))

    return jax.vmap(one_agent)(r_block)


def control(
    params: RQPParams,
    cfg: RQPCADMMConfig,
    f_eq: jnp.ndarray,
    admm_state: CADMMState,
    state: RQPState,
    acc_des,
    forest: forest_mod.Forest | None = None,
    axis_name: str | None = None,
):
    """One distributed control step: ``-> (f_app (n_local, 3), CADMMState,
    SolverStats)`` (reference ``RQPCADMMController.control``, :631-675).

    With ``axis_name=None`` all n agents run in one program (vmap; single chip).
    Inside ``shard_map`` over a mesh axis named ``axis_name``, each shard holds a
    block of agents (the leading axis of every ``CADMMState`` leaf) and the
    consensus mean/residual become ``lax.psum``/``pmax`` collectives over ICI —
    the all-reduce pattern SURVEY.md §2.10 prescribes. ``state``/``acc_des``/
    ``f_eq`` are replicated."""
    n = params.n
    dtype = state.xl.dtype
    rho = jnp.asarray(cfg.rho0, dtype)

    n_local = admm_state.f.shape[0]
    if axis_name is None:
        agent_ids = jnp.arange(n_local)
    else:
        agent_ids = lax.axis_index(axis_name) * n_local + jnp.arange(n_local)

    def _mean_over_agents(x):
        if axis_name is None:
            return jnp.mean(x, axis=0)
        return lax.psum(jnp.sum(x, axis=0), axis_name) / n

    def _max_over_agents(x):
        if axis_name is None:
            return jnp.max(x)
        return lax.pmax(jnp.max(x), axis_name)

    def _min_over_agents(x):
        if axis_name is None:
            return jnp.min(x)
        return lax.pmin(jnp.min(x), axis_name)

    r_local = jnp.take(params.r, agent_ids, axis=0)

    env_cbfs = agent_env_cbfs_for(params, cfg, forest, state, r_local)
    onehots = jax.nn.one_hot(agent_ids, n, dtype=dtype)
    leaders = (agent_ids == cfg.leader_idx).astype(dtype)

    P, q0, A, lb, ub, shift = jax.vmap(
        lambda oh, ld, cbf: _build_agent_qp(
            params, cfg, f_eq, state, acc_des, cbf, oh, ld, rho
        )
    )(onehots, leaders, env_cbfs)

    n_box = 13 + cfg.n_env_cbfs
    m = n_box + 8
    rho_vec = jax.vmap(
        lambda lb_, ub_: socp.make_rho_vec(m, n_box, lb_, ub_, 0.4, dtype)
    )(lb, ub)
    op = socp.kkt_operator(P, A, rho_vec)

    def make_solve(iters):
        return jax.vmap(
            lambda P_, q_, A_, lb_, ub_, shift_, op_, warm_: socp.solve_socp(
                P_, q_, A_, lb_, ub_,
                n_box=n_box, soc_dims=(4, 4), iters=iters,
                warm=warm_, shift=shift_, op=op_,
            )
        )

    solve_cold = make_solve(cfg.inner_iters)
    warm_iters = cfg.inner_iters_warm or cfg.inner_iters
    two_phase = warm_iters != cfg.inner_iters
    solve_warm = make_solve(warm_iters) if two_phase else solve_cold

    def consensus_iter(solve_one, carry):
        f, lam, f_mean, warm, it, res, err_buf = carry
        # Primal: augmented linear term <lam_i, f> - rho <f_mean, f>.
        q_extra = (lam - rho * f_mean[None, :, :]).reshape(n_local, 3 * n)
        q = q0.at[:, 9:].add(q_extra)
        sols = solve_one(P, q, A, lb, ub, shift, op, warm)
        f_new = sols.x[:, 9:].reshape(n_local, n, 3)
        # Failed agents fall back to equilibrium forces (reference :491-494).
        ok = (sols.prim_res < cfg.solver_tol)[:, None, None] & jnp.all(
            jnp.isfinite(f_new), axis=(1, 2), keepdims=True
        )
        f_new = jnp.where(ok, f_new, f_eq[None, :, :])
        # Failed agents also keep their previous warm start (a NaN iterate would
        # poison every later solve; cvxpy in the reference re-solves fresh).
        ok_flat = ok[:, 0, 0]
        sols = jax.tree.map(
            lambda new, old: jnp.where(
                ok_flat.reshape((n_local,) + (1,) * (new.ndim - 1)), new, old
            ),
            sols, warm,
        )
        # Consensus all-reduce: mean + inf-norm residual (psum/pmax over the
        # mesh axis when agents are sharded).
        f_mean_new = _mean_over_agents(f_new)
        res_new = _max_over_agents(jnp.abs(f_new - f_mean_new[None, :, :]))
        err_buf = err_buf.at[it].set(res_new)
        it = it + 1
        # Dual update. Deliberate deviation from the reference: the reference
        # breaks out of its loop *before* updating lambda on the converged
        # iteration (:661-665); here the update runs unconditionally, so the
        # warm-started duals for the NEXT control step include one extra
        # rho*(f - f_mean) term, bounded by rho*res_tol — it only perturbs warm
        # starts, never the applied forces (and err_seq gains the final
        # converged residual the reference omits).
        lam_new = lam + rho * (f_new - f_mean_new[None, :, :])
        return f_new, lam_new, f_mean_new, sols, it, res_new, err_buf

    def cond(carry):
        *_, it, res, _buf = carry
        return (res >= cfg.res_tol) & (it <= cfg.max_iter)

    err_buf0 = jnp.full((cfg.max_iter + 1,), jnp.nan, dtype)
    init = (
        admm_state.f, admm_state.lam, admm_state.f_mean, admm_state.warm,
        jnp.zeros((), jnp.int32), jnp.asarray(jnp.inf, dtype), err_buf0,
    )
    if not two_phase:
        carry = init
    else:
        # Two-phase budget: the first consensus iteration always runs (res
        # starts at inf), so peel it with the cold solver budget; the loop
        # body then uses the warm budget — its warm start is THIS step's
        # previous iterate, far closer than the cross-step start iteration 1
        # sees. (A lax.cond on the iteration index would NOT work: under
        # vmap it becomes a select that executes both solver branches for
        # every lane.)
        carry = consensus_iter(solve_cold, init)
    f, lam, f_mean, warm, iters, res, err_buf = lax.while_loop(
        cond, lambda c: consensus_iter(solve_warm, c), carry
    )

    # Applied forces: agent i applies its own column (reference :669-675).
    f_app = f[jnp.arange(n_local), agent_ids, :]
    new_state = CADMMState(f=f, lam=lam, f_mean=f_mean, warm=warm)
    collision = _max_over_agents(env_cbfs.collision.astype(jnp.int32)) > 0
    stats = SolverStats(
        iters=iters,
        solve_res=res,
        collision=collision,
        min_env_dist=_min_over_agents(env_cbfs.min_dist),
        err_seq=err_buf,
    )
    return f_app, new_state, stats
