"""Consensus-ADMM distributed controller for the RQP model.

TPU-native re-design of reference ``control/rqp_cadmm.py``: ``n`` agents each hold
a full local copy ``f^(i) in R^{n x 3}`` of all forces (global-consensus ADMM).
Per outer iteration (reference ``control``, :631-675):

  1. each agent solves its primal SOCP (cost docstring :27-46) with augmented
     objective ``<lambda_i, f> + (rho/2)||f - f_mean||^2``; only the agent's own
     force column carries actuation constraints (:394-404);
  2. consensus mean ``f_mean = (1/n) sum_i f^(i)`` and inf-norm residual
     ``max_i ||f^(i) - f_mean||_inf`` — the logical all-reduce (:582-625);
  3. stop when residual < ``res_tol`` (1e-2 N) or iteration cap; else dual update
     ``lambda_i += rho (f^(i) - f_mean)`` (:627-629).

TPU mapping (SURVEY.md §2.10): the reference's sequential per-agent loop becomes a
``vmap`` over the agent axis (one fused kernel for all n primal SOCPs); the
consensus mean/max are ``jnp`` reductions on-chip (and ``lax.psum``/``pmax`` over a
mesh axis in the ``parallel`` layer). The rho schedule
``rho_{k+1} = min(rho_k tau_incr, rho_max)`` (:565-567, :657) visits a small
static set of values (one, at the reference default tau_incr = 1), so every
agent's KKT operator is precomputed per distinct rho once per control step and
selected per iteration — only the linear term moves between iterations.

For n >= 4 each agent's per-iteration QP is Schur-reduced to a constant 12
variables (see :class:`SchurQP`): the other agents' force columns carry no
constraints of their own and are eliminated by exact partial minimization,
then reconstructed in closed form for the consensus step — the per-agent
solve cost is O(1) in n instead of O((9+3n)^2).

All controller state (local copies, duals, means, per-agent warm starts) persists
across control steps in :class:`CADMMState`, matching the reference's warm-start
behavior (:576-580 and cvxpy ``warm_start=True``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from tpu_aerial_transport.control.types import EnvCBF, SolverStats, inactive_env_cbf
from tpu_aerial_transport.envs import forest as forest_mod
from tpu_aerial_transport.envs import spatial as spatial_mod
from tpu_aerial_transport.harness.bucketing import bucket_dim as _bucket_dim
from tpu_aerial_transport.models.rqp import GRAVITY, RQPParams, RQPState
from tpu_aerial_transport.obs import phases
from tpu_aerial_transport.ops import lie, socp
from tpu_aerial_transport.parallel import ring
from tpu_aerial_transport.control.centralized import (
    equilibrium_forces,
    smooth_block,
)


@struct.dataclass
class RQPCADMMConfig:
    """Constants from reference ``_set_controller_constants`` (:192-236, :556-567).
    Note the distributed deltas vs centralized: ``k_f, k_m`` scaled by 1/n,
    ``alpha_env_cbf = 1.5``, per-agent vision cone of half-angle 100 deg."""

    min_fz: float
    sec_max_f_ang: float
    max_f: float
    cos_max_p_ang: float
    alpha1_p_cbf: float
    alpha2_p_cbf: float
    max_wl_sq: float
    alpha_wl_cbf: float
    max_vl_sq: float
    alpha_vl_cbf: float
    dist_eps: float
    vision_radius: float
    alpha_env_cbf: float
    max_deceleration: float
    vision_cone_ang: float
    k_f: float  # already divided by n.
    k_m: float
    k_feq: float
    k_dvl: float
    k_dwl: float
    # ADMM penalty schedule (reference rqp_cadmm.py:565-567, :657):
    # rho_{k+1} = min(rho_k * tau_incr, rho_max), constant by default
    # (tau_incr = 1). STATIC fields: the set of distinct rho values the capped
    # schedule can visit must be concrete at trace time — per-agent KKT
    # operators are precomputed for each distinct value and selected per
    # consensus iteration (memory scales with that count, so keep tau_incr
    # coarse; the reference default visits exactly one value).
    rho0: float = struct.field(pytree_node=False, default=1.0)
    tau_incr: float = struct.field(pytree_node=False, default=1.0)
    rho_max: float = struct.field(pytree_node=False, default=2.0)
    res_tol: float = 1e-2
    # Dynamic leader index (reference static index 0, rqp_cadmm.py:556-558,
    # with runtime set_leader/unset_leader hooks :503-507). A pytree LEAF, not
    # a static field, so a leader change mid-rollout (via :func:`set_leader`)
    # re-uses the compiled step; -1 means no leader (no agent carries the
    # tracking cost).
    leader_idx: int = 0
    # Optional force-smoothing cost on the agent's OWN column (reference
    # rqp_cadmm.py:455-462 / rqp_dd.py:451-457, default 0 with the in-code
    # note "Controller is more stable without smoothing"):
    #   k_smooth ||(R_i exp3(w_i dt))[:, :2]^T f_i||^2.
    k_smooth: float = 0.0
    dt: float = 1e-3  # smoothing-axis prediction horizon (reference :287-293).
    # Static fields.
    n_env_cbfs: int = struct.field(pytree_node=False, default=10)
    max_iter: int = struct.field(pytree_node=False, default=100)
    inner_iters: int = struct.field(pytree_node=False, default=60)
    # Per-agent QP formulation: None = auto (Schur-reduced constant-size QP
    # for n >= 4, full (9+3n)-var QP otherwise), True/False forces. The
    # reduction eliminates the other agents' force columns — which carry no
    # constraints of their own (reference rqp_cadmm.py:394-404; they enter
    # only the dynamics equalities and quadratic costs) — by exact partial
    # minimization, leaving a 12-var QP per agent regardless of n. n = 3 is
    # excluded: its 6x6 coupling block E_v is built from hat(r_j - r_k)
    # pairs and is singular, so the elimination needs the full path there.
    reduced_qp: bool | None = struct.field(pytree_node=False, default=None)
    # Inner ADMM budget for consensus iterations >= 2, whose warm start is the
    # SAME control step's previous iterate (far closer than the cross-step
    # warm start the first iteration sees). 0 = use ``inner_iters``.
    inner_iters_warm: int = struct.field(pytree_node=False, default=0)
    solver_tol: float = struct.field(pytree_node=False, default=5e-3)
    # Consensus iterations may continue past residual convergence while any
    # agent's solve still fails tolerance (retries accumulate inner
    # progress through the kept warm starts — without this, a hard agent
    # QP falls back to equilibrium forces every step and e.g. an active
    # near-contact obstacle row is never enforced; measured: the n=8
    # forest soak punches through trees). The default bound is SMALL
    # because warm starts also persist across CONTROL steps, so a stuck
    # agent still accumulates retry progress step over step (measured: the
    # soak stays collision-free at 4), while an unbounded gate drags every
    # lane of a vmapped batch to the worst lane's cap (measured: 4x on the
    # batched headline). 0 = retries up to max_iter.
    solve_retry_iters: int = struct.field(pytree_node=False, default=4)
    max_f_ang: float = struct.field(pytree_node=False, default=jnp.pi / 6)
    # Inner-chunk execution mode forwarded to ops/socp.py solve_socp
    # ("auto" | "scan" | "pallas" | "interpret" | "kernel" |
    # "kernel_interpret"): "pallas" runs each fixed-iteration ADMM chunk as
    # one fused TPU kernel with the per-agent operators VMEM-resident
    # (ops/admm_kernel.py); "kernel" runs the WHOLE inner solve — w2
    # build + every iteration + exit residuals — as one mega-kernel
    # (admm_kernel.fused_solve_lanes; downgrades to scan off-TPU at trace
    # time, so the same config serves CPU fallbacks). The sharded mesh,
    # pods, and serving tiers inherit whichever mode this field holds with
    # zero extra plumbing — it rides the config into every solve_socp call.
    socp_fused: str = struct.field(pytree_node=False, default="auto")
    # Operator storage precision on the "kernel" fused paths ("f32" |
    # "bf16" = bf16-storage / f32-accumulation of the per-agent KKT
    # operators — halves the kernel's HBM payload). Resolved at config
    # build time (socp.resolve_precision; "auto" -> f32 until the chip
    # round's *_fused_kernel_bf16 A/B cells pass the consensus-residual
    # parity bar). Inert off the kernel paths — the scan program is
    # bit-identical under either value (asserted).
    socp_precision: str = struct.field(pytree_node=False, default="f32")
    # Tolerance-chunked inner solves: when inner_tol > 0, each agent QP runs
    # its ADMM iterations in chunks of ``inner_check_every`` and stops as
    # soon as primal AND dual residuals drop below ``inner_tol`` (ops/socp.py
    # check_every/tol path), still capped at ``inner_iters``. Warm-started
    # steady-state solves typically converge well before the fixed budget;
    # inside a vmapped batch the saving realizes once every lane of the
    # batched program is converged (while_loop batching semantics). 0 = off
    # (fixed-iteration solves, bit-identical to the historical path).
    inner_tol: float = struct.field(pytree_node=False, default=0.0)
    inner_check_every: int = struct.field(pytree_node=False, default=10)
    # Consensus-level solver effort (ops/socp.py resolve_effort; "fixed" |
    # "adaptive"). "adaptive" runs the inner solves tolerance-chunked with
    # per-lane early exit (in-kernel on the fused "kernel" paths — one
    # pallas_call per solve, operators read from HBM once) and threads the
    # consensus loop's own per-scenario converged state into them, so a
    # converged lane inside a vmapped batch stops paying full-budget
    # re-solves while the loop drains stragglers; per-step effort lands on
    # SolverStats.inner_iters for the telemetry histograms. "fixed" (the
    # resolved default) stages NOTHING — byte-identical HLO to a pre-knob
    # config (asserted in tests/test_effort.py). The make_config default
    # is resolved at config build time ("auto" -> TAT_EFFORT env, else
    # fixed); this field always holds the RESOLVED name.
    effort: str = struct.field(pytree_node=False, default="fixed")
    # Tile-aligned operator layout (ops/socp.py padded tier): pad every
    # per-agent QP edge — variables and constraint rows — to the next
    # SUBLANE_TILE (8) multiple and run the inner ADMM on the padded
    # operators (the 128-lane axis comes from the folded agent x scenario
    # batch). Exact: pad rows are free, pad variables rest at exactly 0
    # (socp.pad_qp docstring). The make_config default is backend-resolved
    # ("auto" -> False on CPU, True elsewhere — tile padding is layout
    # prep for the TPU (8, 128) tile; see socp.resolve_pad_operators);
    # this field always holds the RESOLVED bool. False is also the
    # bench's padded-vs-unpadded A/B switch.
    pad_operators: bool = struct.field(pytree_node=False, default=True)
    # Per-agent solve-health telemetry (obs.telemetry track_agents): when
    # True, SolverStats.agent_solve_res carries every agent's exit-time QP
    # residual (all_gathered to the full (n,) table under shard_map).
    # STATIC and default-off: the nominal program is bit-identical.
    track_agent_stats: bool = struct.field(pytree_node=False, default=False)
    # Consensus-exchange implementation under shard_map (parallel/ring.py:
    # "allreduce" = global psum/pmax barriers, "ring" = ppermute
    # reduce-scatter/all-gather hops, "pallas_ring" = async remote-DMA TPU
    # kernel overlapping the transfer with the local solve). The
    # make_config default is backend-resolved ("auto" -> allreduce on CPU,
    # ring on tiled backends — ring.resolve_consensus, incl. the
    # TPU_AERIAL_CONSENSUS env override); this field always holds the
    # RESOLVED name. Single-program (axis_name=None) steps never exchange,
    # so the field is inert there.
    consensus_impl: str = struct.field(pytree_node=False, default="allreduce")
    # Environment-query implementation (envs/spatial.py
    # resolve_env_query; "auto" | "dense" | "bucketed"). "dense" (the
    # resolved small-world default) is the historical O(max_trees) sweep
    # — byte-identical HLO to a pre-knob config (asserted in
    # tests/test_spatial.py). "bucketed" gathers the forest's
    # spatial-hash candidate slab (forest.grid, spatial.with_grid) and
    # runs the same per-tree math over candidates only — bitwise-equal
    # EnvCBF rows, O(K) instead of O(max_trees), which is what admits
    # 10^4-10^6-obstacle city-scale worlds. "auto" (stored as-is; env
    # force resolved at make_config time) finishes resolving at TRACE
    # time from the forest's static slot count (spatial.
    # runtime_env_query: dense at <= DENSE_AUTO_MAX_TREES, bucketed
    # above) — the world's size is a shape, unknown at config build.
    # The mesh, pods, and serving tiers inherit the mode with zero
    # plumbing — it rides this config into every query.
    env_query: str = struct.field(pytree_node=False, default="dense")


def make_config(
    params: RQPParams,
    collision_radius: float,
    max_deceleration: float,
    n_env_cbfs: int = 10,
    max_iter: int = 100,
    inner_iters: int = 60,
    res_tol: float = 1e-2,
    inner_iters_warm: int = 0,
    reduced_qp: bool | None = None,
    k_smooth: float = 0.0,
    dt: float = 1e-3,
    rho0: float = 1.0,
    tau_incr: float = 1.0,
    rho_max: float = 2.0,
    socp_fused: str = "auto",
    socp_precision: str = "auto",
    inner_tol: float = 0.0,
    inner_check_every: int = 10,
    solve_retry_iters: int = 4,
    pad_operators: bool | None = None,
    track_agent_stats: bool = False,
    consensus_impl: str = "auto",
    effort: str = "auto",
    env_query: str = "auto",
) -> RQPCADMMConfig:
    """Defaults are reference-conservative (max_iter mirrors the reference's
    100-iteration cap). For warm-started receding-horizon use, the measured
    inner-iteration knee is ~20 (below it the agent solves miss ``solver_tol``
    and trip the equilibrium fallback; at 20 forces match an inner=80 solve to
    < 1e-4 N) — see bench.py / BASELINE.md.

    **k_smooth x row-equilibration interaction** (measured,
    tests/test_ksmooth.py:75): with ``k_smooth > 0`` the smoothing cost adds
    a ~100:1 anisotropy to the force block of P. The UNequilibrated builders'
    large equality-row norms used to act as an accidental preconditioner for
    exactly that corner (A^T rho A dominated the anisotropy); with exact
    row equilibration (unit-norm rows — cheaper for every production-path
    QP) the same smoothed QP needs ~300 inner iterations to ``solver_tol``
    instead of ~80. Budget accordingly: keep the default
    ``inner_iters``/knee (~20) only while ``k_smooth == 0`` (the reference
    default); when enabling smoothing, raise ``inner_iters`` to >= 300 (or
    set ``inner_tol > 0`` so converged solves exit early and only the
    smoothed corner pays the deep budget)."""
    n = params.n
    mTg = float(params.mT) * GRAVITY
    return RQPCADMMConfig(
        min_fz=mTg / (n * 10.0),
        sec_max_f_ang=float(1.0 / jnp.cos(jnp.pi / 6.0)),
        max_f=2.0 * mTg / n,
        cos_max_p_ang=float(jnp.cos(jnp.pi / 12.0)),
        alpha1_p_cbf=1.0,
        alpha2_p_cbf=1.0,
        max_wl_sq=float((jnp.pi / 6.0) ** 2),
        alpha_wl_cbf=1.0,
        max_vl_sq=1.0,
        alpha_vl_cbf=1.0,
        dist_eps=0.1,
        vision_radius=collision_radius + 5.0,
        alpha_env_cbf=1.5,
        max_deceleration=max_deceleration,
        vision_cone_ang=float(100.0 * jnp.pi / 180.0),
        k_f=0.1 / n,
        k_m=0.1 / n,
        k_feq=0.1,
        k_dvl=1.0,
        k_dwl=1.0,
        rho0=rho0,
        tau_incr=tau_incr,
        rho_max=rho_max,
        res_tol=res_tol,
        k_smooth=k_smooth,
        dt=dt,
        n_env_cbfs=n_env_cbfs,
        max_iter=max_iter,
        inner_iters=inner_iters,
        inner_iters_warm=inner_iters_warm,
        reduced_qp=reduced_qp,
        # Resolved here (config build time, outside jit) so the mode is an
        # explicit static field rather than a trace-time backend probe.
        socp_fused=socp.resolve_fused(socp_fused),
        # "auto" resolved here too (socp.resolve_precision: env force,
        # else f32 until the chip-round bf16 parity bars pass).
        socp_precision=socp.resolve_precision(socp_precision),
        inner_tol=inner_tol,
        inner_check_every=inner_check_every,
        solve_retry_iters=solve_retry_iters,
        # None = "auto", resolved here (config build time, outside jit)
        # like socp_fused above: tile-padded on tiled backends, raw on CPU.
        pad_operators=socp.resolve_pad_operators(pad_operators),
        track_agent_stats=track_agent_stats,
        # "auto" resolved here (config build time, outside jit) like
        # socp_fused/pad_operators above: allreduce on CPU, ring on tiled
        # backends (parallel/ring.py resolve_consensus).
        consensus_impl=ring.resolve_consensus(consensus_impl),
        # "auto" resolved here too (socp.resolve_effort: TAT_EFFORT env
        # force, else "fixed" until the chip round's effort A/B cells
        # pass the flip criterion written in its docstring).
        effort=socp.resolve_effort(effort),
        # The TAT_ENV_QUERY env force is consumed here (config build
        # time, outside jit, like every knob above), but "auto" may
        # survive: the dense/bucketed split depends on the WORLD's
        # static slot count, first known at trace time
        # (spatial.runtime_env_query finishes it in agent_env_cbfs_for).
        env_query=spatial_mod.resolve_env_query(env_query),
    )


def _use_reduced(cfg: RQPCADMMConfig, n: int) -> bool:
    """Static (trace-time) decision for the per-agent QP formulation."""
    return cfg.reduced_qp if cfg.reduced_qp is not None else n >= 4


def _rho_schedule(cfg: RQPCADMMConfig) -> list[float]:
    """The distinct rho values ``rho_k = min(rho0 tau_incr^k, rho_max)`` can
    visit before saturating (reference rqp_cadmm.py:657) — a concrete Python
    list (rho0/tau_incr/rho_max are static fields), length 1 when tau_incr
    <= 1 (the reference default: constant rho)."""
    if cfg.tau_incr < 1.0:
        raise ValueError(
            f"tau_incr={cfg.tau_incr} < 1: the reference schedule only ever "
            "increases rho toward rho_max (rqp_cadmm.py:657); a decaying "
            "schedule is not supported"
        )
    rhos = [float(cfg.rho0)]
    if cfg.tau_incr > 1.0:
        while rhos[-1] < cfg.rho_max and len(rhos) <= cfg.max_iter:
            rhos.append(min(rhos[-1] * cfg.tau_incr, cfg.rho_max))
    return rhos


def set_leader(cfg, leader_idx):
    """Runtime leader change (reference ``set_leader``, rqp_cadmm.py:503-505 /
    rqp_dd.py:507-509): agent ``leader_idx`` alone carries the tracking cost.
    ``leader_idx`` is a dynamic pytree leaf, so the returned config re-uses any
    compiled control step — usable mid-rollout (even traced, via
    ``cfg.replace(leader_idx=...)`` inside a scan). Works on both
    :class:`RQPCADMMConfig` and the DD config (pass ``cfg.base``-level
    replace for that, or use the same helper on the wrapper)."""
    if hasattr(cfg, "base"):  # RQPDDConfig wraps the shared base config.
        return cfg.replace(base=cfg.base.replace(leader_idx=leader_idx))
    return cfg.replace(leader_idx=leader_idx)


def unset_leader(cfg):
    """No agent carries the tracking cost (reference ``unset_leader``,
    rqp_cadmm.py:506-507): the team holds formation/equilibrium only."""
    return set_leader(cfg, -1)


def set_tolerance(cfg, res_tol: float):
    """Runtime consensus-tolerance setter (reference ``set_tolerance``,
    rqp_cadmm.py:677-682 / rqp_dd.py:754-759). Dynamic leaf — no recompile."""
    if hasattr(cfg, "base"):
        return cfg.replace(
            base=cfg.base.replace(res_tol=res_tol), prim_inf_tol=res_tol
        )
    return cfg.replace(res_tol=res_tol)


def set_max_iter(cfg, max_iter: int):
    """Runtime iteration-cap setter (reference ``set_max_iterations``,
    rqp_cadmm.py:683-688 / rqp_dd.py:760-764). ``max_iter`` sizes the fixed
    ``err_seq`` buffer, so it is a STATIC field: changing it recompiles the
    step (the reference equivalent re-allocates its Python-side buffers)."""
    if hasattr(cfg, "base"):
        return cfg.replace(base=cfg.base.replace(max_iter=max_iter))
    return cfg.replace(max_iter=max_iter)


@struct.dataclass
class CADMMState:
    """Distributed-solver state carried across control steps (reference
    ``_set_variables`` + ``_set_warm_start``, :569-580)."""

    f: jnp.ndarray  # (n, n, 3): f[i, j] = agent i's copy of all forces.
    lam: jnp.ndarray  # (n, n, 3) duals.
    f_mean: jnp.ndarray  # (n, 3) consensus mean.
    warm: socp.SOCPSolution  # leading agent axis on every leaf.
    # Last DELIVERED copy per agent (resilience layer only; None in nominal
    # use so the nominal pytree/HLO are unchanged): under consensus-message
    # dropout the peers keep consuming this snapshot — frozen at the end of
    # the agent's last delivered step — for the whole dropout window,
    # instead of a merely one-step-delayed view of its undelivered
    # iterates. Initialized by the resilience rollout adapters
    # (prepare_ctrl_state); a direct ``control(health=...)`` call with
    # ``held=None`` falls back to ``f`` (correct at the first step).
    held: jnp.ndarray | None = None


def _qp_dims(cfg: RQPCADMMConfig, n: int):
    """Static per-agent QP dims for this (cfg, n): ``(nv, n_box, nv_p,
    n_box_p, m_p)``. The ``_p`` values are the tile bucket the solve runs in
    (ops/socp.py ``padded_dims``); with ``pad_operators=False`` they equal
    the raw dims. The cone layout is always [box | 2 x SOC(4)]."""
    reduced = _use_reduced(cfg, n)  # static (trace-time) formulation choice.
    if reduced:
        nv, n_box = 12, 7 + cfg.n_env_cbfs
    else:
        nv, n_box = 9 + 3 * n, 13 + cfg.n_env_cbfs
    if cfg.pad_operators:
        nv_p, n_box_p = socp.padded_dims(nv, n_box, (4, 4))
    else:
        nv_p, n_box_p = nv, n_box
    return nv, n_box, nv_p, n_box_p, n_box_p + 8


def init_cadmm_state(params: RQPParams, cfg: RQPCADMMConfig) -> CADMMState:
    n = params.n
    f_eq = equilibrium_forces(params)
    dtype = f_eq.dtype
    nv, _, nv_p, _, m_p = _qp_dims(cfg, n)
    if _use_reduced(cfg, n):
        # Reduced per-agent QP: [dv_com | dvl | dwl | own force] (12 vars).
        x0 = jnp.concatenate(
            [jnp.tile(jnp.zeros(9, dtype), (n, 1)), f_eq], axis=1
        )
    else:
        x0 = jnp.tile(
            jnp.concatenate([jnp.zeros(9, dtype), f_eq.reshape(-1)]), (n, 1)
        )
    # Warm starts live in the (possibly padded) solve layout; pad entries
    # start — and stay — at exactly 0 (socp.pad_qp docstring).
    warm = socp.SOCPSolution(
        x=jnp.pad(x0, ((0, 0), (0, nv_p - nv))),
        y=jnp.zeros((n, m_p), dtype),
        z=jnp.zeros((n, m_p), dtype),
        prim_res=jnp.zeros((n,), dtype),
        dual_res=jnp.zeros((n,), dtype),
    )
    return CADMMState(
        f=jnp.tile(f_eq, (n, 1, 1)),
        lam=jnp.zeros((n, n, 3), dtype),
        f_mean=f_eq,
        warm=warm,
    )


def _build_agent_qp(
    params: RQPParams,
    cfg: RQPCADMMConfig,
    f_eq: jnp.ndarray,
    state: RQPState,
    acc_des,
    env_cbf: EnvCBF,
    onehot: jnp.ndarray,
    is_leader: jnp.ndarray,
    rho,
):
    """Per-agent primal QP matrices (vmapped over ``onehot``/``is_leader``/CBF).

    Variable layout matches the centralized controller: [dv_com | dvl | dwl | f].
    Box rows: [dyn-trans 3 | dyn-rot 3 | kin 3 | own fz 1 | tilt 1 | wl 1 | vl 1 |
    env k]; SOC: own thrust cone + own norm cap. The consensus-ADMM quadratic
    ``(rho/2)||f||^2`` is baked into P (rho is constant within a control step);
    the iteration-varying linear term ``lambda - rho f_mean`` is added by the
    caller per consensus iteration.
    """
    n = params.n
    dtype = state.xl.dtype
    nv = 9 + 3 * n
    dvl_des, dwl_des = acc_des
    e3 = jnp.array([0.0, 0.0, 1.0], dtype=dtype)
    Rl = state.Rl

    P = jnp.zeros((nv, nv), dtype)
    q = jnp.zeros((nv,), dtype)
    k_dvl = cfg.k_dvl * is_leader
    k_dwl = cfg.k_dwl * is_leader
    P = P.at[3:6, 3:6].add(2.0 * k_dvl * jnp.eye(3, dtype=dtype))
    q = q.at[3:6].add(-2.0 * k_dvl * dvl_des)
    P = P.at[6:9, 6:9].add(2.0 * k_dwl * jnp.eye(3, dtype=dtype))
    q = q.at[6:9].add(-2.0 * k_dwl * dwl_des)

    S = jnp.tile(jnp.eye(3, dtype=dtype), (1, n))
    G = jnp.concatenate(
        [lie.hat(params.r_com[i]) @ Rl.T for i in range(n)], axis=1
    )
    own = jnp.repeat(onehot, 3)  # (3n,) mask of the agent's own force block.
    Pff = (
        2.0 * cfg.k_f * (S.T @ S)
        + 2.0 * cfg.k_m * (G.T @ G)
        + 2.0 * cfg.k_feq * jnp.diag(own)
        + rho * jnp.eye(3 * n, dtype=dtype)  # (rho/2)||f||^2.
    )
    # Own-column force-smoothing cost (reference :455-462, default 0).
    R_i = jnp.einsum("n,nij->ij", onehot, state.R)
    w_i = jnp.einsum("n,ni->i", onehot, state.w)
    Pff = Pff + jnp.kron(jnp.diag(onehot), smooth_block(cfg, R_i, w_i))
    P = P.at[9:, 9:].add(Pff)
    q = q.at[9:].add(
        -2.0 * cfg.k_f * (S.T @ (params.mT * GRAVITY * e3))
        - 2.0 * cfg.k_feq * own * f_eq.reshape(-1)
    )

    n_box = 13 + cfg.n_env_cbfs
    A = jnp.zeros((n_box, nv), dtype)
    lb = jnp.zeros((n_box,), dtype)
    ub = jnp.zeros((n_box,), dtype)

    A = A.at[0:3, 0:3].set(params.mT * jnp.eye(3, dtype=dtype))
    A = A.at[0:3, 9:].set(-S)
    rhs = -params.mT * GRAVITY * e3
    lb = lb.at[0:3].set(rhs)
    ub = ub.at[0:3].set(rhs)

    A = A.at[3:6, 6:9].set(jnp.eye(3, dtype=dtype))
    A = A.at[3:6, 9:].set(-params.JT_inv @ G)
    rot_rhs = -params.JT_inv @ jnp.cross(state.wl, params.JT @ state.wl)
    lb = lb.at[3:6].set(rot_rhs)
    ub = ub.at[3:6].set(rot_rhs)

    R_w_hat = Rl @ lie.hat(state.wl)
    R_w_hat_sq = Rl @ lie.hat_square(state.wl, state.wl)
    A = A.at[6:9, 0:3].set(-jnp.eye(3, dtype=dtype))
    A = A.at[6:9, 3:6].set(jnp.eye(3, dtype=dtype))
    A = A.at[6:9, 6:9].set(-Rl @ lie.hat(params.x_com))
    kin_rhs = -R_w_hat_sq @ params.x_com
    lb = lb.at[6:9].set(kin_rhs)
    ub = ub.at[6:9].set(kin_rhs)

    # Own-column f_z lower bound (row 9): one-hot selects the agent's column.
    fz_row = jnp.kron(onehot, e3)  # (3n,)
    A = A.at[9, 9:].set(fz_row)
    lb = lb.at[9].set(cfg.min_fz)
    ub = ub.at[9].set(socp.INF)

    A = A.at[10, 6:9].set(-(Rl[2] @ lie.hat(e3)))
    tilt_rhs = (
        -R_w_hat_sq[2, 2]
        - (cfg.alpha1_p_cbf + cfg.alpha2_p_cbf) * R_w_hat[2, 2]
        - cfg.alpha1_p_cbf * cfg.alpha2_p_cbf * (Rl[2, 2] - cfg.cos_max_p_ang)
    )
    lb = lb.at[10].set(tilt_rhs)
    ub = ub.at[10].set(socp.INF)

    A = A.at[11, 6:9].set(-2.0 * state.wl)
    lb = lb.at[11].set(
        -cfg.alpha_wl_cbf * (cfg.max_wl_sq - jnp.dot(state.wl, state.wl))
    )
    ub = ub.at[11].set(socp.INF)

    A = A.at[12, 3:6].set(-2.0 * state.vl)
    lb = lb.at[12].set(
        -cfg.alpha_vl_cbf * (cfg.max_vl_sq - jnp.dot(state.vl, state.vl))
    )
    ub = ub.at[12].set(socp.INF)

    A = A.at[13 : 13 + cfg.n_env_cbfs, 3:6].set(env_cbf.lhs)
    lb = lb.at[13 : 13 + cfg.n_env_cbfs].set(env_cbf.rhs)
    ub = ub.at[13 : 13 + cfg.n_env_cbfs].set(socp.INF)

    # SOC rows: own thrust cone [sec30 fz; f_own], own norm cap [max_f; f_own].
    soc = jnp.zeros((8, nv), dtype)
    shift_soc = jnp.zeros((8,), dtype)
    own_block = jnp.kron(onehot, jnp.eye(3, dtype=dtype))  # (3, 3n)
    soc = soc.at[0, 9:].set(cfg.sec_max_f_ang * fz_row)
    soc = soc.at[1:4, 9:].set(own_block)
    shift_soc = shift_soc.at[4].set(cfg.max_f)
    soc = soc.at[5:8, 9:].set(own_block)

    A_full = jnp.concatenate([A, soc], axis=0)
    shift = jnp.concatenate([jnp.zeros((n_box,), dtype), shift_soc])
    # Exact row/block equilibration (socp.equilibrate_rows): rotation
    # dynamics rows carry JT_inv-scale entries against O(m) translation
    # rows; unit-norm rows cut the f32 ADMM iteration count severalfold.
    A_full, lb, ub, shift, _ = socp.equilibrate_rows(
        A_full, lb, ub, shift, n_box, (4, 4)
    )
    return P, q, A_full, lb, ub, shift


class SchurPlan(NamedTuple):
    """State-INDEPENDENT Schur-elimination cores for the reduced per-agent
    QP, in the payload-frame force parametrization ``f_j = Rl ft_j``.

    Derivation: split the full per-agent variables into z = (c, u) with
    c = [dv_com, dvl, dwl], u = the agent's own force column (world frame),
    and v = the other n-1 force columns. v carries no constraints of its own
    (reference rqp_cadmm.py:394-404): it appears only in the 6 coupling
    equalities (translational + rotational dynamics) and the quadratic costs,
    so partial minimization over v subject to those equalities is exact and
    closed-form, leaving a reduced 12-var QP in z whose Hessian is the Schur
    complement (validated numerically against an SLSQP solve of the full
    problem). With L = Q_vv^-1, Y = E_v L E_v^T, J = L E_v^T Y^-1,
    N = L - J E_v L:

        H_cc = P_cc + E_cc^T Y^-1 E_cc
        H_uu = Q_uu - Q_uv N Q_uv^T + E_u^T Y^-1 E_u - 2 sym(Q_uv J E_u)
        H_cu = E_cc^T Y^-1 E_u - E_cc^T J^T Q_uv^T
        q_c  = q_c0 - E_cc^T J^T q_v - E_cc^T Y^-1 e0
        q_u  = q_u0 - (Q_uv N + E_u^T J^T) q_v + Q_uv J e0 - E_u^T Y^-1 e0
        v*   = -N (q_v + Q_uv^T u) + J (e0 - E_cc c - E_u u)

    The payload-frame twist is what makes this TPU-cheap: expressing the
    eliminated columns in the payload frame (``v = (I kron Rl) vt``) and
    pre-rotating the translational equality rows by Rl^T makes Q_vv, E_v
    orthogonally invariant — every expensive core (the (3(n-1))^2 inverse
    behind L, N, J) depends ONLY on (params, rho) and is computed here ONCE,
    outside the rollout. Per control step the state enters only through
    Rl-conjugations of 3x3/6x9 blocks and a handful of big-matrix matvecs;
    without this, n batched (3(n-1))^2 inversions ran every step (~13 ms of
    the ~14 ms n=64 step).

    Leaf axes: (n_rho, n_local, ...) — rho-schedule axis first, agents second.
    """

    J: jnp.ndarray      # (.., V, 6)   V = 3(n-1)
    N: jnp.ndarray      # (.., V, V)
    Yinv: jnp.ndarray   # (.., 6, 6)
    Eu: jnp.ndarray     # (.., 6, 3)   scaled E~_u core: E~_u = Eu @ Rl^T.
    Mu: jnp.ndarray     # (.., 3, V)   C N + Eu^T J^T (per-iteration q_u map).
    NCt: jnp.ndarray    # (.., V, 3)   N C^T (reconstruction).
    Nsum: jnp.ndarray   # (.., V, 3)   sum of N's 3-col blocks (q_v0 folding).
    Jsum: jnp.ndarray   # (.., 3, 6)   sum of J's 3-row blocks.
    Musum: jnp.ndarray  # (.., 3, 3)   C Nsum + Eu^T Jsum^T.
    CJ: jnp.ndarray     # (.., 3, 6)   C J.
    YinvEu: jnp.ndarray  # (.., 6, 3)  Yinv Eu.
    UUcore: jnp.ndarray  # (.., 3, 3)  Eu^T Yinv Eu - C N C^T - 2 sym(C J Eu)
    #                                  + 2 k_m hat(r_u)^T hat(r_u).
    CUcore: jnp.ndarray  # (.., 6, 3)  Yinv Eu - J^T C^T.
    perm: jnp.ndarray   # (.., n) int32: [own agent, others...] column order.
    inv_perm: jnp.ndarray  # (.., n) int32 argsort of perm — precomputed so
    #                        the consensus loop body carries no per-iteration
    #                        sort of a plan-static permutation.
    scale: jnp.ndarray  # (.., 6) equality-row equilibration (state-free).


def make_plan(
    params: RQPParams,
    cfg: RQPCADMMConfig,
    agent_ids: jnp.ndarray | None = None,
) -> SchurPlan | None:
    """Public plan factory for ``control(plan=...)``: the precomputed Schur
    plan when the reduced formulation is active for this (cfg, n), else None
    (the full-QP path needs no plan). Build it once outside the rollout scan
    and close over it so the elimination cores never enter the compiled step."""
    if not _use_reduced(cfg, params.n):
        return None
    return make_schur_plan(params, cfg, agent_ids)


def make_schur_plan(
    params: RQPParams,
    cfg: RQPCADMMConfig,
    agent_ids: jnp.ndarray | None = None,
) -> SchurPlan:
    """Precompute the state-independent elimination cores for every agent in
    ``agent_ids`` (default: all n) and every rho the schedule visits.
    Requires n >= 4: at n = 3 the coupling block E_v (built from
    hat(r_j - r_k) pairs) is singular, so n = 3 uses the full QP path."""
    n = params.n
    if n < 4:
        raise ValueError(
            f"the Schur-reduced formulation needs n >= 4 (got n={n}): at "
            "n = 3 the 6x6 coupling block E_v is singular — use the full "
            "QP path (reduced_qp=False / the n < 4 default)"
        )
    dtype = params.r.dtype
    if agent_ids is None:
        agent_ids = jnp.arange(n)

    def one_agent(agent_id, rho):
        V = 3 * (n - 1)
        others = jnp.arange(n - 1) + (jnp.arange(n - 1) >= agent_id)
        perm = jnp.concatenate([agent_id[None], others]).astype(jnp.int32)
        r_perm = params.r_com[perm]  # (n, 3)
        hat_perm = jax.vmap(lie.hat)(r_perm)  # (n, 3, 3)
        hat_u, hat_v = hat_perm[0], hat_perm[1:]

        # Payload-frame blocks (all state-free; see class docstring).
        Sv = jnp.tile(jnp.eye(3, dtype=dtype), (1, n - 1))  # (3, V)
        Gv = jnp.concatenate(list(hat_v), axis=1)  # (3, V)
        Qvv = (
            2.0 * cfg.k_f * (Sv.T @ Sv) + 2.0 * cfg.k_m * (Gv.T @ Gv)
            + rho * jnp.eye(V, dtype=dtype)
        )
        C = 2.0 * cfg.k_f * Sv + 2.0 * cfg.k_m * (hat_u.T @ Gv)  # (3, V)
        Ev = jnp.concatenate([-Sv, -params.JT_inv @ Gv], axis=0)  # (6, V)
        Eu = jnp.concatenate(
            [-jnp.eye(3, dtype=dtype), -params.JT_inv @ hat_u], axis=0
        )  # (6, 3)
        # Row equilibration (row norms are Rl-invariant, so computed on the
        # payload-frame blocks once): rows mix mT ~ O(1) and JT_inv ~ O(1e2).
        # CROSS-AGENT INVARIANT: [Eu | Ev] jointly contains hat(r_j) for
        # EVERY agent j — only the column order differs between agents — so
        # each equality row's norm (hence `scale`) is identical for all
        # agents. _schur_state_pieces relies on this by using agent 0's
        # scale (plan.scale[0, 0]) for the agent-shared Ecc/e0s rows; the
        # invariance is asserted after the plan is built below. Any change
        # that makes the equilibration depend on the agent's own geometry
        # (e.g. per-agent CBF rows folded into the equalities) breaks it.
        Ecc_proxy = jnp.zeros((6, 9), dtype)
        Ecc_proxy = Ecc_proxy.at[0:3, 0:3].set(
            params.mT * jnp.eye(3, dtype=dtype)
        )
        Ecc_proxy = Ecc_proxy.at[3:6, 6:9].set(jnp.eye(3, dtype=dtype))
        scale = 1.0 / jnp.linalg.norm(
            jnp.concatenate([Ecc_proxy, Eu, Ev], axis=1), axis=1
        )
        Ev = Ev * scale[:, None]
        Eu = Eu * scale[:, None]

        L = jnp.linalg.inv(Qvv)
        L = 0.5 * (L + L.T)
        EvL = Ev @ L
        Y = EvL @ Ev.T
        Yinv = jnp.linalg.inv(0.5 * (Y + Y.T))
        Yinv = 0.5 * (Yinv + Yinv.T)
        J = EvL.T @ Yinv  # (V, 6)
        N = L - J @ EvL
        N = 0.5 * (N + N.T)

        NCt = N @ C.T
        Nsum = jnp.sum(N.reshape(V, n - 1, 3), axis=1)  # (V, 3)
        Jsum = jnp.sum(J.reshape(n - 1, 3, 6), axis=0)  # (3, 6)
        Mu = C @ N + Eu.T @ J.T  # (3, V)
        Musum = C @ Nsum + Eu.T @ Jsum.T  # (3, 3)
        CJ = C @ J  # (3, 6)
        YinvEu = Yinv @ Eu  # (6, 3)
        sym_term = C @ (J @ Eu)
        UUcore = (
            Eu.T @ YinvEu - C @ NCt - (sym_term + sym_term.T)
            + 2.0 * cfg.k_m * (hat_u.T @ hat_u)
        )
        CUcore = YinvEu - J.T @ C.T  # (6, 3)
        return SchurPlan(
            J=J, N=N, Yinv=Yinv, Eu=Eu, Mu=Mu, NCt=NCt, Nsum=Nsum,
            Jsum=Jsum, Musum=Musum, CJ=CJ, YinvEu=YinvEu, UUcore=UUcore,
            CUcore=CUcore, perm=perm, inv_perm=jnp.argsort(perm),
            scale=scale,
        )

    rhos = jnp.asarray(_rho_schedule(cfg), dtype)
    plan = jax.vmap(
        lambda rho: jax.vmap(lambda aid: one_agent(aid, rho))(agent_ids)
    )(rhos)
    if cfg.pad_operators:
        # Tile-pad the eliminated-block axis V = 3(n-1) on every core that
        # participates in a long per-iteration contraction (zero pad rows/
        # cols — exact; the consensus loop pads d_v and slices vt to match).
        V = 3 * (n - 1)
        V_p = _bucket_dim(V, socp.SUBLANE_TILE)
        pv = V_p - V

        def padv(x, axes):
            cfgpad = [(0, pv if a in axes else 0) for a in range(x.ndim)]
            return jnp.pad(x, cfgpad)

        plan = plan._replace(
            J=padv(plan.J, (2,)), N=padv(plan.N, (2, 3)),
            Mu=padv(plan.Mu, (3,)), NCt=padv(plan.NCt, (2,)),
            Nsum=padv(plan.Nsum, (2,)),
        )
    if not isinstance(plan.scale, jax.core.Tracer):
        # Guard the cross-agent row-norm invariance documented at the scale
        # construction above (skipped under tracing, where values are
        # abstract — inline plan builds inside jit still get the check from
        # any eager/test build of the same configuration).
        import numpy as _np

        # rtol: each row norm sums ~3(n+1) squared f32 terms in a per-agent
        # order, so worst-case reordering error grows like rows * eps
        # (~2e-5 at n = 64); 1e-4 keeps 4x headroom without masking a real
        # equilibration change (which would shift norms by O(1).
        assert _np.allclose(
            _np.asarray(plan.scale), _np.asarray(plan.scale[:, :1]),
            rtol=1e-4, atol=0.0,
        ), "equality-row equilibration is no longer agent-invariant; " \
           "_schur_state_pieces(plan.scale[0, 0]) would corrupt the " \
           "eliminated equality rows"
    return plan


def _schur_state_pieces(params: RQPParams, cfg: RQPCADMMConfig,
                        state: RQPState, scale: jnp.ndarray):
    """Per-step, agent-shared pieces of the reduced QP: the (scaled)
    payload-frame equality blocks on c and the static linear-term vectors."""
    dtype = state.xl.dtype
    e3 = jnp.array([0.0, 0.0, 1.0], dtype=dtype)
    Rt = state.Rl.T
    Ecc = jnp.zeros((6, 9), dtype)
    Ecc = Ecc.at[0:3, 0:3].set(params.mT * Rt)
    Ecc = Ecc.at[3:6, 6:9].set(jnp.eye(3, dtype=dtype))
    Ecc = Ecc * scale[:, None]
    e0s = scale * jnp.concatenate(
        [Rt @ (-params.mT * GRAVITY * e3),
         -params.JT_inv @ jnp.cross(state.wl, params.JT @ state.wl)]
    )
    xq = -2.0 * cfg.k_f * params.mT * GRAVITY * (Rt @ e3)  # q~_v0 block.
    return Ecc, e0s, xq


def _schur_step_qp(
    params: RQPParams,
    cfg: RQPCADMMConfig,
    pk: SchurPlan,
    f_eq: jnp.ndarray,
    state: RQPState,
    acc_des,
    env_cbf: EnvCBF,
    agent_id: jnp.ndarray,
    is_leader: jnp.ndarray,
    rho,
    Ecc: jnp.ndarray,
    e0s: jnp.ndarray,
    xq: jnp.ndarray,
):
    """Assemble one agent's reduced 12-var QP ``(P, q0, A, lb, ub, shift)``
    from the precomputed plan slice ``pk`` — only small Rl-conjugations, no
    large linear algebra (see :class:`SchurPlan`)."""
    n = params.n
    dtype = state.xl.dtype
    dvl_des, dwl_des = acc_des
    e3 = jnp.array([0.0, 0.0, 1.0], dtype=dtype)
    Rl = state.Rl

    # --- Reduced Hessian.
    k_dvl = cfg.k_dvl * is_leader
    k_dwl = cfg.k_dwl * is_leader
    P_cc = jnp.zeros((9, 9), dtype)
    P_cc = P_cc.at[3:6, 3:6].set(2.0 * k_dvl * jnp.eye(3, dtype=dtype))
    P_cc = P_cc.at[6:9, 6:9].set(2.0 * k_dwl * jnp.eye(3, dtype=dtype))
    H_cc = P_cc + Ecc.T @ pk.Yinv @ Ecc
    H_uu = (
        (2.0 * cfg.k_f + 2.0 * cfg.k_feq + rho) * jnp.eye(3, dtype=dtype)
        + Rl @ pk.UUcore @ Rl.T
        + smooth_block(cfg, state.R[agent_id], state.w[agent_id])
    )
    H_cu = Ecc.T @ pk.CUcore @ Rl.T
    P_red = jnp.block([[H_cc, H_cu], [H_cu.T, H_uu]])
    P_red = 0.5 * (P_red + P_red.T)

    # --- Static linear term.
    q_c0 = jnp.concatenate(
        [jnp.zeros(3, dtype), -2.0 * k_dvl * dvl_des, -2.0 * k_dwl * dwl_des]
    )
    q_u0 = (
        -2.0 * cfg.k_f * params.mT * GRAVITY * e3
        - 2.0 * cfg.k_feq * f_eq[agent_id]
    )
    q_red0 = jnp.concatenate([
        q_c0 - Ecc.T @ (pk.Jsum.T @ xq + pk.Yinv @ e0s),
        q_u0 + Rl @ (-pk.Musum @ xq + pk.CJ @ e0s - pk.YinvEu.T @ e0s),
    ])

    # --- Constraint rows on z = [c | u] (identical math to the full build).
    n_box = 7 + cfg.n_env_cbfs
    A = jnp.zeros((n_box, 12), dtype)
    lb = jnp.zeros((n_box,), dtype)
    ub = jnp.zeros((n_box,), dtype)

    R_w_hat = Rl @ lie.hat(state.wl)
    R_w_hat_sq = Rl @ lie.hat_square(state.wl, state.wl)
    # CoM -> payload-point kinematics equality (full rows 6:9).
    A = A.at[0:3, 0:3].set(-jnp.eye(3, dtype=dtype))
    A = A.at[0:3, 3:6].set(jnp.eye(3, dtype=dtype))
    A = A.at[0:3, 6:9].set(-Rl @ lie.hat(params.x_com))
    kin_rhs = -R_w_hat_sq @ params.x_com
    lb = lb.at[0:3].set(kin_rhs)
    ub = ub.at[0:3].set(kin_rhs)
    # Own f_z lower bound.
    A = A.at[3, 11].set(1.0)
    lb = lb.at[3].set(cfg.min_fz)
    ub = ub.at[3].set(socp.INF)
    # Payload tilt second-order CBF.
    A = A.at[4, 6:9].set(-(Rl[2] @ lie.hat(e3)))
    tilt_rhs = (
        -R_w_hat_sq[2, 2]
        - (cfg.alpha1_p_cbf + cfg.alpha2_p_cbf) * R_w_hat[2, 2]
        - cfg.alpha1_p_cbf * cfg.alpha2_p_cbf * (Rl[2, 2] - cfg.cos_max_p_ang)
    )
    lb = lb.at[4].set(tilt_rhs)
    ub = ub.at[4].set(socp.INF)
    # Angular-velocity norm CBF.
    A = A.at[5, 6:9].set(-2.0 * state.wl)
    lb = lb.at[5].set(
        -cfg.alpha_wl_cbf * (cfg.max_wl_sq - jnp.dot(state.wl, state.wl))
    )
    ub = ub.at[5].set(socp.INF)
    # Velocity norm CBF.
    A = A.at[6, 3:6].set(-2.0 * state.vl)
    lb = lb.at[6].set(
        -cfg.alpha_vl_cbf * (cfg.max_vl_sq - jnp.dot(state.vl, state.vl))
    )
    ub = ub.at[6].set(socp.INF)
    # Environment collision CBFs.
    A = A.at[7 : 7 + cfg.n_env_cbfs, 3:6].set(env_cbf.lhs)
    lb = lb.at[7 : 7 + cfg.n_env_cbfs].set(env_cbf.rhs)
    ub = ub.at[7 : 7 + cfg.n_env_cbfs].set(socp.INF)
    # SOC rows: own thrust cone + own norm cap.
    soc = jnp.zeros((8, 12), dtype)
    shift_soc = jnp.zeros((8,), dtype)
    soc = soc.at[0, 11].set(cfg.sec_max_f_ang)
    soc = soc.at[1:4, 9:12].set(jnp.eye(3, dtype=dtype))
    shift_soc = shift_soc.at[4].set(cfg.max_f)
    soc = soc.at[5:8, 9:12].set(jnp.eye(3, dtype=dtype))

    A_full = jnp.concatenate([A, soc], axis=0)
    shift = jnp.concatenate([jnp.zeros((n_box,), dtype), shift_soc])
    # Equilibrated like the full path (see _build_agent_qp).
    A_full, lb, ub, shift, _ = socp.equilibrate_rows(
        A_full, lb, ub, shift, n_box, (4, 4)
    )
    return P_red, q_red0, A_full, lb, ub, shift


def agent_env_cbfs(
    params: RQPParams,
    cfg: RQPCADMMConfig,
    forest: forest_mod.Forest | None,
    state: RQPState,
) -> EnvCBF:
    """Per-agent vision-cone CBF rows for all n agents (single-program path)."""
    return agent_env_cbfs_for(params, cfg, forest, state, params.r)


def agent_env_cbfs_for(
    params: RQPParams,
    cfg: RQPCADMMConfig,
    forest: forest_mod.Forest | None,
    state: RQPState,
    r_block: jnp.ndarray,
) -> EnvCBF:
    """Per-agent vision-cone-masked collision CBF rows, batched over the agents
    whose attachment points are in ``r_block`` (a shard's block, or all of
    ``params.r``). Reference ``_set_collision_avoidance_cbf_parameters``,
    rqp_cadmm.py:307-373: camera at the agent's attachment point, cone toward
    its bearing from the payload center."""
    n = r_block.shape[0]
    if forest is None:
        base = inactive_env_cbf(
            cfg.n_env_cbfs, cfg.vision_radius, cfg.dist_eps, cfg.alpha_env_cbf,
            dtype=state.xl.dtype,
        )
        return jax.tree.map(lambda x: jnp.tile(x, (n,) + (1,) * x.ndim), base)

    # The braking capsule is identical for every agent (it depends only on the
    # payload state, reference :319-332) — run the expensive segment-cylinder
    # sweep ONCE and give each agent its own vision-cone mask + top-k rows.
    collision_radius = cfg.vision_radius - 5.0  # vision = collision + 5 (:216).
    cap_a, cap_b, cap_h, speed, cap_dir = forest_mod.braking_capsule(
        state.xl, state.vl, collision_radius, cfg.max_deceleration
    )
    # Env-query dispatch (cfg.env_query; envs/spatial.py): the bucketed
    # tier gathers the capsule midpoint's candidate slab ONCE and the
    # per-agent cone masks below run over the (K,) candidates instead of
    # all (max_trees,) slots — same sweep-once/mask-per-agent structure,
    # bitwise-equal rows (the slab coverage is a build-time guarantee).
    mode = spatial_mod.runtime_env_query(cfg.env_query, forest)
    if mode == "bucketed":
        data, centers, _ = spatial_mod.bucketed_distance(
            forest, cap_a, cap_b, collision_radius, cfg.vision_radius,
            n_rows=cfg.n_env_cbfs,
        )
    else:
        data = forest_mod.capsule_forest_distance(
            forest, cap_a, cap_b, collision_radius, cfg.vision_radius
        )
        centers = forest.tree_pos

    def one_agent(r_i):
        camera = (state.xl + state.Rl @ r_i)[:2]
        d = camera - state.xl[:2]
        norm = jnp.linalg.norm(d)
        direction = d / jnp.where(norm > 0, norm, 1.0)
        mask = forest_mod.cone_mask_at(
            centers, camera, direction, cfg.vision_cone_ang
        )
        # Degenerate bearing (attachment above payload center): reference flags
        # collision and disables rows (:337-339).
        mask = mask & (norm > 0)
        cbf = forest_mod.cbf_rows_from_distance(
            data, state.xl, state.vl, cap_h, speed, cap_dir,
            cfg.max_deceleration, cfg.vision_radius, cfg.dist_eps,
            cfg.alpha_env_cbf, cfg.n_env_cbfs, extra_mask=mask,
        )
        return cbf.replace(collision=cbf.collision | (norm == 0))

    return jax.vmap(one_agent)(r_block)


def control(
    params: RQPParams,
    cfg: RQPCADMMConfig,
    f_eq: jnp.ndarray,
    admm_state: CADMMState,
    state: RQPState,
    acc_des,
    forest: forest_mod.Forest | None = None,
    axis_name: str | None = None,
    plan: SchurPlan | None = None,
    health=None,
):
    """One distributed control step: ``-> (f_app (n_local, 3), CADMMState,
    SolverStats)`` (reference ``RQPCADMMController.control``, :631-675).

    ``health``: optional :class:`resilience.faults.FaultStep` (needs
    ``.alive``/``.msg_ok``, both global (n,) bool). With it, the consensus
    degrades gracefully instead of assuming every agent healthy:

    - **dead agents** (``~alive``): their columns are zeroed in every local
      copy (so the survivors' dynamics equalities redistribute the payload
      load), their own copy rows / duals / warm starts are frozen, their
      solves never trigger retries, and their applied force is zero;
    - **dropped messages** (``alive & ~msg_ok``): the agent's copy is
      masked out of the consensus mean and residual for this step — the
      other agents hold its LAST delivered value (the step-start copy)
      while the dropped agent keeps iterating locally;
    - the consensus mean divides by the number of ALIVE agents, not n.

    ``health=None`` (the default) compiles the exact nominal program —
    fault support is zero-cost when unused.

    ``plan``: optional precomputed :func:`make_schur_plan` for the reduced
    (n >= 4) formulation, covering exactly this call's local agents. When
    None it is computed inline — the cores depend only on (params, cfg), so
    inside a jitted rollout scan XLA's loop-invariant code motion hoists the
    computation out of the loop; passing an explicit plan merely saves
    compile time and makes the cost model obvious.

    With ``axis_name=None`` all n agents run in one program (vmap; single chip).
    Inside ``shard_map`` over a mesh axis named ``axis_name``, each shard holds a
    block of agents (the leading axis of every ``CADMMState`` leaf) and the
    consensus mean/residual become cross-shard collectives over ICI — realized
    through the ``parallel.ring.consensus_exchange`` seam as global psum/pmax
    barriers, ppermute ring hops, or the async-DMA Pallas ring per
    ``cfg.consensus_impl`` (the all-reduce pattern SURVEY.md §2.10 prescribes,
    decomposed). ``state``/``acc_des``/``f_eq`` are replicated."""
    n = params.n
    dtype = state.xl.dtype

    n_local = admm_state.f.shape[0]
    if axis_name is None:
        agent_ids = jnp.arange(n_local)
    else:
        agent_ids = lax.axis_index(axis_name) * n_local + jnp.arange(n_local)

    # Consensus-exchange seam (parallel/ring.py): every cross-shard
    # collective goes through ONE impl-selected exchange, attributed under
    # tat.consensus_exchange. Ring size is static: shard_map requires
    # n % n_shards == 0 (parallel.mesh._sharded_control).
    n_shards = 1 if axis_name is None else n // n_local

    def _exch(x, op):
        return ring.consensus_exchange(
            x, axis_name, axis_size=n_shards, op=op, impl=cfg.consensus_impl
        )

    def _mean_over_agents(x):
        if axis_name is None:
            return jnp.mean(x, axis=0)
        return _exch(jnp.sum(x, axis=0), "sum") / n

    def _max_over_agents(x):
        if axis_name is None:
            return jnp.max(x)
        return _exch(jnp.max(x), "max")

    def _min_over_agents(x):
        if axis_name is None:
            return jnp.min(x)
        return _exch(jnp.min(x), "min")

    r_local = jnp.take(params.r, agent_ids, axis=0)

    with phases.scope(phases.CBF_ROWS):
        env_cbfs = agent_env_cbfs_for(params, cfg, forest, state, r_local)
    leaders = (agent_ids == cfg.leader_idx).astype(dtype)

    if health is not None:
        # Graceful-degradation masks (see the docstring). All (n,) leaves
        # are global/replicated; local slices follow agent_ids.
        alive_l = jnp.take(health.alive, agent_ids, axis=0)
        msg_ok_l = jnp.take(health.msg_ok, agent_ids, axis=0)
        w_alive = alive_l.astype(dtype)  # (n_local,)
        contrib = alive_l & msg_ok_l  # copies entering mean/residual fresh.
        alive_cols = health.alive.astype(dtype)  # (n,) global column mask.
        n_alive = jnp.sum(w_alive)
        if axis_name is not None:
            n_alive = _exch(n_alive, "sum")
        n_alive = jnp.maximum(n_alive, 1.0)
        # Dead agents anchor to zero force (callers typically already pass
        # the alive-masked equilibrium_forces; the mask is idempotent).
        f_eq = f_eq * alive_cols[:, None]
        # Peers' view of a dropped agent: its last DELIVERED copy (the
        # ``held`` snapshot, frozen across the whole dropout window), with
        # dead agents' columns zeroed so a held pre-death snapshot cannot
        # re-inject a dead agent's force into the masked mean.
        f_stale = (
            admm_state.held if admm_state.held is not None else admm_state.f
        ) * alive_cols[None, :, None]

    use_reduced = _use_reduced(cfg, n)
    nv, n_box_raw, nv_p, n_box, m = _qp_dims(cfg, n)

    def _pad_batch(P, q0, A, lb, ub, shift):
        """Lift a vmapped QP batch into its tile bucket (no-op when
        pad_operators is off) — see ops/socp.py pad_qp."""
        if not cfg.pad_operators:
            return P, q0, A, lb, ub, shift
        return jax.vmap(
            lambda P_, q_, A_, lb_, ub_, s_: socp.pad_qp(
                P_, q_, A_, lb_, ub_, s_, n_box=n_box_raw, soc_dims=(4, 4)
            )
        )(P, q0, A, lb, ub, shift)

    if use_reduced:
        # Constant-size (12-var) Schur-reduced per-agent QPs: the eliminated
        # force columns are reconstructed after each solve so the consensus
        # mean/residual/dual updates see the same full local copies as the
        # reference (rqp_cadmm.py:569-574). All expensive elimination cores
        # come from the state-independent plan (see SchurPlan docstring).
        if plan is None:
            plan = make_schur_plan(params, cfg, agent_ids)
        elif plan.J.shape[1] != n_local:
            # A full-n plan inside a shard: gather this shard's agent rows
            # (cheap indexing; the plan itself is replicated).
            plan = jax.tree.map(lambda x: jnp.take(x, agent_ids, axis=1), plan)
        Rl = state.Rl
        Ecc, e0s, xq = _schur_state_pieces(params, cfg, state, plan.scale[0, 0])
        V = 3 * (n - 1)  # plan cores may be V-padded; see make_schur_plan.

        def build_qp(rho_k, pk):
            P, q0, A, lb, ub, shift = _pad_batch(*jax.vmap(
                lambda p, aid, ld, cbf: _schur_step_qp(
                    params, cfg, p, f_eq, state, acc_des, cbf, aid, ld,
                    rho_k, Ecc, e0s, xq,
                )
            )(pk, agent_ids, leaders, env_cbfs))
            rho_vec = jax.vmap(
                lambda lb_, ub_: socp.make_rho_vec(m, n_box, lb_, ub_, 0.4, dtype)
            )(lb, ub)
            return (pk, (P, q0, A, lb, ub, shift),
                    socp.kkt_operator(P, A, rho_vec))

        def primal_solve(solve_one, data, rho_k, lam, f_mean, warm,
                         lane_active):
            pk, (P, q0, A, lb, ub, shift), op = data
            delta = lam - rho_k * f_mean[None, :, :]  # (n_local, n, 3)
            dperm = jnp.take_along_axis(delta, pk.perm[:, :, None], axis=1)
            d_u = dperm[:, 0, :]
            # Other columns, rotated into the payload frame (ft = Rl^T f),
            # zero-extended to the plan cores' (possibly V-padded) edge.
            d_v = jnp.einsum("ij,anj->ani", Rl.T, dperm[:, 1:, :]).reshape(
                n_local, V
            )
            d_v = jnp.pad(d_v, ((0, 0), (0, pk.N.shape[-1] - V)))
            jv = jnp.einsum("avk,av->ak", pk.J, d_v)  # (a, 6)
            q_delta = jnp.concatenate([
                -jnp.einsum("kc,ak->ac", Ecc, jv),
                d_u - jnp.einsum("ij,aj->ai", Rl,
                                 jnp.einsum("ajv,av->aj", pk.Mu, d_v)),
            ], axis=1)
            q = q0.at[:, :nv].add(q_delta)
            sols, eff = solve_one(P, q, A, lb, ub, shift, op, warm,
                                  lane_active)
            c, u = sols.x[:, :9], sols.x[:, 9:12]
            ut = jnp.einsum("ij,aj->ai", Rl.T, u)
            d6 = (e0s[None, :] - jnp.einsum("kc,ac->ak", Ecc, c)
                  - jnp.einsum("akj,aj->ak", pk.Eu, ut))
            vt = (
                -pk.Nsum @ xq
                - jnp.einsum("avw,aw->av", pk.N, d_v)
                - jnp.einsum("avj,aj->av", pk.NCt, ut)
                + jnp.einsum("avk,ak->av", pk.J, d6)
            )
            v = jnp.einsum(
                "ij,anj->ani", Rl, vt[:, :V].reshape(n_local, n - 1, 3)
            )
            f_perm = jnp.concatenate([u[:, None, :], v], axis=1)
            f_new = jnp.take_along_axis(f_perm, pk.inv_perm[:, :, None], axis=1)
            return f_new, sols, eff
    else:
        onehots = jax.nn.one_hot(agent_ids, n, dtype=dtype)

        def build_qp(rho_k):
            P, q0, A, lb, ub, shift = _pad_batch(*jax.vmap(
                lambda oh, ld, cbf: _build_agent_qp(
                    params, cfg, f_eq, state, acc_des, cbf, oh, ld, rho_k
                )
            )(onehots, leaders, env_cbfs))
            rho_vec = jax.vmap(
                lambda lb_, ub_: socp.make_rho_vec(m, n_box, lb_, ub_, 0.4, dtype)
            )(lb, ub)
            return (P, q0, A, lb, ub, shift), socp.kkt_operator(P, A, rho_vec)

        def primal_solve(solve_one, data, rho_k, lam, f_mean, warm,
                         lane_active):
            (P, q0, A, lb, ub, shift), op = data
            # Augmented linear term <lam_i, f> - rho <f_mean, f>.
            q_extra = (lam - rho_k * f_mean[None, :, :]).reshape(n_local, 3 * n)
            q = q0.at[:, 9:nv].add(q_extra)
            sols, eff = solve_one(P, q, A, lb, ub, shift, op, warm,
                                  lane_active)
            f_new = sols.x[:, 9:nv].reshape(n_local, n, 3)
            return f_new, sols, eff

    # rho schedule (reference :565-567, :657): precompute the per-agent QP
    # data + KKT operators for every distinct rho the capped schedule visits,
    # select per iteration. The default (tau_incr = 1) visits exactly one
    # value — no stacking, identical to a constant-rho build.
    rhos = _rho_schedule(cfg)
    n_rho = len(rhos)
    rho_arr = jnp.asarray(rhos, dtype)
    if n_rho == 1:
        with phases.scope(phases.QP_BUILD):
            data0 = (build_qp(rho_arr[0], jax.tree.map(lambda x: x[0], plan))
                     if use_reduced else build_qp(rho_arr[0]))

        def qp_at(it):
            return data0

        def rho_at(it):
            return rho_arr[0]
    else:
        with phases.scope(phases.QP_BUILD):
            stack = (jax.vmap(build_qp)(rho_arr, plan)
                     if use_reduced else jax.vmap(build_qp)(rho_arr))

        def qp_at(it):
            idx = jnp.minimum(it, n_rho - 1)
            return jax.tree.map(
                lambda x: lax.dynamic_index_in_dim(x, idx, 0, keepdims=False),
                stack,
            )

        def rho_at(it):
            return rho_arr[jnp.minimum(it, n_rho - 1)]

    # Consensus-level adaptive effort (cfg.effort, socp.resolve_effort):
    # every branch below is PYTHON-level, so effort="fixed" stages the
    # exact pre-knob program (byte-identical HLO — asserted in
    # tests/test_effort.py, the no_faults()/telemetry=None contract).
    adaptive = cfg.effort == "adaptive"
    if adaptive:
        # Adaptive forces the tolerance-chunked early-exit solve path (a
        # fixed-iteration scan cannot express a 0-effective-iteration
        # pass-through); the stop tolerance defaults to the solve-success
        # gate itself so "converged" means "would pass solver_tol".
        inner_tol_eff = cfg.inner_tol if cfg.inner_tol > 0 else cfg.solver_tol
        inner_check_eff = cfg.inner_check_every

    def make_solve(iters):
        if not adaptive:
            vs = jax.vmap(
                lambda P_, q_, A_, lb_, ub_, shift_, op_, warm_:
                socp.solve_socp(
                    P_, q_, A_, lb_, ub_,
                    n_box=n_box, soc_dims=(4, 4), iters=iters,
                    warm=warm_, shift=shift_, op=op_, fused=cfg.socp_fused,
                    precision=cfg.socp_precision,
                    tol=cfg.inner_tol,
                    check_every=(cfg.inner_check_every if cfg.inner_tol > 0
                                 else 0),
                )
            )

            def solve(P_, q_, A_, lb_, ub_, shift_, op_, warm_, active):
                del active  # fixed effort: no gating ops staged.
                return vs(P_, q_, A_, lb_, ub_, shift_, op_, warm_), None

            return solve

        # The per-scenario converged gate broadcasts over the agent axis
        # (in_axes None): a gated-off scenario's agent solves are all
        # 0-effective-iteration pass-throughs; eff is the per-agent
        # effective iteration count for SolverStats.inner_iters.
        return jax.vmap(
            lambda P_, q_, A_, lb_, ub_, shift_, op_, warm_, act_:
            socp.solve_socp(
                P_, q_, A_, lb_, ub_,
                n_box=n_box, soc_dims=(4, 4), iters=iters,
                warm=warm_, shift=shift_, op=op_, fused=cfg.socp_fused,
                precision=cfg.socp_precision,
                tol=inner_tol_eff, check_every=inner_check_eff,
                active=act_, report_iters=True,
            ),
            in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None),
        )

    solve_cold = make_solve(cfg.inner_iters)
    warm_iters = cfg.inner_iters_warm or cfg.inner_iters
    two_phase = warm_iters != cfg.inner_iters
    solve_warm = make_solve(warm_iters) if two_phase else solve_cold

    def _continue_pred(it, res, ok_last, fail_count):
        """The outer loop's continue predicate — shared by ``cond`` and
        the adaptive-effort lane gate so the two cannot drift."""
        return (((res >= cfg.res_tol)
                 | ((ok_last < 1.0) & (fail_count <= retry_cap)))
                & (it <= cfg.max_iter))

    def _consensus_iter_impl(solve_one, carry):
        (f, lam, f_mean, warm, it, res, err_buf, okf, _ok_last,
         fail_count) = carry[:10]
        if adaptive:
            # The lane's own would-continue bit (under vmap the outer
            # while_loop body runs for every lane while ANY lane is
            # active; this gate is what lets a converged lane's solves
            # pass through at 0 effective iterations instead of paying
            # the stragglers' budget).
            lane_active = _continue_pred(it, res, _ok_last, fail_count)
        else:
            lane_active = None
        with phases.scope(phases.LOCAL_SOLVE):
            f_new, sols, eff = primal_solve(
                solve_one, qp_at(it), rho_at(it), lam, f_mean, warm,
                lane_active,
            )
        # Failed agents fall back to equilibrium forces (reference :491-494).
        ok = (sols.prim_res < cfg.solver_tol)[:, None, None] & jnp.all(
            jnp.isfinite(f_new), axis=(1, 2), keepdims=True
        )
        f_new = jnp.where(ok, f_new, f_eq[None, :, :])
        if health is not None:
            # Dead agents: zero their columns in every survivor's copy (the
            # dynamics equalities then redistribute the load) and freeze
            # their own rows at the last pre-death copy.
            f_new = f_new * alive_cols[None, :, None]
            f_new = jnp.where(alive_l[:, None, None], f_new, f)
        # Warm starts keep any FINITE iterate — including tolerance-missed
        # ones: a hard agent QP (e.g. a strongly active near-contact env
        # CBF row) then accumulates inner iterations across consensus
        # retries instead of restarting from the same point and failing
        # identically forever. Only non-finite iterates (which would poison
        # every later solve) revert.
        ok_flat = ok[:, 0, 0]
        finite_flat = socp.solution_is_finite(sols)
        if health is not None:
            # Corpses never trigger retries and keep frozen warm starts.
            ok_flat = ok_flat | ~alive_l
            finite_flat = finite_flat & alive_l
        sols = jax.tree.map(
            lambda new, old: jnp.where(
                finite_flat.reshape((n_local,) + (1,) * (new.ndim - 1)),
                new, old,
            ),
            sols, warm,
        )
        # Consensus all-reduce: mean + inf-norm residual (psum/pmax over the
        # mesh axis when agents are sharded).
        with phases.scope(phases.CONSENSUS):
            if health is None:
                f_mean_new = _mean_over_agents(f_new)
                res_new = _max_over_agents(
                    jnp.abs(f_new - f_mean_new[None, :, :])
                )
            else:
                # Masked consensus: dropped agents contribute their HELD
                # copy, dead agents contribute nothing, and the mean
                # divides by the alive count. The residual measures
                # agreement of the FRESH delivered copies only (a
                # permanently-dropped agent's stale copy is expected to
                # disagree — it must not stall the loop).
                f_eff = jnp.where(msg_ok_l[:, None, None], f_new, f_stale)
                s = jnp.sum(f_eff * w_alive[:, None, None], axis=0)
                if axis_name is not None:
                    s = _exch(s, "sum")
                f_mean_new = s / n_alive
                res_new = _max_over_agents(jnp.where(
                    contrib[:, None, None],
                    jnp.abs(f_eff - f_mean_new[None, :, :]), 0.0,
                ))
        err_buf = err_buf.at[it].set(res_new)
        it = it + 1
        # Dual update, gated exactly like the reference's loop (:655-665):
        # rho advances after the solves, the loop breaks BEFORE the dual
        # update when converged or past the cap, and the update uses the
        # advanced rho.
        with phases.scope(phases.DUAL_UPDATE):
            do_dual = (res_new >= cfg.res_tol) & (it <= cfg.max_iter)
            lam_new = jnp.where(
                do_dual, lam + rho_at(it) * (f_new - f_mean_new[None, :, :]),
                lam,
            )
            if health is not None:
                # Frozen duals for dead agents.
                lam_new = jnp.where(alive_l[:, None, None], lam_new, lam)
        # Worst-iteration solve-success fraction (observability of the
        # equilibrium-fallback path).
        ok_last = _mean_over_agents(ok_flat.astype(dtype))
        okf = jnp.minimum(okf, ok_last)
        # CONSECUTIVE failing iterations: reset on fully-ok ones so a
        # late-onset failure episode always gets the full retry budget.
        fail_count = jnp.where(ok_last < 1.0, fail_count + 1, 0)
        out = (f_new, lam_new, f_mean_new, sols, it, res_new, err_buf, okf,
               ok_last, fail_count)
        if adaptive:
            # Effective inner iterations actually spent this consensus
            # iteration (summed over this shard's agents) — the solver-
            # effort accounting behind SolverStats.inner_iters.
            out = out + (carry[10] + jnp.sum(eff),)
        return out

    # Per-lane batch semantics: no manual freeze is needed — lax.while_loop's
    # batching rule re-evaluates the full per-lane cond inside the body and
    # selects old-vs-new carry per lane, so in a vmapped batch a converged
    # scenario's carry stays frozen while the loop drains the slowest lane,
    # and each lane's result equals a solo run's exactly.
    consensus_iter = _consensus_iter_impl

    retry_cap = cfg.solve_retry_iters or cfg.max_iter

    def cond(carry):
        # Positional indexing (the adaptive-effort carry appends an
        # inner-iteration accumulator at the end): it=4, res=5,
        # ok_last=8, fail_count=9.
        # Keep iterating while any agent's solve is still failing, even at
        # consensus agreement: fallback copies agree trivially (all
        # equilibrium), so a residual-only exit would declare convergence
        # at the exact moment protection is most needed. Retries continue
        # the failed solves from their carried finite iterates, bounded by
        # solve_retry_iters (default 4) FAILING iterations — counted from
        # failure onset, not from iteration 0, so late-onset failures get
        # the full budget.
        return _continue_pred(carry[4], carry[5], carry[8], carry[9])

    err_buf0 = jnp.full((cfg.max_iter + 1,), jnp.nan, dtype)
    init = (
        admm_state.f, admm_state.lam, admm_state.f_mean, admm_state.warm,
        jnp.zeros((), jnp.int32), jnp.asarray(jnp.inf, dtype), err_buf0,
        jnp.ones((), dtype), jnp.ones((), dtype), jnp.zeros((), jnp.int32),
    )
    if adaptive:
        init = init + (jnp.zeros((), jnp.int32),)  # inner-iteration total.
    if not two_phase:
        carry = init
    else:
        # Two-phase budget: the first consensus iteration always runs (res
        # starts at inf), so peel it with the cold solver budget; the loop
        # body then uses the warm budget — its warm start is THIS step's
        # previous iterate, far closer than the cross-step start iteration 1
        # sees. (A lax.cond on the iteration index would NOT work: under
        # vmap it becomes a select that executes both solver branches for
        # every lane.)
        carry = consensus_iter(solve_cold, init)
    carry = lax.while_loop(
        cond, lambda c: consensus_iter(solve_warm, c), carry
    )
    (f, lam, f_mean, warm, iters, res, err_buf, ok_frac,
     _ok_last, _fail_count) = carry[:10]

    # Applied forces: agent i applies its own column (reference :669-675).
    f_app = f[jnp.arange(n_local), agent_ids, :]
    if health is not None:
        f_app = f_app * w_alive[:, None]  # dead agents actuate nothing.
        # Delivered-snapshot update: agents whose messages went through
        # this step publish their final copies; dropped agents' snapshots
        # stay frozen for the peers until their next delivered step.
        held = jnp.where(msg_ok_l[:, None, None], f, f_stale)
    else:
        held = admm_state.held
    new_state = CADMMState(f=f, lam=lam, f_mean=f_mean, warm=warm, held=held)
    collision = _max_over_agents(env_cbfs.collision.astype(jnp.int32)) > 0
    stats = SolverStats(
        iters=iters,
        solve_res=res,
        collision=collision,
        min_env_dist=_min_over_agents(env_cbfs.min_dist),
        err_seq=err_buf,
        ok_frac=ok_frac,
    )
    if adaptive:
        # Whole-fleet effective inner iterations this step (exchanged
        # once, outside the loop; f32 exchange is exact far past any
        # realistic count and keeps the ring impls dtype-uniform).
        inner_tot = carry[10]
        if axis_name is not None:
            inner_tot = _exch(inner_tot.astype(dtype), "sum").astype(
                jnp.int32
            )
        stats = stats.replace(inner_iters=inner_tot)
    if cfg.track_agent_stats:
        # Exit-time per-agent QP residuals for solve-health telemetry
        # (obs.telemetry track_agents): the final warm start's prim_res,
        # all_gathered to the full (n,) table when agents are sharded.
        agent_res = warm.prim_res
        if axis_name is not None:
            agent_res = ring.consensus_gather(
                agent_res, axis_name, axis_size=n_shards,
                impl=cfg.consensus_impl,
            ).reshape(n)
        stats = stats.replace(agent_solve_res=agent_res)
    return f_app, new_state, stats


def jit_control_step(params, cfg, f_eq, forest=None, plan=None,
                     donate: bool = True):
    """Jitted single control step ``(admm_state, state, acc_des) ->
    (f_app, admm_state, stats)`` with the ADMM-state carry DONATED: the
    warm starts, local copies, and duals are updated in place instead of
    round-tripping fresh HBM buffers on every control step — the serving
    pattern for step-at-a-time MPC callers (rollout scans get the same
    effect from the scan carry; see harness.rollout.jit_rollout). The
    caller must thread the returned state forward and not reuse the
    donated argument (jax deletes its buffers)."""
    if plan is None:
        plan = make_plan(params, cfg)

    def step(admm_state, state, acc_des):
        return control(
            params, cfg, f_eq, admm_state, state, acc_des, forest, plan=plan
        )

    return jax.jit(step, donate_argnums=(0,) if donate else ())
