"""Centralized optimal controller for the RQP model: one conic QP per control step
with CBF safety rows, solved by the batched ADMM solver.

TPU-native re-design of reference ``control/rqp_centralized.py``
(``RQPCentralizedController``). Same optimization problem (docstring :28-44), built
as explicit ``(P, q, A, lb, ub, shift)`` matrices in one pure JAX function instead
of a cvxpy parametrized problem re-canonicalized on the host:

  decision  x = [dv_com (3) | dvl (3) | dwl (3) | f_1..f_n (3 each)]
  cost      k_f ||sum f - mT g e3||^2 + k_m ||sum hat(r_com_i) Rl^T f_i||^2
            + k_feq ||f - f_eq||^2 + k_dvl (||dvl||^2 - 2 dvl_des . dvl)
            + k_dwl (||dwl||^2 - 2 dwl_des . dwl)                     (:396-425)
  s.t.      linearized dynamics + CoM->payload kinematics equalities  (:340-356)
            f_z >= min_fz; ||f_i|| <= sec(30deg) f_iz (SOC);
            ||f_i|| <= max_f (SOC)                                    (:358-365)
            payload-tilt / |wl| / |vl| CBF rows                       (:367-391)
            up to n_env_cbfs collision CBF rows  lhs @ dvl >= rhs     (:393-394)

The controller is a pure function ``control(...)`` over pytrees; mutable bits of the
reference (warm start, previous-solution fallback on solver failure, :427-448)
become an explicit ``CtrlState`` carried through ``lax.scan``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from tpu_aerial_transport.control.types import EnvCBF, SolverStats, inactive_env_cbf
from tpu_aerial_transport.models.rqp import GRAVITY, RQPParams, RQPState
from tpu_aerial_transport.ops import lie, socp


@struct.dataclass
class RQPCentralizedConfig:
    """Controller constants (reference ``_set_controller_constants``, :182-225).
    All fields are scalars so the config is a trivially shardable pytree."""

    # Constraints.
    min_fz: float
    sec_max_f_ang: float
    max_f: float
    cos_max_p_ang: float
    alpha1_p_cbf: float
    alpha2_p_cbf: float
    max_wl_sq: float
    alpha_wl_cbf: float
    max_vl_sq: float
    alpha_vl_cbf: float
    # Env collision CBFs.
    dist_eps: float
    vision_radius: float
    alpha_env_cbf: float
    max_deceleration: float
    # Costs.
    k_f: float
    k_m: float
    k_feq: float
    k_dvl: float
    k_dwl: float
    # Optional force-smoothing cost (reference :215-225; carried at its
    # reference default k_smooth = 0, with the reference's own in-code note
    # "Controller is more stable without smoothing"): penalizes the force
    # component orthogonal to each quad's predicted next-step thrust axis,
    #   k_smooth * sum_i ||(R_i exp3(w_i dt))[:, :2]^T f_i||^2   (:117-121).
    k_smooth: float = 0.0
    dt: float = 1e-3  # smoothing-axis prediction horizon (reference :268-271).
    # Static sizes / solver budget.
    n_env_cbfs: int = struct.field(pytree_node=False, default=10)
    solver_iters: int = struct.field(pytree_node=False, default=150)
    solver_tol: float = struct.field(pytree_node=False, default=5e-3)
    # Early-exit cadence for the conic solve: check residuals every this
    # many inner iterations and stop once both are under solver_tol (0 =
    # always run the full solver_iters budget). Warm-started receding-
    # horizon steps typically converge in a fraction of the budget, so this
    # mirrors Clarabel's own tolerance-based termination in the reference.
    solver_check_every: int = struct.field(pytree_node=False, default=25)
    max_f_ang: float = struct.field(pytree_node=False, default=jnp.pi / 6)


def make_config(
    params: RQPParams,
    collision_radius: float,
    max_deceleration: float,
    n_env_cbfs: int = 10,
    solver_iters: int = 150,
    max_f_ang: float = float(jnp.pi / 6.0),
    k_smooth: float = 0.0,
    dt: float = 1e-3,
) -> RQPCentralizedConfig:
    """Defaults from reference :182-225 (RQP: max payload tilt 15 deg)."""
    n = params.n
    mTg = float(params.mT) * GRAVITY
    return RQPCentralizedConfig(
        min_fz=mTg / (n * 10.0),
        sec_max_f_ang=float(1.0 / jnp.cos(max_f_ang)),
        max_f=2.0 * mTg / n,
        cos_max_p_ang=float(jnp.cos(jnp.pi / 12.0)),
        alpha1_p_cbf=1.0,
        alpha2_p_cbf=1.0,
        max_wl_sq=float((jnp.pi / 6.0) ** 2),
        alpha_wl_cbf=1.0,
        max_vl_sq=1.0,
        alpha_vl_cbf=1.0,
        dist_eps=0.1,
        vision_radius=collision_radius + 5.0,
        alpha_env_cbf=2.0,
        max_deceleration=max_deceleration,
        k_f=0.1,
        k_m=0.1,
        k_feq=0.1,
        k_dvl=1.0,
        k_dwl=1.0,
        k_smooth=k_smooth,
        dt=dt,
        n_env_cbfs=n_env_cbfs,
        solver_iters=solver_iters,
        max_f_ang=max_f_ang,
    )


def smooth_block(cfg, R_i: jnp.ndarray, w_i: jnp.ndarray) -> jnp.ndarray:
    """Hessian block ``2 k_smooth Rq_orth Rq_orth^T`` of the optional
    force-smoothing cost on one agent's force (reference
    rqp_centralized.py:421-424 / rqp_cadmm.py:455-462, :287-293):
    ``Rq = R_i exp3(w_i dt)`` is the quad's predicted next-step attitude,
    ``Rq_orth`` its first two columns. ``cfg`` is any controller config
    carrying ``k_smooth``/``dt`` (centralized and distributed share both)."""
    Rq = R_i @ lie.expm_so3(w_i * cfg.dt)
    Rq_orth = Rq[:, :2]
    return 2.0 * cfg.k_smooth * (Rq_orth @ Rq_orth.T)


def equilibrium_forces(params: RQPParams, alive=None) -> jnp.ndarray:
    """Static equilibrium forces ``f_eq (n, 3)``: vertical thrusts solving the
    least-squares wrench balance (reference :155-164).

    ``alive``: optional (n,) healthy-agent mask (bool or 0/1). Dead agents'
    wrench columns are zeroed and the min-norm pseudoinverse solution
    redistributes the payload load over the SURVIVORS (zero thrust on dead
    agents) — the graceful-degradation load share consumed by the
    resilience layer. ``alive=None`` keeps the historical lstsq path
    bit-for-bit (a dynamic mask would force the pinv path into every
    nominal trace)."""
    n = params.n
    # hat(r_com_i) e3 = r_com_i x e3; rows [1, (r_com_i x e3)_x, (r_com_i x e3)_y].
    e3 = jnp.array([0.0, 0.0, 1.0], dtype=params.r.dtype)
    rxe = jnp.cross(params.r_com, e3)  # (n, 3)
    wrench = jnp.concatenate([jnp.ones((n, 1), params.r.dtype), rxe[:, :2]], axis=1).T
    rhs = jnp.array([params.mT * GRAVITY, 0.0, 0.0], dtype=params.r.dtype)
    if alive is None:
        fz = jnp.linalg.lstsq(wrench, rhs)[0]  # (n,)
    else:
        w = jnp.asarray(alive).astype(params.r.dtype)  # (n,)
        # SVD pinv handles the rank drop from zeroed columns (and the
        # all-dead corner, where it returns all-zero thrusts) under jit
        # with a traced mask; the min-norm solution puts exactly 0 on the
        # zeroed (dead) columns.
        fz = w * (jnp.linalg.pinv(wrench * w[None, :]) @ rhs)
    return jnp.concatenate([jnp.zeros((n, 2), params.r.dtype), fz[:, None]], axis=1)


def qp_dims(n: int, n_env_cbfs: int):
    """Single source of truth for the QP row layout: ``(n_box, m, soc_dims)``.
    Box rows: [dyn-trans 3 | dyn-rot 3 | kin 3 | fz_min n | tilt 1 | wl 1 |
    vl 1 | env k]; then per agent two SOC(4) blocks (thrust cone, norm cap)."""
    n_box = 12 + n + n_env_cbfs
    soc_dims = (4,) * (2 * n)
    return n_box, n_box + sum(soc_dims), soc_dims


@struct.dataclass
class CtrlState:
    """Mutable controller state threaded through the rollout scan: previous
    solution (failure fallback, :441-444) + solver warm start (:427-434)."""

    prev_f: jnp.ndarray  # (n, 3)
    warm: socp.SOCPSolution


def init_ctrl_state(params: RQPParams, cfg: RQPCentralizedConfig) -> CtrlState:
    n = params.n
    _, m, _ = qp_dims(n, cfg.n_env_cbfs)
    f_eq = equilibrium_forces(params)
    x0 = jnp.concatenate([jnp.zeros(9, f_eq.dtype), f_eq.reshape(-1)])
    warm = socp.SOCPSolution(
        x=x0,
        y=jnp.zeros((m,), f_eq.dtype),
        z=jnp.zeros((m,), f_eq.dtype),
        prim_res=jnp.zeros((), f_eq.dtype),
        dual_res=jnp.zeros((), f_eq.dtype),
    )
    return CtrlState(prev_f=f_eq, warm=warm)


def _build_qp(
    params: RQPParams,
    cfg: RQPCentralizedConfig,
    f_eq: jnp.ndarray,
    state: RQPState,
    acc_des,
    env_cbf: EnvCBF,
):
    """Assemble ``(P, q, A, lb, ub, shift)`` for the current state. Pure, jittable.

    Variable layout: [dv_com 0:3 | dvl 3:6 | dwl 6:9 | f 9:9+3n] (agent-major);
    the row layout is defined by :func:`qp_dims`.
    """
    n = params.n
    dtype = state.xl.dtype
    nv = 9 + 3 * n
    dvl_des, dwl_des = acc_des
    e3 = jnp.array([0.0, 0.0, 1.0], dtype=dtype)
    Rl = state.Rl

    # --- Cost.
    P = jnp.zeros((nv, nv), dtype)
    q = jnp.zeros((nv,), dtype)
    # k_dvl, k_dwl blocks.
    P = P.at[3:6, 3:6].add(2.0 * cfg.k_dvl * jnp.eye(3, dtype=dtype))
    q = q.at[3:6].add(-2.0 * cfg.k_dvl * dvl_des)
    P = P.at[6:9, 6:9].add(2.0 * cfg.k_dwl * jnp.eye(3, dtype=dtype))
    q = q.at[6:9].add(-2.0 * cfg.k_dwl * dwl_des)
    # Force blocks: S = [I .. I] (3, 3n); G_i = hat(r_com_i) Rl^T (3, 3n).
    S = jnp.tile(jnp.eye(3, dtype=dtype), (1, n))
    G = jnp.concatenate(
        [lie.hat(params.r_com[i]) @ Rl.T for i in range(n)], axis=1
    )  # (3, 3n)
    Pff = (
        2.0 * cfg.k_f * (S.T @ S)
        + 2.0 * cfg.k_m * (G.T @ G)
        + 2.0 * cfg.k_feq * jnp.eye(3 * n, dtype=dtype)
    )
    P = P.at[9:, 9:].add(Pff)
    q = q.at[9:].add(
        -2.0 * cfg.k_f * (S.T @ (params.mT * GRAVITY * e3))
        - 2.0 * cfg.k_feq * f_eq.reshape(-1)
    )
    # Force-smoothing cost (reference :421-424, default k_smooth = 0):
    # k_smooth ||Rq_orth_i^T f_i||^2 with Rq_i = R_i exp3(w_i dt) (:268-271),
    # added block-diagonally over the agent force blocks in one op.
    blocks = jax.vmap(lambda R_i, w_i: smooth_block(cfg, R_i, w_i))(
        state.R, state.w
    )
    P = P.at[9:, 9:].add(jax.scipy.linalg.block_diag(*blocks))

    # --- Box constraint rows.
    n_box, _, _ = qp_dims(n, cfg.n_env_cbfs)
    A = jnp.zeros((n_box, nv), dtype)
    lb = jnp.zeros((n_box,), dtype)
    ub = jnp.zeros((n_box,), dtype)

    # Dynamics translation (rows 0:3): mT dv_com - sum_i f_i = -mT g e3.
    A = A.at[0:3, 0:3].set(params.mT * jnp.eye(3, dtype=dtype))
    A = A.at[0:3, 9:].set(-S)
    rhs = -params.mT * GRAVITY * e3
    lb = lb.at[0:3].set(rhs)
    ub = ub.at[0:3].set(rhs)

    # Dynamics rotation (rows 3:6): dwl - sum_i JT_inv hat(r_com_i) Rl^T f_i
    #   = -JT_inv (wl x JT wl).
    A = A.at[3:6, 6:9].set(jnp.eye(3, dtype=dtype))
    A = A.at[3:6, 9:].set(-params.JT_inv @ G)
    rot_rhs = -params.JT_inv @ jnp.cross(state.wl, params.JT @ state.wl)
    lb = lb.at[3:6].set(rot_rhs)
    ub = ub.at[3:6].set(rot_rhs)

    # Kinematics (rows 6:9): dvl - dv_com - Rl hat(x_com) dwl = -Rl hat^2(wl) x_com.
    R_w_hat = Rl @ lie.hat(state.wl)
    R_w_hat_sq = Rl @ lie.hat_square(state.wl, state.wl)
    A = A.at[6:9, 0:3].set(-jnp.eye(3, dtype=dtype))
    A = A.at[6:9, 3:6].set(jnp.eye(3, dtype=dtype))
    A = A.at[6:9, 6:9].set(-Rl @ lie.hat(params.x_com))
    kin_rhs = -R_w_hat_sq @ params.x_com
    lb = lb.at[6:9].set(kin_rhs)
    ub = ub.at[6:9].set(kin_rhs)

    # f_z lower bounds (rows 9:9+n).
    for i in range(n):
        A = A.at[9 + i, 9 + 3 * i + 2].set(1.0)
    lb = lb.at[9 : 9 + n].set(cfg.min_fz)
    ub = ub.at[9 : 9 + n].set(socp.INF)

    # Payload tilt second-order CBF (row 9+n):
    # -(e3^T Rl hat(e3)) dwl >= -R_w_hat_sq[2,2] - (a1+a2) R_w_hat[2,2]
    #                           - a1 a2 (Rl[2,2] - cos_max_p_ang).
    r_tilt = 9 + n
    A = A.at[r_tilt, 6:9].set(-(Rl[2] @ lie.hat(e3)))
    tilt_rhs = (
        -R_w_hat_sq[2, 2]
        - (cfg.alpha1_p_cbf + cfg.alpha2_p_cbf) * R_w_hat[2, 2]
        - cfg.alpha1_p_cbf * cfg.alpha2_p_cbf * (Rl[2, 2] - cfg.cos_max_p_ang)
    )
    lb = lb.at[r_tilt].set(tilt_rhs)
    ub = ub.at[r_tilt].set(socp.INF)

    # |wl| CBF (row 10+n): -2 wl . dwl >= -alpha (max_wl^2 - ||wl||^2).
    r_wl = 10 + n
    A = A.at[r_wl, 6:9].set(-2.0 * state.wl)
    lb = lb.at[r_wl].set(
        -cfg.alpha_wl_cbf * (cfg.max_wl_sq - jnp.dot(state.wl, state.wl))
    )
    ub = ub.at[r_wl].set(socp.INF)

    # |vl| CBF (row 11+n): -2 vl . dvl >= -alpha (max_vl^2 - ||vl||^2).
    r_vl = 11 + n
    A = A.at[r_vl, 3:6].set(-2.0 * state.vl)
    lb = lb.at[r_vl].set(
        -cfg.alpha_vl_cbf * (cfg.max_vl_sq - jnp.dot(state.vl, state.vl))
    )
    ub = ub.at[r_vl].set(socp.INF)

    # Env collision CBF rows (12+n : 12+n+k): lhs @ dvl >= rhs.
    r_env = 12 + n
    A = A.at[r_env : r_env + cfg.n_env_cbfs, 3:6].set(env_cbf.lhs)
    lb = lb.at[r_env : r_env + cfg.n_env_cbfs].set(env_cbf.rhs)
    ub = ub.at[r_env : r_env + cfg.n_env_cbfs].set(socp.INF)

    # --- SOC rows: per agent [sec30 f_z; f] (cone) + [max_f; f] (cap).
    soc = jnp.zeros((8 * n, nv), dtype)
    shift_soc = jnp.zeros((8 * n,), dtype)
    for i in range(n):
        base = 8 * i
        fi = 9 + 3 * i
        soc = soc.at[base, fi + 2].set(cfg.sec_max_f_ang)
        soc = soc.at[base + 1 : base + 4, fi : fi + 3].set(jnp.eye(3, dtype=dtype))
        # Norm cap: top element is the constant max_f (enters via shift).
        shift_soc = shift_soc.at[base + 4].set(cfg.max_f)
        soc = soc.at[base + 5 : base + 8, fi : fi + 3].set(jnp.eye(3, dtype=dtype))

    A_full = jnp.concatenate([A, soc], axis=0)
    shift = jnp.concatenate([jnp.zeros((n_box,), dtype), shift_soc])
    # Exact row/block equilibration (see cadmm._build_agent_qp).
    A_full, lb, ub, shift, _ = socp.equilibrate_rows(
        A_full, lb, ub, shift, n_box, (4,) * (2 * n)
    )
    return P, q, A_full, lb, ub, shift


def control(
    params: RQPParams,
    cfg: RQPCentralizedConfig,
    f_eq: jnp.ndarray,
    ctrl_state: CtrlState,
    state: RQPState,
    acc_des,
    env_cbf: EnvCBF | None = None,
):
    """One control step: ``-> (f_des (n, 3), CtrlState, SolverStats)``.

    Mirrors ``RQPCentralizedController.control`` (:436-448): solve the conic QP
    warm-started from the previous step; if the solve failed to converge, fall back
    to the previous forces.
    """
    n = params.n
    if env_cbf is None:
        env_cbf = inactive_env_cbf(
            cfg.n_env_cbfs, cfg.vision_radius, cfg.dist_eps, cfg.alpha_env_cbf,
            dtype=state.xl.dtype,
        )
    P, q, A, lb, ub, shift = _build_qp(params, cfg, f_eq, state, acc_des, env_cbf)
    n_box, _, soc_dims = qp_dims(n, cfg.n_env_cbfs)
    sol = socp.solve_socp(
        P, q, A, lb, ub,
        n_box=n_box,
        soc_dims=soc_dims,
        iters=cfg.solver_iters,
        warm=ctrl_state.warm,
        shift=shift,
        check_every=cfg.solver_check_every,
        tol=cfg.solver_tol,
    )
    f = sol.x[9:].reshape(n, 3)
    ok = (sol.prim_res < cfg.solver_tol) & jnp.all(jnp.isfinite(sol.x))
    f_out = jnp.where(ok, f, ctrl_state.prev_f)
    # On failure keep the previous warm start too — warm-starting from a NaN or
    # garbage iterate would poison every subsequent solve (the reference recovers
    # because cvxpy re-solves from scratch; we must recover explicitly).
    keep = lambda new, old: jnp.where(ok, new, old)
    warm = socp.SOCPSolution(
        x=keep(sol.x, ctrl_state.warm.x),
        y=keep(sol.y, ctrl_state.warm.y),
        z=keep(sol.z, ctrl_state.warm.z),
        prim_res=sol.prim_res,
        dual_res=sol.dual_res,
    )
    new_state = CtrlState(prev_f=f_out, warm=warm)
    stats = SolverStats(
        iters=jnp.asarray(-1, jnp.int32),
        solve_res=sol.prim_res,
        collision=env_cbf.collision,
        min_env_dist=env_cbf.min_dist,
        ok_frac=ok.astype(sol.x.dtype),
    )
    return f_out, new_state, stats
