"""Geometric SO(3) attitude tracking laws, batched over agents.

TPU-native replacement for reference ``utils/so3_tracking_controllers.py``. Both laws
compute a body moment ``M`` from ``(R, Rd, w, wd, dwd, J)``; every input may carry
arbitrary leading batch axes (vmap over agents/scenarios).

- :func:`so3_pd_tracking_control`: PD on SO(3) — Lee, Leok, McClamroch, "Geometric
  tracking control of a quadrotor UAV on SE(3)", CDC 2010, Eqs. (10), (11), (16)
  (reference :18-43).
- :func:`so3_sm_tracking_control`: finite-time sliding-mode law — Lee, "Geometric
  Control of Quadrotor UAVs Transporting a Cable-Suspended Rigid Body", TCST 2018,
  Eqs. (34)-(36) (reference :60-95).

Deviation from the reference (deliberate): the reference evaluates its fractional
Jacobian lambda with swapped arguments (``T(e_R, r)`` against signature ``T(r, y)``,
``so3_tracking_controllers.py:87-92``) and scales it by ``l_s`` where the sliding
surface uses ``l_R``; we implement the mathematically intended term
``l_R * r * diag((|e_R| + eps)^(r-1))`` from differentiating the sliding surface
``s = e_Omega + k_R e_R + l_R sign(e_R)|e_R|^r``.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

from tpu_aerial_transport.ops import lie

_EPS = 1e-6


@struct.dataclass
class So3PDParams:
    k_R: float = 0.25
    k_Omega: float = 0.075


@struct.dataclass
class So3SMParams:
    r: float = 0.5
    k_R: float = 1.415
    l_R: float = 0.707
    k_s: float = 0.113
    l_s: float = 0.057


def _errors(R, Rd, w, wd):
    """Attitude error ``e_R = 1/2 vee(Rd^T R - R^T Rd)`` and rate error
    ``e_Omega = w - R^T Rd wd`` (shared by both laws)."""
    Q = jnp.swapaxes(Rd, -1, -2) @ R  # Rd^T R
    e_R = 0.5 * lie.vee(Q - jnp.swapaxes(Q, -1, -2))
    RtRd = jnp.swapaxes(Q, -1, -2)  # R^T Rd
    e_Omega = w - jnp.einsum("...ij,...j->...i", RtRd, wd)
    return e_R, e_Omega, RtRd


def _feedforward(RtRd, w, wd, dwd, J):
    """Gyroscopic + reference feed-forward term shared by both laws:
    ``w x Jw - J (hat(w) R^T Rd wd - R^T Rd dwd)``."""
    Jw = jnp.einsum("...ij,...j->...i", J, w)
    RtRd_wd = jnp.einsum("...ij,...j->...i", RtRd, wd)
    RtRd_dwd = jnp.einsum("...ij,...j->...i", RtRd, dwd)
    inner = jnp.cross(w, RtRd_wd) - RtRd_dwd
    return jnp.cross(w, Jw) - jnp.einsum("...ij,...j->...i", J, inner)


def so3_pd_tracking_control(R, Rd, w, wd, dwd, J, params: So3PDParams):
    e_R, e_Omega, RtRd = _errors(R, Rd, w, wd)
    return (
        -params.k_R * e_R
        - params.k_Omega * e_Omega
        + _feedforward(RtRd, w, wd, dwd, J)
    )


def so3_sm_tracking_control(R, Rd, w, wd, dwd, J, params: So3SMParams):
    r = params.r
    e_R, e_Omega, RtRd = _errors(R, Rd, w, wd)
    trace = RtRd[..., 0, 0] + RtRd[..., 1, 1] + RtRd[..., 2, 2]
    eye = jnp.eye(3, dtype=R.dtype)
    E = 0.5 * (trace[..., None, None] * eye - RtRd)

    def S(y):
        return jnp.power(jnp.abs(y), r) * jnp.sign(y)

    s = e_Omega + params.k_R * e_R + params.l_R * S(e_R)
    # d/dt [l_R S(r, e_R)] = l_R r diag((|e_R|+eps)^(r-1)) de_R,  de_R = E e_Omega.
    frac = jnp.power(jnp.abs(e_R) + _EPS, r - 1.0)
    E_eOm = jnp.einsum("...ij,...j->...i", E, e_Omega)
    JE = jnp.einsum("...ij,...j->...i", J, E_eOm)
    J_frac = jnp.einsum("...ij,...j->...i", J, frac * E_eOm)
    return (
        -params.k_s * s
        - params.l_s * S(s)
        - params.k_R * JE
        - params.l_R * r * J_frac
        + _feedforward(RtRd, w, wd, dwd, J)
    )
