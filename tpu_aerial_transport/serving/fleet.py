"""Fault-tolerant serving fleet: replica supervision, health-checked
routing, and failover re-dispatch over N :class:`ScenarioServer` replica
processes.

The fleet tier composes the standing substrate instead of inventing new
machinery:

- **heartbeat leases** ride the fsync'd jsonl channel
  (``obs.export.jsonl_append`` is pinned cross-process-atomic by
  tests/test_obs_export.py): each replica appends ``fleet_event``
  heartbeat rows; the supervisor drives the per-replica health machine
  ``starting/up → suspect → down → restarting`` (→ ``quarantined``)
  from missed leases and from classified ``BackendError`` kinds
  reported upward — infra kinds strike a per-replica circuit breaker
  (``resilience.backend.BREAKER_KINDS``), ``compile_error`` never does;
- **restarts** are bounded by ``resilience.backend.BackoffPolicy``,
  with poison-replica quarantine after K restart cycles;
- **routing** is ``(family, bucket)`` consistent hashing
  (:class:`HashRing`) so each replica's compiled-shape working set and
  AOT bundle stay hot: one family+shape key always lands on the same
  live replica, and a replica loss moves ONLY that replica's keys;
- **failover re-dispatch** replays a dead replica's in-flight requests
  on a healthy replica ON THE SAME ``trace_id`` — the continuous-
  batching lane-independence contract makes the replayed result
  bit-identical to the uninterrupted run, and the front's open
  ``guard_fallback`` span (member = the request's trace) makes the
  failover an explicit ``retry`` segment in ``obs.trace.critical_path``;
- **chaos** is a seeded :class:`FleetFaultPlan` (the
  ``resilience.faults.FaultSchedule`` / ``TAT_BACKEND_FAULTS`` idiom:
  scheduled, deterministic, env-transportable) that
  ``tools/fleet_local.py --chaos`` turns into real SIGKILL/SIGTERM/
  wedge/error injections.

Module contract (same as ``resilience/backend.py``): NO jax import at
module scope — the front/supervisor run in a coordinator process that
must never pay device initialization; the one jax touch
(:func:`result_digest`) imports lazily inside a replica process that
already owns a runtime.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import os
import random
import re
import time

from tpu_aerial_transport.obs import trace as trace_mod
from tpu_aerial_transport.resilience import backend as backend_mod
from tpu_aerial_transport.serving import queue as queue_mod

# Replica health states (the supervisor's machine; every transition
# lands as a ``fleet_event`` row).
STARTING = "starting"        # spawned, no heartbeat yet (boot grace).
UP = "up"                    # lease current.
SUSPECT = "suspect"          # missed leases, still routable.
DOWN = "down"                # lease expired / breaker open / exit seen.
RESTARTING = "restarting"    # killed; respawn pending under backoff.
QUARANTINED = "quarantined"  # poison replica: K restart cycles burned.

ROUTABLE_STATES = frozenset({STARTING, UP, SUSPECT})

FLEET_FAULTS_ENV = "TAT_FLEET_FAULTS"
# Replica-side actions hit a replica process; CLIENT-side actions (the
# ISSUE-19 session storms — examples/serve_sessions.py) hit a session
# client instead: ``silent`` stops its heartbeats/steps (lease-eviction
# path), ``slow`` delays its next steps by ARG seconds (deadline-
# degradation path), ``duplicate`` re-sends its last step_seq
# (stale_step path), ``zombie`` keeps using its pre-eviction lease after
# the session was reclaimed (fence path). Same grammar; ``rR`` indexes
# the client for client actions.
FAULT_ACTIONS = ("sigkill", "sigterm", "wedge", "error",
                 "silent", "slow", "duplicate", "zombie")
CLIENT_FAULT_ACTIONS = frozenset({"silent", "slow", "duplicate",
                                  "zombie"})


def _emit_fn(sink):
    """Normalize a fleet-event sink: a MetricsWriter (anything with
    ``.emit``) gets ``fleet_event`` rows, a callable gets keyword
    fields, None is the zero-cost path."""
    if sink is None:
        return lambda **kw: None
    if hasattr(sink, "emit"):
        return lambda **kw: sink.emit("fleet_event", **kw)
    return lambda **kw: sink(**kw)


# ----------------------------------------------------------------------
# Consistent-hash routing.
# ----------------------------------------------------------------------

class HashRing:
    """Consistent hashing over replica ids with virtual nodes.

    Keys are ``(family, bucket)`` strings: all requests that will batch
    at one compiled shape route to one replica (its executable cache and
    bundle working set stay hot), and removing a replica moves ONLY the
    keys it owned (every other replica's shape set is undisturbed —
    pinned by tests/test_fleet.py)."""

    def __init__(self, nodes, vnodes: int = 64):
        self.vnodes = int(vnodes)
        self._points: list[tuple[int, object]] = sorted(
            (self._hash(f"{node}#{v}"), node)
            for node in nodes for v in range(self.vnodes)
        )
        self._keys = [h for h, _ in self._points]

    @staticmethod
    def _hash(s: str) -> int:
        return int.from_bytes(
            hashlib.sha256(str(s).encode()).digest()[:8], "big"
        )

    def route(self, key, alive=None):
        """The first live node clockwise from ``key``'s point; ``alive``
        restricts to a live subset (None = all). Returns None only when
        no live node exists."""
        if not self._points:
            return None
        idx = bisect.bisect_right(self._keys, self._hash(str(key)))
        n = len(self._points)
        for i in range(n):
            node = self._points[(idx + i) % n][1]
            if alive is None or node in alive:
                return node
        return None


def bucket_hint(pending: int, buckets) -> int:
    """The shape bucket a ``pending``-wide dispatch group will batch at:
    smallest admitting bucket, largest when oversubscribed (the
    ``serving.batcher.bucket_for`` rule, restated here so the front
    never imports the device-facing batcher)."""
    bs = sorted(int(b) for b in buckets)
    for b in bs:
        if pending <= b:
            return b
    return bs[-1]


# ----------------------------------------------------------------------
# Seeded chaos plan.
# ----------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FaultAction:
    """One scheduled fault: at ``t_s`` seconds into the storm, hit
    ``replica`` with ``action`` (sigkill/sigterm = signal the process
    group; wedge = stop the replica loop AND its heartbeats for ``arg``
    seconds; error = the replica reports a classified BackendError
    ``arg`` upward). For :data:`CLIENT_FAULT_ACTIONS` the ``replica``
    field indexes the session CLIENT the fault hits."""

    t_s: float
    replica: int
    action: str
    arg: str | None = None

    def token(self) -> str:
        base = f"{self.action}@{self.t_s:g}:r{self.replica}"
        return base + (f"={self.arg}" if self.arg is not None else "")


@dataclasses.dataclass(frozen=True)
class FleetFaultPlan:
    """A deterministic fleet chaos schedule (the ``FaultSchedule`` /
    ``TAT_BACKEND_FAULTS`` idiom at fleet scale): parse/print round-trips
    through the spec grammar ``ACTION@T:rR[=ARG],...`` so a plan travels
    through :data:`FLEET_FAULTS_ENV` to the harness."""

    actions: tuple[FaultAction, ...] = ()

    @classmethod
    def parse(cls, spec: str) -> "FleetFaultPlan":
        actions = []
        for token in (t.strip() for t in (spec or "").split(",")):
            if not token:
                continue
            head, _, arg = token.partition("=")
            try:
                act, _, where = head.partition("@")
                t_s, _, rep = where.partition(":")
                if act not in FAULT_ACTIONS or not rep.startswith("r"):
                    raise ValueError(token)
                actions.append(FaultAction(
                    t_s=float(t_s), replica=int(rep[1:]), action=act,
                    arg=arg or None,
                ))
            except (ValueError, IndexError):
                raise ValueError(
                    f"bad fault token {token!r} (grammar: "
                    f"ACTION@T:rR[=ARG], ACTION in {FAULT_ACTIONS})"
                ) from None
        return cls(actions=tuple(sorted(actions, key=lambda a: a.t_s)))

    def to_spec(self) -> str:
        return ",".join(a.token() for a in self.actions)

    @classmethod
    def from_env(cls, env=None) -> "FleetFaultPlan":
        return cls.parse((env or os.environ).get(FLEET_FAULTS_ENV, ""))

    @classmethod
    def seeded(cls, seed: int, n_replicas: int, *, t_span: float = 4.0,
               n_faults: int = 2,
               kinds=("sigkill", "wedge")) -> "FleetFaultPlan":
        """A seeded random storm: same seed => same plan (the chaos
        acceptance e2e's determinism precondition)."""
        rng = random.Random(seed)
        actions = []
        for _ in range(n_faults):
            act = kinds[rng.randrange(len(kinds))]
            arg = None
            if act in ("wedge", "slow"):
                arg = f"{rng.uniform(1.0, 3.0):.2f}"
            elif act == "error":
                infra = sorted(backend_mod.BREAKER_KINDS)
                arg = infra[rng.randrange(len(infra))]
            actions.append(FaultAction(
                t_s=round(rng.uniform(0.2, t_span), 2),
                replica=rng.randrange(n_replicas), action=act, arg=arg,
            ))
        return cls(actions=tuple(sorted(actions, key=lambda a: a.t_s)))

    def due(self, t_from: float, t_to: float) -> list[FaultAction]:
        """Actions scheduled in ``[t_from, t_to)`` (storm-relative
        seconds) — the harness polls this each round."""
        return [a for a in self.actions if t_from <= a.t_s < t_to]


# ----------------------------------------------------------------------
# Autoscaling signal (ISSUE-19 satellite: the last PR-16 sliver).
# ----------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Thresholds + hysteresis for :class:`AutoscaleSignal`.

    The up/down thresholds deliberately leave a dead band (up at depth
    >= 16 or occupancy >= 0.85, down only at depth <= 0 AND occupancy
    <= 0.25 AND no live sessions) and a switch needs ``confirm``
    CONSECUTIVE raw observations agreeing — an input oscillating around
    one threshold can never flap the confirmed hint
    (tests/test_sessions.py pins it)."""

    up_queue_depth: int = 16
    up_occupancy: float = 0.85
    down_queue_depth: int = 0
    down_occupancy: float = 0.25
    down_sessions: int = 0
    confirm: int = 3
    # SLO budget-burn input (the obs/live.py SLOEngine's fast-window
    # burn rate): a fleet burning its error budget at the paging rate
    # is underprovisioned even when the queue looks shallow — the
    # default up threshold matches the engine's fast-burn page
    # (obs.live.DEFAULT_BURN_RATES[0]); scale_down additionally
    # requires the burn at/below sustainable (<= 1.0 = burning no
    # faster than the budget accrues). burn_rate=None (no engine, or
    # no traffic) leaves both gates unchanged.
    up_burn_rate: float = 14.4
    down_burn_rate: float = 1.0


class AutoscaleSignal:
    """Hysteresis'd scale-up/down hint from the telemetry the SLO
    accountant already emits: queue depth (front admission), batch
    occupancy (the serving ``batch_boundary`` rows), and the live
    closed-loop session count (a session is standing capacity demand
    even when momentarily idle). Pure host logic on explicit inputs —
    no clock, no device — so it unit-tests with bare numbers. The
    confirmed ``hint`` is one of ``scale_up``/``steady``/``scale_down``
    and an ``autoscale`` fleet event lands ONLY when it changes."""

    HINTS = ("scale_up", "steady", "scale_down")

    def __init__(self, policy: AutoscalePolicy | None = None, emit=None):
        # `is None`, not truthiness (the HL010 rule): a falsy-but-real
        # policy/sink must still be used.
        self.policy = AutoscalePolicy() if policy is None else policy
        self.emit = _emit_fn(emit)
        self.hint = "steady"
        self.last: dict = {}
        self._candidate = "steady"
        self._streak = 0

    def _raw(self, queue_depth: int, occupancy, sessions: int,
             burn_rate) -> str:
        p = self.policy
        if (queue_depth >= p.up_queue_depth
                or (occupancy is not None
                    and occupancy >= p.up_occupancy)
                or (burn_rate is not None
                    and burn_rate >= p.up_burn_rate)):
            return "scale_up"
        if (queue_depth <= p.down_queue_depth
                and sessions <= p.down_sessions
                and (occupancy is None or occupancy <= p.down_occupancy)
                and (burn_rate is None
                     or burn_rate <= p.down_burn_rate)):
            return "scale_down"
        return "steady"

    def observe(self, *, queue_depth: int = 0, occupancy=None,
                sessions: int = 0, burn_rate=None) -> str:
        """Feed one telemetry observation; returns the CONFIRMED hint
        (which moves only after ``policy.confirm`` consecutive raw
        observations agree on a different value). ``burn_rate`` is the
        SLO engine's fast-window error-budget burn (obs/live.py;
        None when no engine is wired or no traffic is in the window) —
        it rides the SAME confirm-N hysteresis as every other input,
        so a burn spike flaps nothing."""
        raw = self._raw(int(queue_depth), occupancy, int(sessions),
                        burn_rate)
        if raw != self._candidate:
            self._candidate = raw
            self._streak = 1
        else:
            self._streak += 1
        self.last = {"queue_depth": int(queue_depth),
                     "occupancy": occupancy, "sessions": int(sessions),
                     "burn_rate": burn_rate, "raw": raw}
        if raw != self.hint and self._streak >= self.policy.confirm:
            self.hint = raw
            self.emit(kind="autoscale", hint=raw,
                      queue_depth=int(queue_depth), occupancy=occupancy,
                      sessions=int(sessions), burn_rate=burn_rate)
        return self.hint


# ----------------------------------------------------------------------
# Replica supervisor.
# ----------------------------------------------------------------------

class ReplicaHealth:
    """One replica's lease + breaker state (supervisor-internal)."""

    __slots__ = ("replica", "state", "last_heartbeat", "hb_seen",
                 "started_at", "restarts", "restart_at", "breaker",
                 "hb_count")

    def __init__(self, replica, now: float, breaker):
        self.replica = replica
        self.state = STARTING
        self.last_heartbeat: float | None = None
        self.hb_seen = False
        self.started_at = now
        self.restarts = 0          # completed kill→respawn cycles.
        self.restart_at: float | None = None
        self.breaker = breaker
        self.hb_count = 0


class ReplicaSupervisor:
    """Drive each replica's health machine from heartbeats, classified
    errors, and observed exits; hand the harness a list of actions to
    execute (``kill`` / ``failover`` / ``spawn`` / ``quarantine``).

    The supervisor is pure host logic on an injected clock — the
    subprocess side lives in ``tools/fleet_local.py``; tier-1 tests
    drive this class with a fake clock and no processes at all."""

    def __init__(self, replica_ids, *, lease_s: float = 1.0,
                 suspect_misses: int = 2, down_misses: int = 5,
                 boot_grace_s: float = 120.0,
                 backoff: backend_mod.BackoffPolicy | None = None,
                 quarantine_after: int = 3,
                 breaker_threshold: int = 3,
                 clock=time.monotonic, emit=None,
                 rng: random.Random | None = None):
        if suspect_misses >= down_misses:
            raise ValueError("suspect_misses must be < down_misses")
        self.lease_s = float(lease_s)
        self.suspect_misses = suspect_misses
        self.down_misses = down_misses
        self.boot_grace_s = float(boot_grace_s)
        self.backoff = backoff or backend_mod.BackoffPolicy(
            initial_s=0.5, factor=2.0, max_s=30.0, jitter=0.0
        )
        self.quarantine_after = int(quarantine_after)
        self.clock = clock
        self.emit = _emit_fn(emit)
        self._rng = rng or random.Random(0)
        self._seq = 0
        self.replicas: dict = {}
        now = self.clock()
        for rid in replica_ids:
            self.replicas[rid] = ReplicaHealth(
                rid, now,
                backend_mod.CircuitBreaker(
                    failure_threshold=breaker_threshold,
                    policy=self.backoff, clock=clock, rng=self._rng,
                ),
            )

    # ---------------------------------------------------- transitions --
    def _transition(self, h: ReplicaHealth, to: str, reason: str) -> None:
        if h.state == to:
            return
        self._seq += 1
        self.emit(kind="transition", replica=h.replica,
                  from_state=h.state, to_state=to, reason=reason,
                  seq=self._seq)
        h.state = to

    def state(self, rid) -> str:
        return self.replicas[rid].state

    def routable(self) -> list:
        return [rid for rid, h in self.replicas.items()
                if h.state in ROUTABLE_STATES]

    # -------------------------------------------------------- signals --
    def heartbeat(self, rid, now: float | None = None,
                  seq: int | None = None) -> None:
        h = self.replicas[rid]
        if h.state == QUARANTINED:
            return  # a poison replica's zombie heartbeat changes nothing.
        now = self.clock() if now is None else now
        h.last_heartbeat = now
        h.hb_seen = True
        h.hb_count += 1
        if h.state in (STARTING, SUSPECT, DOWN, RESTARTING):
            self._transition(h, UP, "heartbeat")

    def report_error(self, rid, kind: str, detail: str = "") -> list:
        """A classified ``BackendError`` kind surfaced by a replica.
        Infra kinds strike the replica's circuit breaker (the PR-6
        taxonomy boundary: ``compile_error`` NEVER does — a program bug
        must not get a healthy replica killed). An opened breaker
        declares the replica down. Returns harness actions."""
        h = self.replicas[rid]
        self.emit(kind="replica_error", replica=rid, error_kind=kind,
                  detail=detail[:300])
        if kind not in backend_mod.BREAKER_KINDS:
            return []
        h.breaker.record_failure(kind)
        if (h.breaker.state == backend_mod.OPEN
                and h.state in ROUTABLE_STATES):
            return self._declare_down(
                h, f"circuit open ({kind})", self.clock()
            )
        return []

    def notify_exit(self, rid, returncode: int | None = None) -> list:
        """The harness saw the replica process exit. Returns actions."""
        h = self.replicas[rid]
        if h.state in ROUTABLE_STATES:
            return self._declare_down(
                h, f"process exited rc={returncode}", self.clock()
            )
        return []

    def _declare_down(self, h: ReplicaHealth, reason: str,
                      now: float) -> list:
        self._transition(h, DOWN, reason)
        actions = [("kill", h.replica), ("failover", h.replica)]
        h.restarts += 1
        h.hb_seen = False
        if h.restarts > self.quarantine_after:
            self._transition(
                h, QUARANTINED,
                f"poison replica: {h.restarts - 1} restart cycles burned",
            )
            self.emit(kind="quarantine", replica=h.replica,
                      cycles=h.restarts - 1)
            actions.append(("quarantine", h.replica))
        else:
            delay = self.backoff.delay(h.restarts - 1, self._rng)
            h.restart_at = now + delay
            self._transition(h, RESTARTING, reason)
            self.emit(kind="restart", replica=h.replica,
                      attempt=h.restarts, delay_s=round(delay, 3))
        return actions

    # ----------------------------------------------------------- tick --
    def tick(self, now: float | None = None) -> list:
        """Advance lease accounting. Returns harness actions:
        ``("kill", rid)`` / ``("failover", rid)`` / ``("spawn", rid)`` /
        ``("quarantine", rid)``."""
        now = self.clock() if now is None else now
        actions: list = []
        for h in self.replicas.values():
            if h.state in (UP, SUSPECT, STARTING):
                if not h.hb_seen:
                    if now - h.started_at >= self.boot_grace_s:
                        actions += self._declare_down(
                            h, "boot deadline exceeded", now
                        )
                    continue
                misses = (now - h.last_heartbeat) / self.lease_s
                if misses >= self.down_misses:
                    actions += self._declare_down(
                        h, f"{int(misses)} missed heartbeat leases", now
                    )
                elif misses >= self.suspect_misses and h.state == UP:
                    self._transition(
                        h, SUSPECT,
                        f"{int(misses)} missed heartbeat leases",
                    )
            elif h.state == RESTARTING:
                if h.restart_at is not None and now >= h.restart_at:
                    h.restart_at = None
                    h.started_at = now
                    actions.append(("spawn", h.replica))
                elif (h.restart_at is None
                      and not h.hb_seen
                      and now - h.started_at >= self.boot_grace_s):
                    # The respawn itself never booted: burn another cycle.
                    actions += self._declare_down(
                        h, "respawn boot deadline exceeded", now
                    )
        return actions


# ----------------------------------------------------------------------
# Fleet front: admission + routing + failover bookkeeping.
# ----------------------------------------------------------------------

class FleetFront:
    """ONE admission front over N replicas.

    Owns the hardened :class:`AdmissionQueue` (per-tenant token buckets,
    weighted-fair priority dequeue), routes admitted requests by
    ``(family, bucket)`` through the :class:`HashRing`, tracks in-flight
    ownership, and on a replica death re-dispatches that replica's
    incomplete requests to a healthy one — same ``request_id``, same
    ``trace_id``, full replay (bit-identical by the lane-independence
    contract). Completion is front-authoritative: the FIRST result row
    per request wins; any duplicate (a restarted replica re-serving work
    that was already failed over) is counted, emitted as a
    ``duplicate_result`` fleet event, and dropped — no request is ever
    double-completed.

    Transport-agnostic: ``send(replica_id, op_dict)`` is injected
    (``tools/fleet_local.py`` appends to per-replica inbox jsonls;
    tests use in-memory queues)."""

    def __init__(self, replica_ids, coverage, *, send,
                 buckets=(8, 16, 32), capacity: int = 1024,
                 tenants: dict | None = None,
                 supervisor: ReplicaSupervisor | None = None,
                 clock=time.monotonic, metrics=None, tracer=None,
                 autoscale_policy: AutoscalePolicy | None = None,
                 hub=None, slo=None):
        self.replica_ids = list(replica_ids)
        self.send = send
        self.buckets = tuple(sorted(buckets))
        self.supervisor = supervisor
        self.clock = clock
        self.metrics = metrics
        self.tracer = tracer
        # hub/slo=None is the default and costs nothing per request
        # (every touch point is behind an ``is not None`` guard).
        # ``slo`` duck-types obs/live.py's SLOEngine: ``max_burn()``
        # feeds the autoscale burn-rate gate each pump round.
        self.hub = hub
        self.slo = slo
        self.emit_fleet = _emit_fn(metrics)
        self.ring = HashRing(self.replica_ids)
        self.queue = queue_mod.AdmissionQueue(
            coverage, capacity=capacity, clock=clock,
            emit=self._emit_serving, tracer=tracer, tenants=tenants,
            hub=hub,
        )
        self.tickets: dict[str, queue_mod.Ticket] = {}
        self.requests: dict[str, queue_mod.ScenarioRequest] = {}
        self.inflight: dict[str, object] = {}   # request_id -> replica.
        self.results: dict[str, dict] = {}      # first result row wins.
        self.duplicates: list[dict] = []
        self.failovers = 0
        self._failover_spans: dict[str, object] = {}
        # Closed-loop sessions homed through this front:
        # session_id -> {"replica", "family", "trace_id"} (replica None
        # while orphaned by a full-fleet outage — pump() re-homes).
        self.sessions: dict[str, dict] = {}
        self._rehome_spans: dict[str, object] = {}
        self.autoscale = AutoscaleSignal(policy=autoscale_policy,
                                         emit=metrics)

    # --------------------------------------------------------- events --
    def _emit_serving(self, **fields) -> None:
        if self.metrics is not None:
            self.metrics.emit("serving_event", **fields)
        if self.hub is not None:
            # The fields dict already exists for the journal emit — the
            # hub consumes it in place (zero marginal allocation).
            self.hub.ingest_serving(fields)
        if (fields.get("kind") == "rejected"
                and fields.get("reason") == queue_mod.REASON_TENANT_RATE):
            # The throttle ALSO lands in the fleet vocabulary: the
            # run_health fleet section's per-tenant throttle counts.
            self.emit_fleet(kind="tenant_rejected",
                            tenant=fields.get("tenant"),
                            request_id=fields.get("request_id"),
                            reason=queue_mod.REASON_TENANT_RATE)

    # --------------------------------------------------------- submit --
    def submit(self, request: queue_mod.ScenarioRequest
               ) -> queue_mod.Ticket:
        """Admit or reject (structured, never an exception — the chaos
        storm's front loop runs this unguarded by design)."""
        ticket = self.queue.submit(request)
        self.tickets[ticket.request.request_id] = ticket
        if ticket.status == queue_mod.PENDING:
            # ticket.request, NOT the caller's argument: admission mints
            # trace_id onto a replaced request (the server.py rule).
            self.requests[ticket.request.request_id] = ticket.request
        return ticket

    # ------------------------------------------------------- dispatch --
    def routable(self) -> list:
        if self.supervisor is None:
            return list(self.replica_ids)
        return self.supervisor.routable()

    def pump(self) -> int:
        """One routing round: expire deadlines, then flush each family's
        pending group to the replica owning its ``(family, bucket)``
        key. Requests HOLD at the front while no replica is routable
        (nothing is lost during a full-fleet outage). Returns the number
        of requests dispatched."""
        for t in self.queue.expire_deadlines():
            self.requests.pop(t.request.request_id, None)
        alive = set(self.routable())
        burn = self.slo.max_burn() if self.slo is not None else None
        self.autoscale.observe(queue_depth=self.queue.depth(),
                               sessions=len(self.sessions),
                               burn_rate=burn)
        if not alive:
            return 0
        # Sessions orphaned by a full-fleet outage re-home as soon as a
        # replica is routable again (same hold-at-the-front rule as
        # requests).
        for sid, rec in sorted(self.sessions.items()):
            if rec["replica"] is None:
                self._rehome_session(sid, rec, None, alive)
        sent = 0
        for family in self.queue.families_pending():
            group = self.queue.take(family, self.queue.depth(family))
            bucket = bucket_hint(len(group), self.buckets)
            target = self.ring.route(f"{family}:{bucket}", alive)
            for ticket in group:
                self._dispatch(ticket.request, target)
                ticket.slo.t_admit = self.clock()
                if ticket.trace is not None:
                    ticket.trace.admitted(replica=str(target))
                sent += 1
        return sent

    def _dispatch(self, request, replica) -> None:
        self.inflight[request.request_id] = replica
        self.send(replica, {"op": "submit", "request": request.to_json()})

    # ------------------------------------------------------- sessions --
    def open_session(self, session_id: str, family: str,
                     trace_id: str | None = None):
        """Home a closed-loop session: sessions route by ``session_id``
        (NOT family:bucket — a session must stay on one replica so its
        lease/watermark table is local) and the binding persists until
        close or re-home. Returns the owning replica, or None when no
        replica is routable (the caller retries after the fleet heals)."""
        alive = set(self.routable())
        if not alive:
            return None
        sid = str(session_id)
        target = self.ring.route(f"session:{sid}", alive)
        self.sessions[sid] = {"replica": target, "family": family,
                              "trace_id": trace_id}
        self.send(target, {
            "op": "session_open", "session_id": sid, "family": family,
            **({"trace_id": trace_id} if trace_id else {}),
        })
        return target

    def session_replica(self, session_id):
        rec = self.sessions.get(str(session_id))
        return None if rec is None else rec["replica"]

    def close_session(self, session_id: str) -> None:
        rec = self.sessions.pop(str(session_id), None)
        if rec is not None and rec["replica"] is not None:
            self.send(rec["replica"], {"op": "session_close",
                                       "session_id": str(session_id)})

    def _rehome_session(self, sid: str, rec: dict, from_replica,
                        alive: set) -> None:
        """Move one session to a live replica on the SAME trace_id, the
        failover span held open until the session's next result arrives
        (the PR-16 pattern — the re-serve shows up as an explicit retry
        segment on the session's trace)."""
        target = (self.ring.route(f"session:{sid}", alive)
                  if alive else None)
        if (self.tracer is not None and rec.get("trace_id") is not None
                and sid not in self._rehome_spans):
            self._rehome_spans[sid] = self.tracer.begin(
                trace_mod.GUARD_FALLBACK, parent=None,
                trace_id=rec["trace_id"], members=[rec["trace_id"]],
                session_id=sid, failover=True,
                from_replica=str(from_replica), to_replica=str(target),
            )
        rec["replica"] = target  # None = orphaned; pump() retries.
        if target is not None:
            self.send(target, {
                "op": "session_rehome", "session_id": sid,
                "family": rec["family"],
                **({"trace_id": rec["trace_id"]}
                   if rec.get("trace_id") else {}),
            })
        if self.metrics is not None:
            self.metrics.emit("session_event", kind="rehomed",
                              session_id=sid,
                              from_replica=str(from_replica),
                              to_replica=str(target))

    # ------------------------------------------------------- failover --
    def failover(self, dead_replica) -> list[str]:
        """Re-dispatch every incomplete request owned by
        ``dead_replica`` to a healthy replica, on the SAME trace_id.
        The open ``guard_fallback`` span (member = the request's trace)
        runs until the re-served completion arrives, so the critical
        path attributes the whole re-serve to the ``retry`` segment."""
        t_detect = self.clock()
        alive = set(self.routable()) - {dead_replica}
        moved: list[str] = []
        for rid, owner in sorted(self.inflight.items()):
            if owner != dead_replica or rid in self.results:
                continue
            request = self.requests.get(rid)
            if request is None:
                continue
            # Best effort: the restarted replica must not re-serve work
            # that moved (a lost cancel only costs a deduped duplicate).
            self.send(dead_replica,
                      {"op": "cancel", "request_id": rid})
            bucket = bucket_hint(1, self.buckets)
            target = (self.ring.route(f"{request.family}:{bucket}", alive)
                      if alive else None)
            if self.tracer is not None and request.trace_id is not None:
                span = self.tracer.begin(
                    trace_mod.GUARD_FALLBACK, parent=None,
                    trace_id=request.trace_id,
                    members=[request.trace_id], request_id=rid,
                    failover=True, from_replica=str(dead_replica),
                    to_replica=str(target),
                )
                self._failover_spans[rid] = span
            if target is None:
                # Full-fleet outage: hold at the front; the next pump()
                # with a routable replica re-dispatches.
                self.inflight.pop(rid, None)
                self.queue._pending.setdefault(
                    request.family, {}
                ).setdefault(request.tenant, []).append(
                    self.tickets[rid]
                )
            else:
                self._dispatch(request, target)
            self.failovers += 1
            latency = self.clock() - t_detect
            self.emit_fleet(
                kind="failover", request_id=rid,
                from_replica=str(dead_replica), to_replica=str(target),
                trace_id=request.trace_id, latency_s=round(latency, 6),
            )
            moved.append(rid)
        # Re-home the dead replica's closed-loop sessions too (their
        # lease/watermark tables restore replica-side from the journal;
        # the front only moves the binding).
        for sid, rec in sorted(self.sessions.items()):
            if rec["replica"] == dead_replica:
                self._rehome_session(sid, rec, dead_replica, alive)
        return moved

    # ------------------------------------------------------ completion --
    def deliver_result(self, row: dict) -> bool:
        """One replica outbox row ({request_id, status, digest, ...}).
        First result wins; duplicates are dropped and counted. Returns
        True when the row resolved a ticket."""
        rid = row.get("request_id")
        if rid is None or rid in self.results:
            self.duplicates.append(row)
            self.emit_fleet(kind="duplicate_result", request_id=rid,
                            replica=str(row.get("replica")))
            return False
        self.results[rid] = row
        self.inflight.pop(rid, None)
        ticket = self.tickets.get(rid)
        status = row.get("status", queue_mod.COMPLETED)
        span = self._failover_spans.pop(rid, None)
        if span is not None:
            self.tracer.end(span, status=status)
        # A session-step result closes the session's held-open re-home
        # span: the new owner is provably serving it again. Rows SHOULD
        # carry their session id; the request_id fallback only fires on
        # the exact session-step rid shape minted by SessionHost
        # ({sid}.e{epoch}.s{seq:06d}, legacy pre-epoch form tolerated)
        # AND a prefix that names a session this front actually routes
        # — a caller-chosen one-shot rid that happens to contain '.s'
        # must never end another session's re-home span.
        sid = row.get("session")
        if sid is None and rid is not None:
            m = (re.match(r"^(.+)\.e\d+\.s\d{6}$", rid)
                 or re.match(r"^(.+)\.s\d{6}$", rid))
            if m is not None and m.group(1) in self.sessions:
                sid = m.group(1)
        if sid is not None:
            rspan = self._rehome_spans.pop(sid, None)
            if rspan is not None:
                self.tracer.end(rspan, status=status)
        if ticket is None or ticket.done:
            return False
        ticket.slo.t_complete = self.clock()
        ticket.steps_served = int(row.get("steps_served", 0))
        ticket.result = row.get("digest")
        if ticket.trace is not None:
            ticket.trace.resolve(status, replica=str(row.get("replica")))
        ticket._resolve(status, row.get("reason"))
        if self.metrics is not None:
            self.metrics.emit(
                "serving_event", kind=status, request_id=rid,
                family=ticket.request.family,
                tenant=ticket.request.tenant,
                replica=str(row.get("replica")),
                slo=ticket.slo.to_event(),
            )
        return True

    # ----------------------------------------------------------- stats --
    def unresolved(self) -> list[str]:
        return sorted(rid for rid, t in self.tickets.items()
                      if not t.done)

    def stats(self) -> dict:
        by_status: dict[str, int] = {}
        for t in self.tickets.values():
            by_status[t.status] = by_status.get(t.status, 0) + 1
        by_tenant: dict[str, dict] = {}
        for t in self.tickets.values():
            bt = by_tenant.setdefault(
                t.request.tenant, {"submitted": 0, "completed": 0,
                                   "rejected": 0}
            )
            bt["submitted"] += 1
            if t.status == queue_mod.COMPLETED:
                bt["completed"] += 1
            elif t.status == queue_mod.REJECTED:
                bt["rejected"] += 1
        return {
            "requests": len(self.tickets),
            **by_status,
            "failovers": self.failovers,
            "duplicates_dropped": len(self.duplicates),
            "tenants": by_tenant,
            "sessions": len(self.sessions),
            "autoscale": {"hint": self.autoscale.hint,
                          **self.autoscale.last},
        }


# ----------------------------------------------------------------------
# Result digest (replica-side; the cross-process bit-identity token).
# ----------------------------------------------------------------------

def result_digest(result) -> str:
    """sha256 over the result pytree's leaf bytes (+ shape/dtype) — the
    token the chaos acceptance compares against the fault-free run.
    Lazy jax import: only replica processes call this."""
    import jax
    import numpy as np

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(result):
        a = np.ascontiguousarray(np.asarray(leaf))
        h.update(str((a.shape, a.dtype.str)).encode())
        h.update(a.tobytes())
    return h.hexdigest()
