"""Closed-loop session serving: leases, heartbeats, fenced eviction,
and per-step deadline degradation on top of the continuous batcher.

The paper's controller is receding-horizon MPC — in production it is a
LOOP: a client streams its payload state every control step and needs
the next control back under a per-step deadline. :class:`SessionHost`
is that tier. A session is a named, leased binding between a client and
the serving stack; each accepted control step is served as ONE internal
chunk-length :class:`~tpu_aerial_transport.serving.queue
.ScenarioRequest` carrying the session's current (post-delta) state.
The batcher's lane-independence contract (a lane's result depends only
on its own state, never on batch composition or the global step offset
— tests/test_serving.py) is what makes this exact: the served per-step
control stream is bitwise equal to the offline rollout of the same
state stream, whatever else shared the batch.

Lease / fencing state machine::

            open()                        heartbeat()/step()
    (none) ───────► LIVE(lease l_e) ◄──────────────────────┐
                      │    │ renew: expires_at = now + TTL ┘
         TTL expires  │    │
      (sweep: evict,  │    │ open() again (reconnect):
       fence l_e)     │    │   NEW lease l_{e+1}, old l_e FENCED
                      ▼    ▼
                   EVICTED / superseded — l_e ∈ fenced set
                      │
        step/heartbeat│with l_e  ──►  structured ``lease_fenced``
                      ▼               rejection (never a lane write)
                   close() ──► CLOSED (lease fenced)

Every check a zombie could race happens HERE, before any server
interaction: a stale token is rejected without touching the admission
queue, the batch, or the journal — so a reclaimed lane can never see a
write from a fenced client (tests/test_sessions.py pins the absence of
even a journaled ``serving_request``). Fencing cuts BOTH directions:
step identities carry the lease epoch (``{sid}.e{epoch}.s{seq}``), and
a step submitted by a superseded incarnation that resolves after a
reconnect is dropped on the floor session-side — it can never refresh
the new incarnation's hold-last control or lane bookkeeping. Eviction
itself needs no device action in this model: the session's lane claim
ends at its in-flight step's chunk boundary, where the standard
boundary machinery (``serving/lanes.py`` surgery) reclaims the lane as
pristine filler or hands it to a late joiner.

Per-step SLOs degrade, never raise: a step whose inner request misses
its deadline resolves ``completed`` with rung ``hold_last`` (the
serving-layer mirror of PR 1's fallback ladder — the client keeps
applying the last control it was served; before the FIRST served
control the rung is ``no_control``: there is nothing honest to hold),
the miss classified ``in_queue``/``in_flight`` by the batch SLO
machinery and journaled. The session's state stream is UNAFFECTED:
state advances by client deltas only, so a degraded step does not fork
the bitwise contract. An ADMISSION rejection (queue full, tenant
throttled) consumes nothing: watermark and state roll back, nothing is
journaled, and the client retries the same seq — the two sides' views
of the state stream cannot diverge on a transient reject.

Crash safety rides the server's fsync'd ``serving_journal.jsonl``:
``session_open``/``session_step``/``session_evict``/``session_close``
events carry the full session table (lease epoch, step_seq watermark,
exact float64 state — json round-trips doubles exactly), so
:meth:`SessionHost.resume` on top of ``ScenarioServer.resume`` restores
live sessions bit-identically. Leases RE-ARM on resume (the monotonic
clock domain dies with the process — same rule as the server's deadline
re-arm). The ``session_step`` append lands AFTER admission accepts (the
commit is conditional), so the only crash gap is an admitted inner
request whose session_step never journaled: the client's retry of the
unacked seq reattaches to the restored inner ticket by request_id
instead of double-submitting; the defensive reverse path (journaled
session_step, no server record) still resubmits from the journaled
post-delta state.

Host-synchronous and lock-free by design (the server-loop discipline):
one thread drives ``open``/``heartbeat``/``step``/``pump``; the async
surface is the :class:`StepTicket`.
"""

from __future__ import annotations

import os
import re

import numpy as np

from tpu_aerial_transport.obs import trace as trace_mod
from tpu_aerial_transport.serving import queue as queue_mod

# Session lifecycle states.
LIVE = "live"
EVICTED = "evicted"
CLOSED = "closed"

# Per-step serving rungs (honest labels on every resolved step).
RUNG_SERVED = "served"
RUNG_HOLD_LAST = "hold_last"
# A deadline miss before the session was EVER served: there is no last
# control to hold, and a ``hold_last`` carrying None would read as a
# served control. The step still resolves timely — the client's cue to
# keep its own local fallback engaged.
RUNG_NO_CONTROL = "no_control"

DEFAULT_LEASE_S = 30.0


def _step_rid(session_id: str, epoch: int, step_seq: int) -> str:
    """The inner request_id of one session step. The lease EPOCH is
    part of the identity: open() on reconnect resets the step_seq
    watermark, so without it epoch N+1's step k would alias epoch N's —
    and resume's done-request dedup would silently swallow a new
    incarnation's in-flight step whose seq matched a completed old one.
    ``FleetFront.deliver_result`` parses this shape (and only this
    shape) when a replica row omits its session id."""
    return f"{session_id}.e{epoch}.s{step_seq:06d}"


_STEP_RID_RE = re.compile(r"^(?P<sid>.+)\.e(?P<epoch>\d+)\.s(?P<seq>\d{6})$")


def parse_step_rid(request_id) -> tuple[str, int, int] | None:
    """``(session_id, epoch, step_seq)`` when ``request_id`` is a
    canonical session-step id (the :func:`_step_rid` shape), else None —
    the strict inverse, for offline replays and row->session routing."""
    m = _STEP_RID_RE.match(str(request_id))
    if m is None:
        return None
    return m.group("sid"), int(m.group("epoch")), int(m.group("seq"))


def resolve_lease_s(configured=None) -> float:
    """Resolve the session lease TTL (seconds): the ``TAT_SESSION_LEASE_S``
    env force wins, then the configured value, then
    :data:`DEFAULT_LEASE_S`.

    TUNING CRITERION: the TTL is the eviction latency for a silent
    client — the longest a dead client's session lingers before its
    (at most one in-flight) lane claim returns to the filler pool. Set
    it a few multiples of the client's heartbeat period above the p99
    network+pump gap; BELOW that, healthy-but-slow clients flap through
    evict/reconnect (every flap fences a lease and re-admits), ABOVE
    it, capacity hides behind ghosts. The default (30 s) suits ~1 s
    control steps; interactive tests force fractions of a second.
    """
    forced = os.environ.get("TAT_SESSION_LEASE_S", "").strip()
    raw = forced if forced else configured
    if raw is None or raw == "":
        return DEFAULT_LEASE_S
    try:
        val = float(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"TAT_SESSION_LEASE_S / lease_s must be a positive number "
            f"of seconds, got {raw!r}"
        )
    if val <= 0:
        raise ValueError(
            f"TAT_SESSION_LEASE_S / lease_s must be > 0, got {val!r}"
        )
    return val


class Session:
    """One session's host-side record (the session-table row)."""

    def __init__(self, session_id: str, family: str, lease: str,
                 epoch: int, x, v, trace_id, deadline_s):
        self.session_id = session_id
        self.family = family
        self.lease = lease
        self.epoch = epoch              # lease epoch (token minting).
        self.status = LIVE
        self.step_seq = 0               # watermark: highest ACCEPTED seq.
        # Exact host-float64 state stream (client deltas accumulate
        # here; json journaling round-trips these bit-exactly).
        self.x = np.asarray(x, dtype=np.float64).reshape(-1).copy()
        self.v = np.asarray(v, dtype=np.float64).reshape(-1).copy()
        self.trace_id = trace_id
        self.deadline_s = deadline_s    # per-step default (None = none).
        self.expires_at = 0.0           # monotonic clock domain.
        self.last_renew_at = 0.0        # heartbeat-gap bookkeeping.
        self.last_result = None         # last SERVED control (hold-last).
        self.lane = None                # last observed lane binding.
        self.batch_id = None


class StepTicket:
    """The client's handle for one control step: resolves ``rejected``
    (structured reason — fenced lease / stale seq / admission reject;
    an admission reject consumes NOTHING, the client retries the same
    seq) or ``completed`` with an honest ``rung``: ``served`` (fresh
    result, deadline met), ``hold_last`` (deadline missed — ``result``
    is the last served control, ``missed`` classifies
    in_queue/in_flight), or ``no_control`` (deadline missed before any
    control was ever served — ``result`` is None, NOT a control)."""

    def __init__(self, session_id: str, step_seq: int, request_id: str,
                 epoch: int = 0):
        self.session_id = session_id
        self.step_seq = step_seq
        self.request_id = request_id
        self.epoch = epoch              # incarnation that submitted it.
        self.status = queue_mod.PENDING
        self.reason: str | None = None
        self.rung: str | None = None
        self.missed: str | None = None
        self.result = None
        self.latency_s: float | None = None
        self.ticket: queue_mod.Ticket | None = None  # inner request.
        self.span = None                # SESSION_STEP span (tracer on).

    @property
    def done(self) -> bool:
        return self.status != queue_mod.PENDING

    def __repr__(self) -> str:  # operator-facing.
        return (f"StepTicket({self.request_id}, {self.status}"
                + (f", {self.rung}" if self.rung else "")
                + (f", {self.reason}" if self.reason else "") + ")")


class SessionHost:
    """The session tier over one :class:`ScenarioServer`.

    Lock-free and host-synchronous like the server itself; every clock
    read is the server's (injectable, monotonic) ``clock`` so lease
    arithmetic is fake-clock testable and HL001-clean. ``lease_s``
    resolves through :func:`resolve_lease_s` (``TAT_SESSION_LEASE_S``).
    ``step_deadline_s`` is the default per-step SLO (a ``step`` call may
    override per step; None = no deadline)."""

    def __init__(self, server, *, lease_s=None, clock=None,
                 step_deadline_s: float | None = None):
        self.server = server
        self.lease_s = resolve_lease_s(lease_s)
        # `is None`, not truthiness: a falsy-but-callable clock (a Mock)
        # must still be used.
        self.clock = server.clock if clock is None else clock
        self.step_deadline_s = step_deadline_s
        self.sessions: dict[str, Session] = {}
        self._fenced: dict[str, str] = {}  # stale lease -> session_id.
        # In-flight steps: inner request_id -> StepTicket.
        self._steps: dict[str, StepTicket] = {}
        # Monotone counters (stats()/autoscale inputs).
        self.evictions = 0
        self.fence_rejections = 0
        self.stale_rejections = 0
        self.steps_accepted = 0
        self.steps_degraded = 0

    # ---------------------------------------------------------- events --
    def _emit_session(self, **fields) -> None:
        if self.server.metrics is not None:
            self.server.metrics.emit("session_event", **fields)
        if self.server.hub is not None:
            # Live hub fold (obs.live.MetricsHub): the fields dict is
            # this funnel's kwargs, so hub=None adds no per-step
            # allocation — the standing zero-cost contract.
            self.server.hub.ingest_session(fields)

    def _journal(self, obj: dict) -> None:
        if self.server.journal is not None:
            self.server.journal.append(obj)

    # ----------------------------------------------------------- lease --
    def _mint_lease(self, session_id: str, epoch: int) -> str:
        # Deterministic tokens (no randomness): resume must rebuild the
        # SAME fence set from the journal alone. Fencing is correctness
        # (split-brain), not secrecy — same trust model as request_id.
        return f"{session_id}:l{epoch}"

    def _renew(self, sess: Session, now: float) -> None:
        sess.last_renew_at = now
        sess.expires_at = now + self.lease_s

    def _evict(self, sess: Session, now: float) -> None:
        sess.status = EVICTED
        self._fenced[sess.lease] = sess.session_id
        self.evictions += 1
        gap = now - sess.last_renew_at
        self._journal({"event": "session_evict",
                       "session_id": sess.session_id,
                       "lease": sess.lease, "epoch": sess.epoch})
        self._emit_session(kind="evicted", session_id=sess.session_id,
                           lease=sess.lease, gap_s=round(gap, 6),
                           step_seq=sess.step_seq)

    def sweep(self) -> list[str]:
        """Evict every live session whose lease TTL expired (the silent-
        client path). Idempotent; called from every public entrypoint so
        a zombie can never slip a write in before its eviction lands."""
        now = self.clock()
        expired = [s for s in self.sessions.values()
                   if s.status == LIVE and now >= s.expires_at]
        for sess in expired:
            self._evict(sess, now)
        return [s.session_id for s in expired]

    # ------------------------------------------------------- lifecycle --
    def open(self, session_id: str, family: str, x0=(0.0, 0.0, 0.0),
             v0=(0.0, 0.0, 0.0), *, deadline_s: float | None = None,
             tenant: str = queue_mod.DEFAULT_TENANT) -> dict:
        """Open (or re-open) a session: mint a fresh lease and absolute
        state. Reconnecting under an existing session_id fences the
        previous lease — whether it was live (duplicate client: exactly
        one writer survives) or evicted (the normal reconnect) — and
        RESETS the step_seq watermark with the state (a reconnect is a
        new incarnation, not a replay window). Structured grant, never
        an exception: ``{"ok": False, "reason": ...}`` when the family
        has no serving coverage."""
        del tenant  # reserved: per-tenant session policy rides PR-16.
        now = self.clock()
        self.sweep()
        sid = str(session_id)
        if self.server._coverage(family) is None:
            return {"ok": False, "session_id": sid,
                    "reason": queue_mod.REASON_NO_COVERAGE}
        prev = self.sessions.get(sid)
        epoch = 0
        reconnect = False
        if prev is not None:
            epoch = prev.epoch + 1
            reconnect = True
            # The old incarnation's token joins the fence set even if it
            # was still live — exactly one lease per session_id can ever
            # write.
            self._fenced[prev.lease] = sid
        lease = self._mint_lease(sid, epoch)
        trace_id = (trace_mod.new_trace_id()
                    if self.server.tracer is not None else None)
        sess = Session(sid, family, lease, epoch, x0, v0, trace_id,
                       deadline_s)
        self._renew(sess, now)
        self.sessions[sid] = sess
        self._journal({
            "event": "session_open", "session_id": sid, "family": family,
            "lease": lease, "epoch": epoch,
            "x": [float(val) for val in sess.x],
            "v": [float(val) for val in sess.v],
            "deadline_s": (None if deadline_s is None
                           else float(deadline_s)),
            **({"trace_id": trace_id} if trace_id else {}),
        })
        self._emit_session(kind="opened", session_id=sid, lease=lease,
                           family=family, epoch=epoch,
                           reconnect=reconnect)
        return {"ok": True, "session_id": sid, "lease": lease,
                "expires_in_s": self.lease_s, "step_seq": 0}

    def _lease_ok(self, sid: str, lease: str) -> bool:
        sess = self.sessions.get(sid)
        return (sess is not None and sess.status == LIVE
                and lease == sess.lease)

    def heartbeat(self, session_id: str, lease: str) -> dict:
        """Renew the lease. A stale/unknown token (or an already-evicted
        session) gets the structured ``lease_fenced`` answer — the
        zombie's cue to re-``open``."""
        now = self.clock()
        self.sweep()
        sid = str(session_id)
        if not self._lease_ok(sid, lease):
            self.fence_rejections += 1
            self._emit_session(kind="fenced", session_id=sid,
                               op="heartbeat", lease=str(lease))
            return {"ok": False, "session_id": sid,
                    "reason": queue_mod.REASON_LEASE_FENCED}
        sess = self.sessions[sid]
        gap = now - sess.last_renew_at
        self._renew(sess, now)
        self._emit_session(kind="renewed", session_id=sid,
                           gap_s=round(gap, 6))
        return {"ok": True, "session_id": sid,
                "expires_in_s": self.lease_s}

    def close(self, session_id: str, lease: str) -> dict:
        """Graceful teardown: the lease is fenced immediately."""
        self.sweep()
        sid = str(session_id)
        if not self._lease_ok(sid, lease):
            self.fence_rejections += 1
            self._emit_session(kind="fenced", session_id=sid, op="close",
                               lease=str(lease))
            return {"ok": False, "session_id": sid,
                    "reason": queue_mod.REASON_LEASE_FENCED}
        sess = self.sessions[sid]
        sess.status = CLOSED
        self._fenced[sess.lease] = sid
        self._journal({"event": "session_close", "session_id": sid})
        self._emit_session(kind="session_closed", session_id=sid,
                           step_seq=sess.step_seq)
        return {"ok": True, "session_id": sid}

    # ------------------------------------------------------------ steps --
    def step(self, session_id: str, lease: str, step_seq: int,
             dx=(0.0, 0.0, 0.0), dv=(0.0, 0.0, 0.0), *,
             deadline_s: float | None = None) -> StepTicket:
        """One control step: ``(session_id, lease, step_seq, x/v delta)``.

        The validation ladder runs ENTIRELY before any server
        interaction — fence first (a stale token must not even be able
        to leak information about the session's progress), then the
        step sequence — and rejects structurally, never raising into
        the caller's loop:

        1. fenced/unknown/expired lease  -> ``lease_fenced``
        2. ``step_seq != watermark + 1`` -> ``stale_step`` (replay or
           out-of-order; the watermark does not move)

        An accepted step advances the watermark, applies the delta to
        the session's float64 state, submits one chunk-length internal
        request whose result is this step's control, and journals the
        post-delta state. The watermark/delta commit is conditional on
        ADMISSION accepting the inner request: a step rejected at
        admission (queue full, tenant throttled) rolls back and
        journals nothing — the seq is NOT consumed and the client
        retries the same step, so the client's and server's views of
        the state stream cannot diverge on a transient rejection. The
        request_id carries the lease epoch (``{sid}.e{epoch}.s{seq}``)
        so step identities are unique across reconnect incarnations —
        resume's done-request dedup and in-flight reattachment can
        never confuse epoch N's step k with epoch N+1's."""
        self.sweep()
        sid = str(session_id)
        seq = int(step_seq)
        if not self._lease_ok(sid, lease):
            self.fence_rejections += 1
            step = StepTicket(sid, seq, f"{sid}.s{seq:06d}")
            step.status = queue_mod.REJECTED
            step.reason = queue_mod.REASON_LEASE_FENCED
            self._emit_session(kind="fenced", session_id=sid, op="step",
                               step_seq=seq, lease=str(lease))
            return step
        sess = self.sessions[sid]
        step = StepTicket(sid, seq, _step_rid(sid, sess.epoch, seq),
                          epoch=sess.epoch)
        if seq != sess.step_seq + 1:
            self.stale_rejections += 1
            step.status = queue_mod.REJECTED
            step.reason = queue_mod.REASON_STALE_STEP
            self._emit_session(kind="stale_step", session_id=sid,
                               step_seq=seq,
                               expected=sess.step_seq + 1)
            return step

        now = self.clock()
        self._renew(sess, now)  # a stepping client is a live client.
        eff_deadline = (deadline_s if deadline_s is not None
                        else sess.deadline_s if sess.deadline_s is not None
                        else self.step_deadline_s)
        # Tentative commit: the delta/watermark become durable only if
        # admission accepts the inner request.
        prev = (sess.step_seq, sess.x, sess.v)
        sess.step_seq = seq
        sess.x = sess.x + np.asarray(dx, dtype=np.float64).reshape(-1)
        sess.v = sess.v + np.asarray(dv, dtype=np.float64).reshape(-1)
        self._submit_step(sess, step, eff_deadline)
        if step.status == queue_mod.REJECTED:
            # Admission rejected: roll back so the seq is retryable and
            # the unserved delta never enters the state stream (or the
            # journal — nothing was written for this step).
            sess.step_seq, sess.x, sess.v = prev
            return step
        self._journal({
            "event": "session_step", "session_id": sid, "step_seq": seq,
            "epoch": sess.epoch, "request_id": step.request_id,
            "x": [float(val) for val in sess.x],
            "v": [float(val) for val in sess.v],
            "deadline_s": (None if eff_deadline is None
                           else float(eff_deadline)),
        })
        return step

    def _submit_step(self, sess: Session, step: StepTicket,
                     deadline_s: float | None) -> None:
        """Build + submit the step's internal chunk request and open its
        SESSION_STEP span. Shared by ``step`` and resume's replay of
        journaled-but-unsubmitted steps."""
        fam = self.server.families[sess.family]
        if self.server.tracer is not None:
            step.span = self.server.tracer.begin(
                trace_mod.SESSION_STEP, parent=None,
                trace_id=sess.trace_id, session_id=sess.session_id,
                step_seq=step.step_seq, request_id=step.request_id,
            )
        inner = self.server.tickets.get(step.request_id)
        if inner is not None and not inner.done:
            # The step's identity is already admitted: the crash landed
            # between the server journal append and the session's, so
            # resume restored the inner request with no session-step
            # handle, and the client is retrying the unacked seq (a
            # retry MUST carry the original delta — the request content
            # is derived from the same journaled pre-step state).
            # Reattach instead of double-submitting the same rid.
            step.ticket = inner
        else:
            step.ticket = self.server.submit(queue_mod.ScenarioRequest(
                family=sess.family, horizon=fam.chunk_len,
                x0=tuple(float(val) for val in sess.x),
                v0=tuple(float(val) for val in sess.v),
                deadline_s=deadline_s, request_id=step.request_id,
                trace_id=sess.trace_id, session=sess.session_id,
            ))
        if step.ticket.done:
            # Admission rejected (queue full / tenant throttled /
            # coverage lost) or an immediate deadline verdict: resolve
            # the step in place so the caller never polls a dead inner
            # ticket (and, on rejection, rolls back its tentative
            # commit).
            self._resolve_step(step)
            return
        self.steps_accepted += 1
        self._emit_session(kind="step_submitted",
                           session_id=sess.session_id,
                           step_seq=step.step_seq,
                           request_id=step.request_id)
        self._steps[step.request_id] = step

    def _resolve_step(self, step: StepTicket) -> None:
        ticket = step.ticket
        cur = self.sessions.get(step.session_id)
        # Fencing applies to RESULTS too: a step submitted by a
        # superseded incarnation (the session re-opened while it was in
        # flight) resolves its OWN ticket but must never write
        # last_result / lane bookkeeping onto the new incarnation — a
        # later hold_last would otherwise serve the fenced epoch's
        # control.
        sess = (cur if cur is not None and cur.epoch == step.epoch
                else None)
        slo = ticket.slo.to_event()
        step.latency_s = slo.get("latency_s")
        if sess is not None and ticket.lane is not None:
            sess.lane = ticket.lane
            sess.batch_id = ticket.batch_id
        if ticket.status == queue_mod.COMPLETED:
            step.result = ticket.result
            step.rung = RUNG_SERVED
            step.status = queue_mod.COMPLETED
            if sess is not None:
                sess.last_result = ticket.result
            self._emit_session(kind="step_done",
                               session_id=step.session_id,
                               step_seq=step.step_seq, rung=step.rung,
                               request_id=step.request_id, slo=slo)
        elif ticket.status == queue_mod.DEADLINE_MISSED:
            # Graceful degradation: the step RESOLVES (completed, honest
            # rung) — the client applies the last served control, or is
            # told there is none to apply (no_control) when the miss
            # precedes the session's first served step. The late fresh
            # result, when the miss was in_flight, still refreshes
            # hold-last state for the NEXT degradation.
            self.steps_degraded += 1
            step.missed = ticket.slo.missed
            held = sess.last_result if sess is not None else None
            step.rung = (RUNG_HOLD_LAST if held is not None
                         else RUNG_NO_CONTROL)
            step.result = held
            step.status = queue_mod.COMPLETED
            if sess is not None and ticket.result is not None:
                sess.last_result = ticket.result
            self._emit_session(kind="step_degraded",
                               session_id=step.session_id,
                               step_seq=step.step_seq, rung=step.rung,
                               missed=step.missed,
                               request_id=step.request_id, slo=slo)
        else:  # REJECTED by admission — structured pass-through.
            step.status = queue_mod.REJECTED
            step.reason = ticket.reason
            self._emit_session(kind="step_done",
                               session_id=step.session_id,
                               step_seq=step.step_seq, rung="rejected",
                               reason=step.reason,
                               request_id=step.request_id)
        if step.span is not None:
            self.server.tracer.end(step.span, status=step.status,
                                   rung=step.rung or "rejected")
        self._steps.pop(step.request_id, None)

    def pump(self) -> bool:
        """One session-tier round: sweep leases, pump the server, then
        resolve every finished step. Returns True while work remains."""
        self.sweep()
        more = self.server.pump()
        for step in [s for s in self._steps.values()
                     if s.ticket is not None and s.ticket.done]:
            self._resolve_step(step)
        return more or bool(self._steps)

    # ------------------------------------------------------------ stats --
    def stats(self) -> dict:
        live = sum(1 for s in self.sessions.values() if s.status == LIVE)
        return {
            "sessions": len(self.sessions),
            "live": live,
            "evicted": self.evictions,
            "fenced_rejections": self.fence_rejections,
            "stale_rejections": self.stale_rejections,
            "steps_accepted": self.steps_accepted,
            "steps_degraded": self.steps_degraded,
            "steps_in_flight": len(self._steps),
        }

    # ----------------------------------------------------------- resume --
    @classmethod
    def resume(cls, server, *, lease_s=None, clock=None,
               step_deadline_s: float | None = None) -> "SessionHost":
        """Rebuild the session table from the (already-resumed) server's
        journal: lease epochs and the fence set replay from open/evict/
        close events, watermarks and the exact float64 state from the
        last accepted step (epoch-guarded: a superseded incarnation's
        journal rows never advance, and are never reattached to, the
        incarnation that replaced it). Leases RE-ARM (fresh TTL from
        now — the monotonic domain died with the process). Restored
        in-flight steps are reattached so ``pump`` resolves them
        normally; a journaled step with no server record (defensive —
        the live path journals only after admission accepts) is
        resubmitted from its journaled post-delta state."""
        host = cls(server, lease_s=lease_s, clock=clock,
                   step_deadline_s=step_deadline_s)
        if server.journal is None:
            return host
        step_events: dict[str, dict] = {}   # request_id -> event (order).
        for e in server.journal.read():
            ev = e.get("event")
            if ev == "session_open":
                sid = e["session_id"]
                prev = host.sessions.get(sid)
                if prev is not None:
                    host._fenced[prev.lease] = sid
                sess = Session(sid, e["family"], e["lease"], e["epoch"],
                               e["x"], e["v"], e.get("trace_id"),
                               e.get("deadline_s"))
                host.sessions[sid] = sess
            elif ev == "session_step":
                sess = host.sessions.get(e["session_id"])
                # Epoch guard: a step journaled by a superseded
                # incarnation must not advance the incarnation that
                # replaced it (replay order already makes this hold for
                # well-formed journals; the guard keeps a truncated or
                # hand-edited journal from corrupting the watermark).
                if (sess is not None
                        and int(e.get("epoch", 0)) == sess.epoch):
                    sess.step_seq = int(e["step_seq"])
                    sess.x = np.asarray(e["x"], dtype=np.float64)
                    sess.v = np.asarray(e["v"], dtype=np.float64)
                step_events[e["request_id"]] = e
            elif ev == "session_evict":
                sess = host.sessions.get(e["session_id"])
                if sess is not None and sess.status == LIVE:
                    sess.status = EVICTED
                host._fenced[e["lease"]] = e["session_id"]
            elif ev == "session_close":
                sess = host.sessions.get(e["session_id"])
                if sess is not None:
                    sess.status = CLOSED
                    host._fenced[sess.lease] = sess.session_id
        now = host.clock()
        live = 0
        for sess in host.sessions.values():
            if sess.status == LIVE:
                live += 1
                host._renew(sess, now)
        reattached = 0
        for rid, e in step_events.items():
            if rid in server.done_requests:
                continue
            sess = host.sessions[e["session_id"]]
            if int(e.get("epoch", 0)) != sess.epoch:
                # A superseded incarnation's unfinished step: fenced.
                # Its restored inner ticket (if any) resolves server-
                # side as an orphan; the session tier never reattaches
                # it, so it can never write onto the new incarnation.
                continue
            step = StepTicket(sess.session_id, int(e["step_seq"]), rid,
                              epoch=sess.epoch)
            inner = server.tickets.get(rid)
            if inner is not None:
                # Restored (or replayed) by ScenarioServer.resume: just
                # rebind the session-step handle.
                step.ticket = inner
                host._steps[rid] = step
                if server.tracer is not None:
                    step.span = server.tracer.begin(
                        trace_mod.SESSION_STEP, parent=None,
                        trace_id=sess.trace_id,
                        session_id=sess.session_id,
                        step_seq=step.step_seq, request_id=rid,
                        restored=True,
                    )
            elif sess.step_seq == step.step_seq and sess.status == LIVE:
                # Accepted pre-crash, never reached the server journal:
                # resubmit from the journaled post-delta state (only the
                # watermark step can be in this gap — earlier ones are
                # in the server journal or done).
                host._submit_step(sess, step, e.get("deadline_s"))
            reattached += 1
        host._emit_session(kind="sessions_resumed", live=live,
                           sessions=len(host.sessions),
                           steps_reattached=reattached)
        return host
