"""The scenario-serving driver: admission queue + continuous batcher
wired to the device through the backend guard and the AOT serve ladder.

Rules of the road (the ROADMAP's standing-subsystem contract):

- **every device interaction goes through** ``resilience.backend
  .BackendGuard`` — a wedged/flaky backend degrades a chunk to the
  tagged CPU rung instead of killing the server loop;
- **every compiled call is served through** ``aot.loader.serve_entry`` —
  a bundled replica admits requests with ZERO in-process compiles (the
  exec rung replays serialized executables; the family's template carry
  comes from the bundle's ``args_sample``, so even input construction is
  host-numpy); un-bundled processes fall down the ladder to the
  family's ONE pre-jitted batched chunk;
- **preemption safety rides the PR-4 journal**: every chunk boundary
  publishes an atomic carry snapshot + a journaled lane map, so a
  SIGTERM mid-batch completes at the boundary and
  :meth:`ScenarioServer.resume` re-admits the remainder — recomputed
  chunks are bit-identical to the uninterrupted run (the chunked-rollout
  determinism contract, tests/test_serving.py).

The server is host-synchronous by design (``pump()`` drives one
scheduling round; ``run_until_drained()`` loops it): the async surface
is the ticket — ``submit()`` never blocks on device work and consumers
``Ticket.wait()`` from their own threads.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from tpu_aerial_transport.harness import checkpoint
from tpu_aerial_transport.obs import trace as trace_mod
from tpu_aerial_transport.serving import batcher as batcher_mod
from tpu_aerial_transport.serving import cache as cache_mod
from tpu_aerial_transport.serving import lanes as lanes_mod
from tpu_aerial_transport.serving import queue as queue_mod
from tpu_aerial_transport.serving.batcher import (
    DEFAULT_BUCKETS,
    Batch,
    Family,
    make_family,
)

SERVING_JOURNAL = "serving_journal.jsonl"
SNAP_PREFIX = "serving_b"  # + batch_id (checkpoint prefix grammar: no '-').


class ScenarioServer:
    """Serve a heterogeneous scenario-MPC request stream.

    ``families``: iterable of :class:`FamilySpec` / canonical-family
    names / :class:`Family` (default: the canonical families).
    ``bundle``: an ``aot.loader.Bundle`` or bundle directory — the
    zero-compile admission prerequisite; ``require_bundle=True`` makes
    bundle coverage an ADMISSION criterion (uncovered families reject
    with ``no_bucket_coverage``) and never builds a jit fallback.
    ``run_dir`` turns on preemption safety (journal + per-boundary
    snapshots). ``mesh`` (a ``jax.sharding.Mesh``) places each batch
    sharded over its lane axis before dispatch — sharded
    (``min_devices>1``) programs serve through the export/jit rungs, the
    exec replay path addresses one device (PR-8 note). The 2-D pods mesh
    (``parallel.pods.make_pods_mesh``) is accepted too: placement rides
    ``parallel.mesh.shard_scenarios``, which on a MULTI-process mesh
    assembles the global batch from each process's host copy (every
    process runs the same host-synchronous server loop; the carry_host
    is host-global on all of them, which is exactly what that path
    needs).
    """

    def __init__(self, families=None, *, buckets=DEFAULT_BUCKETS,
                 capacity: int = 256, bundle=None,
                 require_bundle: bool = False, run_dir: str | None = None,
                 metrics=None, guard=None, interrupt=None, mesh=None,
                 tracer=None, clock=time.monotonic,
                 surgery: str | None = None, dispatch: str | None = None,
                 cache=None, hub=None):
        from tpu_aerial_transport.obs import export as export_mod
        from tpu_aerial_transport.resilience import backend as backend_mod
        from tpu_aerial_transport.resilience.recovery import RunJournal

        # The ISSUE-18 impl knobs, resolved ONCE at build time
        # (serving/lanes.py resolvers; TAT_SERVING_SURGERY /
        # TAT_SERVING_DISPATCH env forces). Host+sync is the default and
        # its code path is the pre-knob one verbatim — behavior and the
        # chunk program's HLO are byte-identical when the knobs are off.
        self.surgery = lanes_mod.resolve_surgery(surgery)
        self.dispatch = lanes_mod.resolve_dispatch(dispatch)
        if self.dispatch == "pipelined":
            # A host splice needs chunk k's values on host before chunk
            # k+1 can launch — the serialization pipelining removes —
            # so pipelined dispatch implies device surgery (resolver doc).
            self.surgery = "device"
        if self.surgery == "device" and mesh is not None:
            raise ValueError(
                "surgery='device' is single-device serving only: the "
                "mesh path assembles host-global boundary carries "
                "(pods.host_global), which IS host surgery. Use "
                "surgery='host' (default) with a mesh."
            )
        # Content-addressed result cache (serving/cache.py): None =>
        # disabled (default — repeat-query dedup changes how a request
        # is served, so it is opt-in); an int => LRU capacity.
        if cache is None or isinstance(cache, cache_mod.ResultCache):
            self.cache = cache
        else:
            self.cache = cache_mod.ResultCache(int(cache))

        if families is None:
            families = list(batcher_mod.CANONICAL_FAMILIES.values())
        self.families: dict[str, Family] = {}
        for f in families:
            fam = f if isinstance(f, Family) else make_family(f)
            self.families[fam.name] = fam
        self.buckets = tuple(sorted(buckets))
        self.clock = clock
        self.mesh = mesh
        self.require_bundle = require_bundle
        if isinstance(metrics, str):
            metrics = export_mod.MetricsWriter(metrics)
        self.metrics = metrics
        # Distributed tracing (obs.trace): tracer=None is the zero-cost
        # path — no span objects, no per-request allocation, and (since
        # tracing is host-only) the compiled HLO is identical either way
        # (asserted by tests/test_trace.py). Batch/device spans live on
        # the server's OWN trace; request spans each get their own.
        self.tracer = tracer
        self._server_trace = (None if tracer is None
                              else trace_mod.new_trace_id())
        # Live metrics hub (obs.live.MetricsHub | None). None is the
        # zero-cost path: every touch below is guarded `is not None`
        # (HL010) and the serving loop allocates nothing extra per
        # request — the same contract tracer=None keeps.
        self.hub = hub
        # `is None`, not truthiness (the PR-15 tracer=False bug class):
        # a caller-built guard must be used even if it tests falsy.
        self.guard = (backend_mod.BackendGuard(metrics=metrics, hub=hub)
                      if guard is None else guard)
        if self.guard.tracer is None:
            self.guard.tracer = tracer
        if self.guard.hub is None:
            self.guard.hub = hub
        self.interrupt = interrupt
        self.preempted = False
        self.run_dir = run_dir
        self.journal = (RunJournal(run_dir, SERVING_JOURNAL)
                        if run_dir else None)

        if isinstance(bundle, str):
            from tpu_aerial_transport.aot import loader as loader_mod

            bundle = loader_mod.load_bundle(bundle)
        self.bundle = bundle
        self._install_bundle_templates()

        self.queue = queue_mod.AdmissionQueue(
            self._coverage, capacity=capacity, clock=clock,
            emit=self._emit, tracer=tracer, hub=hub,
        )
        self.tickets: dict[str, queue_mod.Ticket] = {}
        self.done_requests: set[str] = set()  # filled by resume().
        self._batches: dict[str, Batch | None] = {}
        self._occupancy: list[float] = []

    # ------------------------------------------------------- coverage --
    def _bundle_entry_buckets(self, fam: Family) -> list[int]:
        """Device-batch sizes the bundle precompiled for this family
        (empty when un-bundled / uncovered / pre-args_sample bundle)."""
        if self.bundle is None or fam.entry is None:
            return []
        try:
            return self.bundle.batch_buckets(fam.entry)
        except Exception:  # missing_entry/manifest-only: no coverage.
            return []

    def _family_buckets(self, fam: Family) -> tuple[int, ...]:
        covered = self._bundle_entry_buckets(fam)
        if covered and self.require_bundle:
            return tuple(covered)
        if covered:
            # Prefer precompiled buckets, but any configured bucket still
            # serves via the jit rung.
            return tuple(sorted(set(covered) | set(self.buckets)))
        return self.buckets

    def _coverage(self, family: str) -> int | None:
        fam = self.families.get(family)
        if fam is None:
            return None
        if self.require_bundle and not self._bundle_entry_buckets(fam):
            return None
        return fam.chunk_len

    def _install_bundle_templates(self) -> None:
        """Template carries from the bundle's build-time argument values:
        lane 0 of the entry's recorded batch — host numpy, no compiles.
        Families the bundle does not cover keep the lazy jnp build —
        EXCEPT under ``require_bundle``, where a missing/corrupt
        ``args_sample`` raises instead of silently degrading the
        "zero-compile" replica into the eager jnp template build (the
        compiles would land in the serve path with no visible cause)."""
        from tpu_aerial_transport.aot.bundle import BundleError

        if self.bundle is None:
            return
        for fam in self.families.values():
            if fam.entry is None:
                continue
            try:
                sample = self.bundle.sample_args(fam.entry)
            except BundleError:
                if self.require_bundle and self._bundle_entry_buckets(fam):
                    # The family IS admissible (bucket coverage exists)
                    # but its template cannot come from the bundle.
                    raise
                continue
            batch_carry = sample[0]
            fam.set_template_carry_host(_tree_map(
                lambda x: np.array(np.asarray(x)[0], copy=True),
                batch_carry,
            ))

    # ---------------------------------------------------------- events --
    def _emit(self, **fields) -> None:
        if self.metrics is not None:
            self.metrics.emit("serving_event", **fields)
        if self.hub is not None:
            # The fields dict already exists (this funnel's kwargs), so
            # the hub fold adds no marginal allocation; hub=None skips
            # entirely — the zero-cost contract.
            self.hub.ingest_serving(fields)
        if self.journal is not None and fields.get("kind") in (
            "completed", "deadline_missed",
        ):
            self.journal.append({
                "event": "serving_done",
                "request_id": fields.get("request_id"),
                "status": fields["kind"],
            })

    # ---------------------------------------------------------- submit --
    def submit(self, request: queue_mod.ScenarioRequest) -> queue_mod.Ticket:
        """Admit or reject one request (never raises out of admission —
        rejection is a resolved ticket with a structured reason). With a
        result cache configured, a content-address hit resolves the
        ticket right here — no queue, no lane, no device dispatch.
        Session steps (``request.session`` set) NEVER consult the cache:
        the content address omits the session/step_seq identity, and a
        cache-resolved step would skip the lane write the session's
        state stream is defined by (tests/test_sessions.py pins this)."""
        if self.cache is not None and request.session is None:
            fam = self.families.get(request.family)
            if fam is not None:
                hit = self.cache.get(
                    cache_mod.request_key(fam.config_hash(), request)
                )
                if hit is not None:
                    return self._resolve_cached(request, fam, hit)
        ticket = self.queue.submit(request)
        self.tickets[request.request_id] = ticket
        if ticket.status == queue_mod.PENDING and self.journal is not None:
            # ticket.request, NOT the caller's argument: admission mints
            # the trace_id onto a replaced request object, and the
            # journal must carry it or resume re-mints and the pre/post
            # spans land on different traces.
            self.journal.append({
                "event": "serving_request",
                "request": ticket.request.to_json(),
            })
        return ticket

    def _resolve_cached(self, request: queue_mod.ScenarioRequest,
                        fam: Family, hit) -> queue_mod.Ticket:
        """Resolve a content-address cache hit: mint a ticket outside the
        admission queue, stamp a zero-length SLO window (submit = admit =
        complete — the request never waited, never held a lane), and emit
        ``cache_hit`` + ``completed``. The journal's ``serving_done``
        record still lands (via ``_emit``) so a client replaying its
        stream after a crash dedupes cache-resolved requests the same as
        device-resolved ones."""
        if self.tracer is not None and request.trace_id is None:
            request = dataclasses.replace(
                request, trace_id=trace_mod.new_trace_id()
            )
        ticket = queue_mod.Ticket(request)
        now = self.clock()
        ticket.slo.t_submit = now
        ticket.slo.t_admit = now
        ticket.slo.t_complete = now
        if self.tracer is not None:
            root = self.tracer.begin(
                trace_mod.REQUEST, parent=None,
                trace_id=request.trace_id,
                request_id=request.request_id, family=request.family,
                horizon=int(request.horizon), cached=True,
            )
            ticket.trace = trace_mod.RequestTrace(self.tracer, root)
        ticket.result, ticket.steps_served = hit
        ticket._resolve(queue_mod.COMPLETED)
        self.tickets[request.request_id] = ticket
        self._emit(kind="cache_hit", request_id=request.request_id,
                   family=request.family)
        self._emit(kind="completed", request_id=request.request_id,
                   family=request.family, steps=ticket.steps_served,
                   cached=True, slo=ticket.slo.to_event())
        if ticket.trace is not None:
            ticket.trace.resolve(queue_mod.COMPLETED,
                                 steps=ticket.steps_served, cached=True)
        return ticket

    def _cache_put(self, fam: Family, finished) -> None:
        """Populate the result cache from a boundary's resolved tickets —
        COMPLETED only (a deadline-missed result is real data but its
        status is an SLO verdict that must not replay onto a fresh
        request), and never session steps (their content address ignores
        the session identity — a later one-shot request with the same
        x0/v0 would replay a mid-session lane state as its own)."""
        if self.cache is None:
            return
        for t in finished:
            if (t.status == queue_mod.COMPLETED
                    and t.request.session is None):
                self.cache.put(
                    cache_mod.request_key(fam.config_hash(), t.request),
                    t.result, t.steps_served,
                )

    # ------------------------------------------------------ scheduling --
    def _check_preempt(self) -> bool:
        if (not self.preempted and self.interrupt is not None
                and self.interrupt.triggered):
            self.preempted = True
            if self.journal is not None:
                self.journal.append({
                    "event": "serving_preempted",
                    "signal": self.interrupt.triggered,
                })
            self._emit(kind="preempted", signal=self.interrupt.triggered)
        return self.preempted

    def has_work(self) -> bool:
        return bool(
            self.queue.depth()
            or any(b is not None and not b.retired
                   for b in self._batches.values())
        )

    def pump(self) -> bool:
        """One scheduling round: expire queue deadlines, launch batches
        for families with pending work, advance every active batch by one
        chunk (the boundary then harvests finished lanes and admits late
        arrivals). Returns True while work remains (False after
        preemption — the remainder is journaled for :meth:`resume`)."""
        if self._check_preempt():
            return False
        self.queue.expire_deadlines()
        for name, fam in self.families.items():
            if self._check_preempt():
                return False
            batch = self._batches.get(name)
            if batch is None or batch.retired:
                if not self.queue.depth(name):
                    continue
                batch = self._launch(fam)
            self._advance(fam, batch)
        return self.has_work() and not self.preempted

    def run_until_drained(self, max_rounds: int | None = None) -> dict:
        rounds = 0
        while self.pump():
            rounds += 1
            if max_rounds is not None and rounds >= max_rounds:
                break
        return self.stats()

    # -------------------------------------------------------- batches --
    def _launch(self, fam: Family) -> Batch:
        bucket = batcher_mod.bucket_for(
            self.queue.depth(fam.name), self._family_buckets(fam)
        )
        span = None
        if self.tracer is not None:
            span = self.tracer.begin(
                trace_mod.BATCH_FORM, parent=None,
                trace_id=self._server_trace, family=fam.name,
                bucket=bucket,
            )
        try:
            batch = Batch(fam, bucket, fam.template_carry_host(),
                          self.clock, self._emit)
            self._batches[fam.name] = batch
            for lane, ticket in enumerate(
                self.queue.take(fam.name, bucket)
            ):
                batch.admit(ticket, lane)
        except BaseException:
            # HL002: the forming span must not leak if admission dies
            # (end() is idempotent, so this defensive close is free).
            if span is not None:
                self.tracer.end(span, error=True)
            raise
        if span is not None:
            self.tracer.end(span, batch_id=batch.batch_id,
                            lanes=batch.lane_map())
        self._emit(kind="batch_launch", family=fam.name,
                   batch_id=batch.batch_id, bucket=bucket,
                   lanes=batch.active_lanes)
        return batch

    def _advance(self, fam: Family, batch: Batch) -> None:
        """Advance one batch by one chunk + its boundary. Impl selection
        (the ISSUE-18 knob): device surgery needs a registered surgery
        entrypoint — families without one fall back to the host splice
        even in device mode (ad-hoc families stay servable)."""
        if self.surgery == "device" and fam.surgery_entry is not None:
            self._advance_device(fam, batch)
        else:
            self._advance_host(fam, batch)

    def _chunk_once(self, fam: Family, batch: Batch, carry,
                    chunk_index: int, *, block: bool = True):
        """One chunk dispatch under its shared CHUNK_DISPATCH span (the
        lane map links every member request's trace to it — the
        critical-path accountant's "device" segment). ``block=False`` is
        the pipelined path: the span then measures dispatch only, and the
        device wait surfaces in the boundary's harvest transfer /
        ``batch_wait`` — the stall the A/B cells exist to expose."""
        label = f"{fam.name}:b{batch.batch_id}:c{chunk_index}"
        i0 = np.int32(chunk_index * fam.chunk_len)
        dspan = None
        if self.tracer is not None:
            dspan = self.tracer.begin(
                trace_mod.CHUNK_DISPATCH, parent=None,
                trace_id=self._server_trace, family=fam.name,
                batch_id=batch.batch_id, chunk=chunk_index,
                bucket=batch.bucket, lanes=batch.lane_map(),
            )
        try:
            (out, serve_rung), guard_rung = self._dispatch(
                fam, (carry, i0), label, trace_parent=dspan, block=block
            )
        except BaseException:
            if dspan is not None:
                self.tracer.end(dspan, error=True)
            raise
        if dspan is not None:
            self.tracer.end(dspan, rung=serve_rung, guard_rung=guard_rung)
        return out, serve_rung, guard_rung

    def _advance_host(self, fam: Family, batch: Batch) -> None:
        """The pre-knob boundary path, verbatim: chunk on device, full
        boundary carry back to host, numpy splice. (Only the trace
        decomposition is new — LANE_SURGERY around the late-join splice,
        BOUNDARY_PUBLISH around the snapshot — both host-only, so the
        compiled chunk HLO is byte-identical to the pre-knob server.)"""
        batch.record_launch()
        carry = batch.carry_host
        if self.mesh is not None:
            from tpu_aerial_transport.parallel import mesh as mesh_mod

            carry = mesh_mod.shard_scenarios(self.mesh, carry, "scenario")
        out, serve_rung, guard_rung = self._chunk_once(
            fam, batch, carry, batch.chunks_done
        )
        new_carry, _logs = out
        hspan = None
        if self.tracer is not None:
            hspan = self.tracer.begin(
                trace_mod.HARVEST, parent=None,
                trace_id=self._server_trace, family=fam.name,
                batch_id=batch.batch_id, chunk=batch.chunks_done + 1,
                lanes=batch.lane_map(),
            )
        try:
            batch.carry_host = self._boundary_host(new_carry)
            finished = batch.harvest()
            sspan = None
            if self.tracer is not None:
                sspan = self.tracer.begin(
                    trace_mod.LANE_SURGERY, parent=hspan,
                    trace_id=self._server_trace, family=fam.name,
                    batch_id=batch.batch_id, impl="host",
                )
            try:
                for lane in batch.free_lanes():
                    late = self.queue.take(fam.name, 1)
                    if not late:
                        break
                    batch.admit(late[0], lane)
            except BaseException:
                if sspan is not None:
                    self.tracer.end(sspan, error=True)
                raise
            if sspan is not None:
                self.tracer.end(sspan, lanes=batch.lane_map())
            occupancy = batch.occupancy_samples[-1]
            self._publish_boundary(fam, batch)
        except BaseException:
            # Same rule as the dispatch span: the boundary where
            # something broke (a SnapshotError from the boundary publish)
            # must not be the one with no harvest record.
            if hspan is not None:
                self.tracer.end(hspan, error=True)
            raise
        if hspan is not None:
            self.tracer.end(hspan)
        self._cache_put(fam, finished)
        self._emit(kind="batch_boundary", family=fam.name,
                   batch_id=batch.batch_id, chunk=batch.chunks_done,
                   occupancy=occupancy, rung=serve_rung,
                   guard_rung=guard_rung)
        if batch.retired:
            self._occupancy.extend(batch.occupancy_samples)

    def _advance_device(self, fam: Family, batch: Batch) -> None:
        """The ISSUE-18 device boundary: chunk k's carry never leaves the
        device. The boundary plan (which lanes finish = admission
        counters; who joins = queue state) is pure host numpy and
        data-independent of chunk k's numeric results
        (``Batch.plan_finishing``) — so the surgery masks are built, the
        donated surgery program runs on the device-resident carry, and
        (pipelined mode) chunk k+1 is dispatched BEFORE anything blocks
        on chunk k's values. Only the harvested scenario state (the
        surgery program's second output) is transferred, and only when a
        lane actually finished. Ordering is load-bearing:
        plan -> surgery -> [speculative dispatch] -> harvest transfer ->
        resolve -> bind joins -> publish. Joins must bind AFTER
        ``Batch.harvest`` (it decrements every ticketed lane's countdown)
        and the snapshot must follow the binds so the journaled lane map
        matches the published carry — the resume bit-identity contract."""
        batch.record_launch()
        pipelined = self.dispatch == "pipelined"

        # --- chunk k: the previous boundary's speculative dispatch, or
        # dispatch it now (first chunk / sync mode / post-resume).
        if batch.inflight is not None:
            out, serve_rung, guard_rung = batch.inflight
            batch.inflight = None
        else:
            carry = (batch.carry_dev if batch.carry_dev is not None
                     else batch.carry_host)
            out, serve_rung, guard_rung = self._chunk_once(
                fam, batch, carry, batch.chunks_done, block=not pipelined
            )
        new_carry, _logs = out

        # --- boundary plan: host counters only, no device values.
        finishing = batch.plan_finishing()
        free_after = sorted(set(batch.free_lanes()) | set(finishing))
        late = self.queue.take(fam.name, len(free_after))
        joins = list(zip(free_after, late))
        joined = {lane for lane, _ in joins}
        # Freed-with-no-joiner lanes reset to pristine filler; lanes that
        # were ALREADY filler are left alone (same as the host path,
        # which only ever splices admitted lanes).
        resets = [lane for lane in finishing if lane not in joined]

        # --- surgery: one donated select program on the device carry.
        sspan = None
        if self.tracer is not None:
            sspan = self.tracer.begin(
                trace_mod.LANE_SURGERY, parent=None,
                trace_id=self._server_trace, family=fam.name,
                batch_id=batch.batch_id, impl="device",
                lanes=batch.lane_map(),
            )
        try:
            args = (new_carry,) + lanes_mod.make_surgery_args(
                fam.batched_template_host(batch.bucket),
                [(lane, t.request) for lane, t in joins], resets,
                batch.bucket,
            )
            (sout, s_rung), s_guard = self._dispatch(
                fam, args,
                f"{fam.name}:b{batch.batch_id}:s{batch.chunks_done}",
                trace_parent=sspan, entry=fam.surgery_entry,
                jit_fallback=fam.surgery_jit, block=not pipelined,
            )
            new_carry2, harvested = sout
        except BaseException:
            if sspan is not None:
                self.tracer.end(sspan, error=True)
            raise
        if sspan is not None:
            self.tracer.end(sspan, rung=s_rung, guard_rung=s_guard)
        batch.carry_dev = new_carry2

        # --- speculative chunk k+1 (pipelined): dispatched before the
        # harvest transfer blocks, IF any lane stays active.
        if pipelined and (batch.active_lanes - len(finishing)
                          + len(joins)) > 0:
            batch.inflight = self._chunk_once(
                fam, batch, new_carry2, batch.chunks_done + 1, block=False
            )

        # --- harvest: transfer the pre-surgery scenario state (only if
        # a lane finished), resolve, THEN bind joins.
        hspan = None
        if self.tracer is not None:
            hspan = self.tracer.begin(
                trace_mod.HARVEST, parent=None,
                trace_id=self._server_trace, family=fam.name,
                batch_id=batch.batch_id, chunk=batch.chunks_done + 1,
                lanes=batch.lane_map(),
            )
        try:
            state_host = None
            if finishing:
                state_host = _tree_map(np.asarray, harvested)
            finished = batch.harvest(state_host=state_host)
            for lane, ticket in joins:
                batch.admit(ticket, lane, write_carry=False)
            occupancy = batch.occupancy_samples[-1]
            self._publish_boundary(fam, batch, carry_dev=new_carry2)
        except BaseException:
            if hspan is not None:
                self.tracer.end(hspan, error=True)
            raise
        if hspan is not None:
            self.tracer.end(hspan, lanes=batch.lane_map())
        self._cache_put(fam, finished)
        self._emit(kind="batch_boundary", family=fam.name,
                   batch_id=batch.batch_id, chunk=batch.chunks_done,
                   occupancy=occupancy, rung=serve_rung,
                   guard_rung=guard_rung)
        if batch.retired:
            self._occupancy.extend(batch.occupancy_samples)
            batch.inflight = None  # nothing admissible rode along.

    def _boundary_host(self, carry):
        """Boundary carry back to host. The server loop is host-global by
        design (late joins / lane surgery operate on the full batch on
        every process), so under a MULTI-process pods mesh the extraction
        is ``pods.host_global`` (all-gather to replicated, then copy) —
        ``recovery.host_copy``'s plain ``np.array`` raises on an array
        spanning non-addressable devices."""
        from tpu_aerial_transport.resilience.recovery import host_copy

        if self.mesh is not None:
            from tpu_aerial_transport.parallel import mesh as mesh_mod
            from tpu_aerial_transport.parallel import pods

            if mesh_mod.is_multiprocess_mesh(self.mesh):
                return pods.host_global(carry)
        return host_copy(carry)

    def _dispatch(self, fam: Family, args, label: str, trace_parent=None,
                  *, entry: str | None = None, jit_fallback=None,
                  block: bool = True):
        """One guarded call through the serve ladder. Returns
        ``((out, serve_rung), guard_rung)``. Defaults serve the family's
        batched chunk; device-surgery dispatches pass
        ``entry=fam.surgery_entry`` / ``jit_fallback=fam.surgery_jit`` —
        same ladder, so a bundled replica's surgery replays a serialized
        executable and the process stays zero-compile. ``block=False``
        (pipelined) skips the ladder's block_until_ready: the call
        returns as soon as the work is enqueued and errors surface at
        the boundary's harvest transfer."""
        from tpu_aerial_transport.aot import loader as loader_mod
        from tpu_aerial_transport.resilience import backend as backend_mod

        entry = entry if entry is not None else (fam.entry or fam.name)
        if jit_fallback is None and not self.require_bundle:
            jit_fallback = fam.batched_jit
        jit_fb = None if self.require_bundle else jit_fallback

        def primary():
            return loader_mod.serve_entry(
                self.bundle, entry, args, jit_fallback=jit_fb,
                metrics=self.metrics, label=label, block=block,
                hub=self.hub,
            )

        fallback = None
        if not self.require_bundle:
            fallback = backend_mod.run_on_cpu(lambda: loader_mod.serve_entry(
                None, entry, args, jit_fallback=jit_fallback,
                metrics=self.metrics, label=label + ":cpu", block=block,
                hub=self.hub,
            ))
        return self.guard.run(label, primary, fallback_fn=fallback,
                              trace_parent=trace_parent)

    def _publish_boundary(self, fam: Family, batch: Batch,
                          carry_dev=None) -> None:
        """Boundary durability publication under its BOUNDARY_PUBLISH
        span (the critical path's "publish" segment): atomic snapshot +
        journaled lane map. Device-surgery mode passes ``carry_dev`` (the
        post-surgery device carry) and pays the host transfer HERE — only
        when a journal is configured; an un-journaled device server never
        round-trips the carry, which is the knob's perf point."""
        if self.journal is None:
            return
        pspan = None
        if self.tracer is not None:
            pspan = self.tracer.begin(
                trace_mod.BOUNDARY_PUBLISH, parent=None,
                trace_id=self._server_trace, family=fam.name,
                batch_id=batch.batch_id, chunk=batch.chunks_done,
                lanes=batch.lane_map(),
            )
        try:
            if carry_dev is not None:
                batch.carry_host = _tree_map(
                    lambda x: np.array(np.asarray(x), copy=True),
                    carry_dev,
                )
            checkpoint.save_snapshot(
                self.run_dir, batch.chunks_done, batch.carry_host,
                prefix=f"{SNAP_PREFIX}{batch.batch_id}",
                config_hash=fam.config_hash(), keep_last=2,
                meta={"family": fam.name, "bucket": batch.bucket},
            )
            self.journal.append({
                "event": "serving_batch", "batch_id": batch.batch_id,
                "family": fam.name, "bucket": batch.bucket,
                "chunk": batch.chunks_done, "lanes": batch.lanes_json(),
            })
        except BaseException:
            if pspan is not None:
                self.tracer.end(pspan, error=True)
            raise
        if pspan is not None:
            self.tracer.end(pspan)

    # ----------------------------------------------------------- stats --
    def stats(self) -> dict:
        by_status: dict[str, int] = {}
        steps = 0
        for t in self.tickets.values():
            by_status[t.status] = by_status.get(t.status, 0) + 1
            if t.status == queue_mod.COMPLETED:
                steps += t.steps_served
        # Retired batches already moved their samples into _occupancy
        # (and may linger in _batches until replaced) — counting them
        # here again would skew the mean toward each family's last batch.
        live = [
            s for b in self._batches.values()
            if b is not None and not b.retired
            for s in b.occupancy_samples
        ]
        occ = self._occupancy + live
        out = {
            "requests": len(self.tickets),
            **by_status,
            "scenario_steps": steps,
            "mean_occupancy": float(np.mean(occ)) if occ else None,
            "preempted": self.preempted,
            "surgery": self.surgery,
            "dispatch": self.dispatch,
        }
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        return out

    # ---------------------------------------------------------- resume --
    @classmethod
    def resume(cls, run_dir: str, families=None, **kw) -> "ScenarioServer":
        """Rebuild a server from a preempted run directory: restore each
        unfinished batch's boundary carry from its newest journaled
        snapshot (lane map + chunk count from the matching journal
        event), re-enqueue requests that were still waiting, and resolve
        nothing twice. Recomputed work is bit-identical to the
        uninterrupted run (chunk determinism); a batch whose snapshot
        fails validation falls back to full request replay — also
        bit-identical, just more recompute. Restored/replayed tickets are
        reachable through ``server.tickets[request_id]``."""
        from tpu_aerial_transport.resilience.recovery import RunJournal

        events = RunJournal(run_dir, SERVING_JOURNAL).read()
        requests: dict[str, queue_mod.ScenarioRequest] = {}
        order: list[str] = []
        done: set[str] = set()
        last_batch: dict[int, dict] = {}
        for e in events:
            if e.get("event") == "serving_request":
                req = queue_mod.ScenarioRequest.from_json(e["request"])
                if req.request_id not in requests:
                    order.append(req.request_id)
                requests[req.request_id] = req
            elif e.get("event") == "serving_done":
                done.add(e.get("request_id"))
            elif e.get("event") == "serving_batch":
                last_batch[e["batch_id"]] = e

        server = cls(families=families, run_dir=run_dir, **kw)
        # Requests the journal already saw through to resolution: clients
        # replaying their stream spec after a crash dedupe against this.
        server.done_requests = done
        server._emit(kind="resumed", run_dir=run_dir,
                     pending=len([r for r in requests if r not in done]))
        if server.journal is not None:
            server.journal.append({"event": "serving_resumed"})

        if last_batch:
            # Fresh-process batch ids restart at 0: future launches must
            # not collide with journaled batch identities/snapshots.
            batcher_mod.reserve_batch_ids(max(last_batch) + 1)
        restored: set[str] = set()
        for bid in sorted(last_batch):
            e = last_batch[bid]
            live = [(lane, rid, rem) for lane, rid, rem in e["lanes"]
                    if rid not in done and rid in requests]
            if not live:
                continue
            fam = server.families.get(e["family"])
            if fam is None:
                continue  # family not configured: requests replay below.
            path = checkpoint.snapshot_path(
                run_dir, e["chunk"], f"{SNAP_PREFIX}{bid}"
            )
            template = _tree_map(
                lambda x: np.stack([np.asarray(x)] * e["bucket"]),
                fam.template_carry_host(),
            )
            try:
                carry, _meta = checkpoint.load_snapshot(
                    path, template, config_hash=fam.config_hash()
                )
            except checkpoint.SnapshotError as exc:
                if server.journal is not None:
                    server.journal.append({
                        "event": "serving_snapshot_skipped",
                        "batch_id": bid, "error": str(exc)[:300],
                    })
                continue  # full replay via the queue below.
            batch = Batch(fam, e["bucket"], fam.template_carry_host(),
                          server.clock, server._emit, batch_id=bid)
            batch.carry_host = _tree_map(
                lambda x: np.array(x, copy=True), carry
            )
            batch.chunks_done = e["chunk"]
            for lane, rid, rem in live:
                ticket = queue_mod.Ticket(requests[rid])
                if server.tracer is not None:
                    # Same trace_id as the preempted run (journaled on
                    # the request): the stitched trace shows pre- and
                    # post-resume spans on one trace, this root marked
                    # restored.
                    root = server.tracer.begin(
                        trace_mod.REQUEST, parent=None,
                        trace_id=requests[rid].trace_id,
                        request_id=rid, family=e["family"],
                        restored=True,
                    )
                    ticket.trace = trace_mod.RequestTrace(
                        server.tracer, root
                    )
                now = server.clock()
                ticket.slo.t_submit = now
                if requests[rid].deadline_s is not None:
                    # Deadlines RE-ARM on resume (the monotonic clock
                    # domain dies with the process) — same fresh budget a
                    # still-queued request gets when it re-submits below.
                    ticket.slo.deadline_at = (
                        now + float(requests[rid].deadline_s)
                    )
                batch.restore_lane(ticket, lane, rem)
                server.tickets[rid] = ticket
                restored.add(rid)
            server._batches[fam.name] = batch

        for rid in order:
            if rid in done or rid in restored:
                continue
            server.submit(requests[rid])
        return server


# One lazy-jax tree.map wrapper for the whole package (batcher.py owns it).
_tree_map = batcher_mod._tree_map
