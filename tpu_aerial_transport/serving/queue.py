"""Bounded admission queue with per-request SLO accounting.

Requests are heterogeneous (:class:`ScenarioRequest`: family = a
registered (controller, n, chunk shape) program, horizon, scenario
parameters, deadline); admission control REJECTS with a structured reason
— never an exception into the server loop — when the queue is full, when
the request's family has no compiled-bucket coverage, or when the request
cannot be served as specified (horizon off the chunk grid, deadline
already spent). Every transition lands as a ``serving_event`` metrics
row (``obs.export`` schema v4) so ``tools/run_health.py`` can render
admit→complete latency percentiles and rejection/deadline-miss counts
without instrumenting the caller.

The SLO clock per request::

    t_submit --(queue)--> t_admit --(lane wait)--> t_launch --> t_complete
                 |                                        |
                 +-- deadline passes: missed "in_queue"   +-- "in_flight"

``t_admit`` is when the request entered a device batch lane (at a batch
launch or a later chunk boundary — the continuous-batching seam);
``t_launch`` is the first chunk dispatch that contained it.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time

import numpy as np

from tpu_aerial_transport.obs import trace as trace_mod

# Statuses a ticket resolves to.
PENDING = "pending"
COMPLETED = "completed"
REJECTED = "rejected"
DEADLINE_MISSED = "deadline_missed"

# Structured rejection reasons (admission control).
REASON_QUEUE_FULL = "queue_full"
REASON_NO_COVERAGE = "no_bucket_coverage"
REASON_BAD_HORIZON = "horizon_not_chunk_aligned"
REASON_DEADLINE_SPENT = "deadline_already_passed"
REASON_TENANT_RATE = "tenant_rate_limited"
# Closed-loop session tier (serving/sessions.py) — same structured
# reject-with-reason discipline, resolved BEFORE any server interaction:
# a zombie client presenting a fenced (stale) lease token, and an
# out-of-order / replayed step_seq.
REASON_LEASE_FENCED = "lease_fenced"
REASON_STALE_STEP = "stale_step"

DEFAULT_TENANT = "default"

# Deadline-miss classification.
MISSED_IN_QUEUE = "in_queue"
MISSED_IN_FLIGHT = "in_flight"

_req_counter = itertools.count()


@dataclasses.dataclass(frozen=True)
class ScenarioRequest:
    """One scenario-MPC job. ``family`` names a server-registered program
    family (controller + n + chunk shape); ``horizon`` is the requested
    high-level step count (must be a multiple of the family's chunk
    length — chunk boundaries are the only admission/harvest seams);
    ``x0``/``v0`` are the scenario's initial payload position/velocity;
    ``deadline_s`` is a wall-clock budget relative to submission (None =
    no deadline)."""

    family: str
    horizon: int
    x0: tuple = (0.0, 0.0, 0.0)
    v0: tuple = (0.0, 0.0, 0.0)
    deadline_s: float | None = None
    request_id: str = dataclasses.field(
        default_factory=lambda: f"req{next(_req_counter):06d}"
    )
    # Multi-tenant admission (serving fleet tier): the tenant the
    # request bills against — rate limits, weighted-fair dequeue share
    # and priority class come from the queue's per-tenant policy table,
    # never from the (client-controlled) request itself.
    tenant: str = DEFAULT_TENANT
    # Distributed-tracing context (obs.trace): clients propagating an
    # upstream trace set it; otherwise admission mints one when the
    # server runs a tracer. Journaled with the request so a resumed
    # run's spans land on the SAME trace as the preempted run's.
    trace_id: str | None = None
    # Closed-loop session tier (serving/sessions.py): the owning
    # session_id when this request is one delta-state step of a live
    # session, None for one-shot requests. Session steps are NEVER
    # served from (or written into) the content-addressed result cache
    # — the cache key is the full (family, x0/v0, horizon) content
    # address, but a step's identity includes its session and step_seq,
    # and serving it from cache would skip the lane write the session's
    # state stream is defined by. Journaled so resume keeps the step's
    # session binding.
    session: str | None = None

    def to_json(self) -> dict:
        return {
            "request_id": self.request_id,
            "family": self.family,
            "horizon": int(self.horizon),
            "x0": [float(v) for v in np.asarray(self.x0).reshape(-1)],
            "v0": [float(v) for v in np.asarray(self.v0).reshape(-1)],
            "deadline_s": (None if self.deadline_s is None
                           else float(self.deadline_s)),
            **({"trace_id": self.trace_id} if self.trace_id else {}),
            **({"tenant": self.tenant}
               if self.tenant != DEFAULT_TENANT else {}),
            **({"session": self.session} if self.session else {}),
        }

    @classmethod
    def from_json(cls, obj: dict) -> "ScenarioRequest":
        return cls(
            family=obj["family"], horizon=obj["horizon"],
            x0=tuple(obj["x0"]), v0=tuple(obj["v0"]),
            deadline_s=obj.get("deadline_s"),
            request_id=obj["request_id"],
            trace_id=obj.get("trace_id"),
            tenant=obj.get("tenant", DEFAULT_TENANT),
            session=obj.get("session"),
        )


@dataclasses.dataclass
class SLO:
    """Per-request SLO record: host timestamps (``clock`` domain — the
    server's monotonic clock by default) plus the deadline bookkeeping."""

    t_submit: float | None = None
    t_admit: float | None = None
    t_launch: float | None = None
    t_complete: float | None = None
    deadline_at: float | None = None  # absolute, clock domain.
    missed: str | None = None         # MISSED_IN_QUEUE / MISSED_IN_FLIGHT.

    def to_event(self) -> dict:
        out = {k: v for k, v in dataclasses.asdict(self).items()
               if v is not None}
        if self.t_complete is not None and self.t_submit is not None:
            out["latency_s"] = self.t_complete - self.t_submit
        if self.t_complete is not None and self.t_admit is not None:
            out["admit_to_complete_s"] = self.t_complete - self.t_admit
        return out


class Ticket:
    """The caller's handle for a submitted request: status, SLO record,
    and (on completion) the request's final scenario state as a host
    pytree. ``wait()`` blocks a consumer thread until resolution — the
    async side of the host pipeline; the server itself never blocks on
    tickets."""

    def __init__(self, request: ScenarioRequest):
        self.request = request
        self.slo = SLO()
        self.status = PENDING
        self.reason: str | None = None
        self.result = None        # host pytree: the lane's final carry.
        self.steps_served = 0
        self.batch_id: int | None = None
        self.lane: int | None = None
        # obs.trace.RequestTrace when the server runs a tracer; None is
        # the zero-cost path (every consumer guards on it).
        self.trace: trace_mod.RequestTrace | None = None
        self._done = threading.Event()

    @property
    def done(self) -> bool:
        return self.status != PENDING

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def _resolve(self, status: str, reason: str | None = None) -> None:
        self.status = status
        self.reason = reason
        self._done.set()

    def __repr__(self) -> str:  # operator-facing.
        return (f"Ticket({self.request.request_id}, {self.status}"
                + (f", {self.reason}" if self.reason else "") + ")")


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """Admission policy for one tenant.

    ``rate_per_s``/``burst`` parameterize a token bucket: each submit
    spends one token, tokens refill continuously at ``rate_per_s`` up to
    ``burst``; an empty bucket rejects with the structured
    ``tenant_rate_limited`` reason (never an exception in the front
    loop). ``rate_per_s=None`` disables the bucket (the default tenant's
    policy, so single-tenant callers see the pre-fleet behavior
    byte-for-byte). ``weight`` is the tenant's weighted-fair dequeue
    share WITHIN its priority class; ``priority`` classes dequeue
    strictly high-to-low (an operator tier that must not queue behind
    batch traffic — starvation of lower classes is the documented
    trade)."""

    rate_per_s: float | None = None
    burst: int = 8
    weight: float = 1.0
    priority: int = 0


class _TokenBucket:
    """Continuous-refill token bucket on the queue's clock domain."""

    def __init__(self, policy: TenantPolicy, now: float):
        self.rate = float(policy.rate_per_s)
        self.capacity = max(1.0, float(policy.burst))
        self.tokens = self.capacity
        self.t_last = now

    def try_take(self, now: float) -> bool:
        self.tokens = min(self.capacity,
                          self.tokens + self.rate * (now - self.t_last))
        self.t_last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class AdmissionQueue:
    """Bounded multi-tenant queue with admission control.

    ``coverage`` maps a family name to its served chunk length (``int``)
    or ``None`` when the family has no compiled-bucket coverage (unknown
    family, or — in strict bundled mode — no bundle entry/variant); the
    server supplies it so the queue never imports device code. ``emit``
    is the server's ``serving_event`` sink (may be None).

    ``tenants`` maps tenant names to :class:`TenantPolicy`; tenants not
    in the table get the default policy (unlimited rate, weight 1,
    priority 0), so the single-tenant path is unchanged. ``submit`` is
    thread-safe (one lock over queue state; ticket ids come from a
    process-global counter), the fleet front's concurrent-submitter
    contract."""

    def __init__(self, coverage, capacity: int = 256,
                 clock=time.monotonic, emit=None, tracer=None,
                 tenants: dict[str, TenantPolicy] | None = None,
                 hub=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.coverage = coverage
        self.capacity = capacity
        self.clock = clock
        # `is None`, not truthiness: a falsy-but-callable sink (a Mock,
        # a partial with no __bool__ guarantee) must still be used.
        self.emit = (lambda **kw: None) if emit is None else emit
        self.tracer = tracer  # obs.trace.Tracer | None (zero-cost off).
        # obs.live.MetricsHub | None: live counters/gauges for the
        # console. None is the zero-cost path — every touch is guarded
        # `is not None` and allocates nothing.
        self.hub = hub
        self.tenants = dict(tenants or {})
        self._default_policy = TenantPolicy()
        self._buckets: dict[str, _TokenBucket] = {}
        # family -> tenant -> FIFO (arrival order within a tenant; the
        # cross-tenant order is weighted-fair at take() time).
        self._pending: dict[str, dict[str, list[Ticket]]] = {}
        # Weighted-fair bookkeeping: dequeues charged per (family,
        # tenant), normalized by weight at selection time.
        self._served: dict[tuple[str, str], float] = {}
        self._lock = threading.Lock()

    def policy(self, tenant: str) -> TenantPolicy:
        return self.tenants.get(tenant, self._default_policy)

    # ------------------------------------------------------ admission --
    def submit(self, request: ScenarioRequest) -> Ticket:
        """Admit or reject one request. ALWAYS returns a resolved-or-
        pending ticket (rejection is a structured status + reason +
        ``serving_event``, never an exception). Safe to call from
        multiple threads concurrently."""
        if self.tracer is not None and request.trace_id is None:
            # Mint the trace context ON the request so journal replays /
            # resumes keep the same trace identity.
            request = dataclasses.replace(
                request, trace_id=trace_mod.new_trace_id()
            )
        ticket = Ticket(request)
        with self._lock:
            now = self.clock()
            ticket.slo.t_submit = now
            if request.deadline_s is not None:
                ticket.slo.deadline_at = now + float(request.deadline_s)
            if self.tracer is not None:
                root = self.tracer.begin(
                    trace_mod.REQUEST, parent=None,
                    trace_id=request.trace_id,
                    request_id=request.request_id, family=request.family,
                    horizon=int(request.horizon),
                )
                ticket.trace = trace_mod.RequestTrace(self.tracer, root)

            reason = self._admission_reason(request, now)
            if reason is None:
                if ticket.trace is not None:
                    ticket.trace.queue_span = self.tracer.begin(
                        trace_mod.QUEUE_WAIT,
                        parent=ticket.trace.request_span,
                        request_id=request.request_id,
                        family=request.family,
                    )
                self._pending.setdefault(request.family, {}).setdefault(
                    request.tenant, []
                ).append(ticket)
            depth = self._depth()
        # Emit + trace-resolve AFTER release (HL003): the metrics sink
        # fsyncs per event and span ends write trace rows — holding the
        # admission lock across those syscalls would serialize every
        # concurrent submitter behind disk. Resolving the rejected
        # ticket out here is safe: it was never appended to the pending
        # FIFO, so no other thread can reach it until submit returns.
        if reason is not None:
            ticket._resolve(REJECTED, reason)
            self.emit(kind="rejected", request_id=request.request_id,
                      family=request.family, reason=reason,
                      tenant=request.tenant, depth=depth)
            if self.hub is not None:
                # Hub updates AFTER the lock too: the hub's own leaf
                # lock is lock-free dict math, but keeping every
                # observability side effect on one side of the
                # admission lock keeps the HL003/HL004 reasoning local.
                self.hub.inc("queue.rejected", key=reason)
                self.hub.gauge("queue.depth", depth)
            if ticket.trace is not None:
                # Terminal span: the rejection IS the request's trace.
                ticket.trace.resolve(REJECTED, reason=reason)
            return ticket
        self.emit(kind="submitted", request_id=request.request_id,
                  family=request.family, horizon=request.horizon,
                  tenant=request.tenant, depth=depth)
        if self.hub is not None:
            self.hub.inc("queue.submitted", key=request.tenant)
            self.hub.gauge("queue.depth", depth)
        return ticket

    def _admission_reason(self, request: ScenarioRequest,
                          now: float) -> str | None:
        if self._depth() >= self.capacity:
            return REASON_QUEUE_FULL
        chunk_len = self.coverage(request.family)
        if chunk_len is None:
            return REASON_NO_COVERAGE
        if request.horizon <= 0 or request.horizon % chunk_len:
            return REASON_BAD_HORIZON
        if request.deadline_s is not None and request.deadline_s <= 0:
            return REASON_DEADLINE_SPENT
        # Token bucket LAST: a malformed request is rejected as such
        # (and costs the tenant nothing), not masked as throttling.
        policy = self.policy(request.tenant)
        if policy.rate_per_s is not None:
            bucket = self._buckets.get(request.tenant)
            if bucket is None:
                bucket = self._buckets[request.tenant] = _TokenBucket(
                    policy, now
                )
            if not bucket.try_take(now):
                return REASON_TENANT_RATE
        return None

    # ------------------------------------------------------- draining --
    def _depth(self, family: str | None = None) -> int:
        if family is not None:
            return sum(len(q) for q in
                       self._pending.get(family, {}).values())
        return sum(len(q) for by_tenant in self._pending.values()
                   for q in by_tenant.values())

    def depth(self, family: str | None = None) -> int:
        with self._lock:
            return self._depth(family)

    def families_pending(self) -> list[str]:
        with self._lock:
            return sorted(
                f for f, by_tenant in self._pending.items()
                if any(by_tenant.values())
            )

    def take(self, family: str, k: int) -> list[Ticket]:
        """Pop up to ``k`` pending tickets of ``family`` (the batcher
        admits them into device lanes): strictly by priority class
        (high first), weighted-fair across tenants within a class
        (each dequeue charges the tenant 1/weight; the least-charged
        tenant goes next), FIFO within a tenant."""
        with self._lock:
            by_tenant = self._pending.get(family, {})
            taken: list[Ticket] = []
            while len(taken) < k:
                candidates = [t for t, q in by_tenant.items() if q]
                if not candidates:
                    break
                top = max(self.policy(t).priority for t in candidates)
                tenant = min(
                    (t for t in candidates
                     if self.policy(t).priority == top),
                    key=lambda t: (self._served.get((family, t), 0.0), t),
                )
                taken.append(by_tenant[tenant].pop(0))
                self._served[(family, tenant)] = (
                    self._served.get((family, tenant), 0.0)
                    + 1.0 / max(self.policy(tenant).weight, 1e-9)
                )
        # Hub bump after the lock (same side as emit — HL003 locality).
        if self.hub is not None and taken:
            self.hub.inc("queue.dequeued", key=family, n=len(taken))
        return taken

    def expire_deadlines(self) -> list[Ticket]:
        """Resolve queued tickets whose deadline passed before admission:
        status ``deadline_missed``, classified ``in_queue``."""
        missed: list[tuple[Ticket, str, str]] = []
        with self._lock:
            now = self.clock()
            for family, by_tenant in self._pending.items():
                for tenant, fifo in by_tenant.items():
                    keep = []
                    for t in fifo:
                        if (t.slo.deadline_at is not None
                                and now >= t.slo.deadline_at):
                            t.slo.missed = MISSED_IN_QUEUE
                            t._resolve(DEADLINE_MISSED)
                            missed.append((t, family, tenant))
                        else:
                            keep.append(t)
                    by_tenant[tenant] = keep
        # Emit + trace-resolve after release (HL003): state changed
        # atomically above; the fsync'd events need no lock.
        for t, family, tenant in missed:
            self.emit(kind="deadline_missed",
                      request_id=t.request.request_id,
                      family=family, tenant=tenant,
                      missed=MISSED_IN_QUEUE,
                      slo=t.slo.to_event())
            if self.hub is not None:
                self.hub.inc("queue.deadline_missed", key=tenant)
            if t.trace is not None:
                t.trace.resolve(DEADLINE_MISSED, missed=MISSED_IN_QUEUE)
        return [t for t, _, _ in missed]
