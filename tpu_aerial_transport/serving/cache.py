"""Content-addressed serving result cache (ISSUE 18 satellite): a
scenario request is a PURE function of ``(family program, x0, v0,
horizon)`` — the serving chunk is deterministic by the chunked-rollout
contract and the family's config hash pins every solver/shape knob — so
a completed result can be served again without touching the device.

Keys are sha256 over the family's ``config_hash`` (which already folds
the full :class:`FamilySpec`), the horizon, and the canonical little-
endian float bytes of ``x0``/``v0`` (the ``aot/`` content-addressing
discipline; tenant/deadline/request identity deliberately excluded —
they change SLO accounting, not the computed trajectory). Values are
deep-copied numpy result pytrees plus the served step count; the cache
is LRU-bounded and hits/misses are counted for ``run_health``'s hit
rate. Host-only and lock-free by design: it lives inside the server's
single-threaded pump loop, same as the batcher's bookkeeping.
"""

from __future__ import annotations

import collections
import hashlib

import numpy as np


def request_key(config_hash: str, request) -> str:
    """The content address of one request's result (see module doc)."""
    h = hashlib.sha256()
    h.update(config_hash.encode())
    h.update(str(int(request.horizon)).encode())
    for vec in (request.x0, request.v0):
        h.update(np.asarray(vec, np.float64).astype("<f8").tobytes())
    return h.hexdigest()


def _copy_tree(tree):
    import jax

    return jax.tree.map(lambda x: np.array(x, copy=True), tree)


class ResultCache:
    """LRU-bounded completed-result cache. ``get`` returns
    ``(result, steps_served)`` copies (callers own their ticket results
    and may mutate them) or ``None``; ``put`` stores COMPLETED results
    only — the caller enforces that, because a deadline-missed ticket's
    result is legitimate data but its status is an SLO verdict that must
    not be replayed onto a fresh request."""

    def __init__(self, max_entries: int = 256):
        if max_entries < 1:
            raise ValueError("ResultCache needs max_entries >= 1")
        self.max_entries = int(max_entries)
        self._entries: collections.OrderedDict = collections.OrderedDict()
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, key: str):
        entry = self._entries.get(key)
        if entry is None:
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        result, steps = entry
        return _copy_tree(result), steps

    def put(self, key: str, result, steps_served: int) -> None:
        if result is None:
            return
        self._entries[key] = (_copy_tree(result), int(steps_served))
        self._entries.move_to_end(key)
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)

    def stats(self) -> dict:
        lookups = self.hits + self.misses
        return {
            "entries": len(self._entries),
            "max_entries": self.max_entries,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": (self.hits / lookups) if lookups else None,
        }
