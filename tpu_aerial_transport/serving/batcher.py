"""Continuous batching: heterogeneous requests grouped by shape bucket
into device batches that reuse ONE compiled chunk program per bucket.

A **family** is a served program: controller + agent count + chunk shape
(:class:`FamilySpec`, pure data). Its device program is the PR-4 chunked
rollout's single compiled chunk ``(carry, i0) -> (carry, logs)`` vmapped
over a leading lane axis — so the compiled shapes are keyed on
``(family, bucket)`` and NEVER churn: partially-full batches pad with
quarantined filler lanes (copies of the family template whose results
are discarded), and the bucket for a group of admitted requests is the
smallest admitting one (``harness.bucketing.pick_bucket`` — the same
rule the AOT loader uses to pick a precompiled batch variant, so
admission-control coverage and bundle coverage agree by construction).

Chunk boundaries are the continuous-batching seam: after every chunk,
lanes whose requests finished their horizon are harvested (result = the
lane's slice of the boundary carry) and late-arriving requests of the
same family are admitted into the freed/filler lanes by host-side lane
surgery on the boundary carry — no reshape, no recompile.

Lane independence contract: a lane's result must not depend on which
OTHER lanes share its batch (admission order, filler contents) or on the
batch's global step offset. The first holds because vmapped lanes
compute independently (the worst-lane ``while_loop`` trip count freezes
converged lanes' carries exactly — asserted for regrouping by
tests/test_bucketing.py and for serving by tests/test_serving.py); the
second is why :func:`make_family` builds a TIME-INVARIANT tracking
reference (``acc_des_fn`` ignores ``t``) — lanes admitted at different
chunk boundaries run at different global offsets inside one batch.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tpu_aerial_transport.harness.bucketing import pick_bucket
from tpu_aerial_transport.serving import queue as queue_mod

# Default shape buckets (bucket_dim grid, f32 sublane tile multiples).
DEFAULT_BUCKETS = (8, 16, 32)


@dataclasses.dataclass(frozen=True)
class FamilySpec:
    """One served program family (pure data — hashable, journalable).
    ``entry`` names the family's ``analysis.entrypoints`` registry /
    AOT-bundle entry when it has one (the canonical families below do;
    ad-hoc families serve through the jit rung only)."""

    name: str
    controller: str = "cadmm"
    n: int = 4
    chunk_len: int = 2
    hl_rel_freq: int = 2
    max_iter: int = 2
    inner_iters: int = 4
    entry: str | None = None
    # The family's on-device boundary lane-surgery entrypoint
    # (serving/lanes.py), when it has one: device-surgery mode serves it
    # through the same ladder as ``entry`` so zero-compile replicas stay
    # zero-compile. None => host splice only.
    surgery_entry: str | None = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


# The canonical families: ONE source of truth shared by the contract
# registry (analysis/contracts.py builds the serving_chunk entrypoints
# from these), the AOT bundle (its variants ARE the serving tier's
# zero-compile admission surface), and the server's defaults — so a
# bundle built from the registry always signature-matches the batches
# the server dispatches.
CANONICAL_FAMILIES: dict[str, FamilySpec] = {
    "cadmm4": FamilySpec(
        name="cadmm4", controller="cadmm", n=4,
        entry="serving.batcher:serving_chunk",
        surgery_entry="serving.lanes:lane_surgery",
    ),
    "centralized4": FamilySpec(
        name="centralized4", controller="centralized", n=4,
        entry="serving.batcher:serving_chunk_centralized",
        surgery_entry="serving.lanes:lane_surgery_centralized",
    ),
}


class Family:
    """A family's host-side handles. Device-program construction is LAZY
    (`.chunk_fn` / `.batched_jit` / `.template_carry_host()`): a strict
    bundled replica never builds them — its template carry comes from the
    bundle's ``args_sample`` and its dispatches replay precompiled
    executables, so the process stays zero-compile."""

    def __init__(self, spec: FamilySpec):
        self.spec = spec
        self._built = None
        self._batched_jit = None
        self._template_host = None
        self._surgery_jit = None
        self._templates_b: dict[int, object] = {}
        self._config_hash: str | None = None

    @property
    def name(self) -> str:
        return self.spec.name

    @property
    def chunk_len(self) -> int:
        return self.spec.chunk_len

    @property
    def entry(self) -> str | None:
        return self.spec.entry

    # ------------------------------------------------ lazy jnp builds --
    def _build(self):
        if self._built is None:
            self._built = _build_chunk(self.spec)
        return self._built

    @property
    def chunk_fn(self):
        """Unjitted single-scenario chunk ``(carry, i0) -> (carry, logs)``."""
        return self._build()[0]

    @property
    def batched_fn(self):
        """Unjitted batched chunk ``(batch_carry, i0) -> (batch_carry,
        logs)`` — the registry/bundle entry callable (lanes vmapped, step
        offset scalar, ``tat.serving_chunk`` scope on the plumbing)."""
        import jax

        from tpu_aerial_transport.obs import phases

        chunk_fn = self.chunk_fn

        def batched(carry, i0):
            with phases.scope(phases.SERVING_CHUNK):
                return jax.vmap(chunk_fn, in_axes=(0, None))(carry, i0)

        return batched

    @property
    def batched_jit(self):
        """The family's ONE jitted batched chunk (pre-jitted so
        ``aot.loader.serve_entry`` reuses its cache across requests)."""
        if self._batched_jit is None:
            import jax

            self._batched_jit = jax.jit(self.batched_fn)
        return self._batched_jit

    def template_carry_host(self):
        """The family's canonical initial lane carry as a HOST pytree
        (identity attitudes, equilibrium warm starts). Built through the
        jnp state factories — pays their eager compiles — so bundled
        servers override it with the bundle's ``args_sample`` instead
        (``server.ScenarioServer``)."""
        if self._template_host is None:
            from tpu_aerial_transport.resilience.recovery import host_copy

            self._template_host = host_copy(self._build()[1])
        return self._template_host

    def set_template_carry_host(self, template) -> None:
        """Install an externally sourced template (the bundle's
        ``args_sample`` lane) — numpy leaves, no device work."""
        self._template_host = template
        self._templates_b = {}

    def batched_template_host(self, bucket: int):
        """The template carry stacked to ``bucket`` lanes (host numpy,
        cached per bucket) — the device lane surgery's ``template_b``
        operand and the launch-time batch padding source."""
        if bucket not in self._templates_b:
            self._templates_b[bucket] = _tree_map(
                lambda x: np.stack([np.asarray(x)] * bucket),
                self.template_carry_host(),
            )
        return self._templates_b[bucket]

    @property
    def surgery_entry(self) -> str | None:
        return self.spec.surgery_entry

    @property
    def surgery_jit(self):
        """The family's ONE pre-jitted donated lane-surgery program
        (serving/lanes.py) — the jit-rung fallback for device-surgery
        mode. The carry is donated: the chunk output it consumes is dead
        after the boundary (the chunk program itself is non-donating, so
        the PREVIOUS carry stays valid for host snapshots)."""
        if self._surgery_jit is None:
            import jax

            from tpu_aerial_transport.serving import lanes as lanes_mod

            self._surgery_jit = jax.jit(
                lanes_mod.lane_surgery, donate_argnums=(0,)
            )
        return self._surgery_jit

    # ------------------------------------------------- host-side lanes --
    def lane_carry(self, template, request: queue_mod.ScenarioRequest):
        """A fresh lane carry for ``request``: the template with the
        scenario's initial payload position/velocity written in. Pure
        numpy — callable on the zero-compile path."""
        state, rest = template[0], template[1:]
        dtype = np.asarray(state.xl).dtype
        state = state.replace(
            xl=np.asarray(request.x0, dtype),
            vl=np.asarray(request.v0, dtype),
        )
        return (state,) + tuple(rest)

    def lane_result(self, carry_host, lane: int):
        """A completed lane's deliverable: the final SCENARIO STATE
        (carry element 0), copied out of the boundary carry. The
        controller state (warm starts, duals, per-solve residual
        diagnostics) is server-internal and deliberately excluded — its
        scalar residual diagnostics are reduction-order artifacts that
        vary with the surrounding batch's bucket size on XLA-CPU, while
        the scenario state itself is bitwise composition-independent
        (asserted by tests/test_serving.py across buckets, filler
        padding, and late joins)."""
        import jax

        return jax.tree.map(
            lambda x: np.array(x[lane], copy=True), carry_host[0]
        )

    def config_hash(self) -> str:
        # Memoized: the spec is frozen and the result-cache path hashes
        # per submit.
        if self._config_hash is None:
            from tpu_aerial_transport.harness.checkpoint import (
                config_fingerprint,
            )

            self._config_hash = config_fingerprint(
                family=self.spec.to_json()
            )
        return self._config_hash


def _build_chunk(spec: FamilySpec):
    """Build the family's unjitted single-scenario chunk + canonical
    initial carry (jnp path). The tracking reference is TIME-INVARIANT
    (PD toward a fixed hover anchor — ``acc_des_fn`` drops ``t``): see
    the module docstring's lane-independence contract."""
    import jax.numpy as jnp

    from tpu_aerial_transport.control import centralized, lowlevel
    from tpu_aerial_transport.harness import rollout as h_rollout
    from tpu_aerial_transport.harness import setup

    params, col, state0 = setup.rqp_setup(spec.n)
    f_eq = centralized.equilibrium_forces(params)
    llc = lowlevel.make_lowlevel_controller("pd", params)
    anchor = jnp.zeros(3, jnp.float32)

    def acc_des_fn(state, t):
        del t  # time-invariant: lanes at different offsets are legal.
        dvl = -1.0 * state.vl - 1.0 * (state.xl - anchor)
        return (dvl, jnp.zeros(3, state.xl.dtype)), anchor, jnp.zeros(3)

    if spec.controller == "cadmm":
        from tpu_aerial_transport.control import cadmm

        # pad_operators pinned True: the serving chunk is a registered
        # TC104-enforced entrypoint — the tile-target program structure is
        # checked even on a CPU host (same pinning as the resilient
        # contract builders).
        cfg = cadmm.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=spec.max_iter, inner_iters=spec.inner_iters,
            pad_operators=True,
        )
        plan = cadmm.make_plan(params, cfg)
        cs0 = cadmm.init_cadmm_state(params, cfg)

        def hl(cs, s, a):
            return cadmm.control(params, cfg, f_eq, cs, s, a, plan=plan)

    elif spec.controller == "dd":
        from tpu_aerial_transport.control import dd

        cfg = dd.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=spec.max_iter, inner_iters=spec.inner_iters,
            pad_operators=True,
        )
        plan = dd.make_dd_plan(params, cfg)
        cs0 = dd.init_dd_state(params, cfg)

        def hl(cs, s, a):
            return dd.control(params, cfg, f_eq, cs, s, a, plan=plan)

    elif spec.controller == "centralized":
        cfg = centralized.make_config(
            params, col.collision_radius, col.max_deceleration,
            solver_iters=max(spec.inner_iters, 4),
        )
        cs0 = centralized.init_ctrl_state(params, cfg)

        def hl(cs, s, a):
            return centralized.control(params, cfg, f_eq, cs, s, a)

    else:
        raise ValueError(f"unknown serving controller {spec.controller!r}")

    run = h_rollout.make_chunked_rollout(
        hl, llc.control, params,
        n_hl_steps=spec.chunk_len, n_chunks=1,
        hl_rel_freq=spec.hl_rel_freq, acc_des_fn=acc_des_fn,
        donate=False,  # boundary carries are harvested/spliced host-side.
    )
    return run.chunk_fn, run.init_carry(state0, cs0)


def make_family(spec: FamilySpec | str) -> Family:
    if isinstance(spec, str):
        spec = CANONICAL_FAMILIES[spec]
    return Family(spec)


# ----------------------------------------------------------------------
# The per-family continuous batch.
# ----------------------------------------------------------------------

_next_batch_id = 0


def _alloc_batch_id() -> int:
    global _next_batch_id
    i = _next_batch_id
    _next_batch_id += 1
    return i


def reserve_batch_ids(past: int) -> None:
    """Advance the process-wide batch-id allocator so every FUTURE batch
    id is >= ``past`` (never moves it backward). ``ScenarioServer.resume``
    calls this with (max journaled batch id + 1): a fresh process's
    allocator restarts at 0, and a post-resume launch reusing a journaled
    id would collide snapshot prefixes (``serving_b<id>``) and journal
    identities with the restored batch — a second resume could then
    silently restore another request's carry."""
    global _next_batch_id
    _next_batch_id = max(_next_batch_id, past)


def _tree_map(fn, *trees):
    import jax

    return jax.tree.map(fn, *trees)


class Batch:
    """Host bookkeeping for one in-flight device batch of ``bucket``
    lanes. The device carry itself is owned by the server (which runs the
    chunks); this class owns lane assignment, per-lane remaining-chunk
    counts, SLO transitions, and the boundary carry's lane surgery."""

    def __init__(self, family: Family, bucket: int, template,
                 clock, emit, batch_id: int | None = None):
        self.family = family
        self.bucket = bucket
        self.batch_id = (_alloc_batch_id() if batch_id is None
                         else batch_id)
        self.clock = clock
        self.emit = emit
        # Filler lanes = template copies; results discarded (quarantined).
        self.carry_host = _tree_map(
            lambda x: np.stack([np.asarray(x)] * bucket), template
        )
        self.tickets: list[queue_mod.Ticket | None] = [None] * bucket
        self.remaining = np.zeros(bucket, np.int64)
        self.chunks_done = 0
        self.occupancy_samples: list[float] = []
        # Device-surgery mode (serving/lanes.py): the post-surgery carry
        # stays device-resident between chunks; carry_host is then only
        # refreshed for snapshot publication. None => host mode.
        self.carry_dev = None
        # Pipelined dispatch: the not-yet-blocked-on chunk dispatch
        # (server-owned record; discarded on preemption/retire).
        self.inflight = None

    # --------------------------------------------------------- lanes ---
    @property
    def active_lanes(self) -> int:
        return sum(t is not None for t in self.tickets)

    @property
    def retired(self) -> bool:
        return self.active_lanes == 0

    def free_lanes(self) -> list[int]:
        return [i for i, t in enumerate(self.tickets) if t is None]

    def admit(self, ticket: queue_mod.Ticket, lane: int,
              remaining: int | None = None, *,
              write_carry: bool = True) -> None:
        """Lane surgery at a boundary (or at launch): write the request's
        initial carry into ``lane`` of the boundary carry and start its
        chunk countdown. ``write_carry=False`` is the device-surgery
        path: the carry write already happened on device
        (serving.lanes.lane_surgery) and this call does the ticket/SLO
        bookkeeping only."""
        req = ticket.request
        if write_carry:
            lane_carry = self.family.lane_carry(
                self.family.template_carry_host(), req
            )
            for dst, src in zip(
                _leaves(self.carry_host), _leaves(lane_carry)
            ):
                dst[lane] = src
        self.tickets[lane] = ticket
        self.remaining[lane] = (
            req.horizon // self.family.chunk_len
            if remaining is None else remaining
        )
        ticket.batch_id = self.batch_id
        ticket.lane = lane
        ticket.slo.t_admit = self.clock()
        if ticket.trace is not None:
            ticket.trace.admitted(batch_id=self.batch_id, lane=lane,
                                  bucket=self.bucket)
        self.emit(kind="admitted", request_id=req.request_id,
                  family=self.family.name, batch_id=self.batch_id,
                  lane=lane, bucket=self.bucket)

    def restore_lane(self, ticket: queue_mod.Ticket, lane: int,
                     remaining: int) -> None:
        """Resume-path bookkeeping ONLY: bind a ticket to a lane whose
        carry was just restored from a boundary snapshot — no lane
        surgery (writing the template over the restored mid-flight carry
        would restart the scenario)."""
        self.tickets[lane] = ticket
        self.remaining[lane] = remaining
        ticket.batch_id = self.batch_id
        ticket.lane = lane
        ticket.slo.t_admit = self.clock()
        if ticket.trace is not None:
            ticket.trace.admitted(batch_id=self.batch_id, lane=lane,
                                  bucket=self.bucket, restored=True)
        self.emit(kind="admitted", request_id=ticket.request.request_id,
                  family=self.family.name, batch_id=self.batch_id,
                  lane=lane, bucket=self.bucket, restored=True)

    # ------------------------------------------------------ boundary ---
    def record_launch(self) -> None:
        """Called just before each chunk dispatch: stamp t_launch on
        newly admitted lanes and sample occupancy."""
        now = self.clock()
        for t in self.tickets:
            if t is not None and t.slo.t_launch is None:
                t.slo.t_launch = now
        self.occupancy_samples.append(self.active_lanes / self.bucket)

    def plan_finishing(self) -> list[int]:
        """Lanes whose requests finish at the NEXT boundary (their chunk
        countdown hits zero) — pure host admission-counter arithmetic,
        data-independent of the chunk's numeric results. This is what
        makes the device boundary plan (and with it double-buffered
        dispatch) legal: the surgery masks can be built, and chunk k+1
        dispatched, before chunk k's values ever reach the host."""
        return [lane for lane, t in enumerate(self.tickets)
                if t is not None and self.remaining[lane] <= 1]

    def harvest(self, state_host=None) -> list[queue_mod.Ticket]:
        """Process one completed chunk boundary: decrement countdowns,
        resolve lanes that finished their horizon (deadline-classified),
        free their lanes. Returns the resolved tickets.

        ``state_host`` (device-surgery mode): the harvested batched
        scenario state — the surgery program's second output transferred
        to host — read for lane results instead of ``carry_host`` (which
        device mode does not refresh per boundary)."""
        self.chunks_done += 1
        now = self.clock()
        results_src = (
            (state_host,) if state_host is not None else self.carry_host
        )
        finished: list[queue_mod.Ticket] = []
        for lane, ticket in enumerate(self.tickets):
            if ticket is None:
                continue
            self.remaining[lane] -= 1
            if self.remaining[lane] > 0:
                continue
            ticket.slo.t_complete = now
            ticket.result = self.family.lane_result(results_src, lane)
            ticket.steps_served = (
                ticket.request.horizon // self.family.chunk_len
            ) * self.family.chunk_len
            slo = ticket.slo
            if slo.deadline_at is not None and now > slo.deadline_at:
                slo.missed = queue_mod.MISSED_IN_FLIGHT
                ticket._resolve(queue_mod.DEADLINE_MISSED)
                self.emit(kind="deadline_missed",
                          request_id=ticket.request.request_id,
                          family=self.family.name,
                          batch_id=self.batch_id,
                          missed=queue_mod.MISSED_IN_FLIGHT,
                          slo=slo.to_event())
                if ticket.trace is not None:
                    ticket.trace.resolve(
                        queue_mod.DEADLINE_MISSED,
                        missed=queue_mod.MISSED_IN_FLIGHT,
                    )
            else:
                ticket._resolve(queue_mod.COMPLETED)
                self.emit(kind="completed",
                          request_id=ticket.request.request_id,
                          family=self.family.name,
                          batch_id=self.batch_id,
                          steps=ticket.steps_served,
                          slo=slo.to_event())
                if ticket.trace is not None:
                    ticket.trace.resolve(queue_mod.COMPLETED,
                                         steps=ticket.steps_served)
            self.tickets[lane] = None
            finished.append(ticket)
        return finished

    def lanes_json(self) -> list[list]:
        """Journal form of the lane map (resume reads it back)."""
        return [
            [lane, t.request.request_id, int(self.remaining[lane])]
            for lane, t in enumerate(self.tickets) if t is not None
        ]

    def lane_map(self) -> list[list]:
        """Trace form of the lane map: ``[[lane, request_id, trace_id],
        ...]`` — the attribute that links every member request's trace
        to the batch's shared device spans (obs.trace critical-path
        accounting keys on the trace ids)."""
        return [
            [lane, t.request.request_id,
             t.trace.trace_id if t.trace is not None
             else t.request.trace_id]
            for lane, t in enumerate(self.tickets) if t is not None
        ]

    def mean_occupancy(self) -> float | None:
        if not self.occupancy_samples:
            return None
        return float(np.mean(self.occupancy_samples))


def _leaves(tree):
    import jax

    return jax.tree.leaves(tree)


def bucket_for(pending: int, buckets) -> int:
    """Device-batch size for ``pending`` waiting requests: the smallest
    admitting bucket, or the largest bucket when more are waiting than
    any bucket holds (the rest stay queued for the next batch/boundary).
    """
    bs = sorted(buckets)
    picked = pick_bucket(min(pending, bs[-1]), bs)
    return bs[-1] if picked is None else picked
