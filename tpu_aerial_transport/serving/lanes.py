"""On-device boundary lane surgery for the continuous batcher
(ISSUE 18): harvest-read + filler-reset + late-join write as ONE jitted
select program over the batched boundary carry, plus the resolvers for
the serving tier's two perf knobs (surgery impl, dispatch mode).

The host-side seam this replaces (``serving/batcher.py``): after every
chunk the server copies the whole batch carry to host, splices late
joiners' initial states into freed lanes with numpy assignments, and
reads finished lanes' results out of the host copy — so the carry
round-trips host<->device once per boundary and chunk k+1 cannot
dispatch until the splice completes. :func:`lane_surgery` keeps the
carry device-resident: the harvested scenario state is returned as a
SECOND output (the pre-surgery ``carry[0]`` — exactly what the host
splice read), join lanes receive the family template with the request's
``x0``/``v0`` selected in, and freed-but-unfilled lanes are reset to the
pristine template (quarantined filler, same as launch padding). Every
write is a ``jnp.where`` lane select — selects copy exact bits, so the
device path is BITWISE-equal to the host splice (asserted across
alone/busy/late-join compositions and SIGTERM+resume by
tests/test_serving.py).

The batched surgery is registered per canonical family
(``serving.lanes:lane_surgery`` / ``:lane_surgery_centralized`` — the
carry pytree differs per controller), donated on the carry (TC105) and
bundled with batch-bucket variants (``aot/bundle.py BUCKETED_ENTRIES``)
so zero-compile replicas stay zero-compile: the boundary plan (which
lanes finish, which join) is pure host numpy over admission counters —
data-independent of the chunk's numeric results, which is also what
makes double-buffered dispatch legal (``serving/server.py``).
"""

from __future__ import annotations

import os

# ----------------------------------------------------------------------
# Knob resolvers (analysis/knobs.py registers both; HL008-checked).
# ----------------------------------------------------------------------

SURGERY_MODES = ("host", "device")
DISPATCH_MODES = ("sync", "pipelined")


def resolve_surgery(configured: str | None = None) -> str:
    """Resolve the serving lane-surgery implementation: ``host`` (the
    numpy splice on a host boundary copy) or ``device`` (the
    :func:`lane_surgery` select program on a device-resident carry).

    Precedence: ``TAT_SERVING_SURGERY`` env force > the server's
    ``surgery=`` config field > auto. Auto resolves to ``host``: on
    XLA-CPU the device "transfer" is a memcpy, so the surgery A/B
    (``bench.py serving_surgery_{host,device}``) measures select-program
    overhead against numpy splice cost with no PCIe term — host wins or
    ties there.

    FLIP CRITERION (the perf-knob discipline): flip the default to
    ``device`` when, on a real accelerator, the ``serving_surgery_device``
    sweep cell shows lower per-boundary wall time than
    ``serving_surgery_host`` AND the critical-path decomposition's
    ``surgery``+``harvest`` segments shrink at equal throughput — i.e.
    when eliminating the per-boundary host round-trip of the full batch
    carry (the real-chip cost the CPU tier cannot see) beats the extra
    select program. Device mode is also the prerequisite for pipelined
    dispatch, which has its own criterion below.
    """
    forced = os.environ.get("TAT_SERVING_SURGERY", "").strip().lower()
    mode = forced or (configured or "").strip().lower() or "host"
    if mode == "auto":
        mode = "host"
    if mode not in SURGERY_MODES:
        raise ValueError(
            f"TAT_SERVING_SURGERY/surgery={mode!r}: expected one of "
            f"{SURGERY_MODES} (or 'auto')"
        )
    return mode


def resolve_dispatch(configured: str | None = None) -> str:
    """Resolve the serving chunk-dispatch mode: ``sync`` (block on chunk
    k before planning boundary k) or ``pipelined`` (dispatch surgery and
    chunk k+1 asynchronously BEFORE blocking on chunk k's harvest
    transfer — legal because the boundary plan depends only on host
    admission counters, never on chunk k's numeric results).

    Precedence: ``TAT_SERVING_DISPATCH`` env force > the server's
    ``dispatch=`` config field > auto (``sync``). Pipelined dispatch
    requires device surgery (a host splice needs the chunk result on
    host, which is the serialization being removed); the server forces
    ``surgery=device`` when dispatch resolves pipelined.

    FLIP CRITERION: flip the default to ``pipelined`` when the
    ``serving_dispatch_pipelined`` sweep cell shows reduced boundary
    stall (the critical-path ``surgery``+``publish``+``harvest``+
    ``batch_wait`` sum per completed request) versus
    ``serving_dispatch_sync`` at equal result digests, on the serving
    deployment's real backend. On XLA-CPU compute and "transfer" share
    the host cores, so overlap buys little there — the cell exists to
    measure the seam, and the decision belongs to the chip round.
    """
    forced = os.environ.get("TAT_SERVING_DISPATCH", "").strip().lower()
    mode = forced or (configured or "").strip().lower() or "sync"
    if mode == "auto":
        mode = "sync"
    if mode not in DISPATCH_MODES:
        raise ValueError(
            f"TAT_SERVING_DISPATCH/dispatch={mode!r}: expected one of "
            f"{DISPATCH_MODES} (or 'auto')"
        )
    return mode


# ----------------------------------------------------------------------
# The surgery program.
# ----------------------------------------------------------------------

def lane_surgery(carry, template_b, x0, v0, join_mask, reset_mask):
    """One boundary's lane surgery on a batched chunk carry.

    Args (all batched over the leading lane axis ``B``):

    - ``carry``: the chunk program's output carry (``carry[0]`` is the
      batched scenario state; the rest is controller state) — donated by
      the registered jit;
    - ``template_b``: the family's pristine initial carry stacked to
      ``B`` lanes (host numpy from ``Family.template_carry_host`` or the
      bundle's ``args_sample`` — the zero-compile template source);
    - ``x0`` / ``v0``: ``(B, 3)`` initial payload position/velocity,
      row ``i`` meaningful only where ``join_mask[i]``;
    - ``join_mask``: ``(B,)`` bool — lanes a late-join request enters
      (template written in, then ``x0``/``v0`` selected into the
      scenario state — the exact writes ``Family.lane_carry`` + the
      host splice perform);
    - ``reset_mask``: ``(B,)`` bool — lanes freed at this boundary with
      no joiner: reset to the pristine template (quarantined filler,
      identical to launch-time padding).

    Returns ``(new_carry, harvested_state)`` where ``harvested_state``
    is the PRE-surgery ``carry[0]`` — the host reads finished lanes'
    results out of it (``Batch.harvest``), exactly as it read the
    boundary host copy before. Selects copy bits verbatim, so active
    lanes and harvested results are bitwise-identical to host surgery.
    """
    import jax
    import jax.numpy as jnp

    from tpu_aerial_transport.obs import phases

    with phases.scope(phases.LANE_SURGERY):
        harvested = carry[0]
        write = jnp.logical_or(join_mask, reset_mask)

        def lane_select(mask):
            def sel(new, old):
                m = jnp.reshape(mask, (-1,) + (1,) * (old.ndim - 1))
                return jnp.where(m, new.astype(old.dtype), old)

            return sel

        new_carry = jax.tree.map(
            lane_select(write), tuple(template_b), tuple(carry)
        )
        state = new_carry[0]
        state = state.replace(
            xl=lane_select(join_mask)(x0, state.xl),
            vl=lane_select(join_mask)(v0, state.vl),
        )
        return (state,) + tuple(new_carry[1:]), harvested


# The centralized family's surgery entry: the SAME select program — the
# registry/bundle entry is per-family only because the carry pytree (and
# with it the entry's abstract signature / precompiled variants) differs
# per controller.
lane_surgery_centralized = lane_surgery


def make_surgery_args(template_b, joins, resets, bucket: int):
    """Host-numpy operand build for :func:`lane_surgery` (everything
    after the carry): ``joins`` is ``[(lane, request), ...]``, ``resets``
    a lane list. Pure numpy — zero-compile replicas call this per
    boundary, so no jax ops and no device-array indexing here."""
    import numpy as np

    state = template_b[0]
    dtype = np.asarray(state.xl).dtype
    x0 = np.zeros((bucket, 3), dtype)
    v0 = np.zeros((bucket, 3), dtype)
    join_mask = np.zeros(bucket, bool)
    reset_mask = np.zeros(bucket, bool)
    for lane, req in joins:
        join_mask[lane] = True
        x0[lane] = np.asarray(req.x0, dtype)
        v0[lane] = np.asarray(req.v0, dtype)
    for lane in resets:
        reset_mask[lane] = True
    return (template_b, x0, v0, join_mask, reset_mask)
