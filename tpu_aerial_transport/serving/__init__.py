"""Scenario-serving tier: continuous batching, admission control, and
per-request SLO accounting over the package's compiled rollout programs.

The ROADMAP's "refactor that turns a bench harness into a service":
heterogeneous :class:`~tpu_aerial_transport.serving.queue.ScenarioRequest`
traffic is admitted through a bounded queue (``queue.py``), grouped by
shape bucket into donation-clean device batches that reuse ONE compiled
chunk program per bucket (``batcher.py`` — late arrivals join at the
PR-4 chunk seam), and driven by a host-side server whose every device
interaction goes through the backend guard and whose every compiled call
is served through the AOT bundle ladder (``server.py``). Preemption
safety rides the recovery tier's journal + snapshots: a SIGTERM mid-batch
completes at the chunk boundary and a restarted process re-admits the
remainder bit-identically.
"""

from tpu_aerial_transport.serving.queue import (  # noqa: F401
    AdmissionQueue,
    ScenarioRequest,
    Ticket,
)
