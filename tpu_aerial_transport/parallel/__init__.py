"""Mesh parallelism: agent-sharded consensus (psum/pmax over ICI) and
scenario-sharded Monte-Carlo batches."""

from tpu_aerial_transport.parallel import mesh  # noqa: F401
