"""Mesh parallelism: agent-sharded consensus (ring / psum collectives over
ICI) and scenario-sharded Monte-Carlo batches.

``ring`` (the consensus-exchange tier) imports eagerly — the controllers
import it at module load. ``mesh`` resolves LAZILY (PEP 562): it imports
the controllers, so an eager import here would cycle through
``control.cadmm -> parallel.ring -> parallel.__init__ -> mesh ->
control.cadmm`` while cadmm is half-initialized. Every existing caller
uses ``from tpu_aerial_transport.parallel import mesh`` (a submodule
import, unaffected); attribute access ``parallel.mesh`` keeps working via
``__getattr__``.
"""

from tpu_aerial_transport.parallel import ring  # noqa: F401


def __getattr__(name):
    if name in ("mesh", "pods"):
        # pods imports mesh lazily inside its functions, but resolving
        # both names here keeps `parallel.pods` attribute access working
        # under the same no-cycle rule as `parallel.mesh`.
        import importlib

        return importlib.import_module(
            f"tpu_aerial_transport.parallel.{name}"
        )
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
