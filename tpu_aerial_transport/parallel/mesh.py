"""Device-mesh parallelism layer: agent-axis sharding for the distributed
controllers and scenario-axis sharding for Monte-Carlo rollouts.

The reference has no communication backend at all — its "distributed" solvers
loop over agents in one process (SURVEY.md §2.10). Here the two scaling axes map
onto a ``jax.sharding.Mesh``:

- **agent axis**: ``shard_map`` the C-ADMM consensus loop so each device owns a
  block of agents' primal solvers; the consensus mean/residual run as
  ``lax.psum``/``pmax`` collectives over ICI (wired through
  ``control.cadmm.control(axis_name=...)``).
- **scenario axis**: Monte-Carlo batches of full rollouts ``vmap``-ed then
  sharded over the mesh with ``NamedSharding`` — pure data parallelism, no
  collectives, so XLA partitions it for free.

Tested on a virtual 8-device CPU mesh (conftest.py); the same code drives real
TPU slices (ICI) and multi-host DCN meshes unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_aerial_transport.control import cadmm, dd, rp_cadmm
from tpu_aerial_transport.envs import forest as forest_mod
from tpu_aerial_transport.models.rqp import RQPParams, RQPState
from tpu_aerial_transport.obs import phases
from tpu_aerial_transport.utils import compat


def make_mesh(axes: dict[str, int] | None = None, devices=None) -> Mesh:
    """Build a mesh over the available devices. Default: all devices on one
    ``"agent"`` axis. ``axes`` maps axis names to sizes (product must divide the
    device count; remaining devices are dropped)."""
    devices = devices if devices is not None else jax.devices()
    if axes is None:
        axes = {"agent": len(devices)}
    names = tuple(axes.keys())
    sizes = tuple(axes.values())
    total = int(np.prod(sizes))
    assert total <= len(devices), (axes, len(devices))
    dev_array = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(dev_array, names)


def _sharded_control(mesh: Mesh, axis: str, n: int, state_spec,
                     control_fn: Callable) -> Callable:
    """Shared shard_map plumbing for every agent-sharded controller: the
    divisibility check, the (state, replicated-state, replicated-acc) specs,
    and the check_vma workaround live in ONE place."""
    n_shards = mesh.shape[axis]
    assert n % n_shards == 0, (n, n_shards)

    @partial(
        compat.shard_map,  # version shim: jax.shard_map on new jax,
        # experimental shard_map (check_rep) on 0.4.x.
        mesh=mesh,
        in_specs=(state_spec, P(), (P(), P())),
        out_specs=(P(axis), state_spec, P()),
        check_vma=False,
    )
    def step(ctrl_state, state, acc_des):
        # Coarse attribution scope: the controllers' fine-grained tat.*
        # scopes live inside control_fn and (being innermost) win; this
        # one catches the shard_map plumbing around them.
        with phases.scope(phases.SHARDED_STEP):
            return control_fn(ctrl_state, state, acc_des)

    return step


def cadmm_control_sharded(
    params: RQPParams,
    cfg: cadmm.RQPCADMMConfig,
    f_eq: jnp.ndarray,
    mesh: Mesh,
    forest: forest_mod.Forest | None = None,
    axis: str = "agent",
) -> Callable:
    """Agent-sharded C-ADMM control step.

    Returns ``step(admm_state, state, acc_des) -> (f_app, admm_state, stats)``
    where every leading-``n`` leaf of ``admm_state`` and the returned ``f_app``
    are sharded over the ``axis`` mesh dimension; ``state``/``acc_des`` are
    replicated. Requires ``n % mesh.shape[axis] == 0``.
    """
    # State-independent Schur plan for ALL agents, computed once outside the
    # shard_map (replicated capture); each shard gathers its agent rows
    # inside cadmm.control.
    plan = cadmm.make_plan(params, cfg)

    state_spec = cadmm.CADMMState(
        f=P(axis), lam=P(axis), f_mean=P(),
        warm=jax.tree.map(lambda _: P(axis), _warm_structure()),
    )
    return _sharded_control(
        mesh, axis, params.n, state_spec,
        lambda cs, s, a: cadmm.control(
            params, cfg, f_eq, cs, s, a, forest, axis_name=axis, plan=plan
        ),
    )


def dd_control_sharded(
    params: RQPParams,
    cfg: dd.RQPDDConfig,
    f_eq: jnp.ndarray,
    mesh: Mesh,
    forest: forest_mod.Forest | None = None,
    axis: str = "agent",
) -> Callable:
    """Agent-sharded dual-decomposition control step (the C-ADMM twin above).

    Returns ``step(dd_state, state, acc_des) -> (f, dd_state, stats)`` with
    every leading-``n`` leaf of ``dd_state`` and the returned ``f`` sharded
    over ``axis``; ``state``/``acc_des``/``f_eq`` replicated. Price sums and
    consensus-violation sums run as ``psum`` and the 6n-dim quasi-Newton dual
    step replicates per shard after an ``all_gather`` (see
    ``control.dd.control``). Requires ``n % mesh.shape[axis] == 0``."""
    # State-independent QN plan, once, outside the shard_map (replicated).
    plan = dd.make_dd_plan(params, cfg)

    state_spec = dd.DDState(
        f=P(axis), F=P(axis), M=P(axis), lam_F=P(axis), lam_M=P(axis),
        warm=jax.tree.map(lambda _: P(axis), _warm_structure()),
    )
    return _sharded_control(
        mesh, axis, params.n, state_spec,
        lambda cs, s, a: dd.control(
            params, cfg, f_eq, cs, s, a, forest, axis_name=axis, plan=plan
        ),
    )


def rp_cadmm_control_sharded(
    params,
    cfg: rp_cadmm.RPCADMMConfig,
    f_eq: jnp.ndarray,
    mesh: Mesh,
    axis: str = "agent",
) -> Callable:
    """Agent-sharded RP consensus-ADMM control step (the beyond-reference
    RP distributed controller, control/rp_cadmm.py): each shard owns a
    block of agents' copies; the consensus mean rides psum(sum)/n and the
    residual pmax.

    Returns ``step(cstate, state, acc_des) -> (f_own, cstate, stats)`` with
    the leading-``n`` leaves of ``cstate`` and the returned ``f_own``
    sharded over ``axis``; ``state``/``acc_des`` replicated."""
    state_spec = rp_cadmm.RPCADMMState(
        f=P(axis), lam=P(axis),
        warm=jax.tree.map(lambda _: P(axis), _warm_structure()),
    )
    return _sharded_control(
        mesh, axis, params.n, state_spec,
        lambda cs, s, a: rp_cadmm.control(
            params, cfg, f_eq, cs, s, a, axis_name=axis
        ),
    )


def _warm_structure():
    """PartitionSpec skeleton matching SOCPSolution's 5 leaves."""
    from tpu_aerial_transport.ops.socp import SOCPSolution

    return SOCPSolution(x=0, y=0, z=0, prim_res=0, dual_res=0)


def is_multiprocess_mesh(mesh: Mesh) -> bool:
    """True when ``mesh`` spans devices of OTHER processes (the pods
    tier): plain ``jax.device_put`` cannot address them, so placement
    must assemble a global ``jax.Array`` from per-process host data
    (``parallel.pods.place_global_batch``)."""
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def shard_scenarios(mesh: Mesh, batch, axis: str = "scenario"):
    """Place a leading-axis Monte-Carlo batch pytree onto the mesh, sharded over
    ``axis`` (payloads/scenarios are independent — pure data parallelism).

    Works on the single-process meshes unchanged (``device_put`` with a
    ``NamedSharding``; a 2-D mesh replicates over the axes ``axis`` does
    not name). On a MULTI-process (pods) mesh the same call still works
    from host-global data: every process passes the full host batch and
    contributes the rows its devices own (``jax.make_array_from_callback``
    under the hood — parallel/pods.py), which is exactly the serving
    tier's ``mesh=`` contract (the server's carry_host is host-global on
    every process)."""
    if is_multiprocess_mesh(mesh):
        from tpu_aerial_transport.parallel import pods

        return pods.place_global_batch(mesh, batch, axis=axis)
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree.map(
        lambda x: jax.device_put(x, sharding) if hasattr(x, "ndim") and x.ndim
        else x,
        batch,
    )


def scenario_rollout(rollout_fn: Callable, mesh: Mesh, axis: str = "scenario",
                     donate: bool = True):
    """Wrap a single-scenario rollout into a sharded Monte-Carlo batch rollout:
    ``vmap`` over the leading scenario axis, jit with shardings so XLA keeps each
    scenario on its device (BASELINE.json config "256 scenarios x 8 agents").

    ``donate=True`` (default) donates the batched (states, ctrl_states)
    carries: a Monte-Carlo driver that chains batches (``batch = run(batch)
    [:2]``) updates every scenario's physics state and warm starts in place
    instead of re-allocating the whole sharded batch per call (TC105
    donation contract, analysis/contracts.py). Donated inputs are deleted —
    thread the returned batch forward, or pass ``donate=False`` to replay
    one initial batch repeatedly."""
    batched_jit = jax.jit(  # jit once: repeated runs hit the compile cache
        jax.vmap(rollout_fn),  # (a fresh wrapper per call would retrace).
        donate_argnums=(0, 1) if donate else (),
    )

    def run(batch_args):
        batch_args = shard_scenarios(mesh, batch_args, axis)
        return batched_jit(*batch_args)

    # Observability hook: the jaxlint trace contracts (analysis/contracts.py)
    # count cache misses and lower through the REAL compiled object.
    run.batched_jit = batched_jit
    return run


def vmap_chunk_jit(chunk_fn: Callable, donate: bool = False):
    """Batched-chunk jit for :func:`scenario_rollout_resumable`: vmap an
    unjitted single-scenario chunk ``(carry, i0) -> (carry, logs)`` over
    the leading lane axis (the step offset stays scalar) and jit it
    once. The serving tier's continuous batcher builds the SAME shape of
    program but wraps its vmap in the ``tat.serving_chunk`` attribution
    scope (``serving.batcher.Family.batched_fn``) — change batching
    semantics (in_axes, donation) in BOTH places or the serving batches
    silently diverge."""
    return jax.jit(
        jax.vmap(chunk_fn, in_axes=(0, None)),
        donate_argnums=(0,) if donate else (),
    )


def scenario_rollout_resumable(
    chunk_fn: Callable,
    mesh: Mesh,
    *,
    n_hl_steps: int,
    n_chunks: int,
    run_dir: str,
    axis: str = "scenario",
    donate: bool = False,
    config_hash: str | None = None,
    seed: int | None = None,
    keep_last: int = 3,
    max_retries: int = 1,
    meta: dict | None = None,
    metrics=None,
):
    """Preemption-safe serving twin of :func:`scenario_rollout`: the sharded
    Monte-Carlo batch rollout split into chunks, with the BATCHED carry
    snapshotted at every chunk boundary (``resilience.recovery`` +
    ``harness.checkpoint`` — atomic versioned snapshots, chunk journal) and
    a host-level retry that requeues the surviving work after a device
    error: the last boundary's host copy of the batch carry is re-placed
    onto the (possibly recovered) mesh via :func:`shard_scenarios` and the
    remaining chunks re-run — a wedged sweep loses at most one chunk of
    work instead of the whole batch (BENCH_r05.json's null row).

    ``chunk_fn`` is the UNJITTED single-scenario chunk ``(carry, i0) ->
    (carry, logs)`` — e.g. ``make_chunked_rollout(...).chunk_fn`` — vmapped
    over the leading scenario axis and jitted ONCE here. ``donate``
    defaults OFF in this recovery tier (bit-reproducibility under the
    persistent compilation cache; see
    ``harness.rollout.make_chunked_rollout``) — the snapshot-less
    throughput path with donated carries remains :func:`scenario_rollout`.

    Returns ``run(batch_carry, resume=False, interrupt=None) ->
    recovery.RunResult``; ``resume=True`` restores the newest fully-valid
    boundary from ``run_dir`` (``batch_carry`` then being the
    deterministically regenerated chunk-0 batch carry / template). The
    jitted batched chunk is exposed as ``run.batched_jit``.

    ``metrics`` (an ``obs.export.MetricsWriter`` or jsonl path) turns on
    the per-boundary flight-recorder export — see
    ``resilience.recovery.run_chunks``.
    """
    from tpu_aerial_transport.resilience import recovery

    if n_hl_steps % n_chunks:
        # RunPlan.chunk_len is a floor division: an uneven split would
        # feed chunk_index_offset a chunk_len that disagrees with the
        # chunk_fn's compiled static length, silently overlapping global
        # step indices and breaking bit-exact resume (the same invariant
        # harness.rollout.validate_chunking enforces for the factories
        # that build chunk_fn).
        raise ValueError(
            f"n_hl_steps={n_hl_steps} not divisible by n_chunks={n_chunks}"
            " — must match the chunking the chunk_fn was built with"
        )
    batched_jit = vmap_chunk_jit(chunk_fn, donate=donate)
    plan = recovery.RunPlan(
        run_dir=run_dir, n_hl_steps=n_hl_steps, n_chunks=n_chunks,
        seed=seed, config_hash=config_hash, keep_last=keep_last,
        # The vmapped chunk's logs lead with the batch axis; time is axis 1.
        logs_time_axis=1,
        meta=meta or {},
    )

    def place(batch_carry):
        return shard_scenarios(mesh, batch_carry, axis)

    def run(batch_carry, resume: bool = False, interrupt=None):
        if resume:
            return recovery.resume_run(
                run_dir, batched_jit, batch_carry,
                config_hash=config_hash, interrupt=interrupt, place=place,
                max_retries=max_retries, metrics=metrics,
            )
        return recovery.run_chunks(
            plan, batched_jit, batch_carry, interrupt=interrupt,
            place=place, max_retries=max_retries, metrics=metrics,
        )

    run.batched_jit = batched_jit
    run.plan = plan
    return run


def jit_sharded_step(step: Callable, donate: bool = True):
    """Jit an agent-sharded control step (:func:`cadmm_control_sharded` /
    :func:`dd_control_sharded` / :func:`rp_cadmm_control_sharded`) with the
    controller-state carry (argument 0) DONATED, so each device's shard of
    warm starts/duals is updated in place across control steps instead of
    round-tripping fresh HBM buffers — the single-step serving twin of
    ``scenario_rollout``'s donated batch. Thread the returned state
    forward; the donated argument's buffers are deleted."""
    return jax.jit(step, donate_argnums=(0,) if donate else ())
