"""Pods tier: multi-process 2-D ``(scenario, agent)`` mesh scale-out.

BASELINE.json's largest benchmark config — 128 payloads x 8 quadrotors
(1024 agents) sharded over a v4-32 — needs more than one PROCESS: a pod
slice presents each host only its local devices, and every sharded path
in this repo (``parallel/mesh.py``, the ring seam, resumable batches,
serving ``mesh=``) previously assumed one process and a 1-D mesh. This
module is the missing tier:

- **Topology spec resolved at config build time** (:func:`resolve_pods_spec`,
  the ``ring.resolve_consensus`` idiom, with a ``TAT_PODS_MESH`` force
  switch): ``scenario_shards x agent_shards`` over ``n_processes``, with
  the process boundary ALWAYS along the scenario axis — the chatty
  consensus collectives (every ADMM iteration) stay on intra-process
  ICI-class links while only the cheap batch statistics cross DCN.
- **Bootstrap** (:func:`initialize`): one ``jax.distributed.initialize``
  wrapper that also selects gloo CPU collectives, so the SAME code runs
  on a localhost CPU harness (tools/pods_local.py) and a real pod.
- **Topology gate** (:func:`check_topology`): MULTICHIP_r01 recorded the
  exact failure this refuses — 1 of 8 devices visible while the
  single-device probe passed. A shortfall raises a classified
  ``BackendError("topology_mismatch")`` instead of silently running 8x
  undersharded.
- **Process-local ingestion** (:func:`place_local_batch` /
  :func:`place_global_batch` / :func:`local_host_shard`): global
  ``jax.Array`` assembly from per-process host blocks
  (``jax.make_array_from_process_local_data``) and the inverse
  extraction, which the recovery tier's ``to_host`` hook uses for
  per-process snapshot shards.
- **The 2-D control step** (:func:`pods_control_step`): C-ADMM / DD over
  ``shard_map`` on the ``(scenario, agent)`` mesh — scenarios vmapped
  per shard, consensus riding ``ring.consensus_exchange`` over the
  AGENT axis exactly as the 1-D tier does (the controller code is
  unchanged; ``axis_name="agent"`` under vmap batches the collectives),
  and the cross-scenario batch statistic (global residual max) riding
  the same seam over the SCENARIO axis — the only collective that
  crosses processes.
- **Resumable pods runs** (:func:`pods_rollout_resumable`): the PR-4
  chunk driver with per-process snapshot shards
  (``checkpoint.shard_prefix`` + one global shard manifest), a
  config hash that folds the topology in (resuming 2-process shards on
  a 4-process mesh refuses), and a cross-process agreement on the resume
  boundary so every process restarts from the same chunk.

Parity bar (tests/test_pods.py + tools/pods_local.py): a 2-process x
4-virtual-device localhost run of the sharded C-ADMM control step matches
the single-process 8-device run to f32 rounding (the test_ring bar),
nominal AND alive-masked.
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_aerial_transport.obs import phases
from tpu_aerial_transport.parallel import ring
from tpu_aerial_transport.utils import compat

SCENARIO_AXIS = "scenario"
AGENT_AXIS = "agent"

# Config-build-time force switch (the ring.ENV_VAR pattern): "SxA", e.g.
# TAT_PODS_MESH=2x4 forces a 2-scenario-shard x 4-agent-shard mesh.
ENV_VAR = "TAT_PODS_MESH"

# Bootstrap env (tools/pods_local.py exports these into its workers; a
# real pod launcher sets the same three).
COORDINATOR_ENV = "TAT_PODS_COORDINATOR"
NUM_PROCESSES_ENV = "TAT_PODS_NUM_PROCESSES"
PROCESS_ID_ENV = "TAT_PODS_PROCESS_ID"


# ----------------------------------------------------------------------
# Topology spec + resolution.
# ----------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class PodsSpec:
    """Static 2-D mesh topology: ``scenario_shards x agent_shards`` over
    ``n_processes`` processes, process boundary along the scenario axis
    (``scenario_shards % n_processes == 0`` — each process owns a
    contiguous slab of scenario rows and ALL agent shards inside it, so
    consensus never crosses a process)."""

    scenario_shards: int
    agent_shards: int
    n_processes: int = 1

    @property
    def n_devices(self) -> int:
        return self.scenario_shards * self.agent_shards

    @property
    def local_devices(self) -> int:
        return self.n_devices // self.n_processes

    def topology(self) -> dict:
        """JSON-able description — journaled in run metadata, folded into
        the resume config hash, stamped on bench cells."""
        return {
            "scenario_shards": self.scenario_shards,
            "agent_shards": self.agent_shards,
            "n_processes": self.n_processes,
            "n_devices": self.n_devices,
        }

    def validate(self, n_agents: int | None = None) -> "PodsSpec":
        if self.scenario_shards < 1 or self.agent_shards < 1:
            raise ValueError(f"non-positive mesh shape: {self}")
        if self.n_processes < 1 or self.n_devices % self.n_processes:
            raise ValueError(
                f"{self.n_devices} devices not divisible by "
                f"{self.n_processes} processes: {self}"
            )
        if self.scenario_shards % self.n_processes:
            raise ValueError(
                f"scenario_shards={self.scenario_shards} not divisible by "
                f"n_processes={self.n_processes}: the process boundary must "
                "lie along the scenario axis (consensus stays intra-process)"
            )
        if n_agents is not None and n_agents % self.agent_shards:
            raise ValueError(
                f"n_agents={n_agents} not divisible by "
                f"agent_shards={self.agent_shards}"
            )
        return self


def _parse_mesh_str(raw: str) -> tuple[int, int]:
    parts = raw.lower().replace("×", "x").split("x")
    if len(parts) != 2:
        raise ValueError(
            f"{ENV_VAR}={raw!r}: expected 'SxA' (scenario_shards x "
            "agent_shards), e.g. '2x4'"
        )
    try:
        return int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(f"{ENV_VAR}={raw!r}: shards must be ints") from None


def resolve_pods_spec(
    n_agents: int,
    spec: "str | tuple | PodsSpec | None" = "auto",
    *,
    n_devices: int | None = None,
    n_processes: int | None = None,
) -> PodsSpec:
    """Resolve the 2-D mesh topology at CONFIG BUILD time (the
    ``ring.resolve_consensus`` idiom — resolving lazily inside a traced
    function would bake the first topology seen into a cache keyed on
    "auto"). Precedence:

    1. an explicit ``spec`` (``PodsSpec`` / ``(S, A)`` / ``"SxA"``);
    2. else the ``TAT_PODS_MESH`` env force (``"SxA"`` / ``"auto"``);
    3. else auto: the largest ``agent_shards`` dividing BOTH ``n_agents``
       and the per-process device count (so agent shards never straddle a
       process), scenario taking the rest.

    ``n_devices`` / ``n_processes`` default to the live runtime counts —
    pass them explicitly to plan a topology without initializing a
    backend (the bench probe path).
    """
    if n_devices is None:
        n_devices = jax.device_count()  # jaxlint: disable=JL005
    if n_processes is None:
        n_processes = jax.process_count()  # jaxlint: disable=JL005
    if n_devices % n_processes:
        raise ValueError(
            f"{n_devices} devices not divisible by {n_processes} processes"
        )
    local = n_devices // n_processes

    if spec is None or spec == "auto":
        env = os.environ.get(ENV_VAR, "").strip()
        if env and env != "auto":
            spec = env
    if isinstance(spec, PodsSpec):
        return spec.validate(n_agents)
    if isinstance(spec, str) and spec not in ("auto", ""):
        s, a = _parse_mesh_str(spec)
        return PodsSpec(s, a, n_processes).validate(n_agents)
    if isinstance(spec, tuple):
        return PodsSpec(spec[0], spec[1], n_processes).validate(n_agents)

    agent_shards = max(
        d for d in range(1, min(local, n_agents) + 1)
        if n_agents % d == 0 and local % d == 0
    )
    return PodsSpec(
        scenario_shards=n_devices // agent_shards,
        agent_shards=agent_shards,
        n_processes=n_processes,
    ).validate(n_agents)


# ----------------------------------------------------------------------
# Bootstrap + topology gate.
# ----------------------------------------------------------------------

def initialize(coordinator: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> bool:
    """``jax.distributed.initialize`` bootstrap: arguments default from
    the ``TAT_PODS_*`` env vars (what tools/pods_local.py exports into
    its workers). Returns True when distributed mode was initialized,
    False for the single-process no-op (no coordinator configured).

    Must run BEFORE any backend use. On the CPU backend the gloo
    collectives implementation is selected first — without it a
    cross-process psum on the localhost harness fails at dispatch, which
    is exactly the class of late failure the probe tier exists to avoid.
    """
    env = os.environ
    if coordinator is None:
        coordinator = env.get(COORDINATOR_ENV, "")
    if num_processes is None and env.get(NUM_PROCESSES_ENV):
        num_processes = int(env[NUM_PROCESSES_ENV])
    if process_id is None and env.get(PROCESS_ID_ENV):
        process_id = int(env[PROCESS_ID_ENV])
    if not coordinator or not num_processes or num_processes < 2:
        return False
    # Harmless off-CPU (each backend picks its own collectives); REQUIRED
    # for cross-process CPU collectives on the localhost harness.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator, num_processes=num_processes,
        process_id=0 if process_id is None else process_id,
    )
    return True


def check_topology(spec: PodsSpec) -> None:
    """Refuse to run on the wrong mesh: raises a classified
    ``BackendError("topology_mismatch")`` when fewer devices/processes
    are visible than ``spec`` requires (MULTICHIP_r01: 1 of 8 devices
    visible, probe green, assert 8 deep inside the run). Touches the
    live backend — callers that need a watchdog run
    ``resilience.backend.probe_subprocess(expect_devices=...,
    expect_processes=...)`` first; this is the in-process belt to that
    suspender."""
    from tpu_aerial_transport.resilience.backend import BackendError

    n_dev = jax.device_count()  # jaxlint: disable=JL005
    n_proc = jax.process_count()  # jaxlint: disable=JL005
    if n_dev < spec.n_devices or n_proc != spec.n_processes:
        raise BackendError(
            "topology_mismatch",
            f"visible {n_dev} of {spec.n_devices} devices "
            f"({n_proc} of {spec.n_processes} processes) — the pods mesh "
            f"{spec.scenario_shards}x{spec.agent_shards} cannot be built; "
            "running undersharded would mis-measure (MULTICHIP_r01)",
        )


def make_pods_mesh(spec: PodsSpec, devices=None) -> Mesh:
    """The 2-D ``(scenario, agent)`` mesh. Each of the spec's processes
    contributes exactly ``spec.local_devices`` devices, and the device
    array fills scenario-major, so each process's devices form a
    contiguous slab of scenario rows — every agent shard of a scenario
    row is local to the row's owner process (the
    consensus-stays-intra-process invariant the spec validates).

    Selection is PER PROCESS, not a flat first-N slice: on a host with
    surplus local devices a flat slice would concentrate the mesh on the
    early processes (later processes owning no shard — their placement
    then fails deep inside ``make_array_from_process_local_data``
    instead of here). Any process short of its share raises the
    classified ``topology_mismatch``."""
    from tpu_aerial_transport.resilience.backend import BackendError

    if devices is None:
        check_topology(spec)
        devices = jax.devices()
    by_proc: dict[int, list] = {}
    for d in sorted(devices, key=lambda d: (d.process_index, d.id)):
        by_proc.setdefault(d.process_index, []).append(d)
    if len(by_proc) != spec.n_processes:
        raise BackendError(
            "topology_mismatch",
            f"devices span {len(by_proc)} processes, mesh "
            f"{spec.scenario_shards}x{spec.agent_shards} needs exactly "
            f"{spec.n_processes}",
        )
    chosen: list = []
    for p in sorted(by_proc):
        local = by_proc[p]
        if len(local) < spec.local_devices:
            raise BackendError(
                "topology_mismatch",
                f"process {p} has {len(local)} of {spec.local_devices} "
                f"devices the {spec.scenario_shards}x{spec.agent_shards} "
                "mesh needs per process",
            )
        chosen.extend(local[:spec.local_devices])
    dev_array = np.asarray(chosen).reshape(
        spec.scenario_shards, spec.agent_shards
    )
    return Mesh(dev_array, (SCENARIO_AXIS, AGENT_AXIS))


def mesh_spec(mesh: Mesh) -> PodsSpec:
    """The :class:`PodsSpec` a 2-D pods mesh realizes (topology stamping
    for bench cells / run metadata)."""
    procs = {d.process_index for d in mesh.devices.flat}
    return PodsSpec(
        scenario_shards=int(mesh.shape[SCENARIO_AXIS]),
        agent_shards=int(mesh.shape.get(AGENT_AXIS, 1)),
        n_processes=len(procs),
    )


def _mesh_process_count(mesh: Mesh) -> int:
    return len({d.process_index for d in mesh.devices.flat})


# ----------------------------------------------------------------------
# Placement / extraction (the multi-process data plane).
# ----------------------------------------------------------------------

def place_global_batch(mesh: Mesh, batch, axis: str = SCENARIO_AXIS):
    """Place a HOST-GLOBAL batch pytree (every process holds the same
    full host copy — the serving server's carry_host contract) onto the
    mesh sharded over ``axis``: each process contributes exactly the
    rows its devices own (``jax.make_array_from_callback`` slices the
    host copy per addressable shard). Single-process meshes work too —
    ``parallel.mesh.shard_scenarios`` routes here only for multi-process
    meshes."""
    def put(x):
        if not (hasattr(x, "ndim") and x.ndim):
            return x
        arr = np.asarray(x)
        sharding = NamedSharding(mesh, P(axis))
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    return jax.tree.map(put, batch)


def place_local_batch(mesh: Mesh, local_batch, axis: str = SCENARIO_AXIS):
    """Assemble a global sharded ``jax.Array`` pytree from each process's
    LOCAL block (leading ``axis`` rows this process owns) —
    ``jax.make_array_from_process_local_data``. The process-local
    ingestion path: a pod run never materializes the global batch on any
    one host. Global leading dim = local rows x process count (the
    process-contiguous slab layout of :func:`make_pods_mesh`)."""
    n_proc = _mesh_process_count(mesh)

    def put(x):
        if not (hasattr(x, "ndim") and x.ndim):
            return x
        arr = np.asarray(x)
        sharding = NamedSharding(mesh, P(axis))
        global_shape = (arr.shape[0] * n_proc,) + arr.shape[1:]
        return jax.make_array_from_process_local_data(
            sharding, arr, global_shape
        )

    return jax.tree.map(put, local_batch)


def local_host_shard(tree):
    """This process's block of a (possibly multi-process) device pytree,
    as freshly-copied host numpy — the pods realization of
    ``recovery.host_copy`` (``np.array`` of a non-fully-addressable
    global array raises; assembling addressable shards, deduplicating
    replicas by index, is the correct local extraction). Fully
    addressable leaves (single-process arrays, host numpy) take the
    plain copy path, so the same function drives single- and
    multi-process runs."""
    def pull(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            pieces: dict[tuple, np.ndarray] = {}
            for s in x.addressable_shards:
                start = tuple(sl.start or 0 for sl in s.index)
                if start not in pieces:  # agent-axis replicas dedup here.
                    pieces[start] = np.asarray(s.data)
            origins = [min(st[d] for st in pieces) for d in range(x.ndim)]
            extents = [
                max(st[d] + arr.shape[d] for st, arr in pieces.items())
                - origins[d]
                for d in range(x.ndim)
            ]
            out = np.empty(tuple(extents), dtype=x.dtype)
            for st, arr in pieces.items():
                sl = tuple(
                    slice(st[d] - origins[d], st[d] - origins[d] + arr.shape[d])
                    for d in range(x.ndim)
                )
                out[sl] = arr
            return out
        return np.array(x, copy=True)

    return jax.tree.map(pull, tree)


def host_global(tree):
    """Host-global numpy of a sharded pytree on EVERY process: jit
    identity re-sharded to fully-replicated (one all-gather), then the
    host copy (a fully-replicated global array is host-convertible).
    Parity/digest tooling only — a real pod workload should never
    materialize the global batch."""
    def pull(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            mesh = x.sharding.mesh
            rep = jax.jit(
                lambda a: a, out_shardings=NamedSharding(mesh, P())
            )(x)
            return np.array(rep)
        return np.array(x, copy=True)

    return jax.tree.map(pull, tree)


# ----------------------------------------------------------------------
# The 2-D sharded control step.
# ----------------------------------------------------------------------

def _consensus_impl(cfg) -> str:
    """The resolved exchange impl a controller config carries (cadmm
    stores it flat, dd nests it under .base)."""
    impl = getattr(cfg, "consensus_impl", None)
    if impl is None:
        impl = cfg.base.consensus_impl
    return impl


def pods_control_step(params, cfg, f_eq, mesh: Mesh, forest=None,
                      controller: str = "cadmm",
                      with_health: bool = False):
    """The distributed-MPC control step over the 2-D pods mesh.

    Returns ``step(css, states, acc_des[, healths]) -> (f, css, stats,
    batch_res)`` where ``css`` is the BATCHED controller state (leading
    scenario axis, then the agent axis — sharded over both mesh axes),
    ``states``/``stats`` are batched over scenarios (sharded over the
    scenario axis, replicated over agent), ``acc_des`` is replicated,
    and ``batch_res`` is the global residual max over every scenario —
    the cross-process batch statistic, exchanged through
    ``ring.consensus_exchange`` over the SCENARIO axis with the same
    impl the consensus itself uses over the AGENT axis (axis-aware: one
    seam, two axes). ``with_health`` adds a batched
    ``resilience.faults.FaultStep`` argument (scenario-sharded, each
    lane carrying the full per-agent masks) and the held-message fields
    to the state spec, exactly as the 1-D masked tier does.

    The controller code is UNCHANGED from the 1-D tier:
    ``control(axis_name="agent")`` under ``jax.vmap`` over the local
    scenario lanes batches every agent-axis collective; parity to the
    single-process run is f32 rounding (tests/test_pods.py).
    """
    from tpu_aerial_transport.parallel import mesh as mesh_mod

    n = params.n
    s_sh = int(mesh.shape[SCENARIO_AXIS])
    a_sh = int(mesh.shape[AGENT_AXIS])
    assert n % a_sh == 0, (n, a_sh)
    impl = _consensus_impl(cfg)
    PSA = P(SCENARIO_AXIS, AGENT_AXIS)
    PS = P(SCENARIO_AXIS)
    warm_spec = jax.tree.map(lambda _: PSA, mesh_mod._warm_structure())

    if controller == "cadmm":
        from tpu_aerial_transport.control import cadmm as ctrl_mod

        plan = ctrl_mod.make_plan(params, cfg)
        cs_spec = ctrl_mod.CADMMState(
            f=PSA, lam=PSA, f_mean=PS, warm=warm_spec,
            **({"held": PSA} if with_health else {}),
        )

        def lane_fn(cs, s, a, h):
            return ctrl_mod.control(
                params, cfg, f_eq, cs, s, a, forest,
                axis_name=AGENT_AXIS, plan=plan, health=h,
            )

    elif controller == "dd":
        from tpu_aerial_transport.control import dd as ctrl_mod

        plan = ctrl_mod.make_dd_plan(params, cfg)
        cs_spec = ctrl_mod.DDState(
            f=PSA, F=PSA, M=PSA, lam_F=PSA, lam_M=PSA, warm=warm_spec,
            **({"held_f": PSA, "held_lam_F": PSA, "held_lam_M": PSA}
               if with_health else {}),
        )

        def lane_fn(cs, s, a, h):
            return ctrl_mod.control(
                params, cfg, f_eq, cs, s, a, forest,
                axis_name=AGENT_AXIS, plan=plan, health=h,
            )

    else:
        raise ValueError(controller)

    in_specs = (cs_spec, PS, (P(), P()))
    if with_health:
        in_specs = in_specs + (PS,)
    out_specs = (PSA, cs_spec, PS, P())

    def fn(css, states, acc_des, *maybe_health):
        # Coarse scope for the 2-D shard plumbing; the controllers' fine
        # tat.* scopes inside (being innermost) win the attribution.
        with phases.scope(phases.PODS_STEP):
            if with_health:
                f, css, stats = jax.vmap(
                    lambda cs, s, h: lane_fn(cs, s, acc_des, h)
                )(css, states, maybe_health[0])
            else:
                f, css, stats = jax.vmap(
                    lambda cs, s: lane_fn(cs, s, acc_des, None)
                )(css, states)
            # Batch statistic over the SCENARIO axis — the only exchange
            # that crosses processes (process boundary lies along this
            # axis). Max is exact under any schedule, so the statistic is
            # identical whatever the impl/topology.
            batch_res = ring.consensus_exchange(
                jnp.max(stats.solve_res), SCENARIO_AXIS,
                axis_size=s_sh, op="max", impl=impl,
            )
            return f, css, stats, batch_res

    return compat.shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )


# ----------------------------------------------------------------------
# Benchmark workload (tools/pods_local.py + bench.py pods_* cells).
# ----------------------------------------------------------------------

def _physics_substeps(params, ll, state, f_des, n_sub=10, dt=1e-3):
    """1 kHz low-level control + physics — the reference's inner loop
    (the bench.py ``_substeps`` program, package-side so the pods harness
    does not import the bench script)."""
    from tpu_aerial_transport.models import rqp

    def body(s, _):
        f, M = ll.control(s, f_des)
        return rqp.integrate(params, s, (f, M), dt), None

    return lax.scan(body, state, None, length=n_sub)[0]


def scenario_batch(state0, n_scenarios: int, seed: int = 0):
    """Deterministic host-side Monte-Carlo batch (the bench.py scenario
    grid): every process builds the SAME global batch from the seed, so
    process-local slabs agree without any exchange."""
    xs = jnp.asarray(
        np.random.default_rng(seed).normal(size=(n_scenarios, 3)) * 2.0
        + np.array([5.0, 0.0, 2.0]),
        jnp.float32,
    )
    return jax.vmap(
        lambda x: state0.replace(
            xl=x, vl=jnp.array([0.5, 0.0, 0.0], jnp.float32)
        )
    )(xs)


def make_pods_workload(n: int, mesh: Mesh, controller: str = "cadmm",
                       max_iter: int = 8, inner_iters: int | None = None,
                       seed: int = 0):
    """The full pods MPC workload: env CBFs + 2-D sharded consensus solve
    + low-level control + 10x physics, scanned over control steps.

    Returns ``(roll, init_batch)`` where ``roll(css, states, n_steps) ->
    (css, states, res_trace)`` is jitted with a static step count
    (``res_trace``: the per-step global batch-residual scalars — the
    cross-process statistic, and the parity digest the localhost harness
    compares across topologies) and ``init_batch(n_scenarios) -> (css,
    states)`` builds the HOST-GLOBAL initial batch (place with
    ``parallel.mesh.shard_scenarios`` / :func:`place_local_batch`).
    """
    from tpu_aerial_transport.control import centralized, lowlevel
    from tpu_aerial_transport.envs import forest as forest_mod
    from tpu_aerial_transport.harness import setup

    params, col, state0 = setup.rqp_setup(n)
    forest = forest_mod.make_forest(seed=0)
    f_eq = centralized.equilibrium_forces(params)
    ll = lowlevel.make_lowlevel_controller("pd", params)
    acc_des = (jnp.array([0.3, 0.0, 0.0], jnp.float32),
               jnp.zeros(3, jnp.float32))

    if controller == "cadmm":
        from tpu_aerial_transport.control import cadmm as ctrl_mod

        cfg = ctrl_mod.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=max_iter,
            inner_iters=20 if inner_iters is None else inner_iters,
        )
        cs0 = ctrl_mod.init_cadmm_state(params, cfg)
    elif controller == "dd":
        from tpu_aerial_transport.control import dd as ctrl_mod

        cfg = ctrl_mod.make_config(
            params, col.collision_radius, col.max_deceleration,
            max_iter=max_iter,
            inner_iters=40 if inner_iters is None else inner_iters,
        )
        cs0 = ctrl_mod.init_dd_state(params, cfg)
    else:
        raise ValueError(controller)

    step = pods_control_step(params, cfg, f_eq, mesh, forest, controller)

    def roll(css, states, n_steps):
        def body(carry, _):
            css, states = carry
            f, css, _stats, batch_res = step(css, states, acc_des)
            states = jax.vmap(
                lambda s, fd: _physics_substeps(params, ll, s, fd)
            )(states, f)
            return (css, states), batch_res

        (css, states), res_trace = lax.scan(
            body, (css, states), None, length=n_steps
        )
        return css, states, res_trace

    def init_batch(n_scenarios: int):
        states = scenario_batch(state0, n_scenarios, seed=seed)
        css = jax.vmap(lambda _: cs0)(jnp.arange(n_scenarios))
        return css, states

    jitted = jax.jit(roll, static_argnames="n_steps")
    jitted.config = cfg
    return jitted, init_batch


def parity_digest(mesh: Mesh, *, n: int = 8, n_scenarios: int = 8,
                  n_steps: int = 2, max_iter: int = 4,
                  inner_iters: int = 8, controller: str = "cadmm",
                  masked: bool = True) -> dict:
    """The pods parity probe: run the deterministic benchmark workload
    over ``mesh`` and return host-global numpy digests — final payload
    positions, the per-step global batch residuals, and (``masked``) one
    alive-masked/fault-injected control step's forces (agent 0 dead,
    agent 2's consensus message dropped — the test_ring fault pattern,
    tiled over the batch).

    The SAME function runs on the 2-process localhost harness
    (tools/pods_local.py) and on a single-process mesh in the test
    process; the two digests must agree to f32 rounding (the exchange
    summation order is the only difference). Every process returns the
    same host-global digest (:func:`host_global`)."""
    from tpu_aerial_transport.control import centralized
    from tpu_aerial_transport.harness import setup
    from tpu_aerial_transport.resilience import faults as faults_mod

    roll, init_batch = make_pods_workload(
        n, mesh, controller=controller, max_iter=max_iter,
        inner_iters=inner_iters,
    )
    from tpu_aerial_transport.parallel import mesh as mesh_mod

    css, states = init_batch(n_scenarios)
    css_p = mesh_mod.shard_scenarios(mesh, css)
    st_p = mesh_mod.shard_scenarios(mesh, states)
    css_out, st_out, res_trace = roll(css_p, st_p, n_steps=n_steps)
    digest = {
        "xl": host_global(st_out.xl),
        "res_trace": host_global(res_trace),
    }

    if masked:
        params, col, state0 = setup.rqp_setup(n)
        cfg = roll.config
        alive = np.ones(n, dtype=bool)
        alive[0] = False
        msg_ok = np.ones(n, dtype=bool)
        msg_ok[min(2, n - 1)] = False
        health = faults_mod.FaultStep(
            alive=jnp.asarray(alive),
            thrust_scale=jnp.asarray(alive, jnp.float32),
            msg_ok=jnp.asarray(msg_ok),
        )
        healths = jax.tree.map(
            lambda x: jnp.tile(x[None], (n_scenarios,) + (1,) * x.ndim),
            health,
        )
        f_eq_m = centralized.equilibrium_forces(
            params, alive=health.alive
        )
        if controller == "cadmm":
            from tpu_aerial_transport.control import cadmm as ctrl_mod

            cs0 = ctrl_mod.init_cadmm_state(params, cfg)
            cs0 = cs0.replace(held=cs0.f)
        else:
            from tpu_aerial_transport.control import dd as ctrl_mod

            cs0 = ctrl_mod.init_dd_state(params, cfg)
            cs0 = cs0.replace(
                held_f=cs0.f, held_lam_F=cs0.lam_F, held_lam_M=cs0.lam_M
            )
        step_m = pods_control_step(
            params, cfg, f_eq_m, mesh, None, controller, with_health=True,
        )
        css_m = jax.vmap(lambda _: cs0)(jnp.arange(n_scenarios))
        states_m = scenario_batch(state0, n_scenarios)
        acc = (jnp.array([0.3, 0.0, 0.1], jnp.float32),
               jnp.zeros(3, jnp.float32))
        f_m, _, _, bres_m = jax.jit(step_m)(
            mesh_mod.shard_scenarios(mesh, css_m),
            mesh_mod.shard_scenarios(mesh, states_m),
            acc,
            mesh_mod.shard_scenarios(mesh, healths),
        )
        digest["f_masked"] = host_global(f_m)
        digest["res_masked"] = host_global(bres_m)
    return digest


# ----------------------------------------------------------------------
# Resumable pods runs: per-process snapshot shards + agreement.
# ----------------------------------------------------------------------

def _agreed_boundary_cap(valid: np.ndarray, n_processes: int) -> int:
    """The newest chunk boundary valid on EVERY process (+1 = the agreed
    start chunk). ``valid[c]`` is this process's verdict on the boundary
    after chunk ``c``; the masks all-gather and AND — a process that died
    mid-publish simply fails its own newest boundary and drags the fleet
    back one chunk, instead of the fleet deadlocking on mismatched
    collectives."""
    if n_processes > 1:
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(
            valid.astype(np.int32)
        )
        valid = np.min(np.asarray(gathered).reshape(-1, valid.size), axis=0)
    agreed = np.nonzero(valid)[0]
    return int(agreed.max()) + 1 if agreed.size else 0


def pods_rollout_resumable(
    chunk_fn,
    mesh: Mesh,
    *,
    n_hl_steps: int,
    n_chunks: int,
    run_dir: str,
    config_hash: str | None = None,
    seed: int | None = None,
    keep_last: int = 3,
    max_retries: int = 1,
    meta: dict | None = None,
    metrics=None,
    tracer=None,
):
    """Preemption-safe pods twin of
    ``parallel.mesh.scenario_rollout_resumable``: the vmapped chunk runs
    over the 2-D mesh, each PROCESS snapshots its own scenario slab
    (``checkpoint.shard_prefix`` carry/log prefixes + a per-process
    journal inside ONE shared run_dir; process 0 publishes the global
    shard manifest), and resume re-places each process's restored slab
    on the rebuilt mesh after a cross-process agreement on the newest
    boundary every process still holds.

    The config hash FOLDS THE TOPOLOGY IN (``config_hash`` is combined
    with the mesh spec), so resuming 2-process shards under a different
    mesh refuses with the standard ``config_mismatch`` — and the shard
    manifest refuses a wrong process count even before any shard is
    read.

    ``run(local_carry, resume=False, interrupt=None)`` takes and returns
    PROCESS-LOCAL host slabs (leading axis = this process's scenario
    rows); ``RunResult.logs`` holds the local block of the concatenated
    chunk logs.

    ``tracer`` (an ``obs.trace.Tracer`` or ``True`` to build one wired
    to this process's metrics sink) turns on distributed tracing through
    the chunk driver: each process records its run/chunk/snapshot/resume
    spans on its OWN track (``p{pid}of{N}`` — the same grammar as the
    shard prefixes), and ``tools/trace_view.py`` stitches the per-process
    monotonic clock domains into one trace through this run dir's shard
    manifest. ``tracer=None`` stays zero-cost.
    """
    from tpu_aerial_transport.harness import checkpoint
    from tpu_aerial_transport.resilience import recovery

    spec = mesh_spec(mesh)
    pid = jax.process_index()  # jaxlint: disable=JL005
    topo_hash = checkpoint.config_fingerprint(
        base=config_hash or "", topology=tuple(sorted(
            spec.topology().items()
        )),
    )
    if n_hl_steps % n_chunks:
        raise ValueError(
            f"n_hl_steps={n_hl_steps} not divisible by n_chunks={n_chunks}"
        )
    # The pods twin of mesh.vmap_chunk_jit, with the OUTPUT shardings
    # pinned to the scenario axis: left to itself XLA picks per-leaf
    # output shardings (replicated logs were observed), and then a
    # process's "local block" of the logs is the whole batch on one leaf
    # and a slab on the next — the per-process shard snapshots would
    # disagree with their resume template. Pinning makes every leaf's
    # local block exactly this process's scenario slab. (Every output
    # leaf is vmapped, so rank >= 1 and P("scenario") is well-formed.)
    batched_jit = jax.jit(
        jax.vmap(chunk_fn, in_axes=(0, None)),
        out_shardings=NamedSharding(mesh, P(SCENARIO_AXIS)),
    )

    def chunk_jit(carry, i0):
        # Offsets reach the jit as host numpy: every process passes the
        # same host value, which multi-process jit treats as replicated
        # (a per-process committed device scalar would not be). Skipped
        # under tracing (resume_run's eval_shape traces this wrapper).
        if not isinstance(i0, jax.core.Tracer):
            i0 = np.int32(i0)
        return batched_jit(carry, i0)

    plan = recovery.RunPlan(
        run_dir=run_dir, n_hl_steps=n_hl_steps, n_chunks=n_chunks,
        seed=seed, config_hash=topo_hash, keep_last=keep_last,
        logs_time_axis=1,
        meta={**(meta or {}), "topology": spec.topology()},
        carry_prefix=checkpoint.shard_prefix(
            recovery.CARRY_PREFIX, pid, spec.n_processes
        ),
        logs_prefix=checkpoint.shard_prefix(
            recovery.LOGS_PREFIX, pid, spec.n_processes
        ),
        journal_filename=f"journal.p{pid}of{spec.n_processes}.jsonl",
    )

    if tracer is True:
        # Convenience wiring: one tracer per process, rows into a
        # per-process metrics jsonl inside the shared run dir (the files
        # trace_view's stitcher globs), track named by the same
        # p{pid}ofN grammar as the shard prefixes.
        from tpu_aerial_transport.obs import export as export_mod
        from tpu_aerial_transport.obs import trace as trace_lib

        track = f"p{pid}of{spec.n_processes}"
        tracer = trace_lib.Tracer(
            export_mod.MetricsWriter(
                os.path.join(run_dir, f"trace.{track}.metrics.jsonl")
            ),
            track=track,
        )
    elif not tracer:
        # Normalize falsy (False from a bool(flag) caller) to None: the
        # chunk driver's zero-cost gate is `tracer is not None`, and
        # False reaching it would crash at the first span.
        tracer = None

    def place(local_carry):
        return place_local_batch(mesh, local_carry)

    def _publish_manifest():
        if pid == 0:
            checkpoint.save_shard_manifest(
                run_dir, prefix=recovery.CARRY_PREFIX,
                n_processes=spec.n_processes, topology=spec.topology(),
                config_hash=topo_hash,
            )

    def _valid_boundaries(local_carry) -> tuple[np.ndarray, list[str]]:
        """Per-boundary validity mask for THIS process's shard files —
        the same carry + complete-log-prefix rule resume_run applies —
        plus the structured reasons for every rejected boundary (they
        journal alongside the agreement, so a fleet-wide fallback is
        diagnosable per process)."""
        _, logs_template = jax.eval_shape(
            chunk_jit, local_carry, np.int32(0)
        )
        valid = np.zeros(n_chunks, dtype=bool)
        reasons: list[str] = []
        log_ok: dict[int, bool] = {}

        def _log_valid(lc: int) -> bool:
            # Memoized: boundary candidates share log prefixes, and a
            # full re-read per candidate would pay O(boundaries x
            # chunks) snapshot loads. (resume_run still re-validates the
            # CHOSEN boundary at load time — integrity is checked where
            # the data is trusted; this mask only drives the agreement.)
            if lc not in log_ok:
                try:
                    checkpoint.load_snapshot(
                        checkpoint.snapshot_path(
                            run_dir, lc, plan.logs_prefix
                        ),
                        logs_template, config_hash=topo_hash,
                    )
                    log_ok[lc] = True
                except checkpoint.SnapshotError as e:
                    reasons.append(str(e)[:300])
                    log_ok[lc] = False
            return log_ok[lc]

        for step, path in checkpoint.list_snapshots(
            run_dir, plan.carry_prefix
        ):
            if step >= n_chunks:
                continue
            try:
                checkpoint.load_snapshot(
                    path, local_carry, config_hash=topo_hash
                )
            except checkpoint.SnapshotError as e:
                reasons.append(str(e)[:300])
                continue
            valid[step] = all(_log_valid(lc) for lc in range(step + 1))
        return valid, reasons

    def run(local_carry, resume: bool = False, interrupt=None):
        if resume:
            checkpoint.load_shard_manifest(
                run_dir, prefix=recovery.CARRY_PREFIX,
                n_processes=spec.n_processes, config_hash=topo_hash,
            )
            valid, reasons = _valid_boundaries(local_carry)
            cap = _agreed_boundary_cap(valid, spec.n_processes)
            if reasons:
                recovery.RunJournal(
                    run_dir, filename=plan.journal_filename
                ).append({
                    "event": "pods_shard_validation",
                    "valid": [bool(v) for v in valid],
                    "agreed_cap": cap, "skipped": reasons[:8],
                })
            return recovery.resume_run(
                run_dir, chunk_jit, local_carry,
                config_hash=topo_hash, interrupt=interrupt, place=place,
                max_retries=max_retries, metrics=metrics,
                journal_filename=plan.journal_filename,
                to_host=local_host_shard, max_start_chunk=cap,
                tracer=tracer,
            )
        _publish_manifest()
        return recovery.run_chunks(
            plan, chunk_jit, local_carry, interrupt=interrupt,
            place=place, max_retries=max_retries, metrics=metrics,
            to_host=local_host_shard, tracer=tracer,
        )

    run.batched_jit = batched_jit
    run.plan = plan
    run.spec = spec
    return run
