"""Consensus-exchange tier: the cross-device collective behind the C-ADMM
consensus mean/residual and the DD price/violation sums, as a seam with
three implementations behind one auto-resolved gate (the
``socp.resolve_fused`` / ``resolve_pad_operators`` idiom):

- ``"allreduce"`` — the historical realization: one global ``lax.psum`` /
  ``pmax`` / ``pmin`` (or ``all_gather``) per exchange. XLA emits a fused
  all-reduce that BLOCKS the program at a barrier: the consensus payload
  cannot start moving until every shard reaches the collective, and no
  shard resumes until the reduce completes.
- ``"ring"`` — a pure-XLA ring decomposition into ``lax.ppermute`` hops:
  sums run as reduce-scatter + all-gather over the ring (each complete
  chunk is produced ONCE on one shard and broadcast, so the result is
  bitwise-identical on every shard — unlike a per-shard accumulation
  order); max/min/gather run as rotate-and-accumulate. Correct under
  ``shard_map`` on ANY backend (the parity tier asserted on the virtual
  multi-device CPU mesh, tests/test_ring.py), and the structural A/B twin
  for the Pallas kernel: same neighbor-hop schedule, XLA-scheduled.
- ``"pallas_ring"`` — the TPU-native tier (SNIPPETS.md [1] pattern): one
  Pallas kernel whose per-hop neighbor transfer is an explicit
  ``pltpu.make_async_remote_copy`` DMA. The kernel starts the DMA for hop
  *i* and only then reduces the payload received at hop *i-1* on the VPU,
  so the wire time hides under the reduce — and, because the exchange is
  a kernel rather than an XLA collective barrier, the scheduler can
  overlap it with the surrounding per-agent QP solve. Chip-only: the
  remote-DMA primitives have no CPU lowering and (measured on jax 0.4.37)
  no off-chip ``jax.export`` AOT lowering either — see
  ``entrypoints.LOWERING_WAIVERS``; off-TPU the call degrades to the XLA
  ring at trace time (``_resolve_impl``, the ``socp._resolve_fused``
  idiom).

Every exchange — whatever the impl — runs inside the
``tat.consensus_exchange`` named scope (obs/phases.py), so
``tools/op_profile.py --by-phase`` attributes the wire time separately
from the local reduce arithmetic (``tat.consensus``) and the solve.

The ring size is passed explicitly (``axis_size``): callers inside
``shard_map`` know it statically (``n // n_local``), and threading it
through avoids trace-time axis-env introspection.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

from tpu_aerial_transport.obs import phases

# Pallas/Mosaic imports live INSIDE the pallas_ring functions (the
# ops/admm_kernel.py pattern): ring.py is imported at module scope by the
# controllers, and a pure-CPU allreduce deployment must not need the
# Pallas TPU extension just to import the control stack.

IMPLS = ("allreduce", "ring", "pallas_ring")
ENV_VAR = "TPU_AERIAL_CONSENSUS"

# Mosaic collective id for the ring kernel's neighbor barrier (must agree
# across all shards of one exchange; distinct from any future collective
# kernel in the package).
_COLLECTIVE_ID = 1

_COMBINE = {"sum": jnp.add, "max": jnp.maximum, "min": jnp.minimum}
_ALLREDUCE = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin}


def effective_platform() -> str:
    """The platform computations actually land on: the ``jax.default_device``
    config/context if set, else the default backend. The distinction matters
    under the backend guard's CPU fallback (``resilience.backend.run_on_cpu``
    wraps the re-run in ``jax.default_device(cpu)``): ``jax.default_backend()``
    ignores that context and still reports the wedged chip's platform, so
    keying the impl resolution (or a bench cell's mesh) on it would re-commit
    the "CPU fallback" to the dead device."""
    # Host-side query only (the socp._resolve_fused pattern), never traced.
    dev = jax.config.jax_default_device  # jaxlint: disable=JL005
    if dev is not None:
        return dev.platform
    return jax.default_backend()  # jaxlint: disable=JL005


def resolve_consensus(impl: str | None = "auto") -> str:
    """Resolve ``"auto"`` (or None) to the backend default, at CONFIG BUILD
    time (the ``socp.resolve_fused`` idiom — resolving inside a jitted
    function would bake the first backend seen into a trace cache keyed on
    the "auto" string):

    1. the ``TPU_AERIAL_CONSENSUS`` env var (``allreduce`` | ``ring`` |
       ``pallas_ring`` | ``auto``/unset) — the per-process force switch;
    2. else ``"allreduce"`` on CPU — a single-host psum is one fused
       reduction with no wire to hide, so the ring's extra hops only add
       scatter/gather bookkeeping (measured on the virtual 8-device CPU
       mesh A/B, ``bench.py --sweep`` ``*_sharded_*`` cells at n=16:
       ring 0.55x of allreduce for C-ADMM, 0.37x for DD — the hops
       serialize on host) — and ``"ring"`` on tiled backends, where the
       decomposed exchange is the tier the Pallas kernel A/Bs against.

    **A/B criterion for flipping the non-CPU default to "pallas_ring"**
    (kept here so the A/B and the flip live together): on a live chip the
    checkpointed sweep's ``{cadmm,dd}_n64_sharded_pallas_ring`` cells must
    beat their ``_sharded_ring`` twins by >= 10% with
    ``tools/op_profile.py --by-phase`` showing the ``consensus_exchange``
    share shrinking (the transfer actually hiding under the solve), and
    the ring-vs-allreduce parity suite must pass on-chip. Until then,
    deployments opt in per-process with ``TPU_AERIAL_CONSENSUS=pallas_ring``
    (or per-config via ``consensus_impl="pallas_ring"``).
    """
    if impl is None:
        impl = "auto"
    if impl == "auto":
        env = os.environ.get(ENV_VAR, "").strip().lower()
        if env in IMPLS:
            return env
        if env not in ("", "auto"):
            raise ValueError(
                f"{ENV_VAR}={env!r}: expected one of {IMPLS} or 'auto'"
            )
        return "allreduce" if effective_platform() == "cpu" else "ring"
    if impl not in IMPLS:
        raise ValueError(
            f"consensus_impl={impl!r}: expected one of {IMPLS} or 'auto'"
        )
    return impl


def _resolve_impl(impl: str) -> str:
    """Trace-time downgrade of ``pallas_ring`` off-TPU (the
    ``socp._resolve_fused`` idiom): the remote-DMA kernel has no CPU/GPU
    lowering, so a config forced to ``pallas_ring`` still compiles — and
    stays a RING — when the program lands on a non-TPU backend (e.g. the
    backend guard's CPU fallback rung re-running a sweep cell). Rejects
    anything outside ``IMPLS`` — in particular an unresolved ``"auto"``
    from a config built without ``make_config`` — instead of silently
    taking the ring path."""
    if impl not in IMPLS:
        raise ValueError(
            f"impl={impl!r}: expected one of {IMPLS} — resolve 'auto' at "
            "config build time with resolve_consensus()"
        )
    # Host-side strings only (impl is static config; effective_platform is
    # a trace-time host query — the socp._resolve_fused pattern), never a
    # traced value.
    if impl == "pallas_ring" and effective_platform() != "tpu":  # jaxlint: disable=JL005
        return "ring"
    return impl


def _right_perm(d: int) -> list[tuple[int, int]]:
    return [(i, (i + 1) % d) for i in range(d)]


def consensus_exchange(x, axis_name: str, *, axis_size: int, op: str = "sum",
                       impl: str = "allreduce"):
    """All-reduce ``x`` (any shape, every shard holding a same-shaped
    value) over the ``shard_map`` axis ``axis_name``, with ``op`` in
    ``{"sum", "max", "min"}`` and the implementation selected by ``impl``
    (see the module docstring; resolve ``"auto"`` with
    :func:`resolve_consensus` at config build time).

    Numerics: ``max``/``min`` are exact under any schedule. ``sum`` under
    ``"ring"`` differs from ``psum`` only in summation order (f32
    rounding) and is bitwise-identical ACROSS shards (reduce-scatter
    computes each chunk once); under ``"pallas_ring"`` the per-shard
    accumulation order differs per shard, so shards may disagree in the
    last bits — exchange consumers that gate loop conditions use exact
    reductions (max of residuals, sums of 0/1 flags), which stay uniform.
    """
    if op not in _COMBINE:
        raise ValueError(f"op={op!r}: expected one of {tuple(_COMBINE)}")
    with phases.scope(phases.CONSENSUS_EXCHANGE):
        impl = _resolve_impl(impl)
        if impl == "allreduce":
            return _ALLREDUCE[op](x, axis_name)
        if axis_size == 1:
            return x
        if impl == "pallas_ring" and op == "sum":
            return _pallas_ring_allreduce(x, axis_name, axis_size)
        if op == "sum":
            return _ring_allreduce_sum(x, axis_name, axis_size)
        # max/min: rotate-and-accumulate (exact; the residual payloads are
        # scalars, so chunked reduce-scatter has nothing to amortize).
        return _rotate_allreduce(x, axis_name, axis_size, _COMBINE[op])


def consensus_gather(x, axis_name: str, *, axis_size: int,
                     impl: str = "allreduce"):
    """``lax.all_gather`` twin through the exchange seam: returns the
    ``(axis_size, *x.shape)`` stack of every shard's ``x``, shard-ordered,
    identical on every shard. The ring realization rotates each shard's
    block around the ring (d-1 hops), scattering into the output by source
    index — bitwise-identical to ``all_gather``, hop-for-hop the same
    schedule as the ring reduce."""
    with phases.scope(phases.CONSENSUS_EXCHANGE):
        impl = _resolve_impl(impl)
        # pallas_ring: gathers ride the XLA ring — the gathered payloads
        # (DD's per-agent violation blocks) feed a replicated solve right
        # after the hop, so there is no local reduce to hide a DMA under.
        if impl == "allreduce" or axis_size == 1:
            return lax.all_gather(x, axis_name)
        return _ring_gather(x, axis_name, axis_size)


def _ring_allreduce_sum(x, axis_name: str, d: int):
    """Ring reduce-scatter + all-gather sum (2(d-1) ``ppermute`` hops of
    1/d of the payload). Each shard accumulates running chunk sums from
    its left neighbor and forwards them right; after d-1 hops shard *i*
    owns the COMPLETE chunk ``(i+1) % d``, which the all-gather phase then
    rotates to everyone. Payloads smaller than ``d`` pad (the "n not
    divisible by the device count" case — pad chunks are zeros and sliced
    off)."""
    shape = x.shape
    flat = x.reshape(-1)
    size = flat.size
    chunk = -(-size // d)
    pad = chunk * d - size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    chunks = flat.reshape(d, chunk)
    i = lax.axis_index(axis_name)
    perm = _right_perm(d)
    # Reduce-scatter: at hop s, shard i forwards its running sum of chunk
    # (i - s) % d and folds the incoming one into chunk (i - s - 1) % d.
    for s in range(d - 1):
        buf = jnp.take(chunks, (i - s) % d, axis=0)
        buf = lax.ppermute(buf, axis_name, perm)
        chunks = chunks.at[(i - s - 1) % d].add(buf)
    # All-gather: rotate the complete chunks around the ring.
    for s in range(d - 1):
        buf = jnp.take(chunks, (i + 1 - s) % d, axis=0)
        buf = lax.ppermute(buf, axis_name, perm)
        chunks = chunks.at[(i - s) % d].set(buf)
    return chunks.reshape(-1)[:size].reshape(shape)


def _rotate_allreduce(x, axis_name: str, d: int, combine):
    """Rotate-and-accumulate ring all-reduce (d-1 full-payload hops): each
    shard's contribution travels the whole ring, folded in on arrival.
    Used for max/min (exact under any order)."""
    acc = x
    buf = x
    perm = _right_perm(d)
    for _ in range(d - 1):
        buf = lax.ppermute(buf, axis_name, perm)
        acc = combine(acc, buf)
    return acc


def _ring_gather(x, axis_name: str, d: int):
    """Ring all-gather: rotate each shard's block right d-1 times; after
    ``s`` hops the in-flight block is shard ``(i - s) % d``'s, scattered
    into the output at its source index."""
    i = lax.axis_index(axis_name)
    out = jnp.zeros((d,) + x.shape, x.dtype).at[i].set(x)
    buf = x
    perm = _right_perm(d)
    for s in range(1, d):
        buf = lax.ppermute(buf, axis_name, perm)
        out = out.at[(i - s) % d].set(buf)
    return out


# ----------------------------------------------------------------------
# Pallas TPU ring kernel (chip-only; see the module docstring).
# ----------------------------------------------------------------------

_LANE = 128
_SUBLANE = 8


def _ring_sum_kernel(x_ref, o_ref, comm, send_sem, recv_sem, *,
                     axis_name: str, d: int):
    """Rotate-and-accumulate ring sum with the hop DMA overlapped against
    the VPU reduce (SNIPPETS.md [1] / pallas_guide ring pattern, with one
    deliberate change: PER-HOP comm slots instead of a 2-slot double
    buffer). With 2 reusable slots and d >= 3, the left neighbor may run
    up to d-1 hops ahead (its progress is gated around the ring, not by
    us), so its hop-(s+2) DMA could overwrite a slot our hop-s send is
    still reading — avoiding that needs credit-based flow control. Per-hop
    slots make every buffer write-once (left's hop-s DMA targets slot s+1,
    which we touch only after waiting ``recv_sem[s+1]``), which deletes
    the race outright and costs ``d * payload`` VMEM — trivial for the
    consensus payloads (a few KB). The overlap the double buffer exists
    for is kept: hop s STARTS its DMA, then reduces the hop-(s-1) payload
    while the wire is busy, then waits."""
    from jax.experimental.pallas import tpu as pltpu

    my = lax.axis_index(axis_name)
    right = lax.rem(my + 1, d)
    left = lax.rem(my + d - 1, d)
    # Neighbor barrier: nobody starts DMAing until both neighbors' kernels
    # hold their scratch buffers (pallas_guide "Local Barrier" pattern).
    barrier = pltpu.get_barrier_semaphore()
    for neighbor in (left, right):
        pltpu.semaphore_signal(
            barrier, inc=1, device_id=(neighbor,),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
    pltpu.semaphore_wait(barrier, 2)
    o_ref[...] = x_ref[...]
    comm[0] = x_ref[...]
    for s in range(d - 1):
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm.at[s],
            dst_ref=comm.at[s + 1],  # written on the RIGHT neighbor; ours
            #                          is filled by the left's mirror copy.
            send_sem=send_sem.at[s],
            recv_sem=recv_sem.at[s + 1],
            device_id=(right,),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        # Overlap: reduce the payload received at hop s-1 (or our own at
        # s=0 — already accumulated, so skip) while the hop-s DMA flies.
        if s:
            o_ref[...] += comm[s]
        rdma.wait()
    o_ref[...] += comm[d - 1]


def _pallas_ring_allreduce(x, axis_name: str, d: int):
    """Run the ring-sum kernel over a tile-padded 2-D view of ``x``: the
    flat payload lands in an (R, 128) f32 tile block (R a sublane-tile
    multiple), zero-padded — pad lanes sum to zero and are sliced off."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    size = flat.size
    rows = -(-size // _LANE)
    rows = -(-rows // _SUBLANE) * _SUBLANE
    buf = jnp.zeros((rows * _LANE,), dtype).at[:size].set(flat)
    buf = buf.reshape(rows, _LANE)
    kernel = functools.partial(_ring_sum_kernel, axis_name=axis_name, d=d)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.VMEM),
        scratch_shapes=[
            pltpu.VMEM((d, rows, _LANE), dtype),
            pltpu.SemaphoreType.DMA((d,)),
            pltpu.SemaphoreType.DMA((d,)),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=_COLLECTIVE_ID,
        ),
    )(buf)
    return out.reshape(-1)[:size].reshape(shape)
