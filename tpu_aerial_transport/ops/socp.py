"""Batched conic QP/SOCP solver in pure JAX — the TPU-native replacement for
cvxpy + Clarabel (SURVEY.md §2.9, "the hard core of the port").

Problem form (OSQP-style splitting with a generalized cone):

    minimize    (1/2) x^T P x + q^T x
    subject to  A x in C,      C = Box(l, u)  x  SOC(d_1) x ... x SOC(d_k)

where the first ``n_box`` rows of ``A`` are box rows (equalities encoded as
``l == u``) and the remaining rows are second-order-cone blocks
``{ z : ||z[1:]||_2 <= z[0] }`` of *static* dims ``soc_dims``. This covers every
problem the reference builds with cvxpy (control/rqp_*.py): quadratic costs, linear
equalities (dynamics, kinematics), linear inequalities (CBF rows, min-thrust), and
per-agent SOC constraints (thrust cone, force norm cap).

Solver: ADMM

    x+ = (P + sigma I + A^T diag(rho) A)^{-1} (sigma x - q + A^T diag(rho)(z - y/rho))
    z+ = Pi_C(alpha A x+ + (1-alpha) z + y / rho)
    y+ = y + rho (alpha A x+ + (1-alpha) z - z+)

with over-relaxation ``alpha``, per-row penalty (equality rows get
``rho * EQ_RHO_SCALE``), and a fixed iteration count under ``lax.scan`` (fixed
shapes; vmappable over agents and Monte-Carlo scenarios; warm-startable by
passing the previous ``(x, y, z)``).

The KKT system ``(P + sigma I + A^T diag(rho) A) x = rhs`` is tiny
(~(12+3n)^2), so it is **explicitly inverted once per solve** and every ADMM
iteration applies the precomputed operator ``[sigma M^{-1} | M^{-1} A^T]`` as a
single matmul. On TPU this matters: batched small triangular solves are
inherently serial and run ~2x slower than the equivalent batched matmul (the
MXU path); the inverse costs one extra O(nv^3) op per solve and, for the
consensus controllers, is hoisted out of the control step entirely
(:func:`kkt_operator`). Accuracy: the KKT matrices are regularized
(``sigma``, ``rho`` scaling) with condition ~1e4, so the explicit-inverse
multiply is good to ~1e-3 relative in f32 — well inside ADMM's fixed-point
tolerance (the consensus loops stop at 1e-2).

Design notes vs the reference:
- cvxpy re-canonicalizes + Clarabel re-factorizes on every ``solve()`` call on the
  host; here the whole solver is one fused XLA computation, so a vmapped batch of
  n agent subproblems costs one kernel launch.
- Clarabel is an interior-point method (high accuracy, ~10 iters, but serial and
  branchy); ADMM trades per-iteration cost for TPU-friendly structure. The
  reference's own consensus loop only needs ~1e-2-accurate forces (res_tol = 1e-2 N,
  control/rqp_cadmm.py:561), well within ADMM's comfort zone.
"""

from __future__ import annotations

import functools
import os
from functools import partial
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from tpu_aerial_transport.obs import phases

EQ_RHO_SCALE = 1e3  # OSQP's rho boost for equality rows.
INF = 1e20  # "infinity" bound; keeps arithmetic finite in f32... used via clipping.

# f32 TPU tile: 8 sublanes x 128 lanes. The padded-operator tier
# (:func:`pad_qp` / :class:`PaddedKKTOp`) rounds every operator edge
# (variables, constraint rows) up to the SUBLANE tile; the 128-lane axis is
# supplied by the FOLDED batch (agents x Monte-Carlo scenarios — the
# controllers' nested vmaps and ops/admm_kernel.py's lane folding), not by
# per-instance padding, so a lone solve never pays 128x blow-up on its
# operator edges.
SUBLANE_TILE = 8

# What ``fused="auto"`` resolves to on a non-CPU backend when the
# TPU_AERIAL_FUSED env var does not say otherwise. Stays "scan" until a
# Pallas tier (the chunk kernel or the whole-solve mega-kernel) is
# validated on the real chip; the A/B criteria for flipping the default
# are in :func:`resolve_fused`'s docstring.
_AUTO_FUSED_NONCPU = "scan"

# The full fused-mode vocabulary ``solve_socp`` accepts. "pallas" /
# "interpret" run the fixed-iteration chunks through the lanes-last chunk
# kernel (ops/admm_kernel.py admm_chunk_lanes; K2 resident across one
# chunk); "kernel" / "kernel_interpret" run the WHOLE solve — per-solve w2
# build, every iteration's K2 apply + cone projection, and the exit
# residual reduction — through the batch-first mega-kernel
# (admm_kernel.fused_solve_lanes; all operators resident across the full
# inner budget). The *_interpret twins are the CPU-testable Pallas
# interpreter realizations of the same kernels.
FUSED_MODES = ("auto", "scan", "pallas", "interpret", "kernel",
               "kernel_interpret")

# Storage precision of the fused-kernel operator payload (see
# :func:`resolve_precision`): "f32", or "bf16" = bf16-storage /
# f32-accumulation of K2/Minv/A/P on the "kernel" paths (inert on
# scan/pallas — asserted HLO-identical in tests/test_fused_solve.py).
PRECISIONS = ("f32", "bf16")


class KKTOp(NamedTuple):
    """Precomputed ADMM x-update operator (see :func:`kkt_operator`)."""

    Minv: jnp.ndarray  # (nv, nv) inverse of P + sigma I + A^T diag(rho) A.
    MinvAT: jnp.ndarray  # (nv, m) Minv @ A^T.
    # The sigma the operator was built with: solve_socp uses THIS value in its
    # x-update so a caller passing an op built with a different sigma than
    # solve_socp's own argument cannot silently mix the two (which would
    # converge to a slightly wrong fixed point).
    sigma: jnp.ndarray = 1e-6
    # Prebuilt fused iteration operator [[sigma Minv, MinvAT],
    # [A sigma Minv, A MinvAT]] ((nv+m, nv+m) — see solve_socp's K2). Built
    # by :func:`kkt_operator` so the concatenates/matmuls run ONCE where the
    # operator is built (the controllers build it outside their consensus
    # loops) instead of relying on XLA hoisting them out of the enclosing
    # while_loop — measured ~0.5 ms/consensus-iteration at n = 64 on CPU
    # when the hoist does not happen. None on operators built by older
    # callers; solve_socp falls back to building it inline.
    K2: jnp.ndarray | None = None


class SOCPSolution(NamedTuple):
    x: jnp.ndarray  # (nv,) primal solution.
    y: jnp.ndarray  # (m,) dual solution.
    z: jnp.ndarray  # (m,) projected constraint values (A x at optimum).
    prim_res: jnp.ndarray  # () inf-norm of A x - z.
    dual_res: jnp.ndarray  # () inf-norm of P x + q + A^T y.


class PaddedKKTOp(NamedTuple):
    """Tile-aligned solve bundle: the padded problem data plus the KKT
    operator built on the padded layout (see :func:`padded_kkt_operator`).

    This is the hot-path tier: every edge of every iterated operator
    (``Minv``/``MinvAT``/``A``/``K2``, and the bounds/shift rows) is padded
    to a :data:`SUBLANE_TILE` multiple via :func:`padded_dims`, so the inner
    ADMM matvec contracts over lane-aligned dims and the 128-lane axis comes
    from the folded agent x scenario batch. Build once per (P, A) — e.g.
    once per control step in the consensus controllers — and solve many
    times with only ``q``/``warm`` moving.
    """

    P: jnp.ndarray  # (nv_p, nv_p) padded cost (identity on the pad block).
    A: jnp.ndarray  # (m_p, nv_p) padded constraints (zero pad rows/cols).
    lb: jnp.ndarray  # (n_box_p,) padded box bounds (pad rows are free).
    ub: jnp.ndarray  # (n_box_p,)
    shift: jnp.ndarray  # (m_p,) padded cone shift (zero on pad rows).
    op: KKTOp  # operator built FROM the padded data (block-exact).


def padded_dims(nv: int, n_box: int, soc_dims: Sequence[int] = ()):
    """Shape bucket for a padded QP: ``(nv_p, n_box_p)`` with ``nv_p`` and
    ``m_p = n_box_p + sum(soc_dims)`` the next :data:`SUBLANE_TILE`
    multiples of ``nv`` / ``m``. Padding goes into the BOX region (free
    rows), never into SOC blocks, so the static cone layout
    ``(n_box_p, soc_dims)`` stays exact.

    Bucketing: because every QP family rounds into the same coarse grid of
    tile multiples (harness/bucketing.py's :func:`~tpu_aerial_transport.
    harness.bucketing.bucket_dim`), heterogeneous per-agent dims that land
    in the same bucket — e.g. two controllers whose padded ``(nv_p, m_p,
    soc_dims)`` coincide — share one compiled ``solve_socp`` program (the
    jit cache keys on the padded shapes)."""
    from tpu_aerial_transport.harness.bucketing import bucket_dim

    m = n_box + sum(soc_dims)
    nv_p = bucket_dim(nv, SUBLANE_TILE)
    m_p = bucket_dim(m, SUBLANE_TILE)
    return nv_p, n_box_p_from(m, m_p, n_box)


def n_box_p_from(m: int, m_p: int, n_box: int) -> int:
    """Padded box-row count: all row padding lands in the box region."""
    return n_box + (m_p - m)


@phases.scope(phases.PAD)
def pad_qp(P, q, A, lb, ub, shift=None, *, n_box: int,
           soc_dims: Sequence[int] = ()):
    """Pad one QP to its tile bucket — EXACT in exact arithmetic, and the
    real entries' arithmetic is unchanged in f32 too (the pad entries are
    zeros; ``x + 0`` is exact), so padded and unpadded solves agree to the
    reduction-order rounding of the underlying matmuls.

    Layout: variables ``[real nv | pad]``; rows ``[box n_box | pad box |
    SOC blocks]`` (SOC blocks keep their exact dims, adjacent to the padded
    rows). Pad semantics:

    - pad variables: unit diagonal in ``P``, zero ``q``/columns — their
      x-update is ``x+ = sigma/(1+sigma) x`` from a zero start: exactly 0;
    - pad rows: zero ``A`` rows with FREE bounds (``+-INF``) and zero
      shift — the box projection is the identity there, ``y`` stays exactly
      0 and ``z`` tracks ``A x = 0``, so residuals are untouched.

    Single-instance; ``vmap`` for batches. Returns ``(P_p, q_p, A_p, lb_p,
    ub_p, shift_p)``; statics come from :func:`padded_dims`.
    """
    dtype = P.dtype
    nv = P.shape[-1]
    m = A.shape[-2]
    nv_p, n_box_p = padded_dims(nv, n_box, soc_dims)
    pad_v = nv_p - nv
    pad_b = n_box_p - n_box
    P_p = jnp.pad(P, ((0, pad_v), (0, pad_v)))
    if pad_v:
        P_p = P_p.at[nv:, nv:].add(jnp.eye(pad_v, dtype=dtype))
    q_p = jnp.pad(q, (0, pad_v))
    A_rows = jnp.concatenate(
        [A[:n_box], jnp.zeros((pad_b, nv), dtype), A[n_box:]], axis=0
    )
    A_p = jnp.pad(A_rows, ((0, 0), (0, pad_v)))
    lb_p = jnp.concatenate([lb, jnp.full((pad_b,), -INF, dtype)])
    ub_p = jnp.concatenate([ub, jnp.full((pad_b,), INF, dtype)])
    if shift is None:
        shift_p = jnp.zeros((m + pad_b,), dtype)
    else:
        shift_p = jnp.concatenate(
            [shift[:n_box], jnp.zeros((pad_b,), dtype), shift[n_box:]]
        )
    return P_p, q_p, A_p, lb_p, ub_p, shift_p


@phases.scope(phases.PAD)
def pad_warm(warm: "SOCPSolution", *, n_box: int,
             soc_dims: Sequence[int] = ()) -> "SOCPSolution":
    """Lift an unpadded warm start into the padded layout (zero pad entries
    — the exact fixed point of the pad rows/variables)."""
    nv = warm.x.shape[-1]
    m = warm.y.shape[-1]
    nv_p, n_box_p = padded_dims(nv, n_box, soc_dims)
    pad_b = n_box_p - n_box

    def pad_rows(v):
        zeros = jnp.zeros(v.shape[:-1] + (pad_b,), v.dtype)
        return jnp.concatenate(
            [v[..., :n_box], zeros, v[..., n_box:]], axis=-1
        )

    return SOCPSolution(
        x=jnp.pad(warm.x, [(0, 0)] * (warm.x.ndim - 1) + [(0, nv_p - nv)]),
        y=pad_rows(warm.y), z=pad_rows(warm.z),
        prim_res=warm.prim_res, dual_res=warm.dual_res,
    )


@phases.scope(phases.PAD)
def unpad_solution(sol: "SOCPSolution", nv: int, n_box: int,
                   n_box_p: int) -> "SOCPSolution":
    """Project a padded-layout solution back to the unpadded layout (drop
    pad variables and pad rows; residual scalars are already exact — the
    pad rows contribute exactly 0 to both inf-norms)."""

    def drop_rows(v):
        return jnp.concatenate([v[..., :n_box], v[..., n_box_p:]], axis=-1)

    return SOCPSolution(
        x=sol.x[..., :nv], y=drop_rows(sol.y), z=drop_rows(sol.z),
        prim_res=sol.prim_res, dual_res=sol.dual_res,
    )


def padded_kkt_operator(P, A, lb, ub, shift=None, *, n_box: int,
                        soc_dims: Sequence[int] = (), rho: float = 0.4,
                        sigma: float = 1e-6) -> PaddedKKTOp:
    """Build the tile-aligned solve bundle for one QP: pad to the bucket
    (:func:`pad_qp` with a zero linear term — ``q`` moves per solve) and
    build the KKT operator ON the padded data. The padded system matrix is
    block-diagonal (``[[M, 0], [0, (1+sigma) I]]``), so the real block of
    ``Minv`` matches the unpadded operator to LU rounding and the pad block
    is exactly diagonal. Single-instance; ``vmap`` for batches."""
    dtype = P.dtype
    nv = P.shape[-1]
    m = A.shape[-2]
    nv_p, n_box_p = padded_dims(nv, n_box, soc_dims)
    P_p, _, A_p, lb_p, ub_p, shift_p = pad_qp(
        P, jnp.zeros((nv,), dtype), A, lb, ub, shift,
        n_box=n_box, soc_dims=soc_dims,
    )
    m_p = m + (n_box_p - n_box)
    rho_vec = make_rho_vec(m_p, n_box_p, lb_p, ub_p, rho, dtype)
    op = kkt_operator(P_p, A_p, rho_vec, sigma)
    return PaddedKKTOp(P=P_p, A=A_p, lb=lb_p, ub=ub_p, shift=shift_p, op=op)


@partial(
    jax.jit,
    static_argnames=("n_box", "soc_dims", "iters", "check_every", "tol",
                     "fused", "alpha", "rho", "sigma", "precision",
                     "report_iters"),
)
def solve_socp_padded(
    P: jnp.ndarray,
    q: jnp.ndarray,
    A: jnp.ndarray,
    lb: jnp.ndarray,
    ub: jnp.ndarray,
    *,
    n_box: int,
    soc_dims: Sequence[int] = (),
    iters: int = 200,
    rho: float = 0.4,
    sigma: float = 1e-6,
    alpha: float = 1.6,
    warm: SOCPSolution | None = None,
    check_every: int = 0,
    tol: float = 0.0,
    shift: jnp.ndarray | None = None,
    pqp: PaddedKKTOp | None = None,
    fused: str = "auto",
    precision: str = "f32",
    active: jnp.ndarray | None = None,
    report_iters: bool = False,
):
    """Tile-aligned :func:`solve_socp`: pads the problem to its bucket
    (:func:`padded_dims`), solves on the padded layout, and returns the
    solution in the UNPADDED layout (pad variables/rows sliced off). Accepts
    a prebuilt :class:`PaddedKKTOp` via ``pqp`` for operator reuse across
    solves; ``warm`` is an UNPADDED warm start. Agreement with the unpadded
    path is to f32 reduction-order rounding (tests/test_socp_padded.py).
    ``active``/``report_iters`` pass through to :func:`solve_socp` (the
    adaptive-effort gate and the effective-iteration report)."""
    nv = P.shape[-1]
    n_box_p = padded_dims(nv, n_box, soc_dims)[1]
    if pqp is None:
        pqp = padded_kkt_operator(
            P, A, lb, ub, shift, n_box=n_box, soc_dims=soc_dims,
            rho=rho, sigma=sigma,
        )
    q_p = jnp.pad(q, (0, pqp.P.shape[-1] - nv))
    warm_p = None if warm is None else pad_warm(
        warm, n_box=n_box, soc_dims=soc_dims
    )
    sol = solve_socp(
        pqp.P, q_p, pqp.A, pqp.lb, pqp.ub,
        n_box=n_box_p, soc_dims=tuple(soc_dims), iters=iters, rho=rho,
        sigma=sigma, alpha=alpha, warm=warm_p, check_every=check_every,
        tol=tol, shift=pqp.shift, op=pqp.op, fused=fused,
        precision=precision, active=active, report_iters=report_iters,
    )
    if report_iters:
        sol, eff = sol
        return unpad_solution(sol, nv, n_box, n_box_p), eff
    return unpad_solution(sol, nv, n_box, n_box_p)


def project_soc(z: jnp.ndarray) -> jnp.ndarray:
    """Euclidean projection of ``z = (t, v) (..., d)`` onto the second-order cone
    ``||v|| <= t`` (closed form; Boyd & Vandenberghe §8.1.1)."""
    t = z[..., 0]
    v = z[..., 1:]
    nv = jnp.linalg.norm(v, axis=-1)
    # Three regimes: inside (keep), polar cone (zero), outside (radial shrink).
    inside = nv <= t
    polar = nv <= -t
    s = 0.5 * (t + nv)
    scale = jnp.where(nv > 0, s / jnp.where(nv > 0, nv, 1.0), 0.0)
    t_out = jnp.where(inside, t, jnp.where(polar, 0.0, s))
    v_out = jnp.where(
        inside[..., None],
        v,
        jnp.where(polar[..., None], 0.0, scale[..., None] * v),
    )
    return jnp.concatenate([t_out[..., None], v_out], axis=-1)


def _project_cone(z, lb, ub, n_box: int, soc_dims: Sequence[int], shift=None):
    """Project the stacked constraint vector onto the translated cone
    ``{z : z + shift in Box x SOC x ... x SOC}`` (``Pi(z) = Pi_C(z + shift) - shift``).

    ``shift`` carries constant offsets inside SOC blocks (e.g. the force-norm cap
    ``||f_i|| <= max_f`` has constant top element ``max_f``); box rows encode their
    offsets in ``lb``/``ub`` and must have zero shift.
    """
    if shift is not None:
        z = z + shift
    parts = []
    if n_box:
        parts.append(jnp.clip(z[..., :n_box], lb, ub))
    off = n_box
    # Group equal-dim SOC blocks into one batched projection (static grouping).
    i = 0
    dims = list(soc_dims)
    while i < len(dims):
        d = dims[i]
        j = i
        while j < len(dims) and dims[j] == d:
            j += 1
        k = j - i
        blk = z[..., off : off + k * d].reshape(*z.shape[:-1], k, d)
        parts.append(project_soc(blk).reshape(*z.shape[:-1], k * d))
        off += k * d
        i = j
    out = jnp.concatenate(parts, axis=-1) if len(parts) > 1 else parts[0]
    if shift is not None:
        out = out - shift
    return out


def _admm_step(carry, K2, w2, rho_vec, lb, ub, shift, *,
               nv, n_box, soc_dims, alpha):
    """One ADMM iteration (the scan path's body AND the numerics contract the
    Pallas chunk kernel transcribes — keep in sync with
    admm_kernel._admm_chunk_kernel)."""
    x, y, z = carry
    v = K2 @ jnp.concatenate([x, rho_vec * z - y]) - w2
    x_new, Ax = v[:nv], v[nv:]
    Ax_rel = alpha * Ax + (1 - alpha) * z
    z_new = _project_cone(Ax_rel + y / rho_vec, lb, ub, n_box, soc_dims, shift)
    y_new = y + rho_vec * (Ax_rel - z_new)
    return (x_new, y_new, z_new)


def _fold_batch_rules(batched, single, n_out: int) -> None:
    """Attach the ONE recursive vmap-folding rule pair every fused-solve
    runner shares (see :func:`_fused_chunk_runner`'s docstring for the
    folding rationale): the ``batched`` rule FOLDS each new (leading)
    vmap axis into the kernel's existing batch axis, the ``single`` rule
    lifts an unbatched call into ``batched`` — one copy, so an axis-
    ordering fix cannot drift between runners."""

    @batched.def_vmap
    def _batched_rule(axis_size, in_batched, *args):
        folded = []
        for a, b in zip(args, in_batched):
            if not b:
                a = jnp.broadcast_to(a[None], (axis_size,) + a.shape)
            folded.append(a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]))
        outs = batched(*folded)
        unfold = lambda o: o.reshape((axis_size, -1) + o.shape[1:])
        return tuple(unfold(o) for o in outs), (True,) * n_out

    @single.def_vmap
    def _single_rule(axis_size, in_batched, *args):
        lifted = [
            a if b else jnp.broadcast_to(a[None], (axis_size,) + a.shape)
            for a, b in zip(args, in_batched)
        ]
        return batched(*lifted), (True,) * n_out


@functools.lru_cache(maxsize=None)
def _fused_chunk_runner(nv: int, n_box: int, soc_dims: tuple, iters: int,
                        alpha: float, interpret: bool):
    """Build the vmap-folding runner for one static chunk configuration.

    Returns a function ``(x, y, z, K2, w2, rho, lb, ub, shift) -> (x, y, z)``
    running ``iters`` ADMM iterations. Unbatched calls use the plain scan
    (a lone solve gains nothing from a kernel); every enclosing ``vmap``
    axis — agents, then Monte-Carlo scenarios — is FOLDED into the Pallas
    kernel's explicit lane axis via a recursive ``custom_vmap`` pair, rather
    than letting vmap lift the kernel to one sequential grid cell per lane
    (see admm_kernel module docstring)."""
    from tpu_aerial_transport.ops import admm_kernel

    kw = dict(nv=nv, n_box=n_box, soc_dims=soc_dims, alpha=alpha)

    @jax.custom_batching.custom_vmap
    def batched(x, y, z, K2, w2, rho, lb, ub, shift):
        # Leading batch axis on every arg.
        return admm_kernel.admm_chunk_lanes(
            x, y, z, K2, w2, rho, lb, ub, shift,
            iters=iters, interpret=interpret, **kw,
        )

    @jax.custom_batching.custom_vmap
    def single(x, y, z, K2, w2, rho, lb, ub, shift):
        def stepf(c, _):
            return _admm_step(c, K2, w2, rho, lb, ub, shift, **kw), None
        return lax.scan(stepf, (x, y, z), None, length=iters)[0]

    _fold_batch_rules(batched, single, 3)
    return single


@functools.lru_cache(maxsize=None)
def _fused_solve_runner(nv: int, n_box: int, soc_dims: tuple, iters: int,
                        alpha: float, interpret: bool, has_shift: bool,
                        precision: str, with_res: bool):
    """Build the vmap-folding runner for the WHOLE-solve mega-kernel
    (admm_kernel.fused_solve_lanes — fused="kernel"/"kernel_interpret").

    Returns ``(x, y, z, K2, Minv, A, P, q, rho, lb, ub, shift) ->
    (x, y, z[, prim_res, dual_res])`` running the per-solve w2 build,
    ``iters`` ADMM iterations, and (``with_res``) the exit residual
    reduction in one kernel. Same batching discipline as
    :func:`_fused_chunk_runner`: unbatched calls take the plain scan path
    (a lone solve gains nothing from a kernel); every enclosing ``vmap``
    axis — agents, then Monte-Carlo scenarios — FOLDS into the kernel's
    leading batch axis via the recursive ``custom_vmap`` pair. ``shift``
    is a fixed-arity placeholder when ``has_shift`` is False (the scan
    twin and the kernel both skip the cone-shift adds statically, so a
    shiftless solve cannot pick up ``z + 0`` signed-zero flips)."""
    from tpu_aerial_transport.ops import admm_kernel

    kw = dict(nv=nv, n_box=n_box, soc_dims=soc_dims, alpha=alpha)
    n_out = 5 if with_res else 3

    @jax.custom_batching.custom_vmap
    def batched(x, y, z, K2, Minv, A, P, q, rho, lb, ub, shift):
        # Leading batch axis on every arg.
        xo, yo, zo, prim, dual = admm_kernel.fused_solve_lanes(
            x, y, z, K2, Minv, A, P, q, rho, lb, ub,
            shift if has_shift else None,
            iters=iters, precision=precision, interpret=interpret, **kw,
        )
        if with_res:
            return xo, yo, zo, prim, dual
        return xo, yo, zo

    @jax.custom_batching.custom_vmap
    def single(x, y, z, K2, Minv, A, P, q, rho, lb, ub, shift):
        # The scan path's own per-instance program (bitwise twin of the
        # kernel body's vmapped functions).
        wq = Minv @ q
        w2 = jnp.concatenate([wq, A @ wq])
        s = shift if has_shift else None

        def stepf(c, _):
            return _admm_step(c, K2, w2, rho, lb, ub, s, **kw), None

        x, y, z = lax.scan(stepf, (x, y, z), None, length=iters)[0]
        if with_res:
            prim = jnp.max(jnp.abs(A @ x - z))
            dual = jnp.max(jnp.abs(P @ x + q + A.T @ y))
            return x, y, z, prim, dual
        return x, y, z

    _fold_batch_rules(batched, single, n_out)
    return single


@functools.lru_cache(maxsize=None)
def _fused_solve_exit_runner(nv: int, n_box: int, soc_dims: tuple,
                             iters: int, alpha: float, interpret: bool,
                             has_shift: bool, precision: str,
                             check_every: int, tol: float,
                             has_active: bool):
    """Early-exit twin of :func:`_fused_solve_runner`: the WHOLE
    tolerance-chunked solve — w2 build, chunks of ``check_every``
    iterations with per-lane converged freezing, whole-grid-cell loop
    exit, the exit residual reduction, and the per-lane effective
    iteration count — in ONE ``pallas_call``
    (admm_kernel.fused_solve_lanes ``check_every/tol``). This is what
    closes the PR-12 regression where a ``check_every/tol`` solve wrapped
    ``run_chunk`` in an XLA-side ``lax.while_loop`` that re-launched the
    kernel (re-streaming every operator from HBM) once per chunk.

    Returns ``(x, y, z, K2, Minv, A, P, q, rho, lb, ub, shift, active) ->
    (x, y, z, prim_res, dual_res, eff_iters)``. ``active`` is the
    per-lane consensus-effort gate ((,) bool per instance; a fixed-arity
    all-ones placeholder when ``has_active`` is False — like ``shift``,
    statically skipped so the common path stages no gating ops). The
    ``single`` twin is the scan path's OWN explicit-masked chunk loop
    (bitwise oracle; value-identical to lax.while_loop's vmap batching
    rule), so vmapping it ≡ the kernel's interpret body by construction.
    """
    from tpu_aerial_transport.ops import admm_kernel

    kw = dict(nv=nv, n_box=n_box, soc_dims=soc_dims, alpha=alpha)
    n_out = 6

    @jax.custom_batching.custom_vmap
    def batched(x, y, z, K2, Minv, A, P, q, rho, lb, ub, shift, active):
        outs = admm_kernel.fused_solve_lanes(
            x, y, z, K2, Minv, A, P, q, rho, lb, ub,
            shift if has_shift else None,
            active if has_active else None,
            iters=iters, precision=precision, interpret=interpret,
            check_every=check_every, tol=tol, **kw,
        )
        return outs

    @jax.custom_batching.custom_vmap
    def single(x, y, z, K2, Minv, A, P, q, rho, lb, ub, shift, active):
        # The scan path's own per-instance program: w2 build + the
        # explicit-masked tolerance-chunked loop (bitwise twin of the
        # kernel body's vmapped functions — see solve_socp's tol path).
        wq = Minv @ q
        w2 = jnp.concatenate([wq, A @ wq])
        s = shift if has_shift else None

        def stepf(c, _):
            return _admm_step(c, K2, w2, rho, lb, ub, s, **kw), None

        def run_chunk(c, n_it):
            return lax.scan(stepf, c, None, length=n_it)[0]

        def residuals(c):
            prim = jnp.max(jnp.abs(A @ c[0] - c[2]))
            dual = jnp.max(jnp.abs(P @ c[0] + q + A.T @ c[1]))
            return prim, dual

        def above_tol(c):
            prim, dual = residuals(c)
            return (prim > tol) | (dual > tol)

        gate = active > 0 if has_active else None
        carry, n_chunks, eff = _masked_chunk_loop(
            (x, y, z), run_chunk, above_tol, gate, iters, check_every,
        )
        prim, dual = residuals(carry)
        return carry[0], carry[1], carry[2], prim, dual, eff

    _fold_batch_rules(batched, single, n_out)
    return single


def _masked_chunk_loop(carry0, run_chunk, above_tol, gate, iters: int,
                       check_every: int):
    """The ONE tolerance-chunked early-exit loop body (per instance):
    chunks of ``check_every`` iterations under a ``lax.while_loop`` whose
    carry holds an EXPLICIT per-lane active bit — converged (or
    ``gate``-masked) lanes take frozen select updates, so under ``vmap``
    the cond is the honest any-lane-active test and frozen lanes are
    documented-cheap selects rather than an implicit batching-rule
    artifact. Value-identical per lane to the pre-explicit form (the
    batching rule applied the same select itself — regression-pinned
    bitwise vs the unbatched solve in tests/test_effort.py).

    Shared by solve_socp's scan/pallas tol path and the kernel runner's
    ``single`` twin so the mask logic cannot drift between them. Returns
    ``(carry, n_chunks, eff_iters)`` with ``eff_iters`` the effective
    iteration count actually applied (0 for a gated-off lane — the
    consensus-level adaptive-effort pass-through).
    """
    n_full, rem = divmod(iters, check_every)
    n_chunks = jnp.zeros((), jnp.int32)
    carry = carry0

    def working(c):
        # gate=None stages NO gating ops (the plain inner_tol path).
        return above_tol(c) if gate is None else gate & above_tol(c)

    if n_full:
        def cond(s):
            # The lane's own active bit; lax.while_loop's vmap batching
            # rule ORs lanes — the honest any-lane-active test.
            return s[2]

        def body(s):
            c, i, act = s
            new = run_chunk(c, check_every)
            c = jax.tree.map(lambda a, b: jnp.where(act, a, b), new, c)
            i = i + act.astype(jnp.int32)
            act = act & (i < n_full) & above_tol(c)
            return (c, i, act)

        carry, n_chunks, _ = lax.while_loop(
            cond, body, (carry, n_chunks, working(carry))
        )
    eff = n_chunks * check_every
    if rem:
        # Remainder chunk keeps the total at exactly ``iters`` when the
        # budget is not a multiple of check_every (skipped if converged
        # or gated off; a select over both branches under vmap).
        need = working(carry)
        carry = lax.cond(
            need, lambda c: run_chunk(c, rem), lambda c: c, carry
        )
        eff = eff + jnp.where(need, rem, 0)
    return carry, n_chunks, eff


# The consensus-level solver-effort vocabulary (controllers'
# ``effort=`` knob; see :func:`resolve_effort`).
EFFORTS = ("fixed", "adaptive")


def resolve_effort(effort: str | None = "auto") -> str:
    """Resolve the controllers' consensus-level solver-effort knob at
    CONFIG BUILD time (the :func:`resolve_fused`/``resolve_consensus``
    idiom): ``"auto"`` (or None) consults the ``TAT_EFFORT`` env var
    (``fixed`` | ``adaptive`` | ``auto``/unset) and otherwise stays
    ``"fixed"`` — the reference's fixed-iteration-cap behavior, which
    compiles HLO identical to a pre-knob config (asserted in
    tests/test_effort.py; the ``no_faults()``/``telemetry=None``
    zero-cost contract).

    ``"adaptive"`` makes effort follow convergence through the whole
    stack: the inner ADMM solves run tolerance-chunked with per-lane
    early exit (in-kernel on the fused="kernel" path — one pallas_call,
    operators read from HBM once per solve), and the consensus loop
    threads its own per-scenario converged mask into them so a converged
    lane's solve is a 0-effective-iteration pass-through instead of a
    full-budget re-solve; per-step effort lands on
    ``SolverStats.inner_iters`` for the telemetry histograms.

    **Chip-round flip criterion** (for making ``adaptive`` the non-CPU
    default; the decision cells are ``{cadmm,dd}_n{16,64}_effort_
    {fixed,adaptive}`` in BENCH_SWEEP.json): (1) the adaptive arm beats
    its fixed twin by >= 15% scenario-MPC-steps/s at EQUAL
    consensus-residual quality — both arms' ``final_consensus_res``
    under the paper's 1e-2 N bar (an adaptive "win" that gave back
    convergence is a refusal, not a flip); (2) the recorded iteration
    histograms (``iters_hist`` / the telemetry effort section) confirm
    the straggler spread the adaptivity exists to exploit — a
    near-degenerate histogram means the workload has no spread and the
    measured win is noise; (3) the parity suite (tests/test_effort.py:
    adaptive vs fixed within 1e-2 N, nominal AND alive-masked, cadmm AND
    dd) stays green on-chip."""
    if effort is None:
        effort = "auto"
    if effort == "auto":
        env = os.environ.get("TAT_EFFORT", "").strip().lower()
        if env in EFFORTS:
            return env
        if env not in ("", "auto"):
            raise ValueError(
                f"TAT_EFFORT={env!r}: expected one of {EFFORTS} or 'auto'"
            )
        return "fixed"
    if effort not in EFFORTS:
        raise ValueError(
            f"effort={effort!r}: expected one of {EFFORTS} or 'auto'"
        )
    return effort


def resolve_fused(fused: str) -> str:
    """Resolve ``"auto"`` to the backend default: "scan" on CPU (the Pallas
    kernels have no useful CPU lowering); elsewhere the ``TPU_AERIAL_FUSED``
    env var (``pallas`` | ``scan`` | ``kernel`` | ``auto``/unset) and then
    the in-code default ``_AUTO_FUSED_NONCPU``. Controllers call this at
    CONFIG BUILD time (outside jit) so the chosen mode is an explicit
    static config field — resolving inside a jitted function would bake
    the first backend seen into a trace cache keyed only on the "auto"
    string (stale if the process later switches platforms).

    **A/B criterion for flipping the non-CPU default to "pallas"** (kept
    here so the ops A/B and the flip live together): on a live chip,
    (1) ``python bench.py --smoke`` passes (Mosaic compiles the kernel and
    scan/pallas solutions agree < 5e-4), and (2) the checkpointed sweep's
    fused A/B cells (``headline_fused_pallas_*``,
    ``{cadmm,dd}_n64_batch64_fused_pallas``) beat their scan twins by >=
    10% on the batched configs. Until both hold on-chip, deployments can
    opt in per-process with ``TPU_AERIAL_FUSED=pallas`` (or per-config via
    ``socp_fused="pallas"``) without a code change.

    **A/B criterion for flipping the non-CPU default to "kernel"** (the
    whole-solve mega-kernel, admm_kernel.fused_solve_lanes): on a live
    chip, (1) the interpret-parity suite (tests/test_fused_solve.py) stays
    green and the on-chip kernel/scan solutions agree to the same f32 bar,
    (2) the sweep's ``{cadmm,dd}_n{16,64}_fused_kernel`` cells beat their
    ``_fused_scan`` twins by >= 15% (it must beat the chunk kernel too, or
    "pallas" wins instead), and (3) ``op_profile --by-phase`` shows the
    local_solve + qp_build share of op self-time (84% on the round-1
    headline trace) shrinking — the HBM re-read traffic the kernel exists
    to delete actually went away. The ``_fused_kernel_bf16`` twins
    additionally require the consensus-residual parity bar
    (< the config's res_tol, the paper's 1e-2 N) before bf16 storage can
    default anywhere — bench.py's bf16 arm refuses (re-measures at f32)
    when that bar fails.

    The env var is consulted HERE only — i.e. at config-build time, the
    documented resolution point. ``solve_socp`` called directly with
    ``fused="auto"`` resolves backend-only (:func:`_resolve_fused`): an
    env read inside its jitted body would execute at trace time and be
    cached under the static key "auto", so a later env change would be
    silently ignored — the exact staleness this function exists to avoid.
    Direct callers who want the env gate call ``resolve_fused`` themselves
    (or pass an explicit mode)."""
    if fused == "auto" and jax.default_backend() != "cpu":
        env = os.environ.get("TPU_AERIAL_FUSED", "").strip().lower()
        if env in ("pallas", "scan", "kernel"):
            return env
        if env not in ("", "auto"):
            raise ValueError(
                f"TPU_AERIAL_FUSED={env!r}: expected 'pallas', 'scan', "
                "'kernel' or 'auto'"
            )
    return _resolve_fused(fused)


def resolve_precision(precision: str | None = "auto") -> str:
    """Resolve the fused-kernel operator storage precision at CONFIG BUILD
    time (the :func:`resolve_fused` idiom): ``"auto"`` (or None) consults
    the ``TPU_AERIAL_PRECISION`` env var (``f32`` | ``bf16`` |
    ``auto``/unset) and otherwise stays ``"f32"`` — bf16 storage halves
    the kernel's HBM operator payload (the tile machinery already pads
    every edge to the (8, 128) discipline; bf16 doubles the lane payload)
    but only becomes a default candidate after the chip round's
    ``*_fused_kernel_bf16`` A/B cells pass the consensus-residual parity
    bar (see :func:`resolve_fused`'s kernel flip criterion). Explicit
    values pass through validated. The knob is inert off the "kernel"
    fused paths (asserted HLO-identical on scan)."""
    if precision is None:
        precision = "auto"
    if precision == "auto":
        env = os.environ.get("TPU_AERIAL_PRECISION", "").strip().lower()
        if env in PRECISIONS:
            return env
        if env not in ("", "auto"):
            raise ValueError(
                f"TPU_AERIAL_PRECISION={env!r}: expected one of "
                f"{PRECISIONS} or 'auto'"
            )
        return "f32"
    if precision not in PRECISIONS:
        raise ValueError(
            f"precision={precision!r}: expected one of {PRECISIONS} or "
            "'auto'"
        )
    return precision


def _kernel_runs_offchip() -> bool:
    """Trace-time host query backing the ``fused="kernel"`` off-TPU
    downgrade (the ``ring._resolve_impl`` precedent): the mega-kernel has
    no CPU/GPU lowering, so a config forced to "kernel" still compiles —
    as the scan path — when the program lands off-TPU (e.g. the backend
    guard's CPU fallback rung re-running a sweep cell). Uses
    ``ring.effective_platform`` so a ``jax.default_device(cpu)`` fallback
    context is honored (``jax.default_backend()`` would still report the
    wedged chip)."""
    from tpu_aerial_transport.parallel import ring

    return ring.effective_platform() != "tpu"


def _resolve_fused(fused: str) -> str:
    """solve_socp-internal "auto" resolution: backend-only, NO env read
    (see resolve_fused — env reads under trace go stale in the jit cache).
    Rejects anything outside :data:`FUSED_MODES` — a typo'd mode must be
    a clear ValueError here, not an opaque Mosaic lowering failure from
    falling into the chunk-kernel branch.
    """
    if fused not in FUSED_MODES:
        raise ValueError(
            f"fused={fused!r}: expected one of {FUSED_MODES}"
        )
    if fused == "auto":
        return (
            "scan" if jax.default_backend() == "cpu" else _AUTO_FUSED_NONCPU
        )
    return fused


def runtime_fused_mode(fused: str, nv: int, m: int,
                       n_box: int | None = None, *,
                       check_every: int = 0, tol: float = 0.0) -> str:
    """The mode :func:`solve_socp` will ACTUALLY run for ``fused`` at
    operator dims ``(nv, m)`` on this host: "auto" backend resolution,
    the "kernel" off-TPU trace-time downgrade, the VMEM-residency
    fallbacks (``fused_solve_fits`` for the whole-solve kernel,
    ``MAX_FUSED_DIM`` for the chunk kernel), and the CHUNKING mode —
    pass the solve's ``check_every``/``tol`` so a tolerance-chunked
    measurement is labeled by the same decision that dispatches it. ONE
    resolver shared by solve_socp's dispatch and by anything that must
    LABEL a measurement with the mode that really ran (bench.py's
    fused/effort A/B cells record it as ``fused_resolved`` — a cell
    whose dims silently fell back to scan must not be read as a kernel
    verdict). A ``check_every/tol`` solve on the "kernel" paths runs the
    in-kernel early-exit form — still ONE pallas_call, so "kernel" is an
    honest label; before the early-exit form existed, a tol-chunked
    solve labeled "kernel" actually paid an XLA-side while_loop of
    per-chunk kernel relaunches (the label drift this fold closes)."""
    # Host-side strings only (the ring._resolve_impl pattern), never a
    # traced value.
    del check_every, tol  # both kernel forms exist for every chunking
    # mode today; the args are part of the contract so a future
    # constraint lands HERE (label + dispatch together), not in a caller.
    mode = _resolve_fused(fused)
    if mode == "kernel" and _kernel_runs_offchip():  # jaxlint: disable=JL005
        mode = "scan"
    if mode in ("kernel", "kernel_interpret"):
        from tpu_aerial_transport.ops import admm_kernel

        if not admm_kernel.fused_solve_fits(
            nv, m, m if n_box is None else n_box
        ):
            mode = "scan"
    elif mode != "scan":
        from tpu_aerial_transport.ops import admm_kernel

        if nv + m > admm_kernel.MAX_FUSED_DIM:
            mode = "scan"
    return mode


def resolve_pad_operators(pad: bool | None) -> bool:
    """Resolve the controllers' ``pad_operators="auto"`` (None) to the
    backend default, at CONFIG BUILD time (the :func:`resolve_fused`
    idiom). Tile padding is layout prep for the f32 (8, 128) TPU tile;
    XLA-CPU has no tile to hit and only sees the extra pad FLOPs —
    measured 0.84-1.00x on the CPU scaling A/B (BENCH_SCALING.json) — so
    the default is False on CPU and True elsewhere. Pass an explicit bool
    to force either layout (the bench A/B and the parity tests do)."""
    if pad is None:
        return jax.default_backend() != "cpu"
    return pad


@partial(
    jax.jit,
    # alpha is static: it parameterizes the fused-chunk kernel build (a
    # Python-level cache key), and it is an algorithm constant at every call
    # site — a traced alpha would also break the scan/pallas parity contract.
    static_argnames=("n_box", "soc_dims", "iters", "check_every", "tol",
                     "fused", "alpha", "precision", "report_iters"),
)
def solve_socp(
    P: jnp.ndarray,
    q: jnp.ndarray,
    A: jnp.ndarray,
    lb: jnp.ndarray,
    ub: jnp.ndarray,
    *,
    n_box: int,
    soc_dims: Sequence[int] = (),
    iters: int = 200,
    rho: float = 0.4,
    sigma: float = 1e-6,
    alpha: float = 1.6,
    warm: SOCPSolution | None = None,
    check_every: int = 0,
    tol: float = 0.0,
    shift: jnp.ndarray | None = None,
    op: KKTOp | None = None,
    fused: str = "auto",
    precision: str = "f32",
    active: jnp.ndarray | None = None,
    report_iters: bool = False,
):
    """Solve one conic QP. All array args may carry leading batch axes only via
    ``vmap`` (this function itself is single-instance).

    Args:
      P: (nv, nv) PSD cost matrix. q: (nv,) linear cost.
      A: (m, nv) constraint matrix; rows [box (n_box) | soc blocks (sum soc_dims)].
      lb/ub: (n_box,) box bounds; equalities have lb == ub. Use +-INF for one-sided.
      n_box / soc_dims: static cone layout.
      iters: fixed ADMM iteration count (scan length).
      warm: previous solution to warm-start from (the reference's
        ``warm_start=True`` semantics, control/rqp_centralized.py:440).
      check_every/tol: if nonzero, early-exit via ``lax.while_loop`` over chunks of
        ``check_every`` scanned iterations once inf-norm residuals < tol.
      shift: optional (m,) constant cone offset — the constraint becomes
        ``A x + shift in C`` for the SOC rows (box rows must have zero shift).
      op: optional precomputed :class:`KKTOp` (see :func:`kkt_operator`). Callers
        that re-solve with the same (P, A) but different q — e.g. the C-ADMM
        consensus loop, where only the dual/consensus linear term moves between
        iterations — build the operator once per control step and amortize.
      fused: how to run the fixed-iteration chunks — "scan" (lax.scan of
        single iterations), "pallas" (the fused TPU chunk kernel,
        ops/admm_kernel.py: K2 resident in VMEM across iterations, enclosing
        vmap axes folded into kernel lanes), "interpret" (same kernel via the
        Pallas interpreter — CPU-testable), "kernel" (the whole-solve
        mega-kernel, admm_kernel.fused_solve_lanes: per-solve w2 build +
        every iteration + the exit residual reduction in ONE pallas_call,
        all operators VMEM-resident; downgrades to scan off-TPU at trace
        time), "kernel_interpret" (its CPU-testable interpreter twin —
        bitwise-equal to scan, tests/test_fused_solve.py), or "auto".
        Solves too big for VMEM residency (admm_kernel.MAX_FUSED_DIM /
        fused_solve_fits, e.g. centralized n = 64) fall back to "scan"
        regardless.
      precision: operator storage on the "kernel" paths — "f32", or "bf16"
        (bf16-storage / f32-accumulation of K2/Minv/A/P; halves the HBM
        operator payload). Inert on scan/pallas paths.
      active: optional () bool gate (tolerance-chunked path only — the
        consensus-level adaptive-effort tier): False makes this solve a
        0-effective-iteration pass-through of the warm start, so a
        converged consensus lane inside a vmapped batch stops paying for
        stragglers. None (the default) stages no gating ops.
      report_iters: when True, return ``(solution, eff_iters)`` with
        ``eff_iters`` the () int32 iteration count actually applied
        (``iters`` on the fixed path; chunks-run x check_every (+ the
        remainder) on the tolerance-chunked path — the effort-telemetry
        input). False (the default) keeps the historical single-value
        return.
    """
    m, nv = A.shape
    assert m == n_box + sum(soc_dims)
    dtype = P.dtype
    if active is not None and not (check_every and tol > 0):
        raise ValueError(
            "solve_socp(active=) needs the tolerance-chunked path "
            "(check_every > 0 and tol > 0): a fixed-iteration solve "
            "cannot express a 0-effective-iteration pass-through"
        )

    rho_vec = make_rho_vec(m, n_box, lb, ub, rho, dtype)

    if op is None:
        op = kkt_operator(P, A, rho_vec, sigma)
    # Fused iteration operator: with u = [x ; rho z - y],
    #   x+   = K @ u - Minv q          (the ADMM x-update)
    #   A x+ = (A K) @ u - A Minv q    (needed by the z/y updates)
    # stack both into ONE (nv+m, nv+m) matmul per iteration — the entire
    # linear-algebra step of an ADMM iteration as a single MXU op.
    # op.sigma (not this function's sigma argument) keeps the x-update
    # consistent with whatever sigma the operator was actually built with.
    # kkt_operator prebuilds K2 (donation-/hoist-clean: the concatenates run
    # where the operator is built, outside any enclosing consensus loop);
    # operators from older builders fall back to the inline build.
    if op.K2 is not None:
        K2 = op.K2
    else:
        K = jnp.concatenate(
            [op.sigma * op.Minv, op.MinvAT], axis=-1
        )  # (nv, nv+m)
        K2 = jnp.concatenate([K, A @ K], axis=0)  # (nv + m, nv + m)

    # Mode resolution runs before any mode-dependent ops are staged:
    # "auto" backend resolution, the "kernel" off-TPU trace-time
    # downgrade (the ring._resolve_impl precedent — a backend-guard CPU
    # re-run of a kernel-configured cell still measures a working solve),
    # the VMEM-residency fallbacks, AND the chunking mode (a
    # check_every/tol solve dispatches the early-exit kernel form), all
    # in the ONE shared resolver so measurement labels (bench
    # fused_resolved) cannot drift from dispatch.
    tol_path = bool(check_every) and tol > 0
    fused_mode = runtime_fused_mode(
        fused, nv, m, n_box, check_every=check_every, tol=tol
    )
    solve_kernel = fused_mode in ("kernel", "kernel_interpret")

    if not solve_kernel:
        # w2 build (the per-solve qp-build tail). The whole-solve kernel
        # runs these two matvecs INSIDE the pallas_call from (Minv, A, q)
        # so the operator read that feeds them stays VMEM-resident.
        wq = op.Minv @ q
        w2 = jnp.concatenate([wq, A @ wq])  # (nv + m,)

    if warm is None:
        x0 = jnp.zeros((nv,), dtype)
        y0 = jnp.zeros((m,), dtype)
        z0 = jnp.zeros((m,), dtype)
    else:
        x0, y0, z0 = warm.x, warm.y, warm.z
    # Always project z0 onto the translated cone: exact identity for any
    # in-cone z (a real warm start — clip and SOC branches return the input
    # unchanged), and it repairs out-of-cone starts, e.g. an all-zeros COLD
    # start passed through the ``warm`` argument by a batched consensus
    # loop: z = 0 violates every equality row's rhs, and with the
    # EQ_RHO_SCALE-boosted penalties an unprojected zero start can burn the
    # whole fixed inner budget recovering (observed: RP C-ADMM cold-start
    # solves stalling at 1.6e-2 primal vs 2e-3 from the projected start).
    z0 = _project_cone(z0, lb, ub, n_box, soc_dims, shift)

    step_kw = dict(nv=nv, n_box=n_box, soc_dims=tuple(soc_dims), alpha=alpha)

    if solve_kernel:
        interp = fused_mode == "kernel_interpret"
        # Fixed-arity placeholder when shift is None — the runner's
        # has_shift static keeps both the kernel and its scan twin on the
        # shiftless branch (no z + 0 signed-zero drift).
        shift_k = shift if shift is not None else jnp.zeros((m,), dtype)
        kernel_args = (K2, op.Minv, A, P, q, rho_vec, lb, ub, shift_k)
        # (No per-chunk runner here: BOTH kernel forms — fixed-iteration
        # and tolerance-chunked — run the whole solve in one pallas_call;
        # the tol path's chunking happens INSIDE the kernel.)
    elif fused_mode == "scan":

        def step(carry, _):
            return _admm_step(
                carry, K2, w2, rho_vec, lb, ub, shift, **step_kw
            ), None

        def run_chunk(carry, k):
            return lax.scan(step, carry, None, length=k)[0]
    else:
        shift_arr = (
            shift if shift is not None else jnp.zeros((m,), dtype)
        )

        def run_chunk(carry, k):
            runner = _fused_chunk_runner(
                nv, n_box, tuple(soc_dims), k, alpha,
                fused_mode == "interpret",
            )
            return runner(*carry, K2, w2, rho_vec, lb, ub, shift_arr)

    def residuals(carry):
        x, y, z = carry
        prim = jnp.max(jnp.abs(A @ x - z))
        dual = jnp.max(jnp.abs(P @ x + q + A.T @ y))
        return prim, dual

    def result(sol, eff):
        return (sol, eff) if report_iters else sol

    if tol_path and solve_kernel:
        # In-kernel early exit: the WHOLE tolerance-chunked solve — w2
        # build, chunks with per-lane converged freezing, whole-grid-cell
        # loop exit, exit residuals, per-lane effective iteration count —
        # in ONE pallas_call, so the operators are still read from HBM
        # once per solve. (Before this, a check_every/tol solve wrapped
        # run_chunk in an XLA-side while_loop re-launching the kernel —
        # re-streaming the operators — once per chunk: exactly the PR-12
        # VMEM-residency win given back.)
        runner = _fused_solve_exit_runner(
            nv, n_box, tuple(soc_dims), iters, alpha, interp,
            shift is not None, precision, check_every, tol,
            active is not None,
        )
        act_arg = active if active is not None else jnp.ones((), dtype)
        with phases.scope(phases.FUSED_SOLVE):
            x, y, z, prim, dual, eff = runner(
                x0, y0, z0, *kernel_args, act_arg
            )
        return result(
            SOCPSolution(x=x, y=y, z=z, prim_res=prim, dual_res=dual), eff
        )
    if tol_path:

        def above_tol(carry):
            prim, dual = residuals(carry)
            return (prim > tol) | (dual > tol)

        carry, _, eff = _masked_chunk_loop(
            (x0, y0, z0), run_chunk, above_tol, active, iters, check_every,
        )
    elif solve_kernel:
        # Fixed-iteration whole-solve kernel: the exit residual reduction
        # rides INSIDE the pallas_call (with_res=True) — nothing of the
        # solve touches HBM between the operator read and the solution
        # write.
        runner = _fused_solve_runner(
            nv, n_box, tuple(soc_dims), iters, alpha, interp,
            shift is not None, precision, True,
        )
        with phases.scope(phases.FUSED_SOLVE):
            x, y, z, prim, dual = runner(x0, y0, z0, *kernel_args)
        return result(
            SOCPSolution(x=x, y=y, z=z, prim_res=prim, dual_res=dual),
            jnp.asarray(iters, jnp.int32),
        )
    else:
        carry = run_chunk((x0, y0, z0), iters)
        eff = jnp.asarray(iters, jnp.int32)

    x, y, z = carry
    prim, dual = residuals(carry)
    return result(
        SOCPSolution(x=x, y=y, z=z, prim_res=prim, dual_res=dual), eff
    )


def solution_is_finite(sols: "SOCPSolution") -> jnp.ndarray:
    """Per-instance all-finite check over a (batched) solution's iterates —
    the warm-start keep/revert gate shared by the consensus controllers
    (a non-finite iterate would poison every later solve; a merely
    tolerance-missed one is kept so retries accumulate progress)."""
    return (
        jnp.all(jnp.isfinite(sols.x), axis=-1)
        & jnp.all(jnp.isfinite(sols.y), axis=-1)
        & jnp.all(jnp.isfinite(sols.z), axis=-1)
    )


def equilibrate_rows(A, lb, ub, shift, n_box: int, soc_dims):
    """Row/block equilibration: rescale every constraint row to ~unit norm.

    Exact — the feasible set is unchanged: a box row scaled by s > 0 keeps
    the same halfspace/interval (lb, ub scale with it), and an SOC block
    scaled by ONE positive scalar maps the cone onto itself (t >= ||v|| is
    positively homogeneous), with the translated-cone shift scaling along.
    What changes is ADMM conditioning: with a uniform per-row penalty, a
    10-100x row-norm disparity (e.g. inertia-inverse-bearing rotation
    dynamics rows against O(0.1) translation rows — the RP QP family)
    measurably costs 5-15x in iterations to tolerance.

    Returns ``(A', lb', ub', shift', scales (m,))``. The scale is the
    CONTINUOUS ``1 / max(norm, 1)`` — it only ever scales DOWN:
    normalizing the over-weighted rows (inertia-inverse-bearing dynamics,
    norms 5-50) is where the measured conditioning win comes from, while
    UP-scaling sub-unit rows would both (a) jump discontinuously for
    state-dependent rows passing through zero between control steps,
    corrupting cross-step warm duals that live in the scaled row space,
    and (b) tighten the solver's absolute tolerance on near-vacuous rows
    by the scale factor — measured: a tiny hover-state CBF row boosted
    ~300x made its agents chronically miss solver_tol and rail the
    consensus loop. Callers that prebuild :func:`kkt_operator` must build
    it from the SCALED matrix (equilibrate at QP-build time, before the
    operator)."""
    m = A.shape[0]
    norms = jnp.linalg.norm(A, axis=-1)
    s = 1.0 / jnp.maximum(norms[:n_box], 1.0)
    scales = [s]
    off = n_box
    for dsoc in soc_dims:
        blk = jnp.max(norms[off:off + dsoc])
        sb = 1.0 / jnp.maximum(blk, 1.0)
        scales.append(jnp.full((dsoc,), sb, A.dtype))
        off += dsoc
    scales = jnp.concatenate(scales)
    A_s = A * scales[:, None]
    lb_s = lb * scales[:n_box]
    ub_s = ub * scales[:n_box]
    shift_s = None if shift is None else shift * scales
    return A_s, lb_s, ub_s, shift_s, scales


def make_rho_vec(m: int, n_box: int, lb, ub, rho: float, dtype=jnp.float32):
    """Per-row ADMM penalty: equality rows (lb == ub) get ``rho * EQ_RHO_SCALE``."""
    rho_vec = jnp.full((m,), rho, dtype)
    if n_box:
        is_eq = (ub - lb) < 1e-9
        rho_vec = rho_vec.at[:n_box].set(jnp.where(is_eq, rho * EQ_RHO_SCALE, rho))
    return rho_vec


def kkt_operator(P, A, rho_vec, sigma: float = 1e-6) -> KKTOp:
    """Invert the ADMM KKT matrix once for reuse across many ``solve_socp``
    calls with identical (P, A) (pass the result as ``op=``). Batched: all args
    may carry leading axes (``jnp.linalg.inv`` batches natively). The fused
    iteration operator ``K2`` is prebuilt here (see :class:`KKTOp`)."""
    nv = P.shape[-1]
    AT = jnp.swapaxes(A, -1, -2)
    M = P + sigma * jnp.eye(nv, dtype=P.dtype) + (AT * rho_vec[..., None, :]) @ A
    Minv = jnp.linalg.inv(M)
    Minv = 0.5 * (Minv + jnp.swapaxes(Minv, -1, -2))  # M is symmetric.
    MinvAT = Minv @ AT
    K = jnp.concatenate([sigma * Minv, MinvAT], axis=-1)  # (.., nv, nv+m)
    K2 = jnp.concatenate([K, A @ K], axis=-2)  # (.., nv+m, nv+m)
    # sigma broadcast to the batch shape so a natively-batched operator stays
    # a uniform pytree (every leaf with the same leading axes) for vmap.
    return KKTOp(
        Minv=Minv, MinvAT=MinvAT,
        sigma=jnp.broadcast_to(jnp.asarray(sigma, P.dtype), P.shape[:-2]),
        K2=K2,
    )


def kkt_residuals(P, q, A, lb, ub, n_box, soc_dims, sol: SOCPSolution, shift=None):
    """Standalone KKT check used by tests: stationarity, primal feasibility
    (distance of A x to the cone), and complementary slackness proxy <y, Ax - z>."""
    x, y = sol.x, sol.y
    Ax = A @ x
    proj = _project_cone(Ax, lb, ub, n_box, soc_dims, shift)
    prim = jnp.max(jnp.abs(Ax - proj))
    stat = jnp.max(jnp.abs(P @ x + q + A.T @ y))
    comp = jnp.abs(jnp.dot(y, Ax - proj))
    return stat, prim, comp
