"""Fused ADMM inner-loop Pallas TPU kernel (SURVEY.md §2.9's "Pallas
kernels" native tier).

Why this kernel exists: one ADMM inner iteration is a per-lane matvec with
the fused operator ``K2 ((nv+m)^2)`` plus a cone projection (ops/socp.py
``step``). Under ``lax.scan`` XLA re-streams every lane's K2 from HBM on
every iteration — for the headline C-ADMM batch (2048 lanes x 31^2 f32
operators, ~8 MB) that is ~8 MB x inner_iters x consensus_iters of pure
re-read traffic per control step, on a workload whose roofline shows it is
bandwidth/latency-bound (AI ~ 0.04 F/B, BASELINE.md round 3). This kernel
runs the whole fixed-iteration chunk with K2 resident in VMEM: each lane's
operator is read from HBM exactly once per chunk.

Layout: batch lanes on the LAST (lane) axis. All arrays arrive transposed
to ``(rows, B)`` / ``(d, d, B)``; the grid tiles B in ``LANE_TILE`` chunks,
so one grid cell holds ``(d, d, LANE_TILE)`` of K2 in VMEM (~0.5 MB at
d = 31) and loops over iterations on the VPU. The per-iteration math is a
transcription of ``ops/socp.py``'s ``step`` (same order of operations, same
``y / rho`` division) so the kernel and the scan path agree to f32
rounding.

Batch capture: ``jax.vmap`` of a ``pallas_call`` lifts the mapped axis to a
sequential grid dimension — one TensorCore grid cell per lane, which is
orders of magnitude too slow. Instead :mod:`ops.socp` wraps this kernel in
a recursive ``jax.custom_batching.custom_vmap`` pair that FOLDS every
enclosing vmap axis (agents, Monte-Carlo scenarios) into the kernel's
explicit lane axis, so the nested ``vmap(vmap(solve))`` the controllers
build becomes a single wide kernel invocation.

Reference provenance: the loop body this kernel fuses implements the same
per-agent conic solves the reference does sequentially through
cvxpy/Clarabel inside its consensus iterations (reference
control/rqp_cadmm.py:644-648); the fusion itself has no reference
counterpart — it is the TPU-native replacement for Clarabel's role in the
hot loop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE_TILE = 128
SUBLANE_TILE = 8  # f32 sublane tile; see ops/socp.py's padded-operator tier.
# Above this operator edge the per-lane K2 tile no longer fits VMEM
# residency (block bytes = 4 d^2 LANE_TILE, double-buffered by the pipeline;
# d = 450 for centralized n = 64 would need ~100 MB): callers fall back to
# scan. Recomputed for the PADDED operator tier (ops/socp.py pad_qp rounds
# every edge to SUBLANE_TILE, so the hot dims are now d = 48 for the
# reduced C-ADMM QPs and d = 56 for DD at the default 10 env-CBF rows, and
# every block is exact-tile (d % 8 == 0 sublanes x LANE_TILE lanes) —
# no Mosaic-side row padding): the budget is ~14 MB of the ~16 MB VMEM for
# the double-buffered K2 blocks, 2 x 4 d^2 x 128 <= 14 MB -> d <= 116,
# rounded DOWN to the sublane tile.
MAX_FUSED_DIM = 112


def _admm_chunk_kernel(
    K2_ref, w2_ref, rho_ref, lb_ref, ub_ref, shift_ref,
    x0_ref, y0_ref, z0_ref,
    xo_ref, yo_ref, zo_ref,
    *, nv: int, n_box: int, soc_dims: tuple, iters: int, alpha: float,
):
    """One grid cell: ``iters`` ADMM iterations over a LANE_TILE-wide slab.

    Shapes (B = LANE_TILE): K2 (d, d, B), w2 (d, B), rho/lb-ub-like rows
    (m or n_box, B), x (nv, B), y/z (m, B), with d = nv + m.
    """
    d = K2_ref.shape[0]
    m = rho_ref.shape[0]
    assert d == nv + m
    K2 = K2_ref[...]
    w2 = w2_ref[...]
    rho = rho_ref[...]
    lb = lb_ref[...]
    ub = ub_ref[...]
    shift = shift_ref[...]

    def project(zin):
        """Translated-cone projection, transcribing socp._project_cone /
        project_soc with rows-first layout."""
        zs = zin + shift
        parts = [jnp.clip(zs[:n_box], lb, ub)]
        off = n_box
        for dsoc in soc_dims:
            t = zs[off:off + 1]              # (1, B)
            v = zs[off + 1:off + dsoc]       # (dsoc-1, B)
            nrm = jnp.sqrt(jnp.sum(v * v, axis=0, keepdims=True))
            inside = nrm <= t
            polar = nrm <= -t
            s = 0.5 * (t + nrm)
            scale = jnp.where(nrm > 0, s / jnp.where(nrm > 0, nrm, 1.0), 0.0)
            parts.append(jnp.where(inside, t, jnp.where(polar, 0.0, s)))
            parts.append(jnp.where(inside, v, jnp.where(polar, 0.0, scale * v)))
            off += dsoc
        return jnp.concatenate(parts, axis=0) - shift

    def body(_, carry):
        x, y, z = carry
        u = jnp.concatenate([x, rho * z - y], axis=0)          # (d, B)
        # Per-lane matvec as a broadcast-multiply + sublane reduction: lanes
        # stay on the 128-wide axis, so the VPU sees full-width vregs.
        v = jnp.sum(K2 * u[None, :, :], axis=1) - w2           # (d, B)
        x_new = v[:nv]
        Ax = v[nv:]
        Ax_rel = alpha * Ax + (1.0 - alpha) * z
        z_new = project(Ax_rel + y / rho)
        y_new = y + rho * (Ax_rel - z_new)
        return (x_new, y_new, z_new)

    x, y, z = lax.fori_loop(
        0, iters, body, (x0_ref[...], y0_ref[...], z0_ref[...]),
        unroll=False,
    )
    xo_ref[...] = x
    yo_ref[...] = y
    zo_ref[...] = z


def _pad_lanes(a, B_pad, fill=0.0):
    B = a.shape[-1]
    if B == B_pad:
        return a
    pad = [(0, 0)] * (a.ndim - 1) + [(0, B_pad - B)]
    return jnp.pad(a, pad, constant_values=fill)


@functools.partial(
    jax.jit,
    static_argnames=("nv", "n_box", "soc_dims", "iters", "alpha", "interpret"),
)
def admm_chunk_lanes(
    x, y, z, K2, w2, rho, lb, ub, shift,
    *, nv: int, n_box: int, soc_dims: tuple, iters: int, alpha: float,
    interpret: bool = False,
):
    """Run the fused chunk over a LEADING batch axis B (lane layout handled
    here): args are batch-first ``(B, rows...)`` as produced by vmap folding;
    returns ``(x, y, z)`` batch-first.

    Padded lanes (B rounded up to LANE_TILE) run the iteration on zero
    operators with rho = 1 — every intermediate stays finite — and are
    sliced off before returning.

    Tile alignment: the lane axis is padded to LANE_TILE here, so with
    operators from the padded tier (ops/socp.py pad_qp: every row dim a
    SUBLANE_TILE multiple) each block spec below is EXACT-tile — (8k, 128)
    f32 blocks with no Mosaic-side padding. Sub-tile row dims from legacy
    unpadded callers still lower correctly; they just pay Mosaic's internal
    padding.
    """
    B = x.shape[0]
    m = rho.shape[-1]
    d = nv + m
    B_pad = max(LANE_TILE, ((B + LANE_TILE - 1) // LANE_TILE) * LANE_TILE)

    # Transpose to lanes-last and pad. (For the consensus controllers K2/w2
    # are loop-invariant across outer iterations; XLA hoists these
    # transposes out of the surrounding while_loop when it can.)
    K2T = _pad_lanes(jnp.moveaxis(K2, 0, -1), B_pad)           # (d, d, Bp)
    w2T = _pad_lanes(jnp.moveaxis(w2, 0, -1), B_pad)           # (d, Bp)
    rhoT = _pad_lanes(jnp.moveaxis(rho, 0, -1), B_pad, 1.0)    # (m, Bp)
    lbT = _pad_lanes(jnp.moveaxis(lb, 0, -1), B_pad)
    ubT = _pad_lanes(jnp.moveaxis(ub, 0, -1), B_pad)
    shiftT = _pad_lanes(jnp.moveaxis(shift, 0, -1), B_pad)
    xT = _pad_lanes(jnp.moveaxis(x, 0, -1), B_pad)
    yT = _pad_lanes(jnp.moveaxis(y, 0, -1), B_pad)
    zT = _pad_lanes(jnp.moveaxis(z, 0, -1), B_pad)

    grid = (B_pad // LANE_TILE,)

    def spec(rows):
        # rows may be a tuple (leading dims) — block covers full rows, one
        # LANE_TILE slab of lanes.
        shape = rows + (LANE_TILE,)
        nlead = len(rows)
        return pl.BlockSpec(
            shape, lambda i: (0,) * nlead + (i,), memory_space=pltpu.VMEM
        )

    kernel = functools.partial(
        _admm_chunk_kernel,
        nv=nv, n_box=n_box, soc_dims=tuple(soc_dims), iters=iters,
        alpha=alpha,
    )
    dtype = x.dtype
    xo, yo, zo = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            spec((d, d)), spec((d,)), spec((m,)), spec((n_box,)),
            spec((n_box,)), spec((m,)), spec((nv,)), spec((m,)), spec((m,)),
        ],
        out_specs=[spec((nv,)), spec((m,)), spec((m,))],
        out_shape=[
            jax.ShapeDtypeStruct((nv, B_pad), dtype),
            jax.ShapeDtypeStruct((m, B_pad), dtype),
            jax.ShapeDtypeStruct((m, B_pad), dtype),
        ],
        interpret=interpret,
    )(K2T, w2T, rhoT, lbT, ubT, shiftT, xT, yT, zT)

    unT = lambda a: jnp.moveaxis(a, -1, 0)[:B]
    return unT(xo), unT(yo), unT(zo)
