"""Fused ADMM inner-loop Pallas TPU kernel (SURVEY.md §2.9's "Pallas
kernels" native tier).

Why this kernel exists: one ADMM inner iteration is a per-lane matvec with
the fused operator ``K2 ((nv+m)^2)`` plus a cone projection (ops/socp.py
``step``). Under ``lax.scan`` XLA re-streams every lane's K2 from HBM on
every iteration — for the headline C-ADMM batch (2048 lanes x 31^2 f32
operators, ~8 MB) that is ~8 MB x inner_iters x consensus_iters of pure
re-read traffic per control step, on a workload whose roofline shows it is
bandwidth/latency-bound (AI ~ 0.04 F/B, BASELINE.md round 3). This kernel
runs the whole fixed-iteration chunk with K2 resident in VMEM: each lane's
operator is read from HBM exactly once per chunk.

Layout: batch lanes on the LAST (lane) axis. All arrays arrive transposed
to ``(rows, B)`` / ``(d, d, B)``; the grid tiles B in ``LANE_TILE`` chunks,
so one grid cell holds ``(d, d, LANE_TILE)`` of K2 in VMEM (~0.5 MB at
d = 31) and loops over iterations on the VPU. The per-iteration math is a
transcription of ``ops/socp.py``'s ``step`` (same order of operations, same
``y / rho`` division) so the kernel and the scan path agree to f32
rounding.

Batch capture: ``jax.vmap`` of a ``pallas_call`` lifts the mapped axis to a
sequential grid dimension — one TensorCore grid cell per lane, which is
orders of magnitude too slow. Instead :mod:`ops.socp` wraps this kernel in
a recursive ``jax.custom_batching.custom_vmap`` pair that FOLDS every
enclosing vmap axis (agents, Monte-Carlo scenarios) into the kernel's
explicit lane axis, so the nested ``vmap(vmap(solve))`` the controllers
build becomes a single wide kernel invocation.

Reference provenance: the loop body this kernel fuses implements the same
per-agent conic solves the reference does sequentially through
cvxpy/Clarabel inside its consensus iterations (reference
control/rqp_cadmm.py:644-648); the fusion itself has no reference
counterpart — it is the TPU-native replacement for Clarabel's role in the
hot loop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE_TILE = 128
SUBLANE_TILE = 8  # f32 sublane tile; see ops/socp.py's padded-operator tier.

# VMEM residency budget the fused kernels size themselves against. 16 MB is
# the per-core VMEM of the current TPU generations (v4/v5e/v5p all ship
# 16 MB cores); ~2 MB is held back for Mosaic's own scratch, semaphores,
# and the non-operator vectors, leaving 14 MB for the double-buffered
# operator blocks the grid pipeline keeps in flight. Both fused kernels'
# size guards (:data:`MAX_FUSED_DIM` for the chunk kernel,
# :func:`fused_solve_fits` for the whole-solve kernel) are DERIVED from
# this bound + the tile math below — change the budget here, not the
# guards (they were hand-recomputed once per layout change before this,
# 96 -> 112 at the padded-operator tier, and drifted).
VMEM_BYTES = 16 * 2**20
VMEM_BUDGET_BYTES = 14 * 2**20


def _max_dim_under(bytes_per_lane, lanes: int = LANE_TILE,
                   budget: int = VMEM_BUDGET_BYTES) -> int:
    """Largest operator edge ``d`` (a SUBLANE_TILE multiple — the padded
    tier guarantees callers' edges are) whose per-grid-cell residency,
    DOUBLE-buffered by the pallas pipeline (the next cell's blocks prefetch
    while the current cell computes), stays under ``budget``:

        2 x bytes_per_lane(d) x lanes <= budget.
    """
    d = SUBLANE_TILE
    while 2 * bytes_per_lane(d + SUBLANE_TILE) * lanes <= budget:
        d += SUBLANE_TILE
    return d


def chunk_kernel_bytes_per_lane(d: int) -> int:
    """Per-lane VMEM bytes of the chunk kernel's dominant resident: the
    (d, d) f32 K2 operator (the O(d) vectors ride inside the budget's
    2 MB holdback)."""
    return 4 * d * d


# Above this operator edge the per-lane K2 tile no longer fits VMEM
# residency (d = 450 for centralized n = 64 would need ~100 MB): callers
# fall back to scan. Derived from the budget above — with the PADDED
# operator tier (ops/socp.py pad_qp rounds every edge to SUBLANE_TILE, so
# the hot dims are d = 48 for the reduced C-ADMM QPs and d = 56 for DD at
# the default 10 env-CBF rows, every block exact-tile) the derivation
# gives 2 x 4 d^2 x 128 <= 14 MB -> d <= 119, floored to the sublane
# tile = 112, matching the value hand-recomputed at the padded tier
# (tests/test_fused_solve.py pins the boundary).
MAX_FUSED_DIM = _max_dim_under(chunk_kernel_bytes_per_lane)

# Folded-batch tile of the whole-solve kernel (one grid cell = this many
# lanes of the agent x scenario batch, batch-FIRST blocks — see
# fused_solve_lanes).
SOLVE_BATCH_TILE = LANE_TILE


def fused_solve_bytes_per_lane(nv: int, m: int, n_box: int) -> int:
    """Per-lane f32 VMEM bytes of the whole-solve kernel's residents: the
    iterated K2 ((d, d)) plus the qp-build/residual operators fused in —
    Minv and P ((nv, nv) each), A ((m, nv)) — and the per-lane vectors
    (q, rho, bounds, shift, the (x, y, z) carry and its output twin, the
    2-wide residual row)."""
    d = nv + m
    mats = d * d + 2 * nv * nv + m * nv
    vecs = nv + m + 2 * n_box + m + (nv + 2 * m)
    outs = (nv + 2 * m) + 2
    return 4 * (mats + vecs + outs)


def fused_solve_fits(nv: int, m: int, n_box: int | None = None) -> bool:
    """Whether one (nv, m) solve's operators fit the whole-solve kernel's
    double-buffered VMEM residency at :data:`SOLVE_BATCH_TILE` lanes per
    grid cell (the :data:`MAX_FUSED_DIM` criterion, recomputed for this
    kernel's larger resident set). Callers above the bound fall back to
    scan (ops/socp.py applies the guard at trace time)."""
    n_box = m if n_box is None else n_box
    return (2 * fused_solve_bytes_per_lane(nv, m, n_box) * SOLVE_BATCH_TILE
            <= VMEM_BUDGET_BYTES)


def _admm_chunk_kernel(
    K2_ref, w2_ref, rho_ref, lb_ref, ub_ref, shift_ref,
    x0_ref, y0_ref, z0_ref,
    xo_ref, yo_ref, zo_ref,
    *, nv: int, n_box: int, soc_dims: tuple, iters: int, alpha: float,
):
    """One grid cell: ``iters`` ADMM iterations over a LANE_TILE-wide slab.

    Shapes (B = LANE_TILE): K2 (d, d, B), w2 (d, B), rho/lb-ub-like rows
    (m or n_box, B), x (nv, B), y/z (m, B), with d = nv + m.
    """
    d = K2_ref.shape[0]
    m = rho_ref.shape[0]
    assert d == nv + m
    K2 = K2_ref[...]
    w2 = w2_ref[...]
    rho = rho_ref[...]
    lb = lb_ref[...]
    ub = ub_ref[...]
    shift = shift_ref[...]

    def project(zin):
        """Translated-cone projection, transcribing socp._project_cone /
        project_soc with rows-first layout."""
        zs = zin + shift
        parts = [jnp.clip(zs[:n_box], lb, ub)]
        off = n_box
        for dsoc in soc_dims:
            t = zs[off:off + 1]              # (1, B)
            v = zs[off + 1:off + dsoc]       # (dsoc-1, B)
            nrm = jnp.sqrt(jnp.sum(v * v, axis=0, keepdims=True))
            inside = nrm <= t
            polar = nrm <= -t
            s = 0.5 * (t + nrm)
            scale = jnp.where(nrm > 0, s / jnp.where(nrm > 0, nrm, 1.0), 0.0)
            parts.append(jnp.where(inside, t, jnp.where(polar, 0.0, s)))
            parts.append(jnp.where(inside, v, jnp.where(polar, 0.0, scale * v)))
            off += dsoc
        return jnp.concatenate(parts, axis=0) - shift

    def body(_, carry):
        x, y, z = carry
        u = jnp.concatenate([x, rho * z - y], axis=0)          # (d, B)
        # Per-lane matvec as a broadcast-multiply + sublane reduction: lanes
        # stay on the 128-wide axis, so the VPU sees full-width vregs.
        v = jnp.sum(K2 * u[None, :, :], axis=1) - w2           # (d, B)
        x_new = v[:nv]
        Ax = v[nv:]
        Ax_rel = alpha * Ax + (1.0 - alpha) * z
        z_new = project(Ax_rel + y / rho)
        y_new = y + rho * (Ax_rel - z_new)
        return (x_new, y_new, z_new)

    x, y, z = lax.fori_loop(
        0, iters, body, (x0_ref[...], y0_ref[...], z0_ref[...]),
        unroll=False,
    )
    xo_ref[...] = x
    yo_ref[...] = y
    zo_ref[...] = z


def _pad_lanes(a, B_pad, fill=0.0):
    B = a.shape[-1]
    if B == B_pad:
        return a
    pad = [(0, 0)] * (a.ndim - 1) + [(0, B_pad - B)]
    return jnp.pad(a, pad, constant_values=fill)


@functools.partial(
    jax.jit,
    static_argnames=("nv", "n_box", "soc_dims", "iters", "alpha", "interpret"),
)
def admm_chunk_lanes(
    x, y, z, K2, w2, rho, lb, ub, shift,
    *, nv: int, n_box: int, soc_dims: tuple, iters: int, alpha: float,
    interpret: bool = False,
):
    """Run the fused chunk over a LEADING batch axis B (lane layout handled
    here): args are batch-first ``(B, rows...)`` as produced by vmap folding;
    returns ``(x, y, z)`` batch-first.

    Padded lanes (B rounded up to LANE_TILE) run the iteration on zero
    operators with rho = 1 — every intermediate stays finite — and are
    sliced off before returning.

    Tile alignment: the lane axis is padded to LANE_TILE here, so with
    operators from the padded tier (ops/socp.py pad_qp: every row dim a
    SUBLANE_TILE multiple) each block spec below is EXACT-tile — (8k, 128)
    f32 blocks with no Mosaic-side padding. Sub-tile row dims from legacy
    unpadded callers still lower correctly; they just pay Mosaic's internal
    padding.
    """
    B = x.shape[0]
    m = rho.shape[-1]
    d = nv + m
    B_pad = max(LANE_TILE, ((B + LANE_TILE - 1) // LANE_TILE) * LANE_TILE)

    # Transpose to lanes-last and pad. (For the consensus controllers K2/w2
    # are loop-invariant across outer iterations; XLA hoists these
    # transposes out of the surrounding while_loop when it can.)
    K2T = _pad_lanes(jnp.moveaxis(K2, 0, -1), B_pad)           # (d, d, Bp)
    w2T = _pad_lanes(jnp.moveaxis(w2, 0, -1), B_pad)           # (d, Bp)
    rhoT = _pad_lanes(jnp.moveaxis(rho, 0, -1), B_pad, 1.0)    # (m, Bp)
    lbT = _pad_lanes(jnp.moveaxis(lb, 0, -1), B_pad)
    ubT = _pad_lanes(jnp.moveaxis(ub, 0, -1), B_pad)
    shiftT = _pad_lanes(jnp.moveaxis(shift, 0, -1), B_pad)
    xT = _pad_lanes(jnp.moveaxis(x, 0, -1), B_pad)
    yT = _pad_lanes(jnp.moveaxis(y, 0, -1), B_pad)
    zT = _pad_lanes(jnp.moveaxis(z, 0, -1), B_pad)

    grid = (B_pad // LANE_TILE,)

    def spec(rows):
        # rows may be a tuple (leading dims) — block covers full rows, one
        # LANE_TILE slab of lanes.
        shape = rows + (LANE_TILE,)
        nlead = len(rows)
        return pl.BlockSpec(
            shape, lambda i: (0,) * nlead + (i,), memory_space=pltpu.VMEM
        )

    kernel = functools.partial(
        _admm_chunk_kernel,
        nv=nv, n_box=n_box, soc_dims=tuple(soc_dims), iters=iters,
        alpha=alpha,
    )
    dtype = x.dtype
    xo, yo, zo = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            spec((d, d)), spec((d,)), spec((m,)), spec((n_box,)),
            spec((n_box,)), spec((m,)), spec((nv,)), spec((m,)), spec((m,)),
        ],
        out_specs=[spec((nv,)), spec((m,)), spec((m,))],
        out_shape=[
            jax.ShapeDtypeStruct((nv, B_pad), dtype),
            jax.ShapeDtypeStruct((m, B_pad), dtype),
            jax.ShapeDtypeStruct((m, B_pad), dtype),
        ],
        interpret=interpret,
    )(K2T, w2T, rhoT, lbT, ubT, shiftT, xT, yT, zT)

    unT = lambda a: jnp.moveaxis(a, -1, 0)[:B]
    return unT(xo), unT(yo), unT(zo)


# ----------------------------------------------------------------------
# Whole-solve mega-kernel: qp-build tail + fused K2 iteration + cone
# projection + residual reduction in ONE pallas_call (ops/socp.py
# fused="kernel" / "kernel_interpret").
# ----------------------------------------------------------------------

def _fused_solve_kernel(
    *refs,
    nv: int, n_box: int, soc_dims: tuple, iters: int, alpha: float,
    has_shift: bool, exact_dot: bool,
    check_every: int = 0, tol: float = 0.0, has_active: bool = False,
):
    """One grid cell: a SOLVE_BATCH_TILE-wide slab of complete ADMM solves.

    Blocks are batch-FIRST (``(T, rows...)`` with T = SOLVE_BATCH_TILE).
    Two realizations of the per-lane matvecs, selected by the static
    ``exact_dot``:

    - ``exact_dot=True`` (the interpret twin): the body is ``jax.vmap`` of
      the scan path's own per-instance functions (``socp._admm_step``, the
      w2 build, the residual inf-norms), so the traced per-lane ops —
      dot_generals with a leading batch dim, elementwise projections — are
      IDENTICAL to what the controllers' nested vmaps stage around
      ``lax.scan``. Interpret mode is therefore bitwise-equal to the scan
      path per iteration BY CONSTRUCTION (asserted in
      tests/test_fused_solve.py), not by tolerance. Mosaic cannot lower
      this form ("Only 2D tensors supported in dot" at the batched
      dot_general — measured via jax.export on this image), so it is the
      interpreter-only twin.
    - ``exact_dot=False`` (the compiled form): the same math with every
      per-lane matvec transcribed to a broadcast-multiply + last-axis
      reduction (``sum(M * v[:, None, :], -1)``) — the chunk kernel's VPU
      idiom, which jax.export AOT-lowers cleanly for the TPU target
      (measured on this image; the entry carries NO lowering waiver).
      Same order of operations per lane up to the reduction order of the
      matvec accumulations, so it agrees with the reference to f32
      rounding — the numerics contract the chunk kernel already set; its
      numerics stay CPU-testable by running it under the interpreter
      (``fused_solve_lanes(..., interpret=True, exact_dot=False)``).

    On a real chip Mosaic maps the leading batch dim to the grid-cell-
    internal loop and the trailing (rows, cols) dims to (sublane, lane)
    tiles — the padded tier's d % 8 == 0 edges keep the sublane axis
    exact-tile. If the chip round shows the lanes-last layout scheduling
    better, it becomes a variant behind the same gate and the A/B cells
    arbitrate.

    What is resident per lane across ALL ``iters`` iterations (read from
    HBM exactly once per solve instead of once per iteration): K2
    ((d, d) — the iterated operator), Minv + A (the per-iteration
    qp-build tail ``w2 = [Minv q; A Minv q]`` runs on-chip), P + A again
    for the exit residuals. bf16 storage (fused_solve_lanes
    ``precision="bf16"``) halves the operator payload; the kernel upcasts
    to f32 before every contraction, so accumulation is always f32.

    **In-kernel early exit** (``check_every > 0 and tol > 0``): instead of
    one fixed ``fori_loop``, the kernel runs chunks of ``check_every``
    iterations under a ``lax.while_loop`` with a per-lane converged mask —
    converged lanes take explicit frozen (select) updates, the whole grid
    cell exits as soon as EVERY lane in it converges (the compiled
    ``scf.while`` form jax.export-lowers clean for the TPU target on this
    image, so the entry carries NO lowering waiver), and the per-lane
    effective iteration counts are written to an extra ``(T, 1)`` int32
    output. The mask logic transcribes solve_socp's tolerance-chunked
    scan loop per lane (the explicit-masked form that is value-identical
    to ``lax.while_loop``'s own vmap batching rule), so the interpret
    twin stays BITWISE equal to the scan path. ``has_active`` adds a
    ``(T, 1)`` f32 gate input (consensus-level adaptive effort,
    ops/socp.py ``active=``): a gated-off lane contributes 0 chunks —
    the 0-effective-iteration pass-through.
    """
    early = bool(check_every) and tol > 0.0
    (K2_ref, Minv_ref, A_ref, P_ref, q_ref, rho_ref, lb_ref, ub_ref,
     shift_ref, x0_ref, y0_ref, z0_ref) = refs[:12]
    k = 12
    act_ref = None
    if early and has_active:
        act_ref = refs[k]
        k += 1
    if early:
        xo_ref, yo_ref, zo_ref, res_ref, it_ref = refs[k:]
    else:
        xo_ref, yo_ref, zo_ref, res_ref = refs[k:]
    f32 = jnp.float32
    K2 = K2_ref[...].astype(f32)
    Minv = Minv_ref[...].astype(f32)
    A = A_ref[...].astype(f32)
    P = P_ref[...].astype(f32)
    q = q_ref[...]
    rho = rho_ref[...]
    lb = lb_ref[...]
    ub = ub_ref[...]
    shift = shift_ref[...] if has_shift else None

    from tpu_aerial_transport.ops import socp as socp_mod

    if exact_dot:
        # qp-build tail, fused: w2 = [Minv q ; A Minv q] — the same two
        # matvecs solve_socp's scan path runs in XLA once per solve call
        # (i.e. once per consensus iteration), vmapped over the lane slab.
        def build_w2(Minv_, A_, q_):
            wq = Minv_ @ q_
            return jnp.concatenate([wq, A_ @ wq])

        w2 = jax.vmap(build_w2)(Minv, A, q)

        step = functools.partial(
            socp_mod._admm_step, nv=nv, n_box=n_box,
            soc_dims=tuple(soc_dims), alpha=alpha,
        )
        if has_shift:
            vstep = jax.vmap(
                lambda c, K2_, w2_, rho_, lb_, ub_, s_:
                step(c, K2_, w2_, rho_, lb_, ub_, s_)
            )

            def body(_, carry):
                return vstep(carry, K2, w2, rho, lb, ub, shift)
        else:
            vstep = jax.vmap(
                lambda c, K2_, w2_, rho_, lb_, ub_:
                step(c, K2_, w2_, rho_, lb_, ub_, None)
            )

            def body(_, carry):
                return vstep(carry, K2, w2, rho, lb, ub)

        def res_pair(x, y, z):
            def res_one(A_, P_, q_, x_, y_, z_):
                prim = jnp.max(jnp.abs(A_ @ x_ - z_))
                dual = jnp.max(jnp.abs(P_ @ x_ + q_ + A_.T @ y_))
                return prim, dual

            return jax.vmap(res_one)(A, P, q, x, y, z)
    else:
        # Compiled transcription: per-lane matvec as broadcast-multiply +
        # last-axis reduction. The cone projection is batch-generic
        # (elementwise + last-axis concatenates), so the REAL
        # socp._project_cone runs here, not a copy.
        def mv(M, v):  # (T, r, c) x (T, c) -> (T, r)
            return jnp.sum(M * v[:, None, :], axis=-1)

        wq = mv(Minv, q)
        w2 = jnp.concatenate([wq, mv(A, wq)], axis=-1)

        def body(_, carry):
            x, y, z = carry
            u = jnp.concatenate([x, rho * z - y], axis=-1)
            v = mv(K2, u) - w2
            x_new, Ax = v[:, :nv], v[:, nv:]
            Ax_rel = alpha * Ax + (1 - alpha) * z
            z_new = socp_mod._project_cone(
                Ax_rel + y / rho, lb, ub, n_box, tuple(soc_dims), shift
            )
            y_new = y + rho * (Ax_rel - z_new)
            return (x_new, y_new, z_new)

        def res_pair(x, y, z):
            prim = jnp.max(jnp.abs(mv(A, x) - z), axis=-1)
            # A^T y per lane: reduce A's row axis against y.
            ATy = jnp.sum(A * y[:, :, None], axis=1)
            dual = jnp.max(jnp.abs(mv(P, x) + q + ATy), axis=-1)
            return prim, dual

    carry0 = (x0_ref[...], y0_ref[...], z0_ref[...])
    if not early:
        x, y, z = lax.fori_loop(0, iters, body, carry0, unroll=False)
    else:
        # Tolerance-chunked with per-lane freezing: the masked transcription
        # of solve_socp's explicit check_every/tol loop (value-identical per
        # lane to lax.while_loop's vmap batching rule — see the docstring).
        n_full, rem = divmod(iters, check_every)
        T = carry0[0].shape[0]
        if act_ref is not None:
            gate = act_ref[...][:, 0] > 0.0
        else:
            gate = jnp.ones((T,), bool)

        def above_tol(c):
            prim, dual = res_pair(*c)
            return (prim > tol) | (dual > tol)

        def chunk(c, n_it):
            return lax.fori_loop(0, n_it, body, c, unroll=False)

        n_chunks = jnp.zeros((T,), jnp.int32)
        carry = carry0
        if n_full:
            def loop_cond(s):
                return jnp.any(s[2])

            def loop_body(s):
                c, i, act = s
                new = chunk(c, check_every)
                m = act[:, None]
                c = tuple(jnp.where(m, a, b) for a, b in zip(new, c))
                i = i + act.astype(jnp.int32)
                act = act & (i < n_full) & above_tol(c)
                return (c, i, act)

            carry, n_chunks, _ = lax.while_loop(
                loop_cond, loop_body, (carry, n_chunks, gate & above_tol(carry))
            )
        eff = n_chunks * check_every
        if rem:
            # The remainder chunk mirrors the scan path's vmapped lax.cond
            # (= select over both branches) — keeping the total at exactly
            # ``iters`` for never-converging lanes.
            need = gate & above_tol(carry)
            new = chunk(carry, rem)
            m = need[:, None]
            carry = tuple(jnp.where(m, a, b) for a, b in zip(new, carry))
            eff = eff + jnp.where(need, rem, 0)
        x, y, z = carry
        it_ref[...] = eff[:, None]
    xo_ref[...] = x
    yo_ref[...] = y
    zo_ref[...] = z

    # Residual reduction (solve_socp's exit ``residuals`` — max is
    # order-exact under any schedule).
    prim, dual = res_pair(x, y, z)
    res_ref[...] = jnp.stack([prim, dual], axis=-1)


def _pad_batch(a, B_pad, fill=0.0):
    B = a.shape[0]
    if B == B_pad:
        return a
    pad = [(0, B_pad - B)] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, pad, constant_values=fill)


@functools.partial(
    jax.jit,
    static_argnames=("nv", "n_box", "soc_dims", "iters", "alpha",
                     "precision", "interpret", "exact_dot", "check_every",
                     "tol"),
)
def fused_solve_lanes(
    x, y, z, K2, Minv, A, P, q, rho, lb, ub, shift=None, active=None,
    *, nv: int, n_box: int, soc_dims: tuple, iters: int, alpha: float,
    precision: str = "f32", interpret: bool = False,
    exact_dot: bool | None = None, check_every: int = 0, tol: float = 0.0,
):
    """Run whole batched solves through :func:`_fused_solve_kernel`: args
    are batch-first ``(B, rows...)`` as produced by the vmap folding in
    ops/socp.py's ``_fused_solve_runner``; returns
    ``(x, y, z, prim_res, dual_res)`` batch-first. ``exact_dot`` defaults
    to ``interpret`` — the bitwise vmapped-dot body under the interpreter,
    the Mosaic-lowerable broadcast-reduce body when compiled (see the
    kernel docstring); pass it explicitly to test the compiled form's
    numerics under the interpreter.

    ``check_every``/``tol`` (both nonzero) select the in-kernel early-exit
    form: per-lane converged masks checked every ``check_every``
    iterations INSIDE the one pallas_call — converged lanes freeze via
    explicit selects, a grid cell's loop exits when all its lanes
    converge — and the return gains a sixth element ``eff_iters`` ((B,)
    int32 per-lane effective iteration counts). ``active`` ((B,) bool;
    early-exit form only) gates lanes off from the start — a gated lane
    is the 0-effective-iteration pass-through the consensus-level
    adaptive-effort tier rides (ops/socp.py ``solve_socp(active=)``).

    ``precision="bf16"`` stores the operator matrices (K2, Minv, A, P) in
    bfloat16 — halving the HBM->VMEM operator payload, the dominant
    traffic of the bandwidth-bound inner loop — while every contraction
    accumulates in f32 (the kernel upcasts before use). Vectors
    (q, rho, bounds, carries) stay f32: they are O(d) against the O(d^2)
    operators, and the carry is the precision-critical fixed-point state.

    Padded lanes (B rounded up to SOLVE_BATCH_TILE) run on zero operators
    with rho = 1 — every intermediate stays finite — and are sliced off.
    """
    B = x.shape[0]
    m = rho.shape[-1]
    d = nv + m
    has_shift = shift is not None
    early = bool(check_every) and tol > 0.0
    has_active = early and active is not None
    if active is not None and not early:
        raise ValueError(
            "active= gating needs the early-exit form (check_every > 0 "
            "and tol > 0): a fixed-iteration kernel cannot express a "
            "0-effective-iteration pass-through"
        )
    if exact_dot is None:
        exact_dot = interpret
    B_pad = max(
        SOLVE_BATCH_TILE,
        ((B + SOLVE_BATCH_TILE - 1) // SOLVE_BATCH_TILE) * SOLVE_BATCH_TILE,
    )
    if precision not in ("f32", "bf16"):
        raise ValueError(
            f"precision={precision!r}: expected 'f32' or 'bf16'"
        )
    dtype = x.dtype
    store = jnp.bfloat16 if precision == "bf16" else dtype

    K2p = _pad_batch(K2.astype(store), B_pad)
    Minvp = _pad_batch(Minv.astype(store), B_pad)
    Ap = _pad_batch(A.astype(store), B_pad)
    Pp = _pad_batch(P.astype(store), B_pad)
    qp_ = _pad_batch(q, B_pad)
    rhop = _pad_batch(rho, B_pad, 1.0)
    lbp = _pad_batch(lb, B_pad)
    ubp = _pad_batch(ub, B_pad)
    xp = _pad_batch(x, B_pad)
    yp = _pad_batch(y, B_pad)
    zp = _pad_batch(z, B_pad)
    inputs = [K2p, Minvp, Ap, Pp, qp_, rhop, lbp, ubp]
    if has_shift:
        inputs.append(_pad_batch(shift, B_pad))
    else:
        # Unread placeholder (has_shift is static): keeps the kernel's ref
        # list fixed-arity without staging a z + 0 add that could flip
        # signed zeros vs the scan path's shift=None branch.
        inputs.append(jnp.zeros((B_pad, m), dtype))
    inputs += [xp, yp, zp]
    if has_active:
        # (B, 1) f32 gate (2-D keeps Mosaic on well-trodden block shapes;
        # pad lanes gate OFF so they cannot hold a grid cell's loop open).
        inputs.append(_pad_batch(active.astype(dtype)[:, None], B_pad))

    grid = (B_pad // SOLVE_BATCH_TILE,)

    def spec(rows):
        shape = (SOLVE_BATCH_TILE,) + rows
        ntrail = len(rows)
        return pl.BlockSpec(
            shape, lambda i: (i,) + (0,) * ntrail, memory_space=pltpu.VMEM
        )

    kernel = functools.partial(
        _fused_solve_kernel,
        nv=nv, n_box=n_box, soc_dims=tuple(soc_dims), iters=iters,
        alpha=alpha, has_shift=has_shift, exact_dot=exact_dot,
        check_every=check_every if early else 0, tol=tol if early else 0.0,
        has_active=has_active,
    )
    in_specs = [
        spec((d, d)), spec((nv, nv)), spec((m, nv)), spec((nv, nv)),
        spec((nv,)), spec((m,)), spec((n_box,)), spec((n_box,)),
        spec((m,)), spec((nv,)), spec((m,)), spec((m,)),
    ]
    if has_active:
        in_specs.append(spec((1,)))
    out_specs = [spec((nv,)), spec((m,)), spec((m,)), spec((2,))]
    out_shape = [
        jax.ShapeDtypeStruct((B_pad, nv), dtype),
        jax.ShapeDtypeStruct((B_pad, m), dtype),
        jax.ShapeDtypeStruct((B_pad, m), dtype),
        jax.ShapeDtypeStruct((B_pad, 2), dtype),
    ]
    if early:
        out_specs.append(spec((1,)))
        out_shape.append(jax.ShapeDtypeStruct((B_pad, 1), jnp.int32))
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*inputs)
    if early:
        xo, yo, zo, res, eff = outs
        return (xo[:B], yo[:B], zo[:B], res[:B, 0], res[:B, 1],
                eff[:B, 0])
    xo, yo, zo, res = outs
    return xo[:B], yo[:B], zo[:B], res[:B, 0], res[:B, 1]
