"""SO(3) / Lie-group math core, pure JAX, batched over arbitrary leading axes.

TPU-native replacement for the tiny pinocchio + scipy.linalg API subset the reference
uses (see SURVEY.md §2.9): ``pin.skew`` -> :func:`hat`, ``pin.unSkew`` -> :func:`vee`,
``pin.skewSquare`` -> :func:`hat_square`, ``pin.exp3`` -> :func:`expm_so3`,
``scipy.linalg.polar`` -> :func:`polar_project` (Newton-Schulz, matmul-only, so it maps
onto the MXU instead of an SVD). Rotation constructions mirror
``utils/math_utils.py:16-60`` in the reference.

Everything is shape-polymorphic: matrix arguments use the trailing two axes, vector
arguments the trailing axis; any leading axes broadcast (so a single code path serves
per-agent vmap, Monte-Carlo scenario vmap, and shard_map shards).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "hat",
    "vee",
    "hat_square",
    "expm_so3",
    "log_so3",
    "polar_project",
    "polar_project_svd",
    "rotation_a_to_b",
    "rotation_from_z",
    "random_cone_vector",
]

_SMALL_ANGLE = 1e-6


def hat(v: jnp.ndarray) -> jnp.ndarray:
    """Skew-symmetric (hat) map: ``v (..., 3) -> (..., 3, 3)`` with hat(v) x = v x x."""
    x, y, z = v[..., 0], v[..., 1], v[..., 2]
    zero = jnp.zeros_like(x)
    rows = jnp.stack(
        [
            jnp.stack([zero, -z, y], axis=-1),
            jnp.stack([z, zero, -x], axis=-1),
            jnp.stack([-y, x, zero], axis=-1),
        ],
        axis=-2,
    )
    return rows


def vee(A: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`hat` for skew-symmetric ``A (..., 3, 3) -> (..., 3)``."""
    return jnp.stack([A[..., 2, 1], A[..., 0, 2], A[..., 1, 0]], axis=-1)


def hat_square(u: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """``hat(u) @ hat(v)`` in closed form: ``v u^T - (u . v) I`` (pin.skewSquare)."""
    uv = jnp.sum(u * v, axis=-1)[..., None, None]
    outer = v[..., :, None] * u[..., None, :]
    eye = jnp.eye(3, dtype=u.dtype)
    return outer - uv * eye


def expm_so3(w: jnp.ndarray) -> jnp.ndarray:
    """SO(3) exponential map (Rodrigues), ``w (..., 3) -> (..., 3, 3)``.

    Uses Taylor expansions of sin(t)/t and (1-cos(t))/t^2 below ``_SMALL_ANGLE`` so the
    function is smooth (and differentiable) through w = 0.
    """
    theta_sq = jnp.sum(w * w, axis=-1)
    safe = theta_sq > _SMALL_ANGLE**2
    # sqrt/div only ever see the safe branch's values, so gradients stay finite at 0.
    theta_sq_nz = jnp.where(safe, theta_sq, 1.0)
    theta_nz = jnp.sqrt(theta_sq_nz)
    a = jnp.where(safe, jnp.sin(theta_nz) / theta_nz, 1.0 - theta_sq / 6.0)
    b = jnp.where(safe, (1.0 - jnp.cos(theta_nz)) / theta_sq_nz, 0.5 - theta_sq / 24.0)
    W = hat(w)
    W2 = W @ W
    eye = jnp.eye(3, dtype=w.dtype)
    return eye + a[..., None, None] * W + b[..., None, None] * W2


def log_so3(R: jnp.ndarray) -> jnp.ndarray:
    """SO(3) logarithm, ``R (..., 3, 3) -> (..., 3)``; accurate away from angle pi."""
    trace = R[..., 0, 0] + R[..., 1, 1] + R[..., 2, 2]
    cos_theta = jnp.clip((trace - 1.0) / 2.0, -1.0, 1.0)
    theta = jnp.arccos(cos_theta)
    w = vee(R - jnp.swapaxes(R, -1, -2)) / 2.0
    sin_theta = jnp.sin(theta)
    safe = sin_theta > _SMALL_ANGLE
    scale = jnp.where(safe, theta / jnp.where(safe, sin_theta, 1.0), 1.0)
    return scale[..., None] * w


def polar_project(R: jnp.ndarray, iters: int = 8) -> jnp.ndarray:
    """Project ``R (..., 3, 3)`` onto SO(3) by Newton-Schulz iteration.

    Replaces ``scipy.linalg.polar`` (reference ``system/*.py project_R``) with a
    matmul-only iteration that XLA fuses and the MXU executes directly:
    ``X <- X (3 I - X^T X) / 2``. Quadratic convergence for singular values in
    (0, sqrt(3)); integrator drift keeps them within ~1e-3 of 1, so ``iters=8`` drives
    the orthogonality error to f32 machine precision with huge margin.
    """
    eye3 = 3.0 * jnp.eye(3, dtype=R.dtype)

    def body(_, X):
        return 0.5 * X @ (eye3 - jnp.swapaxes(X, -1, -2) @ X)

    return lax.fori_loop(0, iters, body, R)


def polar_project_svd(R: jnp.ndarray) -> jnp.ndarray:
    """SVD-based polar projection (oracle/reference path; slower on TPU)."""
    U, _, Vt = jnp.linalg.svd(R)
    return U @ Vt


def rotation_a_to_b(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Rotation mapping unit vector ``a`` to unit vector ``b`` (batched).

    Householder-pair identity ``2 u u^T / ||u||^2 - I`` with ``u = a + b``; the
    antipodal case ``b = -a`` falls back to ``u = a x e1`` then ``u = a x e2``
    (reference ``utils/math_utils.py:45-60``), made branchless with ``where``.
    """
    dtype = a.dtype
    e1 = jnp.array([1.0, 0.0, 0.0], dtype=dtype)
    e2 = jnp.array([0.0, 1.0, 0.0], dtype=dtype)
    u0 = a + b
    n0 = jnp.sum(u0 * u0, axis=-1, keepdims=True)
    u1 = jnp.cross(a, jnp.broadcast_to(e1, a.shape))
    n1 = jnp.sum(u1 * u1, axis=-1, keepdims=True)
    u2 = jnp.cross(a, jnp.broadcast_to(e2, a.shape))

    eps = jnp.asarray(1e-12, dtype)
    u = jnp.where(n0 > eps, u0, jnp.where(n1 > eps, u1, u2))
    normsq = jnp.sum(u * u, axis=-1)[..., None, None]
    outer = u[..., :, None] * u[..., None, :]
    return 2.0 * outer / normsq - jnp.eye(3, dtype=dtype)


def rotation_from_z(q: jnp.ndarray) -> jnp.ndarray:
    """Zero-yaw (ZYX) rotation with ``R e3 = q``, ``q (..., 3)`` unit, ``q_z > 0``.

    Batched replacement for ``utils/math_utils.py:16-42`` and the low-level
    controller's ``_rotation_from_unit_vector`` (``control/rqp_centralized.py:503``).
    """
    sin_x = -q[..., 1]
    cos_x = jnp.sqrt(jnp.maximum(q[..., 0] ** 2 + q[..., 2] ** 2, 1e-12))
    sin_y = q[..., 0] / cos_x
    cos_y = q[..., 2] / cos_x
    zero = jnp.zeros_like(cos_x)
    col0 = jnp.stack([cos_y, zero, -sin_y], axis=-1)
    col1 = jnp.stack([sin_x * sin_y, cos_x, cos_y * sin_x], axis=-1)
    return jnp.stack([col0, col1, q], axis=-1)


def random_cone_vector(key, theta: float, shape=()) -> jnp.ndarray:
    """Uniform random unit vectors within angle ``theta`` of +z (tan-disc sampling).

    PRNG-keyed, batched replacement for ``utils/math_utils.py:6-13``. ``theta`` must
    lie in (0, pi/2); beyond that the tan-disc construction is meaningless (the
    reference asserts theta < 89.99 deg at ``math_utils.py:8``).
    """
    if not 0.0 < float(theta) < 89.99 * jnp.pi / 180.0:
        raise ValueError(f"theta must be in (0, ~pi/2), got {theta}")
    k1, k2 = jax.random.split(key)
    R = jnp.tan(theta)
    r = R * jnp.sqrt(jax.random.uniform(k1, shape))
    phi = 2.0 * jnp.pi * jax.random.uniform(k2, shape)
    v = jnp.stack([r * jnp.cos(phi), r * jnp.sin(phi), jnp.ones_like(r)], axis=-1)
    return v / jnp.linalg.norm(v, axis=-1, keepdims=True)
