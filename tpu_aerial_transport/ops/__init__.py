"""Math/solver cores: batched SO(3)/Lie ops and the conic-QP (SOCP) solver."""

from tpu_aerial_transport.ops import lie, socp  # noqa: F401
