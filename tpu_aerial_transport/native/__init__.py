"""ctypes bindings for the native (C++) conic-QP solver.

The reference's native solver tier is Clarabel (Rust) reached through cvxpy;
this package's native tier is ``socp_solver.cpp`` — the same ADMM algorithm as
:mod:`tpu_aerial_transport.ops.socp`, dependency-free C++, built on demand with
the system compiler and bound via ctypes (no pybind11 in this image). It serves
as an independent f64 oracle for the JAX solver's tests and as a low-latency
host-side fallback for single instances.

Build: lazy, once per process tree — ``g++ -O3 -shared -fPIC`` into
``~/.cache/tpu_aerial_transport``. Use :func:`available` to probe.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path

import numpy as np

_SRC = Path(__file__).with_name("socp_solver.cpp")
# v2: generic-ISA build (no -march=native). The version suffix keys the cache
# on the compile flags, so stale ISA-specific binaries from v1 are not reused.
_LIB_NAME = "libtat_socp_v2.so"
_lib = None
_build_error: str | None = None


def _cache_dir() -> Path:
    base = os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache"))
    d = Path(base) / "tpu_aerial_transport"
    d.mkdir(parents=True, exist_ok=True)
    return d


def _build() -> Path:
    # No -march=native: the solver is tiny and latency-bound, and the cache is
    # keyed only on source mtime — an ISA-specific binary could SIGILL after a
    # host change (shared/NFS home) without ever being rebuilt.
    out = _cache_dir() / _LIB_NAME
    if out.exists() and out.stat().st_mtime >= _SRC.stat().st_mtime:
        return out
    cmd = ["g++", "-O3", "-shared", "-fPIC", str(_SRC), "-o", str(out)]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return out


def _load():
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    try:
        lib = ctypes.CDLL(str(_build()))
    except Exception as e:  # compiler missing, sandboxed fs, ...
        _build_error = str(e)
        return None
    d = ctypes.POINTER(ctypes.c_double)
    i32 = ctypes.POINTER(ctypes.c_int32)
    lib.socp_solve.restype = ctypes.c_int
    lib.socp_solve.argtypes = [
        d, d, d, d, d, d,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, i32, ctypes.c_int,
        ctypes.c_int, ctypes.c_double, ctypes.c_double, ctypes.c_double,
        d, d, d, d, d, d, d,
    ]
    lib.socp_solve_batch.restype = ctypes.c_int
    lib.socp_solve_batch.argtypes = [
        d, d, d, d, d, d,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, i32,
        ctypes.c_int,
        ctypes.c_int, ctypes.c_double, ctypes.c_double, ctypes.c_double,
        d, d, d, d,
    ]
    _lib = lib
    return _lib


def available() -> bool:
    """True if the native library built (or loads) on this host."""
    return _load() is not None


def _ptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def solve_socp_native(
    P, q, A, lb, ub, *, n_box: int, soc_dims=(), iters: int = 200,
    rho: float = 0.4, sigma: float = 1e-6, alpha: float = 1.6, shift=None,
    warm=None,
):
    """Solve one conic QP with the C++ solver (f64). Same problem layout and
    defaults as :func:`tpu_aerial_transport.ops.socp.solve_socp`. Returns
    ``(x, y, z, prim_res, dual_res)`` as numpy arrays/floats."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native solver unavailable: {_build_error}")
    P = np.ascontiguousarray(P, np.float64)
    q = np.ascontiguousarray(q, np.float64)
    A = np.ascontiguousarray(A, np.float64)
    lb = np.ascontiguousarray(lb, np.float64)
    ub = np.ascontiguousarray(ub, np.float64)
    m, nv = A.shape
    dims = np.ascontiguousarray(soc_dims, np.int32)
    assert m == n_box + int(dims.sum())
    shift_p = None
    if shift is not None:
        shift = np.ascontiguousarray(shift, np.float64)
        shift_p = _ptr(shift)
    x = np.zeros(nv)
    y = np.zeros(m)
    z = np.zeros(m)
    res = np.zeros(2)
    x0 = y0 = z0 = None
    if warm is not None:
        x0 = np.ascontiguousarray(warm[0], np.float64)
        y0 = np.ascontiguousarray(warm[1], np.float64)
        z0 = np.ascontiguousarray(warm[2], np.float64)
    rc = lib.socp_solve(
        _ptr(P), _ptr(q), _ptr(A), _ptr(lb), _ptr(ub), shift_p,
        nv, m, n_box,
        dims.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(dims),
        iters, rho, sigma, alpha,
        _ptr(x0) if x0 is not None else None,
        _ptr(y0) if y0 is not None else None,
        _ptr(z0) if z0 is not None else None,
        _ptr(x), _ptr(y), _ptr(z), _ptr(res),
    )
    if rc != 0:
        raise RuntimeError("native KKT factorization failed (P not PSD?)")
    return x, y, z, float(res[0]), float(res[1])


def solve_socp_native_batch(
    P, q, A, lb, ub, *, n_box: int, soc_dims=(), iters: int = 200,
    rho: float = 0.4, sigma: float = 1e-6, alpha: float = 1.6, shift=None,
):
    """Batched native solve over the leading axis (the C counterpart of
    ``vmap(solve_socp)``). Returns ``(x (nb, nv), residuals (nb, 2))``."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native solver unavailable: {_build_error}")
    P = np.ascontiguousarray(P, np.float64)
    q = np.ascontiguousarray(q, np.float64)
    A = np.ascontiguousarray(A, np.float64)
    lb = np.ascontiguousarray(lb, np.float64)
    ub = np.ascontiguousarray(ub, np.float64)
    nb, m, nv = A.shape
    dims = np.ascontiguousarray(soc_dims, np.int32)
    shift_p = None
    if shift is not None:
        shift = np.ascontiguousarray(shift, np.float64)
        shift_p = _ptr(shift)
    x = np.zeros((nb, nv))
    y = np.zeros((nb, m))
    z = np.zeros((nb, m))
    res = np.zeros((nb, 2))
    rc = lib.socp_solve_batch(
        _ptr(P), _ptr(q), _ptr(A), _ptr(lb), _ptr(ub), shift_p,
        nb, nv, m, n_box,
        dims.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(dims),
        iters, rho, sigma, alpha,
        _ptr(x), _ptr(y), _ptr(z), _ptr(res),
    )
    if rc != 0:
        raise RuntimeError("native KKT factorization failed in batch")
    return x, res
