// Native (C++) conic-QP solver — the host-side counterpart of ops/socp.py.
//
// Role: the reference leans on Clarabel (Rust, via cvxpy) as its native conic
// solver (SURVEY.md §2.9). This file fills that native tier for the TPU build:
// a dependency-free ADMM solver for
//
//     minimize    (1/2) x^T P x + q^T x
//     subject to  A x + shift in Box(l, u) x SOC(d_1) x ... x SOC(d_k)
//
// with the SAME splitting, penalty scheme, and cone layout as ops/socp.py, so
// it serves as (a) an independent cross-implementation oracle for the JAX
// solver's tests and (b) a low-latency single-instance fallback on hosts.
//
// Dense row-major matrices; Cholesky-factored KKT; no external deps. Built as a
// shared library and bound through ctypes (tpu_aerial_transport/native).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr double kEqRhoScale = 1e3;  // matches socp.EQ_RHO_SCALE.

// In-place dense Cholesky (lower) of an n x n SPD matrix. Returns false if a
// non-positive pivot appears.
bool cholesky(std::vector<double>& M, int n) {
  for (int j = 0; j < n; ++j) {
    double d = M[j * n + j];
    for (int k = 0; k < j; ++k) d -= M[j * n + k] * M[j * n + k];
    if (d <= 0.0) return false;
    const double L = std::sqrt(d);
    M[j * n + j] = L;
    for (int i = j + 1; i < n; ++i) {
      double s = M[i * n + j];
      for (int k = 0; k < j; ++k) s -= M[i * n + k] * M[j * n + k];
      M[i * n + j] = s / L;
    }
  }
  // Zero the strict upper triangle for cleanliness.
  for (int i = 0; i < n; ++i)
    for (int j = i + 1; j < n; ++j) M[i * n + j] = 0.0;
  return true;
}

void chol_solve(const std::vector<double>& L, int n, std::vector<double>& b) {
  for (int i = 0; i < n; ++i) {
    double s = b[i];
    for (int k = 0; k < i; ++k) s -= L[i * n + k] * b[k];
    b[i] = s / L[i * n + i];
  }
  for (int i = n - 1; i >= 0; --i) {
    double s = b[i];
    for (int k = i + 1; k < n; ++k) s -= L[k * n + i] * b[k];
    b[i] = s / L[i * n + i];
  }
}

// Project z (length m) onto the translated cone; identical regime logic to
// socp.project_soc / _project_cone.
void project_cone(std::vector<double>& z, const double* lb, const double* ub,
                  int n_box, const int32_t* soc_dims, int n_soc,
                  const double* shift) {
  if (shift != nullptr)
    for (size_t i = 0; i < z.size(); ++i) z[i] += shift[i];
  for (int i = 0; i < n_box; ++i) {
    if (z[i] < lb[i]) z[i] = lb[i];
    if (z[i] > ub[i]) z[i] = ub[i];
  }
  int off = n_box;
  for (int b = 0; b < n_soc; ++b) {
    const int d = soc_dims[b];
    const double t = z[off];
    double nv = 0.0;
    for (int i = 1; i < d; ++i) nv += z[off + i] * z[off + i];
    nv = std::sqrt(nv);
    if (nv <= t) {
      // inside: keep.
    } else if (nv <= -t) {
      for (int i = 0; i < d; ++i) z[off + i] = 0.0;
    } else {
      const double s = 0.5 * (t + nv);
      const double scale = (nv > 0.0) ? s / nv : 0.0;
      z[off] = s;
      for (int i = 1; i < d; ++i) z[off + i] *= scale;
    }
    off += d;
  }
  if (shift != nullptr)
    for (size_t i = 0; i < z.size(); ++i) z[i] -= shift[i];
}

}  // namespace

extern "C" {

// Solve one conic QP. Returns 0 on success, 1 on factorization failure.
// All matrices row-major double. Outputs: x (nv), y (m), z (m), and
// residuals[2] = {primal_inf, dual_inf}.
int socp_solve(const double* P, const double* q, const double* A,
               const double* lb, const double* ub, const double* shift,
               int nv, int m, int n_box, const int32_t* soc_dims, int n_soc,
               int iters, double rho, double sigma, double alpha,
               const double* x0, const double* y0, const double* z0,
               double* x_out, double* y_out, double* z_out,
               double* residuals) {
  std::vector<double> rho_vec(m, rho);
  for (int i = 0; i < n_box; ++i)
    if (ub[i] - lb[i] < 1e-9) rho_vec[i] = rho * kEqRhoScale;

  // KKT matrix M = P + sigma I + A^T diag(rho) A, factored once.
  std::vector<double> M(static_cast<size_t>(nv) * nv);
  for (int i = 0; i < nv; ++i)
    for (int j = 0; j < nv; ++j) {
      double s = P[i * nv + j] + (i == j ? sigma : 0.0);
      for (int r = 0; r < m; ++r) s += A[r * nv + i] * rho_vec[r] * A[r * nv + j];
      M[i * nv + j] = s;
    }
  if (!cholesky(M, nv)) return 1;

  std::vector<double> x(nv, 0.0), y(m, 0.0), z(m, 0.0);
  if (x0 != nullptr) std::memcpy(x.data(), x0, nv * sizeof(double));
  if (y0 != nullptr) std::memcpy(y.data(), y0, m * sizeof(double));
  if (z0 != nullptr) {
    std::memcpy(z.data(), z0, m * sizeof(double));
  } else {
    project_cone(z, lb, ub, n_box, soc_dims, n_soc, shift);
  }

  std::vector<double> rhs(nv), Ax(m), zt(m);
  for (int it = 0; it < iters; ++it) {
    // rhs = sigma x - q + A^T (rho z - y); x = M^{-1} rhs.
    for (int i = 0; i < nv; ++i) rhs[i] = sigma * x[i] - q[i];
    for (int r = 0; r < m; ++r) {
      const double w = rho_vec[r] * z[r] - y[r];
      for (int i = 0; i < nv; ++i) rhs[i] += A[r * nv + i] * w;
    }
    chol_solve(M, nv, rhs);
    x.swap(rhs);
    // Ax, over-relaxed z-update, dual update.
    for (int r = 0; r < m; ++r) {
      double s = 0.0;
      for (int i = 0; i < nv; ++i) s += A[r * nv + i] * x[i];
      Ax[r] = alpha * s + (1.0 - alpha) * z[r];
    }
    for (int r = 0; r < m; ++r) zt[r] = Ax[r] + y[r] / rho_vec[r];
    project_cone(zt, lb, ub, n_box, soc_dims, n_soc, shift);
    for (int r = 0; r < m; ++r) {
      y[r] += rho_vec[r] * (Ax[r] - zt[r]);
      z[r] = zt[r];
    }
  }

  // Residuals: prim = ||A x - z||_inf; dual = ||P x + q + A^T y||_inf.
  double prim = 0.0, dual = 0.0;
  for (int r = 0; r < m; ++r) {
    double s = 0.0;
    for (int i = 0; i < nv; ++i) s += A[r * nv + i] * x[i];
    prim = std::max(prim, std::fabs(s - z[r]));
  }
  for (int i = 0; i < nv; ++i) {
    double s = q[i];
    for (int j = 0; j < nv; ++j) s += P[i * nv + j] * x[j];
    for (int r = 0; r < m; ++r) s += A[r * nv + i] * y[r];
    dual = std::max(dual, std::fabs(s));
  }
  std::memcpy(x_out, x.data(), nv * sizeof(double));
  std::memcpy(y_out, y.data(), m * sizeof(double));
  std::memcpy(z_out, z.data(), m * sizeof(double));
  residuals[0] = prim;
  residuals[1] = dual;
  return 0;
}

// Batched entry point: nb independent problems with identical static layout
// (nv, m, cones) but distinct data — the C counterpart of vmap(solve_socp).
int socp_solve_batch(const double* P, const double* q, const double* A,
                     const double* lb, const double* ub, const double* shift,
                     int nb, int nv, int m, int n_box,
                     const int32_t* soc_dims, int n_soc,
                     int iters, double rho, double sigma, double alpha,
                     double* x_out, double* y_out, double* z_out,
                     double* residuals) {
  int rc = 0;
  for (int b = 0; b < nb; ++b) {
    rc |= socp_solve(
        P + static_cast<size_t>(b) * nv * nv, q + static_cast<size_t>(b) * nv,
        A + static_cast<size_t>(b) * m * nv, lb + static_cast<size_t>(b) * n_box,
        ub + static_cast<size_t>(b) * n_box,
        shift ? shift + static_cast<size_t>(b) * m : nullptr,
        nv, m, n_box, soc_dims, n_soc, iters, rho, sigma, alpha,
        nullptr, nullptr, nullptr,
        x_out + static_cast<size_t>(b) * nv, y_out + static_cast<size_t>(b) * m,
        z_out + static_cast<size_t>(b) * m, residuals + 2 * b);
  }
  return rc;
}

}  // extern "C"
