"""Build side of the AOT artifact bundles: turn the
``analysis.entrypoints`` registry into versioned, content-addressed
executable bundles so serving replicas, bench probes, and test drivers pay
compilation at BUILD time, not at first dispatch.

A bundle directory holds::

    <bundle>/manifest.json        # schema version, runtime fingerprint,
                                  # per-entry variants + artifact digests
    <bundle>/objects/<sha>.bin    # content-addressed artifact payloads

Per registered entrypoint x shape signature (a "variant"), two artifact
flavors are built:

- **export**: the ``jax.export`` StableHLO blob of the entry lowered for
  the target platform. Portable across processes and jaxlib patch
  versions; replaying it skips Python tracing entirely but still pays one
  XLA backend compile at load (which the persistent compilation cache can
  absorb). This is the only flavor buildable for a platform the build
  host cannot execute (the TPU-target bundle built on a CPU box — the
  same off-chip trick as the TC106 lowering gate).
- **exec**: the serialized XLA executable itself
  (``client.serialize_executable``) plus its ``CompileOptions`` proto and
  the kept-argument index set. Loading it is a true **zero-compile** cold
  start — no trace, no lowering, no backend compile — but it is only
  valid for the exact jaxlib/XLA fingerprint and platform it was built
  on, which is why the manifest pins :func:`runtime_fingerprint` and the
  loader refuses a mismatch with a structured ``bundle_stale`` error
  (a rebuild hint, never a chip indictment — see
  ``resilience.backend.BREAKER_KINDS``).

Entrypoints are serialized FLAT: the wrapper traced for export takes the
flattened argument leaves and returns the flattened output leaves, and
the bundle stores the pickled in/out treedefs next to the artifacts —
``jax.export`` cannot serialize the package's custom pytree nodes
(flax-struct states), and the manifest's treedef + per-leaf aval record
is exactly the refusal surface the loader checks callers against (the
same manifest discipline as ``harness/checkpoint.py``).

Shape buckets: a variant's identity is :func:`abstract_signature` over
the input avals + treedef. Batched entries can be built at several
scenario-batch buckets (``harness.bucketing.bucket_dim`` rounds requested
batch sizes onto the tile grid) so heterogeneous serving batches land on
a precompiled variant — see :func:`bucketed_batch` and the loader's
``variant_for_batch``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import warnings

import numpy as np

SCHEMA_VERSION = 1

MANIFEST_NAME = "manifest.json"
OBJECTS_DIR = "objects"

# Synthetic probe entry built into every bundle: the exact device
# computation ``resilience.backend.PROBE_CODE`` warms (matmul + an
# explicit convert_element_type round-trip, the r02 failure class), as a
# precompiled program — so a backend probe can validate first REAL
# dispatch without burning its deadline on an XLA compile.
PROBE_ENTRY = "aot:probe"

# Scenario-batch tile for bucketed variants (bucket_dim grid). The f32
# sublane tile; the lane axis comes from folding agents x scenarios.
BATCH_BUCKET_TILE = 8

# Entries with a leading Monte-Carlo scenario-batch axis that may be
# built at several batch buckets (entry name -> batch axis). The serving
# chunk entries are the continuous-batching tier's admission surface: the
# server's shape buckets are exactly the variants built here.
BUCKETED_ENTRIES: dict[str, int] = {
    "parallel.mesh:scenario_rollout": 0,
    "serving.batcher:serving_chunk": 0,
    "serving.batcher:serving_chunk_centralized": 0,
    # The boundary lane-surgery programs ride the same buckets as their
    # chunk entries: device-surgery replicas serve BOTH per boundary, so
    # bucket coverage must agree or admission would be zero-compile for
    # the chunk and jit-compile for the surgery.
    "serving.lanes:lane_surgery": 0,
    "serving.lanes:lane_surgery_centralized": 0,
}


@dataclasses.dataclass(frozen=True)
class BundleError(Exception):
    """Structured bundle failure (same shape as ``checkpoint.SnapshotError``).

    kind: ``unreadable`` (missing/truncated manifest or object),
    ``schema`` (newer bundle format), ``missing_entry`` (entry/variant not
    in the bundle), ``signature_mismatch`` (caller avals differ from every
    built variant), ``treedef_mismatch`` (caller pytree structure differs
    from the recorded one), ``corrupt`` (object payload digest mismatch),
    ``bundle_stale`` (exec artifact's jaxlib/XLA/platform fingerprint
    differs from this process — rebuild the bundle), ``exec_unavailable``
    (no exec artifact for this variant on this platform).
    """

    kind: str
    path: str
    detail: str = ""

    def __str__(self) -> str:
        msg = f"[{self.kind}] {self.path}: {self.detail}"
        if self.kind == "bundle_stale":
            msg += (" — rebuild hint: python tools/aot_bundle.py build "
                    f"--out {os.path.dirname(self.path) or self.path}")
        return msg


# ----------------------------------------------------------------------
# Fingerprints and signatures.
# ----------------------------------------------------------------------

def runtime_fingerprint(platform: str | None = None) -> dict:
    """Identity of the compiling/serving runtime: jax + jaxlib versions,
    target platform, and — when a live backend of that platform exists —
    its ``platform_version`` (the XLA/runtime build). Exec artifacts are
    valid only under an IDENTICAL fingerprint; export artifacts record it
    for provenance but do not enforce it."""
    import jax
    import jaxlib

    if platform is None:
        platform = jax.default_backend()
    version = None
    try:
        if platform == jax.default_backend():
            version = jax.devices()[0].client.platform_version
    except Exception:  # no live backend for the target: export-only build.
        version = None
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": platform,
        "platform_version": version,
    }


def abstract_signature(args) -> str:
    """Shape signature of an argument pytree: treedef string + per-leaf
    shape/dtype, hashed. Computed from concrete arrays or
    ``ShapeDtypeStruct``s alike (no tracing) — the bundle keys variants on
    it, the coverage gate diffs it, and the loader refuses callers whose
    args hash differently."""
    import jax

    leaves, treedef = jax.tree.flatten(args)
    spec = [str(treedef)] + [
        f"{tuple(np.shape(l) if not hasattr(l, 'shape') else l.shape)}:"
        f"{np.dtype(getattr(l, 'dtype', type(l))).str}"
        for l in leaves
    ]
    return hashlib.sha256("\n".join(spec).encode()).hexdigest()[:16]


def _avals_of(args) -> list[dict]:
    import jax

    return [
        {"shape": list(l.shape), "dtype": np.dtype(l.dtype).str}
        for l in jax.tree.leaves(args)
    ]


# ----------------------------------------------------------------------
# Registry iteration (shared by build and the coverage gate).
# ----------------------------------------------------------------------

def _probe_build():
    """The bundled probe program (see :data:`PROBE_ENTRY`)."""
    import jax.numpy as jnp
    from jax import lax

    def fn(x):
        y = lax.convert_element_type(x @ x, jnp.bfloat16)
        return lax.convert_element_type(y, jnp.float32).sum()

    def make_args():
        return (jnp.ones((128, 128), jnp.float32),)

    return fn, make_args


def entry_specs(names=None) -> dict:
    """``{name: spec}`` over the registry (+ :data:`PROBE_ENTRY`), where a
    buildable spec is ``{"sig", "build"}`` and a skipped one is
    ``{"skip": reason}``. Skips mirror the contract machinery: entries
    needing more devices than the host has, ``lowering_only`` chip-only
    programs, and ``entrypoints.LOWERING_WAIVERS`` rows (``jax.export``
    cannot AOT-lower them off-chip by definition). Computing a signature
    needs only ``make_args()`` — no tracing — so the tier-1 coverage gate
    stays cheap."""
    import jax

    from tpu_aerial_transport.analysis import contracts
    from tpu_aerial_transport.analysis import entrypoints as entry_data

    out: dict = {}
    selected = (sorted(contracts.REGISTRY) + [PROBE_ENTRY]
                if names is None else list(names))
    for name in selected:
        if name == PROBE_ENTRY:
            fn, make_args = _probe_build()
            out[name] = {
                "sig": abstract_signature(make_args()),
                "build": (fn, make_args),
            }
            continue
        contract = contracts.REGISTRY[name]
        if jax.device_count() < contract.min_devices:
            out[name] = {"skip": (
                f"needs {contract.min_devices} devices, host has "
                f"{jax.device_count()}"
            )}
            continue
        if contract.lowering_only:
            out[name] = {"skip": f"lowering_only: {contract.lowering_only}"}
            continue
        waiver = entry_data.LOWERING_WAIVERS.get(name)
        if waiver is not None:
            out[name] = {"skip": f"LOWERING_WAIVERS: {waiver[:120]}"}
            continue
        fn, make_args = contract.build()
        args = make_args()
        out[name] = {
            "sig": abstract_signature(args),
            "build": (fn, make_args),
        }
    return out


def bucketed_batch(args, batch_axis: int, batch: int):
    """Re-batch ``args`` along ``batch_axis`` to the bucket grid:
    ``bucket_dim(batch, BATCH_BUCKET_TILE)`` lanes, tiled cyclically from
    the originals (shape bucketing — the VALUES only seed compilation)."""
    import jax
    import jax.numpy as jnp

    from tpu_aerial_transport.harness.bucketing import bucket_dim

    b = bucket_dim(batch, BATCH_BUCKET_TILE)

    def retile(x):
        if x.ndim <= batch_axis:  # scalar args (the chunk step offset)
            return x              # carry no batch axis to retile.
        cur = x.shape[batch_axis]
        reps = [1] * x.ndim
        reps[batch_axis] = -(-b // cur)
        return jnp.moveaxis(
            jnp.moveaxis(jnp.tile(x, reps), batch_axis, 0)[:b],
            0, batch_axis,
        )

    return jax.tree.map(retile, args), b


# ----------------------------------------------------------------------
# Build.
# ----------------------------------------------------------------------

def _flat_fn(fn, in_treedef):
    import jax

    def flat(*leaves):
        args = jax.tree.unflatten(in_treedef, list(leaves))
        return tuple(jax.tree.leaves(fn(*args)))

    return flat


def _write_object(out_dir: str, payload: bytes) -> dict:
    digest = hashlib.sha256(payload).hexdigest()
    objdir = os.path.join(out_dir, OBJECTS_DIR)
    os.makedirs(objdir, exist_ok=True)
    path = os.path.join(objdir, digest[:32] + ".bin")
    if not os.path.exists(path):
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    return {"object": os.path.basename(path), "sha256": digest}


def _build_variant(name: str, fn, args, platform: str, out_dir: str,
                   exec_artifacts: bool, meta: dict | None = None) -> dict:
    """One entry x signature: export artifact always; exec artifact when
    this host can compile for ``platform`` and the program is
    single-device (the low-level replay path addresses one device; the
    sharded tier serves through export + the serving-mesh jit)."""
    import jax
    from jax import export as jax_export

    flat_args, in_treedef = jax.tree.flatten(args)
    in_avals = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in flat_args]
    out_treedef = jax.tree.structure(jax.eval_shape(fn, *args))
    flat = _flat_fn(fn, in_treedef)
    jitted = jax.jit(flat)
    with warnings.catch_warnings():
        # Entries that are already donation-clean jits (chunked_rollout)
        # re-trace here inside a non-donating wrapper; the inner donation
        # becoming unused is expected, not a bundle defect.
        warnings.filterwarnings("ignore", message=".*donated.*")
        exported = jax_export.export(jitted, platforms=[platform])(*in_avals)
        variant: dict = {
            "sig": abstract_signature(args),
            "in_avals": _avals_of(args),
            "out_avals": [
                {"shape": list(a.shape), "dtype": np.dtype(a.dtype).str}
                for a in exported.out_avals
            ],
            "nr_devices": int(exported.nr_devices),
            "in_treedef": _write_object(out_dir, pickle.dumps(in_treedef)),
            "out_treedef": _write_object(out_dir, pickle.dumps(out_treedef)),
            # The build-time argument VALUES (host numpy): a zero-compile
            # serving replica loads these as its template carry instead of
            # running the eager jnp state factories (each of which pays a
            # backend compile) — see loader.Bundle.sample_args.
            "args_sample": _write_object(
                out_dir,
                pickle.dumps([np.asarray(l) for l in flat_args]),
            ),
            "artifacts": {
                "export": _write_object(out_dir, bytes(exported.serialize())),
            },
            **(meta or {}),
        }
        if (exec_artifacts and exported.nr_devices == 1
                and platform == jax.default_backend()):
            # Force a REAL backend compile: an executable the persistent
            # compilation cache handed back re-serializes WITHOUT its
            # compiled object code — the blob deserializes to "Symbols
            # not found: [<fusion kernels>]" (measured on jaxlib 0.4.36,
            # XLA:CPU). Builds on a warm cache (any test/bench host)
            # would silently publish corrupt exec artifacts otherwise.
            # Toggling the dir config alone is NOT enough:
            # compilation_cache.is_cache_used() memoizes its verdict
            # process-wide at first compile, so the toggle must be paired
            # with reset_cache() on both edges.
            from jax._src import compilation_cache as _cc

            cache_dir = jax.config.jax_compilation_cache_dir
            try:
                if cache_dir:
                    jax.config.update("jax_compilation_cache_dir", None)
                    _cc.reset_cache()
                compiled = jitted.lower(*in_avals).compile()
            finally:
                if cache_dir:
                    jax.config.update("jax_compilation_cache_dir",
                                      cache_dir)
                    _cc.reset_cache()
            exe = compiled._executable.xla_executable
            kept = getattr(compiled._executable, "_kept_var_idx", None)
            kept = sorted(kept) if kept is not None else list(
                range(len(flat_args))
            )
            try:
                exec_blob = exe.client.serialize_executable(exe)
                opts_blob = exe.compile_options().SerializeAsString()
                # Round-trip verification at BUILD time: a blob that
                # cannot deserialize here would fail every replica at
                # serve time instead.
                from jax._src.lib import xla_client as _xc

                exe.client.deserialize_executable(
                    exec_blob,
                    _xc.CompileOptions.ParseFromString(opts_blob),
                )
            except Exception as e:  # backend cannot serialize: export-only.
                variant["exec_note"] = (
                    f"exec artifact unavailable: {type(e).__name__}: {e}"
                )[:200]
            else:
                variant["artifacts"]["exec"] = {
                    **_write_object(out_dir, exec_blob),
                    "options": _write_object(out_dir, opts_blob),
                    "kept_var_idx": kept,
                    "fingerprint": runtime_fingerprint(platform),
                }
    return variant


def build_bundle(out_dir: str, *, platform: str | None = None,
                 names=None, exec_artifacts: bool = True,
                 manifest_only: bool = False,
                 batch_buckets=(), progress=None) -> dict:
    """Build (or re-build) a bundle for ``platform`` under ``out_dir`` and
    return the manifest. ``manifest_only`` records coverage (names +
    signatures + skip reasons) without lowering anything — the cheap
    in-tree artifact the CI drift gate diffs against. ``batch_buckets``
    adds bucketed scenario-batch variants for :data:`BUCKETED_ENTRIES`.
    The manifest is published atomically (temp + ``os.replace``)."""
    import jax

    if platform is None:
        platform = jax.default_backend()
    if names is not None and PROBE_ENTRY not in names:
        names = list(names) + [PROBE_ENTRY]  # every bundle carries the probe.
    specs = entry_specs(names)
    manifest: dict = {
        "schema": SCHEMA_VERSION,
        "platform": platform,
        "fingerprint": runtime_fingerprint(platform),
        "manifest_only": bool(manifest_only),
        "entries": {},
        "skipped": {},
    }
    for name, spec in specs.items():
        if "skip" in spec:
            manifest["skipped"][name] = spec["skip"]
            continue
        fn, make_args = spec["build"]
        if manifest_only:
            manifest["entries"][name] = {"variants": [{"sig": spec["sig"]}]}
            continue
        if progress:
            progress(name)
        args = make_args()
        variants = [_build_variant(
            name, fn, args, platform, out_dir, exec_artifacts
        )]
        axis = BUCKETED_ENTRIES.get(name)
        if axis is not None:
            for b in batch_buckets:
                bargs, bb = bucketed_batch(args, axis, int(b))
                variants.append(_build_variant(
                    name, fn, bargs, platform, out_dir, exec_artifacts,
                    meta={"batch": bb, "batch_axis": axis},
                ))
        manifest["entries"][name] = {"variants": variants}
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, MANIFEST_NAME)
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(manifest, fh, indent=1, sort_keys=True)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return manifest


# ----------------------------------------------------------------------
# Coverage diff (the CI drift gate's core).
# ----------------------------------------------------------------------

def coverage_diff(manifest: dict, names=None) -> dict:
    """Diff a bundle manifest against the LIVE registry. Returns
    ``{"missing", "stale", "changed", "uncovered_skips", "ok"}``:

    - ``missing``: registry entries the bundle does not carry (a new
      entrypoint landed without a bundle rebuild);
    - ``stale``: bundle entries no longer in the registry;
    - ``changed``: entries whose default-variant signature differs (arg
      shapes/structure drifted since the bundle was built);
    - ``uncovered_skips``: entries the bundle skipped that ARE buildable
      on this host (the skip reason no longer holds).

    Entries this host cannot build (device count) are not findings when
    the bundle carries them — a bigger build host is allowed.
    """
    specs = entry_specs(names)
    built = manifest.get("entries", {})
    skipped = manifest.get("skipped", {})
    diff = {"missing": [], "stale": [], "changed": [], "uncovered_skips": []}
    for name, spec in specs.items():
        if "skip" in spec:
            continue  # host limitation or waiver; bundle may still carry it.
        if name in built:
            have = {v.get("sig") for v in built[name].get("variants", [])}
            if spec["sig"] not in have:
                diff["changed"].append(
                    f"{name}: live sig {spec['sig']} not in built {sorted(have)}"
                )
        elif name in skipped:
            diff["uncovered_skips"].append(
                f"{name}: bundle skipped it ({skipped[name][:80]}) but it "
                "builds on this host"
            )
        else:
            diff["missing"].append(name)
    live = set(specs)
    for name in sorted(set(built) | set(skipped)):
        if name not in live:
            diff["stale"].append(name)
    diff["ok"] = not any(diff[k] for k in
                         ("missing", "stale", "changed", "uncovered_skips"))
    return diff


def read_manifest(bundle_dir: str) -> dict:
    path = os.path.join(bundle_dir, MANIFEST_NAME)
    try:
        with open(path, encoding="utf-8") as fh:
            manifest = json.load(fh)
    except Exception as e:
        raise BundleError(
            "unreadable", path, f"{type(e).__name__}: {e}"
        ) from e
    if manifest.get("schema", -1) > SCHEMA_VERSION:
        raise BundleError(
            "schema", path,
            f"written by schema {manifest.get('schema')} > supported "
            f"{SCHEMA_VERSION}",
        )
    return manifest
