"""Serve side of the AOT artifact bundles: deserialize precompiled
entrypoints and call them through a journaled fallback ladder.

The ladder, per serve (:func:`serve_entry`)::

    bundle_exec    zero-compile: the serialized XLA executable replays
                   directly (no trace, no lowering, no backend compile).
                   Refused with ``bundle_stale`` when the jaxlib/XLA/
                   platform fingerprint differs from this process.
    bundle_export  zero-lowering: the jax.export StableHLO blob replays
                   (no Python retrace); pays ONE backend compile, which
                   the persistent compilation cache can absorb.
    jit_cached     ordinary jit of the caller-supplied fallback with the
                   persistent XLA cache configured (trace + cache probe).
    jit_cold       ordinary jit, no cache — the pre-bundle world.

Every serve emits one ``aot_serve`` metrics event (schema v3,
``obs.export``) carrying the rung it landed on and what the process paid,
so ``tools/run_health.py`` shows exactly which replicas are still
compiling.

CPU custom-call note: XLA:CPU executables that call LAPACK kernels
resolve them through handlers whose function pointers jax binds lazily
inside the LOWERING rules — a zero-compile process never lowers, so the
loader initializes the binding explicitly (:func:`_ensure_cpu_kernels`);
without it a deserialized conic-solve executable segfaults at dispatch
(measured on jaxlib 0.4.36).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import time

import numpy as np

from tpu_aerial_transport.aot.bundle import (
    MANIFEST_NAME,
    OBJECTS_DIR,
    PROBE_ENTRY,
    BundleError,
    abstract_signature,
    read_manifest,
    runtime_fingerprint,
)

RUNG_EXEC = "bundle_exec"
RUNG_EXPORT = "bundle_export"
RUNG_JIT_CACHED = "jit_cached"
RUNG_JIT_COLD = "jit_cold"

# None until the first attempt; then "ok" or the sticky failure detail.
_cpu_kernels_state: str | None = None


def _ensure_cpu_kernels() -> str | None:
    """Bind the CPU LAPACK custom-call kernels before replaying a
    deserialized executable (see the module docstring). Returns ``None``
    when bound, else the failure detail (sticky across calls): a jaxlib
    that reshuffles the private module makes the exec rung REFUSE
    (``exec_unavailable`` → the ladder serves the export rung) instead of
    dispatching an executable whose LAPACK calls are unbound — that path
    segfaults, it does not raise."""
    global _cpu_kernels_state
    if _cpu_kernels_state is None:
        try:
            from jaxlib.cpu import _lapack

            _lapack.initialize()
            _cpu_kernels_state = "ok"
        except Exception as e:
            _cpu_kernels_state = f"{type(e).__name__}: {e}"
    return None if _cpu_kernels_state == "ok" else _cpu_kernels_state


class Bundle:
    """A loaded bundle directory. Objects are read lazily and verified
    against their manifest sha256 when first read (``corrupt`` refusal);
    deserialized artifacts — treedefs, the XLA executable, the jitted
    export replay — are MEMOIZED per variant, so a serving replica pays
    the read/verify/deserialize cost once per process, not per request
    (the export rung's backend compile included: replays after the first
    hit the jit cache)."""

    def __init__(self, directory: str, manifest: dict):
        self.directory = directory
        self.manifest = manifest
        self.platform = manifest.get("platform")
        self._treedefs: dict = {}     # object name -> unpickled treedef
        self._execs: dict = {}        # object name -> (executable, kept)
        self._exports: dict = {}      # object name -> jitted replay fn

    # -------------------------------------------------- object access --
    def _read_object(self, ref: dict) -> bytes:
        path = os.path.join(self.directory, OBJECTS_DIR, ref["object"])
        try:
            with open(path, "rb") as fh:
                payload = fh.read()
        except OSError as e:
            raise BundleError(
                "unreadable", path, f"{type(e).__name__}: {e}"
            ) from e
        digest = hashlib.sha256(payload).hexdigest()
        if digest != ref["sha256"]:
            raise BundleError(
                "corrupt", path,
                f"payload digest {digest[:12]} != manifest "
                f"{ref['sha256'][:12]}",
            )
        return payload

    def _treedef(self, ref: dict):
        key = ref["object"]
        if key not in self._treedefs:
            self._treedefs[key] = pickle.loads(self._read_object(ref))
        return self._treedefs[key]

    # ------------------------------------------------ variant lookup ---
    def entry_names(self) -> list[str]:
        return sorted(self.manifest.get("entries", {}))

    def variants(self, name: str) -> list[dict]:
        entry = self.manifest.get("entries", {}).get(name)
        if entry is None:
            skipped = self.manifest.get("skipped", {}).get(name)
            detail = (f"entry skipped at build time: {skipped}"
                      if skipped else "entry not in bundle")
            raise BundleError("missing_entry",
                              os.path.join(self.directory, MANIFEST_NAME),
                              f"{name}: {detail}")
        variants = entry.get("variants", [])
        if not variants or "artifacts" not in variants[0]:
            raise BundleError(
                "missing_entry",
                os.path.join(self.directory, MANIFEST_NAME),
                f"{name}: manifest-only bundle carries no artifacts "
                "(coverage record; build without --manifest-only to serve)",
            )
        return variants

    def variant_for(self, name: str, args) -> dict:
        """The variant whose signature matches ``args`` exactly.
        A structural mismatch refuses with ``treedef_mismatch``; a pure
        shape/dtype mismatch with ``signature_mismatch``."""
        import jax

        sig = abstract_signature(args)
        variants = self.variants(name)
        for v in variants:
            if v["sig"] == sig:
                return v
        v0 = variants[0]
        in_treedef = self._treedef(v0["in_treedef"])
        if jax.tree.structure(args) != in_treedef:
            raise BundleError(
                "treedef_mismatch", self.directory,
                f"{name}: caller argument pytree structure differs from "
                "the built one (controller/carry schema drifted since the "
                "bundle was built)",
            )
        raise BundleError(
            "signature_mismatch", self.directory,
            f"{name}: caller avals hash {sig}, built "
            f"{sorted(v['sig'] for v in variants)} — no precompiled "
            "variant for this shape bucket",
        )

    def variant_for_batch(self, name: str, batch: int) -> dict:
        """Smallest bucketed variant admitting ``batch`` lanes (callers
        pad their batch up to the variant's ``batch``); falls back to the
        largest when the request exceeds every bucket. Bucket selection is
        ``harness.bucketing.pick_bucket`` — the ONE smallest-admitting-
        bucket rule this loader and the serving batcher share."""
        from tpu_aerial_transport.harness.bucketing import pick_bucket

        vs = [v for v in self.variants(name) if "batch" in v]
        if not vs:
            raise BundleError(
                "missing_entry", self.directory,
                f"{name}: no bucketed variants (build with --batch-buckets)",
            )
        vs.sort(key=lambda v: v["batch"])
        picked = pick_bucket(batch, [v["batch"] for v in vs])
        if picked is None:  # exceeds every bucket: largest wins.
            return vs[-1]
        return next(v for v in vs if v["batch"] == picked)

    def batch_buckets(self, name: str, batch_axis: int = 0) -> list[int]:
        """Sorted device-batch sizes this bundle precompiled for ``name``
        (the serving tier's admission-control coverage set). The default
        (unbucketed) variant counts too: its batch is the leading dim of
        its first recorded input aval along ``batch_axis``."""
        out = set()
        for v in self.variants(name):
            if "batch" in v:
                out.add(int(v["batch"]))
                continue
            avals = v.get("in_avals") or []
            if avals and len(avals[0]["shape"]) > batch_axis:
                out.add(int(avals[0]["shape"][batch_axis]))
        return sorted(out)

    def sample_args(self, name: str, *, batch: int | None = None):
        """The CONCRETE argument pytree a variant was built from
        (host-numpy leaves, no tracing, no compiles): the bundle stores
        the build-time ``make_args()`` values as an ``args_sample``
        object. This is how a zero-compile serving replica gets a
        semantically valid template carry (equilibrium warm starts,
        identity attitudes) without running the eager jnp factories —
        ``probe_args`` only synthesizes unit values, which are the wrong
        CONTENTS for a controller state."""
        import jax

        variant = (self.variant_for_batch(name, batch)
                   if batch is not None else self.variants(name)[0])
        ref = variant.get("args_sample")
        if ref is None:
            raise BundleError(
                "missing_entry", self.directory,
                f"{name}: bundle predates args_sample artifacts — rebuild "
                "(tools/aot_bundle.py build) to serve template carries",
            )
        leaves = pickle.loads(self._read_object(ref))
        return jax.tree.unflatten(self._treedef(variant["in_treedef"]),
                                  leaves)

    # ------------------------------------------------------ calling ----
    def _call_exec(self, name: str, variant: dict, flat_args):
        import jax
        from jax._src.lib import xla_client as xc

        art = variant["artifacts"].get("exec")
        if art is None:
            raise BundleError(
                "exec_unavailable", self.directory,
                f"{name}: no exec artifact "
                f"({variant.get('exec_note', 'built export-only')})",
            )
        fp = art["fingerprint"]
        here = runtime_fingerprint(self.platform)
        if fp != here:
            drift = {k: (fp.get(k), here.get(k)) for k in set(fp) | set(here)
                     if fp.get(k) != here.get(k)}
            raise BundleError(
                "bundle_stale", self.directory,
                f"{name}: exec artifact fingerprint differs from this "
                f"runtime: {drift}",
            )
        if self.platform == "cpu":
            kerr = _ensure_cpu_kernels()
            if kerr is not None:
                raise BundleError(
                    "exec_unavailable", self.directory,
                    f"{name}: CPU LAPACK custom-call binding unavailable "
                    f"({kerr}) — exec replay would dispatch unbound "
                    "kernels (segfault, not an exception)",
                )
        if art["object"] not in self._execs:
            backend = jax.devices(self.platform)[0].client
            opts = xc.CompileOptions.ParseFromString(
                self._read_object(art["options"])
            )
            self._execs[art["object"]] = backend.deserialize_executable(
                self._read_object(art), opts
            )
        exe = self._execs[art["object"]]
        kept = art["kept_var_idx"]

        import jax.numpy as jnp

        bufs = [jnp.asarray(flat_args[i]) for i in kept]
        results = exe.execute_sharded(bufs)
        return [o[0] for o in results.disassemble_into_single_device_arrays()]

    def _call_export(self, name: str, variant: dict, flat_args):
        import jax
        from jax import export as jax_export

        ref = variant["artifacts"]["export"]
        if ref["object"] not in self._exports:
            blob = self._read_object(ref)
            exported = jax_export.deserialize(bytearray(blob))
            # jit the replay so repeat serves hit the jit cache — a bare
            # exported.call pays the backend compile on EVERY request.
            self._exports[ref["object"]] = jax.jit(exported.call)
        return list(self._exports[ref["object"]](*flat_args))

    def call(self, name: str, args, *, rung: str | None = None):
        """Execute ``name`` on ``args`` (the entry's ORIGINAL pytree
        calling convention) from the bundle. Returns ``(out, rung)`` where
        ``out`` is rebuilt with the recorded output treedef. ``rung``
        pins a flavor (``bundle_exec``/``bundle_export``); default is
        exec with a fall-through to export ONLY for ``exec_unavailable``/
        ``bundle_stale`` (so a stale bundle still skips retracing)."""
        import jax

        variant = self.variant_for(name, args)
        in_treedef = self._treedef(variant["in_treedef"])
        if jax.tree.structure(args) != in_treedef:
            raise BundleError(
                "treedef_mismatch", self.directory,
                f"{name}: caller argument pytree structure differs from "
                "the built one",
            )
        flat_args = jax.tree.leaves(args)
        out_treedef = self._treedef(variant["out_treedef"])
        if rung == RUNG_EXPORT:
            flat_out = self._call_export(name, variant, flat_args)
            ran = RUNG_EXPORT
        elif rung == RUNG_EXEC:
            flat_out = self._call_exec(name, variant, flat_args)
            ran = RUNG_EXEC
        else:
            try:
                flat_out = self._call_exec(name, variant, flat_args)
                ran = RUNG_EXEC
            except BundleError as e:
                if e.kind not in ("exec_unavailable", "bundle_stale"):
                    raise
                flat_out = self._call_export(name, variant, flat_args)
                ran = RUNG_EXPORT
        return jax.tree.unflatten(out_treedef, flat_out), ran

    def probe_args(self, name: str = PROBE_ENTRY):
        """Synthesize unit-valued arguments from a variant's recorded
        avals (host numpy -> device_put; no compilation) — how the probe
        and the zero-compile driver build inputs without the registry."""
        import jax

        variant = self.variants(name)[0]
        in_treedef = self._treedef(variant["in_treedef"])
        leaves = [
            np.ones(tuple(a["shape"]), np.dtype(a["dtype"]))
            for a in variant["in_avals"]
        ]
        return jax.tree.unflatten(in_treedef, leaves)


def load_bundle(directory: str) -> Bundle:
    """Open a bundle directory (manifest schema-checked; artifact objects
    verified lazily per read)."""
    return Bundle(directory, read_manifest(directory))


def call_probe(bundle: Bundle, rung: str | None = RUNG_EXEC):
    """Run the bundled probe program (matmul + convert_element_type round
    trip) from its precompiled artifact; returns the scalar result. The
    backend-probe integration point: first REAL dispatch validated with
    zero in-process compiles — which is why the exec rung is PINNED by
    default: letting the ladder absorb a stale/absent exec artifact would
    silently pay the export rung's backend compile (the deadline-burning
    cost the bundled probe exists to avoid) and hide the ``bundle_stale``
    rebuild hint from the probe's notes."""
    import jax

    out, _ = bundle.call(PROBE_ENTRY, bundle.probe_args(), rung=rung)
    jax.block_until_ready(out)
    return out


# ----------------------------------------------------------------------
# The serve ladder.
# ----------------------------------------------------------------------

# BundleError kinds that mean the artifact store itself is damaged — a
# bitrotted object, a truncated manifest, an unknown schema. These
# re-raise from serve_entry even when a jit fallback is available:
# coverage gaps degrade, integrity failures page an operator.
INTEGRITY_KINDS = frozenset({"corrupt", "unreadable", "schema"})


def serve_entry(bundle: Bundle | None, name: str, args, *,
                jit_fallback=None, metrics=None, journal=None,
                label: str | None = None, block: bool = True,
                hub=None):
    """Serve one entrypoint call through the fallback ladder and journal
    what this process paid. Returns ``(out, rung)``.

    ``block=False`` skips the ``block_until_ready`` on the result — the
    pipelined-dispatch path (serving/server.py): every rung's underlying
    call (exec replay's ``execute_sharded``, the export/jit paths) is
    natively asynchronous, so the caller gets the output handles back at
    dispatch time and overlaps host work with device compute. The
    journaled ``wall_s`` then measures DISPATCH cost only; execution
    errors surface at the caller's eventual blocking read, outside any
    fallback this ladder could have taken.

    ``bundle`` None (or a bundle COVERAGE miss — ``missing_entry``,
    ``signature_mismatch``, ``treedef_mismatch``, a stale/absent exec)
    falls through to ``jit_fallback`` — an unjitted callable taking the
    same args, OR an already-jitted one (anything with ``.lower``, e.g. a
    ``jax.jit`` wrapper): a serving replica calling ``serve_entry`` per
    request must pass its ONE pre-jitted callable, because wrapping a
    plain function in a fresh ``jax.jit`` per serve would retrace every
    request. The rung is ``jit_cached`` when a persistent compilation
    cache is configured in this process, ``jit_cold`` otherwise. An
    INTEGRITY failure (:data:`INTEGRITY_KINDS`: corrupt object,
    unreadable/newer-schema manifest) re-raises after journaling even
    when a fallback exists — a bitrotted artifact must not silently
    become a cold compile on a serving replica's latency budget."""
    import jax

    label = label or name
    t0 = time.perf_counter()
    tried: list[str] = []

    def emit(rung: str, error: str | None = None) -> None:
        event = {
            "entry": name, "rung": rung, "label": label,
            "wall_s": time.perf_counter() - t0,
            **({"tried": tried} if tried else {}),
            **({"error": error} if error else {}),
        }
        if journal is not None:
            journal.append({"event": "aot_serve", **event})
        if metrics is not None:
            metrics.emit("aot_serve", **event)
        if hub is not None:
            # obs.live.MetricsHub (duck-typed): per-rung serve counters
            # + wall-time histogram. None = zero-cost off (HL010
            # identity guard; the event dict exists regardless).
            hub.ingest_aot(event)

    if bundle is not None:
        try:
            out, rung = bundle.call(name, args)
            if block:
                jax.block_until_ready(out)
            emit(rung)
            return out, rung
        except BundleError as e:
            tried.append(f"bundle[{e.kind}]")
            if jit_fallback is None or e.kind in INTEGRITY_KINDS:
                emit("error", error=str(e)[:300])
                raise
    if jit_fallback is None:
        raise BundleError(
            "missing_entry", getattr(bundle, "directory", "<no bundle>"),
            f"{name}: no bundle artifact and no jit fallback",
        )
    rung = (RUNG_JIT_CACHED
            if jax.config.jax_compilation_cache_dir else RUNG_JIT_COLD)
    # A pre-jitted fallback (duck-typed on .lower, which every jax.jit
    # wrapper carries) is called as-is so repeat serves reuse ITS cache.
    jitted = (jit_fallback if hasattr(jit_fallback, "lower")
              else jax.jit(jit_fallback))
    out = jitted(*args)
    if block:
        jax.block_until_ready(out)
    emit(rung)
    return out, rung
