"""AOT compilation-artifact bundles: compilation as a BUILD step.

``bundle`` builds versioned, content-addressed executable bundles from the
``analysis.entrypoints`` registry (one artifact set per entrypoint x shape
signature); ``loader`` deserializes them and serves precompiled calls with
a journaled fallback ladder (bundle-exec -> bundle-export -> persistent-
cache jit -> cold jit). See README "AOT artifact bundles".
"""

from tpu_aerial_transport.aot.bundle import (  # noqa: F401
    BundleError,
    PROBE_ENTRY,
    SCHEMA_VERSION,
    abstract_signature,
    build_bundle,
    entry_specs,
    runtime_fingerprint,
)
from tpu_aerial_transport.aot.loader import (  # noqa: F401
    Bundle,
    load_bundle,
    serve_entry,
)
