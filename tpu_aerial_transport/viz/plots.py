"""Matplotlib figures from rollout logs — the reference's ``example/rqp_plots.py``
paper-figure surface re-pointed at the npz/dict log schema from
``harness.rollout.logs_to_dict``.

All host-side; never inside the compiled path. Figures:
- :func:`plot_tracking_errors` — position/velocity error vs time
  (rqp_example.py:167-181).
- :func:`plot_solver_stats` — iterations + min-env-distance (log scale, with the
  ``dist_eps`` safety line) vs time (rqp_example.py:183-200, rqp_plots.py:393-467).
- :func:`plot_xy_trajectory` — top-down trajectory through the forest with tree
  footprints (rqp_plots.py:173-390, simplified: no mesh snapshots).
- :func:`plot_convergence_rates` — DD vs C-ADMM residual-vs-iteration curves with
  min/max bands (test_rqpcontrollers.py:101-156).
"""

from __future__ import annotations

import numpy as np


def _mpl():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def plot_tracking_errors(logs: dict, path: str):
    plt = _mpl()
    fig, ax = plt.subplots(2, 1, figsize=(3.54, 3.54), dpi=200, sharex=True,
                           layout="constrained")
    T = logs["T"]
    x_err = np.asarray(logs["x_err_seq"])
    v_err = np.asarray(logs["v_err_seq"])
    t = np.linspace(0.0, T, len(x_err))
    ax[0].plot(t, x_err, "-b", lw=1)
    ax[0].set_ylabel(r"$\|x_l - x_{ref}\|$ [m]")
    ax[1].plot(t, v_err, "-b", lw=1)
    ax[1].set_ylabel(r"$\|v_l - v_{ref}\|$ [m/s]")
    ax[1].set_xlabel("t [s]")
    fig.savefig(path)
    plt.close(fig)


def plot_solver_stats(logs: dict, path: str, dist_eps: float = 0.1):
    plt = _mpl()
    fig, ax = plt.subplots(2, 1, figsize=(3.54, 3.54), dpi=200, sharex=True,
                           layout="constrained")
    T = logs["T"]
    iters = np.asarray(logs["iter_seq"])
    t = np.linspace(0.0, T, len(iters))
    ax[0].plot(t, iters, "-b", lw=1)
    ax[0].set_ylabel("solver iterations")
    d = np.asarray(logs["min_env_dist_seq"]) + 1e-6
    t = np.linspace(0.0, T, len(d))
    ax[1].plot(t, d, "-b", lw=1)
    ax[1].axhline(dist_eps, color="r", ls="--", lw=0.8,
                  label=r"$\epsilon_d$")
    ax[1].set_yscale("log")
    ax[1].set_ylabel("min env dist [m]")
    ax[1].set_xlabel("t [s]")
    ax[1].legend()
    fig.savefig(path)
    plt.close(fig)


# Paper-figure palette (reference rqp_plots.py:36-41).
_GRASS_COLOR = "#70AB94"
_BARK_COLOR = "#694B37"
_MESH_COLOR = "#FF22DD"
_QUADROTOR_COLOR = "#1590A0"
_PAYLOAD_COLOR = "#D70E36"
_VISIONCONE_COLOR = "#A8AEAC"
_SAVE_DPI = 600  # reference uses >= 600 for the paper PNGs (:32).

# Key-frame fractions per controller type (reference :245-250).
_KEY_FRAMES = {
    "centralized": (0.5,),
    "dual-decomposition": (0.16, 0.55),
    "consensus-admm": (0.19, 0.51, 0.72),
}


def _draw_capsule_outline(ax, c1, c2, radius, **kwargs):
    """2-D stadium outline of the braking capsule (reference ``_draw_capsule``,
    rqp_plots.py:150-170)."""
    height = float(np.linalg.norm(c2 - c1))
    if height < 1e-9:
        theta = np.linspace(0.0, 2 * np.pi, 100)
        ax.plot(radius * np.cos(theta) + c1[0],
                radius * np.sin(theta) + c1[1], **kwargs)
        return
    d = (c2 - c1) / height
    ang = np.arctan2(d[0], -d[1])  # angle of the left-hand orthogonal.
    theta1 = np.linspace(ang, ang + np.pi, 50)
    theta2 = np.linspace(ang + np.pi, ang + 2 * np.pi, 50)
    x = np.concatenate([
        np.stack([c1[0] + radius * np.cos(theta1),
                  c1[1] + radius * np.sin(theta1)], axis=1),
        np.stack([c2[0] + radius * np.cos(theta2),
                  c2[1] + radius * np.sin(theta2)], axis=1),
    ])
    x = np.concatenate([x, x[:1]])
    ax.plot(x[:, 0], x[:, 1], **kwargs)


def plot_xy_trajectory(
    logs: dict,
    path: str,
    bark_radius: float = 0.3,
    params=None,
    collision=None,
    controller_type: str = "consensus-admm",
    vision_radius: float | None = None,
    vision_cone_ang: float | None = None,
    mountain_center=(30.0, 0.0),
    mountain_radius: float = 25.0,
    key_frames=None,
    dpi: int = _SAVE_DPI,
):
    """Top-down paper figure (reference ``_plot_xy_trajectory``,
    rqp_plots.py:173-390): hill outline, tree footprints, dashed payload
    trajectory, and — at the controller-specific key frames — the payload
    polygon, per-quad footprints, the braking collision capsule, and the
    vision region (full disc for the centralized controller, per-agent wedges
    for the distributed ones).

    The overlays need system geometry: pass ``params`` (RQPParams: attachment
    points ``r``) and ``collision`` (RQPCollision: quad radius, collision
    radius, max deceleration). Without them, only trajectory + forest are
    drawn (the round-1 behavior).
    """
    plt = _mpl()
    from matplotlib import patches

    fig, ax = plt.subplots(figsize=(3.54, 2.0), dpi=200, layout="constrained")
    for side in ("top", "bottom", "left", "right"):
        ax.spines[side].set_visible(False)

    # Hill outline + forest (reference :206-232).
    theta = np.linspace(0.0, 2 * np.pi, 100)
    ax.plot(mountain_radius * np.cos(theta) + mountain_center[0],
            mountain_radius * np.sin(theta) + mountain_center[1],
            ls="--", lw=1, color=_GRASS_COLOR)
    if "tree_pos" in logs:
        for i, p in enumerate(np.asarray(logs["tree_pos"])):
            ax.add_patch(patches.Circle(
                (p[0], p[1]), bark_radius, fc=_BARK_COLOR, ec="black", lw=1.0,
                label="trees" if i == 0 else None,
            ))

    # Payload trajectory (reference :233-239).
    xl = np.asarray(logs["state_seq"]["xl"])
    ax.plot(xl[:, 0], xl[:, 1], ls="--", lw=1, color="black", label=r"$x_L$")

    # Key-frame overlays (reference :240-358).
    if params is not None and collision is not None:
        Rl = np.asarray(logs["state_seq"]["Rl"])
        vl = np.asarray(logs["state_seq"]["vl"])
        r = np.asarray(params.r)  # (n, 3) agent-leading layout.
        frames = key_frames if key_frames is not None else \
            _KEY_FRAMES.get(controller_type, (0.5,))
        n_steps = xl.shape[0]
        for k, frac in enumerate(frames):
            i = min(int(frac * n_steps), n_steps - 1)
            first = k == 0
            xq = xl[i][None, :] + np.einsum("ab,nb->na", Rl[i], r)  # (n, 3)
            ax.add_patch(patches.Polygon(
                xq[:, :2], closed=True, fc=_PAYLOAD_COLOR, ec="black", lw=0.5,
                label="payload" if first else None,
            ))
            for j in range(xq.shape[0]):
                ax.add_patch(patches.Circle(
                    xq[j, :2], collision.quadrotor_radius,
                    fc=_QUADROTOR_COLOR, ec="black", lw=0.5, alpha=0.75,
                    label="quadrotor" if first and j == 0 else None,
                ))
            # Braking collision capsule (reference :289-308).
            c1 = xl[i]
            c2 = xl[i] + 0.5 * np.linalg.norm(vl[i]) \
                / collision.max_deceleration * vl[i]
            _draw_capsule_outline(
                ax, c1[:2], c2[:2], collision.collision_radius,
                ls="--", lw=1, color=_MESH_COLOR,
                label="collision capsule" if first else None,
            )
            # Vision regions (reference :309-358).
            vr = vision_radius if vision_radius is not None \
                else collision.collision_radius + 5.0
            if controller_type == "centralized":
                ax.add_patch(patches.Circle(
                    c1[:2], vr, fc=_VISIONCONE_COLOR, ec="none", alpha=0.25,
                    label="vision region" if first else None,
                ))
            else:
                ang = vision_cone_ang if vision_cone_ang is not None \
                    else 100.0 * np.pi / 180.0
                for j in range(xq.shape[0]):
                    d = xq[j, :2] - xl[i, :2]
                    dir_ang = np.arctan2(d[1], d[0])
                    ax.add_patch(patches.Wedge(
                        xq[j, :2], vr,
                        (dir_ang - ang) * 180 / np.pi,
                        (dir_ang + ang) * 180 / np.pi,
                        fc=_VISIONCONE_COLOR, ec="none", alpha=0.25,
                        label="vision region" if first and j == 0 else None,
                    ))

    ax.legend(loc="upper right", fontsize=8, framealpha=1.0, ncol=2,
              fancybox=False, edgecolor="black", labelspacing=0.15)
    ax.tick_params(axis="both", which="both", bottom=False, top=False,
                   left=False, right=False, labelbottom=False, labelleft=False)
    ax.margins(0.05, 0.05)
    ax.axis("equal")
    fig.savefig(path, dpi=dpi)
    plt.close(fig)


CONTROLLER_TYPE = {
    "centralized": "centralized",
    "cadmm": "consensus-admm",
    "dd": "dual-decomposition",
}


def save_figures(logs: dict, out: str, controller: str, params=None,
                 collision=None, dist_eps: float = 0.1):
    """Render the full reference figure set from one rollout log: tracking
    errors, solver stats, the 600-dpi xy trajectory (with key-frame overlays
    when ``params``/``collision`` are given), and the 600-dpi min-dist plot.
    ``out`` is a directory or filename prefix; ``controller`` is the CLI name
    (centralized/cadmm/dd). Shared by examples/rqp_forest.py and
    examples/replay.py."""
    import os

    prefix = os.path.join(out, "") if os.path.isdir(out) else out
    ctype = CONTROLLER_TYPE[controller]
    plot_tracking_errors(logs, f"{prefix}tracking_{controller}.png")
    plot_solver_stats(logs, f"{prefix}stats_{controller}.png", dist_eps)
    plot_xy_trajectory(
        logs, f"{prefix}xy_{controller}.png",
        params=params, collision=collision, controller_type=ctype,
    )
    plot_min_dist(logs, f"{prefix}min_dist_{controller}.png", dist_eps)


def plot_min_dist(logs: dict, path: str, dist_eps: float = 0.1,
                  t_final_frac: float = 0.85, dpi: int = _SAVE_DPI):
    """Min-obstacle-distance paper figure (reference ``_plot_min_dist``,
    rqp_plots.py:393-467): log-scale distance vs time with the ``eps_d``
    safety line, saved at >= 600 dpi."""
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(3.54, 2.0), dpi=200, layout="constrained")
    ax.spines["top"].set_visible(False)
    ax.spines["right"].set_visible(False)
    T = logs["T"]
    d = np.asarray(logs["min_env_dist_seq"])
    t = np.linspace(0.0, T, len(d))
    ax.plot(t, d, "-b", lw=1,
            label=r"$\min_j\ \mathrm{dist}(CC(x_r(t)), \mathcal{O}_j)$")
    ax.plot(t, dist_eps * np.ones_like(t), "--k", lw=1, label=r"$\epsilon_d$")
    ax.legend(loc="upper right", fontsize=8, framealpha=0.5, fancybox=False,
              edgecolor="black", labelspacing=0.15)
    ax.set_yscale("log")
    ax.set_xlim([0.0, t_final_frac * T])
    ax.set_xlabel("time (s)", fontsize=8)
    ax.set_ylabel("minimum distance (m)", fontsize=8)
    ax.tick_params(axis="both", which="major", labelsize=8)
    ax.margins(0.05, 0.05)
    fig.savefig(path, dpi=dpi)
    plt.close(fig)


def plot_convergence_rates(err_seqs: dict[str, np.ndarray], path: str):
    """``err_seqs`` maps label -> (num_samples, num_iters) residual curves
    (NaN-padded); plots mean with min/max band per solver on a log scale."""
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(3.54, 2.8), dpi=200, layout="constrained")
    colors = {"C-ADMM": "tab:blue", "DD": "tab:orange"}
    for label, errs in err_seqs.items():
        errs = np.asarray(errs)
        # nanmean/nanmin warn on all-NaN columns (tail iterations no sample
        # reached); reduce only columns with at least one finite entry.
        has_data = np.any(~np.isnan(errs), axis=0)
        mean = np.full(errs.shape[1], np.nan)
        lo = np.full(errs.shape[1], np.nan)
        hi = np.full(errs.shape[1], np.nan)
        mean[has_data] = np.nanmean(errs[:, has_data], axis=0)
        lo[has_data] = np.nanmin(errs[:, has_data], axis=0)
        hi[has_data] = np.nanmax(errs[:, has_data], axis=0)
        it = np.arange(1, errs.shape[1] + 1)
        valid = ~np.isnan(mean)
        c = colors.get(label)
        ax.plot(it[valid], mean[valid], lw=1.2, label=label, color=c)
        ax.fill_between(it[valid], lo[valid], hi[valid], alpha=0.2, color=c)
    ax.set_yscale("log")
    ax.set_xlabel("iteration")
    ax.set_ylabel("consensus residual [N]")
    ax.legend()
    fig.savefig(path)
    plt.close(fig)
