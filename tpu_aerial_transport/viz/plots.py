"""Matplotlib figures from rollout logs — the reference's ``example/rqp_plots.py``
paper-figure surface re-pointed at the npz/dict log schema from
``harness.rollout.logs_to_dict``.

All host-side; never inside the compiled path. Figures:
- :func:`plot_tracking_errors` — position/velocity error vs time
  (rqp_example.py:167-181).
- :func:`plot_solver_stats` — iterations + min-env-distance (log scale, with the
  ``dist_eps`` safety line) vs time (rqp_example.py:183-200, rqp_plots.py:393-467).
- :func:`plot_xy_trajectory` — top-down trajectory through the forest with tree
  footprints (rqp_plots.py:173-390, simplified: no mesh snapshots).
- :func:`plot_convergence_rates` — DD vs C-ADMM residual-vs-iteration curves with
  min/max bands (test_rqpcontrollers.py:101-156).
"""

from __future__ import annotations

import numpy as np


def _mpl():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def plot_tracking_errors(logs: dict, path: str):
    plt = _mpl()
    fig, ax = plt.subplots(2, 1, figsize=(3.54, 3.54), dpi=200, sharex=True,
                           layout="constrained")
    T = logs["T"]
    x_err = np.asarray(logs["x_err_seq"])
    v_err = np.asarray(logs["v_err_seq"])
    t = np.linspace(0.0, T, len(x_err))
    ax[0].plot(t, x_err, "-b", lw=1)
    ax[0].set_ylabel(r"$\|x_l - x_{ref}\|$ [m]")
    ax[1].plot(t, v_err, "-b", lw=1)
    ax[1].set_ylabel(r"$\|v_l - v_{ref}\|$ [m/s]")
    ax[1].set_xlabel("t [s]")
    fig.savefig(path)
    plt.close(fig)


def plot_solver_stats(logs: dict, path: str, dist_eps: float = 0.1):
    plt = _mpl()
    fig, ax = plt.subplots(2, 1, figsize=(3.54, 3.54), dpi=200, sharex=True,
                           layout="constrained")
    T = logs["T"]
    iters = np.asarray(logs["iter_seq"])
    t = np.linspace(0.0, T, len(iters))
    ax[0].plot(t, iters, "-b", lw=1)
    ax[0].set_ylabel("solver iterations")
    d = np.asarray(logs["min_env_dist_seq"]) + 1e-6
    t = np.linspace(0.0, T, len(d))
    ax[1].plot(t, d, "-b", lw=1)
    ax[1].axhline(dist_eps, color="r", ls="--", lw=0.8,
                  label=r"$\epsilon_d$")
    ax[1].set_yscale("log")
    ax[1].set_ylabel("min env dist [m]")
    ax[1].set_xlabel("t [s]")
    ax[1].legend()
    fig.savefig(path)
    plt.close(fig)


def plot_xy_trajectory(logs: dict, path: str, bark_radius: float = 0.3):
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(3.54, 3.54), dpi=200, layout="constrained")
    xl = np.asarray(logs["state_seq"]["xl"])
    ax.plot(xl[:, 0], xl[:, 1], "-b", lw=1, label="payload")
    if "tree_pos" in logs:
        for p in np.asarray(logs["tree_pos"]):
            ax.add_patch(plt.Circle((p[0], p[1]), bark_radius, color="saddlebrown",
                                    alpha=0.7))
    ax.set_aspect("equal")
    ax.set_xlabel("x [m]")
    ax.set_ylabel("y [m]")
    ax.legend(loc="upper left")
    fig.savefig(path)
    plt.close(fig)


def plot_convergence_rates(err_seqs: dict[str, np.ndarray], path: str):
    """``err_seqs`` maps label -> (num_samples, num_iters) residual curves
    (NaN-padded); plots mean with min/max band per solver on a log scale."""
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(3.54, 2.8), dpi=200, layout="constrained")
    colors = {"C-ADMM": "tab:blue", "DD": "tab:orange"}
    for label, errs in err_seqs.items():
        errs = np.asarray(errs)
        with np.errstate(all="ignore"):
            mean = np.nanmean(errs, axis=0)
            lo = np.nanmin(errs, axis=0)
            hi = np.nanmax(errs, axis=0)
        it = np.arange(1, errs.shape[1] + 1)
        valid = ~np.isnan(mean)
        c = colors.get(label)
        ax.plot(it[valid], mean[valid], lw=1.2, label=label, color=c)
        ax.fill_between(it[valid], lo[valid], hi[valid], alpha=0.2, color=c)
    ax.set_yscale("log")
    ax.set_xlabel("iteration")
    ax.set_ylabel("consensus residual [N]")
    ax.legend()
    fig.savefig(path)
    plt.close(fig)
