"""Host-side visualization: matplotlib paper figures (reference
``example/rqp_plots.py``). Never inside the compiled path."""

from tpu_aerial_transport.viz import plots  # noqa: F401
