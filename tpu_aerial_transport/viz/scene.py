"""3-D scene rendering and trajectory replay (reference ``RQPVisualizer`` +
``rqp_plots._visualization`` / ``_snapshot``, system/rigid_quadrotor_payload.py:313-418
and example/rqp_plots.py:44-147).

The reference renders through meshcat (a websocket three.js viewer). meshcat is
not part of this image, so the default backend is matplotlib 3-D snapshots —
same scene content (payload hull, quadrotor positions/attitudes, forest, ghost
snapshots), rendered to PNG frames host-side. If meshcat IS importable, the
:class:`MeshcatBackend` provides the reference's live-viewer path with the same
call surface.
"""

from __future__ import annotations

import os

import numpy as np

QUAD_ARM = 0.15  # [m] drawn arm length for the quadrotor cross.


def _mpl():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def draw_snapshot(ax, params, payload_vertices, state, forest=None, alpha=1.0):
    """Draw one scene state into a 3-D matplotlib axis.

    ``state`` needs ``xl, Rl`` and optionally per-agent ``R``; agent positions
    are the attachment points ``xl + Rl r_i`` (rigid attachment, RQP model).
    ``alpha < 1`` renders a ghost (multi-snapshot scenes, rqp_plots.py:112-147).
    """
    from mpl_toolkits.mplot3d.art3d import Poly3DCollection

    xl = np.asarray(state.xl)
    Rl = np.asarray(state.Rl)
    r = np.asarray(params.r)
    n = r.shape[0]

    # Payload hull (world frame).
    verts = np.asarray(payload_vertices) @ Rl.T + xl
    try:
        from scipy.spatial import ConvexHull

        hull = ConvexHull(verts)
        faces = [verts[s] for s in hull.simplices]
        ax.add_collection3d(
            Poly3DCollection(faces, alpha=0.3 * alpha, facecolor="tab:gray")
        )
    except Exception:
        ax.scatter(*verts.T, color="tab:gray", alpha=alpha, s=4)

    # Quadrotors: attachment points + body-frame arms.
    quad_pos = xl + r @ Rl.T
    ax.scatter(*quad_pos.T, color="tab:blue", s=18 * alpha, alpha=alpha)
    if hasattr(state, "R") and state.R is not None:
        R = np.asarray(state.R)
        for i in range(n):
            for axis in (R[i, :, 0], R[i, :, 1]):
                seg = np.stack(
                    [quad_pos[i] - QUAD_ARM * axis, quad_pos[i] + QUAD_ARM * axis]
                )
                ax.plot(*seg.T, color="k", lw=0.8, alpha=alpha)

    if forest is not None:
        num = int(forest.num_trees)
        pos = np.asarray(forest.tree_pos[:num])
        h = forest.bark_height
        for p in pos:
            ax.plot([p[0], p[0]], [p[1], p[1]], [p[2] - h / 2, p[2] + h / 2],
                    color="saddlebrown", lw=2, alpha=0.6)


def draw_pmrl_snapshot(ax, params, payload_vertices, state, alpha=1.0):
    """PMRL scene: payload hull + rigid links (cylinders in the reference,
    ``PMRLVisualizer``, point_mass_rigid_link.py:257-397) + point-mass robots at
    ``xl + Rl r_i + L_i q_i``."""
    xl = np.asarray(state.xl)
    Rl = np.asarray(state.Rl)
    r = np.asarray(params.r)
    L = np.asarray(params.L)
    q = np.asarray(state.q)

    draw_snapshot(ax, params, payload_vertices,
                  type("S", (), {"xl": xl, "Rl": Rl, "R": None})(), alpha=alpha)
    attach = xl + r @ Rl.T
    robots = attach + q * L[:, None]
    ax.scatter(*robots.T, color="tab:red", s=20 * alpha, alpha=alpha)
    for i in range(r.shape[0]):
        seg = np.stack([attach[i], robots[i]])
        ax.plot(*seg.T, color="gray", lw=1.2, alpha=alpha)


def render_frames(
    logs: dict,
    params,
    payload_vertices,
    out_dir: str,
    forest=None,
    stride: int = 25,
    follow: bool = True,
):
    """Replay a rollout log as PNG frames (the reference's meshcat replay with
    follow camera, rqp_plots.py:44-109; camera smoothing via a simple windowed
    mean instead of savgol). Returns the frame paths."""
    plt = _mpl()
    os.makedirs(out_dir, exist_ok=True)
    xl_seq = np.asarray(logs["state_seq"]["xl"])
    Rl_seq = np.asarray(logs["state_seq"]["Rl"])
    R_seq = np.asarray(logs["state_seq"]["R"])

    # Smoothed follow-camera track.
    k = 25
    pad = np.pad(xl_seq, ((k, k), (0, 0)), mode="edge")
    smooth = np.stack([
        pad[i : i + 2 * k + 1].mean(axis=0) for i in range(len(xl_seq))
    ])

    class _S:
        pass

    paths = []
    for fi, t in enumerate(range(0, len(xl_seq), stride)):
        fig = plt.figure(figsize=(5, 4), dpi=120)
        ax = fig.add_subplot(projection="3d")
        s = _S()
        s.xl, s.Rl, s.R = xl_seq[t], Rl_seq[t], R_seq[t]
        draw_snapshot(ax, params, payload_vertices, s, forest)
        c = smooth[t] if follow else xl_seq[0]
        ax.set_xlim(c[0] - 4, c[0] + 4)
        ax.set_ylim(c[1] - 4, c[1] + 4)
        ax.set_zlim(max(0, c[2] - 3), c[2] + 3)
        ax.set_xlabel("x")
        ax.set_ylabel("y")
        path = os.path.join(out_dir, f"frame_{fi:04d}.png")
        fig.savefig(path)
        plt.close(fig)
        paths.append(path)
    return paths


def render_ghost_snapshot(
    logs: dict, params, payload_vertices, path: str, times: list[int],
    forest=None,
):
    """Multi-ghost single figure (reference ``_snapshot``, rqp_plots.py:112-147):
    overlay the system at several log indices with increasing opacity."""
    plt = _mpl()
    fig = plt.figure(figsize=(6, 4.5), dpi=150)
    ax = fig.add_subplot(projection="3d")
    xl_seq = np.asarray(logs["state_seq"]["xl"])
    Rl_seq = np.asarray(logs["state_seq"]["Rl"])
    R_seq = np.asarray(logs["state_seq"]["R"])

    class _S:
        pass

    for k, t in enumerate(times):
        s = _S()
        s.xl, s.Rl, s.R = xl_seq[t], Rl_seq[t], R_seq[t]
        alpha = 0.3 + 0.7 * (k + 1) / len(times)
        draw_snapshot(ax, params, payload_vertices, s, forest, alpha=alpha)
    ax.plot(*xl_seq[: max(times) + 1].T, color="tab:blue", lw=0.8, ls="--")
    lo = xl_seq[times].min(axis=0) - 3
    hi = xl_seq[times].max(axis=0) + 3
    ax.set_xlim(lo[0], hi[0])
    ax.set_ylim(lo[1], hi[1])
    ax.set_zlim(max(0, lo[2]), hi[2])
    fig.savefig(path)
    plt.close(fig)


class MeshcatBackend:
    """Live three.js viewer path, used only when meshcat is installed (the
    reference's default backend). Mirrors ``RQPVisualizer``'s scene graph:
    payload hull mesh, per-quad bodies, forest cylinders."""

    def __init__(self):
        import meshcat  # noqa: F401 — optional dependency.

        self.vis = meshcat.Visualizer()

    def open(self):
        self.vis.open()
        return self

    def visualize_env(self, forest):
        import meshcat.geometry as gm
        import meshcat.transformations as tf

        num = int(forest.num_trees)
        for i, p in enumerate(np.asarray(forest.tree_pos[:num])):
            self.vis[f"bark_{i}"].set_object(
                gm.Cylinder(height=forest.bark_height, radius=forest.bark_radius)
            )
            T = tf.translation_matrix(p)
            # meshcat cylinders are y-up; rotate to z-up.
            T[:3, :3] = np.array([[1, 0, 0], [0, 0, -1], [0, 1, 0]], float).T
            self.vis[f"bark_{i}"].set_transform(T)

    def update(self, params, state, prefix: str = ""):
        import meshcat.geometry as gm
        import meshcat.transformations as tf

        xl = np.asarray(state.xl)
        Rl = np.asarray(state.Rl)
        T = tf.translation_matrix(xl)
        T[:3, :3] = Rl
        self.vis[prefix + "payload"].set_transform(T)
        r = np.asarray(params.r)
        R = np.asarray(state.R)
        if not hasattr(self, "_objs"):
            self._objs = set()
        for i in range(r.shape[0]):
            Ti = tf.translation_matrix(xl + Rl @ r[i])
            Ti[:3, :3] = R[i]
            name = prefix + f"quad_{i}"
            if name not in self._objs:
                self.vis[name].set_object(gm.Sphere(0.08))
                self._objs.add(name)
            self.vis[name].set_transform(Ti)
