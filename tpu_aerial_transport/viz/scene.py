"""3-D scene rendering and trajectory replay (reference ``RQPVisualizer`` +
``rqp_plots._visualization`` / ``_snapshot``, system/rigid_quadrotor_payload.py:313-418
and example/rqp_plots.py:44-147).

The reference renders through meshcat (a websocket three.js viewer). meshcat is
not part of this image, so the default backend is matplotlib 3-D snapshots —
same scene content (payload hull, quadrotor positions/attitudes, forest, ghost
snapshots), rendered to PNG frames host-side. If meshcat IS importable, the
:class:`MeshcatBackend` provides the reference's live-viewer path with the same
call surface.
"""

from __future__ import annotations

import os

import numpy as np

QUAD_ARM = 0.15  # [m] drawn arm length for the quadrotor cross.
# Force-arrow overlay constants (reference system/rigid_payload.py:26-30).
FORCE_SCALING = 1.0  # [m/N] arrow length per Newton.
FORCE_MIN_LENGTH = 0.05  # [m] floor so near-zero forces stay visible.
FORCE_TAIL_RADIUS = 0.01  # [m] arrow shaft cylinder radius.
FORCE_HEAD_BASE_RADIUS = 0.03  # [m] arrow head cone base radius.
FORCE_HEAD_LENGTH = 0.1  # [m] arrow head cone height.
CONE_HEIGHT = 2.0  # [m] foliage cone on each bark (reference env_forest.py:24).
CONE_RADIUS = 1.0


def quadrotor_mesh(arm: float = 0.15, rotor_radius: float = 0.08,
                   body: float = 0.06, segments: int = 8):
    """Procedural quadrotor mesh ``(verts (V, 3), faces (F, 3))`` — the
    replacement for the reference's ``objs/quadrotor.obj`` asset
    (rigid_quadrotor_payload.py:17,308): a box body, four diagonal arms, and
    four rotor discs. Built from primitives rather than shipping a mesh file.
    """
    verts: list[np.ndarray] = []
    faces: list[list[int]] = []

    def add_box(center, half):
        i0 = len(verts)
        for dx in (-1, 1):
            for dy in (-1, 1):
                for dz in (-1, 1):
                    verts.append(center + half * np.array([dx, dy, dz]))
        quads = [(0, 1, 3, 2), (4, 6, 7, 5), (0, 4, 5, 1),
                 (2, 3, 7, 6), (0, 2, 6, 4), (1, 5, 7, 3)]
        for a, b, c, d in quads:
            faces.append([i0 + a, i0 + b, i0 + c])
            faces.append([i0 + a, i0 + c, i0 + d])

    def add_disc(center, radius, z):
        i0 = len(verts)
        verts.append(center + np.array([0.0, 0.0, z]))
        for k in range(segments):
            a = 2 * np.pi * k / segments
            verts.append(center + np.array(
                [radius * np.cos(a), radius * np.sin(a), z]
            ))
        for k in range(segments):
            faces.append([i0, i0 + 1 + k, i0 + 1 + (k + 1) % segments])

    add_box(np.zeros(3), np.array([body, body, body * 0.5]))
    for sx, sy in ((1, 1), (1, -1), (-1, 1), (-1, -1)):
        d = np.array([sx, sy, 0.0]) / np.sqrt(2.0)
        add_box(d * arm / 2, np.array([arm / 2 * abs(d[0]) + 0.01,
                                       arm / 2 * abs(d[1]) + 0.01, 0.008]))
        add_disc(d * arm, rotor_radius, 0.02)
    return np.asarray(verts), np.asarray(faces, np.int32)


def draw_forest_3d(ax, forest, ground: bool = True, max_trees: int | None = None):
    """Forest scene elements for the 3-D matplotlib backend (reference
    ``Forest.visualize_env``, env_forest.py:90-137): bark cylinders (drawn as
    thick lines), green foliage cones, the ground plane, and the spherical-cap
    mountain wireframe."""
    import numpy as _np

    num = int(forest.num_trees)
    if max_trees is not None:
        num = min(num, max_trees)
    pos = np.asarray(forest.tree_pos[:num])
    h = forest.bark_height
    for p in pos:
        ax.plot([p[0], p[0]], [p[1], p[1]], [p[2] - h / 2, p[2] + h / 2],
                color="saddlebrown", lw=2, alpha=0.8)
        # Foliage cone: a small triangle fan.
        tip = np.array([p[0], p[1], p[2] + h / 2 + CONE_HEIGHT])
        ring = [
            np.array([p[0] + CONE_RADIUS * np.cos(a),
                      p[1] + CONE_RADIUS * np.sin(a), p[2] + h / 2])
            for a in np.linspace(0, 2 * np.pi, 9)
        ]
        from mpl_toolkits.mplot3d.art3d import Poly3DCollection

        tris = [[tip, ring[k], ring[k + 1]] for k in range(8)]
        ax.add_collection3d(
            Poly3DCollection(tris, facecolor="forestgreen", alpha=0.5)
        )
    if ground:
        # Spherical-cap mountain surface (coarse) + flat ground ring.
        from tpu_aerial_transport.envs.forest import (
            MOUNTAIN_CENTER, MOUNTAIN_RADIUS,
        )

        th = _np.linspace(0, 2 * np.pi, 24)
        rr = _np.linspace(0, MOUNTAIN_RADIUS, 8)
        R, TH = _np.meshgrid(rr, th)
        X = MOUNTAIN_CENTER[0] + R * _np.cos(TH)
        Y = MOUNTAIN_CENTER[1] + R * _np.sin(TH)
        sr = float(forest.mountain_sphere_radius)
        cd = float(forest.mountain_center_depth)
        Z = _np.sqrt(_np.maximum(sr**2 - R**2, 0.0)) - cd
        Z = _np.maximum(Z, 0.0)
        ax.plot_wireframe(X, Y, Z, color="#70AB94", lw=0.4, alpha=0.5)


def _mpl():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def draw_snapshot(ax, params, payload_vertices, state, forest=None, alpha=1.0,
                  quad_mesh=False, forces=None,
                  force_scaling=FORCE_SCALING):
    """Draw one scene state into a 3-D matplotlib axis.

    ``state`` needs ``xl, Rl`` and optionally per-agent ``R``; agent positions
    are the attachment points ``xl + Rl r_i`` (rigid attachment, RQP model).
    ``alpha < 1`` renders a ghost (multi-snapshot scenes, rqp_plots.py:112-147).
    ``quad_mesh=True`` draws the full procedural quadrotor mesh instead of the
    cross-of-arms sketch. ``forces (n, 3)``: optional per-agent applied-force
    arrows from each agent (the reference's ``_DRAW_FORCE_ARROWS`` option,
    system/rigid_payload.py:25-30 / rigid_quadrotor_payload.py:25, default
    off there too); ``force_scaling`` is meters of arrow per Newton
    (reference ``_FORCE_SCALING``).
    """
    from mpl_toolkits.mplot3d.art3d import Poly3DCollection

    xl = np.asarray(state.xl)
    Rl = np.asarray(state.Rl)
    r = np.asarray(params.r)
    n = r.shape[0]

    # Payload hull (world frame).
    verts = np.asarray(payload_vertices) @ Rl.T + xl
    try:
        from scipy.spatial import ConvexHull

        hull = ConvexHull(verts)
        faces = [verts[s] for s in hull.simplices]
        ax.add_collection3d(
            Poly3DCollection(faces, alpha=0.3 * alpha, facecolor="tab:gray")
        )
    except Exception:
        ax.scatter(*verts.T, color="tab:gray", alpha=alpha, s=4)

    # Quadrotors: attachment points + body-frame arms (or the full procedural
    # mesh when ``quad_mesh=True`` — the reference's .obj-mesh path).
    quad_pos = xl + r @ Rl.T
    ax.scatter(*quad_pos.T, color="tab:blue", s=18 * alpha, alpha=alpha)
    if hasattr(state, "R") and state.R is not None:
        from mpl_toolkits.mplot3d.art3d import Poly3DCollection as _P3D

        R = np.asarray(state.R)
        if quad_mesh:
            mv, mf = quadrotor_mesh()
            for i in range(n):
                v = mv @ R[i].T + quad_pos[i]
                ax.add_collection3d(_P3D(
                    [v[f] for f in mf], facecolor="#1590A0",
                    alpha=0.6 * alpha, edgecolor="none",
                ))
        else:
            for i in range(n):
                for axis in (R[i, :, 0], R[i, :, 1]):
                    seg = np.stack([
                        quad_pos[i] - QUAD_ARM * axis,
                        quad_pos[i] + QUAD_ARM * axis,
                    ])
                    ax.plot(*seg.T, color="k", lw=0.8, alpha=alpha)

    if forces is not None:
        draw_force_arrows(ax, quad_pos, np.asarray(forces),
                          scaling=force_scaling, alpha=alpha)

    if forest is not None:
        draw_forest_3d(ax, forest)


def draw_force_arrows(ax, positions, forces, scaling=FORCE_SCALING,
                      alpha=1.0, color="tab:red"):
    """Per-agent applied-force arrows (reference ``_DRAW_FORCE_ARROWS``
    cylinder+cone pairs, system/rigid_payload.py:204-233, rendered here with
    matplotlib ``quiver``): one arrow per agent from its position along its
    applied force, length ``scaling`` m/N with the reference's
    ``_FORCE_MIN_LENGTH`` floor so near-zero forces stay visible."""
    positions = np.asarray(positions)
    forces = np.asarray(forces)
    norms = np.linalg.norm(forces, axis=-1)
    safe = np.where(norms > 1e-9, norms, 1.0)
    lengths = np.maximum(norms * scaling, FORCE_MIN_LENGTH)
    dirs = forces / safe[:, None]
    # Exactly-zero force: fall back to +z (the reference's default cylinder
    # orientation) so the min-length arrow is still drawn.
    z = np.zeros_like(dirs)
    z[:, 2] = 1.0
    dirs = np.where((norms > 1e-9)[:, None], dirs, z)
    vecs = dirs * lengths[:, None]
    ax.quiver(
        positions[:, 0], positions[:, 1], positions[:, 2],
        vecs[:, 0], vecs[:, 1], vecs[:, 2],
        color=color, alpha=alpha, lw=1.2, arrow_length_ratio=0.25,
    )


def draw_pmrl_snapshot(ax, params, payload_vertices, state, alpha=1.0):
    """PMRL scene: payload hull + rigid links (cylinders in the reference,
    ``PMRLVisualizer``, point_mass_rigid_link.py:257-397) + point-mass robots at
    ``xl + Rl r_i + L_i q_i``."""
    xl = np.asarray(state.xl)
    Rl = np.asarray(state.Rl)
    r = np.asarray(params.r)
    L = np.asarray(params.L)
    q = np.asarray(state.q)

    draw_snapshot(ax, params, payload_vertices,
                  type("S", (), {"xl": xl, "Rl": Rl, "R": None})(), alpha=alpha)
    attach = xl + r @ Rl.T
    robots = attach + q * L[:, None]
    ax.scatter(*robots.T, color="tab:red", s=20 * alpha, alpha=alpha)
    for i in range(r.shape[0]):
        seg = np.stack([attach[i], robots[i]])
        ax.plot(*seg.T, color="gray", lw=1.2, alpha=alpha)


def render_frames(
    logs: dict,
    params,
    payload_vertices,
    out_dir: str,
    forest=None,
    stride: int = 25,
    follow: bool = True,
    force_arrows: bool = False,
):
    """Replay a rollout log as PNG frames (the reference's meshcat replay
    with follow camera, rqp_plots.py:44-109; camera smoothing via
    :func:`smooth_camera_track` — the reference's savgol when scipy is
    present, windowed mean otherwise). ``force_arrows`` overlays the logged
    commanded forces per agent (the reference's ``_DRAW_FORCE_ARROWS``
    option; needs ``f_des_seq`` in the log — state-only log rates fall back
    to no arrows). Returns the frame paths."""
    plt = _mpl()
    os.makedirs(out_dir, exist_ok=True)
    xl_seq = np.asarray(logs["state_seq"]["xl"])
    Rl_seq = np.asarray(logs["state_seq"]["Rl"])
    R_seq = np.asarray(logs["state_seq"]["R"])
    f_seq = None
    if force_arrows and "f_des_seq" in logs:
        f_seq = np.asarray(logs["f_des_seq"])

    # Smoothed follow-camera track (reference savgol, rqp_plots.py:78).
    smooth = smooth_camera_track(xl_seq)

    class _S:
        pass

    paths = []
    for fi, t in enumerate(range(0, len(xl_seq), stride)):
        fig = plt.figure(figsize=(5, 4), dpi=120)
        ax = fig.add_subplot(projection="3d")
        s = _S()
        s.xl, s.Rl, s.R = xl_seq[t], Rl_seq[t], R_seq[t]
        draw_snapshot(ax, params, payload_vertices, s, forest,
                      forces=None if f_seq is None else f_seq[t])
        c = smooth[t] if follow else xl_seq[0]
        ax.set_xlim(c[0] - 4, c[0] + 4)
        ax.set_ylim(c[1] - 4, c[1] + 4)
        ax.set_zlim(max(0, c[2] - 3), c[2] + 3)
        ax.set_xlabel("x")
        ax.set_ylabel("y")
        path = os.path.join(out_dir, f"frame_{fi:04d}.png")
        fig.savefig(path)
        plt.close(fig)
        paths.append(path)
    return paths


def render_ghost_snapshot(
    logs: dict, params, payload_vertices, path: str, times: list[int],
    forest=None,
):
    """Multi-ghost single figure (reference ``_snapshot``, rqp_plots.py:112-147):
    overlay the system at several log indices with increasing opacity."""
    plt = _mpl()
    fig = plt.figure(figsize=(6, 4.5), dpi=150)
    ax = fig.add_subplot(projection="3d")
    xl_seq = np.asarray(logs["state_seq"]["xl"])
    Rl_seq = np.asarray(logs["state_seq"]["Rl"])
    R_seq = np.asarray(logs["state_seq"]["R"])

    class _S:
        pass

    for k, t in enumerate(times):
        s = _S()
        s.xl, s.Rl, s.R = xl_seq[t], Rl_seq[t], R_seq[t]
        alpha = 0.3 + 0.7 * (k + 1) / len(times)
        # Forest drawn once (first ghost) — re-drawing stacks translucent
        # foliage/mountain artists toward opaque and multiplies render time.
        draw_snapshot(ax, params, payload_vertices, s,
                      forest if k == 0 else None, alpha=alpha)
    ax.plot(*xl_seq[: max(times) + 1].T, color="tab:blue", lw=0.8, ls="--")
    lo = xl_seq[times].min(axis=0) - 3
    hi = xl_seq[times].max(axis=0) + 3
    ax.set_xlim(lo[0], hi[0])
    ax.set_ylim(lo[1], hi[1])
    ax.set_zlim(max(0, lo[2]), hi[2])
    fig.savefig(path)
    plt.close(fig)


_Z_UP = np.array([[1, 0, 0], [0, 0, -1], [0, 1, 0]], float).T  # y-up -> z-up.


def smooth_camera_track(xl_seq: np.ndarray, window: int = 51,
                        polyorder: int = 3) -> np.ndarray:
    """Smoothed follow-camera track over a payload trajectory — the
    reference's ``savgol_filter(xl, window, 3)`` (rqp_plots.py:78) when
    scipy is importable, else a centered windowed mean (same intent:
    low-pass the camera so it doesn't shake with the payload)."""
    xl_seq = np.asarray(xl_seq)
    window = min(window, len(xl_seq) - (len(xl_seq) + 1) % 2)  # <= T, T-odd.
    window -= 1 - window % 2  # force odd: savgol rejects even windows.
    if window < 5:
        return xl_seq.copy()
    try:
        from scipy.signal import savgol_filter

        return savgol_filter(xl_seq, window, min(polyorder, window - 1),
                             axis=0)
    except ImportError:
        k = window // 2
        pad = np.pad(xl_seq, ((k, k), (0, 0)), mode="edge")
        return np.stack([
            pad[i: i + 2 * k + 1].mean(axis=0) for i in range(len(xl_seq))
        ])


def _rotation_y_to(d: np.ndarray) -> np.ndarray:
    """Rotation taking the +y axis (meshcat's cylinder axis) onto unit ``d``
    by the minimal rotation (Rodrigues about y x d); antipodal -y falls back
    to a pi flip about x."""
    y = np.array([0.0, 1.0, 0.0])
    c = float(y @ d)
    if c < -1.0 + 1e-12:
        return np.diag([1.0, -1.0, -1.0])
    v = np.cross(y, d)
    vx = np.array([[0, -v[2], v[1]], [v[2], 0, -v[0]], [-v[1], v[0], 0]])
    return np.eye(3) + vx + vx @ vx / (1.0 + c)


class MeshcatBackend:
    """Live three.js viewer path, used only when meshcat is installed (the
    reference's default backend). Mirrors ``RQPVisualizer``'s scene graph
    (rigid_quadrotor_payload.py:313-418): payload hull mesh, per-quad
    quadrotor meshes (procedural, replacing objs/quadrotor.obj), and the full
    forest scene — bark cylinders, foliage cones, ground plane, mountain —
    from ``Forest.visualize_env`` (env_forest.py:90-137). ``replay`` drives
    the smoothed follow camera of ``rqp_plots._visualization`` (:44-109)."""

    def __init__(self):
        import meshcat  # noqa: F401 — optional dependency.

        self.vis = meshcat.Visualizer()
        self._objs: set[str] = set()

    def open(self):
        self.vis.open()
        return self

    def visualize_env(self, forest, ground_extent: float = 60.0):
        import meshcat.geometry as gm
        import meshcat.transformations as tf

        from tpu_aerial_transport.envs.forest import MOUNTAIN_CENTER

        # Ground plane (reference :115-121: a thin box).
        self.vis["ground"].set_object(
            gm.Box([2 * ground_extent, 2 * ground_extent, 0.02])
        )
        self.vis["ground"].set_transform(
            tf.translation_matrix([0.0, 0.0, -0.011])
        )
        # Mountain spherical cap, approximated as in the reference (:123-137)
        # by a sphere sunk below ground level. Center depth matches the
        # physics model (forest.ground_height) so the rendered surface is the
        # surface the terrain-following reference trajectory flies over.
        sr = float(forest.mountain_sphere_radius)
        cd = float(forest.mountain_center_depth)
        self.vis["mountain"].set_object(gm.Sphere(sr))
        self.vis["mountain"].set_transform(tf.translation_matrix(
            [MOUNTAIN_CENTER[0], MOUNTAIN_CENTER[1], -cd]
        ))
        num = int(forest.num_trees)
        for i, p in enumerate(np.asarray(forest.tree_pos[:num])):
            # Bark cylinder (:99-106).
            self.vis[f"bark_{i}"].set_object(
                gm.Cylinder(height=forest.bark_height, radius=forest.bark_radius)
            )
            T = tf.translation_matrix(p)
            T[:3, :3] = _Z_UP
            self.vis[f"bark_{i}"].set_transform(T)
            # Foliage cone on top (:107-114); meshcat Cylinder with zero top
            # radius is a cone, y-up like all meshcat cylinders.
            self.vis[f"cone_{i}"].set_object(gm.Cylinder(
                height=CONE_HEIGHT, radiusBottom=CONE_RADIUS, radiusTop=0.0
            ))
            Tc = tf.translation_matrix(
                p + np.array([0.0, 0.0, forest.bark_height / 2 + CONE_HEIGHT / 2])
            )
            Tc[:3, :3] = _Z_UP
            self.vis[f"cone_{i}"].set_transform(Tc)

    def _ensure_objects(self, params, payload_vertices, prefix: str):
        import meshcat.geometry as gm

        name = prefix + "payload"
        if name not in self._objs and payload_vertices is not None:
            try:
                from tpu_aerial_transport.utils.geometry import (
                    faces_from_vertex_rep,
                )

                verts = np.asarray(payload_vertices)
                self.vis[name].set_object(gm.TriangularMeshGeometry(
                    verts, faces_from_vertex_rep(verts)
                ))
                self._objs.add(name)
            except Exception:
                pass
        missing = [
            i for i in range(np.asarray(params.r).shape[0])
            if prefix + f"quad_{i}" not in self._objs
        ]
        if missing:  # build the procedural mesh only when actually needed.
            mv, mf = quadrotor_mesh()
            for i in missing:
                qn = prefix + f"quad_{i}"
                self.vis[qn].set_object(gm.TriangularMeshGeometry(mv, mf))
                self._objs.add(qn)

    def update(self, params, state, prefix: str = "", payload_vertices=None,
               forces=None):
        import meshcat.transformations as tf

        self._ensure_objects(params, payload_vertices, prefix)
        xl = np.asarray(state.xl)
        Rl = np.asarray(state.Rl)
        T = tf.translation_matrix(xl)
        T[:3, :3] = Rl
        self.vis[prefix + "payload"].set_transform(T)
        r = np.asarray(params.r)
        R = np.asarray(state.R)
        for i in range(r.shape[0]):
            Ti = tf.translation_matrix(xl + Rl @ r[i])
            Ti[:3, :3] = R[i]
            self.vis[prefix + f"quad_{i}"].set_transform(Ti)
        if forces is not None:
            self._update_force_arrows(
                params, xl, Rl, np.asarray(forces), prefix
            )

    def _update_force_arrows(self, params, xl, Rl, forces, prefix: str = ""):
        """Solid cylinder+cone arrow per agent along its applied force
        (reference ``_DRAW_FORCE_ARROWS`` geometry, rigid_payload.py:204-233
        / :249-274): shaft length ``FORCE_SCALING`` m/N with the
        ``FORCE_MIN_LENGTH`` floor, fixed-size cone head at the tip, rooted
        at each attachment point. The shaft is re-created each frame (its
        height changes); the head is created once and re-posed."""
        import meshcat.geometry as gm
        import meshcat.transformations as tf

        r = np.asarray(params.r)
        for i in range(r.shape[0]):
            norm = float(np.linalg.norm(forces[i]))
            d = (forces[i] / norm if norm > 0
                 else np.array([0.0, 0.0, 1.0]))  # zero force: +z, as ref.
            length = max(norm * FORCE_SCALING, FORCE_MIN_LENGTH)
            root = xl + Rl @ r[i]
            rot = _rotation_y_to(d)
            tail = prefix + f"force_tail_{i}"
            head = prefix + f"force_head_{i}"
            # Both pieces are create-once/re-pose: the varying shaft length
            # rides in the transform as a y-axis (cylinder-axis) scale of a
            # unit-height cylinder — no per-frame geometry re-uploads on the
            # replay hot path.
            if tail not in self._objs:
                self.vis[tail].set_object(
                    gm.Cylinder(height=1.0, radius=FORCE_TAIL_RADIUS)
                )
                self._objs.add(tail)
            T = tf.translation_matrix(root + 0.5 * length * d)
            T[:3, :3] = rot @ np.diag([1.0, length, 1.0])
            self.vis[tail].set_transform(T)
            if head not in self._objs:
                self.vis[head].set_object(gm.Cylinder(
                    height=FORCE_HEAD_LENGTH,
                    radiusBottom=FORCE_HEAD_BASE_RADIUS, radiusTop=0.0,
                ))
                self._objs.add(head)
            Th = tf.translation_matrix(
                root + (length + 0.5 * FORCE_HEAD_LENGTH) * d
            )
            Th[:3, :3] = rot
            self.vis[head].set_transform(Th)

    def replay(self, logs: dict, params, payload_vertices=None, forest=None,
               speedup: float = 5.0, min_fps: float = 24.0,
               force_arrows: bool = False):
        """Replay a rollout log with the smoothed follow camera (reference
        ``_visualization``, rqp_plots.py:44-109: savgol-smoothed camera track,
        fast-forward, minimum frame pacing). ``force_arrows`` draws the solid
        cylinder+cone commanded-force arrows (needs ``f_des_seq`` in the
        log)."""
        import time as _time

        if forest is not None:
            self.visualize_env(forest)
        xl_seq = np.asarray(logs["state_seq"]["xl"])
        Rl_seq = np.asarray(logs["state_seq"]["Rl"])
        R_seq = np.asarray(logs["state_seq"]["R"])
        f_seq = (np.asarray(logs["f_des_seq"])
                 if force_arrows and "f_des_seq" in logs else None)
        dt_frame = logs["dt"] * logs["hl_rel_freq"] / speedup
        stride = max(1, int(round(1.0 / (min_fps * dt_frame))))
        smooth = smooth_camera_track(xl_seq)

        class _S:
            pass

        for t in range(0, len(xl_seq), stride):
            s = _S()
            s.xl, s.Rl, s.R = xl_seq[t], Rl_seq[t], R_seq[t]
            self.update(params, s, payload_vertices=payload_vertices,
                        forces=None if f_seq is None else f_seq[t])
            cam = smooth[t] + np.array([-3.0, -3.0, 1.5])
            try:
                self.vis.set_cam_pos(cam)
                self.vis.set_cam_target(smooth[t])
            except Exception:
                pass  # older meshcat versions lack camera helpers.
            _time.sleep(max(dt_frame * stride, 1.0 / min_fps))
