"""Flight-recorder observability layer.

Three parts (ISSUE 5):

- :mod:`obs.telemetry` — an in-jit :class:`TelemetryState` pytree threaded
  through the rollout / chunk carries that accumulates run-health metrics
  on-device (fallback-rung histogram, P² consensus-residual percentiles,
  safety-margin minima, quarantine counts, per-agent solve health);
  zero-cost when disabled (identical HLO, same contract as
  ``resilience.no_faults()``).
- :mod:`obs.phases` — the ``jax.named_scope`` phase vocabulary
  (``tat.<phase>``) annotating the algorithm phases across controllers,
  solver, rollouts and mesh, which ``tools/op_profile.py --by-phase``
  rolls XLA op self-time up to.
- :mod:`obs.export` — the ONE schema-versioned jsonl metrics-event writer
  (chunk boundaries via ``resilience.recovery.run_chunks``, bench sweep
  cells, on-demand rollout summaries), rendered by
  ``tools/run_health.py``.
- :mod:`obs.trace` (ISSUE 15) — host-side distributed request tracing:
  spans with trace/span/parent ids stitched from admission to device
  across serving, recovery, and pods; exported as additive
  ``trace_event`` metrics rows and as Chrome/Perfetto trace JSON
  (``tools/trace_view.py``), with a critical-path accountant
  decomposing each request's latency into queue/batch/device/harvest/
  retry segments. Deliberately NOT imported here: it is stdlib-only and
  must stay loadable from tools on hosts where importing jax (which
  ``obs.export`` pulls transitively) is the hazard being traced.
"""

from tpu_aerial_transport.obs import export, phases, telemetry  # noqa: F401
