"""Host-side metrics export: the ONE schema-versioned jsonl event writer.

Everything that leaves the device for a dashboard goes through
:class:`MetricsWriter`: chunk-boundary events from
``resilience.recovery.run_chunks`` (wall time + the chunk carry's
telemetry accumulator + a per-chunk log digest), per-cell events from
``bench.py --sweep``, and on-demand :func:`rollout_metrics` summaries
from any rollout's logs. ``tools/run_health.py`` renders the file;
``tools/ci_check.sh`` validates any ``artifacts/*.metrics.jsonl`` with
:func:`validate_file`.

Line format: one JSON object per line, append-only, fsync'd per event
(same durability contract as ``resilience.recovery.RunJournal``; a torn
final line from a crash mid-append is tolerated by readers). Every event
carries ``schema`` (:data:`SCHEMA_VERSION`), ``event`` (type tag) and
``ts`` (host unix time).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from tpu_aerial_transport.obs import telemetry as telemetry_mod

# v2: adds the ``backend_event`` type (backend-guard error/circuit/rung
# records from ``resilience.backend.BackendGuard``). v3: adds the
# ``aot_serve`` type (fallback-ladder rung + wall time per served
# entrypoint call, from ``aot.loader.serve_entry`` — which processes are
# still paying compiles). v4: adds the ``serving_event`` type (the
# continuous-batching scenario-serving tier's request/batch lifecycle:
# admission, rejection-with-reason, SLO timestamps, deadline-miss
# classification, per-boundary batch occupancy + rung — ``serving/``).
# v5: adds the ``trace_event`` type (distributed-tracing span rows from
# ``obs.trace.Tracer`` — request/queue/batch/device/guard/chunk spans
# with trace/span/parent ids, per-process track, and BOTH monotonic and
# wall-epoch timestamp pairs so ``tools/trace_view.py`` can stitch
# multi-process runs onto one clock).
# v6: adds the ``fleet_event`` type (the serving-fleet tier's replica
# lifecycle from ``serving/fleet.py`` + ``tools/fleet_local.py``:
# per-replica heartbeat leases, health-state transitions
# up→suspect→down→restarting(→quarantined), failover re-dispatch
# records, per-tenant admission throttling — the rows
# ``tools/run_health.py``'s fleet section renders).
# v7: adds the ``cache_hit`` serving_event kind (the content-addressed
# result cache, ``serving/cache.py``: a submit resolved from a prior
# COMPLETED result with no queue/lane/dispatch).
# v8: adds the ``session_event`` type (the closed-loop session tier,
# ``serving/sessions.py``: lease open/renew/evict/fence lifecycle,
# step-sequenced delta-state admission, per-step deadline degradation —
# the rows ``tools/run_health.py``'s sessions section renders) and the
# ``autoscale`` fleet_event kind (the hysteresis'd scale-up/down hint
# ``serving.fleet.AutoscaleSignal`` derives from queue-depth /
# occupancy / live-session telemetry).
# v9: adds the ``alert`` type (the live SLO engine, ``obs/live.py``:
# error-budget burn-rate alert fire/resolve transitions — per-tenant
# SLO name, severity fast/slow, the burn rate and window that tripped —
# journaled by ``SLOEngine`` and rendered by ``tools/fleet_console.py``
# and ``tools/run_health.py``'s alerts section).
# Files written at older versions remain valid (see
# :data:`SUPPORTED_SCHEMAS`) — each bump only ADDS vocabulary.
SCHEMA_VERSION = 9
SUPPORTED_SCHEMAS = frozenset({1, 2, 3, 4, 5, 6, 7, 8, 9})

# Event vocabulary -> required fields (beyond schema/event/ts). The
# validator rejects unknown event types and missing fields; extra fields
# are allowed (forward compatibility within a schema version).
EVENT_FIELDS: dict[str, tuple[str, ...]] = {
    "run_start": (),
    "chunk": ("chunk", "wall_s"),
    "retry": ("chunk", "attempt", "error"),
    "resume": ("start_chunk",),
    "preempted": ("chunk",),
    "done": ("chunks",),
    "bench_cell": ("cell", "value"),
    "rollout_summary": ("logs",),
    "backend_event": ("kind", "label"),
    "aot_serve": ("entry", "rung"),
    # Per-kind minimums live in SERVING_EVENT_KINDS (extra fields are
    # schema-legal — the reader contract is per-kind, rendered by
    # tools/run_health.py's serving SLO section).
    "serving_event": ("kind",),
    # One finished span (obs.trace.Span.to_row()): t1_* present for
    # spans, absent for instants; parent_id/attrs optional; track is the
    # per-process timeline the stitcher groups by.
    "trace_event": ("name", "trace_id", "span_id", "track",
                    "t0_mono", "t0_wall"),
    # Per-kind minimums live in FLEET_EVENT_KINDS (same convention as
    # serving_event; rendered by tools/run_health.py's fleet section).
    "fleet_event": ("kind",),
    # Per-kind minimums live in SESSION_EVENT_KINDS (closed-loop session
    # tier, serving/sessions.py; rendered by tools/run_health.py's
    # sessions section).
    "session_event": ("kind",),
    # Per-kind minimums live in ALERT_EVENT_KINDS (the live SLO
    # engine's burn-rate alert transitions, obs/live.py; rendered by
    # tools/fleet_console.py and run_health's alerts section).
    "alert": ("kind",),
}

# The serving/fleet KIND vocabularies: kind -> minimum extra keys beyond
# the event-level required fields. These are plain literals ON PURPOSE —
# Tier C's HL007 (analysis/hostrules.py) reads them from this module's
# AST without importing it, so every ``kind="..."`` emitted anywhere in
# the package is checked against this table at lint time, and
# :func:`validate_event` enforces the same minimums at runtime. Stable
# since each kind's introducing schema version (emitters always passed
# these keys); extending a kind's EXTRA fields needs no bump, a new kind
# or key does.
SERVING_EVENT_KINDS: dict[str, tuple[str, ...]] = {
    "submitted": ("request_id",),
    "rejected": ("request_id", "reason"),
    "admitted": ("request_id",),
    # Content-addressed result-cache hit (serving/cache.py): the request
    # resolves at submit with no queue/lane/dispatch; a ``completed``
    # event (with ``cached: true``) follows immediately.
    "cache_hit": ("request_id",),
    "completed": ("request_id",),
    "deadline_missed": ("request_id",),
    "batch_launch": ("batch_id",),
    "batch_boundary": ("batch_id",),
    "preempted": (),
    "resumed": (),
}
FLEET_EVENT_KINDS: dict[str, tuple[str, ...]] = {
    "heartbeat": ("replica",),
    "transition": ("replica",),
    "replica_error": ("replica",),
    "restart": ("replica",),
    "quarantine": ("replica",),
    "failover": ("request_id",),
    "tenant_rejected": ("tenant",),
    "duplicate_result": ("request_id",),
    # Hysteresis'd autoscaling hint (serving.fleet.AutoscaleSignal):
    # emitted when the confirmed hint CHANGES (scale_up/steady/
    # scale_down), never per observation — the no-flap contract.
    "autoscale": ("hint",),
}
SESSION_EVENT_KINDS: dict[str, tuple[str, ...]] = {
    # Lease lifecycle (serving/sessions.py SessionHost): open mints a
    # lease token with a TTL on the host's MONOTONIC clock; heartbeats
    # renew it (gap_s = time since the previous renewal); a silent
    # client is evicted at expiry and its token fenced; a fenced token
    # presented later is a structured rejection, never a lane write.
    "opened": ("session_id", "lease"),
    "renewed": ("session_id", "gap_s"),
    "evicted": ("session_id", "lease"),
    "fenced": ("session_id",),
    # Step-sequenced delta-state admission: an out-of-order or replayed
    # step_seq rejects structurally (stale_step); an accepted step
    # submits one internal chunk request and resolves step_done
    # (rung=served) or step_degraded (per-step deadline missed —
    # rung=hold_last, or no_control when nothing was ever served to
    # hold; missed classified in_queue/in_flight).
    "stale_step": ("session_id", "step_seq"),
    "step_submitted": ("session_id", "step_seq", "request_id"),
    "step_done": ("session_id", "step_seq", "rung"),
    "step_degraded": ("session_id", "step_seq", "rung", "missed"),
    "session_closed": ("session_id",),
    # Crash/failover lifecycle: sessions_resumed is one summary row per
    # SessionHost.resume (leases re-arm — the monotonic domain dies with
    # the process); rehomed is one row per session the fleet front
    # re-routes off a dead replica (same trace_id, PR-16 pattern).
    "sessions_resumed": ("live",),
    "rehomed": ("session_id", "to_replica"),
}
ALERT_EVENT_KINDS: dict[str, tuple[str, ...]] = {
    # Burn-rate alert lifecycle (obs/live.py SLOEngine): ``fire`` lands
    # when BOTH the fast and slow window burn rates clear a threshold
    # (severity "fast" pages, "slow" warns); ``resolve`` lands when the
    # firing pair's fast-window burn drops back under the slow
    # threshold. ``burn_rate`` is the fast-window burn at fire time;
    # ``window_s`` the fast window it was measured over; ``slo`` the
    # SLOSpec name the alert belongs to (per-tenant via the extra
    # ``tenant`` field).
    "fire": ("slo", "severity", "burn_rate", "window_s"),
    "resolve": ("slo", "fired_ts"),
}

# Which kind table governs each kinded event type (disjoint vocabularies
# — a fleet kind on a serving_event is drift, not forward compat).
EVENT_KIND_TABLES: dict[str, dict[str, tuple[str, ...]]] = {
    "serving_event": SERVING_EVENT_KINDS,
    "fleet_event": FLEET_EVENT_KINDS,
    "session_event": SESSION_EVENT_KINDS,
    "alert": ALERT_EVENT_KINDS,
}

# Events that did not exist before a given schema version: an event of
# this type stamped with an OLDER schema is a violation (the reader
# contract for that version never defined it).
EVENT_MIN_SCHEMA: dict[str, int] = {
    "backend_event": 2,
    "aot_serve": 3,
    "serving_event": 4,
    "trace_event": 5,
    "fleet_event": 6,
    "session_event": 8,
    "alert": 9,
}


def jsonl_append(path: str, obj: dict) -> None:
    """THE durable jsonl append (flush + fsync before returning): shared
    by :class:`MetricsWriter` and ``resilience.recovery.RunJournal`` so
    the durability contract lives in exactly one place."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(obj) + "\n")
        fh.flush()
        os.fsync(fh.fileno())


def jsonl_read(path: str) -> list[dict]:
    """All parseable lines; unparseable lines (the torn tail a crash
    mid-append leaves) are skipped — :func:`validate_file` surfaces torn
    INTERIOR lines as errors."""
    out = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


class MetricsWriter:
    """Append-only jsonl metrics writer (one per run/sweep)."""

    def __init__(self, path: str, meta: dict | None = None):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if meta is not None:
            self.emit("run_start", **meta)

    def emit(self, event: str, **fields) -> dict:
        if event not in EVENT_FIELDS:
            raise ValueError(
                f"unknown metrics event type {event!r} (known: "
                f"{sorted(EVENT_FIELDS)}); extend EVENT_FIELDS and bump "
                "SCHEMA_VERSION if readers must distinguish the new shape"
            )
        record = {"schema": SCHEMA_VERSION, "event": event,
                  "ts": time.time(), **fields}
        jsonl_append(self.path, record)
        return record


def read_events(path: str) -> list[dict]:
    """All parseable events (see :func:`jsonl_read`)."""
    return jsonl_read(path)


def validate_event(obj, lineno: int = 0) -> list[str]:
    """Schema errors for one decoded event (empty list = valid)."""
    where = f"line {lineno}: " if lineno else ""
    if not isinstance(obj, dict):
        return [f"{where}event is not a JSON object"]
    errs = []
    schema = obj.get("schema")
    if schema not in SUPPORTED_SCHEMAS:
        errs.append(
            f"{where}schema {schema!r} not in supported "
            f"{sorted(SUPPORTED_SCHEMAS)}"
        )
    event = obj.get("event")
    if event not in EVENT_FIELDS:
        errs.append(
            f"{where}unknown event type {event!r} "
            f"(known: {sorted(EVENT_FIELDS)})"
        )
    elif (schema in SUPPORTED_SCHEMAS
          and schema < EVENT_MIN_SCHEMA.get(event, 0)):
        errs.append(
            f"{where}event {event!r} requires schema >= "
            f"{EVENT_MIN_SCHEMA[event]}, got {schema}"
        )
    else:
        missing = [k for k in EVENT_FIELDS[event] if k not in obj]
        if missing:
            errs.append(f"{where}event {event!r} missing fields {missing}")
        kinds = EVENT_KIND_TABLES.get(event)
        kind = obj.get("kind")
        if kinds is not None and "kind" in obj:
            if kind not in kinds:
                errs.append(
                    f"{where}event {event!r} has unknown kind {kind!r} "
                    f"(known: {sorted(kinds)})"
                )
            else:
                kmissing = [k for k in kinds[kind] if k not in obj]
                if kmissing:
                    errs.append(
                        f"{where}event {event!r} kind {kind!r} missing "
                        f"keys {kmissing}"
                    )
    if not isinstance(obj.get("ts"), (int, float)):
        errs.append(f"{where}missing/non-numeric ts")
    return errs


def validate_file(path: str) -> list[str]:
    """Schema-validate a metrics jsonl. A torn FINAL line is tolerated
    (the state a crash mid-append leaves); torn interior lines and any
    schema violation are errors."""
    errs: list[str] = []
    with open(path, encoding="utf-8") as fh:
        lines = fh.readlines()
    for i, line in enumerate(lines, start=1):
        if not line.strip():
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            if i == len(lines):
                continue  # torn tail from a crash — readers skip it.
            errs.append(f"line {i}: unparseable JSON")
            continue
        errs.extend(validate_event(obj, i))
    return errs


def telemetry_event(tel, cfg=None) -> dict | None:
    """JSON-ready telemetry block from a :class:`TelemetryState` (device
    arrays or a host snapshot copy); None when ``tel`` is None."""
    if tel is None:
        return None
    return telemetry_mod.summary(tel, cfg)


def logs_summary(logs, quantiles=(0.5, 0.9, 0.99)) -> dict:
    """Exact (non-streaming) digest of a rollout's ``RQPLogStep`` pytree —
    any leading batch/time axes are flattened, so it works on single
    rollouts, vmapped batches, and per-chunk slices alike."""
    rung = np.asarray(logs.fallback_rung).reshape(-1)
    res = np.asarray(logs.solve_res).reshape(-1).astype(np.float64)
    res = res[np.isfinite(res)]
    # Exact per-step consensus-iteration digest (the solver-effort view;
    # the centralized controller reports -1 and is excluded). Additive
    # fields — schema-legal within the current version.
    it = np.asarray(logs.iters).reshape(-1)
    it = it[it >= 0]
    out = {
        "steps": int(rung.size),
        "rung_hist": [
            int(v) for v in np.bincount(
                np.clip(rung, 0, telemetry_mod.N_RUNGS - 1),
                minlength=telemetry_mod.N_RUNGS,
            )
        ],
        "min_env_dist": float(np.min(np.asarray(logs.min_env_dist))),
        "collision_steps": int(np.sum(np.asarray(logs.collision))),
        "quarantined_final": int(np.sum(_final_quarantine(logs))),
        "residual": {
            "count": int(res.size),
            "min": float(res.min()) if res.size else None,
            "max": float(res.max()) if res.size else None,
            "mean": float(res.mean()) if res.size else None,
            **{
                "p%g" % (p * 100): (
                    float(np.percentile(res, p * 100)) if res.size else None
                )
                for p in quantiles
            },
        },
        "consensus_iters": {
            "count": int(it.size),
            "mean": float(it.mean()) if it.size else None,
            "p99": float(np.percentile(it, 99)) if it.size else None,
            "max": int(it.max()) if it.size else None,
        },
    }
    return out


def _final_quarantine(logs) -> np.ndarray:
    """Per-scenario final sticky quarantine flags: the LAST time entry.
    Time is axis 0 for single rollouts and axis 1 for batched chunk logs
    (``parallel.mesh`` convention); both reduce to 'last along the axis
    that matches the log length'. The flag is sticky, so max-over-time
    equals the final value on EVERY layout — use that instead of guessing
    the axis order."""
    q = np.asarray(logs.quarantined)
    if q.ndim <= 1:
        q = q.reshape(1, -1)
    return q.reshape(q.shape[0], -1).max(axis=1)


def rollout_metrics(
    path: str,
    logs,
    tel=None,
    cfg=None,
    meta: dict | None = None,
) -> dict:
    """On-demand export: write a ``rollout_summary`` event for a finished
    rollout's logs (plus its telemetry accumulator when one was threaded)
    and return the emitted record."""
    writer = MetricsWriter(path)
    return writer.emit(
        "rollout_summary",
        logs=logs_summary(logs),
        telemetry=telemetry_event(tel, cfg),
        **({"meta": meta} if meta else {}),
    )
