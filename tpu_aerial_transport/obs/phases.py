"""Phase vocabulary for trace attribution.

Every hot code region is wrapped in ``jax.named_scope("tat.<phase>")``;
the scope lands in each HLO instruction's ``op_name`` metadata (and in
TPU trace events' ``tf_op`` stat), which ``tools/op_profile.py
--by-phase`` rolls op self-time up to. Scopes are pure metadata: they
change NO ops — the zero-cost-when-disabled HLO-identity tests
(telemetry, faults) run with the scopes present on both sides.

Scopes nest; attribution uses the INNERMOST ``tat.*`` segment of the
op_name path, so a coarse outer scope (e.g. the sharded-step wrapper)
never steals time from the fine-grained phases inside it.
"""

from __future__ import annotations

import jax

PREFIX = "tat."

# The algorithm phases (the op_profile rollup's row vocabulary):
QP_BUILD = "qp_build"          # per-agent QP matrix assembly + KKT ops.
CBF_ROWS = "cbf_rows"          # env CBF row construction (forest sweep).
ENV_QUERY = "env_query"        # the environment distance sweep itself
#                                (envs/forest.py capsule_forest_distance /
#                                envs/spatial.py bucketed slab gather +
#                                candidate sweep; nested inside
#                                tat.cbf_rows at the controller callsites —
#                                innermost wins, so the query's share
#                                separates from the row construction
#                                around it).
LOCAL_SOLVE = "local_solve"    # per-agent conic QP solves (inner ADMM).
FUSED_SOLVE = "fused_solve"    # whole-solve ADMM mega-kernel dispatch
#                                (ops/admm_kernel.fused_solve_lanes via
#                                solve_socp fused="kernel"; nested inside
#                                tat.local_solve — innermost wins, so the
#                                kernel's share separates from the XLA-side
#                                solve plumbing around it).
CONSENSUS = "consensus"        # consensus mean/residual all-reduce.
CONSENSUS_EXCHANGE = "consensus_exchange"  # the cross-device exchange itself
#                                (psum/ppermute/ring kernel; parallel/ring.py).
DUAL_UPDATE = "dual_update"    # dual / price ascent step.
DYNAMICS = "dynamics"          # physics substeps (integrate scan).
PAD = "pad"                    # tile pad/unpad of operators & warm starts.
FAULTS = "faults"              # fault schedule eval + sensor noise.
FALLBACK = "fallback"          # force-fallback ladder + quarantine.
TELEMETRY = "telemetry"        # in-jit telemetry accumulation.
SHARDED_STEP = "sharded_step"  # shard_map plumbing outside finer scopes.
SERVING_CHUNK = "serving_chunk"  # vmap plumbing of the serving tier's
#                                  batched chunk (serving/batcher.py);
#                                  finer controller scopes inside win.
LANE_SURGERY = "lane_surgery"  # on-device boundary lane surgery
#                                (serving/lanes.py): harvest-read +
#                                filler-reset + late-join select on the
#                                batched boundary carry.
PODS_STEP = "pods_step"        # 2-D (scenario, agent) pods-mesh shard_map
#                                plumbing (parallel/pods.py); the
#                                controllers' fine scopes inside win.

PHASES = (
    QP_BUILD, CBF_ROWS, ENV_QUERY, LOCAL_SOLVE, FUSED_SOLVE, CONSENSUS,
    CONSENSUS_EXCHANGE, DUAL_UPDATE, DYNAMICS, PAD, FAULTS, FALLBACK,
    TELEMETRY, SHARDED_STEP, SERVING_CHUNK, LANE_SURGERY, PODS_STEP,
)


def scope(phase: str):
    """``with scope(phases.LOCAL_SOLVE): ...`` — a ``jax.named_scope``
    carrying the ``tat.`` attribution prefix."""
    return jax.named_scope(PREFIX + phase)
