"""Host-side distributed request tracing: stitched spans from admission
to device, across serving, recovery, and pods.

The flight recorder (PR 5) answers *what the device did* and the serving
SLO rows (PR 9) answer *how long a request took*; this module connects
them. A :class:`Tracer` records **spans** — ``trace_id`` / ``span_id`` /
``parent_id``, structured attributes, one *track* per process — around
the real request path:

- ``serving/queue.py``: a root ``request`` span per submitted request
  (rejections are terminal spans carrying the structured reason) and a
  ``queue_wait`` child that closes when the batcher admits the request
  into a device lane;
- ``serving/batcher.py`` + ``serving/server.py``: ``batch_form`` /
  ``chunk_dispatch`` / ``harvest`` spans on the server's own trace, with
  the batch's lane map (``lanes=[[lane, request_id, trace_id], ...]``)
  linking every member request's trace to the shared device span;
- ``resilience/backend.py``: :class:`BackendGuard` wraps dispatch /
  retry / degrade in ``guard_dispatch`` / ``guard_fallback`` spans whose
  attributes carry the rung and the classified ``BackendError`` kind;
- ``resilience/recovery.py`` + ``parallel/pods.py``: ``run`` / ``chunk``
  / ``snapshot`` / ``resume`` spans around the chunk driver, one track
  per pods process.

**Clock model.** Every span records BOTH a monotonic timestamp pair
(``t0_mono``/``t1_mono`` — durations are exact, immune to wall-clock
steps) and a wall-epoch pair (``t0_wall``/``t1_wall``). Monotonic clocks
are per-process domains (each process's zero is arbitrary — the PR 9
resume clock-domain hazard), so :func:`stitch` aligns every track onto
one shared clock via the median per-row ``wall - mono`` anchor, and
:func:`stitch_run_dir` does it for a multi-process pods run directory
(the shard manifest names how many per-process trace files make the run
complete). Durations stay exactly the monotonic ones; only the origin
shifts.

**Exports.** Finished spans emit as additive ``trace_event`` rows
through the existing fsync'd metrics jsonl (``obs.export`` schema v5),
so ``tools/run_health.py`` and ``tools/ci_check.sh`` cover them for
free; :func:`chrome_trace` converts stitched rows to Chrome/Perfetto
trace-event JSON (``tools/trace_view.py`` is the CLI). On top of the
span graph, :func:`critical_path` decomposes each request's
submit→complete interval into queue-wait / batch-wait / device /
surgery / publish / harvest / retry segments that sum to the interval
EXACTLY by
construction — "why did p99 regress" becomes a table.

**Zero-cost contract** (the ``no_faults()`` / ``telemetry=None``
discipline): ``tracer=None`` takes no locks and allocates nothing per
request — every instrumentation site is a host-level
``if tracer is not None`` — and tracing never enters traced code, so
all compiled HLO is byte-identical with tracing on or off (asserted by
tests/test_trace.py).

Module contract: stdlib-only at module scope (no jax, no numpy) — the
span layer must be importable from tools on hosts where importing jax
is the hazard being traced.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import json
import os
import statistics
import time

# ----------------------------------------------------------------------
# Span vocabulary (the names the accountant and renderers key on).
# ----------------------------------------------------------------------

REQUEST = "request"             # root span of one request's trace.
QUEUE_WAIT = "queue_wait"       # submit -> admitted into a device lane.
BATCH_FORM = "batch_form"       # batch launch: bucket pick + admissions.
CHUNK_DISPATCH = "chunk_dispatch"  # one device chunk of a batch.
HARVEST = "harvest"             # boundary: host copy, resolve, late joins.
LANE_SURGERY = "lane_surgery"   # boundary lane surgery (host splice or
#                                 the serving/lanes.py device entrypoint).
BOUNDARY_PUBLISH = "boundary_publish"  # snapshot + journal publication.
GUARD_DISPATCH = "guard_dispatch"  # BackendGuard primary attempt.
GUARD_FALLBACK = "guard_fallback"  # BackendGuard degrade/retry on CPU.
SESSION_STEP = "session_step"   # one closed-loop session control step
#                                 (serving/sessions.py): accept -> the
#                                 step's inner request resolves. Lives on
#                                 the SESSION's trace so a whole session
#                                 renders as one timeline; not a
#                                 critical-path carve segment (the inner
#                                 request's spans account the time).
RUN = "run"                     # recovery.run_chunks whole-run root.
CHUNK = "chunk"                 # one recovery chunk (compile+execute).
SNAPSHOT = "snapshot"           # boundary snapshot publish.
RESUME = "resume"               # resume_run boundary walk / agreement.
RETRY = "retry"                 # host-level requeue marker (instant).

# Critical-path segment order (also the subtraction priority for
# overlapping spans inside a request's in-batch window — see
# :func:`critical_path`). ``surgery`` and ``publish`` decompose what the
# pre-ISSUE-18 accountant folded into ``harvest``/``batch_wait``: the
# boundary lane-surgery work and the snapshot/journal publication are
# carved FIRST (they nest inside the harvest window in sync mode), so
# the pipelined-dispatch win is measured, not inferred.
SEGMENTS = ("queue_wait", "batch_wait", "device", "surgery", "publish",
            "harvest", "retry")

# Process-unique id prefix: pid alone recycles, so add entropy once per
# process. Ids only need to be unique, not secret or sortable.
_PROC_TOKEN = f"{os.getpid():x}-{os.urandom(3).hex()}"
_id_counter = itertools.count(1)


def new_trace_id() -> str:
    return f"t{_PROC_TOKEN}-{next(_id_counter):x}"


def new_span_id() -> str:
    return f"s{_PROC_TOKEN}-{next(_id_counter):x}"


def default_track() -> str:
    return f"pid{os.getpid()}"


@dataclasses.dataclass
class Span:
    """One open-or-finished span. Mutable on purpose: attributes accrete
    while the span is open (rung, error kind, lane map) and the end
    timestamps land at :meth:`Tracer.end`."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    track: str
    t0_mono: float
    t0_wall: float
    attrs: dict = dataclasses.field(default_factory=dict)
    t1_mono: float | None = None
    t1_wall: float | None = None

    @property
    def ended(self) -> bool:
        return self.t1_mono is not None

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def to_row(self) -> dict:
        row = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "track": self.track,
            "t0_mono": self.t0_mono,
            "t0_wall": self.t0_wall,
        }
        if self.parent_id is not None:
            row["parent_id"] = self.parent_id
        if self.t1_mono is not None:
            row["t1_mono"] = self.t1_mono
            row["t1_wall"] = self.t1_wall
        if self.attrs:
            row["attrs"] = self.attrs
        return row


# Sentinel: "parent defaults to the tracer's current lexical span".
_CURRENT = object()


class Tracer:
    """Records spans and exports each finished one as a ``trace_event``
    row.

    ``sink`` duck-types: an ``obs.export.MetricsWriter`` (anything with
    ``.emit``) receives ``emit("trace_event", **row)`` — the durable
    fsync'd jsonl path — while a plain callable receives the row dict;
    ``None`` keeps rows in-process only (``self.rows``). ``track`` names
    this process's timeline in the stitched trace (the pods tier passes
    ``p{pid}of{N}``).

    NOT thread-safe by design: one tracer per host driver loop (server
    pump, chunk driver, bench sweep), matching how those loops already
    own their journals. The lexical-nesting stack (:meth:`span`) is what
    makes nested ``with`` blocks parent correctly without threading span
    handles everywhere; non-lexical spans (a ``queue_wait`` opened at
    submit and closed at a later boundary) use explicit
    :meth:`begin` / :meth:`end`.
    """

    def __init__(self, sink=None, *, track: str | None = None,
                 clock_mono=time.monotonic, clock_wall=time.time):
        self.sink = sink
        self.track = track or default_track()
        self.clock_mono = clock_mono
        self.clock_wall = clock_wall
        self.rows: list[dict] = []
        self._stack: list[Span] = []

    # ------------------------------------------------------- recording --
    def begin(self, name: str, *, parent=_CURRENT,
              trace_id: str | None = None, **attrs) -> Span:
        """Open a span. ``parent`` may be a :class:`Span`, a span-id
        string (with ``trace_id`` supplied), or ``None`` for an explicit
        root; by default the tracer's current lexical span is the
        parent. A root span with no ``trace_id`` starts a new trace."""
        if parent is _CURRENT:
            parent = self._stack[-1] if self._stack else None
        if isinstance(parent, Span):
            parent_id = parent.span_id
            trace_id = trace_id or parent.trace_id
        else:
            parent_id = parent
        return Span(
            name=name, trace_id=trace_id or new_trace_id(),
            span_id=new_span_id(), parent_id=parent_id, track=self.track,
            t0_mono=self.clock_mono(), t0_wall=self.clock_wall(),
            attrs=dict(attrs),
        )

    def end(self, span: Span, **attrs) -> dict:
        """Close a span (idempotent: a second end keeps the first
        timestamps and only merges attributes — callers on error paths
        may close defensively) and export its row."""
        if attrs:
            span.attrs.update(attrs)
        if span.ended:
            return span.to_row()
        span.t1_mono = self.clock_mono()
        span.t1_wall = self.clock_wall()
        return self._export(span.to_row())

    @contextlib.contextmanager
    def span(self, name: str, *, parent=_CURRENT,
             trace_id: str | None = None, **attrs):
        """Lexically scoped span: children opened inside the ``with``
        body parent under it automatically."""
        sp = self.begin(name, parent=parent, trace_id=trace_id, **attrs)
        self._stack.append(sp)
        try:
            yield sp
        finally:
            self._stack.pop()
            self.end(sp)

    def instant(self, name: str, *, parent=_CURRENT,
                trace_id: str | None = None, **attrs) -> dict:
        """Zero-duration marker (preemption, a skipped snapshot)."""
        sp = self.begin(name, parent=parent, trace_id=trace_id, **attrs)
        sp.t1_mono, sp.t1_wall = sp.t0_mono, sp.t0_wall
        return self._export(sp.to_row())

    def _export(self, row: dict) -> dict:
        self.rows.append(row)
        if self.sink is not None:
            if hasattr(self.sink, "emit"):
                self.sink.emit("trace_event", **row)
            else:
                self.sink(row)
        return row


class RequestTrace:
    """The per-ticket trace handle the serving tier hangs off a
    ``Ticket``: the root ``request`` span plus the (possibly still open)
    ``queue_wait`` child. ``Ticket.trace`` is ``None`` when tracing is
    off — every caller guards on that, which IS the zero-cost path."""

    __slots__ = ("tracer", "request_span", "queue_span")

    def __init__(self, tracer: Tracer, request_span: Span,
                 queue_span: Span | None = None):
        self.tracer = tracer
        self.request_span = request_span
        self.queue_span = queue_span

    @property
    def trace_id(self) -> str:
        return self.request_span.trace_id

    def admitted(self, **attrs) -> None:
        """Close the queue_wait span: the request entered a device lane."""
        if self.queue_span is not None and not self.queue_span.ended:
            self.tracer.end(self.queue_span, **attrs)

    def resolve(self, status: str, **attrs) -> None:
        """Terminal: close queue_wait (if the request never left the
        queue) and the root request span, with the outcome as
        attributes."""
        if self.queue_span is not None and not self.queue_span.ended:
            self.tracer.end(self.queue_span, status=status)
        self.tracer.end(self.request_span, status=status, **attrs)


# ----------------------------------------------------------------------
# Reading + stitching.
# ----------------------------------------------------------------------

def trace_rows(events) -> list[dict]:
    """The trace rows in a mixed event stream: metrics-jsonl
    ``trace_event`` events and bare ``Tracer.rows`` dicts alike."""
    return [
        e for e in events
        if (e.get("event") == "trace_event"
            or ("event" not in e and "span_id" in e and "trace_id" in e))
    ]


def stitch(rows: list[dict]) -> list[dict]:
    """Align every track's monotonic domain onto ONE shared clock.

    Each row carries both clocks, so each track's ``wall - mono`` offset
    is directly observable; the median over the track's rows is robust
    to a wall-clock step (NTP slew) mid-run. Returns copies with
    ``t0`` / ``t1`` stitched-seconds fields added; within a track the
    offset is one constant, so per-track ordering and every duration are
    exactly the monotonic ones."""
    by_track: dict[str, list[float]] = {}
    for r in rows:
        by_track.setdefault(r.get("track", "?"), []).append(
            r["t0_wall"] - r["t0_mono"]
        )
    offsets = {t: statistics.median(a) for t, a in by_track.items()}
    out = []
    for r in rows:
        off = offsets[r.get("track", "?")]
        s = dict(r)
        s["t0"] = r["t0_mono"] + off
        if r.get("t1_mono") is not None:
            s["t1"] = r["t1_mono"] + off
        out.append(s)
    return out


def stitch_run_dir(run_dir: str, *, allow_partial: bool = False,
                   manifest_prefix: str = "carry") -> list[dict]:
    """Stitch every trace row found in a run directory's jsonl files
    into one clock — the multi-process pods path.

    The pods tier gives each process its own metrics/journal file inside
    ONE shared run dir, and process 0 publishes the shard manifest
    (``harness.checkpoint.save_shard_manifest``) naming how many
    processes make the run complete. When that manifest exists, a
    stitched trace covering fewer process tracks than the manifest's
    ``n_processes`` raises (a "fleet" trace silently missing a process
    is exactly the lie this module exists to prevent) unless
    ``allow_partial=True``."""
    import glob as glob_mod

    rows: list[dict] = []
    for path in sorted(
        glob_mod.glob(os.path.join(run_dir, "*.jsonl"))
    ):
        rows.extend(trace_rows(_read_jsonl(path)))
    manifest_path = os.path.join(
        run_dir, f"{manifest_prefix}.shards.json"
    )
    if os.path.exists(manifest_path) and not allow_partial:
        with open(manifest_path, encoding="utf-8") as fh:
            manifest = json.load(fh)
        want = manifest.get("n_processes")
        tracks = {r.get("track") for r in rows}
        # ZERO rows is the most complete form of the partial-fleet lie
        # (every worker killed before a span ended), so the refusal must
        # not be gated on rows being non-empty.
        if want and len(tracks) < want:
            raise ValueError(
                f"{run_dir}: shard manifest names {want} processes but "
                f"trace rows cover only {len(tracks)} track(s) "
                f"({sorted(t for t in tracks if t)}); a partial stitch "
                "would silently drop a process's spans "
                "(allow_partial=True to override)"
            )
    return stitch(rows)


def _read_jsonl(path: str) -> list[dict]:
    """Torn-tail-tolerant jsonl read. Deliberately duplicates the tiny
    ``obs.export.jsonl_read`` loop instead of importing it: export pulls
    the telemetry module (and with it jax) at import time, and this
    module's contract is to stay importable where jax is the hazard."""
    out = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict):
                out.append(obj)
    return out


# ----------------------------------------------------------------------
# Chrome/Perfetto trace-event JSON.
# ----------------------------------------------------------------------

def chrome_trace(rows: list[dict]) -> dict:
    """Convert (stitched) rows to Chrome trace-event JSON.

    Layout: one Chrome *process* per track; inside it, one named thread
    row per span name, widened by greedy interval packing when same-name
    spans overlap (concurrent ``request`` spans get ``request``,
    ``request.1``, ... lanes) — every ``X`` slice track is overlap-free,
    which both Perfetto's trace processor and the ci validator's
    per-track monotonicity check require. Parent/trace linkage rides the
    ``args`` (the span graph is the source of truth; the thread layout
    is presentation)."""
    rows = [dict(r) for r in rows]
    if any("t0" not in r for r in rows):
        rows = stitch(rows)
    tracks = sorted({r.get("track", "?") for r in rows})
    pid_of = {t: i + 1 for i, t in enumerate(tracks)}
    t_origin = min((r["t0"] for r in rows), default=0.0)

    events: list[dict] = []
    for t in tracks:
        events.append({
            "ph": "M", "name": "process_name", "pid": pid_of[t], "tid": 0,
            "args": {"name": t},
        })

    # (track, name) -> packed lanes; tid allocated per (track, name, lane).
    tid_alloc: dict[tuple, int] = {}
    lane_ends: dict[tuple, list[float]] = {}

    def _tid(track: str, name: str, t0: float, t1: float) -> int:
        ends = lane_ends.setdefault((track, name), [])
        for lane, end in enumerate(ends):
            if t0 >= end - 1e-12:
                ends[lane] = t1
                break
        else:
            lane = len(ends)
            ends.append(t1)
        key = (track, name, lane)
        if key not in tid_alloc:
            tid_alloc[key] = len(tid_alloc) + 1
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid_of[track],
                "tid": tid_alloc[key],
                "args": {"name": name if lane == 0 else f"{name}.{lane}"},
            })
        return tid_alloc[key]

    for r in sorted(rows, key=lambda r: (r.get("track", "?"), r["t0"])):
        track = r.get("track", "?")
        args = {
            "trace_id": r["trace_id"], "span_id": r["span_id"],
            **({"parent_id": r["parent_id"]} if r.get("parent_id") else {}),
            **r.get("attrs", {}),
        }
        ts_us = (r["t0"] - t_origin) * 1e6
        t1 = r.get("t1")
        if t1 is None or t1 <= r["t0"]:
            events.append({
                "ph": "i", "s": "t", "name": r["name"],
                "pid": pid_of[track],
                "tid": _tid(track, r["name"], r["t0"], r["t0"]),
                "ts": ts_us, "cat": "tat", "args": args,
            })
        else:
            events.append({
                "ph": "X", "name": r["name"], "pid": pid_of[track],
                "tid": _tid(track, r["name"], r["t0"], t1),
                "ts": ts_us, "dur": (t1 - r["t0"]) * 1e6,
                "cat": "tat", "args": args,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, rows: list[dict]) -> dict:
    obj = chrome_trace(rows)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(obj, fh)
        fh.flush()
        # HL006: fsync BEFORE the rename — otherwise a crash can land
        # the rename on disk ahead of the data and publish empty bytes.
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return obj


def validate_chrome_trace(obj) -> list[str]:
    """Structural checks for an emitted trace file (the ci gate):
    well-formed trace-event JSON, non-negative durations, per-(pid,tid)
    monotone begin timestamps with no overlapping slices, and — the span
    graph's integrity — every ``parent_id`` present among the file's
    span ids."""
    errs: list[str] = []
    if not isinstance(obj, dict) or not isinstance(
        obj.get("traceEvents"), list
    ):
        return ["not a trace-event JSON object with a traceEvents list"]
    span_ids = set()
    parents = []
    by_thread: dict[tuple, list[tuple[float, float]]] = {}
    for i, e in enumerate(obj["traceEvents"]):
        if not isinstance(e, dict) or "ph" not in e:
            errs.append(f"event {i}: not an object with ph")
            continue
        if e["ph"] == "M":
            continue
        for k in ("name", "pid", "tid", "ts"):
            if k not in e:
                errs.append(f"event {i}: missing {k}")
        args = e.get("args", {})
        if isinstance(args, dict):
            if "span_id" in args:
                span_ids.add(args["span_id"])
            if args.get("parent_id"):
                parents.append((i, args["parent_id"]))
        dur = e.get("dur", 0.0)
        if e["ph"] == "X" and dur < 0:
            errs.append(f"event {i}: negative dur {dur}")
        if "ts" in e and "pid" in e and "tid" in e:
            by_thread.setdefault((e["pid"], e["tid"]), []).append(
                (float(e["ts"]), float(e.get("dur", 0.0)))
            )
    for (pid, tid), slices in by_thread.items():
        last_ts, last_end = -1.0, -1.0
        for ts, dur in slices:
            if ts < last_ts:
                errs.append(
                    f"track pid={pid} tid={tid}: non-monotone ts "
                    f"{ts} after {last_ts}"
                )
            if ts < last_end - 1e-6:
                errs.append(
                    f"track pid={pid} tid={tid}: slice at {ts} overlaps "
                    f"previous slice ending {last_end}"
                )
            last_ts, last_end = ts, max(last_end, ts + dur)
    for i, pid_ in parents:
        if pid_ not in span_ids:
            errs.append(f"event {i}: parent_id {pid_} not in this trace")
    return errs


def validate_trace_file(path: str) -> list[str]:
    try:
        with open(path, encoding="utf-8") as fh:
            obj = json.load(fh)
    except Exception as e:  # noqa: BLE001 — report, don't crash the gate.
        return [f"unreadable/unparseable: {type(e).__name__}: {e}"]
    return validate_chrome_trace(obj)


# ----------------------------------------------------------------------
# Critical-path accounting.
# ----------------------------------------------------------------------

def _t0(r):
    return r["t0"] if "t0" in r else r["t0_mono"]


def _t1(r):
    if "t1" in r:
        return r["t1"]
    return r.get("t1_mono")


def _merge(intervals: list[tuple[float, float]]) -> list[tuple[float, float]]:
    out: list[list[float]] = []
    for a, b in sorted(intervals):
        if b <= a:
            continue
        if out and a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return [(a, b) for a, b in out]


def _clip(intervals, lo: float, hi: float):
    return [(max(a, lo), min(b, hi)) for a, b in intervals
            if max(a, lo) < min(b, hi)]


def _subtract(intervals, taken):
    """``intervals`` minus the (merged) ``taken`` set."""
    out = []
    for a, b in intervals:
        cur = a
        for ta, tb in taken:
            if tb <= cur or ta >= b:
                continue
            if ta > cur:
                out.append((cur, ta))
            cur = max(cur, tb)
            if cur >= b:
                break
        if cur < b:
            out.append((cur, b))
    return out


def _measure(intervals) -> float:
    return sum(b - a for a, b in intervals)


def critical_path(rows: list[dict]) -> dict:
    """Decompose each resolved request's submit→complete interval into
    the :data:`SEGMENTS`.

    Per request (one ``request`` root span): ``queue_wait`` is its own
    child span; the in-batch window (queue end → completion) is then
    carved by priority — ``retry`` (guard_fallback time on a dispatch
    that served this request), ``device`` (chunk_dispatch spans whose
    lane map contains the request's trace), ``harvest`` (boundary
    processing of those batches) — and whatever remains is
    ``batch_wait`` (admitted but the device was serving other lanes /
    the server loop was elsewhere). The segments therefore sum to the
    request's total EXACTLY by construction; the residual claim is
    honest because every carved segment is real measured span time.

    Rows may be stitched or single-process raw rows (mono clock); batch
    spans and their member requests always share a process, so the
    per-request arithmetic is clock-consistent either way.

    Re-measured requests (append-mode metrics files, resume re-resolving
    a restored ticket) are deduped per ``request_id`` — the LAST request
    span wins, the run_health dedup rule."""
    reqs = [r for r in rows
            if r.get("name") == REQUEST and _t1(r) is not None]
    by_rid: dict[str, dict] = {}
    for r in sorted(reqs, key=_t0):
        rid = r.get("attrs", {}).get("request_id")
        by_rid[rid or r["trace_id"]] = r
    reqs = list(by_rid.values())
    by_id = {r["span_id"]: r for r in rows if "span_id" in r}
    queue_by_trace: dict[str, list[dict]] = {}
    member_spans: dict[str, dict[str, list[tuple[float, float]]]] = {}
    for r in rows:
        if r.get("name") == QUEUE_WAIT and _t1(r) is not None:
            queue_by_trace.setdefault(r["trace_id"], []).append(r)
        elif (r.get("name") in (CHUNK_DISPATCH, HARVEST, GUARD_FALLBACK,
                                LANE_SURGERY, BOUNDARY_PUBLISH)
              and _t1(r) is not None):
            seg = {CHUNK_DISPATCH: "device", HARVEST: "harvest",
                   GUARD_FALLBACK: "retry", LANE_SURGERY: "surgery",
                   BOUNDARY_PUBLISH: "publish"}[r["name"]]
            for member in _members(r, by_id):
                member_spans.setdefault(member, {}).setdefault(
                    seg, []
                ).append((_t0(r), _t1(r)))

    out_reqs = []
    for r in reqs:
        tid = r["trace_id"]
        t0, t1 = _t0(r), _t1(r)
        total = t1 - t0
        qspans = queue_by_trace.get(tid, [])
        queue_ivs = _clip(
            _merge([(_t0(q), _t1(q)) for q in qspans]), t0, t1
        )
        queue_s = _measure(queue_ivs)
        # Clamped to the request span's own start: a RESTORED request
        # (resume path) has no new queue_wait span, but the dead run's
        # queue span shares its trace_id — an unclamped win_lo would
        # open the window before this request span even began and count
        # pre-resume batch spans into its segments.
        win_lo = max(t0, max((_t1(q) for q in qspans), default=t0))
        window = _clip([(win_lo, t1)], t0, t1)
        taken: list[tuple[float, float]] = []
        segs = {"queue_wait": queue_s}
        # surgery/publish carve BEFORE harvest: in sync mode their spans
        # nest inside the harvest window, and the decomposition must
        # attribute that time to the finer segment, not the envelope.
        for seg in ("retry", "device", "surgery", "publish", "harvest"):
            ivs = _clip(
                _merge(member_spans.get(tid, {}).get(seg, [])), win_lo, t1
            )
            ivs = _subtract(ivs, taken)
            segs[seg] = _measure(ivs)
            taken = _merge(taken + ivs)
        segs["batch_wait"] = max(
            0.0, _measure(window) - segs["retry"] - segs["device"]
            - segs["surgery"] - segs["publish"] - segs["harvest"]
        )
        out_reqs.append({
            "trace_id": tid,
            "request_id": r.get("attrs", {}).get("request_id"),
            "status": r.get("attrs", {}).get("status"),
            "total_s": total,
            "segments": {k: segs[k] for k in SEGMENTS},
        })

    per_segment = {}
    completed = [q for q in out_reqs if q["status"] == "completed"]
    for seg in SEGMENTS:
        xs = sorted(q["segments"][seg] for q in completed)
        if xs:
            per_segment[seg] = {
                "p50": _pctl(xs, 0.5), "p99": _pctl(xs, 0.99),
                "mean": sum(xs) / len(xs), "total": sum(xs),
            }
    worst = max(completed, key=lambda q: q["total_s"], default=None)
    return {
        "requests": out_reqs,
        "completed": len(completed),
        "per_segment": per_segment,
        "worst": worst,
    }


def _members(row: dict, by_id: dict[str, dict]) -> list[str]:
    """Trace ids a batch-level span served: its own ``lanes`` lane map
    (``[[lane, request_id, trace_id], ...]``) or ``members`` list, else
    inherited up the parent chain (guard spans nest under the dispatch
    whose lane map names the riders)."""
    seen = 0
    while row is not None and seen < 8:
        attrs = row.get("attrs", {})
        lanes = attrs.get("lanes")
        if lanes:
            return [m[2] for m in lanes if len(m) >= 3 and m[2]]
        if attrs.get("members"):
            return list(attrs["members"])
        row = by_id.get(row.get("parent_id"))
        seen += 1
    return []


def _pctl(xs_sorted: list[float], p: float) -> float:
    k = min(len(xs_sorted) - 1, max(0, round(p * (len(xs_sorted) - 1))))
    return xs_sorted[k]
