"""Live streaming telemetry: in-process metrics hub, cross-replica jsonl
tailing, rolling windows, and the declarative SLO / burn-rate engine.

Everything the package had before this module is post-mortem — the
``obs.export`` jsonl is written while traffic flows but only *read* after
the run by ``tools/run_health.py``. This module closes the loop in three
layers, all stdlib-only (no jax, no numpy — the console must run in a
coordinator process that never pays device init):

1. :class:`MetricsHub` — in-process counters, gauges, and ONE latency-
   distribution primitive (:class:`LogHistogram`, log-bucketed and
   mergeable: merging is per-bucket integer addition, so it is
   associative and order-independent by construction — the property the
   cross-replica consistency proof rests on). Instrumented into the
   serving server loop, ``AdmissionQueue``, ``SessionHost`` steps,
   ``BackendGuard`` and the AOT serve ladder under the standing
   zero-cost contract: every site guards ``hub is not None`` (HL010) and
   the ``hub=None`` path allocates nothing per request. Hub mutation
   holds only the hub's own leaf lock and never blocks (pure dict math —
   the HL003 discipline).

2. :class:`JsonlTailer` / :class:`FleetTailer` — follow
   ``artifacts/*.metrics.jsonl`` live. Torn-tail tolerant by the same
   rule as :func:`obs.export.jsonl_read` (an unparseable interior line is
   skipped; a not-yet-newline-terminated tail is HELD BACK until the
   writer finishes it, so a concurrent ``jsonl_append`` mid-line never
   yields a phantom event), rotation-aware (inode change or shrink
   reopens from the top) and resume-from-offset-aware (byte offsets are
   exposed so a restarted console continues where it stopped). At
   quiescence the tailed stream equals a post-hoc ``jsonl_read`` —
   pinned by tests/test_live.py.

3. :class:`RollingWindows` + :class:`SLOEngine` — events merge into
   bounded per-second rings keyed ``(tenant, family, replica)``; window
   queries (1s/10s/60s for the console, the specs' 5m/1h for alerting)
   sum the ring's trailing seconds. :class:`SLOSpec` rows (per-tenant
   p99 step latency, deadline-miss rate, rejection rate, cache-hit
   rate) compile into error budgets; the multi-window burn-rate rule
   (the SRE pattern: page only when the SHORT and LONG window both burn
   above threshold) drives alert fire/resolve, journaled as the
   additive schema-v9 ``alert`` event kind and exposed to
   ``serving.fleet.FleetFront`` so the autoscale hint consumes budget
   burn, not just queue depth.

Clock domain: everything here lives on the WALL clock — window and alert
arithmetic keys off the events' journaled ``ts`` (wall epoch), never the
host monotonic clock, so replaying a file yields the same windows the
live run saw (HL001: no domain mixing).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import threading

__all__ = [
    "LogHistogram", "MetricsHub", "JsonlTailer", "FleetTailer",
    "RollingWindows", "SLOSpec", "SLOEngine", "DEFAULT_SLOS",
    "parse_slo_spec", "resolve_refresh_s", "resolve_burn_rates",
]

# ----------------------------------------------------------------------
# Log-bucketed mergeable histogram (THE latency-distribution primitive).
# ----------------------------------------------------------------------

# Buckets per octave: bucket(v) = floor(log2(v) * 4), i.e. boundaries at
# quarter-powers-of-two (~19% relative width — p99 resolution well under
# the rung-to-rung latency ratios the serving tier cares about).
_SUB = 4


class LogHistogram:
    """Sparse log-bucketed histogram over positive floats.

    Values <= 0 land in a dedicated zero bucket (a zero-length SLO
    window from a cache hit is data, not an error). Quantiles return
    the UPPER edge of the bucket where the cumulative count crosses the
    rank — a deterministic, merge-invariant answer: ``quantile`` over
    ``a.merge(b)`` equals ``quantile`` over the concatenated
    observations bucketed the same way, regardless of merge order
    (per-bucket integer addition is associative and commutative;
    asserted by tests/test_live.py)."""

    __slots__ = ("counts", "n", "total", "zero")

    def __init__(self):
        self.counts: dict[int, int] = {}
        self.n = 0
        self.total = 0.0
        self.zero = 0

    @staticmethod
    def bucket_of(value: float) -> int | None:
        """Bucket index for a positive value; None = the zero bucket."""
        if value <= 0.0:
            return None
        return math.floor(math.log2(value) * _SUB)

    @staticmethod
    def upper_edge(idx: int) -> float:
        return 2.0 ** ((idx + 1) / _SUB)

    def add(self, value: float, n: int = 1) -> None:
        idx = self.bucket_of(value)
        if idx is None:
            self.zero += n
        else:
            self.counts[idx] = self.counts.get(idx, 0) + n
        self.n += n
        self.total += float(value) * n

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """In-place per-bucket addition; returns self."""
        for idx, c in other.counts.items():
            self.counts[idx] = self.counts.get(idx, 0) + c
        self.n += other.n
        self.total += other.total
        self.zero += other.zero
        return self

    def copy(self) -> "LogHistogram":
        out = LogHistogram()
        out.counts = dict(self.counts)
        out.n, out.total, out.zero = self.n, self.total, self.zero
        return out

    def quantile(self, q: float) -> float | None:
        """Upper bucket edge at the ``q`` cumulative rank (None when
        empty). The zero bucket sorts first (edge 0.0)."""
        if self.n == 0:
            return None
        rank = max(1, math.ceil(q * self.n))
        cum = self.zero
        if cum >= rank:
            return 0.0
        for idx in sorted(self.counts):
            cum += self.counts[idx]
            if cum >= rank:
                return self.upper_edge(idx)
        return self.upper_edge(max(self.counts))

    def count_above(self, threshold: float) -> int:
        """Observations in buckets strictly ABOVE the bucket containing
        ``threshold`` — the deterministic (bucket-resolution,
        merge-invariant) "requests slower than the SLO threshold"
        count the latency burn rate is computed from."""
        cut = self.bucket_of(threshold)
        if cut is None:
            return self.n - self.zero
        return sum(c for idx, c in self.counts.items() if idx > cut)

    def to_dict(self) -> dict:
        return {
            "n": self.n, "total": self.total, "zero": self.zero,
            "counts": {str(k): v for k, v in sorted(self.counts.items())},
        }

    @classmethod
    def from_dict(cls, obj: dict) -> "LogHistogram":
        out = cls()
        out.n = int(obj.get("n", 0))
        out.total = float(obj.get("total", 0.0))
        out.zero = int(obj.get("zero", 0))
        out.counts = {int(k): int(v)
                      for k, v in obj.get("counts", {}).items()}
        return out

    def summary(self) -> dict:
        return {
            "count": self.n,
            "mean": (self.total / self.n) if self.n else None,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
        }


# ----------------------------------------------------------------------
# In-process metrics hub.
# ----------------------------------------------------------------------

class MetricsHub:
    """Thread-safe in-process counters / gauges / histograms.

    The hub is the live-ops sibling of ``obs.export.MetricsWriter``: the
    writer journals events durably (fsync per row), the hub keeps cheap
    in-memory aggregates the process can snapshot at any point with no
    file reads. Mutation holds only the hub's own lock and does pure
    dict arithmetic — never any I/O (the HL003 discipline) — and the
    hub's lock is a LEAF: hub methods take no other lock, so no
    lock-order cycle can involve it.

    Every instrumentation site is guarded ``hub is not None`` (HL010:
    identity, never truthiness), which is the whole zero-cost contract:
    with ``hub=None`` no per-request allocation or call happens."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = {}
        self._gauges: dict[tuple, float] = {}
        self._hists: dict[tuple, LogHistogram] = {}

    # ---------------------------------------------------- primitives --
    def inc(self, name: str, key=None, n: float = 1) -> None:
        with self._lock:
            k = (name, key)
            self._counters[k] = self._counters.get(k, 0) + n

    def gauge(self, name: str, value: float, key=None) -> None:
        with self._lock:
            self._gauges[(name, key)] = float(value)

    def observe(self, name: str, value: float, key=None) -> None:
        with self._lock:
            h = self._hists.get((name, key))
            if h is None:
                h = self._hists[(name, key)] = LogHistogram()
            h.add(float(value))

    # ------------------------------------- instrumentation ingestors --
    # One mapper per instrumented tier, taking the ALREADY-BUILT event
    # fields dict (the emit funnels allocate it regardless of the hub),
    # so a hub adds zero marginal allocation at the call site.

    def ingest_serving(self, fields: dict) -> None:
        kind = fields.get("kind")
        tenant = fields.get("tenant")
        self.inc("serving.events", key=kind)
        if kind == "rejected":
            self.inc("serving.rejected", key=fields.get("reason"))
        elif kind in ("completed", "deadline_missed"):
            slo = fields.get("slo")
            lat = slo.get("latency_s") if isinstance(slo, dict) else None
            if lat is not None:
                self.observe("serving.latency_s", lat, key=tenant)
        elif kind == "batch_boundary":
            occ = fields.get("occupancy")
            if occ is not None:
                self.gauge("serving.occupancy", occ,
                           key=fields.get("family"))
        if "depth" in fields:
            self.gauge("queue.depth", fields["depth"])

    def ingest_session(self, fields: dict) -> None:
        kind = fields.get("kind")
        self.inc("session.events", key=kind)
        if kind in ("step_done", "step_degraded"):
            slo = fields.get("slo")
            lat = slo.get("latency_s") if isinstance(slo, dict) else None
            if lat is not None:
                self.observe("session.step_latency_s", lat,
                             key=fields.get("rung"))

    def ingest_backend(self, event: dict) -> None:
        self.inc("backend.events", key=event.get("kind"))

    def ingest_aot(self, event: dict) -> None:
        rung = event.get("rung")
        self.inc("aot.serves", key=rung)
        wall = event.get("wall_s")
        if wall is not None:
            self.observe("aot.wall_s", wall, key=rung)

    # ------------------------------------------------------ snapshot --
    @staticmethod
    def _label(k: tuple) -> str:
        name, key = k
        return name if key is None else f"{name}{{{key}}}"

    def snapshot(self) -> dict:
        """JSON-ready copy of every aggregate (counters, gauges, and
        histogram summaries + raw buckets for exact downstream merges)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: h.copy() for k, h in self._hists.items()}
        return {
            "counters": {self._label(k): v
                         for k, v in sorted(counters.items(),
                                            key=lambda kv: str(kv[0]))},
            "gauges": {self._label(k): v
                       for k, v in sorted(gauges.items(),
                                          key=lambda kv: str(kv[0]))},
            "histograms": {
                self._label(k): {**h.summary(), "buckets": h.to_dict()}
                for k, h in sorted(hists.items(),
                                   key=lambda kv: str(kv[0]))
            },
        }


# ----------------------------------------------------------------------
# Live jsonl tailing.
# ----------------------------------------------------------------------

class JsonlTailer:
    """Follow ONE append-only jsonl file.

    ``poll()`` returns the events appended since the last poll. Byte
    offsets (``self.offset``) are the resume token: construct with
    ``offset=`` to continue a previous console's position. Reads are in
    binary so offsets are exact regardless of encoding.

    Torn-tail rule (the ``jsonl_read`` discipline, live edition): only
    NEWLINE-TERMINATED lines are parsed; the unfinished tail a
    concurrent ``jsonl_append`` is mid-write on stays buffered until
    its newline arrives. An unparseable *terminated* line (the torn
    interior a crash left) is skipped, exactly as ``jsonl_read`` skips
    it. Rotation (a new inode at the path, or the file shrinking below
    our offset) reopens from byte 0."""

    def __init__(self, path: str, offset: int = 0):
        self.path = path
        self.offset = int(offset)
        self._ino: int | None = None
        self._buf = b""

    def poll(self) -> list[dict]:
        try:
            st = os.stat(self.path)
        except OSError:
            return []
        if self._ino is None:
            self._ino = st.st_ino
        elif st.st_ino != self._ino or st.st_size < self.offset:
            # Rotated (new file at the path) or truncated: restart.
            self._ino = st.st_ino
            self.offset = 0
            self._buf = b""
        if st.st_size <= self.offset and not self._buf:
            return []
        with open(self.path, "rb") as fh:
            fh.seek(self.offset)
            data = fh.read()
        self.offset += len(data)
        self._buf += data
        lines = self._buf.split(b"\n")
        self._buf = lines.pop()  # the (possibly empty) unfinished tail.
        out = []
        for line in lines:
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn interior line — jsonl_read skips it too.
        return out


class FleetTailer:
    """Tail every replica's metrics jsonl, discovering new files live.

    ``roots`` is a list of file paths and/or directories; directories
    are re-scanned for ``*.metrics.jsonl`` on every poll (a replica that
    boots mid-run starts streaming as soon as its file appears).
    ``poll()`` yields ``(replica, event)`` pairs, the replica label
    being the file stem (``r0.metrics.jsonl`` -> ``r0``)."""

    SUFFIX = ".metrics.jsonl"

    def __init__(self, roots, offsets: dict[str, int] | None = None):
        self.roots = [roots] if isinstance(roots, str) else list(roots)
        self.tailers: dict[str, JsonlTailer] = {}
        self._offsets = dict(offsets or {})

    @classmethod
    def replica_of(cls, path: str) -> str:
        base = os.path.basename(path)
        if base.endswith(cls.SUFFIX):
            return base[: -len(cls.SUFFIX)]
        return os.path.splitext(base)[0]

    def _discover(self) -> list[str]:
        found = []
        for root in self.roots:
            if os.path.isdir(root):
                try:
                    names = sorted(os.listdir(root))
                except OSError:
                    continue
                found.extend(os.path.join(root, n) for n in names
                             if n.endswith(self.SUFFIX))
            else:
                found.append(root)
        return found

    def poll(self) -> list[tuple[str, dict]]:
        out: list[tuple[str, dict]] = []
        for path in self._discover():
            t = self.tailers.get(path)
            if t is None:
                t = self.tailers[path] = JsonlTailer(
                    path, offset=self._offsets.get(path, 0)
                )
            replica = self.replica_of(path)
            for event in t.poll():
                out.append((replica, event))
        return out

    def offsets(self) -> dict[str, int]:
        """Resume tokens for every tailed file."""
        return {path: t.offset for path, t in self.tailers.items()}


# ----------------------------------------------------------------------
# Rolling windows.
# ----------------------------------------------------------------------

class _Slot:
    """One (second, group) aggregation cell."""

    __slots__ = ("counts", "latency")

    def __init__(self):
        self.counts: dict[str, int] = {}
        self.latency = LogHistogram()

    def bump(self, key: str, n: int = 1) -> None:
        self.counts[key] = self.counts.get(key, 0) + n


# The console's standard display windows (seconds).
CONSOLE_WINDOWS = (1, 10, 60)

_DEF_TENANT = "default"


class RollingWindows:
    """Per-second ring of event aggregates keyed (tenant, family,
    replica).

    The ring is a bounded dict of whole-second slots: ingest folds one
    event into its ``int(ts)`` slot, and slots older than ``horizon_s``
    behind the newest timestamp are dropped (the ring wraps). Window
    queries sum the trailing N seconds — any N up to the horizon, so the
    console's 1s/10s/60s views and the SLO engine's 5m/1h burn windows
    read the same ring. All arithmetic is on journaled wall ``ts``
    values: replaying a file reproduces the live run's windows
    exactly."""

    def __init__(self, horizon_s: int = 3600):
        self.horizon_s = int(horizon_s)
        self._seconds: dict[int, dict[tuple, _Slot]] = {}
        self.latest_ts: float | None = None

    # ------------------------------------------------------- ingest --
    def ingest(self, replica: str, event: dict) -> None:
        etype = event.get("event")
        ts = event.get("ts")
        if not isinstance(ts, (int, float)):
            return
        if etype == "serving_event":
            self._ingest_serving(replica, event, ts)
        elif etype == "session_event":
            self._ingest_session(replica, event, ts)
        else:
            return
        if self.latest_ts is None or ts > self.latest_ts:
            self.latest_ts = ts
            self._prune(int(ts))

    def _slot(self, ts: float, tenant: str, family: str,
              replica: str) -> _Slot:
        sec = self._seconds.setdefault(int(ts), {})
        key = (tenant, family, replica)
        slot = sec.get(key)
        if slot is None:
            slot = sec[key] = _Slot()
        return slot

    def _ingest_serving(self, replica: str, event: dict,
                        ts: float) -> None:
        kind = event.get("kind")
        tenant = event.get("tenant", _DEF_TENANT)
        family = event.get("family", "?")
        slot = self._slot(ts, tenant, family, replica)
        if kind == "submitted":
            slot.bump("submitted")
        elif kind == "rejected":
            slot.bump("submitted")  # a rejected submit is an attempt.
            slot.bump("rejected")
        elif kind == "cache_hit":
            slot.bump("cache_hit")
        elif kind == "completed":
            slot.bump("completed")
            slo = event.get("slo")
            lat = slo.get("latency_s") if isinstance(slo, dict) else None
            if lat is not None:
                slot.latency.add(lat)
        elif kind == "deadline_missed":
            slot.bump("missed")

    def _ingest_session(self, replica: str, event: dict,
                        ts: float) -> None:
        kind = event.get("kind")
        tenant = event.get("tenant", _DEF_TENANT)
        family = event.get("family", "session")
        if kind == "step_done":
            slot = self._slot(ts, tenant, family, replica)
            slot.bump("steps")
            slo = event.get("slo")
            lat = slo.get("latency_s") if isinstance(slo, dict) else None
            if lat is not None:
                slot.latency.add(lat)
        elif kind == "step_degraded":
            slot = self._slot(ts, tenant, family, replica)
            slot.bump("steps")
            slot.bump("degraded")

    def _prune(self, newest_sec: int) -> None:
        floor = newest_sec - self.horizon_s
        if len(self._seconds) > self.horizon_s + 60:
            for sec in [s for s in self._seconds if s < floor]:
                del self._seconds[sec]

    # ------------------------------------------------------ queries --
    def groups(self) -> list[tuple]:
        seen = set()
        for sec in self._seconds.values():
            seen.update(sec)
        return sorted(seen)

    def tenants(self) -> list[str]:
        return sorted({g[0] for g in self.groups()})

    def window(self, window_s: int, now: float | None = None,
               tenant: str | None = None):
        """Aggregate the trailing ``window_s`` seconds ending at ``now``
        (default: the newest ingested ts) into one counts dict + merged
        latency histogram; ``tenant`` restricts to one tenant."""
        now = self.latest_ts if now is None else now
        counts: dict[str, int] = {}
        hist = LogHistogram()
        if now is None:
            return counts, hist
        end = int(now)
        for sec in range(end - int(window_s) + 1, end + 1):
            by_group = self._seconds.get(sec)
            if not by_group:
                continue
            for (t, _f, _r), slot in by_group.items():
                if tenant is not None and t != tenant:
                    continue
                for k, v in slot.counts.items():
                    counts[k] = counts.get(k, 0) + v
                hist.merge(slot.latency)
        return counts, hist

    def rates(self, window_s: int, now: float | None = None) -> dict:
        """Per-tenant derived rates over one window — the console row."""
        out: dict[str, dict] = {}
        for tenant in self.tenants():
            counts, hist = self.window(window_s, now=now, tenant=tenant)
            resolved = counts.get("completed", 0) + counts.get("missed", 0)
            attempts = counts.get("submitted", 0)
            out[tenant] = {
                "window_s": int(window_s),
                **counts,
                "latency": hist.summary(),
                "miss_rate": (counts.get("missed", 0) / resolved
                              if resolved else None),
                "rejection_rate": (counts.get("rejected", 0) / attempts
                                   if attempts else None),
                "cache_hit_rate": (
                    counts.get("cache_hit", 0) / counts["completed"]
                    if counts.get("completed") else None
                ),
            }
        return out


# ----------------------------------------------------------------------
# Declarative SLOs + multi-window burn-rate alerting.
# ----------------------------------------------------------------------

# Metric -> (bad, total) extractors over one window's (counts, hist).
SLO_METRICS = ("step_latency", "deadline_miss", "rejection", "cache_hit")

DEFAULT_BURN_RATES = (14.4, 6.0)


def resolve_burn_rates(configured=None) -> tuple[float, float]:
    """Resolve the (fast, slow) burn-rate thresholds: the
    ``TAT_SLO_BURN_RATES`` env force (``"FAST:SLOW"``) wins, then the
    configured pair, then :data:`DEFAULT_BURN_RATES`.

    TUNING CRITERION: a burn rate of B exhausts the error budget in
    ``period / B`` — the defaults are the classic SRE pair (14.4 over
    the short window pages when a 30-day budget would die in ~2 days;
    6 warns at ~5 days). Lower them when budgets are tighter than the
    window ratio assumes; raising them above ~30 makes the fast alert
    fire only on total outages."""
    spec = os.environ.get("TAT_SLO_BURN_RATES")
    if spec:
        parts = spec.split(":")
        if len(parts) != 2:
            raise ValueError(
                f"TAT_SLO_BURN_RATES must be 'FAST:SLOW', got {spec!r}"
            )
        fast, slow = (float(p) for p in parts)
    elif configured is not None:
        fast, slow = (float(v) for v in configured)
    else:
        fast, slow = DEFAULT_BURN_RATES
    if fast <= 0 or slow <= 0:
        raise ValueError(
            f"burn-rate thresholds must be > 0, got ({fast}, {slow})"
        )
    return fast, slow


DEFAULT_REFRESH_S = 1.0


def resolve_refresh_s(configured=None) -> float:
    """Resolve the live-console refresh period (seconds): the
    ``TAT_CONSOLE_REFRESH_S`` env force wins, then the configured value,
    then :data:`DEFAULT_REFRESH_S`.

    TUNING CRITERION: the refresh is pure reader-side cost (tail +
    window math; the serving path is untouched), so the floor is
    terminal legibility, not overhead — but every refresh re-stats N
    replica files, so fleets with hundreds of replicas on networked
    filesystems should back off to a few seconds."""
    env = os.environ.get("TAT_CONSOLE_REFRESH_S")
    if env:
        value = float(env)
    elif configured is not None:
        value = float(configured)
    else:
        value = DEFAULT_REFRESH_S
    if value <= 0:
        raise ValueError(f"refresh period must be > 0, got {value}")
    return value


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative SLO: ``objective`` is the GOOD fraction target
    (0.99 = 99% of events good), compiling to an error budget of
    ``1 - objective``. ``metric`` picks the bad/total extractor:

    - ``step_latency``: bad = resolved requests/steps slower than
      ``threshold_s`` (bucket-resolution, merge-invariant);
    - ``deadline_miss``: bad = deadline misses / resolved;
    - ``rejection``: bad = rejected / submit attempts;
    - ``cache_hit``: bad = uncached completions / completions (an
      inverted SLI: the objective is the hit rate).

    ``tenant=None`` evaluates per tenant over every tenant seen. The
    burn rule is multi-window: an alert fires only when the burn rate
    over BOTH the fast and slow window clears a threshold (fast pair
    pages, slow pair warns), and resolves when the fast window drops
    back below the slow threshold."""

    name: str
    metric: str
    objective: float
    threshold_s: float | None = None
    tenant: str | None = None
    fast_window_s: int = 300
    slow_window_s: int = 3600
    fast_burn: float | None = None
    slow_burn: float | None = None

    def __post_init__(self):
        if self.metric not in SLO_METRICS:
            raise ValueError(
                f"unknown SLO metric {self.metric!r} "
                f"(known: {SLO_METRICS})"
            )
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective}"
            )
        if self.metric == "step_latency" and self.threshold_s is None:
            raise ValueError("step_latency SLOs need threshold_s")

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    def bad_total(self, counts: dict, hist: LogHistogram) -> tuple:
        if self.metric == "step_latency":
            resolved = hist.n
            return (hist.count_above(self.threshold_s), resolved)
        if self.metric == "deadline_miss":
            resolved = (counts.get("completed", 0)
                        + counts.get("steps", 0)
                        + counts.get("missed", 0))
            return (counts.get("missed", 0)
                    + counts.get("degraded", 0), resolved)
        if self.metric == "rejection":
            return (counts.get("rejected", 0),
                    counts.get("submitted", 0))
        # cache_hit: bad = completions NOT served from cache.
        done = counts.get("completed", 0)
        return (done - min(done, counts.get("cache_hit", 0)), done)


def parse_slo_spec(spec: str) -> SLOSpec:
    """Parse the console grammar
    ``NAME:METRIC:OBJECTIVE[:key=value...]`` — keys: ``threshold_s``,
    ``tenant``, ``fast_window_s``, ``slow_window_s``, ``fast_burn``,
    ``slow_burn``. Example: ``p99:step_latency:0.99:threshold_s=0.5``."""
    parts = spec.split(":")
    if len(parts) < 3:
        raise ValueError(
            f"bad SLO spec {spec!r} (grammar: NAME:METRIC:OBJECTIVE"
            "[:key=value...])"
        )
    kw: dict = {"name": parts[0], "metric": parts[1],
                "objective": float(parts[2])}
    casts = {"threshold_s": float, "tenant": str,
             "fast_window_s": int, "slow_window_s": int,
             "fast_burn": float, "slow_burn": float}
    for extra in parts[3:]:
        key, sep, value = extra.partition("=")
        if not sep or key not in casts:
            raise ValueError(
                f"bad SLO spec field {extra!r} in {spec!r} "
                f"(known keys: {sorted(casts)})"
            )
        kw[key] = casts[key](value)
    return SLOSpec(**kw)


# The console/examples defaults: conservative enough that a nominal
# storm (no deadline pressure) fires nothing.
DEFAULT_SLOS = (
    SLOSpec(name="step_p99", metric="step_latency", objective=0.99,
            threshold_s=30.0),
    SLOSpec(name="miss_rate", metric="deadline_miss", objective=0.99),
    SLOSpec(name="rejection", metric="rejection", objective=0.95),
)


class SLOEngine:
    """Compile :class:`SLOSpec` rows against a :class:`RollingWindows`
    and drive alert fire/resolve.

    ``evaluate(now)`` recomputes every (spec, tenant) burn rate over the
    spec's fast and slow windows and walks the alert state machine; each
    transition is journaled through ``metrics`` (an
    ``obs.export.MetricsWriter`` or None) as a schema-v9 ``alert`` event
    (kind ``fire``/``resolve``) and kept in ``self.alerts`` for
    in-process consumers. ``max_burn()`` is the fleet front's autoscale
    input: the worst fast-window burn across every evaluated pair. All
    timestamps are the journaled wall-``ts`` domain."""

    def __init__(self, specs=None, *, windows: RollingWindows | None = None,
                 metrics=None, burn_rates=None):
        self.specs = tuple(DEFAULT_SLOS if specs is None else specs)
        fast, slow = resolve_burn_rates(burn_rates)
        self._default_burns = (fast, slow)
        horizon = max(
            [3600] + [s.slow_window_s for s in self.specs]
        )
        # `is None`, not truthiness (HL010): a falsy-but-real windows /
        # metrics sink must still be used.
        self.windows = (RollingWindows(horizon_s=horizon)
                        if windows is None else windows)
        self.metrics = metrics
        self.firing: dict[tuple, dict] = {}   # (spec, tenant) -> record.
        self.alerts: list[dict] = []          # fire/resolve journal.
        self.last_burns: dict[tuple, float] = {}

    # ------------------------------------------------------- ingest --
    def ingest(self, replica: str, event: dict) -> None:
        self.windows.ingest(replica, event)

    def ingest_all(self, pairs) -> int:
        n = 0
        for replica, event in pairs:
            self.ingest(replica, event)
            n += 1
        return n

    # -------------------------------------------------------- burns --
    def _burn(self, spec: SLOSpec, tenant: str, window_s: int,
              now: float | None) -> float | None:
        counts, hist = self.windows.window(window_s, now=now,
                                           tenant=tenant)
        bad, total = spec.bad_total(counts, hist)
        if total <= 0:
            return None
        return (bad / total) / spec.budget

    def burn_rates(self, now: float | None = None) -> dict:
        """(spec name, tenant) -> {fast, slow} burn rates (None = no
        traffic in that window)."""
        out: dict = {}
        for spec in self.specs:
            tenants = ([spec.tenant] if spec.tenant is not None
                       else self.windows.tenants())
            for tenant in tenants:
                out[(spec.name, tenant)] = {
                    "fast": self._burn(spec, tenant, spec.fast_window_s,
                                       now),
                    "slow": self._burn(spec, tenant, spec.slow_window_s,
                                       now),
                }
        return out

    def max_burn(self) -> float | None:
        """Worst fast-window burn from the LAST evaluate() — the
        autoscale hint's budget-burn input (None before any traffic)."""
        if not self.last_burns:
            return None
        return max(self.last_burns.values())

    # ----------------------------------------------------- evaluate --
    def _severity(self, spec: SLOSpec, fast: float | None,
                  slow: float | None) -> str | None:
        fast_thr = (spec.fast_burn if spec.fast_burn is not None
                    else self._default_burns[0])
        slow_thr = (spec.slow_burn if spec.slow_burn is not None
                    else self._default_burns[1])
        if fast is None or slow is None:
            return None
        if fast >= fast_thr and slow >= fast_thr:
            return "fast"
        if fast >= slow_thr and slow >= slow_thr:
            return "slow"
        return None

    def evaluate(self, now: float | None = None) -> list[dict]:
        """One alerting pass at wall time ``now`` (default: the newest
        ingested ts). Returns the transitions (fired/resolved) this
        pass produced."""
        now = self.windows.latest_ts if now is None else now
        if now is None:
            return []
        specs = {s.name: s for s in self.specs}
        transitions: list[dict] = []
        self.last_burns = {}
        for (name, tenant), burns in self.burn_rates(now=now).items():
            spec = specs[name]
            fast, slow = burns["fast"], burns["slow"]
            if fast is not None:
                self.last_burns[(name, tenant)] = fast
            severity = self._severity(spec, fast, slow)
            key = (name, tenant)
            active = self.firing.get(key)
            if severity is not None and active is None:
                record = {
                    "kind": "fire", "slo": name, "tenant": tenant,
                    "severity": severity,
                    "burn_rate": round(fast, 4),
                    "window_s": spec.fast_window_s, "ts": now,
                }
                self.firing[key] = record
                self.alerts.append(record)
                transitions.append(record)
                if self.metrics is not None:
                    self.metrics.emit(
                        "alert", kind="fire", slo=name, tenant=tenant,
                        severity=severity, burn_rate=round(fast, 4),
                        window_s=spec.fast_window_s, ts=now,
                        objective=spec.objective, metric=spec.metric,
                    )
            elif severity is None and active is not None:
                del self.firing[key]
                record = {"kind": "resolve", "slo": name,
                          "tenant": tenant, "ts": now,
                          "fired_ts": active["ts"]}
                self.alerts.append(record)
                transitions.append(record)
                if self.metrics is not None:
                    self.metrics.emit(
                        "alert", kind="resolve", slo=name, tenant=tenant,
                        ts=now, fired_ts=active["ts"],
                    )
        return transitions

    # -------------------------------------------------------- state --
    def snapshot(self, now: float | None = None) -> dict:
        burns = self.burn_rates(now=now)
        return {
            "specs": [dataclasses.asdict(s) for s in self.specs],
            "burn_rates": {
                f"{name}/{tenant}": v
                for (name, tenant), v in sorted(burns.items())
            },
            "firing": sorted(
                f"{name}/{tenant}" for name, tenant in self.firing
            ),
            "alerts": list(self.alerts),
        }
