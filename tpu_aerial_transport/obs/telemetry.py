"""In-jit run-health telemetry: a :class:`TelemetryState` pytree threaded
through the rollout / chunk scan carries, updated once per HL control step
ON-DEVICE — so a week-long chunked run answers "was this fleet healthy"
from O(1) state instead of O(T) logs.

Accumulated per step (from the controller's ``SolverStats`` plus the
resilience layer's quarantine flag):

- **fallback-rung histogram** (rungs 0-3, ``resilience.rollout`` ladder);
- **consensus-residual running percentiles** via the P² (P-squared)
  streaming estimator of Jain & Chlamtac — 5 markers per tracked
  quantile, O(1) memory, no reservoir RNG, fully vectorized over the
  quantile axis (so it lives happily inside a ``lax.scan``) — plus exact
  running min/max/mean;
- **safety-margin minima**: min environment/CBF margin
  (``stats.min_env_dist``) and worst-step ``ok_frac``;
- **counts**: collision steps, quarantined steps, total consensus
  iterations;
- **per-agent solve health** (optional; needs the controller's
  ``track_agent_stats`` static config so it stays zero-cost when off):
  per-agent count of steps whose final QP residual missed
  ``solver_tol`` (the agents persistently falling back to equilibrium
  forces) and the per-agent worst residual.

**Zero-cost when disabled**: ``telemetry=None`` and
``telemetry=no_telemetry()`` compile to the IDENTICAL HLO (``active`` is
a static field and every telemetry branch in the rollouts is a
Python-level ``if``) — asserted by tests/test_telemetry.py, the same
contract as ``resilience.faults.no_faults()``.

State is an ordinary pytree: it snapshots/restores through
``harness.checkpoint`` with the chunk carry (telemetry survives
preemption), and ``obs.export.telemetry_event`` renders it to the
metrics jsonl at chunk boundaries.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

# Fallback-ladder rung count (resilience.rollout RUNG_* constants 0-3).
N_RUNGS = 4

# Solver-effort histogram buckets (log2-spaced upper edges; the last
# bucket is the > ITER_BUCKETS[-1] overflow). Static: any config's
# max_iter / inner budget lands in the same fixed-shape accumulators, so
# the carry structure never depends on the controller. Bucket i counts
# observations v with ITER_BUCKETS[i-1] < v <= ITER_BUCKETS[i].
ITER_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048)
N_ITER_BUCKETS = len(ITER_BUCKETS) + 1


def iter_bucket_index(v) -> jnp.ndarray:
    """Static-shape bucket index for one iteration-count observation
    (int or float — the inner-effort stream is a per-consensus-iteration
    RATIO, bucketed un-floored so the in-jit histogram agrees with the
    host-side :func:`iter_histogram` on the same values)."""
    edges = jnp.asarray(ITER_BUCKETS, jnp.asarray(v).dtype)
    return jnp.sum((v > edges).astype(jnp.int32))


def _iter_one_hot(v) -> jnp.ndarray:
    return (iter_bucket_index(v)
            == jnp.arange(N_ITER_BUCKETS)).astype(jnp.int32)


def iter_histogram(values) -> np.ndarray:
    """Host-side histogram on the :data:`ITER_BUCKETS` grid with the SAME
    right-closed bucket semantics as :func:`iter_bucket_index`
    (bucket i counts v <= ITER_BUCKETS[i], first match) — the one
    implementation bench cells and examples share so their histograms
    and the in-jit telemetry accumulators read on the same axis
    (np.histogram's left-closed bins would shift every edge-valued
    observation one bucket)."""
    v = np.asarray(values).reshape(-1)
    idx = np.searchsorted(np.asarray(ITER_BUCKETS), v, side="left")
    return np.bincount(idx, minlength=N_ITER_BUCKETS)


@struct.dataclass
class TelemetryConfig:
    """Static telemetry knobs. ``active`` and the structure-determining
    fields are static (they select the compiled program); ``solver_tol``
    is a dynamic leaf (retunable without recompiling)."""

    # Master switch: False compiles the exact no-telemetry program.
    active: bool = struct.field(pytree_node=False, default=True)
    # Quantiles tracked by the P² estimators over the per-step consensus
    # residual (static: sizes the marker arrays).
    quantiles: tuple = struct.field(
        pytree_node=False, default=(0.5, 0.9, 0.99)
    )
    # Track per-agent solve health. Requires the controller config's
    # matching ``track_agent_stats=True`` (cadmm/dd) so SolverStats
    # carries ``agent_solve_res``; mismatches raise at trace time.
    track_agents: bool = struct.field(pytree_node=False, default=False)
    # Per-agent failure threshold for agent_fail_steps (the controllers'
    # solver_tol; a residual at/above it means the step's final solve for
    # that agent missed tolerance).
    solver_tol: float = 5e-3


@struct.dataclass
class TelemetryState:
    """The on-device accumulator (one per rollout / chunked-run carry).

    ``quantiles`` rides along as a STATIC field (part of the treedef, not
    a leaf): the host-side readers (``summary``/``residual_percentiles``,
    hence ``obs.export`` and ``recovery.run_chunks``' boundary events)
    label the P² marker rows from the state itself, so a snapshot or a
    host copy is self-describing — no config needed at read time."""

    steps: jnp.ndarray  # () int32 — HL steps accumulated.
    rung_hist: jnp.ndarray  # (N_RUNGS,) int32 fallback-rung counts.
    iters_sum: jnp.ndarray  # () int32 — total consensus iterations.
    # Solver-effort histograms (adaptive-effort observability; log2
    # buckets, :data:`ITER_BUCKETS`): per-step consensus iteration counts,
    # and — when the controller tracks it (effort="adaptive" populates
    # SolverStats.inner_iters) — per-step inner ADMM iterations PER SOLVE
    # (per consensus iteration per agent — see ``n_agents``), plus the
    # raw inner-iteration total.
    consensus_hist: jnp.ndarray  # (N_ITER_BUCKETS,) int32.
    inner_hist: jnp.ndarray  # (N_ITER_BUCKETS,) int32.
    inner_iters_sum: jnp.ndarray  # () int32.
    ok_frac_min: jnp.ndarray  # () worst-step solve-success fraction.
    min_env_dist: jnp.ndarray  # () running min CBF/env margin.
    collision_steps: jnp.ndarray  # () int32.
    quarantine_steps: jnp.ndarray  # () int32 steps spent quarantined.
    # Consensus-residual stream (finite observations only).
    res_count: jnp.ndarray  # () int32.
    res_min: jnp.ndarray  # ().
    res_max: jnp.ndarray  # ().
    res_sum: jnp.ndarray  # () (res_sum / res_count = mean).
    p2_q: jnp.ndarray  # (Q, 5) P² marker heights.
    p2_n: jnp.ndarray  # (Q, 5) P² marker positions (float).
    # Per-agent solve health ((0,) when track_agents is off — the leaves
    # stay in the pytree so the carry STRUCTURE never depends on data).
    agent_fail_steps: jnp.ndarray  # (n,) int32 or (0,).
    agent_res_max: jnp.ndarray  # (n,) or (0,).
    # The quantile each p2_q/p2_n row tracks (see class docstring).
    quantiles: tuple = struct.field(
        pytree_node=False, default=(0.5, 0.9, 0.99)
    )
    # Fleet size (static; init_telemetry's n_agents): normalizes the
    # inner-effort histogram to PER-SOLVE iterations — an agents-summed
    # total would saturate the static bucket grid at large n (64 x 40
    # already overflows 2048, the pods tier by 100x). 0 = unknown,
    # treated as 1.
    n_agents: int = struct.field(pytree_node=False, default=0)


def no_telemetry() -> TelemetryConfig:
    """A disabled config: ``rollout(..., telemetry=no_telemetry())``
    compiles to the identical HLO as ``telemetry=None`` (asserted)."""
    return TelemetryConfig(active=False)


def init_telemetry(
    cfg: TelemetryConfig, n_agents: int = 0, dtype=jnp.float32
) -> TelemetryState:
    """Fresh accumulator. ``n_agents`` sizes the per-agent leaves when
    ``cfg.track_agents`` (pass the controller's ``params.n``)."""
    nq = len(cfg.quantiles)
    na = n_agents if cfg.track_agents else 0
    return TelemetryState(
        quantiles=tuple(cfg.quantiles),
        n_agents=int(n_agents),
        steps=jnp.zeros((), jnp.int32),
        rung_hist=jnp.zeros((N_RUNGS,), jnp.int32),
        iters_sum=jnp.zeros((), jnp.int32),
        consensus_hist=jnp.zeros((N_ITER_BUCKETS,), jnp.int32),
        inner_hist=jnp.zeros((N_ITER_BUCKETS,), jnp.int32),
        inner_iters_sum=jnp.zeros((), jnp.int32),
        ok_frac_min=jnp.ones((), dtype),
        min_env_dist=jnp.asarray(jnp.inf, dtype),
        collision_steps=jnp.zeros((), jnp.int32),
        quarantine_steps=jnp.zeros((), jnp.int32),
        res_count=jnp.zeros((), jnp.int32),
        res_min=jnp.asarray(jnp.inf, dtype),
        res_max=jnp.asarray(-jnp.inf, dtype),
        res_sum=jnp.zeros((), dtype),
        # +inf marker padding: the bootstrap insert-and-sort keeps the
        # first < 5 observations sorted in the leading columns.
        p2_q=jnp.full((nq, 5), jnp.inf, dtype),
        p2_n=jnp.tile(jnp.arange(1.0, 6.0, dtype=dtype), (nq, 1)),
        agent_fail_steps=jnp.zeros((na,), jnp.int32),
        agent_res_max=jnp.full((na,), -jnp.inf, dtype),
    )


def _p2_update(cfg: TelemetryConfig, q, npos, count, x):
    """One P² observation, vectorized over the quantile axis.

    ``q``/``npos`` are (Q, 5) marker heights/positions, ``count`` the
    number of PRIOR observations, ``x`` the new scalar. Returns the
    updated ``(q, npos)``. The three middle markers adjust in parallel
    from the pre-observation snapshot (the textbook algorithm adjusts
    them sequentially; the parallel variant's estimates agree to the
    same O(1/sqrt(n)) accuracy — tests/test_telemetry.py bounds it
    against np.percentile)."""
    dtype = q.dtype
    quant = jnp.asarray(cfg.quantiles, dtype)  # (Q,)
    # Desired marker positions for count+1 total observations:
    # n'_i = 1 + count * d_i with d = [0, p/2, p, (1+p)/2, 1].
    dvec = jnp.stack([
        jnp.zeros_like(quant), quant / 2.0, quant,
        (1.0 + quant) / 2.0, jnp.ones_like(quant),
    ], axis=1)  # (Q, 5)

    # --- Bootstrap (< 5 observations): insert sorted, positions fixed.
    q_boot = jnp.sort(q.at[:, jnp.minimum(count, 4)].set(x), axis=1)

    # --- Main path (>= 5 observations). Computed unconditionally and
    # selected below; NaNs from the inf-padded bootstrap rows never
    # propagate through the jnp.where select.
    qc = q.at[:, 0].min(x).at[:, 4].max(x)
    # Cell index k in 0..3 with q[k] <= x < q[k+1] (edges clamped).
    k = jnp.clip(jnp.sum((x >= qc[:, 1:4]).astype(jnp.int32), axis=1), 0, 3)
    npos_inc = npos + (jnp.arange(5)[None, :] > k[:, None]).astype(dtype)
    ndes = 1.0 + count.astype(dtype) * dvec
    nm, ni, npl = npos_inc[:, :-2], npos_inc[:, 1:-1], npos_inc[:, 2:]
    qm, qi, qp = qc[:, :-2], qc[:, 1:-1], qc[:, 2:]
    di = ndes[:, 1:-1] - ni
    s = jnp.where(
        (di >= 1.0) & (npl - ni > 1.0), 1.0,
        jnp.where((di <= -1.0) & (nm - ni < -1.0), -1.0, 0.0),
    ).astype(dtype)
    # Piecewise-parabolic (P²) height estimate, linear fallback when the
    # parabola leaves the bracketing markers.
    gap_r = jnp.maximum(npl - ni, 1.0)
    gap_l = jnp.maximum(ni - nm, 1.0)
    qpar = qi + s / (npl - nm) * (
        (ni - nm + s) * (qp - qi) / gap_r + (npl - ni - s) * (qi - qm) / gap_l
    )
    qlin = qi + s * jnp.where(s >= 0.0, (qp - qi) / gap_r, (qi - qm) / gap_l)
    q_mid = jnp.where(
        s != 0.0,
        jnp.where((qm < qpar) & (qpar < qp), qpar, qlin),
        qi,
    )
    q_main = qc.at[:, 1:-1].set(q_mid)
    npos_main = npos_inc.at[:, 1:-1].add(s)

    boot = count < 5
    return (
        jnp.where(boot, q_boot, q_main),
        jnp.where(boot, npos, npos_main),
    )


def update(
    cfg: TelemetryConfig,
    tel: TelemetryState,
    stats,
    quarantined=None,
) -> TelemetryState:
    """Fold one control step's ``SolverStats`` (post fallback-rung
    stamping) into the accumulator. Runs under the rollout scan — pure
    jnp, no host round-trips. ``quarantined`` is the resilience layer's
    sticky per-scenario flag (None in the nominal rollout)."""
    dtype = tel.res_min.dtype
    rung = jnp.clip(stats.fallback_rung.astype(jnp.int32), 0, N_RUNGS - 1)
    rung_hist = tel.rung_hist + (rung == jnp.arange(N_RUNGS)).astype(jnp.int32)

    # Consensus-residual stream: finite observations only (a poisoned
    # step's inf/nan residual is already visible on the rung histogram;
    # folding it into the percentile markers would wedge them at inf).
    x = stats.solve_res.astype(dtype)
    finite = jnp.isfinite(x)
    p2_q, p2_n = _p2_update(cfg, tel.p2_q, tel.p2_n, tel.res_count, x)

    na = tel.agent_fail_steps.shape[0]
    agent_res = getattr(stats, "agent_solve_res", None)
    if na and (agent_res is None or agent_res.shape[0] != na):
        raise ValueError(
            "telemetry.track_agents is on but this controller's "
            "SolverStats carries no matching agent_solve_res — enable "
            "track_agent_stats in the controller make_config "
            f"(telemetry expects ({na},), stats has "
            f"{None if agent_res is None else agent_res.shape})"
        )
    if na:
        a_res = agent_res.astype(dtype)
        a_fin = jnp.isfinite(a_res)
        agent_fail = tel.agent_fail_steps + (
            ~a_fin | (a_res >= cfg.solver_tol)
        ).astype(jnp.int32)
        agent_max = jnp.maximum(
            tel.agent_res_max, jnp.where(a_fin, a_res, -jnp.inf)
        )
    else:
        agent_fail, agent_max = tel.agent_fail_steps, tel.agent_res_max

    quar = (jnp.zeros((), bool) if quarantined is None
            else quarantined.astype(bool))
    # Solver-effort histograms. Consensus: every step's iteration count;
    # the centralized controller's sentinel iters = -1 is EXCLUDED from
    # the histogram (the logs_summary `it >= 0` rule — clipping it into
    # bucket 0 would render a bogus "solver effort" section for a
    # controller with no consensus loop) while iters_sum keeps its
    # pre-existing clip-at-0 semantics. Inner: only when the controller
    # tracks effort (SolverStats.inner_iters is a populated scalar under
    # effort="adaptive"; the (0,) default means "not tracked" — same
    # sentinel convention as agent_solve_res), as inner iterations PER
    # SOLVE (per consensus iteration per agent) — the per-QP effort the
    # adaptive tier actually modulates, and scale-free across fleets.
    iters_step = jnp.maximum(stats.iters.astype(jnp.int32), 0)
    consensus_hist = tel.consensus_hist + _iter_one_hot(iters_step) * (
        stats.iters.astype(jnp.int32) >= 0
    ).astype(jnp.int32)
    inner = getattr(stats, "inner_iters", None)
    inner_tracked = inner is not None and inner.ndim == 0
    if inner_tracked:
        inner_step = jnp.maximum(inner.astype(jnp.int32), 0)
        # Un-floored PER-SOLVE ratio (inner total / consensus iters /
        # fleet size): the bench cells and the example bucket the SAME
        # float value (iter_bucket_index handles floats), so the three
        # surfaces genuinely read on one axis — and the value is
        # scale-free (an agents-summed total saturates the static
        # bucket grid at large n).
        inner_hist = tel.inner_hist + _iter_one_hot(
            inner_step.astype(dtype)
            / (jnp.maximum(iters_step, 1) * max(tel.n_agents, 1))
        )
        inner_sum = tel.inner_iters_sum + inner_step
    else:
        inner_hist = tel.inner_hist
        inner_sum = tel.inner_iters_sum
    return TelemetryState(
        quantiles=tel.quantiles,
        n_agents=tel.n_agents,
        steps=tel.steps + 1,
        rung_hist=rung_hist,
        iters_sum=tel.iters_sum + iters_step,
        consensus_hist=consensus_hist,
        inner_hist=inner_hist,
        inner_iters_sum=inner_sum,
        ok_frac_min=jnp.minimum(
            tel.ok_frac_min, stats.ok_frac.astype(dtype)
        ),
        min_env_dist=jnp.minimum(
            tel.min_env_dist, stats.min_env_dist.astype(dtype)
        ),
        collision_steps=tel.collision_steps
        + stats.collision.astype(jnp.int32),
        quarantine_steps=tel.quarantine_steps + quar.astype(jnp.int32),
        res_count=tel.res_count + finite.astype(jnp.int32),
        res_min=jnp.where(
            finite, jnp.minimum(tel.res_min, x), tel.res_min
        ),
        res_max=jnp.where(
            finite, jnp.maximum(tel.res_max, x), tel.res_max
        ),
        res_sum=jnp.where(finite, tel.res_sum + x, tel.res_sum),
        p2_q=jnp.where(finite, p2_q, tel.p2_q),
        p2_n=jnp.where(finite, p2_n, tel.p2_n),
        agent_fail_steps=agent_fail,
        agent_res_max=agent_max,
    )


def find_state(tree):
    """The first :class:`TelemetryState` inside an arbitrary carry pytree
    (how ``resilience.recovery`` discovers telemetry in a chunk carry it
    is otherwise generic over), or None. Works on host copies too: any
    object of the dataclass type qualifies, whatever its leaf types."""
    found = []

    def visit(x):
        if isinstance(x, TelemetryState):
            found.append(x)
            return True  # treat as leaf: do not recurse into it.
        return False

    jax.tree.flatten(tree, is_leaf=visit)
    return found[0] if found else None


def _lane_summaries(tel: TelemetryState) -> list[TelemetryState]:
    """Split a BATCHED accumulator (every leaf carrying a leading
    Monte-Carlo lane axis — the vmapped chunk carries of
    ``parallel.mesh.scenario_rollout_resumable``) into per-lane states.
    Host-side only."""
    n_lanes = np.asarray(tel.steps).shape[0]
    return [
        jax.tree.map(lambda x, i=i: np.asarray(x)[i], tel)
        for i in range(n_lanes)
    ]


def residual_percentiles(
    tel: TelemetryState, quantiles=None
) -> dict[str, float]:
    """Host-side percentile estimates from the P² markers: the center
    marker once >= 5 observations exist, exact small-sample percentiles
    from the (sorted) bootstrap markers below that. The quantile labels
    come from the STATE (``tel.quantiles`` — always row-aligned with
    ``p2_q``); passing ``quantiles`` explicitly is not supported beyond
    the state's own tuple and exists only for symmetry with summary().
    Each quantile's estimator is independent, so small-sample estimates
    can cross; a running max restores monotonicity for ASCENDING
    quantiles (the config default) without biasing converged estimates."""
    quantiles = tel.quantiles if quantiles is None else quantiles
    if len(quantiles) != tel.p2_q.shape[0]:
        raise ValueError(
            f"{len(quantiles)} quantile labels for "
            f"{tel.p2_q.shape[0]} P² marker rows — read the labels from "
            "tel.quantiles (they are part of the state)"
        )
    count = int(np.asarray(tel.res_count))
    out = {}
    q_arr = np.asarray(tel.p2_q)
    prev = -np.inf
    for i, p in enumerate(quantiles):
        key = "p%g" % (p * 100)
        if count == 0:
            out[key] = None
        elif count < 5:
            vals = q_arr[i][np.isfinite(q_arr[i])]
            out[key] = float(np.percentile(vals, p * 100)) if len(vals) \
                else None
        else:
            out[key] = float(max(q_arr[i, 2], prev))
            prev = out[key]
    return out


def hist_percentile(hist, p: float):
    """Bucket-edge percentile estimate from an :data:`ITER_BUCKETS`
    histogram (host-side): the upper edge of the first bucket whose
    cumulative count reaches ``p`` of the total — conservative (an upper
    bound within the log2 grid). None on an empty histogram AND on the
    overflow bucket (an infinite upper bound has no JSON spelling —
    ``json.dumps(inf)`` emits the non-standard ``Infinity`` token into
    the metrics jsonl; readers render None as "—")."""
    hist = np.asarray(hist)
    total = int(hist.sum())
    if not total:
        return None
    cum = np.cumsum(hist)
    idx = int(np.searchsorted(cum, p * total))
    if idx >= len(ITER_BUCKETS):
        return None  # overflow bucket: no finite upper edge.
    return ITER_BUCKETS[idx]


def _effort_summary(tel: TelemetryState) -> dict:
    """JSON-ready solver-effort block (the adaptive-effort observability
    section run_health renders): consensus-iteration histogram + mean /
    bucket-p99, and — when the controller tracked it — the PER-SOLVE
    inner-iteration histogram (inner total / consensus iters / fleet
    size — scale-free on the static bucket grid) and totals."""
    steps = int(np.asarray(tel.steps))
    iters_sum = int(np.asarray(tel.iters_sum))
    inner_sum = int(np.asarray(tel.inner_iters_sum))
    out = {
        "buckets": list(ITER_BUCKETS),
        "consensus_hist": [int(v) for v in np.asarray(tel.consensus_hist)],
        "iters_mean": (iters_sum / steps) if steps else None,
        "iters_p99": hist_percentile(tel.consensus_hist, 0.99),
    }
    if int(np.asarray(tel.inner_hist).sum()) or inner_sum:
        na = max(tel.n_agents, 1)
        out["inner_hist"] = [int(v) for v in np.asarray(tel.inner_hist)]
        out["inner_iters_sum"] = inner_sum
        out["n_agents"] = tel.n_agents
        out["inner_per_solve_mean"] = (
            inner_sum / (iters_sum * na) if iters_sum else None
        )
        out["inner_per_solve_p99"] = hist_percentile(tel.inner_hist, 0.99)
    return out


def summary(tel: TelemetryState, cfg: TelemetryConfig | None = None) -> dict:
    """Render an accumulator (device arrays or a host/numpy snapshot copy)
    to the JSON-ready dict ``obs.export`` embeds in metrics events.
    Quantile labels come from the state itself (``tel.quantiles``), so
    readers that only hold a snapshot — ``recovery.run_chunks``' boundary
    export — label non-default configs correctly; ``cfg`` is accepted for
    API symmetry but never consulted for them.

    A BATCHED accumulator (leading Monte-Carlo lane axis on every leaf —
    the vmapped chunk carry of ``scenario_rollout_resumable``) rolls up
    across lanes: counts/histograms sum, minima take the fleet min,
    maxima the fleet max, and each percentile reports the WORST lane's
    estimate (conservative for a health readout); ``lanes`` records the
    batch width."""
    del cfg
    if np.asarray(tel.steps).ndim:
        return _batched_summary(tel)
    count = int(np.asarray(tel.res_count))
    mean = float(np.asarray(tel.res_sum)) / count if count else None
    out = {
        "steps": int(np.asarray(tel.steps)),
        "rung_hist": [int(v) for v in np.asarray(tel.rung_hist)],
        "iters_sum": int(np.asarray(tel.iters_sum)),
        "ok_frac_min": float(np.asarray(tel.ok_frac_min)),
        "min_env_dist": float(np.asarray(tel.min_env_dist)),
        "collision_steps": int(np.asarray(tel.collision_steps)),
        "quarantine_steps": int(np.asarray(tel.quarantine_steps)),
        "effort": _effort_summary(tel),
        "residual": {
            "count": count,
            "min": float(np.asarray(tel.res_min)) if count else None,
            "max": float(np.asarray(tel.res_max)) if count else None,
            "mean": mean,
            **residual_percentiles(tel),
        },
    }
    if tel.agent_fail_steps.shape[0]:
        out["agent_fail_steps"] = [
            int(v) for v in np.asarray(tel.agent_fail_steps)
        ]
        out["agent_res_max"] = [
            float(v) for v in np.asarray(tel.agent_res_max)
        ]
    return out


def _rollup_effort(per: list[dict], iters_sums: list[int]) -> dict:
    """Cross-lane roll-up of per-lane effort blocks: histograms sum (every
    lane shares the static :data:`ITER_BUCKETS` grid), means recompute
    from the EXACT per-lane integer totals (``iters_sums`` — the lanes'
    ``iters_sum`` accumulators; reconstructing them from the float means
    would drift and silently assume steps == histogram count)."""
    nb = N_ITER_BUCKETS
    hist = [sum(p["consensus_hist"][i] for p in per) for i in range(nb)]
    steps = sum(h for h in hist)
    iters_sum = sum(iters_sums)
    out = {
        "buckets": list(ITER_BUCKETS),
        "consensus_hist": hist,
        "iters_mean": (iters_sum / steps) if steps else None,
        "iters_p99": hist_percentile(hist, 0.99),
    }
    inners = [p for p in per if "inner_hist" in p]
    if inners:
        ih = [sum(p["inner_hist"][i] for p in inners) for i in range(nb)]
        isum = sum(p["inner_iters_sum"] for p in inners)
        na = max(inners[0].get("n_agents", 0), 1)  # lanes share a fleet.
        out["inner_hist"] = ih
        out["inner_iters_sum"] = isum
        out["n_agents"] = inners[0].get("n_agents", 0)
        out["inner_per_solve_mean"] = (
            isum / (iters_sum * na) if iters_sum else None
        )
        out["inner_per_solve_p99"] = hist_percentile(ih, 0.99)
    return out


def _batched_summary(tel: TelemetryState) -> dict:
    """Cross-lane roll-up of a batched accumulator (see :func:`summary`)."""
    lanes = _lane_summaries(tel)
    per = [summary(t) for t in lanes]
    counts = [p["residual"]["count"] for p in per]
    total = sum(counts)
    out = {
        "lanes": len(per),
        "steps": max(p["steps"] for p in per),
        "rung_hist": [
            sum(p["rung_hist"][i] for p in per) for i in range(N_RUNGS)
        ],
        "iters_sum": sum(p["iters_sum"] for p in per),
        "effort": _rollup_effort(
            [p["effort"] for p in per], [p["iters_sum"] for p in per]
        ),
        "ok_frac_min": min(p["ok_frac_min"] for p in per),
        "min_env_dist": min(p["min_env_dist"] for p in per),
        "collision_steps": sum(p["collision_steps"] for p in per),
        "quarantine_steps": sum(p["quarantine_steps"] for p in per),
        "residual": {
            "count": total,
            "min": min(
                (p["residual"]["min"] for p in per
                 if p["residual"]["min"] is not None), default=None,
            ),
            "max": max(
                (p["residual"]["max"] for p in per
                 if p["residual"]["max"] is not None), default=None,
            ),
            "mean": (
                sum(p["residual"]["mean"] * c
                    for p, c in zip(per, counts) if c) / total
                if total else None
            ),
            # Worst lane per quantile: conservative fleet health readout.
            **{
                "p%g" % (q * 100): max(
                    (p["residual"]["p%g" % (q * 100)] for p in per
                     if p["residual"]["p%g" % (q * 100)] is not None),
                    default=None,
                )
                for q in tel.quantiles
            },
        },
    }
    if "agent_fail_steps" in per[0]:
        na = len(per[0]["agent_fail_steps"])
        out["agent_fail_steps"] = [
            sum(p["agent_fail_steps"][i] for p in per) for i in range(na)
        ]
        out["agent_res_max"] = [
            max(p["agent_res_max"][i] for p in per) for i in range(na)
        ]
    return out
