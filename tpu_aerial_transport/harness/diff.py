"""Differentiable simulation: gradient-based tuning through the physics.

A capability the reference's numpy/cvxpy stack cannot express and a direct
payoff of the models being pure jit-compiled pytree functions: the full
two-rate cascade (low-level SO(3) attitude control at 1 kHz inside manifold
integrator substeps) is differentiable end-to-end with ``jax.grad``, so
controller gains (or physical parameters) can be tuned by gradient descent
against a rollout loss instead of hand-tuning (the reference hand-scales its
gains from the Lee-2010 paper values, utils/so3_tracking_controllers.py and
control/rqp_centralized.py:487-497).

Long rollouts use ``jax.checkpoint`` rematerialization on the per-step
function: activation memory for the backward pass drops from
O(n_steps * n_sub) stored substates to O(n_steps) (each MPC-rate step's
substeps are recomputed on the backward sweep) — the standard TPU
FLOPs-for-HBM trade.

The high-level force law used here is a differentiable payload-space PD
share (equilibrium forces + equal-share payload acceleration demand), NOT
the conic-QP controllers: differentiating through hundreds of unrolled ADMM
iterations is possible but numerically and computationally pointless for
gain tuning; the low-level law and the physics are the differentiated
surface.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from tpu_aerial_transport.control import lowlevel as lowlevel_mod
from tpu_aerial_transport.control import so3_tracking
from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.models.rqp import RQPParams, RQPState
from tpu_aerial_transport.ops import lie


def substep_rollout(
    params: RQPParams,
    gains: dict,
    state: RQPState,
    f_des: jnp.ndarray,
    n_sub: int = 10,
    dt: float = 1e-3,
) -> RQPState:
    """The 1 kHz inner loop under a fixed high-level command: ``n_sub``
    substeps of SO(3)-PD low-level control (gains from the ``gains`` pytree)
    + manifold integration. The single differentiable implementation every
    rollout in this module (and its tests) shares — the recorded and the
    replayed trajectory must come from the same code path or system
    identification silently desynchronizes."""
    ll = so3_tracking.So3PDParams(k_R=gains["k_R"], k_Omega=gains["k_Omega"])

    def sub(s, _):
        f, M = lowlevel_mod.lowlevel_control(params.J, ll, s, f_des)
        return rqp.integrate(params, s, (f, M), dt), None

    state, _ = jax.lax.scan(sub, state, None, length=n_sub)
    return state


def payload_pd_forces(
    params: RQPParams,
    f_eq: jnp.ndarray,
    state: RQPState,
    xl_ref: jnp.ndarray,
    k_p: float = 2.0,
    k_d: float = 2.5,
) -> jnp.ndarray:
    """Differentiable high-level force law: equilibrium shares plus an
    equal-share payload-acceleration PD demand toward ``xl_ref`` —
    ``f_des_i = f_eq_i + (mT / n) (k_p (xl_ref - xl) - k_d vl)``."""
    acc = k_p * (xl_ref - state.xl) - k_d * state.vl
    share = (params.mT / params.n) * acc
    return f_eq + share[None, :]


def make_rollout_loss(
    params: RQPParams,
    f_eq: jnp.ndarray,
    xl_ref: jnp.ndarray,
    n_steps: int = 50,
    n_sub: int = 10,
    dt: float = 1e-3,
    remat: bool = True,
    k_p: float = 2.0,
    k_d: float = 2.5,
    k_att: float = 0.0,
) -> Callable:
    """Build ``loss(gains, state0) -> scalar``: mean squared payload position
    error to ``xl_ref`` plus a small velocity penalty over an ``n_steps``
    MPC-rate rollout (each step = ``n_sub`` 1 kHz low-level + physics
    substeps, the reference's two-rate cascade, rqp_example.py:120-131).

    ``gains`` is a pytree ``{"k_R": ..., "k_Omega": ...}`` of the SO(3) PD
    attitude gains (reference values 0.25 / 0.075); everything reaching the
    loss from it is jit- and grad-traceable. ``remat=True`` wraps the
    per-step function in ``jax.checkpoint`` so the backward pass re-computes
    substeps instead of storing every intermediate state.

    ``k_att`` weights an attitude-alignment term ``sum_i tr(I - Rd_i^T R_i)``
    (the geodesic-distance surrogate of the Lee-2010 error the SO(3) law
    minimizes). Near hover the payload-position loss is nearly flat in the
    attitude gains (thrusts stay aligned regardless), so pure position loss
    gives vanishing gradients; a nonzero ``k_att`` makes the attitude loop
    itself part of the objective.
    """

    def mpc_step(state: RQPState, gains):
        f_des = payload_pd_forces(params, f_eq, state, xl_ref, k_p, k_d)
        state = substep_rollout(params, gains, state, f_des, n_sub, dt)
        err = state.xl - xl_ref
        cost = jnp.sum(err * err) + 0.1 * jnp.sum(state.vl * state.vl)
        if k_att:
            qd = f_des / jnp.linalg.norm(f_des, axis=-1, keepdims=True)
            Rd = lie.rotation_from_z(qd)
            align = jnp.einsum("nij,nij->", Rd, state.R)  # sum_i tr(Rd^T R)
            cost = cost + k_att * (3.0 * params.n - align)
        return state, cost

    step = jax.checkpoint(mpc_step) if remat else mpc_step

    def loss(gains, state0: RQPState) -> jnp.ndarray:
        def body(s, _):
            s, c = step(s, gains)
            return s, c

        _, costs = jax.lax.scan(body, state0, None, length=n_steps)
        return jnp.mean(costs)

    return loss


def simulate_commands(
    params: RQPParams,
    gains: dict,
    f_des_seq: jnp.ndarray,
    state0: RQPState,
    n_sub: int = 10,
    dt: float = 1e-3,
    remat: bool = True,
):
    """Roll the model under a RECORDED high-level command sequence
    ``f_des_seq (T, n, 3)`` (the low-level SO(3) loop still closes on the
    simulated state, as on the real system): returns ``(xl_seq (T, 3),
    vl_seq (T, 3))`` at the MPC rate. The replay half of system
    identification — commands logged, states observed."""

    def mpc_step(state: RQPState, f_des):
        state = substep_rollout(params, gains, state, f_des, n_sub, dt)
        return state, (state.xl, state.vl)

    step = jax.checkpoint(mpc_step) if remat else mpc_step
    _, (xl_seq, vl_seq) = jax.lax.scan(step, state0, f_des_seq)
    return xl_seq, vl_seq


def make_sysid_loss(
    m: jnp.ndarray,
    J: jnp.ndarray,
    Jl: jnp.ndarray,
    r: jnp.ndarray,
    gains: dict,
    f_des_seq: jnp.ndarray,
    xl_obs: jnp.ndarray,
    vl_obs: jnp.ndarray,
    n_sub: int = 10,
    dt: float = 1e-3,
) -> Callable:
    """System identification by gradient: ``loss(theta, state0)`` replays the
    recorded commands through a candidate model with payload mass
    ``ml = exp(theta["log_ml"])`` (log parameterization keeps the mass
    positive) and scores the trajectory mismatch against the observations.
    ``rqp_params`` recomputes every derived quantity (total mass, CoM shift,
    composite inertia and its inverse) inside the differentiated graph, so
    the gradient sees the full physical coupling — the reference's numpy
    parameter struct (RQPParameters, system/rigid_quadrotor_payload.py:48-84)
    has no analogue of this."""

    def loss(theta, state0: RQPState) -> jnp.ndarray:
        params = rqp.rqp_params(m, J, jnp.exp(theta["log_ml"]), Jl, r)
        xl_seq, vl_seq = simulate_commands(
            params, gains, f_des_seq, state0, n_sub=n_sub, dt=dt
        )
        exl = xl_seq - xl_obs
        evl = vl_seq - vl_obs
        return jnp.mean(jnp.sum(exl * exl, -1) + 0.1 * jnp.sum(evl * evl, -1))

    return loss


def make_trajopt_loss(
    params: RQPParams,
    f_eq: jnp.ndarray,
    goal: jnp.ndarray,
    n_steps: int = 40,
    n_sub: int = 10,
    dt: float = 1e-3,
    gains: dict | None = None,
    obstacle_xy: jnp.ndarray | None = None,
    obstacle_radius: float = 0.5,
    w_effort: float = 1e-3,
    w_obstacle: float = 30.0,
) -> Callable:
    """Trajectory optimization through the physics: ``loss(plan, state0)``
    rolls the full two-rate cascade under a per-step payload-acceleration
    schedule ``plan["acc"] (n_steps, 3)`` (shared equally by the agents on
    top of the equilibrium forces) and scores terminal goal distance +
    control effort + a soft obstacle-clearance penalty (squared hinge on an
    xy-cylinder of radius ``obstacle_radius``). Descending it with
    :func:`tune_gains` (``min_gain=None``) is direct single-shooting optimal
    control — the third capability the pure-pytree models buy that the
    reference's numpy stack cannot express (gain tuning and system
    identification being the other two)."""
    gains = gains or {"k_R": jnp.asarray(0.25), "k_Omega": jnp.asarray(0.075)}

    def mpc_step(state: RQPState, acc):
        state = substep_rollout(
            params, gains, state,
            plan_share_forces(params, f_eq, acc), n_sub, dt,
        )
        cost = w_effort * jnp.sum(acc * acc)
        if obstacle_xy is not None:
            d = jnp.linalg.norm(state.xl[:2] - obstacle_xy)
            cost = cost + w_obstacle * jnp.maximum(
                obstacle_radius - d, 0.0
            ) ** 2
        return state, cost

    step = jax.checkpoint(mpc_step)

    def loss(plan, state0: RQPState) -> jnp.ndarray:
        if plan["acc"].shape[0] != n_steps:
            raise ValueError(
                f"plan horizon {plan['acc'].shape[0]} != n_steps {n_steps}"
            )
        state, costs = jax.lax.scan(step, state0, plan["acc"])
        err = state.xl - goal
        vel = state.vl
        return (jnp.sum(err * err) + 0.1 * jnp.sum(vel * vel)
                + jnp.sum(costs))

    return loss


def plan_share_forces(params: RQPParams, f_eq: jnp.ndarray,
                      acc: jnp.ndarray) -> jnp.ndarray:
    """The trajopt plan's force law — equilibrium shares plus an equal-share
    payload-acceleration demand. Exposed so replays (tests, analysis) roll
    the exact system the plan was optimized for."""
    return f_eq + (params.mT / params.n) * acc[None, :]


def tune_gains(
    loss: Callable,
    gains0: dict,
    state0: RQPState,
    lr: float = 0.05,
    iters: int = 30,
    min_gain: float | None = 1e-4,
    optimizer: str = "sgd",
):
    """Projected gradient descent on the rollout loss. ``min_gain`` floors
    every parameter after each step (gains must stay positive for the SO(3)
    law to be stabilizing); pass ``None`` for unconstrained parameters —
    e.g. LOG-parameterized quantities like ``make_sysid_loss``'s
    ``log_ml``, which are legitimately negative and must not be floored.

    ``optimizer``: ``"sgd"`` (default — 1-2-parameter tuning problems) or
    ``"adam"`` (optax; needed when the parameter spectrum is badly
    conditioned, e.g. :func:`make_trajopt_loss`'s per-step plan where
    terminal-error and effort modes differ by ~1e5 in curvature and any
    single SGD step size either diverges or crawls).

    The entire loop is one jitted program. Returns ``(best_gains,
    loss_history (iters + 1,))`` — the best iterate seen, not the last (a
    fixed step can overshoot the valley and oscillate; the best-so-far
    selection makes the result monotone in ``iters``)."""
    vg = jax.value_and_grad(loss)
    if optimizer == "sgd":
        # Hand-rolled: the default path stays free of the optax dependency.
        opt = None
    elif optimizer == "adam":
        import optax

        opt = optax.adam(lr)
    else:
        raise ValueError(optimizer)

    def project(g):
        return g if min_gain is None else jnp.maximum(g, min_gain)

    def body(carry, _):
        gains, opt_state, best_gains, best_val = carry
        val, grad = vg(gains, state0)
        better = val < best_val
        best_gains = jax.tree.map(
            lambda b, g: jnp.where(better, g, b), best_gains, gains
        )
        best_val = jnp.minimum(best_val, val)
        if opt is None:  # plain SGD.
            gains = jax.tree.map(
                lambda g, d: project(g - lr * d), gains, grad
            )
        else:
            updates, opt_state = opt.update(grad, opt_state, gains)
            gains = jax.tree.map(
                lambda g, u: project(g + u), gains, updates
            )
        return (gains, opt_state, best_gains, best_val), val

    @jax.jit
    def run(gains0):
        opt_state0 = () if opt is None else opt.init(gains0)
        init = (gains0, opt_state0, gains0, jnp.asarray(jnp.inf))
        (gains, _, best_gains, best_val), hist = jax.lax.scan(
            body, init, None, length=iters
        )
        final_val = loss(gains, state0)
        better = final_val < best_val
        best_gains = jax.tree.map(
            lambda b, g: jnp.where(better, g, b), best_gains, gains
        )
        return best_gains, jnp.concatenate([hist, final_val[None]])

    return run(gains0)
