"""Checkpoint / resume for rollouts and solver state.

The reference's persistence story is trajectory-level only: the finished run is
pickled (example/rqp_example.py:141-165) and later replayed, with the forest
reconstructed from logged tree positions (rqp_plots.py:503-505); there is no
mid-run resume (SURVEY.md §5.4). Here both levels exist:

- :func:`save_run` / :func:`load_run` — the reference's artifact: the log dict
  (npz) including tree positions, so plotting/replay tools work unchanged.
- :func:`save_state` / :func:`load_state` — mid-run resume: any pytree
  (``(RQPState, CtrlState/CADMMState/DDState)`` scan carry included) via orbax,
  so a 100 s rollout can be split into segments or recovered after preemption.
  Forest regeneration stays deterministic through ``make_forest(seed)``.
"""

from __future__ import annotations

import os

import jax
import numpy as np


def save_run(path: str, log_dict: dict) -> None:
    """Persist a rollout log dict (from ``rollout.logs_to_dict``) as npz."""
    flat = {}
    for k, v in log_dict.items():
        if isinstance(v, dict):
            for k2, v2 in v.items():
                flat[f"{k}.{k2}"] = np.asarray(v2)
        else:
            flat[k] = np.asarray(v)
    np.savez_compressed(path, **flat)


def load_run(path: str) -> dict:
    """Inverse of :func:`save_run`; nested keys are restored."""
    raw = np.load(path, allow_pickle=False)
    out: dict = {}
    for k in raw.files:
        v = raw[k]
        if v.ndim == 0:
            v = v.item()
        if "." in k:
            outer, inner = k.split(".", 1)
            out.setdefault(outer, {})[inner] = v
        else:
            out[k] = v
    return out


def save_state(path: str, state) -> None:
    """Checkpoint an arbitrary pytree (scan carry, solver state) with orbax."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, state, force=True)


def load_state(path: str, template):
    """Restore a pytree checkpoint; ``template`` supplies structure/dtypes
    (pass the same pytree shape you saved, e.g. a freshly-initialized state)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(path, item=template)
    return jax.tree.map(lambda t, r: jax.numpy.asarray(r, t.dtype)
                        if hasattr(t, "dtype") else r, template, restored)
