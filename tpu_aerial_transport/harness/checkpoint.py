"""Checkpoint / resume for rollouts and solver state.

The reference's persistence story is trajectory-level only: the finished run
is pickled (example/rqp_example.py:141-165) and later replayed, with the
forest reconstructed from logged tree positions (rqp_plots.py:503-505);
there is no mid-run resume (SURVEY.md §5.4). Here three levels exist:

- :func:`save_run` / :func:`load_run` — the reference's artifact: the log
  dict (npz) including tree positions, so plotting/replay tools work
  unchanged.
- :func:`save_state` / :func:`load_state` — loose mid-run pytree persistence
  via the installed backend (orbax when present, npz otherwise —
  ``utils.compat.pytree_io``). No integrity metadata; kept for ad-hoc use.
- :func:`save_snapshot` / :func:`load_snapshot` / :func:`load_latest_valid`
  — the crash-recovery tier (``resilience.recovery`` drives it): atomic
  versioned snapshots with a schema version, a pytree treedef fingerprint,
  per-leaf payload digests, and a caller-supplied config hash. Writes are
  temp-file + ``os.replace`` (a crash mid-write can never truncate a
  published snapshot), retention is keep-last-K, and ``load`` classifies
  truncation / corruption / structure drift / config mismatch into a
  structured :class:`SnapshotError` instead of returning garbage —
  :func:`load_latest_valid` then falls back to the newest snapshot that
  passes every check.

Snapshot container: one uncompressed ``.ckpt`` file in npz layout —
``__manifest__`` (UTF-8 JSON as a uint8 array) plus ``leaf_NNNNNN`` arrays
in ``jax.tree.flatten`` order. Exact bytes in, exact bytes out: leaves are
stored at their on-device dtype and restored with it, so resume is
bit-exact (no pickled objects anywhere; ``allow_pickle=False`` on read).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re

import jax
import numpy as np

SCHEMA_VERSION = 1

_MANIFEST_KEY = "__manifest__"
# The prefix grammar is shared by snapshot_path (write side) and
# list_snapshots (read side): a prefix the filename pattern cannot parse
# back would produce snapshots that are published but invisible to
# retention and recovery, so snapshot_path validates against the same rule.
_PREFIX_RE = re.compile(r"^[A-Za-z0-9_.]+$")
_SNAP_RE = re.compile(r"^(?P<prefix>[A-Za-z0-9_.]+)-(?P<step>\d{8})\.ckpt$")


def save_run(path: str, log_dict: dict) -> None:
    """Persist a rollout log dict (from ``rollout.logs_to_dict``) as npz."""
    flat = {}
    for k, v in log_dict.items():
        if isinstance(v, dict):
            for k2, v2 in v.items():
                flat[f"{k}.{k2}"] = np.asarray(v2)
        else:
            flat[k] = np.asarray(v)
    np.savez_compressed(path, **flat)


def load_run(path: str) -> dict:
    """Inverse of :func:`save_run`; nested keys are restored. 0-d arrays
    come back as numpy SCALARS of the saved dtype (``v[()]``) — the
    previous ``v.item()`` silently widened e.g. a saved ``np.float32``
    scalar to a Python float, so a save/load/save cycle changed dtypes
    (regression-tested in tests/test_checkpoint.py)."""
    raw = np.load(path, allow_pickle=False)
    out: dict = {}
    for k in raw.files:
        v = raw[k]
        if v.ndim == 0:
            v = v[()]
        if "." in k:
            outer, inner = k.split(".", 1)
            out.setdefault(outer, {})[inner] = v
        else:
            out[k] = v
    return out


def save_state(path: str, state) -> None:
    """Checkpoint an arbitrary pytree (scan carry, solver state) with the
    installed backend — orbax when present, the npz fallback otherwise
    (``utils.compat.pytree_io``; before the shim this hard-ImportError'd
    without orbax)."""
    from tpu_aerial_transport.utils import compat

    save, _, _ = compat.pytree_io()
    save(os.path.abspath(path), state)


def load_state(path: str, template):
    """Restore a pytree checkpoint; ``template`` supplies structure/dtypes
    (pass the same pytree shape you saved, e.g. a freshly-initialized state)."""
    from tpu_aerial_transport.utils import compat

    _, restore, _ = compat.pytree_io()
    restored = restore(os.path.abspath(path), template)
    return jax.tree.map(lambda t, r: jax.numpy.asarray(r, t.dtype)
                        if hasattr(t, "dtype") else r, template, restored)


# ----------------------------------------------------------------------
# Crash-recovery snapshot tier.
# ----------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SnapshotError(Exception):
    """Structured load failure — the machine-readable record
    ``resilience.recovery`` journals when it skips a snapshot.

    kind: ``unreadable`` (truncated/not-a-zip/missing manifest),
    ``corrupt`` (a leaf's payload digest mismatches its manifest entry),
    ``schema`` (written by a newer format), ``structure_mismatch`` (treedef
    fingerprint differs from the template's), ``config_mismatch`` (the
    run's params/config hash changed — resuming would silently mix
    configurations), ``no_valid_snapshot`` (every candidate failed;
    ``errors`` holds the per-file reasons).
    """

    kind: str
    path: str
    detail: str = ""
    errors: tuple = ()

    def __str__(self) -> str:
        msg = f"[{self.kind}] {self.path}: {self.detail}"
        if self.errors:
            msg += "".join(f"\n  - {e}" for e in self.errors)
        return msg


def tree_fingerprint(tree) -> str:
    """Stable fingerprint of a pytree's STRUCTURE: treedef string plus
    per-leaf shape/dtype, hashed. Works on concrete arrays and on
    ``jax.eval_shape`` outputs (ShapeDtypeStructs) alike, so a resume
    driver can fingerprint the expected carry without running a chunk."""
    leaves, treedef = jax.tree.flatten(tree)
    spec = [str(treedef)] + [
        f"{tuple(getattr(l, 'shape', ()))}:{np.dtype(getattr(l, 'dtype', type(l))).str}"
        for l in leaves
    ]
    return hashlib.sha256("\n".join(spec).encode()).hexdigest()[:32]


def config_fingerprint(**named) -> str:
    """Hash of named configuration objects (params, controller config,
    fault schedule, CLI args...), such that ANY config drift between save
    and resume flips the hash and :func:`load_snapshot` refuses the mix.

    Array leaves are hashed from their full bytes + shape/dtype, NOT their
    repr: numpy/jax array reprs summarize interiors with ``...`` beyond
    ~1000 elements, so two different big-fleet params tables (or long
    per-step fault schedules) would repr — and therefore hash — identical.
    Non-array leaves keep the repr path (the configs here are flax struct
    / frozen dataclasses whose reprs are deterministic and
    value-complete)."""

    def _digest(v) -> str:
        leaves, treedef = jax.tree.flatten(v)
        parts = [repr(treedef)]
        for leaf in leaves:
            if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
                a = np.asarray(leaf)
                parts.append(
                    f"ndarray:{a.dtype}:{a.shape}:"
                    + hashlib.sha256(
                        np.ascontiguousarray(a).tobytes()
                    ).hexdigest()
                )
            else:
                parts.append(repr(leaf))
        return "\x00".join(parts)

    blob = json.dumps({k: _digest(v) for k, v in sorted(named.items())})
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


def snapshot_path(directory: str, step: int, prefix: str = "snap") -> str:
    if not _PREFIX_RE.match(prefix):
        raise ValueError(
            f"snapshot prefix {prefix!r} must match {_PREFIX_RE.pattern} "
            "(list_snapshots could not parse the filename back, making the "
            "snapshot invisible to retention and recovery)"
        )
    return os.path.join(directory, f"{prefix}-{step:08d}.ckpt")


def list_snapshots(directory: str, prefix: str = "snap") -> list[tuple[int, str]]:
    """``(step, path)`` pairs for every published snapshot, step-ascending.
    In-flight temp files (``*.tmp.*``) are invisible by construction."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        m = _SNAP_RE.match(name)
        if m and m.group("prefix") == prefix:
            out.append((int(m.group("step")), os.path.join(directory, name)))
    return sorted(out)


def save_snapshot(
    directory: str,
    step: int,
    state,
    *,
    prefix: str = "snap",
    config_hash: str | None = None,
    meta: dict | None = None,
    keep_last: int = 3,
) -> str:
    """Atomically publish snapshot ``step`` of ``state`` under
    ``directory`` and prune to the newest ``keep_last`` (0 disables
    pruning). The file appears under its final name only after a complete,
    fsync'd write (temp file + ``os.replace``), so a crash at ANY byte
    leaves either the previous snapshot set or the new one — never a
    half-written file under a valid name. Returns the published path."""
    os.makedirs(directory, exist_ok=True)
    leaves = [np.asarray(l) for l in jax.tree.leaves(state)]
    manifest = {
        "schema": SCHEMA_VERSION,
        "step": int(step),
        "treedef": tree_fingerprint(state),
        "config_hash": config_hash,
        "leaves": [
            {
                "shape": list(l.shape),
                "dtype": l.dtype.str,
                "sha256": hashlib.sha256(
                    np.ascontiguousarray(l).tobytes()
                ).hexdigest(),
            }
            for l in leaves
        ],
        "meta": meta or {},
    }
    arrs = {f"leaf_{i:06d}": l for i, l in enumerate(leaves)}
    arrs[_MANIFEST_KEY] = np.frombuffer(
        json.dumps(manifest).encode(), np.uint8
    )
    path = snapshot_path(directory, step, prefix)
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "wb") as fh:
        # Uncompressed: snapshots are hot-path IO and the payload is
        # mostly incompressible f32 state; digests protect integrity.
        np.savez(fh, **arrs)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    if keep_last > 0:
        for _, old in list_snapshots(directory, prefix)[:-keep_last]:
            os.remove(old)
    return path


def _parse_manifest(raw, path: str) -> dict:
    """Manifest from an open npz handle (schema-checked); raises
    :class:`SnapshotError` (kind ``unreadable``/``schema``)."""
    if _MANIFEST_KEY not in raw.files:
        raise SnapshotError("unreadable", path, "manifest missing")
    manifest = json.loads(bytes(raw[_MANIFEST_KEY]).decode())
    if manifest.get("schema", -1) > SCHEMA_VERSION:
        raise SnapshotError(
            "schema", path,
            f"written by schema {manifest.get('schema')} > supported "
            f"{SCHEMA_VERSION}",
        )
    return manifest


def read_manifest(path: str) -> dict:
    """Manifest of a snapshot file, or raise :class:`SnapshotError`
    (kind ``unreadable``/``schema``)."""
    try:
        with np.load(path, allow_pickle=False) as raw:
            return _parse_manifest(raw, path)
    except SnapshotError:
        raise
    except Exception as e:  # truncated zip, bad CRC, bad JSON, missing file
        raise SnapshotError(
            "unreadable", path, f"{type(e).__name__}: {e}"
        ) from e


def load_snapshot(
    path: str,
    template,
    *,
    config_hash: str | None = None,
):
    """Verify and restore one snapshot into ``template``'s structure.

    Every check runs BEFORE any data is trusted: container readability and
    schema (:func:`read_manifest`), per-leaf payload digests (bit-rot /
    torn writes that survived the zip CRC), treedef fingerprint against
    ``template`` (a ShapeDtypeStruct tree from ``jax.eval_shape`` works),
    and — when both sides supply one — the config hash. Failure raises a
    structured :class:`SnapshotError`; success returns
    ``(state, manifest)`` with every leaf restored at its SAVED dtype
    (bit-exact, independent of the template's concrete values). The file
    is opened ONCE — manifest checks run before any leaf payload is read,
    so a refused snapshot costs one zip-directory parse, not a full read
    (resume validates whole log prefixes through this path)."""
    try:
        with np.load(path, allow_pickle=False) as raw:
            manifest = _parse_manifest(raw, path)
            if (config_hash is not None
                    and manifest.get("config_hash") is not None
                    and manifest["config_hash"] != config_hash):
                raise SnapshotError(
                    "config_mismatch", path,
                    f"snapshot config {manifest['config_hash']} != current "
                    f"{config_hash}: resuming would mix configurations",
                )
            if manifest.get("treedef") != tree_fingerprint(template):
                raise SnapshotError(
                    "structure_mismatch", path,
                    "snapshot pytree structure differs from the template "
                    "(carry schema drifted since the run was started)",
                )
            leaves = [raw[f"leaf_{i:06d}"]
                      for i in range(len(manifest["leaves"]))]
    except SnapshotError:
        raise
    except Exception as e:
        raise SnapshotError(
            "unreadable", path, f"{type(e).__name__}: {e}"
        ) from e
    for i, (leaf, spec) in enumerate(zip(leaves, manifest["leaves"])):
        digest = hashlib.sha256(
            np.ascontiguousarray(leaf).tobytes()
        ).hexdigest()
        if digest != spec["sha256"]:
            raise SnapshotError(
                "corrupt", path,
                f"leaf {i} payload digest mismatch (stored "
                f"{spec['sha256'][:12]}, read {digest[:12]})",
            )
    treedef = jax.tree.structure(template)
    state = jax.tree.unflatten(
        treedef, [jax.numpy.asarray(l) for l in leaves]
    )
    return state, manifest


# ----------------------------------------------------------------------
# Multi-process (pods) shard snapshots: per-process shard files + ONE
# global manifest (parallel/pods.py drives this tier).
# ----------------------------------------------------------------------

def shard_prefix(prefix: str, process_id: int, n_processes: int) -> str:
    """Snapshot prefix for one process's shard of a sharded carry —
    ``carry.p0of2`` — inside the normal prefix grammar, so retention,
    :func:`list_snapshots` and recovery see shard snapshots like any
    other snapshot family. Each process writes ONLY its own prefix (no
    cross-process file races); the shard manifest below ties the set
    together."""
    if not 0 <= process_id < n_processes:
        raise ValueError(f"process_id {process_id} not in [0, {n_processes})")
    return f"{prefix}.p{process_id}of{n_processes}"


def shard_manifest_path(directory: str, prefix: str = "snap") -> str:
    return os.path.join(directory, f"{prefix}.shards.json")


def save_shard_manifest(
    directory: str,
    *,
    prefix: str = "snap",
    n_processes: int,
    topology: dict | None = None,
    config_hash: str | None = None,
) -> str:
    """Atomically publish the GLOBAL manifest for a sharded snapshot
    family: how many per-process shard prefixes make a complete boundary,
    plus the topology the carry was sharded under and the run's config
    hash. Written by process 0 ONCE per run (the topology is static); a
    resume on a rebuilt mesh validates against it BEFORE trusting any
    shard (:func:`load_shard_manifest`) — the config-hash refusal covers
    topology drift because the pods runner folds the topology into the
    hash."""
    path = shard_manifest_path(directory, prefix)
    os.makedirs(directory, exist_ok=True)
    payload = {
        "schema": SCHEMA_VERSION,
        "prefix": prefix,
        "n_processes": int(n_processes),
        "shard_prefixes": [
            shard_prefix(prefix, p, n_processes) for p in range(n_processes)
        ],
        "topology": topology or {},
        "config_hash": config_hash,
    }
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=1)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return path


def load_shard_manifest(
    directory: str,
    *,
    prefix: str = "snap",
    n_processes: int | None = None,
    config_hash: str | None = None,
) -> dict:
    """Read + validate the shard manifest. Raises :class:`SnapshotError`:
    ``unreadable`` when missing/corrupt, ``schema`` for a newer writer,
    ``config_mismatch`` when the rebuilt mesh's process count or the
    config hash disagrees with what the shards were written under —
    re-placing 2-process shards on a 4-process mesh would silently load
    half a carry per process."""
    path = shard_manifest_path(directory, prefix)
    try:
        with open(path) as fh:
            manifest = json.load(fh)
    except Exception as e:
        raise SnapshotError(
            "unreadable", path, f"{type(e).__name__}: {e}"
        ) from e
    if manifest.get("schema", -1) > SCHEMA_VERSION:
        raise SnapshotError(
            "schema", path,
            f"written by schema {manifest.get('schema')} > supported "
            f"{SCHEMA_VERSION}",
        )
    if (n_processes is not None
            and manifest.get("n_processes") != n_processes):
        raise SnapshotError(
            "config_mismatch", path,
            f"shards written by {manifest.get('n_processes')} processes, "
            f"resuming with {n_processes}: re-placing would split the "
            "carry wrong (rebuild the mesh with the journaled topology "
            "or restart fresh)",
        )
    if (config_hash is not None
            and manifest.get("config_hash") is not None
            and manifest["config_hash"] != config_hash):
        raise SnapshotError(
            "config_mismatch", path,
            f"shard manifest config {manifest['config_hash']} != current "
            f"{config_hash}: resuming would mix configurations/topologies",
        )
    return manifest


def load_latest_valid(
    directory: str,
    template,
    *,
    prefix: str = "snap",
    config_hash: str | None = None,
):
    """Newest snapshot that passes EVERY integrity check, walking backwards
    over older snapshots on failure (the keep-last-K retention exists
    exactly so there is something to fall back to). Returns
    ``(state, manifest, skipped)`` where ``skipped`` lists the structured
    errors of every newer snapshot that was rejected; raises
    :class:`SnapshotError` (kind ``no_valid_snapshot``) when none survive."""
    skipped: list[SnapshotError] = []
    for _, path in reversed(list_snapshots(directory, prefix)):
        try:
            state, manifest = load_snapshot(
                path, template, config_hash=config_hash
            )
            return state, manifest, skipped
        except SnapshotError as e:
            skipped.append(e)
    raise SnapshotError(
        "no_valid_snapshot", directory,
        f"no loadable '{prefix}' snapshot",
        errors=tuple(str(e) for e in skipped),
    )
