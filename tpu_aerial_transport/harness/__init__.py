"""Simulation harness (reference ``example/``): setup factories, two-rate
jit-compiled rollouts, log schema."""

from tpu_aerial_transport.harness import rollout, setup  # noqa: F401
