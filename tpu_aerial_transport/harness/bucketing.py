"""Congestion-bucketed Monte-Carlo batching: worst-lane decoupling for
vmapped consensus loops.

A vmapped ``lax.while_loop`` runs every lane to the batch's worst-case trip
count (converged lanes' carries freeze, but their per-iteration cost is still
paid), so one congested scenario drags the whole batch (BASELINE.md round 2
quantified this at ~25 ms of the headline step). Consensus iteration counts
correlate with how many obstacle CBF rows are active, which is observable
BEFORE solving — so: sort the batch by a cheap congestion metric, split into
``n_buckets`` contiguous groups, and run the step's consensus loop once per
group. Quiet buckets drain at their own (small) worst case; only the
congested bucket pays the deep trip count. Per-scenario results are exactly
the unbucketed ones (same solves, same data, just grouped) — asserted by
tests/test_bucketing.py.

Cost model: bucket b's time ~ (B / n_buckets) x worst_iters(b) + fixed
overhead per bucket (kernel dispatch, gathers). Wins when iteration counts
are heavy-tailed across the batch; loses slightly when uniform. Measured
A/B lives in bench.py (``--buckets``).

No reference counterpart: the reference solves scenarios one at a time in a
Python loop (test_rqpcontrollers.py:112-124) and never faces batch coupling.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp


def bucket_dim(d: int, tile: int) -> int:
    """Round a static dim up to its shape bucket (the next ``tile``
    multiple). This is the SHAPE-bucketing twin of the congestion bucketing
    below: ops/socp.py's padded-operator tier (``padded_dims``) routes every
    QP family's operator edges through this rounding, so heterogeneous
    per-agent dims (C-ADMM reduced d = 37, DD d = 49, ...) land on a coarse
    grid of tile multiples and families whose padded shapes coincide share
    one compiled solver program (the jit cache keys on the bucket, not the
    raw dim)."""
    if d < 0 or tile <= 0:
        raise ValueError((d, tile))
    return ((d + tile - 1) // tile) * tile


def pick_bucket(size: int, buckets: Sequence[int]) -> int | None:
    """Smallest bucket that ADMITS ``size`` (bucket >= size), or ``None``
    when no bucket does. THE shared bucket-selection rule: the AOT
    loader's ``variant_for_batch`` (which precompiled batch variant serves
    a request batch) and the serving tier's batcher (which device-batch
    size a group of admitted requests lands on) both route through here,
    so "smallest admitting bucket" has exactly one definition.

    Ties (duplicate bucket values) resolve to that value — the caller's
    variant list order decides between equal-sized variants. Callers that
    want the PR-8 loader semantics ("largest bucket when the request
    exceeds every bucket, caller truncates/splits") handle the ``None``
    themselves; admission control instead REJECTS on ``None`` for
    per-request shapes (no coverage) and splits batches for counts.
    """
    if size < 0:
        raise ValueError(f"pick_bucket: negative size {size}")
    if not buckets:
        raise ValueError("pick_bucket: empty bucket list")
    admitting = [b for b in buckets if b >= size]
    return min(admitting) if admitting else None


def _take(tree, idx):
    return jax.tree.map(lambda x: jnp.take(x, idx, axis=0), tree)


def _slice(tree, lo, hi):
    return jax.tree.map(lambda x: x[lo:hi], tree)


def _concat(trees):
    return jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *trees)


def env_congestion_metric(forest, vision_radius: float) -> Callable:
    """Congestion metric for the forest env: number of trees whose axis lies
    within ``vision_radius`` of the payload position — an O(num_trees)
    distance sweep, ~free next to one consensus iteration, and a direct
    proxy for how many env-CBF rows will be active."""

    def metric(state):
        d = jnp.linalg.norm(
            forest.tree_pos[:, :2] - state.xl[None, :2], axis=-1
        )
        alive = jnp.arange(forest.tree_pos.shape[0]) < forest.num_trees
        return jnp.sum((d < vision_radius) & alive)

    return metric


def quarantine_guarded_metric(metric_fn: Callable) -> Callable:
    """Wrap a congestion metric so a quarantined/diverged scenario (any
    non-finite leaf in its state) maps to -1 — sorted into the quietest
    bucket with a well-defined key — instead of feeding NaN/garbage
    distances into the argsort that groups the batch. Compose with
    :func:`env_congestion_metric` when running bucketed Monte-Carlo under
    the resilience layer's NaN quarantine."""
    from tpu_aerial_transport.resilience.quarantine import tree_all_finite

    def metric(state):
        m = metric_fn(state)
        return jnp.where(tree_all_finite(state), m, -1)

    return metric


def bucketed_step(step_fn: Callable, metric_fn: Callable,
                  n_buckets: int = 2) -> Callable:
    """Wrap a per-scenario MPC step ``step_fn(cs, state) -> (cs, state,
    stats)`` into a batched step that runs ``n_buckets`` separate vmapped
    consensus loops grouped by ascending ``metric_fn(state)``.

    The batch size must be divisible by ``n_buckets`` (static shapes). The
    returned function maps ``(css, states) -> (css, states, stats)`` with
    leading batch axes, bit-identical per scenario to ``vmap(step_fn)``
    modulo lane order (results are scattered back to input order).
    """
    if n_buckets < 2:
        return jax.vmap(step_fn)

    def batched(css, states):
        B = jax.tree.leaves(states)[0].shape[0]
        if B % n_buckets != 0:
            divisors = [d for d in range(2, B + 1) if B % d == 0]
            raise ValueError(
                f"bucketed_step: batch size {B} is not divisible by "
                f"n_buckets={n_buckets} (static shapes require equal "
                f"buckets); valid bucket counts for this batch: {divisors}"
            )
        per = B // n_buckets
        m = jax.vmap(metric_fn)(states)
        order = jnp.argsort(m)
        inv = jnp.argsort(order)
        css_s = _take(css, order)
        states_s = _take(states, order)
        outs = []
        for b in range(n_buckets):
            outs.append(jax.vmap(step_fn)(
                _slice(css_s, b * per, (b + 1) * per),
                _slice(states_s, b * per, (b + 1) * per),
            ))
        out = _concat(outs)
        return _take(out, inv)

    return batched
