"""End-to-end receding-horizon rollout harness: the ``main()`` of reference
``example/rqp_example.py`` re-designed as one jit-compiled two-rate ``lax.scan``.

Reference hot loop (rqp_example.py:120-137): 1 kHz physics with high-level control
every ``hl_rel_freq = 10`` steps (100 Hz) and logging at the HL rate. Here the
outer scan runs over HL control steps and an inner scan runs the ``hl_rel_freq``
physics substeps, so the entire simulation — env query, conic solve, low-level
SO(3) control, manifold integration, logging — is a single XLA computation that
can be vmapped over Monte-Carlo scenarios and sharded over a mesh.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from tpu_aerial_transport.control.types import SolverStats
from tpu_aerial_transport.envs import forest as forest_mod
from tpu_aerial_transport.models import rqp
from tpu_aerial_transport.obs import phases
from tpu_aerial_transport.obs import telemetry as telemetry_mod


@struct.dataclass
class RQPLogStep:
    """Per-HL-step log record (reference ``RQPStateData`` + the error/stat
    sequences, rqp_example.py:23-30,111-137). One leading time axis after scan."""

    xl: jnp.ndarray
    vl: jnp.ndarray
    Rl: jnp.ndarray
    wl: jnp.ndarray
    R: jnp.ndarray
    w: jnp.ndarray
    f_des: jnp.ndarray
    x_err: jnp.ndarray
    v_err: jnp.ndarray
    iters: jnp.ndarray
    solve_res: jnp.ndarray
    collision: jnp.ndarray
    min_env_dist: jnp.ndarray
    # Resilience extensions (defaults keep the nominal harness construction
    # unchanged; resilience.rollout.resilient_rollout fills them):
    # fallback-ladder rung taken this step (see SolverStats.fallback_rung)
    # and the sticky per-scenario NaN-quarantine flag.
    fallback_rung: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((), jnp.int32)
    )
    quarantined: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.zeros((), bool)
    )


def make_forest_acc_des(forest: forest_mod.Forest):
    """Terrain-following constant-velocity tracking reference (reference
    ``_desired_acceleration_forest``, rqp_example.py:33-59): waypoint 1.5 m ahead
    in x at 1.5 m above terrain, v_ref = 0.5 m/s x, PD acceleration with norm
    clamped to 1."""

    def acc_des_fn(state, t):
        del t
        ground = forest_mod.ground_height(forest, state.xl[:2])
        x_ref = jnp.stack([state.xl[0] + 1.5, jnp.zeros_like(ground), ground + 1.5])
        v_ref = jnp.array([0.5, 0.0, 0.0], dtype=state.xl.dtype)
        dvl_des = -1.0 * (state.vl - v_ref) - 1.0 * (state.xl - x_ref)
        norm = jnp.linalg.norm(dvl_des)
        dvl_des = jnp.where(
            norm > 1.0, dvl_des / jnp.where(norm > 0, norm, 1.0), dvl_des
        )
        dwl_des = jnp.zeros(3, dtype=state.xl.dtype)
        return (dvl_des, dwl_des), x_ref, v_ref

    return acc_des_fn


def rollout(
    hl_step: Callable,
    ll_control: Callable,
    params: rqp.RQPParams,
    state0: rqp.RQPState,
    ctrl_state0,
    n_hl_steps: int,
    hl_rel_freq: int = 10,
    dt: float = 1e-3,
    acc_des_fn: Callable | None = None,
    step_offset=0,
    telemetry: "telemetry_mod.TelemetryConfig | None" = None,
    telem0: "telemetry_mod.TelemetryState | None" = None,
):
    """Run ``n_hl_steps`` high-level control periods.

    Args:
      hl_step: ``(ctrl_state, state, acc_des) -> (f_des (n,3), ctrl_state,
        SolverStats)`` — any of the centralized/C-ADMM/DD controllers with params
        closed over.
      ll_control: ``(state, f_des) -> (f (n,), M (n,3))``.
      acc_des_fn: ``(state, t) -> (acc_des, x_ref, v_ref)``; default hover at the
        initial position.
      step_offset: global index of the first HL step (a traced int32 scalar
        under :func:`make_chunked_rollout`, so every chunk reuses ONE
        compiled program). The scan runs over ``step_offset + arange``;
        int32 addition is exact, so the per-step times — and therefore the
        whole trajectory — are bitwise-identical to an unchunked run.
      telemetry: optional :class:`obs.telemetry.TelemetryConfig`. When
        active, an :class:`obs.telemetry.TelemetryState` accumulator rides
        the scan carry (run-health metrics folded on-device every step)
        and a fourth return value carries its final value. ``None`` or an
        inactive config compiles the IDENTICAL HLO to the telemetry-less
        harness (asserted by tests/test_telemetry.py).
      telem0: accumulator to continue from (the chunked path); default is
        a fresh :func:`obs.telemetry.init_telemetry`.

    Returns ``(final_state, final_ctrl_state, logs: RQPLogStep)`` with a leading
    time axis of length ``n_hl_steps`` on every log leaf — plus the final
    ``TelemetryState`` when telemetry is active.
    """
    tel_on = telemetry is not None and telemetry.active
    if acc_des_fn is None:
        x0 = state0.xl

        def acc_des_fn(state, t):
            del t
            dvl_des = -1.0 * state.vl - 1.0 * (state.xl - x0)
            return (dvl_des, jnp.zeros(3, state.xl.dtype)), x0, jnp.zeros(3)

    def hl_body(carry, i):
        if tel_on:
            state, cs, tel = carry
        else:
            state, cs = carry
        t = i * hl_rel_freq * dt
        acc_des, x_ref, v_ref = acc_des_fn(state, t)
        f_des, cs, stats = hl_step(cs, state, acc_des)

        def ll_body(s, _):
            f, M = ll_control(s, f_des)
            return rqp.integrate(params, s, (f, M), dt), None

        with phases.scope(phases.DYNAMICS):
            state, _ = lax.scan(ll_body, state, None, length=hl_rel_freq)
        log = RQPLogStep(
            xl=state.xl,
            vl=state.vl,
            Rl=state.Rl,
            wl=state.wl,
            R=state.R,
            w=state.w,
            f_des=f_des,
            x_err=jnp.linalg.norm(x_ref - state.xl),
            v_err=jnp.linalg.norm(v_ref - state.vl),
            iters=stats.iters,
            solve_res=stats.solve_res,
            collision=stats.collision,
            min_env_dist=stats.min_env_dist,
        )
        if tel_on:
            with phases.scope(phases.TELEMETRY):
                tel = telemetry_mod.update(telemetry, tel, stats)
            return (state, cs, tel), log
        return (state, cs), log

    steps = jnp.arange(n_hl_steps)
    if not (isinstance(step_offset, int) and step_offset == 0):
        steps = steps + step_offset
    if tel_on:
        if telem0 is None:
            telem0 = telemetry_mod.init_telemetry(
                telemetry, params.n, state0.xl.dtype
            )
        (state, cs, tel), logs = lax.scan(
            hl_body, (state0, ctrl_state0, telem0), steps
        )
        return state, cs, logs, tel
    (state, cs), logs = lax.scan(hl_body, (state0, ctrl_state0), steps)
    return state, cs, logs


def jit_rollout(
    hl_step: Callable,
    ll_control: Callable,
    params: rqp.RQPParams,
    *,
    n_hl_steps: int,
    hl_rel_freq: int = 10,
    dt: float = 1e-3,
    acc_des_fn: Callable | None = None,
    donate: bool = True,
    telemetry: "telemetry_mod.TelemetryConfig | None" = None,
):
    """Donation-clean jitted rollout entrypoint: returns ``run(state0,
    ctrl_state0) -> (final_state, final_ctrl_state, logs)`` with BOTH
    carries donated, so a receding-horizon caller that chains rollouts
    (``state, cs, _ = run(state, cs)``) updates the physics state and the
    controller's warm starts/duals in place instead of allocating fresh
    buffers per call. The donated arguments are deleted by jax — always
    thread the returned values forward (tests/test_socp_padded.py asserts
    both the lowered input-output aliasing and the runtime deletion).
    ``donate=False`` compiles the same program without aliasing for
    callers that must replay the same initial state.

    ``telemetry``: forwarded to :func:`rollout` — when active the jitted
    run returns ``(final_state, final_ctrl_state, logs, telemetry_state)``
    with a fresh accumulator per call.

    Shared-buffer caveat: jax deduplicates identical small constants, so a
    freshly built initial state can hold several leaves backed by ONE
    buffer (e.g. the zero ``vl``/``wl``/``w`` of a rest state) — donating
    that pytree raises "Attempt to donate the same buffer twice". Decouple
    first: ``state0 = jax.tree.map(jnp.copy, state0)``. Carries returned
    by a previous donated call are always decoupled."""
    def run(state0, ctrl_state0):
        return rollout(
            hl_step, ll_control, params, state0, ctrl_state0,
            n_hl_steps, hl_rel_freq, dt, acc_des_fn,
            telemetry=telemetry,
        )

    return jax.jit(run, donate_argnums=(0, 1) if donate else ())


def make_chunked_rollout(
    hl_step: Callable,
    ll_control: Callable,
    params: rqp.RQPParams,
    *,
    n_hl_steps: int,
    n_chunks: int,
    hl_rel_freq: int = 10,
    dt: float = 1e-3,
    acc_des_fn: Callable,
    donate: bool = False,
    telemetry: "telemetry_mod.TelemetryConfig | None" = None,
):
    """Preemption-safe twin of :func:`jit_rollout`: the T-step scan split
    into ``n_chunks`` chunks of ``T / n_chunks`` HL steps each, reusing ONE
    compiled chunk function, with the scan carry surfaced (and snapshot-able)
    at every chunk boundary.

    The chunk program is ``chunk(carry, i0) -> (carry, logs)`` with
    ``carry = (state, ctrl_state)`` and the global step offset ``i0`` a
    traced int32 scalar — all C chunks hit one jit-cache entry (asserted by
    the ``harness.rollout:chunked_rollout`` trace contract). Because int32
    offset addition is exact, the concatenated logs and final carry are
    BITWISE-identical to an unchunked :func:`jit_rollout`
    (tests/test_recovery.py asserts this).

    ``donate=True`` donates the carry (the TC105 aliasing the
    ``harness.rollout:chunked_rollout`` contract checks — its builder pins
    ``donate=True``) but is OFF by default in this recovery tier: measured
    on XLA-CPU with the persistent compilation cache, in-place buffer reuse
    interacts with cache-loaded executables' buffer assignment and can flip
    low-order result bits depending on allocation history — breaking the
    bit-exact resume guarantee this tier exists for. The saving donation
    buys here (one carry copy per chunk boundary, where a host-side
    snapshot is being written anyway) is noise next to that guarantee;
    chained high-rate serving without snapshots should keep using
    :func:`jit_rollout` with its donated carries.

    ``acc_des_fn`` is REQUIRED (no default): the hover default of
    :func:`rollout` closes over the rollout's *initial* state, which under
    chunking would silently re-anchor the reference at every chunk boundary
    and break the bitwise-identity guarantee.

    Returns ``run(state0, ctrl_state0, on_boundary=None) -> (final_state,
    final_ctrl_state, logs)``; ``on_boundary(chunk_idx, carry, logs_chunk)``
    fires after each chunk (the hook may read/copy the carry — it is not
    consumed until the next chunk call). Attributes: ``run.chunk_jit`` (the
    one jitted chunk, ``(carry, i0) -> (carry, logs)``), ``run.n_chunks``,
    ``run.chunk_len``, ``run.init_carry`` — the uniform chunk contract
    ``resilience.recovery`` drives for snapshot/resume.
    """
    chunk_len = validate_chunking(n_hl_steps, n_chunks, acc_des_fn)
    tel_on = telemetry is not None and telemetry.active

    if tel_on:
        # Telemetry rides the chunk carry: every boundary snapshot (and so
        # every crash-recovery resume) carries the accumulated run-health
        # state, and recovery.run_chunks exports it per boundary.
        def chunk(carry, i0):
            state, cs, tel = carry
            state, cs, logs, tel = rollout(
                hl_step, ll_control, params, state, cs, chunk_len,
                hl_rel_freq, dt, acc_des_fn, step_offset=i0,
                telemetry=telemetry, telem0=tel,
            )
            return (state, cs, tel), logs

        def init_carry(state0, ctrl_state0):
            return (state0, ctrl_state0, telemetry_mod.init_telemetry(
                telemetry, params.n, state0.xl.dtype
            ))

        def unpack(carry):
            return carry[0], carry[1]
    else:
        def chunk(carry, i0):
            state, cs = carry
            state, cs, logs = rollout(
                hl_step, ll_control, params, state, cs, chunk_len,
                hl_rel_freq, dt, acc_des_fn, step_offset=i0,
            )
            return (state, cs), logs

        def init_carry(state0, ctrl_state0):
            return (state0, ctrl_state0)

        def unpack(carry):
            return carry

    return make_chunk_driver(
        chunk, n_chunks=n_chunks, chunk_len=chunk_len,
        init_carry=init_carry, unpack=unpack, donate=donate,
    )


def chunked_rollout(
    hl_step: Callable,
    ll_control: Callable,
    params: rqp.RQPParams,
    state0: rqp.RQPState,
    ctrl_state0,
    *,
    n_hl_steps: int,
    n_chunks: int,
    hl_rel_freq: int = 10,
    dt: float = 1e-3,
    acc_des_fn: Callable,
    donate: bool = False,
    on_boundary: Callable | None = None,
    telemetry: "telemetry_mod.TelemetryConfig | None" = None,
):
    """Build-and-run convenience over :func:`make_chunked_rollout` (same
    return contract as :func:`rollout`). With ``donate=True`` the passed
    ``(state0, ctrl_state0)`` are consumed — the shared-constant-buffer
    caveat of :func:`jit_rollout` applies (``jax.tree.map(jnp.copy, ...)``
    a freshly built rest state before donating it). With telemetry active,
    the final accumulator is reachable through ``on_boundary``'s carry
    (``obs.telemetry.find_state``)."""
    run = make_chunked_rollout(
        hl_step, ll_control, params, n_hl_steps=n_hl_steps,
        n_chunks=n_chunks, hl_rel_freq=hl_rel_freq, dt=dt,
        acc_des_fn=acc_des_fn, donate=donate, telemetry=telemetry,
    )
    return run(state0, ctrl_state0, on_boundary=on_boundary)


def validate_chunking(n_hl_steps: int, n_chunks: int,
                      acc_des_fn: Callable | None) -> int:
    """Shared argument validation for the chunked-rollout factories;
    returns the static chunk length."""
    if n_hl_steps % n_chunks:
        raise ValueError(
            f"n_hl_steps={n_hl_steps} not divisible by n_chunks={n_chunks}: "
            "chunks must share one static chunk length (one compiled "
            "program) or the jit cache fragments"
        )
    if acc_des_fn is None:
        raise ValueError(
            "chunked rollouts need an explicit acc_des_fn: the hover "
            "default anchors at each chunk's initial state and would "
            "diverge from the unchunked trajectory"
        )
    return n_hl_steps // n_chunks


def make_chunk_driver(
    chunk: Callable,
    *,
    n_chunks: int,
    chunk_len: int,
    init_carry: Callable,
    unpack: Callable,
    donate: bool,
):
    """The one chunk-loop driver both chunked-rollout factories share:
    jits ``chunk(carry, i0) -> (carry, logs)`` once (optionally donating
    the carry) and returns ``run(state0, ctrl_state0, on_boundary=None) ->
    (final_state, final_ctrl_state, logs)`` with the uniform attributes
    ``resilience.recovery`` drives (``chunk_jit``/``chunk_fn``/
    ``n_chunks``/``chunk_len``/``init_carry``). ``unpack`` maps the final
    carry back to ``(state, ctrl_state)``."""
    chunk_jit = jax.jit(chunk, donate_argnums=(0,) if donate else ())

    def run(state0, ctrl_state0, on_boundary: Callable | None = None):
        carry = init_carry(state0, ctrl_state0)
        chunk_logs = []
        for c in range(n_chunks):
            carry, logs = chunk_jit(carry, chunk_index_offset(c, chunk_len))
            chunk_logs.append(logs)
            if on_boundary is not None:
                on_boundary(c, carry, logs)
        state, cs = unpack(carry)
        return state, cs, concat_chunk_logs(chunk_logs)

    run.chunk_jit = chunk_jit
    run.chunk_fn = chunk  # unjitted, for vmap/shard wrappers (parallel.mesh).
    run.n_chunks = n_chunks
    run.chunk_len = chunk_len
    run.init_carry = init_carry
    return run


def chunk_index_offset(chunk_idx: int, chunk_len: int) -> jnp.ndarray:
    """Global step offset of a chunk as the traced int32 scalar every chunk
    call must pass (a Python int would be a fresh weak-typed constant —
    still one cache entry, but an explicit dtype keeps the contract
    obvious and the key stable)."""
    return jnp.asarray(chunk_idx * chunk_len, jnp.int32)


def concat_chunk_logs(chunk_logs: list, time_axis: int = 0):
    """Concatenate per-chunk log pytrees along the time axis (axis 0 for a
    single-scenario rollout; axis 1 when the chunk was vmapped over a
    leading Monte-Carlo batch axis — ``parallel.mesh`` passes 1)."""
    if len(chunk_logs) == 1:
        return chunk_logs[0]
    return jax.tree.map(
        lambda *xs: jnp.concatenate(xs, axis=time_axis), *chunk_logs
    )


def logs_to_dict(logs: RQPLogStep, n: int, dt: float, hl_rel_freq: int,
                 forest: forest_mod.Forest | None = None) -> dict:
    """Flatten a log pytree into the reference's pickle-dict schema
    (rqp_example.py:141-160) so plotting/replay tools port directly."""
    import numpy as np

    out = {
        "n": n,
        "dt": dt,
        "T": float(logs.xl.shape[0] * hl_rel_freq * dt),
        "hl_rel_freq": hl_rel_freq,
        "log_freq": hl_rel_freq,
        "state_seq": {
            k: np.asarray(getattr(logs, k)) for k in ("R", "w", "xl", "vl", "Rl", "wl")
        },
        "x_err_seq": np.asarray(logs.x_err),
        "v_err_seq": np.asarray(logs.v_err),
        "f_des_seq": np.asarray(logs.f_des),
        "iter_seq": np.asarray(logs.iters),
        "solve_res_seq": np.asarray(logs.solve_res),
        "min_env_dist_seq": np.asarray(logs.min_env_dist),
        "collision_seq": np.asarray(logs.collision),
        "fallback_rung_seq": np.asarray(logs.fallback_rung),
        "quarantined_seq": np.asarray(logs.quarantined),
    }
    if forest is not None:
        num = int(forest.num_trees)
        out["num_trees"] = num
        out["tree_pos"] = np.asarray(forest.tree_pos[:num])
    return out
