"""Parameter / collision / initial-state factories for all three system models.

TPU-native counterpart of reference ``example/setup.py``. For ``n == 3`` the exact
reference values are reproduced (masses 0.5 kg, payload 0.225 kg, the triangle
attachment geometry, setup.py:64-118); for other ``n`` — which the reference
rejects with ``NotImplementedError`` (setup.py:23,81,144) — we generalize to a
regular n-gon of attachments with the same total actuator mass per unit payload,
so every controller/benchmark scales in the agent axis.
"""

from __future__ import annotations

import numpy as np

from tpu_aerial_transport.models import pmrl, rp, rqp

_REF_R3 = np.array(
    [
        [-0.42, -0.27, 0.0],
        [0.48, -0.27, 0.0],
        [-0.06, 0.55, 0.0],
    ]
)
_REF_ML = 0.225
_REF_JL = np.diag([2.1, 1.87, 3.97]) * 1e-2
_REF_MQ = 0.5
_REF_JQ = np.diag([2.32, 2.32, 4.0]) * 1e-3

_PAYLOAD_VERTICES = np.array(
    [
        [-0.42, -0.27, 0.0],
        [0.48, -0.27, 0.0],
        [-0.06, 0.55, 0.0],
        [-0.42, -0.27, -0.1],
        [0.48, -0.27, -0.1],
        [-0.06, 0.55, -0.1],
    ]
)
_PAYLOAD_MESH_VERTICES = np.array(
    [
        [-0.52, -0.37, 0.1],
        [0.58, -0.37, 0.1],
        [-0.06, 0.65, 0.1],
        [-0.52, -0.37, -0.2],
        [0.58, -0.37, -0.2],
        [-0.06, 0.65, -0.2],
    ]
)


def _attachments(n: int) -> np.ndarray:
    """Reference triangle for n=3; regular n-gon of circumradius 0.5 otherwise."""
    if n == 3:
        return _REF_R3.copy()
    ang = 2.0 * np.pi * np.arange(n) / n
    return np.stack(
        [0.5 * np.cos(ang), 0.5 * np.sin(ang), np.zeros(n)], axis=-1
    )


def rqp_setup(n: int = 3, dtype=None):
    """-> (RQPParams, RQPCollision, RQPState) (reference setup.py:121-126)."""
    kw = {} if dtype is None else {"dtype": dtype}
    params = rqp.rqp_params(
        m=np.full(n, _REF_MQ),
        J=np.tile(_REF_JQ, (n, 1, 1)),
        ml=_REF_ML,
        Jl=_REF_JL,
        r=_attachments(n),
        **kw,
    )
    col = rqp.RQPCollision(_PAYLOAD_VERTICES, _PAYLOAD_MESH_VERTICES)
    state = rqp.rqp_identity_state(n, **kw)
    return params, col, state


def rp_setup(n: int = 3, dtype=None):
    """-> (RPParams, RPCollision, RPState) (reference setup.py:59-60)."""
    kw = {} if dtype is None else {"dtype": dtype}
    params = rp.rp_params(ml=_REF_ML, Jl=_REF_JL, r=_attachments(n), **kw)
    col = rp.RPCollision(_PAYLOAD_VERTICES, _PAYLOAD_MESH_VERTICES)
    state = rp.rp_identity_state(**kw)
    return params, col, state


def pmrl_setup(n: int = 3, dtype=None):
    """-> (PMRLParams, PMRLCollision, PMRLState) (reference setup.py:182-187).
    Initial link directions all +z, zero tangent velocity."""
    kw = {} if dtype is None else {"dtype": dtype}
    params = pmrl.pmrl_params(
        m=np.full(n, _REF_MQ),
        ml=_REF_ML,
        Jl=_REF_JL,
        r=_attachments(n),
        L=np.ones(n),
        **kw,
    )
    col = pmrl.PMRLCollision(
        _PAYLOAD_VERTICES, _PAYLOAD_MESH_VERTICES, link_lengths=params.L
    )
    q = np.tile(np.array([0.0, 0.0, 1.0]), (n, 1))
    state = pmrl.pmrl_state(
        q=q, dq=np.zeros((n, 3)), xl=np.zeros(3), vl=np.zeros(3),
        Rl=np.eye(3), wl=np.zeros(3), **kw,
    )
    return params, col, state
