"""System models (reference ``system/``): RQP (primary), RP, PMRL."""

from tpu_aerial_transport.models import pmrl, rp, rqp  # noqa: F401
