"""Rigid-quadrotor-payload (RQP) system model — the primary model.

TPU-native re-design of reference ``system/rigid_quadrotor_payload.py`` (dynamics
docstring at :151-163): ``n`` quadrotors rigidly attached to a shared payload at body
points ``r_i``; each quadrotor keeps an independent attitude ``R_i`` and contributes
scalar thrust ``f_i`` along its body z-axis plus a body moment ``M_i``.

Differences from the reference (deliberate, TPU-first):
- Structure-of-arrays pytrees with the **agent axis leading** (``R: (n, 3, 3)``,
  ``w: (n, 3)``, ``r: (n, 3)``) so ``vmap``/sharding over agents is a leading-axis
  operation; the reference uses trailing-axis ``(3, 3, n)`` numpy arrays.
- Pure functions of ``(params, state) -> state`` instead of mutating classes, so the
  whole physics step jit-compiles and composes with ``lax.scan`` rollouts.
- SO(3) projection (reference: scipy polar via SVD every 20 steps,
  ``rigid_quadrotor_payload.py:121-148``) uses the matmul-only Newton-Schulz
  iteration from :mod:`tpu_aerial_transport.ops.lie`, selected by a step counter
  carried in the state pytree.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from flax import struct

from tpu_aerial_transport.ops import lie

GRAVITY = 9.80665  # scipy.constants.g, [m/s^2].

# Reference `_INTEGRATION_STEPS_PER_ROTATION_PROJECTION = 20`
# (rigid_quadrotor_payload.py:14).
PROJECTION_PERIOD = 20

# Reference RQPCollision constants (rigid_quadrotor_payload.py:37,301-310).
QUADROTOR_RADIUS = 0.3  # [m].
MAX_DECELERATION = GRAVITY / 5.0  # [m/s^2].


@struct.dataclass
class RQPParams:
    """System parameters (reference ``RQPParameters``, :48-84). Agent axis leads."""

    m: jnp.ndarray  # (n,) quadrotor masses [kg].
    J: jnp.ndarray  # (n, 3, 3) quadrotor inertias.
    ml: jnp.ndarray  # () payload mass.
    Jl: jnp.ndarray  # (3, 3) payload inertia (body frame).
    r: jnp.ndarray  # (n, 3) attachment points (payload body frame).
    # Derived (precomputed in rqp_params()):
    mT: jnp.ndarray  # () total mass.
    x_com: jnp.ndarray  # (3,) CoM offset in payload body frame.
    r_com: jnp.ndarray  # (n, 3) attachments relative to CoM.
    JT: jnp.ndarray  # (3, 3) composite inertia about CoM.
    JT_inv: jnp.ndarray  # (3, 3).
    J_inv: jnp.ndarray  # (n, 3, 3).

    @property
    def n(self) -> int:
        return self.r.shape[-2]


def rqp_params(m, J, ml, Jl, r, dtype=jnp.float32) -> RQPParams:
    """Build :class:`RQPParams` with derived quantities.

    Mirrors reference ``RQPParameters.__init__`` (:59-84): total mass, CoM offset
    ``x_com = sum_i m_i r_i / mT``, CoM-relative attachments, composite inertia
    ``JT = Jl - ml hat^2(x_com) - sum_i m_i hat^2(r_com_i)``.
    """
    m = jnp.asarray(m, dtype)
    J = jnp.asarray(J, dtype)
    ml = jnp.asarray(ml, dtype)
    Jl = jnp.asarray(Jl, dtype)
    r = jnp.asarray(r, dtype)
    n = r.shape[0]
    assert m.shape == (n,) and J.shape == (n, 3, 3) and Jl.shape == (3, 3)

    mT = jnp.sum(m) + ml
    x_com = jnp.sum(r * m[:, None], axis=0) / mT
    r_com = r - x_com
    JT = (
        Jl
        - ml * lie.hat_square(x_com, x_com)
        - jnp.sum(m[:, None, None] * lie.hat_square(r_com, r_com), axis=0)
    )
    return RQPParams(
        m=m,
        J=J,
        ml=ml,
        Jl=Jl,
        r=r,
        mT=mT,
        x_com=x_com,
        r_com=r_com,
        JT=JT,
        JT_inv=jnp.linalg.inv(JT),
        J_inv=jnp.linalg.inv(J),
    )


@struct.dataclass
class RQPState:
    """System state (reference ``RQPState``, :87-148). Agent axis leads."""

    R: jnp.ndarray  # (n, 3, 3) quadrotor rotations.
    w: jnp.ndarray  # (n, 3) quadrotor body angular velocities.
    xl: jnp.ndarray  # (3,) payload position.
    vl: jnp.ndarray  # (3,) payload velocity.
    Rl: jnp.ndarray  # (3, 3) payload rotation.
    wl: jnp.ndarray  # (3,) payload body angular velocity.
    step: jnp.ndarray  # () int32 counter for periodic SO(3) re-projection.

    @property
    def n(self) -> int:
        return self.w.shape[-2]


def rqp_state(R, w, xl, vl, Rl, wl, dtype=jnp.float32) -> RQPState:
    """Build a state, projecting rotations onto SO(3) (reference ctor behavior).

    Uses the SVD polar factor here: this is a host-side, setup-time constructor that
    must handle arbitrary user input (Newton-Schulz only converges for singular
    values in (0, sqrt(3)) and is reserved for in-loop drift correction).
    """
    return RQPState(
        R=lie.polar_project_svd(jnp.asarray(R, dtype)),
        w=jnp.asarray(w, dtype),
        xl=jnp.asarray(xl, dtype),
        vl=jnp.asarray(vl, dtype),
        Rl=lie.polar_project_svd(jnp.asarray(Rl, dtype)),
        wl=jnp.asarray(wl, dtype),
        step=jnp.zeros((), jnp.int32),
    )


def rqp_identity_state(n: int, dtype=jnp.float32) -> RQPState:
    """Identity attitudes, zero velocities at the origin (reference setup.py:109)."""
    eye = jnp.broadcast_to(jnp.eye(3, dtype=dtype), (n, 3, 3))
    z3 = jnp.zeros(3, dtype)
    return RQPState(
        R=eye,
        w=jnp.zeros((n, 3), dtype),
        xl=z3,
        vl=z3,
        Rl=jnp.eye(3, dtype=dtype),
        wl=z3,
        step=jnp.zeros((), jnp.int32),
    )


def forward_dynamics(params: RQPParams, state: RQPState, wrench):
    """Accelerations from quadrotor inputs (reference ``RQPDynamics.forward_dynamics``,
    :173-222).

    ``wrench = (f, M)`` with ``f (n,)`` scalar thrusts (along each quad's body z, in
    world frame via ``R_i e3``) and ``M (n, 3)`` body moments.
    Returns ``(dw (n, 3), dvl (3,), dwl (3,))``.
    """
    f, M = wrench
    gravity = jnp.array([0.0, 0.0, -GRAVITY], dtype=state.xl.dtype)

    # Per-quad Euler equation: dw_i = J_i^{-1} (M_i - w_i x J_i w_i).
    Jw = jnp.einsum("nij,nj->ni", params.J, state.w)
    dw = jnp.einsum("nij,nj->ni", params.J_inv, M - jnp.cross(state.w, Jw))

    # CoM translation: dv_com = sum_i f_i R_i e3 / mT + g.
    quad_force = state.R[..., :, 2] * f[..., None]  # (n, 3) world-frame thrusts.
    dv_com = jnp.sum(quad_force, axis=0) / params.mT + gravity

    # Composite rotation: dwl = JT^{-1} (sum_i r_com_i x Rl^T F_i - wl x JT wl).
    force_body = quad_force @ state.Rl  # rows = Rl^T F_i.
    net_moment = jnp.sum(jnp.cross(params.r_com, force_body), axis=0)
    JTwl = params.JT @ state.wl
    dwl = params.JT_inv @ (net_moment - jnp.cross(state.wl, JTwl))

    # Payload-point kinematic correction:
    # dvl = dv_com - Rl (hat(wl)^2 + hat(dwl)) x_com.
    corr = (lie.hat_square(state.wl, state.wl) + lie.hat(dwl)) @ params.x_com
    dvl = dv_com - state.Rl @ corr
    return dw, dvl, dwl


def integrate_state(
    state: RQPState, acc, dt, project_every: int = PROJECTION_PERIOD
) -> RQPState:
    """Semi-implicit trapezoidal manifold integrator (reference ``RQPState.integrate``,
    :129-148): rotations via ``R exp3((w + dw dt/2) dt)``, positions via trapezoid,
    Newton-Schulz SO(3) re-projection every ``project_every`` steps."""
    dw, dvl, dwl = acc
    R = state.R @ lie.expm_so3((state.w + dw * (dt / 2)) * dt)
    w = state.w + dw * dt
    xl = state.xl + state.vl * dt + dvl * (dt**2 / 2)
    vl = state.vl + dvl * dt
    Rl = state.Rl @ lie.expm_so3((state.wl + dwl * (dt / 2)) * dt)
    wl = state.wl + dwl * dt

    step = state.step + 1
    project = step >= project_every
    # Projection is a handful of 3x3 matmuls; compute unconditionally and select, which
    # is cheaper than lax.cond under vmap (where cond lowers to select anyway).
    R = jnp.where(project, lie.polar_project(R), R)
    Rl = jnp.where(project, lie.polar_project(Rl), Rl)
    step = jnp.where(project, 0, step)
    return state.replace(R=R, w=w, xl=xl, vl=vl, Rl=Rl, wl=wl, step=step)


def integrate(
    params: RQPParams,
    state: RQPState,
    wrench,
    dt,
    project_every: int = PROJECTION_PERIOD,
) -> RQPState:
    """Forward dynamics + state integration (reference ``RQPDynamics.integrate``)."""
    return integrate_state(
        state, forward_dynamics(params, state, wrench), dt, project_every
    )


def inverse_dynamics_error(state: RQPState, params: RQPParams, wrench, acc):
    """Residual norm of the full (per-quad + payload) Newton-Euler equations — the
    test oracle (reference ``RQPDynamics.inverse_dynamics_error``, :224-269): for a
    consistent ``(state, wrench, acc)`` triple the residual is ~machine epsilon."""
    f, M = wrench
    dw, dvl, dwl = acc
    gravity = jnp.array([0.0, 0.0, -GRAVITY], dtype=state.xl.dtype)

    # Quadrotor CoM accelerations from payload kinematics.
    kin = (lie.hat_square(state.wl, state.wl) + lie.hat(dwl)) @ params.r.T  # (3, n)
    dv_quad = dvl[:, None] + state.Rl @ kin  # (3, n)
    dv_quad = dv_quad.T  # (n, 3)
    quad_force = state.R[..., :, 2] * f[..., None]
    internal_force = (
        quad_force + gravity * params.m[:, None] - params.m[:, None] * dv_quad
    )
    com_acc_err = jnp.linalg.norm(
        params.ml * dvl - params.ml * gravity - jnp.sum(internal_force, axis=0)
    )
    load_moment = jnp.sum(jnp.cross(params.r, internal_force @ state.Rl), axis=0)
    Jlwl = params.Jl @ state.wl
    com_ang_err = jnp.linalg.norm(
        params.Jl @ dwl + jnp.cross(state.wl, Jlwl) - load_moment
    )
    Jw = jnp.einsum("nij,nj->ni", params.J, state.w)
    quad_ang_res = jnp.einsum("nij,nj->ni", params.J, dw) + jnp.cross(state.w, Jw) - M
    quad_ang_err_sq = jnp.sum(quad_ang_res**2)
    return jnp.sqrt(com_acc_err**2 + com_ang_err**2 + quad_ang_err_sq)


class RQPCollision:
    """Host-side collision metadata (reference ``RQPCollision``, :279-310): payload
    hull vertices for visualization plus the bounding-sphere collision radius and max
    braking deceleration consumed by the controllers' collision CBFs."""

    def __init__(self, payload_vertices, payload_mesh_vertices):
        payload_vertices = np.asarray(payload_vertices, np.float64)
        payload_mesh_vertices = np.asarray(payload_mesh_vertices, np.float64)
        assert payload_vertices.shape[1] == 3
        self.payload_vertices = payload_vertices
        self.payload_mesh_vertices = payload_mesh_vertices
        self.quadrotor_radius = QUADROTOR_RADIUS
        self.collision_radius = float(
            np.max(np.linalg.norm(payload_mesh_vertices, axis=1))
            + QUADROTOR_RADIUS
            + 0.1
        )
        self.max_deceleration = MAX_DECELERATION
