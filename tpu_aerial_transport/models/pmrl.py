"""Point-mass rigid-link (PMRL) system model.

TPU-native re-design of reference ``system/point_mass_rigid_link.py``: ``n``
point-mass robots attached to payload body points ``r_i`` through massless rigid
links of length ``L_i``; link directions ``q_i`` live on S^2 and are extra state.
Robot positions are ``x_i = xl + L_i q_i + Rl r_i``. Dynamics (reference docstring
:135-146):

    m_i x_i'' = f_i - m_i g e3 - T_i q_i,
    ml dvl    = sum_i T_i q_i - ml g e3,
    Jl dwl + wl x Jl wl = sum_i r_i x (T_i Rl^T q_i),
    q_i . ddq_i = -||dq_i||^2        (sphere constraint, second derivative)

with link tensions ``T in R^n`` solved from an n x n SPD system each step
(reference :156-208). This is the only model with implicit constraint forces; the
SPD solve is a batched ``jnp.linalg.solve`` on an n x n matrix (Cholesky-sized for
n <= O(100) agents, trivially vmappable over scenarios).

Layout: agent axis leading (``q, dq, f: (n, 3)``), pure functions, S^2 projection
every step + SO(3) projection every 20 (reference :101-132).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from flax import struct

from tpu_aerial_transport.ops import lie

GRAVITY = 9.80665
PROJECTION_PERIOD = 20


@struct.dataclass
class PMRLParams:
    """Reference ``PMRLParameters`` (point_mass_rigid_link.py:37-64)."""

    m: jnp.ndarray  # (n,) robot masses.
    ml: jnp.ndarray  # () payload mass.
    Jl: jnp.ndarray  # (3, 3) payload inertia.
    r: jnp.ndarray  # (n, 3) link attachment points (payload body frame).
    L: jnp.ndarray  # (n,) link lengths.
    Jl_inv: jnp.ndarray  # (3, 3).
    Jl_inv_factor: jnp.ndarray  # (3, 3) F with F^T F = Jl_inv (for SPD assembly).

    @property
    def n(self) -> int:
        return self.r.shape[-2]


def pmrl_params(m, ml, Jl, r, L, dtype=jnp.float32) -> PMRLParams:
    m = jnp.asarray(m, dtype)
    ml = jnp.asarray(ml, dtype)
    Jl = jnp.asarray(Jl, dtype)
    r = jnp.asarray(r, dtype)
    L = jnp.asarray(L, dtype)
    n = r.shape[0]
    assert m.shape == (n,) and L.shape == (n,) and Jl.shape == (3, 3)
    Jl_inv = jnp.linalg.inv(Jl)
    # jnp Cholesky is lower (A = C C^T); F = C^T satisfies F^T F = Jl_inv.
    Jl_inv_factor = jnp.linalg.cholesky(Jl_inv).T
    return PMRLParams(m=m, ml=ml, Jl=Jl, r=r, L=L, Jl_inv=Jl_inv,
                      Jl_inv_factor=Jl_inv_factor)


@struct.dataclass
class PMRLState:
    """Reference ``PMRLState`` (point_mass_rigid_link.py:67-132)."""

    q: jnp.ndarray  # (n, 3) unit link directions (world frame).
    dq: jnp.ndarray  # (n, 3) tangent velocities, q_i . dq_i = 0.
    xl: jnp.ndarray  # (3,) payload CoM position.
    vl: jnp.ndarray  # (3,) payload CoM velocity.
    Rl: jnp.ndarray  # (3, 3) payload rotation.
    wl: jnp.ndarray  # (3,) body angular velocity.
    step: jnp.ndarray  # () int32 projection counter.


def _project_q(q, dq):
    """Normalize q to S^2 and project dq onto the tangent space (reference :101-105)."""
    q = q / jnp.linalg.norm(q, axis=-1, keepdims=True)
    dq = dq - q * jnp.sum(q * dq, axis=-1, keepdims=True)
    return q, dq


def pmrl_state(q, dq, xl, vl, Rl, wl, dtype=jnp.float32) -> PMRLState:
    q, dq = _project_q(jnp.asarray(q, dtype), jnp.asarray(dq, dtype))
    return PMRLState(
        q=q,
        dq=dq,
        xl=jnp.asarray(xl, dtype),
        vl=jnp.asarray(vl, dtype),
        Rl=lie.polar_project_svd(jnp.asarray(Rl, dtype)),
        wl=jnp.asarray(wl, dtype),
        step=jnp.zeros((), jnp.int32),
    )


def forward_dynamics(params: PMRLParams, state: PMRLState, f):
    """``f (n, 3)`` world-frame robot thrusts -> ``((ddq, dvl, dwl), T)``
    (reference ``PMRLDynamics.forward_dynamics``, point_mass_rigid_link.py:156-208).

    The link tensions T couple all agents through the payload: eliminating the
    constraint forces yields an SPD system
    ``[diag(1/m) + (1/ml) q q^T + rcq Jl_inv rcq^T] T = rhs`` where
    ``rcq_i = r_i x Rl^T q_i``.
    """
    dtype = state.xl.dtype
    gravity = jnp.array([0.0, 0.0, -GRAVITY], dtype=dtype)
    q, dq, Rl, wl = state.q, state.dq, state.Rl, state.wl

    cor_acc = params.Jl_inv @ jnp.cross(wl, params.Jl @ wl)  # (3,)
    cor_mat = Rl @ (lie.hat_square(wl, wl) - lie.hat(cor_acc))  # (3, 3)
    # add_force_i = f_i - cor_mat @ (m_i r_i): applied force net of payload
    # rotational pseudo-forces transmitted through the attachment.
    add_force = f - (params.r * params.m[:, None]) @ cor_mat.T  # (n, 3)

    rhs = (
        jnp.sum(add_force * q, axis=-1)
        + params.m * params.L * jnp.sum(dq * dq, axis=-1)
    ) / params.m  # (n,)

    rcq = jnp.cross(params.r, q @ Rl)  # (n, 3); rows r_i x (Rl^T q_i).
    temp = rcq @ params.Jl_inv_factor.T  # (n, 3); temp temp^T = rcq Jl_inv rcq^T.
    lhs = (
        jnp.diag(1.0 / params.m)
        + (q @ q.T) / params.ml
        + temp @ temp.T
    )  # (n, n) SPD.
    T = jnp.linalg.solve(lhs, rhs)  # (n,) link tensions.

    qT = q.T @ T  # (3,) = sum_i T_i q_i.
    rcqT = params.Jl_inv @ (rcq.T @ T)  # (3,)
    mL = (params.m * params.L)[:, None]
    ddq = (
        (add_force - q * T[:, None]) / mL
        - qT / (params.ml * params.L)[:, None]
        - (params.r / params.L[:, None]) @ (Rl @ lie.hat(rcqT)).T
    )
    dvl = qT / params.ml + gravity
    dwl = rcqT - cor_acc
    return (ddq, dvl, dwl), T


def integrate_state(state: PMRLState, acc, dt,
                    project_every: int = PROJECTION_PERIOD) -> PMRLState:
    """Trapezoidal integrator; q re-projected to S^2 every step, Rl to SO(3)
    every ``project_every`` steps (reference :113-132)."""
    ddq, dvl, dwl = acc
    q = state.q + state.dq * dt + ddq * (dt**2 / 2)
    dq = state.dq + ddq * dt
    q, dq = _project_q(q, dq)
    xl = state.xl + state.vl * dt + dvl * (dt**2 / 2)
    vl = state.vl + dvl * dt
    Rl = state.Rl @ lie.expm_so3((state.wl + dwl * (dt / 2)) * dt)
    wl = state.wl + dwl * dt
    step = state.step + 1
    project = step >= project_every
    Rl = jnp.where(project, lie.polar_project(Rl), Rl)
    step = jnp.where(project, 0, step)
    return state.replace(q=q, dq=dq, xl=xl, vl=vl, Rl=Rl, wl=wl, step=step)


def integrate(params: PMRLParams, state: PMRLState, f, dt,
              project_every: int = PROJECTION_PERIOD) -> PMRLState:
    acc, _ = forward_dynamics(params, state, f)
    return integrate_state(state, acc, dt, project_every)


class PMRLCollision:
    """Host-side collision/visual metadata (reference ``PMRLCollision``,
    point_mass_rigid_link.py:257-278): payload hull + collision-mesh vertex
    sets. Unlike RQP there is no quadrotor mesh — the robots are point masses —
    so the conservative bounding radius covers payload + fully-extended links."""

    def __init__(self, payload_vertices, payload_mesh_vertices,
                 link_lengths=None):
        payload_vertices = np.asarray(payload_vertices, np.float64)
        payload_mesh_vertices = np.asarray(payload_mesh_vertices, np.float64)
        assert payload_vertices.shape[1] == 3
        assert payload_mesh_vertices.shape[1] == 3
        self.payload_vertices = payload_vertices
        self.payload_mesh_vertices = payload_mesh_vertices
        mesh_radius = float(np.max(np.linalg.norm(payload_mesh_vertices, axis=1)))
        max_link = float(np.max(np.asarray(link_lengths))) \
            if link_lengths is not None else 0.0
        self.collision_radius = mesh_radius + max_link + 0.1


def inverse_dynamics_error(state: PMRLState, params: PMRLParams, f, T, acc):
    """Residual norm of all four dynamics equations incl. the sphere constraint —
    the test oracle (reference :210-249); validates the implicit tension solve."""
    ddq, dvl, dwl = acc
    gravity = jnp.array([0.0, 0.0, -GRAVITY], dtype=state.xl.dtype)
    q, Rl, wl = state.q, state.Rl, state.wl

    kin = (lie.hat_square(wl, wl) + lie.hat(dwl)) @ params.r.T  # (3, n)
    dv_robot = dvl[None, :] + ddq * params.L[:, None] + (Rl @ kin).T  # (n, 3)
    robot_res = (
        dv_robot * params.m[:, None]
        - f
        - gravity * params.m[:, None]
        + q * T[:, None]
    )
    load_lin_res = params.ml * dvl - q.T @ T - params.ml * gravity
    rcq = jnp.cross(params.r, q @ Rl)
    load_ang_res = params.Jl @ dwl + jnp.cross(wl, params.Jl @ wl) - rcq.T @ T
    sphere_res = jnp.sum(q * ddq, axis=-1) + jnp.sum(state.dq**2, axis=-1)
    return jnp.sqrt(
        jnp.sum(robot_res**2)
        + jnp.sum(load_lin_res**2)
        + jnp.sum(load_ang_res**2)
        + jnp.sum(sphere_res**2)
    )
