"""Rigid-payload (RP) system model.

TPU-native re-design of reference ``system/rigid_payload.py``: a single rigid payload
carried by ``n >= 3`` abstract point-force actuators attached at body-frame points
``r_i`` (no actuator dynamics). Dynamics (reference docstring :92-98):

    ml dvl = sum_i f_i - ml g e3,
    Jl dwl + wl x Jl wl = sum_i r_i x Rl^T f_i.

Same conventions as :mod:`tpu_aerial_transport.models.rqp`: structure-of-arrays
pytrees with the agent axis leading (``r, f: (n, 3)``), pure functions, periodic
Newton-Schulz SO(3) re-projection.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from flax import struct

from tpu_aerial_transport.ops import lie

GRAVITY = 9.80665
PROJECTION_PERIOD = 20  # reference rigid_payload.py:12.


@struct.dataclass
class RPParams:
    """Reference ``RPParameters`` (rigid_payload.py:33-47), agent axis leading."""

    ml: jnp.ndarray  # () payload mass.
    Jl: jnp.ndarray  # (3, 3) payload inertia.
    r: jnp.ndarray  # (n, 3) actuator attachment points (body frame).
    Jl_inv: jnp.ndarray  # (3, 3).

    @property
    def n(self) -> int:
        return self.r.shape[-2]


def rp_params(ml, Jl, r, dtype=jnp.float32) -> RPParams:
    ml = jnp.asarray(ml, dtype)
    Jl = jnp.asarray(Jl, dtype)
    r = jnp.asarray(r, dtype)
    assert Jl.shape == (3, 3) and r.ndim == 2 and r.shape[-1] == 3
    return RPParams(ml=ml, Jl=Jl, r=r, Jl_inv=jnp.linalg.inv(Jl))


@struct.dataclass
class RPState:
    """Reference ``RPState`` (rigid_payload.py:50-88)."""

    xl: jnp.ndarray  # (3,) payload position.
    vl: jnp.ndarray  # (3,) payload velocity.
    Rl: jnp.ndarray  # (3, 3) payload rotation.
    wl: jnp.ndarray  # (3,) body angular velocity.
    step: jnp.ndarray  # () int32 projection counter.


def rp_state(xl, vl, Rl, wl, dtype=jnp.float32) -> RPState:
    return RPState(
        xl=jnp.asarray(xl, dtype),
        vl=jnp.asarray(vl, dtype),
        Rl=lie.polar_project_svd(jnp.asarray(Rl, dtype)),
        wl=jnp.asarray(wl, dtype),
        step=jnp.zeros((), jnp.int32),
    )


def rp_identity_state(dtype=jnp.float32) -> RPState:
    z3 = jnp.zeros(3, dtype)
    return RPState(xl=z3, vl=z3, Rl=jnp.eye(3, dtype=dtype), wl=z3,
                   step=jnp.zeros((), jnp.int32))


def forward_dynamics(params: RPParams, state: RPState, f):
    """``f (n, 3)`` world-frame actuator forces -> ``(dvl, dwl)``
    (reference ``RPDynamics.forward_dynamics``, rigid_payload.py:107-130)."""
    gravity = jnp.array([0.0, 0.0, -GRAVITY], dtype=state.xl.dtype)
    dvl = jnp.sum(f, axis=0) / params.ml + gravity
    f_body = f @ state.Rl  # rows = Rl^T f_i.
    net_moment = jnp.sum(jnp.cross(params.r, f_body), axis=0)
    Jlwl = params.Jl @ state.wl
    dwl = params.Jl_inv @ (net_moment - jnp.cross(state.wl, Jlwl))
    return dvl, dwl


def integrate_state(state: RPState, acc, dt,
                    project_every: int = PROJECTION_PERIOD) -> RPState:
    """Semi-implicit trapezoidal manifold integrator (rigid_payload.py:76-88)."""
    dvl, dwl = acc
    xl = state.xl + state.vl * dt + dvl * (dt**2 / 2)
    vl = state.vl + dvl * dt
    Rl = state.Rl @ lie.expm_so3((state.wl + dwl * (dt / 2)) * dt)
    wl = state.wl + dwl * dt
    step = state.step + 1
    project = step >= project_every
    Rl = jnp.where(project, lie.polar_project(Rl), Rl)
    step = jnp.where(project, 0, step)
    return state.replace(xl=xl, vl=vl, Rl=Rl, wl=wl, step=step)


def integrate(params: RPParams, state: RPState, f, dt,
              project_every: int = PROJECTION_PERIOD) -> RPState:
    return integrate_state(state, forward_dynamics(params, state, f), dt,
                           project_every)


def inverse_dynamics_error(state: RPState, params: RPParams, f, acc):
    """Newton-Euler residual norm — the test oracle (rigid_payload.py:132-156)."""
    dvl, dwl = acc
    gravity = jnp.array([0.0, 0.0, -GRAVITY], dtype=state.xl.dtype)
    lin_res = params.ml * dvl - jnp.sum(f, axis=0) - params.ml * gravity
    f_body = f @ state.Rl
    net_moment = jnp.sum(jnp.cross(params.r, f_body), axis=0)
    Jlwl = params.Jl @ state.wl
    ang_res = params.Jl @ dwl + jnp.cross(state.wl, Jlwl) - net_moment
    return jnp.sqrt(jnp.sum(lin_res**2) + jnp.sum(ang_res**2))


class RPCollision:
    """Host-side collision metadata (reference ``RPCollision``, rigid_payload.py:164-185)."""

    def __init__(self, payload_vertices, payload_mesh_vertices):
        self.payload_vertices = np.asarray(payload_vertices, np.float64)
        self.payload_mesh_vertices = np.asarray(payload_mesh_vertices, np.float64)
        self.collision_radius = float(
            np.max(np.linalg.norm(self.payload_mesh_vertices, axis=1)) + 0.1
        )
