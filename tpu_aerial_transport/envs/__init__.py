"""Environments (reference ``example/env_forest.py``): procedural forest with
closed-form collision distance queries in JAX."""

from tpu_aerial_transport.envs import forest  # noqa: F401
